/* fw_helpers.h - minimal self-contained BPF program scaffolding.
 *
 * First-party replacement for libbpf's bpf_helpers.h so fw.c builds with
 * nothing but clang and the kernel UAPI headers (the TPU-VM provisioning
 * container has clang; it does not need libbpf-dev to build the programs,
 * only to build the fwctl loader).  Helper IDs are the stable UAPI
 * numbers from uapi/linux/bpf.h.
 *
 * The same header compiles under the host compiler (gcc -fsyntax-only)
 * for the repo-local syntax gate, where no BPF backend exists.
 */
#ifndef CLAWKER_FW_HELPERS_H
#define CLAWKER_FW_HELPERS_H

#include <linux/types.h>

#define SEC(name) __attribute__((section(name), used))

#ifndef __always_inline
#define __always_inline inline __attribute__((always_inline))
#endif

/* BTF-style map definition keywords (the libbpf convention, re-declared) */
#define __uint(name, val) int (*name)[val]
#define __type(name, val) typeof(val) *name

/* map types used here (uapi enum bpf_map_type) */
#define BPF_MAP_TYPE_HASH     1
#define BPF_MAP_TYPE_LRU_HASH 9
#define BPF_MAP_TYPE_RINGBUF  27

/* bpf_map_update_elem flags */
#define BPF_ANY 0

#ifdef CLAWKER_FW_HARNESS
/* Userspace test harness build (native/ebpf/fw_harness.c): the helpers
 * resolve to in-process emulations so the REAL program logic runs under
 * the host compiler and is driven from the unit suite via ctypes.  The
 * kernel build below uses the stable UAPI helper ids instead. */
void *fwh_map_lookup_elem(void *map, const void *key);
long fwh_map_update_elem(void *map, const void *key, const void *value,
			 __u64 flags);
long fwh_map_delete_elem(void *map, const void *key);
__u64 fwh_ktime_get_ns(void);
__u64 fwh_ktime_get_boot_ns(void);
__u64 fwh_get_socket_cookie(void *ctx);
__u64 fwh_get_current_cgroup_id(void);
void *fwh_ringbuf_reserve(void *ringbuf, __u64 size, __u64 flags);
void fwh_ringbuf_submit(void *data, __u64 flags);
void fwh_ringbuf_discard(void *data, __u64 flags);

static void *(*bpf_map_lookup_elem)(void *map, const void *key) = fwh_map_lookup_elem;
static long (*bpf_map_update_elem)(void *map, const void *key, const void *value,
				   __u64 flags) = fwh_map_update_elem;
static long (*bpf_map_delete_elem)(void *map, const void *key) = fwh_map_delete_elem;
static __u64 (*bpf_ktime_get_ns)(void) = fwh_ktime_get_ns;
static __u64 (*bpf_ktime_get_boot_ns)(void) = fwh_ktime_get_boot_ns;
static __u64 (*bpf_get_socket_cookie)(void *ctx) = fwh_get_socket_cookie;
static __u64 (*bpf_get_current_cgroup_id)(void) = fwh_get_current_cgroup_id;
static void *(*bpf_ringbuf_reserve)(void *ringbuf, __u64 size, __u64 flags) = fwh_ringbuf_reserve;
static void (*bpf_ringbuf_submit)(void *data, __u64 flags) = fwh_ringbuf_submit;
static void (*bpf_ringbuf_discard)(void *data, __u64 flags) = fwh_ringbuf_discard;
#else
/* helpers by stable UAPI id */
static void *(*bpf_map_lookup_elem)(void *map, const void *key) = (void *)1;
static long (*bpf_map_update_elem)(void *map, const void *key, const void *value,
				   __u64 flags) = (void *)2;
static long (*bpf_map_delete_elem)(void *map, const void *key) = (void *)3;
static __u64 (*bpf_ktime_get_ns)(void) = (void *)5;
static __u64 (*bpf_ktime_get_boot_ns)(void) = (void *)125;
static __u64 (*bpf_get_socket_cookie)(void *ctx) = (void *)46;
static __u64 (*bpf_get_current_cgroup_id)(void) = (void *)80;
static void *(*bpf_ringbuf_reserve)(void *ringbuf, __u64 size, __u64 flags) = (void *)131;
static void (*bpf_ringbuf_submit)(void *data, __u64 flags) = (void *)132;
static void (*bpf_ringbuf_discard)(void *data, __u64 flags) = (void *)133;
#endif /* CLAWKER_FW_HARNESS */

/* byte-order (constant-foldable) */
#define fw_htons(x) ((__be16)__builtin_bswap16((__u16)(x)))
#define fw_ntohs(x) ((__u16)__builtin_bswap16((__u16)(x)))
#define fw_htonl(x) ((__be32)__builtin_bswap32((__u32)(x)))
#define fw_ntohl(x) ((__u32)__builtin_bswap32((__u32)(x)))

static const char _license[] SEC("license") = "GPL";

#endif /* CLAWKER_FW_HELPERS_H */
