/* fw_maps.h - kernel/userspace ABI for the clawker-tpu egress firewall.
 *
 * The Python twin of every struct lives in clawker_tpu/firewall/model.py
 * (pack formats in the class FMT strings); tests/test_ebpf_abi.py compiles
 * this header with the host compiler and pins sizeof/offsetof against the
 * Python side, so the two cannot drift.
 *
 * Layout convention: IPv4 addresses and L4 ports are stored in NETWORK
 * byte order exactly as bpf_sock_addr presents them (user_ip4 is __be32,
 * user_port holds a __be16), so the programs compare and rewrite without
 * byte swapping.
 *
 * Parity reference: controlplane/firewall/ebpf/bpf/common.h defines the
 * reference's map set (container_map/bypass_map/dns_cache/route_map/
 * udp_flow_map/events_ringbuf + rate limiting).  This ABI is re-designed:
 * reverse-NAT is keyed by socket cookie rather than a flow tuple, and the
 * route table carries an explicit action + redirect target.
 */
#ifndef CLAWKER_FW_MAPS_H
#define CLAWKER_FW_MAPS_H

#include <linux/types.h>

/* route_val.action / event.verdict (model.py Action) */
#define FW_ALLOW        0
#define FW_DENY         1
#define FW_REDIRECT     2
#define FW_REDIRECT_DNS 3

/* event.reason (model.py Reason) */
#define FW_R_UNMANAGED    0
#define FW_R_BYPASS       1
#define FW_R_LOOPBACK     2
#define FW_R_DNS          3
#define FW_R_ENVOY        4
#define FW_R_HOSTPROXY    5
#define FW_R_ROUTE        6
#define FW_R_NO_ROUTE     7
#define FW_R_NO_DNS_ENTRY 8
#define FW_R_RAW_SOCKET   9
#define FW_R_IPV6         10
#define FW_R_MONITOR      11
#define FW_R_INTRA_NET    12

/* fw_container.flags (model.py FLAG_*) */
#define FW_F_ENFORCE   (1u << 0)
#define FW_F_HOSTPROXY (1u << 1)

#define FW_PROTO_TCP 6
#define FW_PROTO_UDP 17

/* map capacities (maps.py UDP_FLOWS_MAX; ring sized for event bursts) */
#define FW_CONTAINERS_MAX 1024
#define FW_DNS_MAX        65536
#define FW_ROUTES_MAX     16384
#define FW_UDP_FLOWS_MAX  4096
#define FW_EVENTS_RING_SZ (1 << 19)

/* event rate limit: per-cgroup token window (common.h:443 analogue,
 * simplified to a windowed counter - approximate under races, which is
 * acceptable for telemetry) */
#define FW_RL_WINDOW_NS  100000000ull /* 100ms */
#define FW_RL_BURST      64

/* containers value - model.py ContainerPolicy, 20 bytes */
struct fw_container {
	__be32 envoy_ip;
	__be32 dns_ip;
	__be32 hostproxy_ip;
	__be16 hostproxy_port;
	__u16  pad;
	__u32  flags;
	__be32 net_ip;      /* sandbox bridge subnet base */
	__u32  net_prefix;  /* prefix length; 0 = no intra-net allowance */
};

/* dns_cache value (key = __be32 resolved ip) - model.py DnsEntry, 16 bytes */
struct fw_dns {
	__u64 zone_hash;
	__u64 expires_unix;
};

/* routes key - model.py RouteKey, 12 bytes (packed: u64 head would pad to 16) */
struct fw_route_key {
	__u64  zone_hash;
	__be16 port;   /* 0 = any port */
	__u8   proto;  /* FW_PROTO_TCP | FW_PROTO_UDP */
	__u8   pad;
} __attribute__((packed));

/* routes value - model.py RouteVal, 8 bytes */
struct fw_route {
	__u8   action;
	__u8   pad;
	__be16 redirect_port;
	__be32 redirect_ip;
};

/* udp_flows value (key = u64 socket cookie) - model.py UdpFlow, 8 bytes */
struct fw_udp_flow {
	__be32 orig_ip;
	__be16 orig_port;
	__u8   pad[2];
};

/* events ringbuf record - model.py EgressEvent, 40 bytes */
struct fw_event {
	__u64  ts_ns;
	__u64  cgroup_id;
	__u64  zone_hash;
	__be32 dst_ip;
	__be16 dst_port;
	__u8   verdict;
	__u8   proto;
	__u8   reason;
	__u8   pad[7];
};

/* rate-limit state (kernel-internal, not part of the Python ABI) */
struct fw_rl {
	__u64 window_start_ns;
	__u32 count;
	__u32 pad;
};

/* the decision a program acts on (kernel-internal) */
struct fw_verdict {
	__u8   action;
	__u8   reason;
	__be16 redirect_port;
	__be32 redirect_ip;
	__u64  zone_hash;
};

#endif /* CLAWKER_FW_MAPS_H */
