/* fwctl - load/attach/inspect the clawker-tpu egress firewall.
 *
 * The one component that needs libbpf (ELF load + relocation); everything
 * else in userspace reaches the PINNED maps via raw bpf(2) from Python
 * (clawker_tpu/firewall/bpfsys.py).  Built on the target TPU-VM host by
 * the provisioning step (`make fwctl`), where clang + libbpf-dev are
 * installed; never needed on the operator laptop.
 *
 *   fwctl load   --obj fw.o [--pin-dir DIR]     load + pin maps/progs
 *   fwctl attach --cgroup PATH [--pin-dir DIR]  attach all 9 to a cgroup
 *   fwctl detach --cgroup PATH [--pin-dir DIR]
 *   fwctl events [--max N] [--follow] [--pin-dir DIR]   JSON lines
 *   fwctl status [--pin-dir DIR]                map entry counts
 *   fwctl unload [--pin-dir DIR]                unpin everything
 *
 * Parity reference: controlplane/firewall/ebpf/manager.go (Load :81,
 * Install :605, Remove :656) and cmd/ebpf-manager break-glass CLI; this
 * is the C equivalent driven over SSH by clawker_tpu/fleet provisioning.
 */
#include <errno.h>
#include <fcntl.h>
#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/stat.h>
#include <unistd.h>
#include <arpa/inet.h>

#include <bpf/bpf.h>
#include <bpf/libbpf.h>

#include "fw_maps.h"

#define DEFAULT_PIN_DIR "/sys/fs/bpf/clawker-tpu"

static const struct {
	const char *prog;
	enum bpf_attach_type type;
} ATTACHMENTS[] = {
	{ "fw_connect4",     BPF_CGROUP_INET4_CONNECT },
	{ "fw_connect6",     BPF_CGROUP_INET6_CONNECT },
	{ "fw_sendmsg4",     BPF_CGROUP_UDP4_SENDMSG },
	{ "fw_sendmsg6",     BPF_CGROUP_UDP6_SENDMSG },
	{ "fw_recvmsg4",     BPF_CGROUP_UDP4_RECVMSG },
	{ "fw_recvmsg6",     BPF_CGROUP_UDP6_RECVMSG },
	{ "fw_getpeername4", BPF_CGROUP_INET4_GETPEERNAME },
	{ "fw_getpeername6", BPF_CGROUP_INET6_GETPEERNAME },
	{ "fw_sock_create",  BPF_CGROUP_INET_SOCK_CREATE },
};
#define N_ATTACH (sizeof(ATTACHMENTS) / sizeof(ATTACHMENTS[0]))

/* must mirror clawker_tpu/firewall/maps.py ALL_MAPS (pinned by
 * tests/test_ebpf_abi.py) */
static const char *MAPS[] = { "containers", "bypass", "dns_cache", "routes",
			      "udp_flows", "tcp_flows", "events", "ratelimit" };
#define N_MAPS (sizeof(MAPS) / sizeof(MAPS[0]))

static int die(const char *what)
{
	fprintf(stderr, "fwctl: %s: %s\n", what, strerror(errno));
	return 1;
}

static void pin_path(char *buf, size_t n, const char *dir, const char *kind,
		     const char *name)
{
	if (kind)
		snprintf(buf, n, "%s/%s/%s", dir, kind, name);
	else
		snprintf(buf, n, "%s/%s", dir, name);
}

/* ------------------------------------------------------------------ load */

static int cmd_load(const char *obj_path, const char *pin_dir)
{
	struct bpf_object *obj;
	struct bpf_program *prog;
	struct bpf_map *map;
	char path[512];

	obj = bpf_object__open_file(obj_path, NULL);
	if (!obj)
		return die("open object");

	/* Maps pin flat under pin_dir (bpfsys.py opens <pin_dir>/<name>).
	 * Setting the pin path BEFORE load makes libbpf REUSE a compatible
	 * existing pin instead of creating a fresh map: programs already
	 * attached to cgroups keep enforcing the same maps userspace writes
	 * to.  Unlink+re-pin here would silently decouple enforcement from
	 * the control plane until every cgroup re-attached.  An existing pin
	 * with a changed schema fails the load -- run `fwctl unload` first
	 * (refuse, never orphan). */
	bpf_object__for_each_map(map, obj) {
		pin_path(path, sizeof(path), pin_dir, NULL, bpf_map__name(map));
		if (bpf_map__set_pin_path(map, path))
			return die(path);
	}
	if (bpf_object__load(obj))
		return die("load object (verifier, or incompatible existing "
			   "pin -- `fwctl unload` to reset)");
	snprintf(path, sizeof(path), "%s/progs", pin_dir);
	mkdir(path, 0755);
	bpf_object__for_each_program(prog, obj) {
		pin_path(path, sizeof(path), pin_dir, "progs",
			 bpf_program__name(prog));
		unlink(path);
		if (bpf_program__pin(prog, path))
			return die(path);
	}
	printf("loaded %s: %zu programs, %zu maps pinned under %s\n",
	       obj_path, N_ATTACH, N_MAPS, pin_dir);
	bpf_object__close(obj);
	return 0;
}

/* --------------------------------------------------------- attach/detach */

static int cmd_attach(const char *cgroup_path, const char *pin_dir, int detach)
{
	char path[512];
	int cg_fd, prog_fd, err = 0;
	size_t i;

	if (!cgroup_path) {
		fprintf(stderr, "fwctl: --cgroup PATH required\n");
		return 2;
	}
	cg_fd = open(cgroup_path, O_RDONLY | O_DIRECTORY);
	if (cg_fd < 0)
		return die(cgroup_path);
	for (i = 0; i < N_ATTACH; i++) {
		pin_path(path, sizeof(path), pin_dir, "progs",
			 ATTACHMENTS[i].prog);
		prog_fd = bpf_obj_get(path);
		if (prog_fd < 0) {
			fprintf(stderr, "fwctl: %s not pinned (run load)\n", path);
			err = 1;
			continue;
		}
		if (detach) {
			/* ignore ENOENT: program may not be attached */
			bpf_prog_detach2(prog_fd, cg_fd, ATTACHMENTS[i].type);
		} else if (bpf_prog_attach(prog_fd, cg_fd, ATTACHMENTS[i].type,
					   BPF_F_ALLOW_MULTI)) {
			fprintf(stderr, "fwctl: attach %s: %s\n",
				ATTACHMENTS[i].prog, strerror(errno));
			err = 1;
		}
		close(prog_fd);
	}
	close(cg_fd);
	if (!err)
		printf("%s %zu programs %s %s\n",
		       detach ? "detached" : "attached", N_ATTACH,
		       detach ? "from" : "to", cgroup_path);
	return err;
}

/* ---------------------------------------------------------------- events */

static volatile sig_atomic_t stop_flag;
static long events_left = -1;

static void on_sigint(int sig)
{
	(void)sig;
	stop_flag = 1;
}

static int on_event(void *ctx, void *data, size_t len)
{
	const struct fw_event *ev = data;
	char ip[INET_ADDRSTRLEN];
	struct in_addr a;

	(void)ctx;
	if (len < sizeof(*ev))
		return 0;
	a.s_addr = ev->dst_ip;
	inet_ntop(AF_INET, &a, ip, sizeof(ip));
	printf("{\"ts_ns\":%llu,\"cgroup\":%llu,\"zone\":%llu,"
	       "\"dst_ip\":\"%s\",\"dst_port\":%u,\"verdict\":%u,"
	       "\"proto\":%u,\"reason\":%u}\n",
	       (unsigned long long)ev->ts_ns,
	       (unsigned long long)ev->cgroup_id,
	       (unsigned long long)ev->zone_hash,
	       ip, ntohs(ev->dst_port), ev->verdict, ev->proto, ev->reason);
	fflush(stdout);
	if (events_left > 0 && --events_left == 0)
		stop_flag = 1;
	return 0;
}

static int cmd_events(const char *pin_dir, long max, int follow)
{
	struct ring_buffer *rb;
	char path[512];
	int map_fd;

	pin_path(path, sizeof(path), pin_dir, NULL, "events");
	map_fd = bpf_obj_get(path);
	if (map_fd < 0)
		return die(path);
	events_left = max;
	rb = ring_buffer__new(map_fd, on_event, NULL, NULL);
	if (!rb)
		return die("ring_buffer__new");
	signal(SIGINT, on_sigint);
	signal(SIGTERM, on_sigint);
	while (!stop_flag) {
		int n = ring_buffer__poll(rb, 200 /* ms */);
		if (n < 0 && n != -EINTR)
			break;
		if (!follow && n == 0)
			break;  /* --max drains what's there, then exits */
	}
	ring_buffer__free(rb);
	close(map_fd);
	return 0;
}

/* ---------------------------------------------------------------- status */

static long map_count(const char *pin_dir, const char *name, size_t key_size)
{
	char path[512], key[64], next[64];
	int fd;
	long n = 0;

	if (key_size > sizeof(key))
		return -1;
	pin_path(path, sizeof(path), pin_dir, NULL, name);
	fd = bpf_obj_get(path);
	if (fd < 0)
		return -1;
	if (bpf_map_get_next_key(fd, NULL, next) == 0) {
		do {
			n++;
			memcpy(key, next, key_size);
		} while (bpf_map_get_next_key(fd, key, next) == 0);
	}
	close(fd);
	return n;
}

static int cmd_status(const char *pin_dir)
{
	printf("{\"pin_dir\":\"%s\",\"containers\":%ld,\"bypass\":%ld,"
	       "\"dns_cache\":%ld,\"routes\":%ld,\"udp_flows\":%ld}\n",
	       pin_dir,
	       map_count(pin_dir, "containers", 8),
	       map_count(pin_dir, "bypass", 8),
	       map_count(pin_dir, "dns_cache", 4),
	       map_count(pin_dir, "routes", sizeof(struct fw_route_key)),
	       map_count(pin_dir, "udp_flows", 8));
	return 0;
}

/* ---------------------------------------------------------------- unload */

static int cmd_unload(const char *pin_dir)
{
	char path[512];
	size_t i;

	for (i = 0; i < N_MAPS; i++) {
		pin_path(path, sizeof(path), pin_dir, NULL, MAPS[i]);
		unlink(path);
	}
	for (i = 0; i < N_ATTACH; i++) {
		pin_path(path, sizeof(path), pin_dir, "progs",
			 ATTACHMENTS[i].prog);
		unlink(path);
	}
	snprintf(path, sizeof(path), "%s/progs", pin_dir);
	rmdir(path);
	printf("unpinned %s\n", pin_dir);
	return 0;
}

/* ------------------------------------------------------------------ main */

static const char *flag(int argc, char **argv, const char *name,
			const char *dflt)
{
	int i;

	for (i = 2; i < argc - 1; i++)
		if (strcmp(argv[i], name) == 0)
			return argv[i + 1];
	return dflt;
}

static int has_flag(int argc, char **argv, const char *name)
{
	int i;

	for (i = 2; i < argc; i++)
		if (strcmp(argv[i], name) == 0)
			return 1;
	return 0;
}

int main(int argc, char **argv)
{
	const char *pin_dir;

	if (argc < 2) {
		fprintf(stderr,
			"usage: fwctl load|attach|detach|events|status|unload [flags]\n");
		return 2;
	}
	pin_dir = flag(argc, argv, "--pin-dir", DEFAULT_PIN_DIR);
	libbpf_set_strict_mode(LIBBPF_STRICT_ALL);

	if (strcmp(argv[1], "load") == 0)
		return cmd_load(flag(argc, argv, "--obj", "fw.o"), pin_dir);
	if (strcmp(argv[1], "attach") == 0)
		return cmd_attach(flag(argc, argv, "--cgroup", NULL), pin_dir, 0);
	if (strcmp(argv[1], "detach") == 0)
		return cmd_attach(flag(argc, argv, "--cgroup", NULL), pin_dir, 1);
	if (strcmp(argv[1], "events") == 0)
		return cmd_events(pin_dir,
				  atol(flag(argc, argv, "--max", "-1")),
				  has_flag(argc, argv, "--follow"));
	if (strcmp(argv[1], "status") == 0)
		return cmd_status(pin_dir);
	if (strcmp(argv[1], "unload") == 0)
		return cmd_unload(pin_dir);
	fprintf(stderr, "fwctl: unknown command %s\n", argv[1]);
	return 2;
}
