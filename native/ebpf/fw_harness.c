/* fw_harness.c - userspace harness around the REAL kernel programs.
 *
 * Compiles fw.c with the host compiler (CLAWKER_FW_HARNESS routes the BPF
 * helpers to the emulations below) into a shared library the unit suite
 * drives via ctypes (tests/test_fw_kernel.py).  The point: fw_decide and
 * every program entry run as written, against emulated maps, so the
 * kernel decision logic is differential-tested against the Python policy
 * oracle (clawker_tpu/firewall/policy.py) without clang, libbpf, or a
 * verifier in the dev environment.  The clang/verifier gate proper is
 * scripts/check_bpf.sh, run where clang exists (TPU-VM provisioning).
 *
 * Map emulation: fixed-slot linear tables keyed by memcmp -- semantics
 * (update/lookup/delete, LRU approximated as plain hash) match what the
 * programs assume; capacity-full behaves like E2BIG (update fails),
 * which none of the tests rely on.
 */
#define CLAWKER_FW_HARNESS
#include "fw.c"

#include <string.h>

/* ------------------------------------------------------------ map tables */

#define FWH_SLOTS 4096
#define FWH_KEY_MAX 16
#define FWH_VAL_MAX 32

struct fwh_map {
	void *id;          /* &containers, &bypass, ... (map identity) */
	int key_sz, val_sz;
	int used[FWH_SLOTS];
	unsigned char keys[FWH_SLOTS][FWH_KEY_MAX];
	unsigned char vals[FWH_SLOTS][FWH_VAL_MAX];
};

/* map ids exported to Python (order is part of the harness ABI) */
enum {
	FWH_MAP_CONTAINERS = 0,
	FWH_MAP_BYPASS,
	FWH_MAP_DNS,
	FWH_MAP_ROUTES,
	FWH_MAP_UDP_FLOWS,
	FWH_MAP_TCP_FLOWS,
	FWH_MAP_RATELIMIT,
	FWH_N_MAPS,
};

static struct fwh_map fwh_maps[FWH_N_MAPS];

static void fwh_bind_maps(void)
{
	static const struct { void *id; int k, v; } spec[FWH_N_MAPS] = {
		[FWH_MAP_CONTAINERS] = { &containers, 8, sizeof(struct fw_container) },
		[FWH_MAP_BYPASS]     = { &bypass, 8, 8 },
		[FWH_MAP_DNS]        = { &dns_cache, 4, sizeof(struct fw_dns) },
		[FWH_MAP_ROUTES]     = { &routes, sizeof(struct fw_route_key),
					 sizeof(struct fw_route) },
		[FWH_MAP_UDP_FLOWS]  = { &udp_flows, 8, sizeof(struct fw_udp_flow) },
		[FWH_MAP_TCP_FLOWS]  = { &tcp_flows, 8, sizeof(struct fw_udp_flow) },
		[FWH_MAP_RATELIMIT]  = { &ratelimit, 8, sizeof(struct fw_rl) },
	};
	int i;

	for (i = 0; i < FWH_N_MAPS; i++) {
		fwh_maps[i].id = spec[i].id;
		fwh_maps[i].key_sz = spec[i].k;
		fwh_maps[i].val_sz = spec[i].v;
	}
}

static struct fwh_map *fwh_by_ptr(void *map)
{
	int i;

	if (!fwh_maps[0].id)
		fwh_bind_maps();
	for (i = 0; i < FWH_N_MAPS; i++)
		if (fwh_maps[i].id == map)
			return &fwh_maps[i];
	return 0;
}

static int fwh_find(struct fwh_map *m, const void *key)
{
	int i;

	for (i = 0; i < FWH_SLOTS; i++)
		if (m->used[i] && !memcmp(m->keys[i], key, m->key_sz))
			return i;
	return -1;
}

void *fwh_map_lookup_elem(void *map, const void *key)
{
	struct fwh_map *m = fwh_by_ptr(map);
	int i;

	if (!m)
		return 0;
	i = fwh_find(m, key);
	return i < 0 ? 0 : (void *)m->vals[i];
}

long fwh_map_update_elem(void *map, const void *key, const void *value,
			 __u64 flags)
{
	struct fwh_map *m = fwh_by_ptr(map);
	int i;

	(void)flags;
	if (!m)
		return -1;
	i = fwh_find(m, key);
	if (i < 0) {
		for (i = 0; i < FWH_SLOTS; i++)
			if (!m->used[i])
				break;
		if (i >= FWH_SLOTS)
			return -1;
		m->used[i] = 1;
		memcpy(m->keys[i], key, m->key_sz);
	}
	memcpy(m->vals[i], value, m->val_sz);
	return 0;
}

long fwh_map_delete_elem(void *map, const void *key)
{
	struct fwh_map *m = fwh_by_ptr(map);
	int i;

	if (!m)
		return -1;
	i = fwh_find(m, key);
	if (i < 0)
		return -1;
	m->used[i] = 0;
	return 0;
}

/* --------------------------------------------------- clock/identity stubs */

static __u64 fwh_now_ns;
static __u64 fwh_boot_ns;
static __u64 fwh_cgroup;
static __u64 fwh_cookie;

__u64 fwh_ktime_get_ns(void) { return fwh_now_ns; }
__u64 fwh_ktime_get_boot_ns(void) { return fwh_boot_ns; }
__u64 fwh_get_current_cgroup_id(void) { return fwh_cgroup; }
__u64 fwh_get_socket_cookie(void *ctx) { (void)ctx; return fwh_cookie; }

/* ------------------------------------------------------- ringbuf emulation */

#define FWH_EVQ 256
static struct fw_event fwh_events[FWH_EVQ];
static int fwh_ev_head, fwh_ev_count, fwh_ev_dropped;
static struct fw_event fwh_pending;  /* one in-flight reserve, like the ring */
static int fwh_reserved;

void *fwh_ringbuf_reserve(void *ringbuf, __u64 size, __u64 flags)
{
	(void)ringbuf; (void)flags;
	if (size != sizeof(struct fw_event) || fwh_reserved)
		return 0;
	if (fwh_ev_count >= FWH_EVQ) {
		fwh_ev_dropped++;
		return 0;
	}
	fwh_reserved = 1;
	return &fwh_pending;
}

void fwh_ringbuf_submit(void *data, __u64 flags)
{
	(void)flags;
	if (!fwh_reserved || data != (void *)&fwh_pending)
		return;
	fwh_events[(fwh_ev_head + fwh_ev_count) % FWH_EVQ] = fwh_pending;
	fwh_ev_count++;
	fwh_reserved = 0;
}

void fwh_ringbuf_discard(void *data, __u64 flags)
{
	(void)data; (void)flags;
	fwh_reserved = 0;
}

/* ------------------------------------------------------------ test API */

void fwh_reset(void)
{
	memset(fwh_maps, 0, sizeof(fwh_maps));
	fwh_bind_maps();
	fwh_now_ns = fwh_boot_ns = 0;
	fwh_cgroup = fwh_cookie = 0;
	fwh_ev_head = fwh_ev_count = fwh_ev_dropped = fwh_reserved = 0;
}

void fwh_set_cgroup(__u64 cg) { fwh_cgroup = cg; }
void fwh_set_cookie(__u64 c) { fwh_cookie = c; }
void fwh_set_time_ns(__u64 t) { fwh_now_ns = t; }
void fwh_set_boot_ns(__u64 t) { fwh_boot_ns = t; }

int fwh_map_update(int map_id, const void *key, const void *val)
{
	if (map_id < 0 || map_id >= FWH_N_MAPS)
		return -1;
	if (!fwh_maps[0].id)
		fwh_bind_maps();
	return (int)fwh_map_update_elem(fwh_maps[map_id].id, key, val, 0);
}

int fwh_map_lookup(int map_id, const void *key, void *val_out)
{
	void *v;

	if (map_id < 0 || map_id >= FWH_N_MAPS)
		return 0;
	if (!fwh_maps[0].id)
		fwh_bind_maps();
	v = fwh_map_lookup_elem(fwh_maps[map_id].id, key);
	if (!v)
		return 0;
	memcpy(val_out, v, fwh_maps[map_id].val_sz);
	return 1;
}

int fwh_map_delete(int map_id, const void *key)
{
	if (map_id < 0 || map_id >= FWH_N_MAPS)
		return -1;
	if (!fwh_maps[0].id)
		fwh_bind_maps();
	return (int)fwh_map_delete_elem(fwh_maps[map_id].id, key);
}

int fwh_pop_event(struct fw_event *out)
{
	if (!fwh_ev_count)
		return 0;
	*out = fwh_events[fwh_ev_head];
	fwh_ev_head = (fwh_ev_head + 1) % FWH_EVQ;
	fwh_ev_count--;
	return 1;
}

int fwh_event_drops(void) { return fwh_ev_dropped; }

/* program drivers: run the REAL entry points against a caller ctx */

int fwh_run_connect4(struct bpf_sock_addr *ctx) { return fw_connect4(ctx); }
int fwh_run_sendmsg4(struct bpf_sock_addr *ctx) { return fw_sendmsg4(ctx); }
int fwh_run_recvmsg4(struct bpf_sock_addr *ctx) { return fw_recvmsg4(ctx); }
int fwh_run_getpeername4(struct bpf_sock_addr *ctx) { return fw_getpeername4(ctx); }
int fwh_run_connect6(struct bpf_sock_addr *ctx) { return fw_connect6(ctx); }
int fwh_run_sendmsg6(struct bpf_sock_addr *ctx) { return fw_sendmsg6(ctx); }
int fwh_run_recvmsg6(struct bpf_sock_addr *ctx) { return fw_recvmsg6(ctx); }
int fwh_run_getpeername6(struct bpf_sock_addr *ctx) { return fw_getpeername6(ctx); }

int fwh_run_sock_create(__u32 family, __u32 type, __u32 protocol)
{
	struct bpf_sock sk = { .bound_dev_if = 0, .family = family,
			       .type = type, .protocol = protocol };
	return fw_sock_create(&sk);
}
