/* fwctl_raw.c - raw-syscall firewall control: no libbpf, no ELF.
 *
 * The full fwctl (fwctl.c) needs libbpf for one thing only: loading the
 * clang-built ELF object.  Every OTHER operation -- attaching pinned
 * programs to cgroups, dumping maps, draining the events ringbuf -- is
 * plain bpf(2) + mmap, so this tool compiles with nothing but a libc
 * and works against ANY pinned program set: the clang/libbpf object on
 * provisioned workers, or the in-process assembled programs the Python
 * lane pins via FwKernel.pin_all().
 *
 * Commands (JSON on stdout, errors on stderr, exit != 0 on failure):
 *   fwctl-raw attach  --cgroup PATH --pin-dir DIR
 *   fwctl-raw detach  --cgroup PATH --pin-dir DIR
 *   fwctl-raw events  [--max N] --pin-dir DIR
 *   fwctl-raw status  --pin-dir DIR
 *
 * The events output is the exact JSON dialect
 * clawker_tpu/firewall/bpfsys.PinnedMaps.drain_events parses, so this
 * binary IS the product's native event drain.
 *
 * Parity reference: controlplane/firewall/ebpf/manager.go Attach/Events
 * -- re-implemented at the syscall layer (tested against the real
 * kernel by tests/test_fwctl_raw.py, which this build runs live).
 */

#define _GNU_SOURCE
#include <errno.h>
#include <fcntl.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <sys/syscall.h>
#include <unistd.h>

#include "fw_maps.h"

/* ---- bpf(2) plumbing (uapi/linux/bpf.h subset) ---- */

#define BPF_OBJ_GET 7
#define BPF_PROG_ATTACH 8
#define BPF_PROG_DETACH 9
#define BPF_MAP_GET_NEXT_KEY 4
#define BPF_MAP_LOOKUP_ELEM 1
#define BPF_OBJ_GET_INFO_BY_FD 15

#define BPF_F_ALLOW_MULTI 2

struct obj_attr { uint64_t pathname; uint32_t bpf_fd; uint32_t file_flags; };
struct attach_attr {
	uint32_t target_fd, attach_bpf_fd, attach_type, attach_flags,
		replace_bpf_fd;
};
struct elem_attr {
	uint32_t map_fd, pad;
	uint64_t key, value, flags;
};
struct info_attr { uint32_t bpf_fd, info_len; uint64_t info; };

static long sys_bpf(int cmd, void *attr, unsigned int size)
{
	return syscall(__NR_bpf, cmd, attr, size);
}

static int obj_get(const char *dir, const char *name)
{
	char path[512];
	struct obj_attr a = {0};
	int fd;

	snprintf(path, sizeof(path), "%s/%s", dir, name);
	a.pathname = (uint64_t)(uintptr_t)path;
	fd = (int)sys_bpf(BPF_OBJ_GET, &a, sizeof(a));
	if (fd < 0)
		fprintf(stderr, "fwctl-raw: obj_get %s: %s\n", path,
			strerror(errno));
	return fd;
}

/* ---- program set: name -> expected cgroup attach type ---- */

static const struct { const char *name; uint32_t attach_type; } PROGS[] = {
	{ "fw_connect4", 10 },     /* BPF_CGROUP_INET4_CONNECT */
	{ "fw_sendmsg4", 14 },     /* BPF_CGROUP_UDP4_SENDMSG */
	{ "fw_recvmsg4", 19 },     /* BPF_CGROUP_UDP4_RECVMSG */
	{ "fw_getpeername4", 29 }, /* BPF_CGROUP_INET4_GETPEERNAME */
	{ "fw_connect6", 11 },     /* BPF_CGROUP_INET6_CONNECT */
	{ "fw_sendmsg6", 15 },     /* BPF_CGROUP_UDP6_SENDMSG */
	{ "fw_recvmsg6", 20 },     /* BPF_CGROUP_UDP6_RECVMSG */
	{ "fw_getpeername6", 30 }, /* BPF_CGROUP_INET6_GETPEERNAME */
	{ "fw_sock_create", 2 },   /* BPF_CGROUP_INET_SOCK_CREATE */
};
#define NPROGS (sizeof(PROGS) / sizeof(PROGS[0]))

static int cmd_attach(const char *cgroup, const char *pin_dir, int detach)
{
	int cg_fd = open(cgroup, O_RDONLY | O_DIRECTORY);
	size_t i;
	int rc = 0;

	if (cg_fd < 0) {
		fprintf(stderr, "fwctl-raw: open %s: %s\n", cgroup,
			strerror(errno));
		return 1;
	}
	for (i = 0; i < NPROGS; i++) {
		char pin[300];
		struct attach_attr a = {0};
		int prog_fd;

		snprintf(pin, sizeof(pin), "prog_%s", PROGS[i].name);
		prog_fd = obj_get(pin_dir, pin);
		if (prog_fd < 0) {
			rc = 1;
			continue;
		}
		a.target_fd = (uint32_t)cg_fd;
		a.attach_bpf_fd = (uint32_t)prog_fd;
		a.attach_type = PROGS[i].attach_type;
		a.attach_flags = detach ? 0 : BPF_F_ALLOW_MULTI;
		if (sys_bpf(detach ? BPF_PROG_DETACH : BPF_PROG_ATTACH, &a,
			    sizeof(a)) < 0) {
			/* detach of a never-attached prog is not an error */
			if (!(detach && errno == ENOENT)) {
				fprintf(stderr, "fwctl-raw: %s %s: %s\n",
					detach ? "detach" : "attach",
					PROGS[i].name, strerror(errno));
				rc = 1;
			}
		}
		close(prog_fd);
	}
	close(cg_fd);
	if (!rc)
		printf("{\"ok\": true, \"cgroup\": \"%s\", \"programs\": %zu}\n",
		       cgroup, NPROGS);
	return rc;
}

/* ---- events: mmap ringbuf drain (kernel/bpf/ringbuf.c layout) ---- */

static int map_max_entries(int fd)
{
	/* struct bpf_map_info: type,id,key_size,value_size,max_entries,... */
	uint32_t info[20] = {0};
	struct info_attr a = {0};

	a.bpf_fd = (uint32_t)fd;
	a.info_len = sizeof(info);
	a.info = (uint64_t)(uintptr_t)info;
	if (sys_bpf(BPF_OBJ_GET_INFO_BY_FD, &a, sizeof(a)) < 0)
		return -1;
	return (int)info[4];
}

static int cmd_events(const char *pin_dir, int max_events)
{
	long page = sysconf(_SC_PAGESIZE);
	int fd = obj_get(pin_dir, "events");
	int size, n = 0;
	unsigned char *cons, *data;
	uint64_t cons_pos, prod_pos;

	if (fd < 0)
		return 1;
	size = map_max_entries(fd);
	if (size <= 0) {
		fprintf(stderr, "fwctl-raw: events map info failed\n");
		return 1;
	}
	cons = mmap(NULL, (size_t)page, PROT_READ | PROT_WRITE, MAP_SHARED,
		    fd, 0);
	data = mmap(NULL, (size_t)page + 2ul * (size_t)size, PROT_READ,
		    MAP_SHARED, fd, page);
	if (cons == MAP_FAILED || data == MAP_FAILED) {
		fprintf(stderr, "fwctl-raw: ringbuf mmap: %s\n",
			strerror(errno));
		return 1;
	}
	cons_pos = *(volatile uint64_t *)cons;
	while (n < max_events) {
		uint32_t hdr, len;
		const struct fw_event *ev;
		size_t off;

		prod_pos = *(volatile uint64_t *)data;
		if (cons_pos >= prod_pos)
			break;
		off = (size_t)page + (cons_pos & ((uint64_t)size - 1));
		hdr = *(volatile uint32_t *)(data + off);
		if (hdr & (1u << 31))          /* BUSY: producer mid-write */
			break;
		len = hdr & ~((1u << 31) | (1u << 30));
		if (!(hdr & (1u << 30)) && len >= sizeof(*ev)) {
			ev = (const struct fw_event *)(data + off + 8);
			printf("{\"ts_ns\": %llu, \"cgroup\": %llu, "
			       "\"dst_ip\": \"%u.%u.%u.%u\", \"dst_port\": %u, "
			       "\"zone\": %llu, \"verdict\": %u, "
			       "\"proto\": %u, \"reason\": %u}\n",
			       (unsigned long long)ev->ts_ns,
			       (unsigned long long)ev->cgroup_id,
			       ev->dst_ip & 0xff, (ev->dst_ip >> 8) & 0xff,
			       (ev->dst_ip >> 16) & 0xff,
			       (ev->dst_ip >> 24) & 0xff,
			       /* __be16 -> host order */
			       (unsigned)((ev->dst_port >> 8) |
					  ((ev->dst_port & 0xff) << 8)),
			       (unsigned long long)ev->zone_hash,
			       ev->verdict, ev->proto, ev->reason);
			n++;
		}
		cons_pos += (len + 8 + 7) & ~7u;
		*(volatile uint64_t *)cons = cons_pos;
	}
	munmap(cons, (size_t)page);
	munmap(data, (size_t)page + 2ul * (size_t)size);
	close(fd);
	return 0;
}

static int cmd_status(const char *pin_dir)
{
	int fd = obj_get(pin_dir, "containers");
	uint64_t key = 0, next = 0;
	struct fw_container val;
	int have = 0, count = 0;

	if (fd < 0)
		return 1;
	printf("{\"enrolled\": [");
	for (;;) {
		struct elem_attr a = {0};

		a.map_fd = (uint32_t)fd;
		a.key = have ? (uint64_t)(uintptr_t)&key : 0;
		a.value = (uint64_t)(uintptr_t)&next;
		if (sys_bpf(BPF_MAP_GET_NEXT_KEY, &a, sizeof(a)) < 0)
			break;
		key = next;
		have = 1;
		memset(&val, 0, sizeof(val));
		a.map_fd = (uint32_t)fd;
		a.key = (uint64_t)(uintptr_t)&key;
		a.value = (uint64_t)(uintptr_t)&val;
		if (sys_bpf(BPF_MAP_LOOKUP_ELEM, &a, sizeof(a)) == 0) {
			printf("%s{\"cgroup\": %llu, \"flags\": %u}",
			       count ? ", " : "",
			       (unsigned long long)key, val.flags);
			count++;
		}
	}
	printf("], \"count\": %d}\n", count);
	close(fd);
	return 0;
}

static const char *flag_value(int argc, char **argv, const char *flag)
{
	int i;

	for (i = 1; i < argc - 1; i++)
		if (strcmp(argv[i], flag) == 0)
			return argv[i + 1];
	return NULL;
}

int main(int argc, char **argv)
{
	const char *pin_dir, *cgroup;

	if (argc < 2) {
		fprintf(stderr,
			"usage: fwctl-raw attach|detach|events|status ...\n");
		return 2;
	}
	pin_dir = flag_value(argc, argv, "--pin-dir");
	if (!pin_dir) {
		fprintf(stderr, "fwctl-raw: --pin-dir required\n");
		return 2;
	}
	if (strcmp(argv[1], "attach") == 0 || strcmp(argv[1], "detach") == 0) {
		cgroup = flag_value(argc, argv, "--cgroup");
		if (!cgroup) {
			fprintf(stderr, "fwctl-raw: --cgroup required\n");
			return 2;
		}
		return cmd_attach(cgroup, pin_dir,
				  strcmp(argv[1], "detach") == 0);
	}
	if (strcmp(argv[1], "events") == 0) {
		const char *m = flag_value(argc, argv, "--max");

		return cmd_events(pin_dir, m ? atoi(m) : 256);
	}
	if (strcmp(argv[1], "status") == 0)
		return cmd_status(pin_dir);
	fprintf(stderr, "fwctl-raw: unknown command %s\n", argv[1]);
	return 2;
}
