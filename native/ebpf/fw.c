/* fw.c - cgroup-attached egress enforcement programs.
 *
 * Nine programs, attached per managed-container cgroup with
 * BPF_F_ALLOW_MULTI by the fwctl loader:
 *
 *   fw_connect4 / fw_connect6       - TCP/UDP connect() policy + rewrite
 *   fw_sendmsg4 / fw_sendmsg6      - unconnected-UDP sendto() policy
 *   fw_recvmsg4 / fw_recvmsg6      - reverse-NAT of redirected UDP replies
 *   fw_getpeername4 / fw_getpeername6 - apps see the dst they aimed at
 *   fw_sock_create                  - SOCK_RAW/SOCK_PACKET deny (no ICMP)
 *
 * The decision semantics are the executable spec in
 * clawker_tpu/firewall/policy.py (fw_decide mirrors policy.decide step by
 * step -- the comments carry the same step numbers); the map ABI is
 * fw_maps.h / model.py.  Fail-closed property: the maps are pinned, so if
 * the control plane dies the last-synced policy keeps enforcing.
 *
 * Parity reference: the reference's program set lives in
 * controlplane/firewall/ebpf/bpf/clawker.c (:121 connect4 ... :394
 * sock_create) with shared logic in common.h.  Re-designed here: reverse-
 * NAT keys on bpf_get_socket_cookie() instead of a flow tuple (one lookup,
 * no tuple ambiguity), Envoy loop-prevention falls out of cgroup scoping
 * (the proxy is not an enrolled cgroup) instead of SO_MARK, and verdicts
 * ride an explicit action enum shared with userspace.
 *
 * Verifier notes: every map value pointer is null-checked before deref;
 * no loops; event emission bounded by the per-cgroup window counter.
 */
#include "fw_helpers.h"
#include "fw_maps.h"

/* bpf_sock_addr / bpf_sock contexts: declared locally with just the
 * fields these programs touch, in UAPI layout (uapi/linux/bpf.h).  Using
 * local declarations keeps the build dependent only on linux/types.h. */
struct bpf_sock_addr {
	__u32 user_family;
	__u32 user_ip4;      /* __be32 */
	__u32 user_ip6[4];   /* __be32[4] */
	__u32 user_port;     /* __be16 value in a __u32 slot */
	__u32 family;
	__u32 type;
	__u32 protocol;
	__u32 msg_src_ip4;
	__u32 msg_src_ip6[4];
};

struct bpf_sock {
	__u32 bound_dev_if;
	__u32 family;
	__u32 type;
	__u32 protocol;
};

#define FW_OK   1
#define FW_EPERM 0

/* ------------------------------------------------------------------ maps */

struct {
	__uint(type, BPF_MAP_TYPE_HASH);
	__uint(max_entries, FW_CONTAINERS_MAX);
	__type(key, __u64);                /* cgroup id */
	__type(value, struct fw_container);
} containers SEC(".maps");

struct {
	__uint(type, BPF_MAP_TYPE_HASH);
	__uint(max_entries, FW_CONTAINERS_MAX);
	__type(key, __u64);                /* cgroup id */
	__type(value, __u64);              /* bypass deadline, CLOCK_BOOTTIME ns */
} bypass SEC(".maps");

/* The dead-man is enforced HERE, not by a userspace timer: an expired
 * entry is deleted on first touch and enforcement resumes even if the
 * control plane died right after granting the bypass (fail-closed). */
static __always_inline int fw_bypass_active(__u64 cg)
{
	__u64 *deadline = bpf_map_lookup_elem(&bypass, &cg);

	if (!deadline)
		return 0;
	if (bpf_ktime_get_boot_ns() > *deadline) {
		bpf_map_delete_elem(&bypass, &cg);
		return 0;
	}
	return 1;
}

struct {
	__uint(type, BPF_MAP_TYPE_LRU_HASH);
	__uint(max_entries, FW_DNS_MAX);
	__type(key, __be32);               /* resolved ip */
	__type(value, struct fw_dns);
} dns_cache SEC(".maps");

struct {
	__uint(type, BPF_MAP_TYPE_HASH);
	__uint(max_entries, FW_ROUTES_MAX);
	__type(key, struct fw_route_key);
	__type(value, struct fw_route);
} routes SEC(".maps");

struct {
	__uint(type, BPF_MAP_TYPE_LRU_HASH);
	__uint(max_entries, FW_UDP_FLOWS_MAX);
	__type(key, __u64);                /* socket cookie */
	__type(value, struct fw_udp_flow);
} udp_flows SEC(".maps");

/* TCP connect-redirect originals live in their own LRU so proxy-bound
 * TCP churn can never evict a live UDP reverse-NAT entry. */
struct {
	__uint(type, BPF_MAP_TYPE_LRU_HASH);
	__uint(max_entries, FW_UDP_FLOWS_MAX);
	__type(key, __u64);                /* socket cookie */
	__type(value, struct fw_udp_flow);
} tcp_flows SEC(".maps");

struct {
	__uint(type, BPF_MAP_TYPE_RINGBUF);
	__uint(max_entries, FW_EVENTS_RING_SZ);
} events SEC(".maps");

struct {
	__uint(type, BPF_MAP_TYPE_LRU_HASH);
	__uint(max_entries, FW_CONTAINERS_MAX);
	__type(key, __u64);                /* cgroup id */
	__type(value, struct fw_rl);
} ratelimit SEC(".maps");

/* ----------------------------------------------------------------- events */

static __always_inline int fw_rl_admit(__u64 cg)
{
	__u64 now = bpf_ktime_get_ns();
	struct fw_rl *rl = bpf_map_lookup_elem(&ratelimit, &cg);

	if (!rl) {
		struct fw_rl fresh = { .window_start_ns = now, .count = 1, .pad = 0 };
		bpf_map_update_elem(&ratelimit, &cg, &fresh, BPF_ANY);
		return 1;
	}
	if (now - rl->window_start_ns > FW_RL_WINDOW_NS) {
		rl->window_start_ns = now;  /* racy reset: approximate is fine */
		rl->count = 1;
		return 1;
	}
	if (rl->count >= FW_RL_BURST)
		return 0;
	rl->count++;
	return 1;
}

static __always_inline void fw_emit(__u64 cg, __be32 dst, __be16 dport,
				    __u8 proto, const struct fw_verdict *v)
{
	struct fw_event *ev;

	if (!fw_rl_admit(cg))
		return;
	ev = bpf_ringbuf_reserve(&events, sizeof(*ev), 0);
	if (!ev)
		return;
	ev->ts_ns = bpf_ktime_get_ns();
	ev->cgroup_id = cg;
	ev->zone_hash = v->zone_hash;
	ev->dst_ip = dst;
	ev->dst_port = dport;
	ev->verdict = v->action;
	ev->proto = proto;
	ev->reason = v->reason;
	ev->pad[0] = ev->pad[1] = ev->pad[2] = 0;
	ev->pad[3] = ev->pad[4] = ev->pad[5] = ev->pad[6] = 0;
	bpf_ringbuf_submit(ev, 0);
}

/* ---------------------------------------------------------------- decide */

/* policy.py decide(), step for step.  Returns 0 when the cgroup is not
 * enrolled (caller passes through untouched); fills *v otherwise. */
static __always_inline int fw_decide(const struct fw_container *pol, __u64 cg,
				     __be32 dst, __be16 dport, __u8 proto,
				     struct fw_verdict *v)
{
	struct fw_dns *dns;
	struct fw_route *rt;
	struct fw_route_key rk;

	v->zone_hash = 0;
	v->redirect_ip = 0;
	v->redirect_port = 0;

	/* 2. bypass (dead-man entry unexpired -> allow everything, logged) */
	if (fw_bypass_active(cg)) {
		v->action = FW_ALLOW;
		v->reason = FW_R_BYPASS;
		fw_emit(cg, dst, dport, proto, v);
		return 1;
	}

	/* 3. loopback: first octet 127 (be32 low byte on little-endian) */
	if ((dst & 0xff) == 127) {
		v->action = FW_ALLOW;
		v->reason = FW_R_LOOPBACK;
		return 1;
	}

	/* 4. all DNS flows terminate at our gate */
	if (dport == fw_htons(53)) {
		if (dst == pol->dns_ip) {
			v->action = FW_ALLOW;
			v->reason = FW_R_DNS;
			return 1;
		}
		v->action = FW_REDIRECT_DNS;
		v->reason = FW_R_DNS;
		v->redirect_ip = pol->dns_ip;
		v->redirect_port = fw_htons(53);
		fw_emit(cg, dst, dport, proto, v);
		return 1;
	}

	/* 5. the proxy itself */
	if (dst == pol->envoy_ip) {
		v->action = FW_ALLOW;
		v->reason = FW_R_ENVOY;
		return 1;
	}

	/* 6. host side-channel (browser-open / OAuth / git-cred) */
	if ((pol->flags & FW_F_HOSTPROXY) && dst == pol->hostproxy_ip &&
	    dport == pol->hostproxy_port) {
		v->action = FW_ALLOW;
		v->reason = FW_R_HOSTPROXY;
		return 1;
	}

	/* 6b. intra-network bypass: sibling services on the clawker-managed
	 * bridge (CP, otel-collector, project listeners) need no rules.
	 * dst/net_ip are network byte order; build the mask in host order
	 * and compare in host order so the prefix counts leading bits.
	 * The gateway (= the host: where the DNS gate and host proxy live)
	 * is NOT a sibling -- the reference blocks non-proxy host ports even
	 * with the CIDR bypass live (firewall_test.go:497), so host daemons
	 * stay reachable only through steps 4 and 6 above. */
	if (pol->net_prefix > 0 && pol->net_prefix <= 32 &&
	    dst != pol->dns_ip && dst != pol->hostproxy_ip) {
		__u32 mask = pol->net_prefix == 32
				     ? 0xffffffff
				     : ~(0xffffffffu >> pol->net_prefix);
		if ((fw_ntohl(dst) & mask) == (fw_ntohl(pol->net_ip) & mask)) {
			v->action = FW_ALLOW;
			v->reason = FW_R_INTRA_NET;
			return 1;
		}
	}

	/* 7. ip-literal egress: no resolution through the gate -> deny */
	dns = bpf_map_lookup_elem(&dns_cache, &dst);
	if (!dns) {
		v->action = (pol->flags & FW_F_ENFORCE) ? FW_DENY : FW_ALLOW;
		v->reason = (pol->flags & FW_F_ENFORCE) ? FW_R_NO_DNS_ENTRY
						       : FW_R_MONITOR;
		fw_emit(cg, dst, dport, proto, v);
		return 1;
	}
	v->zone_hash = dns->zone_hash;

	/* 8. zone route: exact port first, then any-port */
	rk.zone_hash = dns->zone_hash;
	rk.port = dport;
	rk.proto = proto;
	rk.pad = 0;
	rt = bpf_map_lookup_elem(&routes, &rk);
	if (!rt) {
		rk.port = 0;
		rt = bpf_map_lookup_elem(&routes, &rk);
	}
	if (!rt) {
		/* 9. resolved zone, but proto/port not ruled */
		v->action = (pol->flags & FW_F_ENFORCE) ? FW_DENY : FW_ALLOW;
		v->reason = (pol->flags & FW_F_ENFORCE) ? FW_R_NO_ROUTE
						       : FW_R_MONITOR;
		fw_emit(cg, dst, dport, proto, v);
		return 1;
	}

	v->action = rt->action;
	v->reason = FW_R_ROUTE;
	v->redirect_ip = rt->redirect_ip;
	v->redirect_port = rt->redirect_port;
	fw_emit(cg, dst, dport, proto, v);
	return 1;
}

/* Record the app's intended destination so recvmsg/getpeername can
 * reverse the rewrite (policy.py connect4/sendmsg4 flow recording). */
static __always_inline void fw_note_flow(void *ctx, __be32 dst, __be16 dport,
					 __u8 proto)
{
	__u64 cookie = bpf_get_socket_cookie(ctx);
	struct fw_udp_flow f = { .orig_ip = dst, .orig_port = dport,
				 .pad = { 0, 0 } };

	if (!cookie)
		return;
	if (proto == FW_PROTO_UDP)
		bpf_map_update_elem(&udp_flows, &cookie, &f, BPF_ANY);
	else
		bpf_map_update_elem(&tcp_flows, &cookie, &f, BPF_ANY);
}

/* Shared v4 egress path for connect4/sendmsg4. */
static __always_inline int fw_egress4(struct bpf_sock_addr *ctx, __u8 proto)
{
	__u64 cg = bpf_get_current_cgroup_id();
	struct fw_container *pol;
	struct fw_verdict v;
	__be32 dst = ctx->user_ip4;
	__be16 dport = (__be16)ctx->user_port;

	/* 1. not enrolled -> never interfere */
	pol = bpf_map_lookup_elem(&containers, &cg);
	if (!pol)
		return FW_OK;
	fw_decide(pol, cg, dst, dport, proto, &v);
	switch (v.action) {
	case FW_ALLOW:
		return FW_OK;
	case FW_REDIRECT:
	case FW_REDIRECT_DNS:
		fw_note_flow(ctx, dst, dport, proto);
		ctx->user_ip4 = v.redirect_ip;
		ctx->user_port = (__u32)v.redirect_port;
		return FW_OK;
	default:
		return FW_EPERM;
	}
}

SEC("cgroup/connect4")
int fw_connect4(struct bpf_sock_addr *ctx)
{
	__u8 proto = (ctx->protocol == FW_PROTO_UDP) ? FW_PROTO_UDP
						      : FW_PROTO_TCP;
	return fw_egress4(ctx, proto);
}

SEC("cgroup/sendmsg4")
int fw_sendmsg4(struct bpf_sock_addr *ctx)
{
	return fw_egress4(ctx, FW_PROTO_UDP);
}

/* Reverse-NAT: a reply whose source is our gate/proxy is presented as
 * coming from the destination the app originally addressed.  recvmsg
 * consults only udp_flows; getpeername also covers redirected TCP
 * connects via tcp_flows (policy.py recvmsg4/getpeername4). */
static __always_inline int fw_ingress_rewrite4(struct bpf_sock_addr *ctx,
					       int include_tcp)
{
	__u64 cg = bpf_get_current_cgroup_id();
	struct fw_container *pol;
	struct fw_udp_flow *f;
	__u64 cookie;

	pol = bpf_map_lookup_elem(&containers, &cg);
	if (!pol)
		return FW_OK;
	cookie = bpf_get_socket_cookie(ctx);
	if (!cookie)
		return FW_OK;
	f = bpf_map_lookup_elem(&udp_flows, &cookie);
	if (!f && include_tcp)
		f = bpf_map_lookup_elem(&tcp_flows, &cookie);
	if (!f)
		return FW_OK;
	if (ctx->user_ip4 == pol->dns_ip || ctx->user_ip4 == pol->envoy_ip) {
		ctx->user_ip4 = f->orig_ip;
		ctx->user_port = (__u32)f->orig_port;
	}
	return FW_OK;
}

SEC("cgroup/recvmsg4")
int fw_recvmsg4(struct bpf_sock_addr *ctx)
{
	return fw_ingress_rewrite4(ctx, 0);
}

SEC("cgroup/getpeername4")
int fw_getpeername4(struct bpf_sock_addr *ctx)
{
	return fw_ingress_rewrite4(ctx, 1);
}

/* ------------------------------------------------------------------ IPv6 */

/* ::ffff:a.b.c.d prefix word (bytes 00 00 ff ff as a be32 load) */
#define FW_V4MAPPED ((__u32)__builtin_bswap32(0x0000ffffu))

static __always_inline int fw_is_v4mapped(const __u32 ip6[4])
{
	return ip6[0] == 0 && ip6[1] == 0 && ip6[2] == FW_V4MAPPED;
}

static __always_inline int fw_is_v6_loopback(const __u32 ip6[4])
{
	return ip6[0] == 0 && ip6[1] == 0 && ip6[2] == 0 &&
	       ip6[3] == (__u32)__builtin_bswap32(1u);
}

/* policy.py connect6: v4-mapped routes through the v4 decision (rewrite
 * stays inside the mapped form); native v6 is denied -- the sandbox data
 * plane is v4-only, so v6 would be an enforcement hole. */
static __always_inline int fw_egress6(struct bpf_sock_addr *ctx, __u8 proto)
{
	__u64 cg = bpf_get_current_cgroup_id();
	struct fw_container *pol;
	struct fw_verdict v;
	__be32 dst4;
	__be16 dport = (__be16)ctx->user_port;

	pol = bpf_map_lookup_elem(&containers, &cg);
	if (!pol)
		return FW_OK;
	/* break-glass bypass must open v6 too (policy.py connect6) */
	if (fw_bypass_active(cg)) {
		v.action = FW_ALLOW;
		v.reason = FW_R_BYPASS;
		v.zone_hash = 0;
		v.redirect_ip = 0;
		v.redirect_port = 0;
		fw_emit(cg, 0, dport, proto, &v);
		return FW_OK;
	}
	if (fw_is_v6_loopback(ctx->user_ip6))
		return FW_OK;
	if (!fw_is_v4mapped(ctx->user_ip6)) {
		v.action = FW_DENY;
		v.reason = FW_R_IPV6;
		v.zone_hash = 0;
		v.redirect_ip = 0;
		v.redirect_port = 0;
		fw_emit(cg, 0, dport, proto, &v);
		return FW_EPERM;
	}
	dst4 = ctx->user_ip6[3];
	fw_decide(pol, cg, dst4, dport, proto, &v);
	switch (v.action) {
	case FW_ALLOW:
		return FW_OK;
	case FW_REDIRECT:
	case FW_REDIRECT_DNS:
		fw_note_flow(ctx, dst4, dport, proto);
		ctx->user_ip6[3] = v.redirect_ip;
		ctx->user_port = (__u32)v.redirect_port;
		return FW_OK;
	default:
		return FW_EPERM;
	}
}

SEC("cgroup/connect6")
int fw_connect6(struct bpf_sock_addr *ctx)
{
	__u8 proto = (ctx->protocol == FW_PROTO_UDP) ? FW_PROTO_UDP
						      : FW_PROTO_TCP;
	return fw_egress6(ctx, proto);
}

SEC("cgroup/sendmsg6")
int fw_sendmsg6(struct bpf_sock_addr *ctx)
{
	return fw_egress6(ctx, FW_PROTO_UDP);
}

static __always_inline int fw_ingress_rewrite6(struct bpf_sock_addr *ctx,
					       int include_tcp)
{
	__u64 cg = bpf_get_current_cgroup_id();
	struct fw_container *pol;
	struct fw_udp_flow *f;
	__u64 cookie;

	pol = bpf_map_lookup_elem(&containers, &cg);
	if (!pol)
		return FW_OK;
	if (!fw_is_v4mapped(ctx->user_ip6))
		return FW_OK;
	cookie = bpf_get_socket_cookie(ctx);
	if (!cookie)
		return FW_OK;
	f = bpf_map_lookup_elem(&udp_flows, &cookie);
	if (!f && include_tcp)
		f = bpf_map_lookup_elem(&tcp_flows, &cookie);
	if (!f)
		return FW_OK;
	if (ctx->user_ip6[3] == pol->dns_ip || ctx->user_ip6[3] == pol->envoy_ip) {
		ctx->user_ip6[3] = f->orig_ip;
		ctx->user_port = (__u32)f->orig_port;
	}
	return FW_OK;
}

SEC("cgroup/recvmsg6")
int fw_recvmsg6(struct bpf_sock_addr *ctx)
{
	return fw_ingress_rewrite6(ctx, 0);
}

SEC("cgroup/getpeername6")
int fw_getpeername6(struct bpf_sock_addr *ctx)
{
	return fw_ingress_rewrite6(ctx, 1);
}

/* ------------------------------------------------------------ sock_create */

#define FW_SOCK_RAW    3
#define FW_SOCK_PACKET 10

/* policy.py sock_create: raw/packet sockets denied for enrolled cgroups
 * (blocks ICMP ping exfil and packet crafting; reference e2e
 * firewall_test.go:103). */
SEC("cgroup/sock_create")
int fw_sock_create(struct bpf_sock *ctx)
{
	__u64 cg = bpf_get_current_cgroup_id();
	struct fw_verdict v;

	if (!bpf_map_lookup_elem(&containers, &cg))
		return FW_OK;
	if (fw_bypass_active(cg))
		return FW_OK;
	if (ctx->type == FW_SOCK_RAW || ctx->type == FW_SOCK_PACKET) {
		v.action = FW_DENY;
		v.reason = FW_R_RAW_SOCKET;
		v.zone_hash = 0;
		v.redirect_ip = 0;
		v.redirect_port = 0;
		fw_emit(cg, 0, 0, 0, &v);
		return FW_EPERM;
	}
	return FW_OK;
}
