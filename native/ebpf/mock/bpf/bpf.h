/* Mock bpf.h (syscall-wrapper half) for fwctl unit tests.
 *
 * Attach-type values are the GENUINE uapi/linux/bpf.h enum values: the
 * recorded "MOCK: attach type=N" lines must read correctly against kernel
 * documentation, and anything cross-referencing these constants (e.g. the
 * raw-bpf(2) Python side) must not inherit wrong hook numbers.
 */
#ifndef FWCTL_MOCK_BPF_H
#define FWCTL_MOCK_BPF_H

enum bpf_attach_type {
	BPF_CGROUP_INET_SOCK_CREATE = 2,
	BPF_CGROUP_INET4_CONNECT = 10,
	BPF_CGROUP_INET6_CONNECT = 11,
	BPF_CGROUP_UDP4_SENDMSG = 14,
	BPF_CGROUP_UDP6_SENDMSG = 15,
	BPF_CGROUP_UDP4_RECVMSG = 19,
	BPF_CGROUP_UDP6_RECVMSG = 20,
	BPF_CGROUP_INET4_GETPEERNAME = 29,
	BPF_CGROUP_INET6_GETPEERNAME = 30,
};

#define BPF_F_ALLOW_MULTI (1u << 1)

int bpf_obj_get(const char *pathname);
int bpf_prog_attach(int prog_fd, int attachable_fd, enum bpf_attach_type type,
		    unsigned int flags);
int bpf_prog_detach2(int prog_fd, int attachable_fd, enum bpf_attach_type type);
int bpf_map_get_next_key(int fd, const void *key, void *next_key);

#endif /* FWCTL_MOCK_BPF_H */
