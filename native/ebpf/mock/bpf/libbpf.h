/* Mock libbpf.h for fwctl unit tests (native/ebpf/mock/).
 *
 * Declares exactly the libbpf surface fwctl.c consumes, with the real
 * library's signatures and iteration macros, so fwctl.c compiles
 * unmodified against either this mock or the genuine libbpf-dev on a
 * TPU-VM worker.  The implementations (fwctl_mock.c) print a MOCK: line
 * per call; tests/test_fwctl.py asserts on the sequences.
 */
#ifndef FWCTL_MOCK_LIBBPF_H
#define FWCTL_MOCK_LIBBPF_H

#include <stddef.h>

struct bpf_object;
struct bpf_map;
struct bpf_program;
struct bpf_object_open_opts;
struct ring_buffer;

enum libbpf_strict_mode { LIBBPF_STRICT_ALL = 0xffffffff };
int libbpf_set_strict_mode(enum libbpf_strict_mode mode);

struct bpf_object *bpf_object__open_file(const char *path,
					 const struct bpf_object_open_opts *opts);
int bpf_object__load(struct bpf_object *obj);
void bpf_object__close(struct bpf_object *obj);

struct bpf_map *bpf_object__next_map(const struct bpf_object *obj,
				     const struct bpf_map *map);
const char *bpf_map__name(const struct bpf_map *map);
int bpf_map__set_pin_path(struct bpf_map *map, const char *path);
int bpf_map__pin(struct bpf_map *map, const char *path);

struct bpf_program *bpf_object__next_program(const struct bpf_object *obj,
					     struct bpf_program *prog);
const char *bpf_program__name(const struct bpf_program *prog);
int bpf_program__pin(struct bpf_program *prog, const char *path);

#define bpf_object__for_each_map(pos, obj)                \
	for ((pos) = bpf_object__next_map((obj), NULL);   \
	     (pos) != NULL;                               \
	     (pos) = bpf_object__next_map((obj), (pos)))

#define bpf_object__for_each_program(pos, obj)               \
	for ((pos) = bpf_object__next_program((obj), NULL);  \
	     (pos) != NULL;                                  \
	     (pos) = bpf_object__next_program((obj), (pos)))

typedef int (*ring_buffer_sample_fn)(void *ctx, void *data, size_t size);
struct ring_buffer_opts;
struct ring_buffer *ring_buffer__new(int map_fd, ring_buffer_sample_fn sample_cb,
				     void *ctx, const struct ring_buffer_opts *opts);
int ring_buffer__poll(struct ring_buffer *rb, int timeout_ms);
void ring_buffer__free(struct ring_buffer *rb);

#endif /* FWCTL_MOCK_LIBBPF_H */
