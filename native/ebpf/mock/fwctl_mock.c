/* fwctl_mock.c - recording libbpf mock behind the mock/bpf headers.
 *
 * Each call prints one "MOCK: ..." line on stdout; failure injection via
 * env:
 *   FWCTL_MOCK_OPEN_FAIL=1   bpf_object__open_file returns NULL
 *   FWCTL_MOCK_LOAD_FAIL=1   bpf_object__load fails (verifier/pin clash)
 *   FWCTL_MOCK_NO_PINS=1     bpf_obj_get fails (nothing pinned)
 *   FWCTL_MOCK_ATTACH_FAIL=<progname>  that attach fails
 *   FWCTL_MOCK_EVENTS=<n>    ring_buffer__poll delivers n events, then 0
 *
 * The object model mirrors fw.c: 8 maps (fw_maps.h ALL_MAPS order) and 9
 * programs (fwctl.c ATTACHMENTS order).
 */
#include <errno.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include <bpf/bpf.h>
#include <bpf/libbpf.h>

#include "../fw_maps.h"

static const char *MOCK_MAPS[] = { "containers", "bypass", "dns_cache",
				   "routes", "udp_flows", "tcp_flows",
				   "events", "ratelimit" };
#define N_MOCK_MAPS 8

static const char *MOCK_PROGS[] = {
	"fw_connect4", "fw_connect6", "fw_sendmsg4", "fw_sendmsg6",
	"fw_recvmsg4", "fw_recvmsg6", "fw_getpeername4", "fw_getpeername6",
	"fw_sock_create",
};
#define N_MOCK_PROGS 9

struct bpf_map { int idx; };
struct bpf_program { int idx; };
struct bpf_object {
	struct bpf_map maps[N_MOCK_MAPS];
	struct bpf_program progs[N_MOCK_PROGS];
};

static struct bpf_object mock_obj;

int libbpf_set_strict_mode(enum libbpf_strict_mode mode)
{
	(void)mode;
	return 0;
}

struct bpf_object *bpf_object__open_file(const char *path,
					 const struct bpf_object_open_opts *opts)
{
	int i;

	(void)opts;
	printf("MOCK: open %s\n", path);
	if (getenv("FWCTL_MOCK_OPEN_FAIL")) {
		errno = ENOENT;
		return NULL;
	}
	for (i = 0; i < N_MOCK_MAPS; i++)
		mock_obj.maps[i].idx = i;
	for (i = 0; i < N_MOCK_PROGS; i++)
		mock_obj.progs[i].idx = i;
	return &mock_obj;
}

int bpf_object__load(struct bpf_object *obj)
{
	(void)obj;
	printf("MOCK: load\n");
	if (getenv("FWCTL_MOCK_LOAD_FAIL")) {
		errno = EINVAL;
		return -EINVAL;
	}
	return 0;
}

void bpf_object__close(struct bpf_object *obj)
{
	(void)obj;
	printf("MOCK: close\n");
}

struct bpf_map *bpf_object__next_map(const struct bpf_object *obj,
				     const struct bpf_map *map)
{
	int next = map ? map->idx + 1 : 0;

	if (next >= N_MOCK_MAPS)
		return NULL;
	return (struct bpf_map *)&obj->maps[next];
}

const char *bpf_map__name(const struct bpf_map *map)
{
	return MOCK_MAPS[map->idx];
}

int bpf_map__set_pin_path(struct bpf_map *map, const char *path)
{
	printf("MOCK: set_pin_path %s %s\n", MOCK_MAPS[map->idx], path);
	return 0;
}

int bpf_map__pin(struct bpf_map *map, const char *path)
{
	printf("MOCK: map_pin %s %s\n", MOCK_MAPS[map->idx], path);
	return 0;
}

struct bpf_program *bpf_object__next_program(const struct bpf_object *obj,
					     struct bpf_program *prog)
{
	int next = prog ? prog->idx + 1 : 0;

	if (next >= N_MOCK_PROGS)
		return NULL;
	return (struct bpf_program *)&obj->progs[next];
}

const char *bpf_program__name(const struct bpf_program *prog)
{
	return MOCK_PROGS[prog->idx];
}

int bpf_program__pin(struct bpf_program *prog, const char *path)
{
	printf("MOCK: prog_pin %s %s\n", MOCK_PROGS[prog->idx], path);
	return 0;
}

/* ----------------------------------------------------------- bpf.h half */

/* obj_get encodes the pinned program's index into the returned fd
 * (100+idx) so attach can resolve the fd back to a name for logging and
 * name-keyed failure injection. */
int bpf_obj_get(const char *pathname)
{
	const char *base = strrchr(pathname, '/');
	int i;

	printf("MOCK: obj_get %s\n", pathname);
	if (getenv("FWCTL_MOCK_NO_PINS")) {
		errno = ENOENT;
		return -1;
	}
	base = base ? base + 1 : pathname;
	for (i = 0; i < N_MOCK_PROGS; i++)
		if (!strcmp(base, MOCK_PROGS[i]))
			return 100 + i;
	return 100 + N_MOCK_PROGS;  /* a map pin */
}

int bpf_prog_attach(int prog_fd, int attachable_fd, enum bpf_attach_type type,
		    unsigned int flags)
{
	const char *fail = getenv("FWCTL_MOCK_ATTACH_FAIL");
	int idx = prog_fd - 100;
	const char *name = (idx >= 0 && idx < N_MOCK_PROGS) ? MOCK_PROGS[idx]
							    : "?";

	(void)attachable_fd;
	printf("MOCK: attach %s type=%d flags=%u\n", name, (int)type, flags);
	if (fail && !strcmp(fail, name)) {
		errno = EPERM;
		return -1;
	}
	return 0;
}

int bpf_prog_detach2(int prog_fd, int attachable_fd, enum bpf_attach_type type)
{
	(void)prog_fd; (void)attachable_fd;
	printf("MOCK: detach type=%d\n", (int)type);
	return 0;
}

int bpf_map_get_next_key(int fd, const void *key, void *next_key)
{
	(void)fd; (void)key; (void)next_key;
	errno = ENOENT;  /* empty map */
	return -1;
}

/* ------------------------------------------------------------- ringbuf */

struct ring_buffer {
	ring_buffer_sample_fn cb;
	void *ctx;
	int remaining;
};

static struct ring_buffer mock_rb;

struct ring_buffer *ring_buffer__new(int map_fd, ring_buffer_sample_fn sample_cb,
				     void *ctx, const struct ring_buffer_opts *opts)
{
	const char *n = getenv("FWCTL_MOCK_EVENTS");

	(void)map_fd; (void)opts;
	printf("MOCK: ringbuf_new\n");
	mock_rb.cb = sample_cb;
	mock_rb.ctx = ctx;
	mock_rb.remaining = n ? atoi(n) : 0;
	return &mock_rb;
}

int ring_buffer__poll(struct ring_buffer *rb, int timeout_ms)
{
	struct fw_event ev;

	(void)timeout_ms;
	if (rb->remaining <= 0)
		return 0;
	rb->remaining--;
	memset(&ev, 0, sizeof(ev));
	ev.ts_ns = 123;
	ev.cgroup_id = 42;
	ev.zone_hash = 0xA1;
	ev.dst_ip = 0x0100007f;  /* 127.0.0.1 be32 */
	ev.dst_port = 0xbb01;    /* 443 be16 */
	ev.verdict = 1;
	ev.proto = 6;
	ev.reason = 8;
	rb->cb(rb->ctx, &ev, sizeof(ev));
	return 1;
}

void ring_buffer__free(struct ring_buffer *rb)
{
	(void)rb;
	printf("MOCK: ringbuf_free\n");
}
