// clawker-supervisord: native PID-1 supervisor for agent containers.
//
// Parity reference: clawkerd/ PID-1 contract (SURVEY.md 2.9) -- single-shot
// CAS spawn of the user CMD with kernel privilege drop, signal forwarding
// with exclusions (SIGCHLD/SIGURG stay home), two-phase zombie reaping,
// SIGKILL watchdog on shutdown, bash-convention exit codes (128+signum).
// The reference folds supervision into its Go daemon; this build splits the
// PID-1 core into a dependency-free C++ binary so it works in any image,
// with the TLS session daemon (clawker_tpu/agentd) riding next to it and
// driving it over a Unix control socket.
//
// Control protocol: netstring frames `<len>:<payload>,` where payload is
// NUL-separated fields, field 0 = verb:
//   SPAWN \0 uid \0 gid \0 cwd \0 k=v... \0 -- \0 argv...   -> OK\0pid | ERR\0msg
//   SIGNAL \0 signum                                        -> OK | ERR\0msg
//   STATUS                              -> IDLE | RUNNING\0pid | EXITED\0code
//   WAIT                 (blocks until user CMD exit)       -> EXIT\0code
//   SHUTDOWN \0 grace_ms                                    -> OK (then exit)
//
// Run modes: as PID 1 in a container (normal), or as an ordinary process
// for tests -- reaping then covers only our own descendants.

#include <cerrno>
#include <csignal>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include <fcntl.h>
#include <grp.h>
#include <poll.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <sys/un.h>
#include <sys/wait.h>
#include <time.h>
#include <unistd.h>

namespace {

// ---------------------------------------------------------------- globals

volatile sig_atomic_t g_sigchld = 0;
volatile sig_atomic_t g_termsig = 0;  // TERM/INT/QUIT received as PID 1
int g_sigpipe[2] = {-1, -1};  // self-pipe: signal handler -> poll loop

struct UserCmd {
  pid_t pid = -1;       // -1 = never spawned; 0 = exited
  int exit_code = -1;   // bash convention once exited
  bool running() const { return pid > 0; }
  bool exited() const { return pid == 0; }
};

struct Client {
  int fd;
  std::string inbuf;
  bool waiting = false;  // parked on WAIT until user CMD exits
};

UserCmd g_cmd;
pid_t g_service_pid = -1;  // the session daemon child (agentd), if any
int g_service_exit = 0;
bool g_shutdown = false;
long g_grace_ms = 5000;
struct timespec g_deadline = {0, 0};  // SIGKILL watchdog deadline

void on_signal(int sig) {
  int saved = errno;
  if (sig == SIGCHLD) {
    g_sigchld = 1;
    (void)!write(g_sigpipe[1], "c", 1);
  } else {
    // PID-1 forwarding: relay to the user CMD's process group. SIGURG is
    // excluded by never installing this handler for it (Go runtimes use
    // SIGURG for preemption; forwarding it breaks agents).
    if (g_cmd.running()) kill(-g_cmd.pid, sig);
    // termination signals also begin supervisor shutdown (docker stop
    // sends TERM to PID 1 and expects the container to exit)
    if (sig == SIGTERM || sig == SIGINT || sig == SIGQUIT) g_termsig = sig;
    (void)!write(g_sigpipe[1], "s", 1);
  }
  errno = saved;
}

int bash_code(int status) {
  if (WIFEXITED(status)) return WEXITSTATUS(status);
  if (WIFSIGNALED(status)) return 128 + WTERMSIG(status);
  return 1;
}

// Two-phase reap: phase 1 drains every zombie non-blocking (PID 1 inherits
// orphans); phase 2 records exit status for the pids we own.  The reference
// splits these phases to avoid racing concurrent waiters (SURVEY.md 7,
// "hard parts" #3); here one loop owns all wait4 calls so the race cannot
// exist by construction.
void reap() {
  for (;;) {
    int status = 0;
    pid_t p = waitpid(-1, &status, WNOHANG);
    if (p <= 0) break;
    if (p == g_cmd.pid) {
      g_cmd.exit_code = bash_code(status);
      g_cmd.pid = 0;
    } else if (p == g_service_pid) {
      g_service_exit = bash_code(status);
      g_service_pid = 0;
    }
    // orphans reaped silently: that IS the PID-1 job
  }
}

// ------------------------------------------------------------- netstrings

bool frame_complete(const std::string& buf, std::string* payload, size_t* consumed) {
  size_t colon = buf.find(':');
  if (colon == std::string::npos) return buf.size() < 12;  // still plausible
  size_t len = 0;
  for (size_t i = 0; i < colon; i++) {
    if (buf[i] < '0' || buf[i] > '9') return false;  // malformed -> drop client
    len = len * 10 + (buf[i] - '0');
    if (len > 1 << 20) return false;
  }
  if (buf.size() < colon + 1 + len + 1) {
    *consumed = 0;
    payload->clear();
    return true;  // incomplete but well-formed so far
  }
  if (buf[colon + 1 + len] != ',') return false;
  *payload = buf.substr(colon + 1, len);
  *consumed = colon + 1 + len + 1;
  return true;
}

std::vector<std::string> split_fields(const std::string& payload) {
  std::vector<std::string> out;
  size_t start = 0;
  for (;;) {
    size_t nul = payload.find('\0', start);
    if (nul == std::string::npos) {
      out.push_back(payload.substr(start));
      return out;
    }
    out.push_back(payload.substr(start, nul - start));
    start = nul + 1;
  }
}

void send_frame(int fd, const std::vector<std::string>& fields) {
  std::string payload;
  for (size_t i = 0; i < fields.size(); i++) {
    if (i) payload.push_back('\0');
    payload += fields[i];
  }
  char head[32];
  int n = snprintf(head, sizeof head, "%zu:", payload.size());
  std::string wire(head, n);
  wire += payload;
  wire.push_back(',');
  (void)!write(fd, wire.data(), wire.size());
}

// ------------------------------------------------------------------ spawn

std::string spawn_cmd(const std::vector<std::string>& f, pid_t* out_pid) {
  if (g_cmd.running()) return "already running";       // single-shot CAS
  if (f.size() < 5) return "SPAWN needs uid,gid,cwd,env...,--,argv...";
  long uid = atol(f[1].c_str());
  long gid = atol(f[2].c_str());
  const std::string& cwd = f[3];
  std::vector<std::string> envs, argv;
  bool after_sep = false;
  for (size_t i = 4; i < f.size(); i++) {
    if (!after_sep && f[i] == "--") { after_sep = true; continue; }
    (after_sep ? argv : envs).push_back(f[i]);
  }
  if (argv.empty()) return "empty argv";

  pid_t pid = fork();
  if (pid < 0) return std::string("fork: ") + strerror(errno);
  if (pid == 0) {
    // child: own session+pgroup so signals hit the whole job
    setsid();
    if (!cwd.empty() && chdir(cwd.c_str()) != 0) _exit(127);
    if (gid > 0) {
      if (setgroups(0, nullptr) != 0 && errno != EPERM) _exit(126);
      if (setgid((gid_t)gid) != 0) _exit(126);
    }
    if (uid > 0 && setuid((uid_t)uid) != 0) _exit(126);  // kernel drop, no return
    std::vector<char*> envp, args;
    for (auto& e : envs) envp.push_back(const_cast<char*>(e.c_str()));
    envp.push_back(nullptr);
    for (auto& a : argv) args.push_back(const_cast<char*>(a.c_str()));
    args.push_back(nullptr);
    // reset dispositions the parent customized
    signal(SIGCHLD, SIG_DFL);
    sigset_t empty; sigemptyset(&empty); sigprocmask(SIG_SETMASK, &empty, nullptr);
    execve(args[0], args.data(), envp.data());
    _exit(127);
  }
  g_cmd.pid = pid;
  g_cmd.exit_code = -1;
  *out_pid = pid;
  return "";
}

void arm_watchdog(long grace_ms) {
  clock_gettime(CLOCK_MONOTONIC, &g_deadline);
  g_deadline.tv_sec += grace_ms / 1000;
  g_deadline.tv_nsec += (grace_ms % 1000) * 1000000L;
  if (g_deadline.tv_nsec >= 1000000000L) { g_deadline.tv_sec++; g_deadline.tv_nsec -= 1000000000L; }
}

long watchdog_remaining_ms() {
  if (g_deadline.tv_sec == 0) return -1;
  struct timespec now;
  clock_gettime(CLOCK_MONOTONIC, &now);
  long ms = (g_deadline.tv_sec - now.tv_sec) * 1000 + (g_deadline.tv_nsec - now.tv_nsec) / 1000000L;
  return ms < 0 ? 0 : ms;
}

// ---------------------------------------------------------------- request

void notify_waiters(std::vector<Client>& clients) {
  for (auto& c : clients) {
    if (c.waiting && g_cmd.exited()) {
      send_frame(c.fd, {"EXIT", std::to_string(g_cmd.exit_code)});
      c.waiting = false;
    }
  }
}

bool handle_request(Client& c, const std::vector<std::string>& f) {
  if (f.empty()) return true;
  const std::string& verb = f[0];
  if (verb == "SPAWN") {
    pid_t pid = -1;
    std::string err = spawn_cmd(f, &pid);
    if (err.empty()) send_frame(c.fd, {"OK", std::to_string(pid)});
    else send_frame(c.fd, {"ERR", err});
  } else if (verb == "SIGNAL") {
    if (f.size() < 2 || !g_cmd.running()) {
      send_frame(c.fd, {"ERR", "no running command"});
    } else {
      int sig = atoi(f[1].c_str());
      if (kill(-g_cmd.pid, sig) == 0) send_frame(c.fd, {"OK"});
      else send_frame(c.fd, {"ERR", strerror(errno)});
    }
  } else if (verb == "STATUS") {
    if (g_cmd.running()) send_frame(c.fd, {"RUNNING", std::to_string(g_cmd.pid)});
    else if (g_cmd.exited()) send_frame(c.fd, {"EXITED", std::to_string(g_cmd.exit_code)});
    else send_frame(c.fd, {"IDLE"});
  } else if (verb == "WAIT") {
    if (g_cmd.exited()) send_frame(c.fd, {"EXIT", std::to_string(g_cmd.exit_code)});
    else if (!g_cmd.running()) send_frame(c.fd, {"ERR", "nothing spawned"});
    else c.waiting = true;
  } else if (verb == "SHUTDOWN") {
    g_shutdown = true;
    g_grace_ms = f.size() > 1 ? atol(f[1].c_str()) : 5000;
    send_frame(c.fd, {"OK"});
    if (g_cmd.running()) kill(-g_cmd.pid, SIGTERM);
    if (g_service_pid > 0) kill(g_service_pid, SIGTERM);
    if (g_cmd.running() || g_service_pid > 0) arm_watchdog(g_grace_ms);
  } else {
    send_frame(c.fd, {"ERR", "unknown verb"});
  }
  return true;
}

}  // namespace

int main(int argc, char** argv) {
  const char* sock_path = "/run/clawker/supervisor.sock";
  const char* ready_file = nullptr;
  std::vector<char*> service_argv;
  for (int i = 1; i < argc; i++) {
    if (!strcmp(argv[i], "--socket") && i + 1 < argc) sock_path = argv[++i];
    else if (!strcmp(argv[i], "--ready-file") && i + 1 < argc) ready_file = argv[++i];
    else if (!strcmp(argv[i], "--child")) {
      for (int j = i + 1; j < argc; j++) service_argv.push_back(argv[j]);
      break;
    }
  }

  if (pipe2(g_sigpipe, O_CLOEXEC | O_NONBLOCK) != 0) { perror("pipe2"); return 1; }

  struct sigaction sa = {};
  sa.sa_handler = on_signal;
  sigemptyset(&sa.sa_mask);
  sa.sa_flags = SA_RESTART;
  sigaction(SIGCHLD, &sa, nullptr);
  // forwarded set: the job-control signals an operator sends PID 1.
  for (int sig : {SIGTERM, SIGINT, SIGHUP, SIGQUIT, SIGUSR1, SIGUSR2, SIGWINCH})
    sigaction(sig, &sa, nullptr);
  // SIGURG deliberately untouched (default ignore): Go preemption noise.

  unlink(sock_path);
  int lfd = socket(AF_UNIX, SOCK_STREAM | SOCK_CLOEXEC, 0);
  if (lfd < 0) { perror("socket"); return 1; }
  struct sockaddr_un addr = {};
  addr.sun_family = AF_UNIX;
  strncpy(addr.sun_path, sock_path, sizeof(addr.sun_path) - 1);
  if (bind(lfd, (struct sockaddr*)&addr, sizeof addr) != 0) { perror("bind"); return 1; }
  chmod(sock_path, 0600);
  if (listen(lfd, 8) != 0) { perror("listen"); return 1; }

  if (!service_argv.empty()) {
    pid_t pid = fork();
    if (pid == 0) {
      signal(SIGCHLD, SIG_DFL);
      service_argv.push_back(nullptr);
      execvp(service_argv[0], service_argv.data());
      _exit(127);
    }
    g_service_pid = pid;
  }

  if (ready_file) {
    int rfd = open(ready_file, O_CREAT | O_WRONLY | O_TRUNC, 0644);
    if (rfd >= 0) { (void)!write(rfd, "ok\n", 3); close(rfd); }
  }

  std::vector<Client> clients;
  for (;;) {
    std::vector<struct pollfd> pfds;
    pfds.push_back({g_sigpipe[0], POLLIN, 0});
    pfds.push_back({lfd, POLLIN, 0});
    for (auto& c : clients) pfds.push_back({c.fd, POLLIN, 0});

    long timeout = -1;
    long wd = watchdog_remaining_ms();
    if (wd >= 0) timeout = wd;
    int rc = poll(pfds.data(), pfds.size(), (int)timeout);
    if (rc < 0 && errno != EINTR) { perror("poll"); return 1; }

    if (g_sigchld) {
      g_sigchld = 0;
      char drain[64];
      while (read(g_sigpipe[0], drain, sizeof drain) > 0) {}
      reap();
      notify_waiters(clients);
    }

    if (g_termsig && !g_shutdown) {
      // same path as the SHUTDOWN verb: the handler already forwarded the
      // signal to the user CMD pgroup; arm the KILL watchdog and tell the
      // service child to wind down
      g_shutdown = true;
      if (g_cmd.running() || g_service_pid > 0) arm_watchdog(g_grace_ms);
      if (g_service_pid > 0) kill(g_service_pid, SIGTERM);
    }

    // watchdog: grace expired with processes still alive -> SIGKILL
    if (g_deadline.tv_sec != 0 && watchdog_remaining_ms() == 0) {
      if (g_cmd.running()) kill(-g_cmd.pid, SIGKILL);
      if (g_shutdown && g_service_pid > 0) kill(g_service_pid, SIGKILL);
      g_deadline = {0, 0};
    }

    if (pfds[1].revents & POLLIN) {
      int cfd = accept4(lfd, nullptr, nullptr, SOCK_CLOEXEC);
      if (cfd >= 0) clients.push_back(Client{cfd, {}, false});
    }

    for (size_t i = 0; i < clients.size();) {
      Client& c = clients[i];
      // pfds index: 2 + i only valid if client existed before poll; find by fd
      bool readable = false, dead = false;
      for (auto& p : pfds)
        if (p.fd == c.fd) { readable = p.revents & POLLIN; dead = p.revents & (POLLHUP | POLLERR); }
      if (readable) {
        char buf[4096];
        ssize_t n = read(c.fd, buf, sizeof buf);
        if (n <= 0) dead = true;
        else {
          c.inbuf.append(buf, n);
          for (;;) {
            std::string payload;
            size_t consumed = 0;
            if (!frame_complete(c.inbuf, &payload, &consumed)) { dead = true; break; }
            if (consumed == 0) break;  // partial frame
            c.inbuf.erase(0, consumed);
            handle_request(c, split_fields(payload));
          }
        }
      }
      if (dead) {
        close(c.fd);
        clients.erase(clients.begin() + i);
      } else {
        i++;
      }
    }

    if (g_shutdown && !g_cmd.running() && g_service_pid <= 0) break;
    // service daemon gone and nothing running: container is done
    if (!g_shutdown && !service_argv.empty() && g_service_pid == 0 && !g_cmd.running()) break;
  }

  unlink(sock_path);
  if (g_cmd.exited()) return g_cmd.exit_code;
  if (!service_argv.empty()) return g_service_exit;
  return 0;
}
