"""Framework-wide constants: names, labels, ports, paths, domains.

Parity reference: internal/consts/consts.go (ports at consts.go:567-583,
label keys, bootstrap dir /run/clawker/bootstrap). Values are re-derived for
this framework, not copied; the namespace is ``clawker-tpu`` / ``dev.clawker-tpu``
so a reference install and this framework can coexist on one host.
"""

from __future__ import annotations

# ---------------------------------------------------------------------------
# Product identity
# ---------------------------------------------------------------------------

PRODUCT = "clawker-tpu"
CLI_NAME = "clawker"

# ---------------------------------------------------------------------------
# Naming
#
# Containers are named ``clawker.<project>.<agent>`` (reference:
# internal/docker/names.go).  Images are ``clawker-<project>:<tag>`` with the
# two-stage build producing ``:base`` and ``:<harness>`` tags (reference:
# internal/bundler/dockerfile.go GenerateBase/GenerateHarness).
# ---------------------------------------------------------------------------

CONTAINER_NAME_PREFIX = "clawker"
CONTAINER_NAME_SEP = "."
IMAGE_NAME_PREFIX = "clawker-"
IMAGE_TAG_BASE = "base"
IMAGE_TAG_DEFAULT = "default"

CONTROLPLANE_CONTAINER = "clawker-controlplane"
ENVOY_CONTAINER = "clawker-envoy"
COREDNS_CONTAINER = "clawker-coredns"
NETWORK_NAME = "clawker-net"
# Default docker0 gateway: how agent containers reach host-side CP/hostproxy
# when the CP runs as a host daemon (the reference CP is a container at .202
# on clawker-net instead; ARCHITECTURE.md:490).
DOCKER_BRIDGE_GATEWAY = "172.17.0.1"

# Deterministic static addressing on clawker-net (reference:
# .claude/docs/ARCHITECTURE.md:490 -- gateway+.2 Envoy, +.3 CoreDNS, +.202 CP).
ENVOY_HOST_OFFSET = 2
COREDNS_HOST_OFFSET = 3
CONTROLPLANE_HOST_OFFSET = 202

# ---------------------------------------------------------------------------
# Labels (the label jail: every object the engine may touch carries the
# managed label; reference: pkg/whail/engine.go injectManagedFilter +
# internal/docker/labels.go dev.clawker.*)
# ---------------------------------------------------------------------------

LABEL_NS = "dev.clawker-tpu"
LABEL_MANAGED = f"{LABEL_NS}.managed"
LABEL_PROJECT = f"{LABEL_NS}.project"
LABEL_AGENT = f"{LABEL_NS}.agent"
LABEL_HARNESS = f"{LABEL_NS}.harness"
LABEL_ROLE = f"{LABEL_NS}.role"          # agent | controlplane | envoy | coredns | monitor
LABEL_WORKER = f"{LABEL_NS}.worker"      # tpu_vm worker id the object lives on
LABEL_VOLUME_PURPOSE = f"{LABEL_NS}.volume.purpose"  # workspace | config | history
LABEL_IMAGE_KIND = f"{LABEL_NS}.image.kind"          # base | harness | infra
LABEL_CONTENT_SHA = f"{LABEL_NS}.content-sha"        # content-derived infra image cache key
LABEL_LOOP = f"{LABEL_NS}.loop"          # loop-run id for `clawker loop` members
LABEL_LOOP_EPOCH = f"{LABEL_NS}.loop-epoch"  # placement epoch that created the
#                                          container: --resume adopts a
#                                          current-epoch copy and sweeps
#                                          stale ones as ghosts
LABEL_WARMPOOL = f"{LABEL_NS}.warmpool"  # warm-pool placeholder agent name:
#                                          set at pool fill, KEPT through
#                                          adoption so volume sweeps and
#                                          resumes can trace a container
#                                          back to its pool origin
POOL_EPOCH = "pool"                      # LABEL_LOOP_EPOCH value of an
#                                          unadopted warm-pool member

MANAGED_VALUE = "true"

# ---------------------------------------------------------------------------
# Ports (reference: internal/consts/consts.go:567-583 and Envoy listener
# blocks in controlplane/firewall/envoy_config.go)
# ---------------------------------------------------------------------------

CP_ADMIN_PORT = 7443          # AdminService gRPC (mTLS + bearer)
CP_AGENT_PORT = 7444          # AgentService gRPC (clawkerd -> CP register)
CP_HEALTH_PORT = 7080         # /healthz aggregate probe
AGENTD_PORT = 7700            # in-container clawkerd session listener
ENVOY_TLS_PORT = 10000        # SNI/MITM listener
ENVOY_TCP_PORT_BASE = 10001   # sequential raw-TCP listeners
ENVOY_HEALTH_PORT = 9902
HOSTPROXY_PORT = 18374        # host side-channel HTTP (browser-open, OAuth, git-cred)
DNS_PORT = 53

# ---------------------------------------------------------------------------
# In-container paths
# ---------------------------------------------------------------------------

RUN_STATE_DIR = "/run/clawker"             # in-container advisory state files
#                                            (loop-state, agent-env fixup)
BOOTSTRAP_DIR = "/run/clawker/bootstrap"   # cert/key/ca/assertion delivered pre-start
READY_FILE = "/var/run/clawker/ready"      # agentd healthcheck marker
INIT_MARKER = "/var/lib/clawker/initialized"
SUPERVISOR_PATH = "/usr/local/bin/clawker-supervisord"  # native PID 1
SUPERVISOR_SOCKET = "/run/clawker/supervisor.sock"
AGENTD_PYZ_PATH = "/usr/local/lib/clawker-agentd.pyz"   # session daemon zipapp
WORKSPACE_DIR = "/workspace"
CONTAINER_HOME = "/home/agent"   # agent user's home (staging dests are
#                                  home-relative, workspace/strategy mounts
#                                  config/history volumes under it)
CA_CERT_PATH = "/usr/local/share/ca-certificates/clawker-firewall-ca.crt"
# Container-side host-proxy scripts (reference: internal/hostproxy/internals
# host-open.sh + git-credential-clawker.sh, baked in by the bundler)
GIT_CREDENTIAL_HELPER_PATH = "/usr/local/bin/git-credential-clawker"
HOST_OPEN_PATH = "/usr/local/bin/host-open"

# Bootstrap file names inside BOOTSTRAP_DIR (reference: clawkerd/bootstrap.go
# reads cert/key/ca/assertion.jwt).
BOOTSTRAP_FILES = ("agent.crt", "agent.key", "ca.crt", "assertion.jwt", "session.key")

# ---------------------------------------------------------------------------
# eBPF (reference: controlplane/firewall/ebpf/bpf/common.h)
# ---------------------------------------------------------------------------

BPF_PIN_DIR = "/sys/fs/bpf/clawker-tpu"
# SO_MARK applied by Envoy egress so its own upstream connections bypass the
# cgroup hook (loop prevention; reference: common.h:76 CLAWKER_MARK 0xC1A4).
FW_SOCK_MARK = 0xC1A7

# ---------------------------------------------------------------------------
# Environment variable overrides for XDG dirs
# ---------------------------------------------------------------------------

ENV_CONFIG_DIR = "CLAWKER_TPU_CONFIG_DIR"
ENV_DATA_DIR = "CLAWKER_TPU_DATA_DIR"
ENV_STATE_DIR = "CLAWKER_TPU_STATE_DIR"
ENV_CACHE_DIR = "CLAWKER_TPU_CACHE_DIR"

# Project-level config discovery (reference: internal/storage discovery --
# dir-form `.clawker/` vs flat `.clawker.yaml`, bounded walk-up).
PROJECT_DIR_FORM = ".clawker"
PROJECT_FLAT_FORM = ".clawker.yaml"
PROJECT_LOCAL_SUFFIX = ".local"
WALKUP_LIMIT = 24

SETTINGS_FILE = "settings.yaml"
REGISTRY_FILE = "registry.yaml"
EGRESS_RULES_FILE = "egress-rules.yaml"

# ---------------------------------------------------------------------------
# Internal egress requirements: domains every agent needs regardless of
# project rules (reference: internal/config EgressRules() merge of required
# internal + project rules).
# ---------------------------------------------------------------------------

REQUIRED_EGRESS_DOMAINS = (
    "api.anthropic.com",
    "statsig.anthropic.com",
    "sentry.io",
)

# Upstream resolvers for allowed zones (reference:
# controlplane/firewall/coredns_config.go -- Cloudflare malware-blocking).
UPSTREAM_DNS = ("1.1.1.2", "1.0.0.2")
DOCKER_INTERNAL_DNS = "127.0.0.11"  # only valid INSIDE a container netns
INTERNAL_ZONE = "docker.internal"   # answered from the engine inventory

# OTLP/HTTP ingest of the monitor collector; also the side-channel tunnel
# port on workers (fleet/channels.py, provision systemd unit).
OTLP_HTTP_PORT = 4318

# ---------------------------------------------------------------------------
# TPU-VM runtime
# ---------------------------------------------------------------------------

TPU_METADATA_HOST = "metadata.google.internal"
TPU_WORKER_DOCKER_PORT = 2375        # remote dockerd reached only via SSH tunnel
TPU_SSH_USER_DEFAULT = "clawker"
TPU_SSH_MUX_DIR = "ssh-mux"          # under state dir: ControlMaster sockets

DEFAULT_COLD_START_BUDGET_S = 10.0   # BASELINE.md p50 container cold-start target
