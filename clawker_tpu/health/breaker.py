"""Per-worker circuit breaker: closed -> open -> half-open -> closed.

One breaker per pod worker, driven by the health prober (probe
successes/failures) and by scheduler signals (a wedged lane trips it
directly).  The state machine is the classic one:

- **closed** -- worker is serving.  K consecutive failures open it.
- **open** -- worker is quarantined; no probes until the backoff
  deadline.  Backoff grows exponentially per consecutive open (with
  jitter, so a pod of breakers re-probing a recovering daemon doesn't
  stampede it) and is capped.
- **half-open** -- backoff expired; trial probes run.  M consecutive
  successes close the breaker (the worker rejoins the placement set);
  any failure re-opens it with a deeper backoff.

Thread-safety: state mutations ride one lock; transition callbacks fire
OUTSIDE it (the monitor's callback publishes events and re-enters
scheduler code -- holding the breaker lock across that would couple
every prober to event-sink latency).
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass

BREAKER_CLOSED = "closed"
BREAKER_OPEN = "open"
BREAKER_HALF_OPEN = "half_open"


@dataclass(frozen=True)
class BreakerConfig:
    failure_threshold: int = 3      # K consecutive failures -> open
    backoff_base_s: float = 1.0     # first open's re-probe delay
    backoff_max_s: float = 30.0     # cap for repeated opens
    backoff_jitter: float = 0.2     # +/- fraction of the delay
    half_open_successes: int = 2    # M trial successes -> closed


class CircuitBreaker:
    """One worker's serve/quarantine state machine."""

    def __init__(self, name: str, config: BreakerConfig | None = None, *,
                 on_transition=None, clock=time.monotonic,
                 rng: random.Random | None = None):
        self.name = name
        self.config = config or BreakerConfig()
        self.on_transition = on_transition   # (name, old, new, reason)
        self._clock = clock
        self._rng = rng or random.Random()
        self._lock = threading.Lock()
        self._state = BREAKER_CLOSED
        self._failures = 0          # consecutive, while closed
        self._half_open_ok = 0      # consecutive trial successes
        self._open_streak = 0       # consecutive opens since last close
        self._open_until = 0.0
        self.last_error = ""

    # ------------------------------------------------------------- queries

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "state": self._state,
                "failures": self._failures,
                "open_streak": self._open_streak,
                "retry_in_s": (max(0.0, self._open_until - self._clock())
                               if self._state == BREAKER_OPEN else 0.0),
                "last_error": self.last_error,
            }

    def probe_due(self) -> bool:
        """Should a probe run now?  Open breakers sit out their backoff;
        the first call past the deadline transitions to half-open (the
        probe that follows is the trial)."""
        fire = None
        with self._lock:
            if self._state != BREAKER_OPEN:
                return True
            if self._clock() < self._open_until:
                return False
            self._state = BREAKER_HALF_OPEN
            self._half_open_ok = 0
            fire = (BREAKER_OPEN, BREAKER_HALF_OPEN, "backoff expired")
        self._fire(*fire)
        return True

    # ----------------------------------------------------------- verdicts

    def record_success(self) -> None:
        fire = None
        with self._lock:
            if self._state == BREAKER_CLOSED:
                self._failures = 0
                self.last_error = ""    # a below-threshold blip is over;
                #                         don't show it as current state
            elif self._state == BREAKER_HALF_OPEN:
                self._half_open_ok += 1
                if self._half_open_ok >= self.config.half_open_successes:
                    self._state = BREAKER_CLOSED
                    self._failures = 0
                    self._open_streak = 0
                    self.last_error = ""
                    fire = (BREAKER_HALF_OPEN, BREAKER_CLOSED,
                            f"{self._half_open_ok} trial probes ok")
            # success while OPEN: stale signal from before the trip; ignore
        if fire:
            self._fire(*fire)

    def record_failure(self, reason: str = "") -> None:
        fire = None
        with self._lock:
            self.last_error = reason
            if self._state == BREAKER_CLOSED:
                self._failures += 1
                if self._failures >= self.config.failure_threshold:
                    fire = self._open_locked(
                        reason or f"{self._failures} consecutive failures")
            elif self._state == BREAKER_HALF_OPEN:
                # one failed trial re-quarantines with a deeper backoff
                fire = self._open_locked(reason or "half-open trial failed")
        if fire:
            self._fire(*fire)

    def trip(self, reason: str = "") -> None:
        """Immediate open from any state (a wedged lane is conclusive --
        no need to wait out K probe failures)."""
        fire = None
        with self._lock:
            if self._state != BREAKER_OPEN:
                self.last_error = reason
                fire = self._open_locked(reason or "tripped")
        if fire:
            self._fire(*fire)

    # ------------------------------------------------------------ internals

    def _open_locked(self, reason: str) -> tuple[str, str, str]:
        old = self._state
        self._state = BREAKER_OPEN
        self._open_streak += 1
        cfg = self.config
        delay = min(cfg.backoff_base_s * (2 ** (self._open_streak - 1)),
                    cfg.backoff_max_s)
        delay *= 1.0 + cfg.backoff_jitter * (2.0 * self._rng.random() - 1.0)
        self._open_until = self._clock() + delay
        self._failures = 0
        return (old, BREAKER_OPEN, reason)

    def _fire(self, old: str, new: str, reason: str) -> None:
        if self.on_transition is not None:
            self.on_transition(self.name, old, new, reason)
