"""Fleet health monitor: one lightweight prober per pod worker.

Each worker gets a daemon prober thread that round-trips the driver's
probe hook (engine ``ping`` + a cheap ``list_containers`` -- see
``RuntimeDriver.probe``) under a hard deadline and feeds the verdict
into that worker's :class:`~clawker_tpu.health.breaker.CircuitBreaker`.
The deadline is enforced with a per-attempt side thread: a wedged
engine call must cost the prober one blocked daemon thread, never the
probe cadence itself (the same isolation stance as the scheduler's
per-worker lanes).

External signals ride in from the scheduler: consecutive poll failures
(``report_failure``) accelerate the breaker past probe cadence, and a
wedged lane (``report_wedge``) trips it immediately.

Every breaker transition publishes a typed ``worker.health`` event on
the shared :class:`~clawker_tpu.monitor.events.EventBus` (so loop
consumers see ``closed->open`` interleaved with their agent streams, in
order) and bumps a ``health.<state>`` phases counter for bench
attribution.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field

from .. import logsetup, telemetry
from ..engine.drivers import Worker
from ..monitor.events import WORKER_HEALTH, EventBus, WorkerHealthEvent
from ..util import phases
from .breaker import (
    BREAKER_CLOSED,
    BREAKER_HALF_OPEN,
    BREAKER_OPEN,
    BreakerConfig,
    CircuitBreaker,
)

log = logsetup.get("health.monitor")

LATENCY_WINDOW = 256    # per-worker probe-latency samples kept for p50/p95

# Registry metrics (docs/telemetry.md): the breaker-state gauge encodes
# closed=0 / half_open=1 / open=2 so a flat scrape can alert on any
# non-zero worker without string matching.
BREAKER_GAUGE = {BREAKER_CLOSED: 0, BREAKER_HALF_OPEN: 1, BREAKER_OPEN: 2}
_PROBE_SECONDS = telemetry.histogram(
    "health_probe_seconds", "Worker probe round-trip latency (successes)",
    labels=("worker",))
_PROBE_FAILURES = telemetry.counter(
    "health_probe_failures_total", "Failed worker probes", labels=("worker",))
_BREAKER_STATE = telemetry.gauge(
    "health_breaker_state",
    "Worker circuit-breaker state (0=closed 1=half_open 2=open)",
    labels=("worker",))
_ORPHANED = telemetry.counter(
    "health_orphaned_total", "Loops orphaned off a worker by its breaker",
    labels=("worker",))
_MIGRATIONS = telemetry.counter(
    "health_migrations_total", "Loop migrations between workers",
    labels=("src", "dst"))


@dataclass(frozen=True)
class HealthConfig:
    probe_interval_s: float = 1.0
    probe_deadline_s: float = 2.0
    breaker: BreakerConfig = field(default_factory=BreakerConfig)


@dataclass(frozen=True)
class ProbeResult:
    ok: bool
    latency_s: float
    error: str = ""


def _quantile(samples: list[float], q: float) -> float:
    if not samples:
        return 0.0
    ordered = sorted(samples)
    idx = min(len(ordered) - 1, int(q * len(ordered)))
    return ordered[idx]


class HealthMonitor:
    """Drives one CircuitBreaker per worker from probes + scheduler signals."""

    def __init__(self, driver, workers: list[Worker] | None = None, *,
                 config: HealthConfig | None = None,
                 events: EventBus | None = None,
                 on_verdict=None):
        self.driver = driver
        self.workers = list(workers if workers is not None else driver.workers())
        self.config = config or HealthConfig()
        self.events = events if events is not None else EventBus(None)
        self.on_verdict = on_verdict        # (worker_id, old, new, reason)
        self._by_id = {w.id: w for w in self.workers}
        self.breakers: dict[str, CircuitBreaker] = {
            w.id: CircuitBreaker(w.id, self.config.breaker,
                                 on_transition=self._transition)
            for w in self.workers
        }
        self._lock = threading.Lock()
        self._last_probe: dict[str, tuple[float, bool]] = {}  # (mono, ok)
        self._latency: dict[str, deque[float]] = {
            w.id: deque(maxlen=LATENCY_WINDOW) for w in self.workers}
        self._counts: dict[str, dict[str, int]] = {
            w.id: {"probes": 0, "probe_failures": 0,
                   "orphaned": 0, "migrations_out": 0, "migrations_in": 0}
            for w in self.workers}
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        # a worker that never dialed is KNOWN dead: pre-open its breaker
        # so placement routes around it from tick one instead of burning
        # K probe failures (and a strand per loop slotted there) first
        for w in self.workers:
            # seed the gauge so a scrape sees every worker from tick one
            # (pre-opened breakers below overwrite via their transition)
            _BREAKER_STATE.labels(w.id).set(BREAKER_GAUGE[BREAKER_CLOSED])
        for w in self.workers:
            if w.engine is None:
                self.breakers[w.id].trip(
                    w.meta.get("dial_error", "engine not connected"))

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        if self._threads:
            return
        self._stop.clear()
        for w in self.workers:
            t = threading.Thread(target=self._probe_loop, args=(w,),
                                 daemon=True, name=f"health-probe-{w.id}")
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=0.5)   # daemonic; a deadline-blocked attempt
        self._threads.clear()     # thread dies with the process

    # -------------------------------------------------------------- probing

    def _probe_loop(self, worker: Worker) -> None:
        while not self._stop.is_set():
            self.probe_worker(worker)
            self._stop.wait(self.config.probe_interval_s)

    def probe_worker(self, worker: Worker) -> ProbeResult:
        """One probe round for one worker (breaker-gated): runs the
        driver probe hook under the deadline and records the verdict."""
        br = self.breakers[worker.id]
        if not br.probe_due():
            return ProbeResult(False, 0.0, "breaker open (backoff)")
        res = self._probe_once(worker)
        with self._lock:
            self._counts[worker.id]["probes"] += 1
            self._last_probe[worker.id] = (time.monotonic(), res.ok)
            if res.ok:
                self._latency[worker.id].append(res.latency_s)
            else:
                self._counts[worker.id]["probe_failures"] += 1
        if res.ok:
            _PROBE_SECONDS.labels(worker.id).observe(res.latency_s)
            br.record_success()
        else:
            _PROBE_FAILURES.labels(worker.id).inc()
            br.record_failure(res.error)
        return res

    def probe_all(self) -> dict[str, ProbeResult]:
        """One probe round across the fleet, all workers concurrently
        (CLI one-shot): a round costs ONE deadline, not n_dead x
        deadline -- each attempt already rides its own side thread, so
        serializing here would only stack their waits."""
        out: dict[str, ProbeResult] = {}
        rounds = []
        for w in self.workers:
            t = threading.Thread(
                target=lambda w=w: out.__setitem__(w.id, self.probe_worker(w)),
                daemon=True, name=f"health-round-{w.id}")
            t.start()
            rounds.append(t)
        for t in rounds:
            t.join(self.config.probe_deadline_s + 1.0)
        return out

    @staticmethod
    def _bounded(fn, deadline_s: float, name: str) -> tuple[bool, dict]:
        """Run ``fn(out_dict)`` on a daemon side thread with a hard
        deadline; -> (finished_in_time, out_dict).  The shared shape for
        anything that might wedge (engine probes, ssh diagnosis): a hung
        call costs one blocked thread, never the prober's cadence."""
        out: dict = {}
        done = threading.Event()

        def attempt() -> None:
            try:
                fn(out)
            except Exception as e:      # noqa: BLE001 -- failure IS the answer
                out["error"] = str(e) or repr(e)
            done.set()

        threading.Thread(target=attempt, daemon=True, name=name).start()
        return done.wait(deadline_s), out

    def _probe_once(self, worker: Worker) -> ProbeResult:
        """Run the driver probe hook with a hard deadline.  The attempt
        rides its own daemon thread: a wedged engine (hung socket, fake
        'wedge' fault) blocks that thread, not the prober."""
        def attempt(out: dict) -> None:
            t0 = time.perf_counter()
            with phases.phase("health.probe"):
                self.driver.probe(worker)
            out["latency"] = time.perf_counter() - t0

        deadline = self.config.probe_deadline_s
        in_time, out = self._bounded(attempt, deadline,
                                     f"health-attempt-{worker.id}")
        if not in_time:
            err = f"probe deadline {deadline:g}s exceeded"
            extra = self._diagnose(worker)
            if extra:
                err = f"{err}; {extra}"
            return ProbeResult(False, deadline, err)
        if "error" in out:
            return ProbeResult(False, 0.0, out["error"])
        return ProbeResult(True, out["latency"])

    def _diagnose(self, worker: Worker) -> str:
        """The driver's why-is-it-failing one-liner, itself bounded -- a
        wedged transport must not wedge the prober that just survived a
        wedged engine call."""
        def attempt(out: dict) -> None:
            out["msg"] = self.driver.diagnose(worker)

        _, out = self._bounded(attempt, self.config.probe_deadline_s,
                               f"health-diagnose-{worker.id}")
        return out.get("msg", "")

    # ----------------------------------------------- signals from the fleet

    def report_success(self, worker_id: str) -> None:
        br = self.breakers.get(worker_id)
        if br is not None:
            br.record_success()

    def report_failure(self, worker_id: str, reason: str = "") -> None:
        br = self.breakers.get(worker_id)
        if br is not None:
            br.record_failure(reason)

    def report_wedge(self, worker_id: str, reason: str = "") -> None:
        """A wedged lane (poll future pending past the deadline) is
        conclusive: trip the breaker, don't wait out K probe failures."""
        br = self.breakers.get(worker_id)
        if br is not None:
            br.trip(reason or "lane wedged")

    def note_orphaned(self, worker_id: str, n: int = 1) -> None:
        _ORPHANED.labels(worker_id).inc(n)
        with self._lock:
            if worker_id in self._counts:
                self._counts[worker_id]["orphaned"] += n

    def note_migration(self, src_id: str, dst_id: str) -> None:
        _MIGRATIONS.labels(src_id, dst_id).inc()
        with self._lock:
            if src_id in self._counts:
                self._counts[src_id]["migrations_out"] += 1
            if dst_id in self._counts:
                self._counts[dst_id]["migrations_in"] += 1

    # ------------------------------------------------------------- verdicts

    def state(self, worker_id: str) -> str:
        br = self.breakers.get(worker_id)
        return br.state if br is not None else BREAKER_CLOSED

    def probe_says_alive(self, worker_id: str,
                         max_age_s: float | None = None) -> bool:
        """True when the most recent COMPLETED probe of this worker
        succeeded and is fresh.  This is direct evidence -- unlike the
        breaker state, it cannot be perturbed by failure reports from
        other signal sources, so callers use it to tell 'the daemon is
        provably alive, this is a deterministic fault' apart from
        'the worker may be dying' without racing the breaker."""
        rec = self._last_probe.get(worker_id)
        if rec is None:
            return False
        ts, ok = rec
        if not ok:
            return False
        if max_age_s is None:
            max_age_s = 2.0 * (self.config.probe_interval_s
                               + self.config.probe_deadline_s)
        return time.monotonic() - ts <= max_age_s

    def latency_p50_s(self, worker_id: str) -> float:
        """Median recent probe latency in seconds (0.0 = no samples).
        The placement policies read this to rebalance slot shares: a
        slow-but-alive worker gets proportionally fewer placements."""
        with self._lock:
            lat = self._latency.get(worker_id)
            samples = list(lat) if lat else []
        return _quantile(samples, 0.50)

    def healthy_ids(self) -> list[str]:
        return [w.id for w in self.workers
                if self.breakers[w.id].state == BREAKER_CLOSED]

    def pick_target(self, load: dict[str, int],
                    exclude: set[str] | None = None) -> Worker | None:
        """Healthiest placement target: least-loaded worker whose breaker
        is CLOSED.  Half-open workers are mid-trial and never receive
        migrations (one flap would bounce the loop right back); ties
        break on pod worker order."""
        exclude = exclude or set()
        candidates = [w for w in self.workers
                      if w.id not in exclude
                      and self.breakers[w.id].state == BREAKER_CLOSED]
        if not candidates:
            return None
        return min(candidates, key=lambda w: (load.get(w.id, 0), w.index))

    def stats(self) -> list[dict]:
        out = []
        with self._lock:
            for w in self.workers:
                lat = list(self._latency[w.id])
                counts = dict(self._counts[w.id])
                snap = self.breakers[w.id].snapshot()
                out.append({
                    "worker": w.id,
                    "state": snap["state"],
                    "breaker_state_gauge": BREAKER_GAUGE.get(snap["state"], -1),
                    "probe_p50_ms": round(_quantile(lat, 0.50) * 1000, 2),
                    "probe_p95_ms": round(_quantile(lat, 0.95) * 1000, 2),
                    "retry_in_s": round(snap["retry_in_s"], 2),
                    "last_error": snap["last_error"],
                    **counts,
                })
        return out

    # ------------------------------------------------------------ internals

    def _transition(self, worker_id: str, old: str, new: str,
                    reason: str) -> None:
        phases.incr(f"health.{new}")
        _BREAKER_STATE.labels(worker_id).set(BREAKER_GAUGE.get(new, -1))
        ev = WorkerHealthEvent(worker_id, old, new, reason)
        self.events.emit(worker_id, WORKER_HEALTH, ev.detail())
        log.info("worker %s: %s -> %s (%s)", worker_id, old, new, reason)
        if self.on_verdict is not None:
            try:
                self.on_verdict(worker_id, old, new, reason)
            except Exception:
                log.exception("health verdict consumer failed for %s",
                              worker_id)
