"""Fleet health & failover: per-worker probes, circuit breakers, verdicts.

The pod-scale counterpart of the per-worker lanes (ISSUE 1) and the
connection pool (ISSUE 2): those isolate and cheapen a wedged or dead
worker engine, this subsystem *detects* it, reports it, and lets the
loop scheduler move the stranded agent loops.  Production cluster
managers treat machine failure as the common case (Borg, EuroSys 2015)
and recover by restarting from clean state instead of diagnosing in
place (crash-only software, HotOS 2003) -- the breaker + migration
model here follows that shape.
"""

from .breaker import BREAKER_CLOSED, BREAKER_HALF_OPEN, BREAKER_OPEN, BreakerConfig, CircuitBreaker
from .monitor import HealthConfig, HealthMonitor, ProbeResult

__all__ = [
    "BREAKER_CLOSED",
    "BREAKER_HALF_OPEN",
    "BREAKER_OPEN",
    "BreakerConfig",
    "CircuitBreaker",
    "HealthConfig",
    "HealthMonitor",
    "ProbeResult",
]
