"""LeaseManager: the router side of the capacity-lease protocol.

The problem leases solve (docs/federation.md#leases): a naive router
asks each pod's admission controller "may I launch?" once per launch --
one WAN round-trip on the hot path of every loop, multiplied by the
DCN RTT between front tier and pod.  A lease amortizes that: the
router acquires a bounded, renewable block of N launch credits with a
TTL from the pod's loopd (``lease_acquire``), spends them LOCALLY
(zero RPCs), and only goes back to the wire when the block runs out or
the TTL nears expiry.  The pod's admission token buckets still meter
every real launch -- a lease is router-side flow control, not a bypass
-- so the worst a stale lease can cause is a short queue at the pod,
never an over-cap launch.

Expiry discipline: a renew against a lapsed lease fails (the daemon
swept it); the manager drops its state and re-acquires.  Partitions
therefore cost exactly one failed RPC before recovery, and a pod that
restarted mid-lease simply sees a fresh acquire.

``amortize=False`` degrades every spend to a per-launch
``lease_acquire(tokens=1)`` round-trip -- the naive protocol, kept as
the measured baseline the federation bench compares against (the >=5x
round-trip amortization gate).
"""

from __future__ import annotations

import time
from dataclasses import dataclass

from .. import logsetup, telemetry
from ..errors import ClawkerError
from ..loopd.client import LoopdClient

log = logsetup.get("federation.lease")

# router->pod admission control RPCs, by pod and verb
# (acquire|renew|release); the amortization evidence the bench gates
_LEASE_RPCS = telemetry.counter(
    "federation_lease_rpcs_total",
    "Router-to-pod lease RPCs by pod and verb",
    labels=("pod", "verb"))

# renew when this fraction of the TTL remains: early enough that one
# slow RPC does not lapse the lease, late enough to amortize
RENEW_AT_TTL_FRACTION = 0.25

# bounded wait when a pod's credit pool is exhausted (grant 0):
# attempts, not time -- a pod that never grants reads as an error
EXHAUSTED_RETRIES = 50


@dataclass
class _PodLease:
    lease_id: str
    credits: int
    granted: int
    ttl_s: float
    expires_at: float       # monotonic


class LeaseManager:
    """Per-pod capacity leases, spent locally on the launch hot path.

    ``spend(pod, client)`` is the only call the router's submit path
    makes: it consumes one local credit when the pod's lease block is
    live, and pays a wire round-trip only to (re)fill the block.
    ``rtt_s`` injects a deterministic sleep per wire RPC -- the DCN
    round trip the federation bench models (fake pods answer over a
    loopback socket; the injected RTT is what makes per-launch
    admission measurably expensive, as it is on a real front tier).
    """

    def __init__(self, *, tokens: int = 0, ttl_s: float = 0.0,
                 amortize: bool = True, rtt_s: float = 0.0):
        self.tokens = int(tokens)
        self.ttl_s = float(ttl_s)
        self.amortize = amortize
        self.rtt_s = max(0.0, float(rtt_s))
        self.rpcs = 0                       # total wire round-trips
        self.spends = 0                     # total credits consumed
        self._leases: dict[str, _PodLease] = {}

    # ------------------------------------------------------------- wire

    def _rpc(self, pod: str, verb: str, fn, *args, **kw) -> dict:
        if self.rtt_s > 0:
            time.sleep(self.rtt_s)
        self.rpcs += 1
        _LEASE_RPCS.labels(pod, verb).inc()
        return fn(*args, **kw)

    def _acquire(self, pod: str, client: LoopdClient, *, tenant: str,
                 tokens: int) -> _PodLease | None:
        doc = self._rpc(pod, "acquire", client.lease_acquire,
                        tenant=tenant, tokens=tokens, ttl_s=self.ttl_s)
        granted = int(doc.get("tokens", 0))
        if granted <= 0:
            return None
        lease = _PodLease(
            lease_id=str(doc.get("lease", "")),
            credits=granted,
            granted=granted,
            ttl_s=float(doc.get("ttl_s", self.ttl_s)),
            expires_at=time.monotonic() + float(doc.get("ttl_s", 0.0)))
        self._leases[pod] = lease
        return lease

    # -------------------------------------------------------- hot path

    def spend(self, pod: str, client: LoopdClient, *,
              tenant: str = "") -> None:
        """Consume one launch credit against ``pod``; acquires/renews
        over the wire only when the local block is out.  Raises
        :class:`ClawkerError` when the pod refuses to grant credits
        across the bounded retry budget (pool exhausted for too long).
        """
        self.spends += 1
        if not self.amortize:
            # the per-launch baseline: one admission round-trip per
            # spend, credits never held locally
            for _ in range(EXHAUSTED_RETRIES):
                doc = self._rpc(pod, "acquire", client.lease_acquire,
                                tenant=tenant, tokens=1, ttl_s=self.ttl_s)
                if int(doc.get("tokens", 0)) > 0:
                    return
                time.sleep(float(doc.get("retry_after_s", 0.05)))
            raise ClawkerError(
                f"federation: pod {pod} granted no launch credit")
        for _ in range(EXHAUSTED_RETRIES):
            lease = self._leases.get(pod)
            now = time.monotonic()
            if lease is not None and now < lease.expires_at:
                if lease.credits > 0:
                    lease.credits -= 1
                    # opportunistic renew near TTL expiry so the NEXT
                    # spend never stalls on a lapsed lease
                    if (lease.expires_at - now
                            < lease.ttl_s * RENEW_AT_TTL_FRACTION):
                        self._renew(pod, client)
                    return
                # block spent inside the TTL: refresh the credit block
                if self._renew(pod, client):
                    continue
            else:
                self._leases.pop(pod, None)
            if self._acquire(pod, client, tenant=tenant,
                             tokens=self.tokens) is not None:
                continue
            time.sleep(0.05)
        raise ClawkerError(
            f"federation: pod {pod} granted no launch credit")

    def _renew(self, pod: str, client: LoopdClient) -> bool:
        lease = self._leases.get(pod)
        if lease is None:
            return False
        try:
            doc = self._rpc(pod, "renew", client.lease_renew,
                            lease.lease_id)
        except (ClawkerError, OSError):
            # swept by the daemon (TTL lapsed, daemon restarted): drop
            # and let the caller re-acquire -- one failed RPC, no stall
            self._leases.pop(pod, None)
            return False
        lease.credits = int(doc.get("tokens", lease.granted))
        lease.granted = max(lease.granted, lease.credits)
        lease.ttl_s = float(doc.get("ttl_s", lease.ttl_s))
        lease.expires_at = time.monotonic() + lease.ttl_s
        return True

    # ------------------------------------------------------- lifecycle

    def forget(self, pod: str) -> None:
        """Drop local state for a dead pod (no wire traffic)."""
        self._leases.pop(pod, None)

    def release_all(self, clients: dict[str, LoopdClient]) -> None:
        """Best-effort release of every held lease (router shutdown);
        a pod that went away just keeps its lease until TTL sweep."""
        for pod, lease in list(self._leases.items()):
            client = clients.get(pod)
            if client is None:
                continue
            try:
                self._rpc(pod, "release", client.lease_release,
                          lease.lease_id)
            except (ClawkerError, OSError):
                pass
            self._leases.pop(pod, None)

    def stats(self) -> dict:
        return {
            "rpcs": self.rpcs,
            "spends": self.spends,
            "leases": {
                pod: {"credits": lease.credits, "granted": lease.granted}
                for pod, lease in self._leases.items()
            },
        }
