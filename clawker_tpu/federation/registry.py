"""PodRegistry: the router's live view of every federated pod.

One :class:`PodState` per registered loopd endpoint, refreshed from the
pod's status RPC (docs/federation.md#registry).  The refresh is the
ONLY control-plane poll the router runs -- everything pod-tier
placement consults (load, breaker counts, lease pool, measured RTT)
rides the one status round-trip, so adding a pod costs one RPC per
``federation.status_interval_s``, not one per decision.

A pod whose status RPC fails is marked dead (``alive=False``) but kept
in the registry: dead pods are what :meth:`FederationRouter.migrate_pod
<clawker_tpu.federation.router.FederationRouter.migrate_pod>` drains,
and a later successful refresh revives them.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from .. import logsetup
from ..errors import ClawkerError
from ..loopd.client import LoopdClient

log = logsetup.get("federation.registry")


@dataclass
class PodState:
    """One pod as the router sees it: identity, the control client,
    and the last status snapshot's placement-relevant digest."""

    name: str
    client: LoopdClient
    index: int
    alive: bool = True
    workers: int = 0            # live workers behind the pod's admission
    load: int = 0               # live run slots (sum of parallel)
    runs: list[str] = field(default_factory=list)   # live run ids
    breakers_open: int = 0      # workers with a non-closed breaker
    rtt_s: float = 0.0          # measured status round-trip
    last_status: dict = field(default_factory=dict)
    last_seen: float = 0.0      # monotonic stamp of last good refresh

    # run states that still own capacity (mirror loopd's live set:
    # anything not yet terminal)
    _LIVE_STATES = ("starting", "running", "draining")

    def digest(self, doc: dict, rtt_s: float) -> None:
        """Fold one status reply into the placement-relevant fields."""
        self.alive = True
        self.last_status = doc
        self.rtt_s = rtt_s
        self.last_seen = time.monotonic()
        admission = doc.get("admission") or {}
        # admission only lists workers that have seen launches; an idle
        # pod still reports its fleet via workerd/health rows
        self.workers = (len(admission.get("workers") or {})
                        or len(doc.get("workerd") or {})
                        or len(doc.get("health") or []))
        load = 0
        runs: list[str] = []
        for r in doc.get("runs") or []:
            state = str(r.get("state", ""))
            if state and state not in self._LIVE_STATES:
                continue
            runs.append(str(r.get("run", "")))
            load += max(1, int(r.get("parallel", 0)))
        self.load = load
        self.runs = runs
        open_count = 0
        for h in doc.get("health") or []:
            breaker = str(h.get("breaker", h.get("state", "closed")))
            if breaker and breaker != "closed":
                open_count += 1
        self.breakers_open = open_count

    @property
    def healthy(self) -> bool:
        """Placement-eligible: alive AND a majority of workers carry a
        closed breaker.  A pod with most breakers open is effectively
        down for new placements even though its daemon still answers --
        the same stance worker-tier placement takes one level down."""
        if not self.alive:
            return False
        if self.workers and self.breakers_open * 2 >= self.workers:
            return False
        return True


class PodRegistry:
    """Name -> :class:`PodState` over the federation's loopd endpoints.

    Built from connected clients (normally ``discover_all``'s output);
    pod names come from each daemon's hello (``federation.name``,
    defaulting to the socket directory name), with positional
    ``pod<i>`` fallbacks so an unnamed fleet still federates.
    """

    def __init__(self, clients: list[LoopdClient]):
        self.pods: dict[str, PodState] = {}
        for i, client in enumerate(clients):
            name = ""
            try:
                name = client.daemon_pod()
            except (ClawkerError, OSError):
                pass
            name = name or f"pod{i}"
            if name in self.pods:        # two daemons claiming one name
                name = f"{name}@{i}"
            self.pods[name] = PodState(name=name, client=client, index=i)

    def __len__(self) -> int:
        return len(self.pods)

    def names(self) -> list[str]:
        """Pod names in index order (the federation's pod order)."""
        return [p.name for p in sorted(self.pods.values(),
                                       key=lambda p: p.index)]

    def get(self, name: str) -> PodState | None:
        return self.pods.get(name)

    def refresh(self, name: str | None = None) -> None:
        """Poll status on one pod (or all): fold the reply into its
        :class:`PodState`, mark the pod dead on any RPC failure."""
        targets = [self.pods[name]] if name else list(self.pods.values())
        for pod in targets:
            t0 = time.monotonic()
            try:
                doc = pod.client.status()
            except (ClawkerError, OSError) as e:
                if pod.alive:
                    log.warning("pod %s status failed (%s): marking dead",
                                pod.name, e)
                pod.alive = False
                continue
            pod.digest(doc, time.monotonic() - t0)

    def alive_pods(self) -> list[PodState]:
        return [p for p in sorted(self.pods.values(), key=lambda p: p.index)
                if p.alive]

    def close(self) -> None:
        for pod in self.pods.values():
            pod.client.close()
