"""Multi-pod federation: the front-tier run router (docs/federation.md).

Every subsystem below this package tops out at ONE pod: the scheduler
places onto one pod's workers, loopd admits onto one pod's daemons.
Federation is the next scale multiplier -- a router that owns a
registry of per-pod loopd endpoints and places whole runs (or shards
of one large ``--parallel N`` run) across pods, without rewriting the
scheduler:

- :mod:`.registry` -- :class:`PodRegistry`: the live view of every
  pod (status RPC polls: load, breaker counts, lease pool, measured
  control RTT), the health input to pod-tier placement.
- :mod:`.lease` -- :class:`LeaseManager`: the router side of the
  capacity-lease protocol.  Bounded, renewable blocks of launch
  credits are acquired from each pod's loopd ONCE per block, then
  spent locally -- zero WAN admission round-trips on the launch hot
  path (the lease amortizes admission the way workerd amortized
  engine calls).
- :mod:`.router` -- :class:`FederationRouter`: two-level placement
  (:class:`~clawker_tpu.placement.PodPolicy` picks the pod, the pod's
  own policy places within it), global WFQ tenant fairness layered on
  top of per-pod tenant caps, and cross-pod migration of a dead pod's
  runs via the journal/``adopt_run`` machinery.

Degrade matrix: with no ``federation.pods`` configured the router is
never built and the single-pod loopd path is byte-identical to before.
"""

from __future__ import annotations

from .lease import LeaseManager
from .registry import PodRegistry, PodState
from .router import FederationRouter

__all__ = [
    "FederationRouter",
    "LeaseManager",
    "PodRegistry",
    "PodState",
]
