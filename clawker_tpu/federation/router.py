"""FederationRouter: two-level placement, global fairness, migration.

The front tier (docs/federation.md#router).  One router owns N pods'
loopd endpoints and answers three questions the single-pod stack
cannot:

- **Where does a run land?**  Two-level placement: a
  :class:`~clawker_tpu.placement.PodPolicy` picks the pod -- locality
  tier (DCN-adjacent pod groups via
  :func:`~clawker_tpu.fleet.inventory.federation_topology`), live load
  and measured status RTT from the :class:`PodRegistry`, pod-level
  breaker state -- then the pod's OWN per-run policy places loops onto
  workers, untouched.  The router never sees a worker.
- **Who goes first?**  Global WFQ across tenants
  (:meth:`FederationRouter.submit_many`): the same virtual-finish-time
  discipline the per-pod admission controller runs, layered one level
  up, so a tenant saturating pod A cannot starve pod B's queue.
- **What happens when a pod dies?**  :meth:`migrate_pod` re-places a
  dead pod's live runs onto survivors via ``adopt_run`` -- the journal
  replay / resume machinery that already moves loops between workers,
  generalized one level up.  Runs keep their ids, so the journal's
  exactly-once accounting holds across the move.

Launch hot path: ``submit`` spends a local lease credit
(:class:`~clawker_tpu.federation.lease.LeaseManager`) and pays exactly
one wire round-trip -- the submit itself.  Admission adds zero WAN
hops.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from pathlib import Path

from .. import logsetup, telemetry
from ..engine.drivers import Worker
from ..errors import ClawkerError
from ..fleet.inventory import federation_topology
from ..health import BREAKER_CLOSED, BREAKER_OPEN
from ..loopd.client import LoopdClient
from ..monitor.ledger import FLIGHT_DIR, FlightRecorder
from ..placement import PlacementContext, PodPolicy
from ..telemetry.spans import SpanRecord
from ..tracing.context import TraceContext
from ..tracing.names import SPAN_ROUTER_SUBMIT
from ..tracing.skew import ChannelClock
from ..util import ids
from .lease import LeaseManager
from .registry import PodRegistry, PodState

# placeholder trace id on submit-frame traceparents: the run id (= the
# real trace id) does not exist until the pod's ack names it, and the
# receiving pod only reads the SPAN id (its upstream parent) anyway
PENDING_TRACE = "pending"

log = logsetup.get("federation.router")

# runs routed, by landing pod and tenant
_SUBMITS = telemetry.counter(
    "federation_submits_total", "Runs routed to a pod by the federation "
    "router", labels=("pod", "tenant"))
# cross-pod migrations, by ADOPTING pod
_MIGRATIONS = telemetry.counter(
    "federation_migrations_total", "Runs adopted cross-pod after a pod "
    "died", labels=("pod",))


@dataclass
class _TenantShare:
    """Router-tier WFQ state for one tenant: same virtual-finish-time
    discipline as placement.admission, one level up."""

    weight: float = 1.0
    vfinish: float = 0.0
    dispatched: int = 0


class FederationRouter:
    """Places runs across pods; see the module docstring.

    ``clients`` is normally ``loopd.client.discover_all(cfg)``'s
    output.  ``amortize=False`` selects the per-launch admission
    baseline (bench comparison only); ``control_rtt_s`` injects a
    deterministic DCN round trip on every admission RPC.
    """

    def __init__(self, cfg, clients: list[LoopdClient], *,
                 amortize: bool = True, control_rtt_s: float = 0.0):
        if not clients:
            raise ClawkerError("federation: no pod endpoints "
                               "(is loopd running on any pod?)")
        self.cfg = cfg
        fed = cfg.settings.federation
        self.registry = PodRegistry(clients)
        self.lease = LeaseManager(
            tokens=fed.lease_tokens, ttl_s=fed.lease_ttl_s,
            amortize=amortize, rtt_s=control_rtt_s)
        self.policy = PodPolicy()
        self.topology = federation_topology(fed.shape, len(self.registry))
        self._placements: dict[str, str] = {}       # run id -> pod name
        self._shares: dict[str, _TenantShare] = {}
        self._vtime = 0.0
        # distributed tracing (docs/tracing.md): the router IS the root
        # clock.  One skew estimator per pod, fed by the ``ts`` replies
        # on RPCs the router already pays (submit acks); ``router.submit``
        # hop spans land in a router-lifetime flight recorder.
        self.name = fed.name or "front"
        self._clocks: dict[str, ChannelClock] = {}
        self.flight: FlightRecorder | None = None
        try:
            tele = cfg.settings.telemetry
            if tele.tracing.enable and tele.flight_recorder.enable:
                self.flight = FlightRecorder(
                    Path(cfg.logs_dir) / FLIGHT_DIR
                    / f"router-{self.name}.jsonl",
                    max_bytes=tele.flight_recorder.max_bytes)
        except AttributeError:
            self.flight = None
        self.registry.refresh()

    # ------------------------------------------------------ pod tier

    def _context(self) -> PlacementContext:
        """Pod stand-ins as placement Workers: id = pod name, index =
        pod index, engine = the pod's control client (non-None = pod
        addressable).  The worker-tier policy machinery then applies
        verbatim, one level up."""
        pods = sorted(self.registry.pods.values(), key=lambda p: p.index)
        workers = [Worker(id=p.name, index=p.index, hostname=p.name,
                          engine=p.client if p.alive else None)  # type: ignore[arg-type]
                   for p in pods]
        states = {p.name: (BREAKER_CLOSED if p.healthy else BREAKER_OPEN)
                  for p in pods}
        latency = {p.name: p.rtt_s for p in pods}
        load = {p.name: p.load for p in pods}
        return PlacementContext(
            workers=workers,
            breaker_state=lambda wid: states.get(wid, BREAKER_CLOSED),
            latency_s=lambda wid: latency.get(wid, 0.0),
            load=load,
            topology=self.topology if self.topology.known else None)

    def pick_pod(self, *, exclude: set[str] | None = None,
                 near: str | None = None) -> PodState:
        ctx = self._context()
        near_w = None
        if near is not None:
            near_w = next((w for w in ctx.workers if w.id == near), None)
        picked = self.policy.pick(ctx, exclude=exclude, near=near_w)
        if picked is None:
            raise ClawkerError("federation: no healthy pod eligible")
        pod = self.registry.get(picked.id)
        assert pod is not None
        return pod

    def plan_pods(self, n: int) -> list[PodState]:
        """One pod per slot for ``n`` slots (sharding a --parallel N
        run): locality-packed, load/latency-weighted, health-gated."""
        ctx = self._context()
        return [self.registry.pods[w.id]
                for w in self.policy.plan(ctx, n)]

    # ------------------------------------------------------ submit path

    def _clock(self, pod_name: str) -> ChannelClock:
        clock = self._clocks.get(pod_name)
        if clock is None:
            clock = self._clocks[pod_name] = ChannelClock()
        return clock

    def _submit_to(self, pod: PodState, doc: dict, *, keep: bool,
                   tenant: str) -> dict:
        """One traced submit RPC: the router's traceparent and its
        cumulative clock-offset estimate for this pod ride the frame,
        the round-trip itself feeds the pod's skew estimator, and the
        ``router.submit`` hop span is recorded once the ack names the
        run (= trace) id.  Zero new round-trips."""
        clock = self._clock(pod.name)
        span_id = ids.short_id(16) if self.flight is not None else ""
        tp = (TraceContext(PENDING_TRACE, span_id).to_header()
              if span_id else "")
        t0 = time.time()
        ack = pod.client.submit_run(doc, keep=keep, stream=False, tp=tp,
                                    clock_offset_s=clock.cumulative())
        t1 = time.time()
        clock.observe(t0, float(ack.get("ts") or 0.0), t1)
        run_id = str(ack.get("run", ""))
        if run_id and self.flight is not None:
            self.flight.append(SpanRecord(
                trace_id=run_id, span_id=span_id, parent_id="",
                name=SPAN_ROUTER_SUBMIT, agent="", worker=self.name,
                t_start=t0, t_end=t1,
                attrs={"pod": pod.name, "tenant": tenant or "-",
                       "wan_ms": round((t1 - t0) * 1000.0, 3)}).to_json())
        return ack

    def submit(self, spec_doc: dict, *, keep: bool = False
               ) -> tuple[str, dict]:
        """Route one whole run: pick a pod, spend a lease credit,
        submit.  Returns ``(pod_name, ack)``."""
        tenant = str(spec_doc.get("tenant") or "")
        pod = self.pick_pod()
        self.lease.spend(pod.name, pod.client, tenant=tenant)
        ack = self._submit_to(pod, dict(spec_doc), keep=keep,
                              tenant=tenant)
        run_id = str(ack.get("run", ""))
        if run_id:
            self._placements[run_id] = pod.name
        pod.load += max(1, int(spec_doc.get("parallel", 1)))
        _SUBMITS.labels(pod.name, tenant or "-").inc()
        return pod.name, ack

    def submit_sharded(self, spec_doc: dict, *, keep: bool = False
                       ) -> list[tuple[str, int, dict]]:
        """Shard one large ``--parallel N`` run across pods: the pod
        policy assigns each of the N slots a pod, contiguous slots on
        one pod become one per-pod run of that shard's size.  Returns
        ``[(pod_name, shard_parallel, ack), ...]``.  Each shard is an
        ordinary run under its pod (own id, own agents); the caller
        aggregates."""
        n = max(1, int(spec_doc.get("parallel", 1)))
        shards: dict[str, int] = {}
        for pod in self.plan_pods(n):
            shards[pod.name] = shards.get(pod.name, 0) + 1
        tenant = str(spec_doc.get("tenant") or "")
        out: list[tuple[str, int, dict]] = []
        for pod_name, size in shards.items():
            pod = self.registry.pods[pod_name]
            self.lease.spend(pod.name, pod.client, tenant=tenant)
            doc = dict(spec_doc)
            doc["parallel"] = size
            ack = self._submit_to(pod, doc, keep=keep, tenant=tenant)
            run_id = str(ack.get("run", ""))
            if run_id:
                self._placements[run_id] = pod.name
            pod.load += size
            _SUBMITS.labels(pod.name, tenant or "-").inc()
            out.append((pod.name, size, ack))
        return out

    # --------------------------------------------------- global fairness

    def _share(self, tenant: str, weight: float = 1.0) -> _TenantShare:
        share = self._shares.get(tenant)
        if share is None:
            share = self._shares[tenant] = _TenantShare(weight=weight)
        if weight != 1.0:
            share.weight = weight
        return share

    def dispatch_order(self, requests: list[tuple[str, dict]]
                       ) -> list[int]:
        """WFQ order over ``(tenant, spec_doc)`` requests: each request
        gets a virtual finish time ``start + 1/weight`` against its
        tenant's share, dispatch goes in vfinish order -- so a tenant
        that submitted 400 runs interleaves with one that submitted 4
        instead of burying it (the admission controller's discipline,
        at router scope, on top of per-pod tenant caps)."""
        stamped: list[tuple[float, int]] = []
        for i, (tenant, doc) in enumerate(requests):
            weight = float(doc.get("tenant_weight") or 1.0)
            share = self._share(tenant or "-", weight)
            start = max(self._vtime, share.vfinish)
            share.vfinish = start + 1.0 / max(share.weight, 1e-9)
            stamped.append((share.vfinish, i))
        stamped.sort()
        return [i for _, i in stamped]

    def submit_many(self, requests: list[tuple[str, dict]], *,
                    keep: bool = False) -> list[tuple[str, dict]]:
        """Submit a batch of ``(tenant, spec_doc)`` in global-WFQ
        order; result list is index-aligned with ``requests``."""
        out: list[tuple[str, dict] | None] = [None] * len(requests)
        for i in self.dispatch_order(requests):
            tenant, doc = requests[i]
            self._vtime = max(self._vtime,
                              self._shares[tenant or "-"].vfinish)
            out[i] = self.submit(doc, keep=keep)
            self._shares[tenant or "-"].dispatched += 1
        return [r for r in out if r is not None]

    # -------------------------------------------------------- migration

    def migrate_pod(self, pod_name: str, *,
                    orphan_grace_s: float | None = None) -> list[str]:
        """Drain a dead pod: re-place every live run it hosted onto
        surviving pods via ``adopt_run`` (journal replay + resume under
        the survivor's admission).  Runs keep their ids -- loop
        accounting stays exactly-once across the move.  Returns the
        migrated run ids."""
        dead = self.registry.get(pod_name)
        if dead is None:
            raise ClawkerError(f"federation: unknown pod {pod_name!r}")
        dead.alive = False
        self.lease.forget(pod_name)
        runs = list(dead.runs)
        runs += [r for r, p in self._placements.items()
                 if p == pod_name and r not in runs]
        moved: list[str] = []
        for run_id in runs:
            try:
                target = self.pick_pod(exclude={pod_name}, near=pod_name)
            except ClawkerError:
                log.error("pod %s died with %d runs left and no healthy "
                          "survivor", pod_name, len(runs) - len(moved))
                break
            try:
                target.client.adopt_run(run_id,
                                        orphan_grace_s=orphan_grace_s)
            except (ClawkerError, OSError) as e:
                log.warning("pod %s refused adoption of %s: %s",
                            target.name, run_id, e)
                continue
            self._placements[run_id] = target.name
            target.load += 1
            _MIGRATIONS.labels(target.name).inc()
            moved.append(run_id)
            log.info("migrated run %s: %s -> %s", run_id, pod_name,
                     target.name)
        return moved

    # -------------------------------------------------------- lifecycle

    def placements(self) -> dict[str, str]:
        """run id -> pod name, as routed (migrations folded in)."""
        return dict(self._placements)

    def status(self) -> dict:
        """One doc over every pod: per-pod digests + router state
        (what ``clawker fed status`` renders)."""
        self.registry.refresh()
        pods = []
        for p in sorted(self.registry.pods.values(), key=lambda x: x.index):
            pods.append({
                "pod": p.name, "alive": p.alive, "healthy": p.healthy,
                "workers": p.workers, "load": p.load,
                "runs": list(p.runs), "breakers_open": p.breakers_open,
                "rtt_ms": round(p.rtt_s * 1000.0, 2),
                "leases": (p.last_status.get("leases") or {}),
            })
        return {
            "pods": pods,
            "placements": self.placements(),
            "lease": self.lease.stats(),
            "tenants": {t: {"weight": s.weight,
                            "dispatched": s.dispatched}
                        for t, s in self._shares.items()},
        }

    def close(self) -> None:
        self.lease.release_all(
            {p.name: p.client for p in self.registry.pods.values()
             if p.alive})
        self.registry.close()
        if self.flight is not None:
            self.flight.close()
