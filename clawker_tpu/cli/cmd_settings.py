"""settings + auth + version + alias verbs.

Parity reference: internal/cmd/{settings,auth,version,alias}
(SURVEY.md 2.4).  ``settings`` reads/writes the layered YAML through
the same provenance-routed store the rest of the framework uses; user
aliases expand before dispatch in root.py.
"""

from __future__ import annotations

import json

import click
import yaml

from .. import consts
from ..config.schema import to_dict
from .factory import Factory

pass_factory = click.make_pass_decorator(Factory)


# ------------------------------------------------------------------ settings

@click.group("settings")
def settings_group():
    """Inspect and edit settings.yaml."""


def _dotted_get(tree, path: str):
    cur = tree
    for part in path.split("."):
        if not isinstance(cur, dict) or part not in cur:
            raise KeyError(path)
        cur = cur[part]
    return cur


@settings_group.command("list")
@pass_factory
def settings_list(f: Factory):
    click.echo(yaml.safe_dump(to_dict(f.config.settings), sort_keys=True) or "{}")


@settings_group.command("get")
@click.argument("path")
@pass_factory
def settings_get(f: Factory, path):
    """Read one dotted key (e.g. firewall.enable)."""
    try:
        val = _dotted_get(to_dict(f.config.settings), path)
    except KeyError:
        # unset-but-valid keys answer their schema default
        from dataclasses import is_dataclass

        from ..config.schema import Settings

        try:
            cur = Settings()
            for part in path.split("."):
                cur = getattr(cur, part)
            val = cur
        except AttributeError:
            raise click.ClickException(f"unknown settings key {path!r}")
        if is_dataclass(val):
            # full subtree incl. defaults (to_dict drops default values)
            import dataclasses

            val = dataclasses.asdict(val)
    click.echo(json.dumps(val) if not isinstance(val, str) else val)


@settings_group.command("edit")
@click.option("--select", "select_mode", is_flag=True,
              help="Numbered-select editor instead of the full browser.")
@pass_factory
def settings_edit(f: Factory, select_mode):
    """Interactively browse + edit settings fields (reflection-driven,
    reference internal/storeui + internal/tui field browser)."""
    from ..ui.fieldbrowser import edit_store

    n = edit_store(f.config.settings_store_ref, f.streams,
                   select_mode=select_mode)
    click.echo(f"{n} field(s) changed")


@settings_group.command("set")
@click.argument("path")
@click.argument("value")
@pass_factory
def settings_set(f: Factory, path, value):
    """Write one dotted key into the user settings layer."""
    from ..config.config import settings_store

    try:
        parsed = json.loads(value)
    except json.JSONDecodeError:
        parsed = value
    # schema guard: the dotted path must exist AND the value must match
    # the field type -- `set firewall.enable no` silently storing the
    # truthy string "no" would invert a security setting
    from ..config.schema import Settings

    cur = Settings()
    parts = path.split(".")
    try:
        for part in parts[:-1]:
            cur = getattr(cur, part)
        current = getattr(cur, parts[-1])
    except AttributeError:
        raise click.ClickException(f"unknown settings key {path!r}")
    if isinstance(current, bool):
        if not isinstance(parsed, bool):
            raise click.ClickException(
                f"{path} is a boolean; use `true` or `false` (got {value!r})")
    elif isinstance(current, int) and not isinstance(parsed, (int, float)):
        raise click.ClickException(f"{path} is a number (got {value!r})")
    elif isinstance(current, float) and not isinstance(parsed, (int, float)):
        raise click.ClickException(f"{path} is a number (got {value!r})")
    elif isinstance(current, str) and not isinstance(parsed, str):
        parsed = str(parsed)
    elif isinstance(current, list) and not isinstance(parsed, list):
        raise click.ClickException(
            f"{path} is a list; pass JSON, e.g. '[\"a\", \"b\"]'")
    store = settings_store()
    store.set(path, parsed)
    click.echo(f"{path} = {json.dumps(parsed)}")


# ---------------------------------------------------------------------- auth

@click.group("auth")
def auth_group():
    """PKI and identity management."""


@auth_group.command("rotate")
@click.confirmation_option(
    prompt="Rotate the CA? Every agent leaf and MITM cert becomes invalid; "
           "images must be rebuilt and agents re-enrolled.")
@pass_factory
def auth_rotate(f: Factory):
    """Rotate the framework CA (reference: auth rotate -> RotateCA)."""
    from ..firewall import pki

    pki.rotate_ca(f.config.pki_dir)
    # stale CP/agent leaves are now untrusted; remove so they re-mint
    for leaf in ("cp.crt", "cp.key"):
        (f.config.pki_dir / leaf).unlink(missing_ok=True)
    click.echo("CA rotated; rebuild images (`clawker build`) and restart "
               "the control plane to re-mint service certs")


@auth_group.command("status")
@pass_factory
def auth_status(f: Factory):
    from cryptography import x509

    ca_path = f.config.pki_dir / "ca.crt"
    if not ca_path.exists():
        click.echo("CA: not initialized (minted on first use)")
        return
    cert = x509.load_pem_x509_certificate(ca_path.read_bytes())
    click.echo(f"CA: {cert.subject.rfc4514_string()}")
    click.echo(f"  serial: {cert.serial_number:x}")
    click.echo(f"  not after: {cert.not_valid_after_utc.isoformat()}")


# ------------------------------------------------------------------- version

@click.command("version")
def version_cmd():
    """Show the framework version."""
    from .. import __version__

    click.echo(f"{consts.PRODUCT} {__version__}")


# --------------------------------------------------------------------- alias

@click.group("alias")
def alias_group():
    """User command aliases (expanded before dispatch)."""


def _alias_path(f: Factory | None):
    from ..util import xdg

    return xdg.config_dir() / "aliases.yaml"


def load_aliases(f: Factory | None) -> dict[str, str]:
    p = _alias_path(f)
    if not p.exists():
        return {}
    try:
        raw = yaml.safe_load(p.read_text()) or {}
    except (yaml.YAMLError, OSError):
        return {}
    if not isinstance(raw, dict):
        return {}
    # hand-edited files must never crash command dispatch
    return {str(k): v for k, v in raw.items() if isinstance(v, str)}


@alias_group.command("set")
@click.argument("name")
@click.argument("expansion")
@pass_factory
def alias_set(f: Factory, name, expansion):
    """e.g. `clawker alias set co "container"`."""
    aliases = load_aliases(f)
    aliases[name] = expansion
    _alias_path(f).parent.mkdir(parents=True, exist_ok=True)
    _alias_path(f).write_text(yaml.safe_dump(aliases, sort_keys=True))
    click.echo(f"{name} -> {expansion}")


@alias_group.command("ls")
@pass_factory
def alias_ls(f: Factory):
    for name, exp in sorted(load_aliases(f).items()):
        click.echo(f"{name}\t{exp}")


@alias_group.command("rm")
@click.argument("name")
@pass_factory
def alias_rm(f: Factory, name):
    aliases = load_aliases(f)
    if name not in aliases:
        raise click.ClickException(f"no alias {name!r}")
    del aliases[name]
    _alias_path(f).write_text(yaml.safe_dump(aliases, sort_keys=True))
    click.echo(f"removed alias {name}")


def register(cli: click.Group) -> None:
    cli.add_command(settings_group)
    cli.add_command(auth_group)
    cli.add_command(version_cmd)
    cli.add_command(alias_group)
