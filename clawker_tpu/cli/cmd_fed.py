"""``clawker fed``: the multi-pod federation front tier.

``status`` is the operator's one-glance view of the federation: every
registered pod's liveness, load, breaker posture, lease pool, and
measured control RTT, straight off each pod's loopd status RPC (see
docs/federation.md).  With no ``federation.pods`` configured it shows
the single canonical daemon -- a federation of one.
"""

from __future__ import annotations

import json

import click

from .factory import Factory

pass_factory = click.make_pass_decorator(Factory)


@click.group("fed")
def fed_group():
    """Multi-pod federation: route runs across pods."""


_POD_COLUMNS = ("POD", "ALIVE", "HEALTHY", "WORKERS", "RUNS", "LOAD",
                "BRK-OPEN", "RTT-MS", "LEASES")


@fed_group.command("status")
@click.option("--format", "fmt", type=click.Choice(["table", "json"]),
              default="table")
@pass_factory
def fed_status(f: Factory, fmt):
    """Per-pod federation status over every pod's loopd status RPC.

    Lists each registered pod (the canonical socket plus every
    ``federation.pods`` entry) with liveness, worker count, live run
    load, open breakers, outstanding capacity leases, and the measured
    status round-trip.  Exits non-zero when NO pod answers --
    scriptable as a federation liveness probe.
    """
    from ..federation.registry import PodRegistry
    from ..loopd.client import discover_all

    try:
        project = f.config.project_name()
    except LookupError:
        project = None
    clients = discover_all(f.config, require_project=project)
    if not clients:
        click.echo("fed: no pod's loopd answering (start one with "
                   "`clawker loopd start`; register pods under "
                   "settings federation.pods)", err=True)
        raise SystemExit(1)
    registry = PodRegistry(clients)
    try:
        registry.refresh()
        pods = []
        for p in sorted(registry.pods.values(), key=lambda x: x.index):
            leases = (p.last_status.get("leases") or {})
            pods.append({
                "pod": p.name, "alive": p.alive, "healthy": p.healthy,
                "workers": p.workers, "runs": list(p.runs),
                "load": p.load, "breakers_open": p.breakers_open,
                "rtt_ms": round(p.rtt_s * 1000.0, 2),
                "leases": leases,
            })
    finally:
        registry.close()
    if fmt == "json":
        click.echo(json.dumps({"pods": pods}, indent=2))
        return
    click.echo("\t".join(_POD_COLUMNS))
    for p in pods:
        leases = p["leases"] or {}
        click.echo("\t".join(str(x) for x in (
            p["pod"],
            "yes" if p["alive"] else "NO",
            "yes" if p["healthy"] else "NO",
            p["workers"], len(p["runs"]), p["load"],
            p["breakers_open"], p["rtt_ms"],
            f"{leases.get('active', 0)}"
            f"/{leases.get('outstanding_tokens', 0)}tok")))


def register(cli: click.Group) -> None:
    cli.add_command(fed_group)
