"""CLI layer (reference: internal/clawker + internal/cmd/*).

Entry point: ``python -m clawker_tpu`` or the ``clawker`` console script.
All commands receive a :class:`Factory` through the click context -- tests
inject one wired to a FakeDriver (reference: Tier-2 command tests with a
fake Docker client, TESTING-REFERENCE.md:253-299).
"""

from .root import cli, main

__all__ = ["cli", "main"]
