"""Root command wiring (reference: internal/cmd/root/root.go:29 NewCmdRoot;
builtin Docker-style aliases at aliases.go:132).
"""

from __future__ import annotations

import sys

import click

from .. import __version__, logsetup
from ..errors import ClawkerError, ExitError, FlagError, SilentError
from .factory import Factory

CONTEXT_SETTINGS = {"help_option_names": ["-h", "--help"], "max_content_width": 100}


class _RootGroup(click.Group):
    """Centralized domain-error rendering (reference: internal/clawker/cmd.go
    error presentation): ClawkerErrors become clean one-line CLI errors in
    both standalone and embedded (test) invocation modes.  Unknown names
    fall back to user aliases (reference: root/useraliases.go), resolved
    by walking the expansion words through the command tree."""

    def resolve_command(self, ctx: click.Context, args: list):
        # argv-level alias expansion (docker/gh-style): flags and
        # arguments inside an expansion survive, because parsing restarts
        # on the rewritten argv rather than resolving a command object
        if args and super().get_command(ctx, args[0]) is None:
            from .cmd_settings import load_aliases

            expansion = load_aliases(None).get(args[0], "")
            if expansion:
                args = expansion.split() + list(args[1:])
        return super().resolve_command(ctx, args)

    def invoke(self, ctx: click.Context):
        try:
            return super().invoke(ctx)
        except ExitError as e:
            raise SystemExit(e.code) from e
        except SilentError:
            raise SystemExit(1) from None
        except FlagError as e:
            raise click.UsageError(str(e)) from e
        except ClawkerError as e:
            raise click.ClickException(str(e)) from e


@click.group(cls=_RootGroup, context_settings=CONTEXT_SETTINGS)
@click.option("--verbose", "-v", is_flag=True, help="Debug logging to stderr.")
@click.version_option(__version__, prog_name="clawker")
@click.pass_context
def cli(ctx: click.Context, verbose: bool) -> None:
    """clawker -- run AI coding agents in locked-down containers on your
    laptop's Docker daemon or across the worker VMs of a Cloud TPU pod."""
    logsetup.setup("debug" if verbose else "warning")
    if ctx.obj is None:
        ctx.obj = Factory()


def _start_notices() -> "object | None":
    """Kick off the update check + changelog teaser CONCURRENTLY with the
    command (reference: internal/clawker cmd.go:79-120 background
    notification goroutines).  Returns the thread, or None when notices
    are disabled.  The probe must never delay the user: the collector at
    command end waits at most a beat, and a missed fetch just retries on
    a later run (the TTL cache absorbs the cost)."""
    import os
    import sys
    import threading

    if not sys.stderr.isatty() or os.environ.get("CLAWKER_TPU_NO_NOTICES"):
        return None
    lines: list[str] = []

    def probe() -> None:
        try:
            from ..changelog import teaser
            from ..state import check_for_update

            lines.extend(l for l in (check_for_update(), teaser()) if l)
        except Exception:  # noqa: BLE001 - notices never break a command
            pass

    t = threading.Thread(target=probe, name="notices", daemon=True)
    t.lines = lines  # type: ignore[attr-defined]
    t.start()
    return t


def _finish_notices(t) -> None:
    if t is None:
        return
    t.join(0.3)
    if not t.is_alive():
        for line in t.lines:
            click.echo(line, err=True)


def main(argv: list[str] | None = None) -> int:
    notices = _start_notices()
    try:
        cli.main(args=argv, standalone_mode=False)
        _finish_notices(notices)
        return 0
    except click.exceptions.Exit as e:
        return e.exit_code
    except SystemExit as e:
        return int(e.code or 0)
    except click.ClickException as e:
        e.show()
        return e.exit_code
    except click.Abort:
        click.echo("aborted", err=True)
        return 130
    except ExitError as e:
        return e.code
    except SilentError:
        return 1
    except FlagError as e:
        click.echo(f"error: {e}", err=True)
        return 2
    except ClawkerError as e:
        click.echo(f"error: {e}", err=True)
        return 1


def register_commands() -> None:
    """Attach all command groups (import-cycle-free late binding)."""
    from . import (
        cmd_analyze,
        cmd_build,
        cmd_bundle,
        cmd_chaos,
        cmd_container,
        cmd_controlplane,
        cmd_fed,
        cmd_firewall,
        cmd_fleet,
        cmd_harness,
        cmd_image,
        cmd_init,
        cmd_journal,
        cmd_loop,
        cmd_loopd,
        cmd_monitor,
        cmd_network,
        cmd_plugin,
        cmd_project,
        cmd_settings,
        cmd_trace,
        cmd_volume,
        cmd_workerd,
    )

    cmd_analyze.register(cli)
    cmd_build.register(cli)
    cmd_bundle.register(cli)
    cmd_chaos.register(cli)
    cmd_container.register(cli)
    cmd_controlplane.register(cli)
    cmd_fed.register(cli)
    cmd_firewall.register(cli)
    cmd_fleet.register(cli)
    cmd_harness.register(cli)
    cmd_image.register(cli)
    cmd_init.register(cli)
    cmd_journal.register(cli)
    cmd_loop.register(cli)
    cmd_loopd.register(cli)
    cmd_monitor.register(cli)
    cmd_network.register(cli)
    cmd_project.register(cli)
    cmd_plugin.register(cli)
    cmd_settings.register(cli)
    cmd_trace.register(cli)
    cmd_volume.register(cli)
    cmd_workerd.register(cli)


register_commands()
