"""``clawker loopd``: the host-resident loop-supervisor daemon.

``start`` brings one daemon up per host (detached, project-scoped);
``status`` renders its hosted runs + pod-scale admission/health state
over the status RPC; ``stop`` drains every hosted run (durable
``shutdown`` journal records -- resumable) and exits it.  See
docs/loopd.md for the lifecycle, wire protocol, and degrade matrix.
"""

from __future__ import annotations

import json
import os
import signal
import time

import click

from ..loopd import (
    LoopdError,
    logfile_path,
    pidfile_path,
    socket_path,
    spawn_daemon,
)
from ..loopd.client import LoopdClient, discover
from .factory import Factory

pass_factory = click.make_pass_decorator(Factory)


@click.group("loopd")
def loopd_group():
    """Host-resident loop supervisor: runs outlive the CLI."""


@loopd_group.command("start")
@click.option("--foreground", is_flag=True,
              help="Run the daemon in THIS process (debugging/ops; "
                   "Ctrl-C drains and exits).")
@pass_factory
def loopd_start(f: Factory, foreground):
    """Start the daemon (no-op when one already answers).

    The daemon is project-scoped: start it from the project it will
    serve.  Once up, every ``clawker loop`` in this project submits its
    runs to the daemon instead of scheduling in-process -- admission
    caps and tenant fairness then hold across CLI processes, and runs
    keep executing after the submitting terminal closes.
    """
    client = discover(f.config)
    if client is not None:
        pong = client.ping()
        client.close()
        click.echo(f"loopd already running (pid {pong.get('pid')}, "
                   f"{pong.get('runs', 0)} live run(s)) on "
                   f"{socket_path(f.config)}")
        return
    if foreground:
        from ..loopd.server import LoopdServer

        server = LoopdServer(f.config, f.driver)
        signal.signal(signal.SIGINT, lambda *_: server.stop())
        signal.signal(signal.SIGTERM, lambda *_: server.stop())
        server.start()
        click.echo(f"loopd listening on {server.sock_path} "
                   f"(pid {os.getpid()}; Ctrl-C drains)", err=True)
        server.serve_forever()
        return
    pid = spawn_daemon(f.config, cwd=f.cwd)
    click.echo(f"loopd started (pid {pid}) on {socket_path(f.config)}; "
               f"log: {logfile_path(f.config)}")


@loopd_group.command("stop")
@click.option("--force", is_flag=True,
              help="SIGTERM the pidfile's process when the socket does "
                   "not answer (wedged daemon).")
@pass_factory
def loopd_stop(f: Factory, force):
    """Drain every hosted run and stop the daemon.

    Drained runs journal a durable ``shutdown`` record first: resume
    any of them later with ``clawker loop --resume <run>``.
    """
    client = discover(f.config)
    if client is not None:
        client.shutdown()
        client.close()
        # the drain is asynchronous; wait for the socket to go away so
        # `loopd stop && loopd start` cannot race the old daemon
        sock = socket_path(f.config)
        deadline = time.monotonic() + f.config.settings.loopd.drain_grace_s + 5
        while time.monotonic() < deadline and sock.exists():
            time.sleep(0.1)
        if sock.exists():
            # a wedged drain must not report success: the very next
            # `loopd start` would hit "already running"
            raise click.ClickException(
                "loopd did not drain within the grace period (socket "
                f"still present at {sock}); retry with --force to "
                "SIGTERM it")
        click.echo("loopd stopped")
        return
    pidfile = pidfile_path(f.config)
    if force and pidfile.exists():
        try:
            pid = int(pidfile.read_text().strip())
            os.kill(pid, signal.SIGTERM)
            click.echo(f"loopd: SIGTERM sent to pid {pid}")
            return
        except (OSError, ValueError) as e:
            raise click.ClickException(f"loopd: force-stop failed: {e}")
    click.echo("loopd: not running", err=True)


_RUN_COLUMNS = ("RUN", "STATE", "TENANT", "CLIENT", "LOOPS", "PLACEMENT",
                "SUBS", "DROPS")


@loopd_group.command("status")
@click.option("--format", "fmt", type=click.Choice(["table", "json"]),
              default="table")
@pass_factory
def loopd_status(f: Factory, fmt):
    """Daemon status: hosted runs, admission tokens, worker breakers.

    Exits non-zero when no daemon answers -- scriptable as a liveness
    probe.
    """
    client = discover(f.config)
    if client is None:
        click.echo("loopd: not running (start one with `clawker loopd "
                   "start`)", err=True)
        raise SystemExit(1)
    try:
        doc = client.status()
    finally:
        client.close()
    doc.pop("type", None)
    if fmt == "json":
        from ..loopd.feed import console_feed

        # `console` is THE script-facing schema -- the exact document
        # `clawker fleet console --format json` emits, so the TUI and
        # scripts can never drift (docs/fleet-console.md#feed)
        doc["console"] = console_feed(doc)
        click.echo(json.dumps(doc, indent=2))
        return
    click.echo(f"loopd pid {doc['pid']} project={doc.get('project') or '-'} "
               f"uptime={doc.get('uptime_s', 0)}s "
               f"socket={doc.get('socket')}")
    runs = doc.get("runs", [])
    if runs:
        click.echo("\t".join(_RUN_COLUMNS))
        for r in runs:
            click.echo("\t".join(str(x) for x in (
                r["run"], r["state"], r["tenant"], r["client"],
                r["parallel"], r["placement"], r["subscribers"],
                r.get("events_dropped", 0))))
    else:
        click.echo("no hosted runs")
    ship = doc.get("shipper") or {}
    if ship.get("enabled"):
        click.echo(f"shipper: {ship.get('ingested_docs', 0)} doc(s) in, "
                   f"{ship.get('flushed_batches', 0)} batch(es) shipped, "
                   f"{ship.get('pending_batches', 0)} pending, "
                   f"{ship.get('dropped_docs', 0)} dropped")
    adm = doc.get("admission", {})
    for wid, w in sorted(adm.get("workers", {}).items()):
        click.echo(f"worker {wid}: tokens {w['inflight']}/{w['capacity']} "
                   f"hwm={w['inflight_hwm']} pending={w['pending']} "
                   f"dispatched={w['dispatched']} rejected={w['rejected']}")
    for tenant, t in sorted(adm.get("tenants", {}).items()):
        click.echo(f"tenant {tenant}: weight={t['weight']} "
                   f"inflight={t['inflight']} queued={t['queued']} "
                   f"dispatched={t['dispatched']}")


def ensure_daemon(f: Factory) -> "LoopdClient | None":
    """Autostart path for ``clawker loop``: a connected client when a
    daemon answers (spawning one first if settings ``loopd.autostart``
    asks for it), else None -- the caller degrades in-process."""
    project = None
    try:
        project = f.config.project_name()
    except LookupError:
        pass
    client = discover(f.config, require_project=project)
    if client is not None:
        return client
    if not f.config.settings.loopd.autostart:
        return None
    try:
        spawn_daemon(f.config, cwd=f.cwd)
    except LoopdError as e:
        click.echo(f"loopd autostart failed ({e}); running in-process",
                   err=True)
        return None
    return discover(f.config, require_project=project)


def register(cli: click.Group) -> None:
    cli.add_command(loopd_group)
