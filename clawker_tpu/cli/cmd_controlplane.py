"""controlplane verbs: up/down/status/agents.

Parity reference: internal/cmd/controlplane (up/down/status/agents,
SURVEY.md 2.4) -- status and agents go through the AdminService with the
mTLS + bearer contract, exactly like the reference's adminclient Dial.
"""

from __future__ import annotations

import json

import click

from ..controlplane import manager
from ..errors import ClawkerError
from .factory import Factory

pass_factory = click.make_pass_decorator(Factory)


def _admin_client(f: Factory):
    try:
        return manager.admin_client(f.config)
    except manager.ControlPlaneError as e:
        raise click.ClickException(str(e)) from None


@click.group("controlplane")
def cp_group():
    """Manage the control-plane daemon."""


@cp_group.command("up")
@pass_factory
def cp_up(f: Factory):
    manager.ensure_running(f.config)
    click.echo("control plane running")


@cp_group.command("down")
@pass_factory
def cp_down(f: Factory):
    if manager.stop(f.config):
        click.echo("control plane stopped")
    else:
        click.echo("control plane not running")


@cp_group.command("status")
@click.option("--format", "fmt", type=click.Choice(["table", "json"]), default="table")
@pass_factory
def cp_status(f: Factory, fmt):
    h = manager.health(f.config)
    if h is None:
        if fmt == "json":
            click.echo(json.dumps({"running": False}))
        else:
            click.echo("control plane: not running")
        raise SystemExit(1)
    if fmt == "json":
        click.echo(json.dumps({"running": True, **h}, indent=2))
        return
    click.echo("control plane: running")
    for k in ("admin", "agent_service", "feeder", "watcher"):
        click.echo(f"  {k:14} {'ok' if h.get(k) else 'DOWN'}")
    if h.get("unavailable"):
        click.echo(f"  unavailable    {', '.join(h['unavailable'])}")
    click.echo(f"  uptime         {h.get('uptime_s', 0):.0f}s")


@cp_group.command("agents")
@click.option("--project", default="", help="Filter by project.")
@click.option("--format", "fmt", type=click.Choice(["table", "json"]), default="table")
@pass_factory
def cp_agents(f: Factory, project, fmt):
    try:
        reply = _admin_client(f).call("ListAgents", {"project": project})
    except ClawkerError as e:
        raise click.ClickException(str(e)) from e
    agents = reply.get("agents", [])
    if fmt == "json":
        click.echo(json.dumps(agents, indent=2))
        return
    if not agents:
        click.echo("no agents")
        return
    click.echo(f"{'AGENT':32} {'STATE':12} {'INIT':5} {'REG':5} CONTAINER")
    for a in agents:
        click.echo(
            f"{a['full_name']:32} {a['state']:12} "
            f"{'yes' if a['initialized'] else 'no':5} "
            f"{'yes' if a['registered'] else 'no':5} {a['container_id'][:12]}"
        )


def register(root: click.Group) -> None:
    root.add_command(cp_group)
