"""Volume verbs (reference: internal/cmd/volume; the network group
lives in cmd_network.py)."""

from __future__ import annotations

import json

import click

from .. import consts
from .factory import Factory

pass_factory = click.make_pass_decorator(Factory)


@click.group("volume")
def volume_group():
    """Manage agent volumes."""


@volume_group.command("ls")
@click.option("--format", "fmt", type=click.Choice(["table", "json"]), default="table")
@pass_factory
def volume_ls(f: Factory, fmt):
    vols = f.engine().list_volumes()
    if fmt == "json":
        click.echo(json.dumps(vols, indent=2))
        return
    for v in vols:
        labels = v.get("Labels") or {}
        click.echo(
            f"{v['Name']}\t{labels.get(consts.LABEL_PROJECT, '')}"
            f"\t{labels.get(consts.LABEL_VOLUME_PURPOSE, '')}"
        )


@volume_group.command("rm")
@click.argument("names", nargs=-1, required=True)
@click.option("--force", "-f", is_flag=True)
@pass_factory
def volume_rm(f: Factory, names, force):
    if not f.confirm_destructive(
            f"Remove volume(s) {', '.join(names)}? Data is not recoverable.",
            skip=force):
        raise SystemExit(1)
    for n in names:
        f.engine().remove_volume(n, force=force)
        click.echo(n)


def register(root: click.Group) -> None:
    root.add_command(volume_group)
