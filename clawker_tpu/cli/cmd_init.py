"""`clawker init` -- scaffold project config (reference: internal/cmd/init)."""

from __future__ import annotations

from pathlib import Path

import click

from .. import consts
from .factory import Factory

pass_factory = click.make_pass_decorator(Factory)

TEMPLATE = """\
# clawker project configuration
project: {name}

build:
  stack: {stack}          # language stack bundle: python | go | node | ...
  harness: {harness}         # agent harness bundle

workspace:
  mode: {mode}              # bind (live) | snapshot (ephemeral copy)

security:
  egress: []              # extra allowed domains, e.g.
  #  - dst: pypi.org
  #    proto: https
"""


def _slug(raw: str) -> str:
    import re

    return re.sub(r"[^a-z0-9_-]+", "-", raw.lower()).strip("-_") or "project"


def _wizard(f: Factory, name: str, stack: str) -> tuple[str, str, str, str]:
    """Interactive init wizard (reference: internal/tui wizard used by
    init, SURVEY.md 2.4): name, stack (from the resolved bundle
    inventory), harness, workspace mode -- flags pre-answer.  Only
    called on promptable streams (init_cmd gates)."""
    harness = "claude"
    from ..bundle.resolver import Resolver

    p = f.prompter
    pname = _slug(p.string("Project name", default=_slug(name or f.cwd.name)))
    stacks = sorted(s.name for s in Resolver(f.config).list("stack"))
    if stack not in stacks:
        # honor an explicit --stack even without a bundle for it (loose/
        # installed tiers may provide it later) instead of silently
        # defaulting to the alphabetically-first bundle
        stacks = [stack] + stacks
    idx = p.select("Language stack", stacks, default=stacks.index(stack))
    stack = stacks[idx]
    harnesses = sorted(h.name for h in Resolver(f.config).list("harness")) \
        or [harness]
    hidx = p.select("Agent harness", harnesses,
                    default=harnesses.index("claude")
                    if "claude" in harnesses else 0)
    harness = harnesses[hidx]
    midx = p.select("Workspace mode",
                    ["bind (live project tree)",
                     "snapshot (ephemeral copy per agent)"], default=0)
    mode = "bind" if midx == 0 else "snapshot"
    return pname, stack, harness, mode


@click.command("init")
@click.option("--name", default="", help="Project name (default: directory name).")
@click.option("--stack", default="python", show_default=True)
@click.option("--yes", "-y", is_flag=True,
              help="Skip the wizard; take flags/defaults as-is.")
@click.option("--force", is_flag=True, help="Overwrite existing config.")
@pass_factory
def init_cmd(f: Factory, name, stack, yes, force):
    """Initialize a clawker project in the current directory.

    Interactive terminals get a short wizard (name, stack, harness,
    workspace mode); --yes or a non-TTY run takes the flags/defaults."""
    target = f.cwd / consts.PROJECT_FLAT_FORM
    if target.exists() and not force:
        raise click.ClickException(f"{target} already exists (use --force)")
    if yes or not f.streams.can_prompt():
        pname, harness, mode = _slug(name or f.cwd.name), "claude", "bind"
    else:
        pname, stack, harness, mode = _wizard(f, name, stack)
    target.write_text(TEMPLATE.format(name=pname, stack=stack,
                                      harness=harness, mode=mode))
    click.echo(f"initialized project {pname!r} ({target})")


def register(root: click.Group) -> None:
    root.add_command(init_cmd)
