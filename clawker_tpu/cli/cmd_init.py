"""`clawker init` -- scaffold project config (reference: internal/cmd/init)."""

from __future__ import annotations

from pathlib import Path

import click

from .. import consts
from .factory import Factory

pass_factory = click.make_pass_decorator(Factory)

TEMPLATE = """\
# clawker project configuration
project: {name}

build:
  stack: {stack}          # language stack bundle: python | go | node | ...
  harness: claude         # agent harness bundle

workspace:
  mode: bind              # bind (live) | snapshot (ephemeral copy)

security:
  egress: []              # extra allowed domains, e.g.
  #  - dst: pypi.org
  #    proto: https
"""


@click.command("init")
@click.option("--name", default="", help="Project name (default: directory name).")
@click.option("--stack", default="python", show_default=True)
@click.option("--force", is_flag=True, help="Overwrite existing config.")
@pass_factory
def init_cmd(f: Factory, name, stack, force):
    """Initialize a clawker project in the current directory."""
    target = f.cwd / consts.PROJECT_FLAT_FORM
    if target.exists() and not force:
        raise click.ClickException(f"{target} already exists (use --force)")
    import re

    raw = (name or f.cwd.name).lower()
    pname = re.sub(r"[^a-z0-9_-]+", "-", raw).strip("-_") or "project"
    target.write_text(TEMPLATE.format(name=pname, stack=stack))
    click.echo(f"initialized project {pname!r} ({target})")


def register(root: click.Group) -> None:
    root.add_command(init_cmd)
