"""``clawker journal``: run-journal integrity tooling.

Net-new verb (docs/durability.md#verify).  Every journal record
carries a CRC32 trailer (monitor/ledger.py); ``journal verify`` scans a
run's WAL and reports the verdict per record class -- verified,
legacy (pre-checksum), corrupt (bit-flip or mid-file damage), torn
tail (crash mid-append; expected, tolerated).  Exit code is the
contract: 0 clean, 2 corruption -- CI and the chaos invariants gate on
it.  ``--repair`` quarantines the damaged lines to a ``.quarantine``
sidecar and atomically rewrites the journal with the intact records,
so a bit-flipped journal becomes resumable again without silently
discarding the evidence of what was lost.
"""

from __future__ import annotations

import json
import os
from pathlib import Path

import click

from .factory import Factory

pass_factory = click.make_pass_decorator(Factory)


@click.group("journal")
def journal_group() -> None:
    """Inspect and repair run journals (docs/durability.md)."""


def _quarantine_and_rewrite(path: Path) -> dict:
    """Move every damaged line to ``<path>.quarantine`` (appended, with
    a line-number prefix) and atomically rewrite the journal with the
    intact lines verbatim -- kept records are NOT re-encoded, so a
    repair never invents bytes the writer didn't fsync."""
    from ..monitor.ledger import classify_line

    kept: list[str] = []
    bad: list[tuple[int, str]] = []
    with path.open("r", encoding="utf-8", errors="replace") as fh:
        lines = fh.read().splitlines()
    for i, line in enumerate(lines, start=1):
        status, _ = classify_line(line)
        if status == "blank":
            continue
        if status in ("ok", "legacy"):
            kept.append(line)
        else:
            bad.append((i, line))
    if bad:
        sidecar = path.with_name(path.name + ".quarantine")
        with sidecar.open("a", encoding="utf-8") as fh:
            for i, line in bad:
                fh.write(f"{i}:{line}\n")
    tmp = path.with_name(path.name + ".repair")
    with tmp.open("w", encoding="utf-8") as fh:
        fh.write("".join(l + "\n" for l in kept))
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    return {"kept": len(kept), "quarantined": len(bad)}


@journal_group.command("verify")
@click.argument("run")
@click.option("--repair", is_flag=True,
              help="Quarantine damaged lines to a .quarantine sidecar "
                   "and atomically rewrite the journal with the intact "
                   "records.")
@click.option("--json", "as_json", is_flag=True,
              help="Integrity report as JSON.")
@pass_factory
def journal_verify(f: Factory, run: str, repair: bool, as_json: bool):
    """Checksum-scan RUN's journal (a run id, unambiguous prefix, or a
    journal file path).

    Exit 0 when every record verifies (legacy pre-checksum records and
    a single torn final record are tolerated), exit 2 on corruption.
    With ``--repair`` the damaged lines move to a sidecar and the exit
    reflects the REWRITTEN journal.
    """
    from ..monitor.ledger import verify_jsonl
    from .cmd_loop import _resolve_journal

    path = _resolve_journal(f, run)
    report = verify_jsonl(path)
    repaired = None
    if repair and not report.ok:
        repaired = _quarantine_and_rewrite(path)
        report = verify_jsonl(path)
    if as_json:
        doc = report.to_doc()
        if repaired is not None:
            doc["repaired"] = repaired
        click.echo(json.dumps(doc, indent=2))
    else:
        click.echo(f"{path.name}: {report.total} record(s) -- "
                   f"{report.verified} verified, {report.legacy} legacy, "
                   f"{report.corrupt} corrupt"
                   + (", torn tail" if report.torn_tail else ""))
        if repaired is not None:
            click.echo(f"repaired: kept {repaired['kept']}, quarantined "
                       f"{repaired['quarantined']} -> "
                       f"{path.name}.quarantine")
        if not report.ok:
            click.echo(f"first corrupt record at line "
                       f"{report.first_corrupt_line} -- resume folds only "
                       "the prefix above it (docs/durability.md#verify)",
                       err=True)
    if not report.ok:
        raise SystemExit(2)


def register(cli: click.Group) -> None:
    cli.add_command(journal_group)
