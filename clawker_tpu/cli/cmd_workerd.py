"""``clawker workerd``: manage the worker-resident launch daemon.

Run ON a worker host (docs/workerd.md): ``start`` forks the daemon
detached, serving the host's local engine socket; ``status`` probes the
control socket; ``stop`` asks a running daemon to shut down.  The
scheduler (or loopd) on the client host discovers the socket --
tunneled over the existing SSH mux for ``tpu_vm`` -- and moves the
launch data plane onto it, so engine mutations stop paying a
host<->worker WAN round trip each.
"""

from __future__ import annotations

import json as _json
import os
import signal
import time

import click

from .factory import Factory

pass_factory = click.make_pass_decorator(Factory)


@click.group("workerd")
def workerd_group() -> None:
    """Worker-resident launch daemon (docs/workerd.md)."""


@workerd_group.command("start")
@click.option("--driver", "driver_override", default="",
              help="Runtime driver the daemon serves (default: settings "
                   "runtime.driver; pass `local` on a provisioned worker "
                   "whose settings still name tpu_vm).")
@pass_factory
def workerd_start(f: Factory, driver_override) -> None:
    """Start workerd detached on THIS host.

    The daemon binds a 0600 unix socket in a 0700 runtime dir under the
    state dir and executes launch intents against this host's engine;
    it outlives this CLI.  Idempotent: a daemon already answering is
    left alone.
    """
    from ..workerd import WorkerdError, socket_path, spawn_daemon
    from ..workerd.executor import ping_socket

    sock = socket_path(f.config)
    if ping_socket(sock):
        click.echo(f"workerd already running on {sock}")
        return
    try:
        pid = spawn_daemon(f.config, cwd=f.cwd,
                           driver_override=driver_override)
    except WorkerdError as e:
        raise click.ClickException(str(e))
    click.echo(f"workerd started (pid {pid}) on {sock}")


@workerd_group.command("status")
@click.option("--json", "as_json", is_flag=True, help="Status as JSON.")
@pass_factory
def workerd_status(f: Factory, as_json) -> None:
    """Probe the local workerd (exit 1 when nothing answers).

    Also renders per-worker liveness for the active runtime driver:
    ``live`` (socket answers), ``degraded`` (socket exists, daemon
    dead -- that worker's data plane silently fell back to the WAN
    path), ``absent`` (never provisioned).
    """
    from ..agentd import protocol
    from ..workerd import liveness, socket_path

    sock = socket_path(f.config)
    doc = None
    try:
        import socket as _s

        with _s.socket(_s.AF_UNIX, _s.SOCK_STREAM) as s:
            s.settimeout(2.0)
            s.connect(str(sock))
            protocol.write_msg(s, {"type": "status"})
            doc = protocol.read_msg(s)
    except OSError:
        doc = None
    fleet = liveness(f.config, f.driver)
    if as_json:
        click.echo(_json.dumps({"local": doc, "workers": fleet}, indent=2))
    else:
        if doc is not None:
            click.echo(f"workerd pid {doc.get('pid')} on {sock}: "
                       f"{doc.get('intents', 0)} intent(s), "
                       f"{doc.get('events', 0)} event(s) in "
                       f"{doc.get('batches', 0)} batch(es), "
                       f"uptime {doc.get('uptime_s', 0)}s")
        else:
            click.echo(f"no workerd answering on {sock}", err=True)
        for wid in sorted(fleet):
            click.echo(f"{wid}\t{fleet[wid]}")
    if doc is None:
        raise SystemExit(1)


@workerd_group.command("stop")
@pass_factory
def workerd_stop(f: Factory) -> None:
    """Stop a running workerd (graceful; in-flight intents finish on
    the local lane, clients degrade to the direct path)."""
    import socket as _s

    from ..agentd import protocol
    from ..workerd import pidfile_path, socket_path

    sock = socket_path(f.config)
    try:
        with _s.socket(_s.AF_UNIX, _s.SOCK_STREAM) as s:
            s.settimeout(2.0)
            s.connect(str(sock))
            protocol.write_msg(s, {"type": "shutdown"})
            protocol.read_msg(s)
    except OSError:
        # nothing answering: sweep a stale pidfile/socket best-effort
        pid_path = pidfile_path(f.config)
        try:
            pid = int(pid_path.read_text().strip())
            os.kill(pid, signal.SIGTERM)
        except (OSError, ValueError):
            raise click.ClickException(
                f"no workerd answering on {sock} (and no live pidfile)")
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and sock.exists():
        time.sleep(0.1)
    click.echo("workerd stopped" if not sock.exists()
               else "workerd stop requested (socket still present)")


def register(cli: click.Group) -> None:
    cli.add_command(workerd_group)
