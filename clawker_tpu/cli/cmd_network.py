"""network verbs: Docker-parity surface over MANAGED networks only.

Parity reference: internal/cmd/network (SURVEY.md 2.4); the label jail
means these verbs can only see/touch clawker-created networks.
"""

from __future__ import annotations

import json

import click

from .. import consts
from .factory import Factory

pass_factory = click.make_pass_decorator(Factory)


@click.group("network")
def net_group():
    """Manage clawker networks (label-jailed)."""


@net_group.command("ls")
@click.option("--format", "fmt", type=click.Choice(["table", "json"]), default="table")
@pass_factory
def net_ls(f: Factory, fmt):
    nets = f.engine().api.network_list(
        filters={"label": [f"{consts.LABEL_MANAGED}={consts.MANAGED_VALUE}"]})
    if fmt == "json":
        click.echo(json.dumps(nets, indent=2))
        return
    for n in nets:
        subnet = ""
        cfgs = (n.get("IPAM") or {}).get("Config") or []
        if cfgs:
            subnet = cfgs[0].get("Subnet", "")
        click.echo(f"{n.get('Name')}\t{n.get('Driver','bridge')}\t{subnet}")


@net_group.command("ensure")
@click.argument("name", default=consts.NETWORK_NAME)
@click.option("--subnet", default="", help="CIDR for the new network.")
@pass_factory
def net_ensure(f: Factory, name, subnet):
    """Idempotently create a managed bridge network."""
    n = f.engine().ensure_network(name, subnet=subnet)
    click.echo(f"{n['Name']} ready")


@net_group.command("inspect")
@click.argument("name")
@pass_factory
def net_inspect(f: Factory, name):
    click.echo(json.dumps(f.engine().api.network_inspect(name), indent=2))


@net_group.command("rm")
@click.argument("name")
@pass_factory
def net_rm(f: Factory, name):
    f.engine().remove_network(name)
    click.echo(f"removed network {name}")


def register(cli: click.Group) -> None:
    cli.add_command(net_group)
