"""Container verbs: run / create / start / attach / stop / kill / rm / ps /
logs / inspect, as a ``container`` group plus Docker-style top-level aliases
(reference: internal/cmd/container 20 verbs; builtin aliases aliases.go:132).
"""

from __future__ import annotations

import io
import json
import re
import sys

import click

from .. import consts
from ..runtime.names import container_name
from ..runtime.orchestrate import CreateOptions
from .factory import Factory

pass_factory = click.make_pass_decorator(Factory)

_HEX_ID = re.compile(r"^[0-9a-f]{12,64}$")


def _resolve_ref(f: Factory, name_or_agent: str) -> str:
    """Accept a bare agent name (scoped to the current project) or a full
    container name/id (reference: cmdutil name resolution)."""
    if "." in name_or_agent or _HEX_ID.match(name_or_agent):
        return name_or_agent
    return container_name(f.config.project_name(), name_or_agent)


# ------------------------------------------------------------------- run


@click.command("run", context_settings={
    # docker semantics: everything after the first CMD token belongs to
    # the container command ("run ... sh -c 'exit 7'"), never to clawker
    "ignore_unknown_options": True,
    "allow_interspersed_args": False,
})
@click.option("--agent", "-a", default=None, help="Agent name (default: project config).")
@click.option("--image", default="@", show_default=True, help="Image ('@' = project image).")
@click.option("--env", "-e", multiple=True, help="KEY=VALUE (repeatable).")
@click.option("--env-file", "env_files", multiple=True,
              type=click.Path(exists=True),
              help="Read KEY=VALUE pairs from a dotenv file (repeatable; "
                   "--env wins on conflicts).")
@click.option("--workspace", type=click.Choice(["bind", "snapshot"]), default=None)
@click.option("--replace", is_flag=True, help="Replace an existing agent container.")
@click.option("--detach", "-d", is_flag=True, help="Start without attaching.")
@click.option("--no-tty", is_flag=True, help="Disable TTY allocation.")
@click.option("--worktree", default="", help="Run in the named git worktree.")
@click.option("--workdir", default="",
              help="Override the container working directory.")
@click.argument("cmd", nargs=-1, type=click.UNPROCESSED)
@pass_factory
def run_cmd(f: Factory, agent, image, env, env_files, workspace, replace,
            detach, no_tty, worktree, workdir, cmd):
    """Create an agent container and attach to it (create+start+attach)."""
    cfg = f.config
    # TTL-gated bundle refresh before resolving images/harnesses
    # (reference cmdutil.RunBundleAutoUpdate, run.go:166); soft-fails
    try:
        from ..bundle.manager import BundleManager

        for ref in BundleManager(cfg).auto_update_check():
            click.echo(f"bundle updated: {ref}", err=True)
    except Exception:  # noqa: BLE001 - never block a run on bundle refresh
        pass
    agent = agent or (cfg.project.agent.default if cfg.project else "dev")
    envd = _assemble_env(env, env_files)
    opts = CreateOptions(
        agent=agent,
        image=image,
        cmd=list(cmd),
        env=envd,
        tty=not no_tty,
        workspace_mode=workspace or "",
        replace=replace,
        workdir=workdir,
    )
    if worktree:
        from ..project.manager import ProjectManager

        pm = ProjectManager(cfg)
        wt = pm.get_worktree(cfg.project_name(), worktree)
        opts.workspace_root = wt.path
        opts.worktree_git_dir = wt.main_git_dir
        opts.workspace_mode = "bind"
    rt = f.runtime()
    cid = rt.create(opts)
    name = container_name(cfg.project_name(), agent)
    if detach:
        rt.start(cid)
        click.echo(name)
        return
    code = rt.attach_and_run(cid, tty=not no_tty)
    if code != 0:
        raise SystemExit(code)


# ------------------------------------------------------------------ group


@click.group("container")
def container_group():
    """Manage agent containers."""


def _assemble_env(env: tuple, env_files: tuple) -> dict[str, str]:
    """dotenv files first (in order), explicit --env pairs win."""
    from ..util.dotenv import parse_file

    out: dict[str, str] = {}
    for path in env_files:
        out.update(parse_file(path))
    out.update(dict(e.split("=", 1) if "=" in e else (e, "") for e in env))
    return out


@container_group.command("create", context_settings={
    "ignore_unknown_options": True,
    "allow_interspersed_args": False,
})
@click.option("--agent", "-a", default=None)
@click.option("--image", default="@")
@click.option("--env", "-e", multiple=True)
@click.option("--env-file", "env_files", multiple=True,
              type=click.Path(exists=True))
@click.option("--replace", is_flag=True)
@click.option("--workspace", type=click.Choice(["bind", "snapshot"]),
              default=None)
@click.option("--workdir", default="",
              help="Override the container working directory.")
@click.argument("cmd", nargs=-1, type=click.UNPROCESSED)
@pass_factory
def create_cmd(f: Factory, agent, image, env, env_files, replace, workspace,
               workdir, cmd):
    """Create an agent container without starting it."""
    cfg = f.config
    agent = agent or (cfg.project.agent.default if cfg.project else "dev")
    envd = _assemble_env(env, env_files)
    f.runtime().create(
        CreateOptions(agent=agent, image=image, cmd=list(cmd), env=envd,
                      replace=replace, workspace_mode=workspace or "",
                      workdir=workdir)
    )
    click.echo(container_name(cfg.project_name(), agent))


@container_group.command("ls")
@click.option("--all/--running", "-A", "all_", default=True, help="Include stopped (default) or only running.")
@click.option("--project", "-p", default=None, help="Filter by project.")
@click.option("--format", "fmt", type=click.Choice(["table", "json"]), default="table")
@pass_factory
def ls_cmd(f: Factory, all_, project, fmt):
    """List agent containers (all projects by default)."""
    rows = []
    for w in f.driver.workers():
        for c in f.runtime(w.require_engine()).list_agents(all=all_, project=project):
            labels = c.get("Labels", {})
            rows.append(
                {
                    "name": c["Names"][0].lstrip("/"),
                    "project": labels.get(consts.LABEL_PROJECT, ""),
                    "agent": labels.get(consts.LABEL_AGENT, ""),
                    "state": c.get("State", ""),
                    "image": c.get("Image", ""),
                    "worker": w.id,
                }
            )
    if fmt == "json":
        click.echo(json.dumps(rows, indent=2))
        return
    if not rows:
        click.echo("no agent containers")
        return
    widths = {k: max(len(k), *(len(r[k]) for r in rows)) for k in rows[0]}
    click.echo("  ".join(k.upper().ljust(widths[k]) for k in rows[0]))
    for r in rows:
        click.echo("  ".join(str(r[k]).ljust(widths[k]) for k in r))


@container_group.command("start")
@click.argument("name")
@pass_factory
def start_cmd(f: Factory, name):
    """Start a stopped agent container."""
    f.runtime().start(_resolve_ref(f, name))
    click.echo(name)


@container_group.command("attach")
@click.argument("name")
@click.option("--no-tty", is_flag=True)
@pass_factory
def attach_cmd(f: Factory, name, no_tty):
    """Attach to a running agent container."""
    ref = _resolve_ref(f, name)
    engine = f.engine()
    info = engine.inspect_container(ref)
    if not info["State"]["Running"]:
        raise click.ClickException(f"{name} is not running (use `clawker start`)")
    stream = engine.attach_container(ref, tty=not no_tty)
    from ..runtime import attach as attach_mod

    attach_mod.wire_resize(engine, ref)
    attach_mod.pump_streams(stream, sys.stdin.buffer, sys.stdout.buffer)
    code = engine.wait_container(ref)
    if code != 0:
        raise SystemExit(code)


@container_group.command("stop")
@click.argument("names", nargs=-1, required=True)
@click.option("--time", "-t", default=10, show_default=True)
@pass_factory
def stop_cmd(f: Factory, names, time):
    """Stop running agent containers."""
    for n in names:
        f.engine().stop_container(_resolve_ref(f, n), timeout=time)
        click.echo(n)


@container_group.command("kill")
@click.argument("names", nargs=-1, required=True)
@click.option("--signal", "-s", default="KILL", show_default=True)
@pass_factory
def kill_cmd(f: Factory, names, signal):
    """Kill running agent containers."""
    for n in names:
        f.engine().kill_container(_resolve_ref(f, n), signal=signal)
        click.echo(n)


@container_group.command("rm")
@click.argument("names", nargs=-1, required=True)
@click.option("--force", "-f", is_flag=True)
@click.option("--volumes", "-v", is_flag=True, help="Also remove agent volumes.")
@pass_factory
def rm_cmd(f: Factory, names, force, volumes):
    """Remove agent containers."""
    what = ", ".join(names) + (" (and volumes)" if volumes else "")
    if not f.confirm_destructive(f"Remove {what}?", skip=force):
        raise SystemExit(1)
    for n in names:
        f.engine().remove_container(_resolve_ref(f, n), force=force, volumes=volumes)
        click.echo(n)


@container_group.command("inspect")
@click.argument("name")
@pass_factory
def inspect_cmd(f: Factory, name):
    """Inspect an agent container (JSON)."""
    click.echo(json.dumps(f.engine().inspect_container(_resolve_ref(f, name)), indent=2))


@container_group.command("logs")
@click.argument("name")
@click.option("--follow", "-F", is_flag=True)
@click.option("--tail", default="all", show_default=True)
@pass_factory
def logs_cmd(f: Factory, name, follow, tail):
    """Print container logs."""
    for chunk in f.engine().logs(_resolve_ref(f, name), follow=follow, tail=tail):
        sys.stdout.buffer.write(chunk)
    sys.stdout.flush()


@container_group.command("wait")
@click.argument("name")
@pass_factory
def wait_cmd(f: Factory, name):
    """Block until the container exits; echo its exit code."""
    click.echo(f.engine().wait_container(_resolve_ref(f, name)))


@click.command("exec", context_settings={
    "ignore_unknown_options": True,
    "allow_interspersed_args": False,
})
@click.option("--tty", "-t", is_flag=True, help="Allocate a pseudo-TTY.")
@click.option("--interactive", "-i", is_flag=True, help="Keep stdin open.")
@click.option("--env", "-e", multiple=True, help="KEY=VALUE (repeatable).")
@click.option("--user", "-u", default="", help="User inside the container.")
@click.option("--workdir", default="", help="Working directory for the command.")
@click.argument("name")
@click.argument("cmd", nargs=-1, type=click.UNPROCESSED, required=True)
@pass_factory
def exec_cmd(f: Factory, tty, interactive, env, user, workdir, name, cmd):
    """Run a command inside a running agent container.

    Reference parity: clawker container exec / clawker exec
    (docs/cli-reference/clawker_container_exec.md); exit code propagates.
    """
    ref = _resolve_ref(f, name)
    engine = f.engine()
    envd = dict(e.split("=", 1) if "=" in e else (e, "") for e in env)
    eid, stream = engine.exec(ref, list(cmd), user=user, env=envd,
                              tty=tty, stdin=interactive, workdir=workdir)
    from ..runtime import attach as attach_mod

    stdin: object = sys.stdin.buffer if interactive else io.BytesIO(b"")
    if tty and interactive and sys.stdin.isatty() and sys.stdout.isatty():
        # same raw-mode discipline as the attach path: without it the
        # local cooked terminal double-echoes and eats Ctrl-C
        with attach_mod.raw_terminal(sys.stdin.fileno()):
            attach_mod.pump_streams(stream, stdin, sys.stdout.buffer)
    else:
        attach_mod.pump_streams(stream, stdin, sys.stdout.buffer)
    code = engine.exec_exit_code(eid)
    if code != 0:
        raise SystemExit(code)


def register(root: click.Group) -> None:
    root.add_command(run_cmd)
    root.add_command(container_group)
    # Docker-style top-level aliases (reference: root/aliases.go)
    root.add_command(ls_cmd, "ps")
    root.add_command(start_cmd, "start")
    root.add_command(stop_cmd, "stop")
    root.add_command(rm_cmd, "rm")
    root.add_command(attach_cmd, "attach")
    root.add_command(kill_cmd, "kill")
    root.add_command(logs_cmd, "logs")
    root.add_command(exec_cmd, "exec")
    container_group.add_command(exec_cmd)
