"""``clawker loop``: run N autonomous agent loops across the fleet.

Net-new verb (no reference analogue -- SURVEY.md header); BASELINE.json
benchmark configs 3-4: a single firewalled loop on one TPU-VM, and
``--parallel 8`` fanning one loop per v5e-8 worker with aggregated
status output.

``loop`` is a group whose bare invocation runs the loops (the original
verb shape, so ``clawker loop -p 8`` keeps working); ``loop trace``
reconstructs a finished run's iteration span trees from its flight
recorder (docs/telemetry.md); ``loop --resume <run>`` replays a run's
write-ahead journal after a scheduler death and reconciles against the
containers still on the workers (docs/loop-resume.md).
"""

from __future__ import annotations

import json
import os
import signal
from pathlib import Path

import click

from ..loop import LoopScheduler, LoopSpec
from .factory import Factory

pass_factory = click.make_pass_decorator(Factory)

_hard_exit = os._exit       # seam: tests stub the second-stage exit


class _TwoStageInterrupt:
    """First Ctrl-C drains gracefully -- journal a clean ``shutdown``
    record and print the ``--resume`` hint; a second Ctrl-C hard-exits.
    Previously both signals raced ``sched.stop()`` with no feedback."""

    def __init__(self, sched: LoopScheduler):
        self.sched = sched
        self.hits = 0

    def __call__(self, signum=None, frame=None) -> None:
        self.hits += 1
        if self.hits == 1:
            click.echo(
                f"\ninterrupt: draining loops (resume later with "
                f"`clawker loop --resume {self.sched.loop_id}`; "
                "Ctrl-C again to hard-exit)", err=True)
            self.sched.request_shutdown("sigint")
        else:
            click.echo("\nsecond interrupt: hard exit", err=True)
            _hard_exit(130)


class _ClientInterrupt:
    """Daemon-owned runs invert the Ctrl-C contract: the run belongs to
    loopd, this CLI is only a viewer -- so the first Ctrl-C DETACHES
    (the run keeps executing; `clawker loop attach <run>` re-streams)
    instead of journaling a shutdown.  A second Ctrl-C hard-exits the
    viewer; the run is still untouched."""

    def __init__(self, client, run_id: str):
        self.client = client
        self.run_id = run_id
        self.hits = 0
        self.detached = False

    def __call__(self, signum=None, frame=None) -> None:
        self.hits += 1
        if self.hits == 1:
            self.detached = True
            click.echo(
                f"\ninterrupt: detached -- the run keeps executing under "
                f"loopd (re-attach with `clawker loop attach "
                f"{self.run_id}`; stop it with `clawker loopd stop` or "
                "`clawker loop --resume` after)", err=True)
            # shuts the socket down too, so a reader blocked in
            # events() wakes immediately
            self.client.detach()
        else:
            click.echo("\nsecond interrupt: hard exit (run unaffected)",
                       err=True)
            _hard_exit(130)


@click.group("loop", invoke_without_command=True)
@click.option("--parallel", "-p", type=int, default=0,
              help="Number of agent loops (default: settings loop.parallel).")
@click.option("--iterations", "-n", type=int, default=-1,
              help="Iterations per agent (0 = until interrupted; "
                   "default: settings loop.max_iterations).")
@click.option("--placement",
              type=click.Choice(["spread", "pack", "topology"]), default=None,
              help="spread = latency-weighted round-robin over pod workers "
                   "(default); pack = all on the first healthy worker; "
                   "topology = prefer pod-local ICI groups (falls back to "
                   "spread when the pod topology is unknown).")
@click.option("--tenant", default=None,
              help="Fairness class this run bills launches under "
                   "(default: settings loop.placement.tenant).  Runs "
                   "sharing a pod split each worker's admission tokens "
                   "by tenant weight instead of first-burst-wins.")
@click.option("--tenant-weight", type=float, default=None,
              help="Weighted-fair-queue share vs co-tenants "
                   "(default: settings loop.placement.tenant_weight).")
@click.option("--max-inflight-per-worker", type=int, default=None,
              help="Admission token bucket: concurrent in-flight "
                   "create/start launches allowed per worker (default: "
                   "settings loop.placement.max_inflight_per_worker).")
@click.option("--warm-pool", "warm_pool", type=int, default=None,
              help="Per-worker warm pool depth: keep N pre-created agent "
                   "containers per worker that placements adopt (relabel/"
                   "env-fixup + start) instead of paying a full create "
                   "(default: settings loop.warm_pool; 0 = off; ignored "
                   "with bind-mode --worktrees).")
@click.option("--image", default="@", help="Agent image ('@' = project default).")
@click.option("--prompt", default="", help="Prompt handed to each harness loop.")
@click.option("--worktrees/--no-worktrees", default=False,
              help="One git worktree + branch per agent loop, branched "
                   "from one base (never N clones); agent branches land "
                   "serially through the merge queue at iteration end "
                   "(settings loop.worktrees.*; docs/loop-worktrees.md).")
@click.option("--gitguard/--no-gitguard", "gitguard", default=None,
              help="Worktree runs only: route agent git traffic through "
                   "the run's gitguard proxy -- advertisements hide "
                   "out-of-namespace refs, pushes outside the agent's "
                   "branch namespace are refused with a git-readable "
                   "error, and run-scoped egress rules pin ssh/22 + "
                   "git/9418 shut so guarded smart-HTTP is the only git "
                   "path (default: settings gitguard.enable; "
                   "docs/git-policy.md).")
@click.option("--env", "env_kv", multiple=True, help="KEY=VAL extra agent env.")
@click.option("--failover", type=click.Choice(["migrate", "wait", "fail"]),
              default=None,
              help="When a worker's health breaker opens: migrate its loops "
                   "to the healthiest worker (default), wait for recovery, "
                   "or fail them.")
@click.option("--orphan-grace", type=float, default=None,
              help="Seconds an orphaned loop may wait for a healthy "
                   "placement before failing (default 600, 0 = fail "
                   "immediately; bounds a run against a fleet that "
                   "never recovers).")
@click.option("--resume", "resume_run", default=None, metavar="RUN",
              help="Resume a journaled run (id, unambiguous prefix, or "
                   "journal path) instead of starting a new one: adopts "
                   "still-running agent containers in place, accounts "
                   "exits the dead scheduler missed, re-launches lost "
                   "placements, sweeps ghosts.  The journal fixes the "
                   "run's shape; shape flags (-p/--placement/--image/"
                   "--prompt/...) are ignored.")
@click.option("--metrics-port", type=int, default=None,
              help="Serve Prometheus metrics on 127.0.0.1:<port>/metrics "
                   "for the run (default: settings telemetry.metrics_port; "
                   "0 = off).")
@click.option("--sentinel/--no-sentinel", "sentinel_flag", default=None,
              help="Attach the online fleet sentinel: fused egress + "
                   "behavior windows scored live each tick, flags as "
                   "typed anomaly.flag events/metrics/spans -- strictly "
                   "observe-only (default: settings sentinel.enable; "
                   "docs/analytics-online.md).")
@click.option("--ship-telemetry/--no-ship-telemetry", "ship_telemetry",
              default=None,
              help="Bulk-ship this run's registry snapshots, typed bus "
                   "events, and flight spans into the monitor stack's "
                   "OpenSearch index (default: settings "
                   "monitoring.shipper.enable).  Bounded backpressure: "
                   "a slow or down index drops oldest batches, never "
                   "stalls the run (docs/fleet-console.md#ingestion).")
@click.option("--chaos-plan", "chaos_plan", type=click.Path(exists=True),
              default=None,
              help="DEV: apply a chaos fault plan (clawker chaos plan "
                   "--out) to this live run -- worker faults where the "
                   "driver is injectable (fake), cli_sigkill events as a "
                   "REAL SIGKILL at the named crash seam (crash-test "
                   "--resume).  See docs/chaos.md.")
@click.option("--json", "as_json", is_flag=True, help="Final status as JSON.")
@click.option("--keep", is_flag=True, help="Keep containers after the run.")
@click.option("--daemon/--no-daemon", "use_daemon", default=None,
              help="Submit the run to a discovered loopd daemon "
                   "(docs/loopd.md) / force the in-process scheduler.  "
                   "Default: use the daemon when one answers on this "
                   "project's socket (settings loopd.enable).")
@click.option("--workerd/--no-workerd", "use_workerd", default=None,
              help="Route the launch data plane through worker-resident "
                   "workerd daemons (docs/workerd.md): batched intents + "
                   "events over one channel per worker instead of a WAN "
                   "round trip per engine call.  Default: use any workerd "
                   "that answers (settings workerd.enable); workers "
                   "without one keep the direct path.")
@click.option("--detach", is_flag=True,
              help="Daemon mode only: submit the run and exit "
                   "immediately -- it keeps executing under loopd; "
                   "re-attach with `clawker loop attach <run>`.")
@click.option("--pods", "use_pods", is_flag=True,
              help="Shard the run across every federated pod "
                   "(docs/federation.md): the front-tier router splits "
                   "--parallel N over the pods the pod policy picks "
                   "(locality, load, health), acquires capacity leases, "
                   "and submits one per-pod run per shard.  Shards run "
                   "detached; re-attach each with `clawker loop attach`.")
@pass_factory
@click.pass_context
def loop_group(ctx: click.Context, f: Factory, parallel, iterations,
               placement, tenant, tenant_weight, max_inflight_per_worker,
               warm_pool, image, prompt, worktrees, gitguard, env_kv,
               failover, orphan_grace, resume_run, metrics_port,
               sentinel_flag, ship_telemetry, chaos_plan, as_json, keep,
               use_daemon, use_workerd, detach, use_pods):
    """Fan autonomous agent loops across the runtime's workers."""
    if ctx.invoked_subcommand is not None:
        return
    _run_loops(f, parallel, iterations, placement, image, prompt, worktrees,
               env_kv, failover, orphan_grace, metrics_port, as_json, keep,
               gitguard=gitguard, resume_run=resume_run, tenant=tenant,
               tenant_weight=tenant_weight,
               max_inflight_per_worker=max_inflight_per_worker,
               warm_pool=warm_pool, sentinel_flag=sentinel_flag,
               ship_telemetry=ship_telemetry, chaos_plan=chaos_plan,
               use_daemon=use_daemon, use_workerd=use_workerd,
               detach=detach, use_pods=use_pods)


def _run_loops(f: Factory, parallel, iterations, placement, image, prompt,
               worktrees, env_kv, failover, orphan_grace, metrics_port,
               as_json, keep, gitguard=None, resume_run=None, tenant=None,
               tenant_weight=None, max_inflight_per_worker=None,
               warm_pool=None, sentinel_flag=None, ship_telemetry=None,
               chaos_plan=None, use_daemon=None, use_workerd=None,
               detach=False, use_pods=False):
    from .. import telemetry

    if use_pods and (resume_run or chaos_plan):
        raise click.ClickException(
            "--pods cannot combine with "
            + ("--resume" if resume_run else "--chaos-plan")
            + ": these stay in-process by design (docs/federation.md "
            "degrade matrix)")
    if use_daemon and (resume_run or chaos_plan):
        # an explicit --daemon must never silently degrade to a
        # CLI-owned run -- the exact ownership the user opted out of
        raise click.ClickException(
            "--daemon cannot combine with "
            + ("--resume" if resume_run else "--chaos-plan")
            + ": these stay in-process by design (docs/loopd.md "
            "degrade matrix)")
    env = {}
    for kv in env_kv:
        if "=" not in kv:
            raise click.BadParameter(f"--env {kv!r}: expected KEY=VAL")
        k, _, v = kv.partition("=")
        env[k] = v
    defaults = f.config.settings.loop
    tele = f.config.settings.telemetry

    live = f.streams.is_stdout_tty() and not as_json
    dashboard = None

    def on_event(agent, event, detail=""):
        if event == "trace.span":
            return      # spans go to the flight recorder; the stderr
            #             lines / dashboard ticker stay the lifecycle
            #             stream
        if dashboard is not None:
            dashboard.record_event(agent, event, detail)
            return
        line = f"[{agent}] {event}" + (f" {detail}" if detail else "")
        click.echo(line, err=True)

    def discover_workerd(worktree_run: bool, workspace_mode: str = ""):
        """ExecutorSet for the in-process scheduler, or None (direct).
        BIND-mode worktree runs stay direct (the worktree mount is
        host-local); snapshot-mode worktree runs dispatch -- content
        travels as a content-addressed workspace seed the worker-local
        store resolves (docs/loop-worktrees.md)."""
        if use_workerd is False:
            return None
        if worktree_run:
            mode = (workspace_mode
                    or f.config.settings.loop.worktrees.workspace_mode
                    or "bind")
            if mode == "bind":
                return None
        from ..workerd.executor import discover_executors

        execset = discover_executors(f.config, f.driver)
        if not execset:
            if use_workerd:
                raise click.ClickException(
                    "--workerd: no workerd answering on any worker "
                    "(start one per worker with `clawker workerd start`; "
                    "docs/workerd.md)")
            return None
        click.echo(f"workerd: launch data plane on {len(execset)} "
                   "worker(s) (batched intents over one channel each)",
                   err=True)
        return execset

    if resume_run:
        if (parallel or placement or prompt or env_kv or image != "@"
                or tenant or tenant_weight is not None
                or max_inflight_per_worker):
            click.echo("note: --resume takes the run shape from the "
                       "journal; shape flags are ignored", err=True)
        from ..loop.journal import RunJournal, replay

        jpath = _resolve_journal(f, resume_run)
        # checksum-verified fold: replay stops at the last verified
        # prefix, so a bit-flipped or torn journal can never seed the
        # resume with garbage state (docs/durability.md#verify)
        records, integrity = RunJournal.read_verified(jpath)
        if integrity.corrupt:
            click.echo(
                f"warning: {jpath.name}: {integrity.corrupt} corrupt "
                f"record(s) from line {integrity.first_corrupt_line}; "
                f"resuming from the last verified prefix "
                f"({integrity.verified} records) -- inspect with "
                f"`clawker journal verify {resume_run}`", err=True)
        elif integrity.torn_tail:
            click.echo(f"note: {jpath.name}: torn final record "
                       "(crash mid-append) dropped", err=True)
        run_image = replay(records)
        if not run_image.run_id:
            raise click.ClickException(
                f"{jpath}: no usable run header -- the journal is too "
                "damaged to resume; start a fresh run")
        executors = discover_workerd(
            bool(run_image.spec.get("worktrees")),
            str(run_image.spec.get("workspace_mode") or ""))
        sched = LoopScheduler.resume(
            f.config, f.driver, run_image, on_event=on_event,
            failover=failover,
            iterations=iterations if iterations >= 0 else None,
            orphan_grace_s=orphan_grace,
            telemetry=tele.flight_recorder.enable,
            executors=executors)
        spec = sched.spec
    else:
        pdef = defaults.placement
        wps = defaults.warm_pool
        spec = LoopSpec(
            parallel=parallel or defaults.parallel,
            iterations=(iterations if iterations >= 0
                        else defaults.max_iterations),
            placement=placement or pdef.policy,
            tenant=tenant or pdef.tenant,
            tenant_weight=(tenant_weight if tenant_weight is not None
                           else pdef.tenant_weight),
            max_inflight_per_worker=max_inflight_per_worker or 0,
            warm_pool_depth=(warm_pool if warm_pool is not None
                             else (wps.depth if wps.enable else 0)),
            image=image,
            prompt=prompt,
            worktrees=worktrees,
            gitguard=gitguard,
            env=env,
            failover=failover or defaults.failover,
            orphan_grace_s=orphan_grace,
            telemetry=tele.flight_recorder.enable,
        )
        # --- federated mode (docs/federation.md): the front-tier
        # router shards the run across every federated pod's loopd.
        # Shards are detached per-pod runs; a single-pod federation
        # degrades to exactly the daemon path below.
        if use_pods:
            if _run_loops_federated(f, spec, as_json=as_json, keep=keep):
                return
            click.echo("--pods: one pod answering; submitting as a "
                       "single daemon run", err=True)
        # --- daemon mode (docs/loopd.md): when a loopd answers on this
        # project's socket the CLI becomes a thin control client -- the
        # run executes inside the daemon (shared admission caps +
        # fairness across every concurrent CLI) and survives this
        # process exiting.  No daemon = the in-process path below,
        # unchanged.  --resume and --chaos-plan stay in-process: resume
        # reconciles against a DEAD scheduler's journal, and the chaos
        # controller needs the scheduler in-process to kill it.
        if use_daemon is not False and chaos_plan is None:
            from .cmd_loopd import ensure_daemon

            client = ensure_daemon(f)
            if client is not None:
                if use_workerd:
                    click.echo(
                        "note: loopd-hosted runs keep the in-process "
                        "launch path -- --workerd is ignored under the "
                        "daemon (docs/workerd.md degrade matrix)",
                        err=True)
                if max_inflight_per_worker:
                    click.echo(
                        "note: the admission bucket is daemon-scoped -- "
                        "--max-inflight-per-worker is ignored under "
                        "loopd (tune settings loop.placement.* and "
                        "restart the daemon)", err=True)
                if metrics_port:
                    click.echo(
                        "note: metrics are daemon-scoped under loopd -- "
                        "--metrics-port is ignored; scrape settings "
                        "loopd.metrics_port instead", err=True)
                if sentinel_flag:
                    click.echo(
                        "note: the sentinel is daemon-scoped under loopd "
                        "-- --sentinel is ignored; set settings "
                        "sentinel.enable and restart the daemon "
                        "(docs/analytics-online.md)", err=True)
                if ship_telemetry:
                    click.echo(
                        "note: telemetry shipping is daemon-scoped under "
                        "loopd -- --ship-telemetry is ignored; set "
                        "settings monitoring.shipper.enable and restart "
                        "the daemon (docs/fleet-console.md)", err=True)
                _run_loops_client(f, client, spec, detach=detach,
                                  as_json=as_json, keep=keep)
                return
            if use_daemon:
                raise click.ClickException(
                    "--daemon: no loopd answering on this project's "
                    "socket (start one with `clawker loopd start`)")
        if detach:
            raise click.ClickException(
                "--detach needs a loopd daemon to own the run "
                "(start one with `clawker loopd start`)")
        executors = discover_workerd(worktrees)
        sched = LoopScheduler(f.config, f.driver, spec, on_event=on_event,
                              executors=executors)
    # --- elastic capacity (docs/elastic-capacity.md): for in-process
    # runs the controller ticks on the scheduler's run thread -- the
    # same three loops loopd runs daemon-wide.  Settings-driven: a
    # loopd-hosted run gets the daemon's controller instead.
    cs = f.config.settings.capacity
    if cs.enable:
        from ..capacity import CapacityController, make_scaler

        scaler = (make_scaler(f.driver, f.config,
                              max_workers=cs.autoscale.max_workers)
                  if cs.autoscale.enable else None)
        sched.attach_capacity(CapacityController(cs, scaler=scaler))
        click.echo("capacity: elastic controller attached (pool "
                   f"[{cs.pool_min_depth},{cs.pool_max_depth}], "
                   f"slo={cs.slo.default_s or 'off'}, "
                   f"autoscale={'on' if cs.autoscale.enable else 'off'})",
                   err=True)
    chaos = None
    if chaos_plan:
        from ..chaos.plan import FaultPlan
        from ..chaos.runner import ChaosController

        plan = FaultPlan.load(chaos_plan)
        chaos = ChaosController(sched, f.driver, plan)
        click.echo(f"chaos: applying {len(plan.events)} event(s) from "
                   f"{chaos_plan} (seed {plan.seed})", err=True)
    feed = None
    watch = None
    metrics_server = None
    shipper = None
    port = metrics_port if metrics_port is not None else tele.metrics_port
    if port:
        metrics_server = telemetry.MetricsServer(port).start()
        click.echo(f"metrics: http://127.0.0.1:{metrics_server.port}/metrics",
                   err=True)
    if tele.otlp:
        lane = telemetry.telemetry_lane(f.config)
        if lane is not None:
            shipper = telemetry.MetricsOtlpShipper(lane).start()
    # --- bulk ingestion into the monitor stack (docs/fleet-console.md):
    # registry snapshots + typed bus events + flight spans into the
    # OpenSearch bulk API under bounded batching -- a down index drops
    # oldest batches, never the run
    bulk_shipper = None
    want_ship = (ship_telemetry if ship_telemetry is not None
                 else f.config.settings.monitoring.shipper.enable)
    if want_ship:
        from ..monitor.shipper import TelemetryShipper

        bulk_shipper = TelemetryShipper.from_config(
            f.config, source=f"loop:{sched.loop_id}").start()
        sched.attach_shipper(bulk_shipper)
        click.echo("telemetry: shipping into the monitor stack "
                   "(bounded; see monitor_ingest_* metrics)", err=True)
    # fleet anomaly scoring rides along whenever the accelerator runtime
    # is importable: scores land in the dashboard's ANOM-Z column, the
    # status JSON, and as scheduler events past the threshold.  With
    # --sentinel (or settings sentinel.enable) the single-file
    # AnomalyWatch is replaced by the online fleet sentinel: every
    # worker's stream fused with the run's typed events, scored as one
    # sharded program per tick, flags as typed anomaly.flag bus events
    # + metrics + flight spans (docs/analytics-online.md).  Strictly
    # observe-only either way.
    ss = f.config.settings.sentinel
    want_sentinel = (sentinel_flag if sentinel_flag is not None
                     else ss.enable)
    try:
        from ..analytics import runtime as art
    except ImportError:      # numpy-less host: the loop still runs
        art = None
    if art is not None and art.jax_available():
        if want_sentinel:
            from ..sentinel import FleetSentinel

            watch = FleetSentinel(
                f.config, f.driver, run_id=sched.loop_id,
                interval_s=ss.interval_s, window_s=ss.window_s,
                train_steps=ss.train_steps, threshold=ss.threshold,
                baseline_window=ss.baseline_window)
            sched.attach_sentinel(watch)
        else:
            watch = art.AnomalyWatch(f.config.logs_dir / "ebpf-egress.jsonl")
            sched.attach_anomaly_watch(watch)
        watch.start()
    elif want_sentinel:
        click.echo("note: --sentinel needs the accelerator runtime "
                   "(jax unavailable); running without live scoring",
                   err=True)
    if live:
        # BASELINE config 4: the shared monitor TUI over the fan-out, with
        # EVERY worker's egress stream merged into the ticker (remote
        # workers tail their jsonl back over the SSH mux)
        from ..fleet.egress_tail import EgressFeed
        from ..ui.dashboard import LoopDashboard

        feed = EgressFeed()
        local_log = f.config.logs_dir / "ebpf-egress.jsonl"
        for w in f.driver.workers():
            feed.add_worker(w, local_path=local_log)
        dashboard = LoopDashboard(
            f.streams, sched,
            egress_path=local_log,
            egress_feed=feed,
        )
    signal.signal(signal.SIGINT, _TwoStageInterrupt(sched))
    signal.signal(signal.SIGTERM,
                  lambda *_: sched.request_shutdown("sigterm"))
    click.echo(
        f"loop {sched.loop_id}: {spec.parallel} agent(s), "
        f"{spec.iterations or 'unbounded'} iteration(s), {spec.placement} "
        f"placement, {spec.failover} failover"
        + (f", tenant {spec.tenant}" if spec.tenant != "default" else "")
        + (f", warm-pool {spec.warm_pool_depth}"
           if spec.warm_pool_depth else "")
        + (" (resumed)" if resume_run else ""),
        err=True,
    )
    # chaos starts BEFORE start()/reconcile(): run.post_placement fires
    # inside start() and the resume.* seams inside reconcile(), so a
    # controller started after them could never land those kills
    if chaos is not None:
        chaos.start()
    if resume_run:
        summary = sched.reconcile()
        click.echo(
            "resume: {adopted} adopted, {continued} continued, "
            "{relaunched} relaunched, {exits_accounted} exit(s) accounted, "
            "{ghosts} ghost(s) swept, {orphaned} orphaned, "
            "{pool_restored} pool member(s) restored".format(**summary),
            err=True)
    else:
        sched.start()
    try:
        if dashboard is not None:
            with dashboard:
                loops = sched.run()
        else:
            loops = sched.run()
    finally:
        if chaos is not None:
            chaos.stop()
        if feed is not None:
            feed.stop()
        if watch is not None:
            watch.stop()
        if shipper is not None:
            shipper.stop()
        if bulk_shipper is not None:
            bulk_shipper.stop()
        if metrics_server is not None:
            metrics_server.stop()
        if executors is not None:
            executors.close_all()
    if not keep:
        sched.cleanup(remove_containers=True)
    stor = sched.storage_summary()
    if as_json:
        click.echo(json.dumps({"loop_id": sched.loop_id,
                               "agents": sched.status(),
                               "storage": stor}, indent=2))
    else:
        if stor.get("durability") != "ok":
            click.echo(f"storage: durability {stor.get('durability')} "
                       f"({stor.get('faults', 0)} fault(s)) -- inspect "
                       f"with `clawker journal verify {sched.loop_id}`",
                       err=True)
        for l in loops:
            codes = ",".join(map(str, l.exit_codes)) or "-"
            click.echo(f"{l.agent}\t{l.worker.id}\t{l.status}\t"
                       f"iters={l.iteration}\texits={codes}")
        if sched.flight is not None:
            click.echo(f"trace: clawker loop trace {sched.loop_id}", err=True)
        if sched.journal is not None and any(
                l.status == "stopped" for l in loops):
            click.echo(f"resume: clawker loop --resume {sched.loop_id}",
                       err=True)
    # orphaned loops never completed their budget (worker died, no
    # failover outcome before stop): that is not a success either
    if any(l.status in ("failed", "orphaned") for l in loops):
        raise SystemExit(1)


# ------------------------------------------------------------ daemon mode


def _client_spec_doc(spec: LoopSpec) -> dict:
    """LoopSpec -> the submit_run spec doc (the journal's run-header
    vocabulary; loopd.server.spec_from_doc is the inverse)."""
    return {
        "parallel": spec.parallel, "iterations": spec.iterations,
        "placement": spec.placement, "image": spec.image,
        "prompt": spec.prompt, "worktrees": spec.worktrees,
        "gitguard": spec.gitguard,
        "workspace_mode": spec.workspace_mode,
        "agent_prefix": spec.agent_prefix, "env": dict(spec.env),
        "failover": spec.failover, "tenant": spec.tenant,
        "tenant_weight": spec.tenant_weight,
        "tenant_max_inflight": spec.tenant_max_inflight,
        "max_inflight_per_worker": spec.max_inflight_per_worker,
        "warm_pool_depth": spec.warm_pool_depth,
        "orphan_grace_s": spec.orphan_grace_s,
        "telemetry": spec.telemetry,
    }


def _run_loops_federated(f: Factory, spec: LoopSpec, *, as_json: bool,
                         keep: bool) -> bool:
    """Shard the run across federated pods via the front-tier router
    (docs/federation.md).  Returns False when fewer than two pods
    answer -- the caller degrades to the single-daemon path."""
    from ..errors import ClawkerError
    from ..federation.router import FederationRouter
    from ..loopd.client import discover_all

    project = None
    try:
        project = f.config.project_name()
    except LookupError:
        pass
    clients = discover_all(f.config, require_project=project)
    if len(clients) < 2:
        for c in clients:
            c.close()
        if not clients and not f.config.settings.federation.pods:
            raise click.ClickException(
                "--pods: no federation configured and no loopd "
                "answering (register pods under settings "
                "federation.pods; docs/federation.md)")
        return False
    router = FederationRouter(f.config, clients)
    try:
        shards = router.submit_sharded(_client_spec_doc(spec), keep=keep)
    except ClawkerError as e:
        router.close()
        raise click.ClickException(f"federated submit failed: {e}")
    router.close()
    for pod, size, ack in shards:
        click.echo(f"loop {ack.get('run')}: {size} agent(s) on pod {pod} "
                   f"(tenant {ack.get('tenant')})", err=True)
    click.echo(f"detached: {len(shards)} shard(s) across "
               f"{len({p for p, _, _ in shards})} pod(s); re-attach "
               "each with `clawker loop attach <run>`", err=True)
    if as_json:
        click.echo(json.dumps({"shards": [
            {"pod": pod, "parallel": size, "loop_id": str(ack.get("run"))}
            for pod, size, ack in shards], "detached": True}))
    return True


def _run_loops_client(f: Factory, client, spec: LoopSpec, *, detach: bool,
                      as_json: bool, keep: bool) -> None:
    """Submit the run to loopd and (unless ``--detach``) stream it."""
    from ..errors import ClawkerError

    try:
        ack = client.submit_run(_client_spec_doc(spec), keep=keep,
                                stream=not detach)
    except ClawkerError as e:
        client.close()
        raise click.ClickException(f"loopd submit failed: {e}")
    run_id = str(ack.get("run", ""))
    click.echo(
        f"loop {run_id}: {spec.parallel} agent(s), "
        f"{spec.iterations or 'unbounded'} iteration(s), {spec.placement} "
        f"placement -- daemon-owned (loopd tenant {ack.get('tenant')})",
        err=True)
    if detach:
        client.close()
        click.echo(f"detached: the run executes under loopd; re-attach "
                   f"with `clawker loop attach {run_id}`", err=True)
        if as_json:
            click.echo(json.dumps({"loop_id": run_id, "detached": True}))
        return
    _stream_daemon_run(client, run_id, as_json)


def _stream_daemon_run(client, run_id: str, as_json: bool) -> None:
    """Render a daemon-owned run's event stream; exit semantics match
    the in-process path (non-zero on failed/orphaned loops).  Ctrl-C
    DETACHES -- killing the viewer must never kill the run."""
    from ..agentd.protocol import ProtocolError
    from ..errors import ClawkerError

    handler = _ClientInterrupt(client, run_id)
    signal.signal(signal.SIGINT, handler)
    final = None
    try:
        for frame in client.events():
            kind = frame.get("type")
            if kind == "event":
                detail = frame.get("detail", "")
                click.echo(f"[{frame.get('agent')}] {frame.get('event')}"
                           + (f" {detail}" if detail else ""), err=True)
            elif kind == "run_done":
                final = frame
                break
    except (ProtocolError, ClawkerError, OSError):
        pass        # daemon gone, or our own detach shut the socket
    finally:
        client.close()
    if final is None:
        if handler.detached:
            return      # clean viewer exit; the run lives on
        raise click.ClickException(
            f"loopd stream ended unexpectedly (daemon died?) -- the "
            f"journal survives: `clawker loop --resume {run_id}`")
    agents = final.get("agents", [])
    dropped = int(final.get("events_dropped", 0))
    if dropped:
        # the live view was lossy (slow subscriber queues); the journal
        # and flight record were not -- say so instead of looking whole
        click.echo(f"note: {dropped} event frame(s) dropped on slow "
                   f"subscriber queues during this run "
                   f"(loopd_events_dropped_total); the journal and "
                   f"flight record are complete", err=True)
    if as_json:
        click.echo(json.dumps({"loop_id": run_id, "agents": agents,
                               "events_dropped": dropped}, indent=2))
    else:
        for a in agents:
            codes = ",".join(map(str, a.get("exit_codes", []))) or "-"
            click.echo(f"{a.get('agent')}\t{a.get('worker')}\t"
                       f"{a.get('status')}\titers={a.get('iteration')}\t"
                       f"exits={codes}")
    if not final.get("ok", False):
        raise SystemExit(1)


@loop_group.command("attach")
@click.argument("run")
@click.option("--json", "as_json", is_flag=True, help="Final status as JSON.")
@pass_factory
def loop_attach(f: Factory, run, as_json):
    """Re-attach to a daemon-owned run and stream it.

    RUN is the loop id printed at submit time (or an unambiguous
    prefix).  The stream replays the run's recent events, then follows
    it live; Ctrl-C detaches again without touching the run
    (docs/loopd.md).
    """
    from ..errors import ClawkerError
    from ..loopd.client import discover

    client = discover(f.config)
    if client is None:
        raise click.ClickException(
            "no loopd daemon answering (check `clawker loopd status`; "
            "a dead daemon's runs resume with `clawker loop --resume`)")
    try:
        ack = client.attach(run)
    except ClawkerError as e:
        client.close()
        raise click.ClickException(str(e))
    run_id = str(ack.get("run", run))
    click.echo(f"attached to run {run_id} ({ack.get('state')})", err=True)
    _stream_daemon_run(client, run_id, as_json)


def _resolve_journal(f: Factory, run: str) -> Path:
    """RUN (an id, an unambiguous prefix, or a journal file path) -> the
    run journal to resume from."""
    from ..loop.journal import RUNS_DIR, journal_path

    runs_dir = f.config.logs_dir / RUNS_DIR
    as_path = Path(run)
    if as_path.exists() and as_path.is_file():
        return as_path
    exact = journal_path(f.config.logs_dir, run)
    if exact.exists():
        return exact
    matches = sorted(runs_dir.glob(f"{run}*.journal"))
    if len(matches) == 1:
        return matches[0]
    if matches:
        names = ", ".join(m.stem for m in matches)
        raise click.ClickException(f"run {run!r} is ambiguous: {names}")
    raise click.ClickException(
        f"no run journal for {run!r} under {runs_dir} (runs journal one "
        "by default; check settings loop.journal.enable)")


# ------------------------------------------------------------------- trace


def _resolve_flight(f: Factory, run: str | None) -> Path:
    from ..monitor.ledger import FLIGHT_DIR, flight_path

    flight_dir = f.config.logs_dir / FLIGHT_DIR
    if run:
        as_path = Path(run)
        if as_path.exists() and as_path.is_file():
            return as_path
        exact = flight_path(f.config.logs_dir, run)
        if exact.exists():
            return exact
        # id prefixes are fine as long as they are unambiguous
        matches = sorted(flight_dir.glob(f"loop-{run}*.jsonl"))
        if len(matches) == 1:
            return matches[0]
        if matches:
            names = ", ".join(m.stem.removeprefix("loop-") for m in matches)
            raise click.ClickException(
                f"run {run!r} is ambiguous: {names}")
        raise click.ClickException(
            f"no flight record for run {run!r} under {flight_dir}")
    latest = max(flight_dir.glob("loop-*.jsonl"), default=None,
                 key=lambda p: p.stat().st_mtime)
    if latest is None:
        raise click.ClickException(
            f"no flight records under {flight_dir} (runs record one by "
            "default; check settings telemetry.flight_recorder)")
    return latest


def _fmt_ms(seconds: float) -> str:
    return f"{seconds * 1000:.1f}ms"


def _render_node(node, depth: int, out: list[str]) -> None:
    rec = node.record
    pad = "  " * depth
    if depth == 0:
        from ..telemetry.spans import STANDALONE_SPANS

        attrs = rec.attrs
        extra = "".join(
            f" {k}={attrs[k]}" for k in ("queue_ms", "resumed", "adopted")
            if k in attrs)
        # a non-iteration root is a phase span whose iteration root never
        # flushed (crashed run): show it, flagged, rather than hide it.
        # Standalone run-level spans (sentinel ticks) are their own kind.
        name = (f"iteration {attrs.get('iteration', '?')}"
                if rec.name == "iteration"
                else rec.name if rec.name in STANDALONE_SPANS
                else f"{rec.name} (no iteration root)")
        out.append(f"{rec.agent}  {name} "
                   f"[{rec.status}] {_fmt_ms(rec.wall_s)} "
                   f"worker={rec.worker}{extra}")
    else:
        keys = [k for k in sorted(rec.attrs) if k != "iteration"]
        extra = "".join(f" {k}={rec.attrs[k]}" for k in keys)
        out.append(f"{pad}{rec.name} {_fmt_ms(rec.wall_s)}{extra}")
    for child in node.children:
        _render_node(child, depth + 1, out)


@loop_group.command("trace")
@click.argument("run", required=False)
@click.option("--json", "as_json", is_flag=True,
              help="Reconstructed span trees as JSON.")
@pass_factory
def loop_trace(f: Factory, run, as_json):
    """Reconstruct a loop run's iteration span trees.

    RUN is a loop id (as printed by `clawker loop`), an unambiguous id
    prefix, or a path to a flight-recorder JSONL file; the newest run is
    traced when omitted.  Shows per-span wall time, lane queue time, and
    migration hops -- the post-mortem view of what every iteration paid
    and where it travelled (docs/telemetry.md).
    """
    from ..monitor.ledger import read_rotated_lines
    from ..telemetry import build_trees, load_spans, tree_to_dict

    path = _resolve_flight(f, run)
    # read across the rotation boundary: a size-capped recorder keeps the
    # previous generation at <path>.1 (docs/telemetry.md)
    spans = load_spans(read_rotated_lines(path))
    if not spans:
        raise click.ClickException(f"{path}: no span records")
    trees = build_trees(spans)
    run_id = spans[0].trace_id or path.stem.removeprefix("loop-")
    if as_json:
        click.echo(json.dumps({
            "run": run_id,
            "path": str(path),
            "iterations": [tree_to_dict(t) for t in trees],
        }, indent=2))
        return
    from ..telemetry.spans import STANDALONE_SPANS

    agents = sorted({s.agent for s in spans})
    migrations = [s for s in spans if s.name == "migrate"]
    # a phase span promoted to a root means its iteration root never
    # flushed -- the writer died before end_iteration/close_open ran.
    # Run-level standalone roots (sentinel ticks) are by-design roots.
    promoted = [t for t in trees if t.record.name != "iteration"
                and t.record.name not in STANDALONE_SPANS]
    n_iters = sum(1 for t in trees if t.record.name == "iteration")
    click.echo(f"run {run_id}: {n_iters} iteration span(s) across "
               f"{len(agents)} agent(s)  ({path})")
    out: list[str] = []
    for tree in trees:
        _render_node(tree, 0, out)
    for line in out:
        click.echo(line)
    if migrations:
        click.echo("migration hops:")
        for m in sorted(migrations, key=lambda s: s.t_start):
            click.echo(f"  {m.agent} iteration {m.attrs.get('iteration')}: "
                       f"{m.attrs.get('src')} -> {m.attrs.get('dst')}")
    if promoted:
        click.echo(f"warning: {len(promoted)} span(s) without a recorded "
                   "iteration root (crashed run?)", err=True)


def register(cli: click.Group) -> None:
    cli.add_command(loop_group)
