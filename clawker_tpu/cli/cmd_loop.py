"""``clawker loop``: run N autonomous agent loops across the fleet.

Net-new verb (no reference analogue -- SURVEY.md header); BASELINE.json
benchmark configs 3-4: a single firewalled loop on one TPU-VM, and
``--parallel 8`` fanning one loop per v5e-8 worker with aggregated
status output.
"""

from __future__ import annotations

import json
import signal

import click

from ..loop import LoopScheduler, LoopSpec
from .factory import Factory

pass_factory = click.make_pass_decorator(Factory)


@click.command("loop")
@click.option("--parallel", "-p", type=int, default=0,
              help="Number of agent loops (default: settings loop.parallel).")
@click.option("--iterations", "-n", type=int, default=-1,
              help="Iterations per agent (0 = until interrupted; "
                   "default: settings loop.max_iterations).")
@click.option("--placement", type=click.Choice(["spread", "pack"]), default=None,
              help="spread = round-robin over pod workers (default); "
                   "pack = all on worker 0.")
@click.option("--image", default="@", help="Agent image ('@' = project default).")
@click.option("--prompt", default="", help="Prompt handed to each harness loop.")
@click.option("--worktrees/--no-worktrees", default=False,
              help="One git worktree per agent loop.")
@click.option("--env", "env_kv", multiple=True, help="KEY=VAL extra agent env.")
@click.option("--failover", type=click.Choice(["migrate", "wait", "fail"]),
              default=None,
              help="When a worker's health breaker opens: migrate its loops "
                   "to the healthiest worker (default), wait for recovery, "
                   "or fail them.")
@click.option("--orphan-grace", type=float, default=None,
              help="Seconds an orphaned loop may wait for a healthy "
                   "placement before failing (default 600, 0 = fail "
                   "immediately; bounds a run against a fleet that "
                   "never recovers).")
@click.option("--json", "as_json", is_flag=True, help="Final status as JSON.")
@click.option("--keep", is_flag=True, help="Keep containers after the run.")
@pass_factory
def loop_cmd(f: Factory, parallel, iterations, placement, image, prompt,
             worktrees, env_kv, failover, orphan_grace, as_json, keep):
    """Fan autonomous agent loops across the runtime's workers."""
    env = {}
    for kv in env_kv:
        if "=" not in kv:
            raise click.BadParameter(f"--env {kv!r}: expected KEY=VAL")
        k, _, v = kv.partition("=")
        env[k] = v
    defaults = f.config.settings.loop
    spec = LoopSpec(
        parallel=parallel or defaults.parallel,
        iterations=iterations if iterations >= 0 else defaults.max_iterations,
        placement=placement or defaults.placement,
        image=image,
        prompt=prompt,
        worktrees=worktrees,
        env=env,
        failover=failover or defaults.failover,
        orphan_grace_s=orphan_grace,
    )

    live = f.streams.is_stdout_tty() and not as_json
    dashboard = None

    def on_event(agent, event, detail=""):
        if dashboard is not None:
            dashboard.record_event(agent, event, detail)
            return
        line = f"[{agent}] {event}" + (f" {detail}" if detail else "")
        click.echo(line, err=True)

    sched = LoopScheduler(f.config, f.driver, spec, on_event=on_event)
    feed = None
    watch = None
    # fleet anomaly scoring rides along whenever the accelerator runtime
    # is importable: scores land in the dashboard's ANOM-Z column, the
    # status JSON, and as scheduler events past the threshold
    try:
        from ..analytics import runtime as art
    except ImportError:      # numpy-less host: the loop still runs
        art = None
    if art is not None and art.jax_available():
        watch = art.AnomalyWatch(f.config.logs_dir / "ebpf-egress.jsonl")
        sched.attach_anomaly_watch(watch)
        watch.start()
    if live:
        # BASELINE config 4: the shared monitor TUI over the fan-out, with
        # EVERY worker's egress stream merged into the ticker (remote
        # workers tail their jsonl back over the SSH mux)
        from ..fleet.egress_tail import EgressFeed
        from ..ui.dashboard import LoopDashboard

        feed = EgressFeed()
        local_log = f.config.logs_dir / "ebpf-egress.jsonl"
        for w in f.driver.workers():
            feed.add_worker(w, local_path=local_log)
        dashboard = LoopDashboard(
            f.streams, sched,
            egress_path=local_log,
            egress_feed=feed,
        )
    signal.signal(signal.SIGINT, lambda *_: sched.stop())
    signal.signal(signal.SIGTERM, lambda *_: sched.stop())
    click.echo(
        f"loop {sched.loop_id}: {spec.parallel} agent(s), "
        f"{spec.iterations or 'unbounded'} iteration(s), {spec.placement} "
        f"placement, {spec.failover} failover",
        err=True,
    )
    sched.start()
    try:
        if dashboard is not None:
            with dashboard:
                loops = sched.run()
        else:
            loops = sched.run()
    finally:
        if feed is not None:
            feed.stop()
        if watch is not None:
            watch.stop()
    if not keep:
        sched.cleanup(remove_containers=True)
    if as_json:
        click.echo(json.dumps({"loop_id": sched.loop_id,
                               "agents": sched.status()}, indent=2))
    else:
        for l in loops:
            codes = ",".join(map(str, l.exit_codes)) or "-"
            click.echo(f"{l.agent}\t{l.worker.id}\t{l.status}\t"
                       f"iters={l.iteration}\texits={codes}")
    # orphaned loops never completed their budget (worker died, no
    # failover outcome before stop): that is not a success either
    if any(l.status in ("failed", "orphaned") for l in loops):
        raise SystemExit(1)


def register(cli: click.Group) -> None:
    cli.add_command(loop_cmd)
