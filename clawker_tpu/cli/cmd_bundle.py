"""`clawker bundle` verbs: list / install / validate / remove
(reference: internal/cmd/bundle over internal/bundle Manager)."""

from __future__ import annotations

from pathlib import Path

import click

from ..bundle import BundleManager, Resolver
from .factory import Factory

pass_factory = click.make_pass_decorator(Factory)


@click.group("bundle")
def bundle_group():
    """Manage harness / stack / monitoring bundles."""


@bundle_group.command("list")
@pass_factory
def bundle_list(f: Factory):
    """List visible components by kind and tier."""
    r = Resolver(f.config)
    for kind in ("harness", "stack", "monitoring"):
        for comp in r.list(kind):
            click.echo(f"{kind}\t{comp.name}\t{comp.tier}\t{comp.description}")
    for b in BundleManager(f.config).list_installed():
        click.echo(f"bundle\t{b.namespace}/{b.name}\t{b.source or '-'}")


@bundle_group.command("install")
@click.argument("source")
@click.option("--namespace", "-n", default="local", show_default=True)
@click.option("--name", default="", help="Bundle name (default: derived from source).")
@pass_factory
def bundle_install(f: Factory, source, namespace, name):
    """Install a bundle from a directory or git URL."""
    b = BundleManager(f.config).install(source, namespace=namespace, name=name)
    comps = ", ".join(f"{k}:{len(v)}" for k, v in b.components.items() if v)
    click.echo(f"installed {b.namespace}/{b.name} ({comps})")


@bundle_group.command("validate")
@click.argument("path", type=click.Path(exists=True, file_okay=False, path_type=Path))
@pass_factory
def bundle_validate(f: Factory, path):
    """Validate a bundle directory without installing it."""
    errs = BundleManager(f.config).validate_tree(path)
    if errs:
        for e in errs:
            click.echo(e, err=True)
        raise SystemExit(1)
    click.echo("ok")


def _parse_spec(spec: str) -> tuple[str, str]:
    """``namespace/name`` (default namespace: local)."""
    ns, _, name = spec.partition("/")
    if not name:
        ns, name = "local", ns
    return ns, name


@bundle_group.command("remove")
@click.argument("spec")
@pass_factory
def bundle_remove(f: Factory, spec):
    """Remove an installed bundle (namespace/name)."""
    ns, name = _parse_spec(spec)
    BundleManager(f.config).remove(ns, name)
    click.echo(f"removed {ns}/{name}")


@bundle_group.command("update")
@click.argument("spec", required=False)
@pass_factory
def bundle_update(f: Factory, spec):
    """Re-install bundles from their recorded sources.

    With SPEC (namespace/name), updates that one bundle; without, runs
    the drift-checked refresh over every install (what `run` does on its
    daily TTL, forced now)."""
    mgr = BundleManager(f.config)
    if spec:
        ns, name = _parse_spec(spec)
        match = [b for b in mgr.list_installed()
                 if b.namespace == ns and b.name == name]
        if not match:
            raise click.ClickException(f"bundle {ns}/{name} not installed")
        (inst,) = match
        if not inst.source:
            raise click.ClickException(
                f"bundle {ns}/{name} has no recorded source")
        mgr.install(inst.source, namespace=ns, name=name)
        click.echo(f"updated {ns}/{name} from {inst.source}")
        return
    errors: list[tuple[str, str]] = []
    updated = mgr.auto_update_check(ttl_s=0, errors=errors)  # forced
    for ref in updated:
        click.echo(f"updated {ref}")
    for ref, err in errors:
        click.echo(f"update failed: {ref}: {err}", err=True)
    if not updated and not errors:
        click.echo("all bundles current")
    if errors:
        raise SystemExit(1)


@bundle_group.command("prune")
@click.option("--apply", is_flag=True,
              help="Actually delete (default: dry-run report).")
@click.option("--grace-days", type=float, default=7.0, show_default=True,
              help="Installs younger than this never qualify.")
@pass_factory
def bundle_prune(f: Factory, apply, grace_days):
    """GC installed bundles: crashed-swap leftovers + installs no
    registered project references (reference internal/bundle/gc.go)."""
    report = BundleManager(f.config).gc(apply=apply,
                                        grace_s=grace_days * 86400)
    for p in report["leftovers"]:
        click.echo(f"leftover\t{p}")
    for b in report["unreferenced"]:
        click.echo(f"unreferenced\t{b}")
    if apply:
        click.echo(f"removed {len(report['removed'])}")
    elif report["leftovers"] or report["unreferenced"]:
        click.echo("dry-run: pass --apply to delete")
    else:
        click.echo("nothing to prune")


def register(root: click.Group) -> None:
    root.add_command(bundle_group)
