"""Image verbs: ls/rm now; `build` joins with the bundler milestone
(reference: internal/cmd/image)."""

from __future__ import annotations

import json

import click

from .factory import Factory

pass_factory = click.make_pass_decorator(Factory)


@click.group("image")
def image_group():
    """Manage project images."""


@image_group.command("ls")
@click.option("--format", "fmt", type=click.Choice(["table", "json"]), default="table")
@pass_factory
def image_ls(f: Factory, fmt):
    imgs = f.engine().list_images()
    if fmt == "json":
        click.echo(json.dumps(imgs, indent=2))
        return
    for i in imgs:
        for tag in i.get("RepoTags") or []:
            click.echo(tag)


@image_group.command("rm")
@click.argument("refs", nargs=-1, required=True)
@click.option("--force", "-f", is_flag=True)
@pass_factory
def image_rm(f: Factory, refs, force):
    for r in refs:
        f.engine().remove_image(r, force=force)
        click.echo(r)


def register(root: click.Group) -> None:
    root.add_command(image_group)
