"""``clawker trace``: one causal span tree across every process.

Net-new verb (docs/tracing.md).  Where ``clawker loop trace`` renders
the SCHEDULER's flight recorder alone, this merges every recorder
family that holds a piece of the run -- router submit hops, loopd
submit hops, the scheduler's iteration trees, workerd's remote
create/start/wait segments, engine request spans -- into one rooted
waterfall with per-hop WAN wait attributed and clock skew already
adjusted (and audited: a span whose adjusted time still escapes its
parent renders flagged, never re-ordered).  Missing segments render as
explicit ``gap`` spans: a dead workerd is a gap, not a broken tree.
"""

from __future__ import annotations

import json
from pathlib import Path

import click

from .factory import Factory

pass_factory = click.make_pass_decorator(Factory)


def _resolve_run(f: Factory, run: str | None) -> str:
    """A run id from an id, an unambiguous prefix, a flight-recorder
    path, or (when omitted) the newest scheduler recorder."""
    from ..monitor.ledger import FLIGHT_DIR

    flight_dir = f.config.logs_dir / FLIGHT_DIR
    if run:
        as_path = Path(run)
        if as_path.is_file():
            return as_path.stem.removeprefix("loop-")
        matches = sorted(flight_dir.glob(f"loop-{run}*.jsonl"))
        if len(matches) == 1:
            return matches[0].stem.removeprefix("loop-")
        if matches:
            names = ", ".join(m.stem.removeprefix("loop-") for m in matches)
            raise click.ClickException(f"run {run!r} is ambiguous: {names}")
        return run      # daemon recorders may hold it without a local file
    latest = max(flight_dir.glob("loop-*.jsonl"), default=None,
                 key=lambda p: p.stat().st_mtime)
    if latest is None:
        raise click.ClickException(
            f"no flight records under {flight_dir} (runs record one by "
            "default; check settings telemetry.flight_recorder)")
    return latest.stem.removeprefix("loop-")


def _label(rec) -> str:
    if rec.name == "iteration":
        return f"iteration {rec.attrs.get('iteration', '?')}"
    return rec.name


def _flags(rec) -> str:
    out = []
    wan = rec.attrs.get("wan_ms")
    if wan is not None:
        out.append(f"wan={float(wan):.1f}ms")
    if rec.attrs.get("skew_adjusted"):
        out.append(f"skew={float(rec.attrs.get('skew_s', 0.0)) * 1000:+.1f}ms")
    if rec.attrs.get("skew_suspect"):
        out.append("SKEW-SUSPECT")
    if rec.attrs.get("gap"):
        out.append(f"GAP(expect={rec.attrs.get('expect', '?')})")
    if rec.status not in ("ok", ""):
        out.append(rec.status)
    return "  ".join(out)


def _render(node, t0: float, depth: int, out: list[str]) -> None:
    rec = node.record
    who = rec.agent or rec.worker or "-"
    src = rec.attrs.get("source", "")
    off = (rec.t_start - t0) * 1000.0
    wall = rec.wall_s * 1000.0
    flags = _flags(rec)
    out.append(f"  {'  ' * depth}{_label(rec):<24} {who:<14} "
               f"{src:<18} +{off:>8.1f}ms {wall:>9.1f}ms"
               + (f"  {flags}" if flags else ""))
    for child in node.children:
        _render(child, t0, depth + 1, out)


@click.command("trace")
@click.argument("run", required=False)
@click.option("--json", "as_json", is_flag=True,
              help="Merged trace forest as JSON.")
@pass_factory
def trace_cmd(f: Factory, run, as_json):
    """Cross-process trace waterfall for a loop run.

    RUN is a loop id (as printed by `clawker loop`), an unambiguous id
    prefix, or a path to a flight-recorder JSONL file; the newest run
    is traced when omitted.  Joins the router/loopd/scheduler/workerd
    flight recorders into one causal tree per iteration
    (docs/tracing.md): per-hop WAN wait, clock-skew-adjusted offsets,
    explicit gap spans where a daemon's segment is missing.
    """
    from ..tracing.merge import hop_waits, merge_run

    run_id = _resolve_run(f, run)
    res = merge_run(f.config.logs_dir, run_id)
    if as_json:
        click.echo(json.dumps(res.to_dict(), indent=2))
        return
    if not res.roots:
        raise click.ClickException(
            f"no spans for run {run_id!r} in any recorder under "
            f"{f.config.logs_dir}")
    srcs = ", ".join(f"{k}={v}" for k, v in sorted(res.sources.items()))
    click.echo(f"run {run_id}: {res.spans} span(s) from [{srcs}]")
    if res.gaps or res.skew_suspects:
        click.echo(f"  {res.gaps} gap(s), "
                   f"{res.skew_suspects} skew suspect(s)")
    t0 = min(r.record.t_start for r in res.roots)
    out: list[str] = []
    for root in res.roots:
        _render(root, t0, 0, out)
    for line in out:
        click.echo(line)
    waits = hop_waits(res.roots)
    if waits:
        click.echo("per-hop WAN wait:")
        for name, ms in waits.items():
            click.echo(f"  {name:<24} {ms:>9.1f}ms")


def register(cli: click.Group) -> None:
    cli.add_command(trace_cmd)
