"""`clawker build` -- build the project's base + harness images
(reference: internal/cmd/image/build/build.go:110; progress tree parity
with tui.RunProgress at :395)."""

from __future__ import annotations

import click

from ..bundler.build import ProjectBuilder
from .factory import Factory

pass_factory = click.make_pass_decorator(Factory)


@click.command("build")
@click.option("--harness", default="", help="Harness override (default: project config).")
@click.option("--no-cache", is_flag=True, help="Build without layer cache.")
@click.option("--quiet", "-q", is_flag=True, help="Only print the final image ref.")
@click.option("--plain", is_flag=True, help="Raw build output (no progress tree).")
@click.option("--secret", "secret_specs", multiple=True,
              help="id=NAME,src=PATH secret for RUN --mount=type=secret "
                   "(BuildKit session lane; repeatable).")
@click.option("--ssh", "ssh_spec", default="",
              help="Forward an ssh agent into the build: 'default' uses "
                   "$SSH_AUTH_SOCK, or default=/path/to/sock.")
@pass_factory
def build_cmd(f: Factory, harness, no_cache, quiet, plain, secret_specs,
              ssh_spec):
    """Build the project image (base stage + harness stage + :default tag)."""
    from ..ui.buildview import BuildProgressView
    from ..ui.progress import ProgressTree

    ca_pem = None
    if f.config.settings.firewall.enable:
        from ..firewall.pki import ensure_ca

        ca_pem = ensure_ca(f.config.pki_dir).cert_pem

    if quiet:
        progress = lambda _line: None  # noqa: E731
        view = None
    elif plain:
        progress = lambda line: click.echo(line)  # noqa: E731
        view = None
    else:
        tree = ProgressTree(f.streams)
        view = BuildProgressView(tree)

        def progress(line: str) -> None:
            # stage boundary lines come from the builder itself
            if line.startswith(("building ", "tagged ")):
                view.stage(line)
            else:
                view.line(line)

    secrets = _parse_secrets(secret_specs)
    ssh_sock = _parse_ssh(ssh_spec)
    builder = ProjectBuilder(f.engine(), f.config, ca_cert_pem=ca_pem,
                             progress=progress)
    kw = dict(harness_override=harness, no_cache=no_cache,
              secrets=secrets, ssh_auth_sock=ssh_sock)
    if view is not None:
        with view.tree:
            try:
                res = builder.build(**kw)
                view.done()
            except Exception as e:
                view.failed(str(e))
                raise
    else:
        res = builder.build(**kw)
    click.echo(res.default_ref)
    if not res.with_agentd and not quiet:
        click.echo(
            "warning: agentd binary not found -- image runs the harness "
            "directly without PID-1 supervision (build native/ first)",
            err=True,
        )


def register(root: click.Group) -> None:
    root.add_command(build_cmd)


def _parse_secrets(specs: tuple[str, ...]) -> dict[str, bytes] | None:
    """docker-compatible: --secret id=NAME,src=PATH (also env=VAR)."""
    import os

    out: dict[str, bytes] = {}
    for spec in specs:
        kv = dict(part.split("=", 1) for part in spec.split(",") if "=" in part)
        sid = kv.get("id", "")
        if not sid:
            raise click.BadParameter(f"--secret {spec!r}: id= required")
        if "src" in kv or "source" in kv:
            path = kv.get("src") or kv.get("source", "")
            try:
                out[sid] = open(path, "rb").read()
            except OSError as e:
                raise click.BadParameter(f"--secret {sid}: {e}") from None
        elif "env" in kv:
            val = os.environ.get(kv["env"])
            if val is None:
                raise click.BadParameter(
                    f"--secret {sid}: env {kv['env']} not set")
            out[sid] = val.encode()
        else:
            raise click.BadParameter(f"--secret {spec!r}: src= or env= required")
    return out or None


def _parse_ssh(spec: str) -> str:
    import os

    if not spec:
        return ""
    name, _, path = spec.partition("=")
    if path:
        return path
    sock = os.environ.get("SSH_AUTH_SOCK", "")
    if not sock:
        raise click.BadParameter("--ssh default: SSH_AUTH_SOCK not set")
    return sock
