"""`clawker build` -- build the project's base + harness images
(reference: internal/cmd/image/build/build.go:110; progress tree parity
with tui.RunProgress at :395)."""

from __future__ import annotations

import click

from ..bundler.build import ProjectBuilder
from .factory import Factory

pass_factory = click.make_pass_decorator(Factory)


@click.command("build")
@click.option("--harness", default="", help="Harness override (default: project config).")
@click.option("--no-cache", is_flag=True, help="Build without layer cache.")
@click.option("--quiet", "-q", is_flag=True, help="Only print the final image ref.")
@click.option("--plain", is_flag=True, help="Raw build output (no progress tree).")
@pass_factory
def build_cmd(f: Factory, harness, no_cache, quiet, plain):
    """Build the project image (base stage + harness stage + :default tag)."""
    from ..ui.buildview import BuildProgressView
    from ..ui.progress import ProgressTree

    ca_pem = None
    if f.config.settings.firewall.enable:
        from ..firewall.pki import ensure_ca

        ca_pem = ensure_ca(f.config.pki_dir).cert_pem

    if quiet:
        progress = lambda _line: None  # noqa: E731
        view = None
    elif plain:
        progress = lambda line: click.echo(line)  # noqa: E731
        view = None
    else:
        tree = ProgressTree(f.streams)
        view = BuildProgressView(tree)

        def progress(line: str) -> None:
            # stage boundary lines come from the builder itself
            if line.startswith(("building ", "tagged ")):
                view.stage(line)
            else:
                view.line(line)

    builder = ProjectBuilder(f.engine(), f.config, ca_cert_pem=ca_pem,
                             progress=progress)
    if view is not None:
        with view.tree:
            try:
                res = builder.build(harness_override=harness, no_cache=no_cache)
                view.done()
            except Exception as e:
                view.failed(str(e))
                raise
    else:
        res = builder.build(harness_override=harness, no_cache=no_cache)
    click.echo(res.default_ref)
    if not res.with_agentd and not quiet:
        click.echo(
            "warning: agentd binary not found -- image runs the harness "
            "directly without PID-1 supervision (build native/ first)",
            err=True,
        )


def register(root: click.Group) -> None:
    root.add_command(build_cmd)
