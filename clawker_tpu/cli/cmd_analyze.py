"""``clawker analyze``: first-party static architectural-invariant checks.

Net-new verb (docs/static-analysis.md).  Walks the package with the
stdlib ``ast`` module and runs the registered checkers -- write-ahead
discipline, import layering + sentinel observe-only, no blocking calls
under locks, AF_UNIX socket hardening, seam/metric registry parity,
chaos plan determinism.  Pre-existing findings live in the committed
grandfather baseline (analysis-baseline.json); NEW findings exit 2.

Thin shim over ``clawker_tpu.analysis.runner.main`` so the same engine
also runs bare (``python -m clawker_tpu.analysis``) on hosts without
the CLI deps installed.
"""

from __future__ import annotations

import click

from ..errors import ExitError


@click.command("analyze")
@click.option("--json", "as_json", is_flag=True,
              help="Stable JSON report on stdout (CI consumption).")
@click.option("--baseline", "baseline_path", type=click.Path(), default=None,
              help="Baseline file (default: <root>/analysis-baseline.json).")
@click.option("--baseline-update", is_flag=True,
              help="Rewrite the baseline to the current findings "
                   "(grandfather new ones, expire stale entries).")
@click.option("--root", "root_path", type=click.Path(exists=True),
              default=None,
              help="Repo root to analyze (default: the repo this package "
                   "lives in).")
@click.option("--checker", "checkers", multiple=True, metavar="ID",
              help="Run only this checker (repeatable; see "
                   "--list-checkers).")
@click.option("--list-checkers", is_flag=True,
              help="List registered checkers and exit.")
def analyze(as_json, baseline_path, baseline_update, root_path, checkers,
            list_checkers):
    """Run the static architectural-invariant checkers.

    Exit 0 when every finding is grandfathered in the committed
    baseline, 2 when a NEW finding exists -- the CI gate.  Checker
    catalogue, the baseline workflow, and how to add a checker:
    docs/static-analysis.md.
    """
    from ..analysis.runner import main as run_main

    argv: list[str] = []
    if as_json:
        argv.append("--json")
    if baseline_path:
        argv += ["--baseline", baseline_path]
    if baseline_update:
        argv.append("--baseline-update")
    if root_path:
        argv += ["--root", root_path]
    for c in checkers:
        argv += ["--checker", c]
    if list_checkers:
        argv.append("--list-checkers")
    rc = run_main(argv)
    if rc:
        raise ExitError(rc)


def register(cli: click.Group) -> None:
    cli.add_command(analyze)
