"""``clawker chaos``: seeded chaos soak + deterministic replay.

Net-new verb (docs/chaos.md).  ``chaos run`` executes N seeded fault
scenarios against an in-process fake pod -- worker kills/wedges/flaps/
slow-loris, engine 5xx bursts, probe drops, CLI SIGKILLs at named crash
seams with kill/resume cycles -- and audits the fleet invariants after
each one (zero duplicate creates, zero leaks, admission caps held,
no spurious quarantine, exits accounted exactly once, span trees
complete).  A failing scenario is shrunk to a minimal event schedule
and reported with its one-command repro.
"""

from __future__ import annotations

import json

import click

from .factory import Factory

pass_factory = click.make_pass_decorator(Factory)


@click.group("chaos")
def chaos_group():
    """Deterministic chaos injection against the loop scheduler's
    robustness stack (breakers/failover, journal/--resume, admission,
    warm pools)."""


@chaos_group.command("run")
@click.option("--scenarios", "-n", type=int, default=None,
              help="Seeded scenarios to execute "
                   "(default: settings chaos.scenarios).")
@click.option("--seed", "-s", type=int, default=None,
              help="Soak seed: scenario i replays as (seed, i) "
                   "(default: settings chaos.seed).")
@click.option("--parallel", "-p", type=int, default=None,
              help="Agent loops per scenario (default: settings "
                   "chaos.parallel).")
@click.option("--workers", "-w", type=int, default=None,
              help="Fake pod size per scenario (default: settings "
                   "chaos.workers).")
@click.option("--iterations", type=int, default=None,
              help="Iteration budget per loop (default: settings "
                   "chaos.iterations).")
@click.option("--keep-going", is_flag=True,
              help="Run every scenario even after a failure "
                   "(default: stop and shrink the first).")
@click.option("--no-shrink", is_flag=True,
              help="Skip minimal-repro shrinking of failing schedules.")
@click.option("--json", "as_json", is_flag=True, help="Report as JSON.")
@pass_factory
def chaos_run(f: Factory, scenarios, seed, parallel, workers, iterations,
              keep_going, no_shrink, as_json):
    """Run a seeded chaos soak and audit fleet invariants.

    Every scenario builds a fresh fake pod, executes its generated
    fault schedule (kill/resume cycles included), and cross-audits
    engine state vs journal replay vs telemetry.  Exit is non-zero on
    any invariant violation; the report names the exact
    ``clawker chaos replay --seed S --scenario I`` repro and, unless
    --no-shrink, the minimal failing schedule.
    """
    from ..chaos.runner import run_soak

    cs = f.config.settings.chaos
    scenarios = scenarios if scenarios is not None else cs.scenarios
    seed = seed if seed is not None else cs.seed

    def progress(result):
        if not as_json:
            mark = "ok" if result.ok else "FAIL"
            click.echo(
                f"scenario {result.scenario}: {mark} "
                f"({result.wall_s:.2f}s, {result.injected} fault(s), "
                f"{result.kills} kill(s), gen {result.generations})",
                err=True)

    report = run_soak(
        scenarios, seed,
        n_workers=workers if workers is not None else cs.workers,
        n_loops=parallel if parallel is not None else cs.parallel,
        iterations=(iterations if iterations is not None
                    else cs.iterations),
        shrink=not no_shrink, keep_going=keep_going,
        on_progress=progress, cfg=f.config)
    if as_json:
        click.echo(json.dumps(report, indent=2))
    else:
        click.echo(
            f"chaos: {report['passed']}/{report['scenarios']} scenario(s) "
            f"passed (seed {report['seed']}, {report['injected']} "
            f"injection(s), {report['kills']} kill/resume cycle(s), "
            f"{report['wall_s']}s)")
        for fail in report["failures"]:
            click.echo(f"FAILED scenario {fail['scenario']}:", err=True)
            for v in fail["violations"]:
                click.echo(f"  - {v}", err=True)
            click.echo(f"  repro: {fail['repro']}", err=True)
            if "minimal_plan" in fail:
                click.echo("  minimal schedule: "
                           + json.dumps(fail["minimal_plan"]["events"]),
                           err=True)
    if not report["ok"]:
        raise SystemExit(1)


@chaos_group.command("replay")
@click.option("--seed", "-s", type=int, default=None,
              help="Seed of the soak that found the failure.")
@click.option("--scenario", "-i", type=int, default=0,
              help="Scenario index within the soak (default 0).")
@click.option("--workers", "-w", type=int, default=None,
              help="Fleet shape of the soak that found the failure "
                   "(default: settings chaos.workers).")
@click.option("--parallel", "-p", type=int, default=None,
              help="Loops per scenario of that soak (default: settings "
                   "chaos.parallel).")
@click.option("--iterations", type=int, default=None,
              help="Iteration budget of that soak (default: settings "
                   "chaos.iterations).")
@click.option("--plan", "plan_file", type=click.Path(exists=True),
              default=None,
              help="Replay a saved plan JSON instead of (seed, scenario).")
@click.option("--json", "as_json", is_flag=True, help="Result as JSON.")
@pass_factory
def chaos_replay(f: Factory, seed, scenario, workers, parallel, iterations,
                 plan_file, as_json):
    """Deterministically re-execute one scenario.

    Either --seed/--scenario (regenerates the exact schedule the soak
    ran -- pass the soak's --workers/--parallel/--iterations too if it
    used a non-default fleet shape, as the schedule depends on it) or
    --plan FILE (a saved or hand-edited schedule).  Exit is non-zero
    when an invariant is violated.
    """
    from ..chaos.plan import FaultPlan, generate_plan
    from ..chaos.runner import run_plan

    cs = f.config.settings.chaos
    if plan_file is not None:
        plan = FaultPlan.load(plan_file)
    elif seed is not None:
        plan = generate_plan(
            seed, scenario,
            n_workers=workers if workers is not None else cs.workers,
            n_loops=parallel if parallel is not None else cs.parallel,
            iterations=(iterations if iterations is not None
                        else cs.iterations))
    else:
        raise click.UsageError("need --seed (with --scenario) or --plan")
    result = run_plan(plan, cfg=f.config)
    if as_json:
        click.echo(json.dumps({**result.to_doc(),
                               "plan": plan.to_doc()}, indent=2))
    else:
        click.echo(f"scenario ({plan.seed}, {plan.scenario}): "
                   + ("ok" if result.ok else "FAILED"))
        for v in result.violations:
            click.echo(f"  - {v}", err=True)
    if not result.ok:
        raise SystemExit(1)


@chaos_group.command("plan")
@click.option("--seed", "-s", type=int, required=True,
              help="Soak seed to generate from.")
@click.option("--scenario", "-i", type=int, default=0,
              help="Scenario index (default 0).")
@click.option("--workers", "-w", type=int, default=None,
              help="Fleet shape the soak used (default: settings "
                   "chaos.workers; the schedule depends on it).")
@click.option("--parallel", "-p", type=int, default=None,
              help="Loops per scenario (default: settings chaos.parallel).")
@click.option("--iterations", type=int, default=None,
              help="Iteration budget (default: settings chaos.iterations).")
@click.option("--out", "out_file", type=click.Path(), default=None,
              help="Write the plan JSON here instead of stdout "
                   "(editable; replay with --plan).")
@pass_factory
def chaos_plan(f: Factory, seed, scenario, workers, parallel, iterations,
               out_file):
    """Print (or save) the fault schedule for one (seed, scenario).

    The schedule is exactly what ``chaos run``/``chaos replay`` would
    execute under the same fleet shape -- save it, edit the events, and
    replay the edited plan to bisect a failure by hand.
    """
    from ..chaos.plan import generate_plan

    cs = f.config.settings.chaos
    plan = generate_plan(
        seed, scenario,
        n_workers=workers if workers is not None else cs.workers,
        n_loops=parallel if parallel is not None else cs.parallel,
        iterations=(iterations if iterations is not None
                    else cs.iterations))
    if out_file:
        path = plan.save(out_file)
        click.echo(f"wrote {path}")
    else:
        click.echo(plan.to_json(), nl=False)


def register(cli: click.Group) -> None:
    cli.add_command(chaos_group)
