"""monitor verbs: init/up/down/status + egress log tail.

Parity reference: internal/cmd/monitor (init/up/down/status/reload,
SURVEY.md 2.4); `up` drives docker compose over the rendered stack.
"""

from __future__ import annotations

import json
from pathlib import Path

import click

from ..monitor.stack import LOG_INDICES, MonitorStack
from .factory import Factory

pass_factory = click.make_pass_decorator(Factory)


@click.group("monitor")
def monitor_group():
    """Manage the observability stack (OTel, OpenSearch, Prometheus)."""


@monitor_group.command("init")
@pass_factory
def monitor_init(f: Factory):
    """Render the compose stack + configs without starting anything."""
    path = MonitorStack(f.config).render()
    click.echo(f"rendered monitor stack under {path}")
    click.echo("indices: " + ", ".join(LOG_INDICES))


@monitor_group.command("up")
@pass_factory
def monitor_up(f: Factory):
    MonitorStack(f.config).up()
    s = f.config.settings.monitoring
    click.echo(f"monitor stack up: dashboards http://localhost:{s.dashboards_port} "
               f"prometheus http://localhost:{s.prometheus_port}")


@monitor_group.command("down")
@pass_factory
def monitor_down(f: Factory):
    MonitorStack(f.config).down()
    click.echo("monitor stack down")


@monitor_group.command("status")
@click.option("--format", "fmt", type=click.Choice(["table", "json"]), default="table")
@pass_factory
def monitor_status(f: Factory, fmt):
    rows = MonitorStack(f.config).status()
    if fmt == "json":
        click.echo(json.dumps(rows, indent=2))
        return
    if not rows:
        click.echo("monitor stack: not running")
        raise SystemExit(1)
    for r in rows:
        click.echo(f"{r.get('Service', r.get('Name', '?'))}\t{r.get('State', '?')}")


@monitor_group.command("units")
@pass_factory
def monitor_units(f: Factory):
    """List monitoring units: discovered (floor + loose) and seeded.

    Reference: `clawker monitor extensions` over the units ledger
    (internal/monitor/ledger.go)."""
    from ..monitor.ledger import Ledger
    from ..monitor.unit import discover_units

    stack = MonitorStack(f.config)
    units = discover_units(stack.unit_roots())
    ledger = Ledger(stack.dir)
    for name, unit in sorted(units.items()):
        seeded = ledger.units.get(name)
        state = "seeded" if seeded and seeded.content_hash == unit.content_hash() \
            else ("stale" if seeded else "unseeded")
        lanes = ",".join(l.index for l in unit.manifest.logs)
        click.echo(f"{name}\t{state}\t{lanes}\t{unit.manifest.description}")
    if not units:
        click.echo("no monitoring units discovered")


@monitor_group.command("egress")
@click.option("--tail", type=int, default=20, help="Last N egress decisions.")
@click.option("--deny-only", is_flag=True, help="Only DENY verdicts.")
@pass_factory
def monitor_egress(f: Factory, tail, deny_only):
    """Show recent kernel egress decisions (netlogger output)."""
    path = f.config.logs_dir / "ebpf-egress.jsonl"
    if not path.exists():
        click.echo("no egress log yet (is the control plane running with "
                   "the firewall enabled?)", err=True)
        raise SystemExit(1)
    records = []
    for line in path.read_text().splitlines():
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if deny_only and rec.get("verdict") != "DENY":
            continue
        records.append(rec)
    for rec in records[-tail:]:  # the NEWEST N matching decisions
        click.echo(f"{rec.get('@timestamp','')}\t{rec.get('verdict','')}\t"
                   f"{rec.get('container') or rec.get('cgroup_id')}\t"
                   f"{rec.get('dst_ip')}:{rec.get('dst_port')}\t"
                   f"{rec.get('zone') or '-'}\t{rec.get('reason','')}")


@monitor_group.command("anomalies")
@click.option("--input", "input_path", type=click.Path(),
              default=None, help="Egress jsonl (default: logs dir stream).")
@click.option("--window", type=click.IntRange(min=1), default=60,
              help="Window seconds.")
@click.option("--train-steps", type=click.IntRange(min=1), default=120,
              help="Autoencoder fit steps before scoring.")
@click.option("--top", type=int, default=0, help="Only the N hottest agents.")
@click.option("--threshold", type=float, default=None,
              help="Exit 2 when any agent's latest z-score crosses this.")
@click.option("--format", "fmt", type=click.Choice(["table", "json"]),
              default="table")
@pass_factory
def monitor_anomalies(f: Factory, input_path, window, train_steps, top,
                      threshold, fmt):
    """Score per-agent egress behavior on the accelerator.

    Folds the netlogger stream into per-agent windows (32-feature
    vectors), fits the fleet autoencoder (clawker_tpu/analytics) on
    them, and reports reconstruction-error z-scores: the fleet's own
    behavior is the normal profile, agents that deviate surface first.
    """
    try:
        from ..analytics import runtime as art
    except ImportError:
        click.echo("anomalies: analytics runtime unavailable on this host "
                   "(numpy missing)", err=True)
        raise SystemExit(1)
    if not art.jax_available():
        click.echo("anomalies: jax unavailable on this host -- the scoring "
                   "lane needs an accelerator runtime (cpu works)", err=True)
        raise SystemExit(1)
    path = (Path(input_path) if input_path
            else f.config.logs_dir / "ebpf-egress.jsonl")
    rep = art.score_file(path, window_s=window, train_steps=train_steps)
    if rep is None:
        click.echo(f"anomalies: no scorable egress windows in {path}",
                   err=True)
        raise SystemExit(1)

    thr = threshold if threshold is not None else art.ANOMALY_Z
    agents = sorted(rep.agents, key=lambda a: -a.latest)
    if top:
        agents = agents[:top]
    hot = [a for a in rep.agents if a.latest >= thr]
    if fmt == "json":
        click.echo(json.dumps({
            "windows": len(rep.keys), "device": rep.device,
            "train_ms": round(rep.train_ms, 2),
            "score_ms": round(rep.score_ms, 2),
            "train_steps": rep.train_steps,
            "threshold": thr,
            "agents": [{
                "agent": a.agent, "windows": a.windows,
                "latest_z": round(a.latest, 3), "peak_z": round(a.peak, 3),
                "latest_window": a.latest_start,
                "anomalous": a.latest >= thr,
            } for a in agents],
        }))
    else:
        click.echo(f"{'AGENT':<28} {'WINDOWS':>7} {'LATEST-Z':>9} "
                   f"{'PEAK-Z':>8}  FLAG")
        for a in agents:
            flag = "ANOMALOUS" if a.latest >= thr else ""
            click.echo(f"{a.agent:<28.28} {a.windows:>7} {a.latest:>9.2f} "
                       f"{a.peak:>8.2f}  {flag}")
        click.echo(f"\n{len(rep.keys)} windows scored on {rep.device} "
                   f"(fit {rep.train_steps} steps {rep.train_ms:.0f} ms, "
                   f"score {rep.score_ms:.1f} ms)")
    if threshold is not None and hot:
        raise SystemExit(2)


def register(cli: click.Group) -> None:
    cli.add_command(monitor_group)
