"""monitor verbs: init/up/down/status + egress log tail.

Parity reference: internal/cmd/monitor (init/up/down/status/reload,
SURVEY.md 2.4); `up` drives docker compose over the rendered stack.
"""

from __future__ import annotations

import json

import click

from ..monitor.stack import LOG_INDICES, MonitorStack
from .factory import Factory

pass_factory = click.make_pass_decorator(Factory)


@click.group("monitor")
def monitor_group():
    """Manage the observability stack (OTel, OpenSearch, Prometheus)."""


@monitor_group.command("init")
@pass_factory
def monitor_init(f: Factory):
    """Render the compose stack + configs without starting anything."""
    path = MonitorStack(f.config).render()
    click.echo(f"rendered monitor stack under {path}")
    click.echo("indices: " + ", ".join(LOG_INDICES))


@monitor_group.command("up")
@pass_factory
def monitor_up(f: Factory):
    MonitorStack(f.config).up()
    s = f.config.settings.monitoring
    click.echo(f"monitor stack up: dashboards http://localhost:{s.dashboards_port} "
               f"prometheus http://localhost:{s.prometheus_port}")


@monitor_group.command("down")
@pass_factory
def monitor_down(f: Factory):
    MonitorStack(f.config).down()
    click.echo("monitor stack down")


@monitor_group.command("status")
@click.option("--format", "fmt", type=click.Choice(["table", "json"]), default="table")
@pass_factory
def monitor_status(f: Factory, fmt):
    rows = MonitorStack(f.config).status()
    if fmt == "json":
        click.echo(json.dumps(rows, indent=2))
        return
    if not rows:
        click.echo("monitor stack: not running")
        raise SystemExit(1)
    for r in rows:
        click.echo(f"{r.get('Service', r.get('Name', '?'))}\t{r.get('State', '?')}")


@monitor_group.command("units")
@pass_factory
def monitor_units(f: Factory):
    """List monitoring units: discovered (floor + loose) and seeded.

    Reference: `clawker monitor extensions` over the units ledger
    (internal/monitor/ledger.go)."""
    from ..monitor.ledger import Ledger
    from ..monitor.unit import discover_units

    stack = MonitorStack(f.config)
    units = discover_units(stack.unit_roots())
    ledger = Ledger(stack.dir)
    for name, unit in sorted(units.items()):
        seeded = ledger.units.get(name)
        state = "seeded" if seeded and seeded.content_hash == unit.content_hash() \
            else ("stale" if seeded else "unseeded")
        lanes = ",".join(l.index for l in unit.manifest.logs)
        click.echo(f"{name}\t{state}\t{lanes}\t{unit.manifest.description}")
    if not units:
        click.echo("no monitoring units discovered")


@monitor_group.command("egress")
@click.option("--tail", type=int, default=20, help="Last N egress decisions.")
@click.option("--deny-only", is_flag=True, help="Only DENY verdicts.")
@pass_factory
def monitor_egress(f: Factory, tail, deny_only):
    """Show recent kernel egress decisions (netlogger output)."""
    path = f.config.logs_dir / "ebpf-egress.jsonl"
    if not path.exists():
        click.echo("no egress log yet (is the control plane running with "
                   "the firewall enabled?)", err=True)
        raise SystemExit(1)
    records = []
    for line in path.read_text().splitlines():
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if deny_only and rec.get("verdict") != "DENY":
            continue
        records.append(rec)
    for rec in records[-tail:]:  # the NEWEST N matching decisions
        click.echo(f"{rec.get('@timestamp','')}\t{rec.get('verdict','')}\t"
                   f"{rec.get('container') or rec.get('cgroup_id')}\t"
                   f"{rec.get('dst_ip')}:{rec.get('dst_port')}\t"
                   f"{rec.get('zone') or '-'}\t{rec.get('reason','')}")


def register(cli: click.Group) -> None:
    cli.add_command(monitor_group)
