"""fleet verbs: worker inventory, provisioning, health across a TPU pod.

Net-new command group (the reference is single-host); the operational
surface of SURVEY.md 7 step 7 -- everything here works over the SSH
transport + scripted-runner seam, so `--dry-run` shows exactly what will
run before anything touches a worker.
"""

from __future__ import annotations

import json
import threading
from pathlib import Path

import click

from .. import consts
from .factory import Factory

pass_factory = click.make_pass_decorator(Factory)


def _transports(f: Factory):
    from ..fleet.transport import SSHTransport

    tpu = f.config.settings.runtime.tpu
    from ..fleet.inventory import discover_workers

    hosts = discover_workers(tpu)
    if not hosts:
        raise click.ClickException(
            "no TPU workers configured (runtime.tpu.workers / runtime.tpu.pod)"
        )
    mux = f.config.ssh_mux_dir
    return [SSHTransport(tpu, h, i, mux_dir=mux) for i, h in enumerate(hosts)]


@click.group("fleet")
def fleet_group():
    """Manage TPU-pod worker VMs (tpu_vm driver substrate)."""


@fleet_group.command("workers")
@pass_factory
def fleet_workers(f: Factory):
    """List the pod's worker hosts in pod order."""
    from ..fleet.inventory import discover_workers

    hosts = discover_workers(f.config.settings.runtime.tpu)
    for i, h in enumerate(hosts):
        click.echo(f"{i}\t{h}")
    if not hosts:
        raise SystemExit(1)


@fleet_group.command("provision")
@click.option("--dry-run", is_flag=True, help="Print the plan, touch nothing.")
@click.option("--no-firewall", is_flag=True, help="Skip the eBPF/kernel half.")
@click.option("--no-cp", is_flag=True, help="Skip the per-worker control plane.")
@click.option("--worker", "only", type=int, default=-1,
              help="Provision a single worker index.")
@click.option("--jobs", "-j", type=int, default=8,
              help="Concurrent worker provisions (bounded pool).")
@pass_factory
def fleet_provision(f: Factory, dry_run, no_firewall, no_cp, only, jobs):
    """Install the worker stack (native bits, eBPF, control plane).

    Workers provision concurrently (one payload tar shared by all);
    step results stream as they land, prefixed with the worker index.
    """
    from ..fleet.provision import build_plan, provision_fleet

    plan = build_plan(with_firewall=not no_firewall, with_cp=not no_cp)
    if dry_run:
        for step in plan:
            opt = " (optional)" if step.optional else ""
            click.echo(f"{step.name}{opt}\n    {step.cmd}")
        return
    repo_root = Path(__file__).resolve().parents[2]
    transports = _transports(f)
    if only >= 0:
        chosen = [t for t in transports if t.index == only]
        if not chosen:
            valid = ", ".join(str(t.index) for t in transports)
            raise click.ClickException(
                f"--worker {only}: no such worker index (valid: {valid})")
        transports = chosen

    echo_lock = threading.Lock()   # step lines land from worker threads

    def on_step(index, r):
        mark = "+" if r.ok else "!"
        with echo_lock:
            click.echo(f"worker {index}: {mark} {r.name}"
                       + (f": {r.detail}" if r.detail else ""))

    def on_report(report):
        """Per-worker summary the moment THAT worker finishes -- slow
        workers must not gate the fast workers' verdicts (the streaming
        behavior docs/loop-parallel.md promises)."""
        if report.ok:
            line = f"worker {report.index} ({report.host}): ok"
        else:
            # the streamed '!' line may be interleaved far above: the
            # summary must carry the failure on its own
            bad = next((r for r in report.results if not r.ok), None)
            why = ""
            if bad is not None:
                why = f" at {bad.name}" + (f": {bad.detail}" if bad.detail else "")
            line = f"worker {report.index} ({report.host}): FAILED{why}"
        with echo_lock:
            click.echo(line)

    reports = provision_fleet(
        transports, repo_root,
        with_firewall=not no_firewall, with_cp=not no_cp,
        monitor=f.config.settings.monitoring.enable,
        max_workers=max(1, jobs), on_step=on_step, on_report=on_report)
    if any(not r.ok for r in reports):
        raise SystemExit(1)


def _loopd_status(f: Factory, no_daemon: bool) -> dict | None:
    """One status RPC to a discovered loopd, or None (degrade to the
    CLI-side probe path).  The daemon already probes the fleet
    continuously -- fleet views should read ITS breakers instead of
    spinning up their own probe rounds (docs/loopd.md)."""
    if no_daemon:
        return None
    from ..loopd.client import discover

    # project-scoped like the loop submit path: the socket lives under
    # the GLOBAL state dir, and rendering another project's daemon
    # state here (or gating CI exit codes on its breakers) would lie
    try:
        project = f.config.project_name()
    except LookupError:
        project = None
    client = discover(f.config, require_project=project)
    if client is None:
        return None
    try:
        doc = client.status()
    except Exception as e:      # noqa: BLE001 -- view must degrade
        click.echo(f"loopd status failed ({e}); probing directly",
                   err=True)
        return None
    finally:
        client.close()
    return doc


_HEALTH_COLUMNS = ("WORKER", "STATE", "BRK", "WORKERD", "STORAGE", "P50MS",
                   "P95MS", "PROBES", "FAILS", "ORPHANED", "MIG-OUT",
                   "MIG-IN", "LAST-ERROR")


def _storage_verdict(doc: dict | None) -> str:
    """Compact STORAGE cell from a loopd status doc: the worst WAL
    durability across hosted runs (ok|degraded|failed,
    docs/durability.md) with the disk-pressure ladder level appended
    when the daemon is shedding (``/p1``) or GC-ing (``/p2``)."""
    if not doc:
        return "-"
    worst = "ok"
    for r in doc.get("runs") or []:
        d = (r.get("storage") or {}).get("durability")
        if d == "failed":
            worst = "failed"
            break
        if d == "degraded":
            worst = "degraded"
    stor = doc.get("storage") or {}
    wal = stor.get("capacity_wal") or {}
    if worst == "ok" and wal and not wal.get("healthy", True):
        worst = "degraded"
    level = int((stor.get("pressure") or {}).get("level") or 0)
    return f"{worst}/p{level}" if level else worst


def _health_rows(stats: list[dict], anom: dict | None = None,
                 workerd: dict | None = None,
                 storage: str = "-") -> list[str]:
    # BRK is the registry's health_breaker_state gauge (0=closed
    # 1=half_open 2=open) -- the same value a Prometheus scrape of
    # `clawker loop --metrics-port` serves (docs/telemetry.md).
    # WORKERD is the worker-resident launch daemon's liveness
    # (docs/workerd.md): `degraded` means the socket exists but nothing
    # answers -- that worker's data plane silently fell back to the WAN
    # path, visibly slower while every breaker still reads healthy.
    # ``anom`` (worker -> hottest sentinel z, from a loopd-hosted
    # sentinel) appends the live ANOM-Z column (docs/analytics-online.md)
    cols = _HEALTH_COLUMNS + (("ANOM-Z",) if anom is not None else ())
    lines = ["\t".join(cols)]
    for s in stats:
        row = [str(x) for x in (
            s["worker"], s["state"], s["breaker_state_gauge"],
            (workerd or {}).get(s["worker"], "absent"), storage,
            s["probe_p50_ms"], s["probe_p95_ms"],
            s["probes"], s["probe_failures"], s["orphaned"],
            s["migrations_out"], s["migrations_in"],
            (s["last_error"] or "-")[:60])]
        if anom is not None:
            z = anom.get(s["worker"])
            row.append("-" if z is None else f"{z:.2f}")
        lines.append("\t".join(row))
    return lines


def _sentinel_anom_by_worker(doc: dict | None) -> dict | None:
    """worker -> hottest latest z from a loopd status doc's sentinel
    rows; None when the daemon hosts no sentinel."""
    rows = ((doc or {}).get("sentinel") or {}).get("rows")
    if not rows:
        return None
    out: dict = {}
    for r in rows:
        wid = r.get("worker") or ""
        z = float(r.get("latest_z", 0.0))
        if wid and (wid not in out or z > out[wid]):
            out[wid] = z
    return out


@fleet_group.command("health")
@click.option("--probes", type=int, default=3,
              help="Probe rounds before the one-shot verdict.")
@click.option("--watch", is_flag=True,
              help="Keep probing and re-print the table every interval.")
@click.option("--interval", type=float, default=2.0,
              help="Probe/refresh interval seconds (with --watch).")
@click.option("--format", "fmt", type=click.Choice(["table", "json"]),
              default="table")
@click.option("--no-daemon", is_flag=True,
              help="Probe directly even when a loopd daemon is running.")
@pass_factory
def fleet_health(f: Factory, probes, watch, interval, fmt, no_daemon):
    """Per-worker breaker state, probe latency, and failover counters.

    With a loopd daemon running (docs/loopd.md) this renders the
    daemon's LIVE breakers over its status RPC -- the breakers actual
    placements use -- instead of a fresh CLI-side probe round.
    Otherwise probes every worker of the active runtime driver through
    the same probe hook and circuit breakers `clawker loop --failover`
    uses (docs/fleet-health.md).  One-shot by default: exits non-zero
    when any worker's breaker is not closed.
    """
    import json as _json
    import time as _time

    from ..health import BreakerConfig, HealthConfig, HealthMonitor

    from ..workerd import liveness as workerd_liveness

    if not watch:
        doc = _loopd_status(f, no_daemon)
        if doc is not None:
            stats = doc.get("health", [])
            anom = _sentinel_anom_by_worker(doc)
            wd = doc.get("workerd") or {}
            storage = _storage_verdict(doc)
            if fmt == "json":
                out = {"source": f"loopd:{doc.get('pid')}", "health": stats,
                       "workerd": wd,
                       "storage": {"verdict": storage,
                                   **(doc.get("storage") or {})}}
                if doc.get("sentinel"):
                    out["sentinel"] = doc["sentinel"]
                click.echo(_json.dumps(out, indent=2))
            else:
                click.echo(f"source: loopd (pid {doc.get('pid')}, "
                           f"{len(doc.get('runs', []))} hosted run(s))",
                           err=True)
                for line in _health_rows(stats, anom, wd, storage):
                    click.echo(line)
            if any(s["state"] != "closed" for s in stats):
                raise SystemExit(1)
            return

    # one-shot: the breaker must be able to open within the rounds the
    # user asked for, or `--probes 1` would report a dead fleet healthy
    threshold = (BreakerConfig.failure_threshold if watch
                 else max(1, min(BreakerConfig.failure_threshold, probes)))
    cfg = HealthConfig(probe_interval_s=max(0.1, interval),
                       probe_deadline_s=max(1.0, min(interval, 5.0)),
                       breaker=BreakerConfig(failure_threshold=threshold,
                                             backoff_base_s=max(0.5, interval)))
    mon = HealthMonitor(f.driver, config=cfg)

    def emit() -> list[dict]:
        # liveness probed per emit, not once: under --watch a workerd
        # dying mid-session must flip the column to `degraded`, which
        # is the whole reason the column exists
        wd = workerd_liveness(f.config, f.driver)
        stats = mon.stats()
        if fmt == "json":
            for s in stats:
                s["workerd"] = wd.get(s["worker"], "absent")
            click.echo(_json.dumps(stats, indent=2))
        else:
            for line in _health_rows(stats, None, wd):
                click.echo(line)
        return stats

    if watch:
        try:
            while True:
                mon.probe_all()
                emit()
                _time.sleep(max(0.1, interval))
        except KeyboardInterrupt:
            return
    for _ in range(max(1, probes)):
        mon.probe_all()
    stats = emit()
    if any(s["state"] != "closed" for s in stats):
        raise SystemExit(1)


_PLACEMENT_COLUMNS = ("WORKER", "STATE", "COORD", "GROUP", "P50MS",
                      "WEIGHT", "SLOTS", "TOKENS", "REJECTS")


@fleet_group.command("placement")
@click.option("--policy", type=click.Choice(["spread", "pack", "topology"]),
              default=None,
              help="Policy to preview (default: settings "
                   "loop.placement.policy).")
@click.option("--slots", type=int, default=0,
              help="Loop slots to plan in the preview (default: settings "
                   "loop.parallel).")
@click.option("--probes", type=int, default=1,
              help="Probe rounds before planning (latency weights and "
                   "breaker states come from these).")
@click.option("--metrics-url", default="",
              help="Scrape a running loop's --metrics-port endpoint "
                   "(e.g. http://127.0.0.1:9464/metrics) for live queue "
                   "depth, in-flight tokens, and rejection counts.")
@click.option("--format", "fmt", type=click.Choice(["table", "json"]),
              default="table")
@click.option("--no-daemon", is_flag=True,
              help="Probe directly even when a loopd daemon is running.")
@pass_factory
def fleet_placement(f: Factory, policy, slots, probes, metrics_url, fmt,
                    no_daemon):
    """Placement & admission view: per-worker tokens, shares, queue depth.

    With a loopd daemon running (docs/loopd.md) the breakers, probe
    latencies, token counts, and tenant queues come straight off the
    daemon's status RPC -- the LIVE admission state every concurrent
    run bills against -- instead of a fresh CLI-side probe round.
    When the daemon hosts an elastic-capacity controller
    (docs/elastic-capacity.md) the view adds its live state: the
    SLO-scaled token cap per worker, shed/queueing mode with the
    current retry_after_s, and per-tenant SLO headroom.
    Otherwise probes every worker of the active runtime driver (the
    same breakers `clawker loop` places against), derives the pod
    topology, and shows how the chosen policy would spread N loop
    slots -- plus the admission token/queue configuration and
    per-tenant fairness shares (docs/loop-placement.md).  With
    ``--metrics-url`` pointing at a live run's metrics port, the
    static view is joined by the run's actual queue depths and
    in-flight token counts.
    """
    import json as _json
    from collections import Counter

    from ..engine.drivers import Worker
    from ..fleet.inventory import pod_topology
    from ..health import BREAKER_CLOSED, BreakerConfig, HealthConfig, HealthMonitor
    from ..placement import PlacementContext, get_policy

    settings = f.config.settings
    pdef = settings.loop.placement
    policy_name = policy or pdef.policy
    n_slots = slots or settings.loop.parallel
    daemon_doc = _loopd_status(f, no_daemon) if not metrics_url else None
    if daemon_doc is not None:
        hstats = daemon_doc.get("health", [])
        astats = daemon_doc.get("admission", {})
        # plan preview over the DAEMON's breakers/latency: engine-less
        # stand-in workers are fine, policies only read ids/indices
        workers = [Worker(id=s["worker"], index=i, hostname=s["worker"])
                   for i, s in enumerate(hstats)]
        breaker = {s["worker"]: s["state"] for s in hstats}
        lat = {s["worker"]: s.get("probe_p50_ms", 0.0) / 1000.0
               for s in hstats}
        topo = pod_topology(settings.runtime.tpu, len(workers))
        ctx = PlacementContext(
            workers=workers,
            breaker_state=lambda wid: breaker.get(wid, BREAKER_CLOSED),
            latency_s=lambda wid: lat.get(wid, 0.0), topology=topo)
        eng = get_policy(policy_name)
        try:
            plan = Counter(w.id for w in eng.plan(ctx, n_slots))
        except Exception as e:      # noqa: BLE001 -- preview must render
            plan = Counter()
            click.echo(f"plan: {e}", err=True)
        aworkers = astats.get("workers", {})
        cap = astats.get("max_inflight_per_worker",
                         pdef.max_inflight_per_worker)
        rows = []
        for w in workers:
            coord = topo.coords.get(w.index) if topo.known else None
            aw = aworkers.get(w.id, {})
            rows.append({
                "worker": w.id,
                "state": breaker.get(w.id, "closed"),
                "coord": f"{coord[0]},{coord[1]}" if coord else "-",
                "group": topo.group_of(w.index) if topo.known else "-",
                "probe_p50_ms": round(lat.get(w.id, 0.0) * 1000, 2),
                "weight": round(ctx.weight(w.id), 2),
                "planned_slots": plan.get(w.id, 0),
                "tokens": f"{aw.get('inflight', 0)}"
                          f"/{aw.get('capacity', cap)}",
                "rejections": aw.get("rejected", 0),
            })
        cstats = daemon_doc.get("capacity") or {}
        if cstats.get("enabled"):
            # live adaptive state joins the static columns: the token
            # cap each worker's bucket was scaled to, and whether its
            # queue is shedding (docs/elastic-capacity.md)
            for r in rows:
                cw = (cstats.get("workers") or {}).get(r["worker"]) or {}
                r["scaled_cap"] = cw.get("token_cap", 0)
                r["shed_retry_after_s"] = cw.get("shed_retry_after_s", 0.0)
        doc = {
            "source": f"loopd:{daemon_doc.get('pid')}",
            "policy": policy_name,
            "slots": n_slots,
            "topology": ({"rows": topo.rows, "cols": topo.cols}
                         if topo.known else None),
            "admission": {
                "max_inflight_per_worker": cap,
                "max_pending_per_worker": astats.get(
                    "max_pending_per_worker", pdef.max_pending_per_worker),
            },
            "tenants": {
                t: {"weight": s["weight"], "queue_depth": s["queued"],
                    "inflight": s["inflight"],
                    "dispatched": s["dispatched"]}
                for t, s in astats.get("tenants", {}).items()},
            "workers": rows,
        }
        if cstats.get("enabled"):
            doc["capacity"] = cstats
        # per-run git firewall summary (docs/git-policy.md): which runs
        # have a gitguard up, the egress rule set it installed (the
        # ssh/git lane pins + guarded https hosts), and its decision
        # tallies -- the placement view doubles as the "is the only git
        # path the guarded one" check
        grows = [{"run": r.get("run"), **(r.get("gitguard") or {})}
                 for r in daemon_doc.get("runs", [])
                 if (r.get("gitguard") or {}).get("enabled")]
        if grows:
            doc["gitguard"] = grows
        if fmt == "table":
            click.echo(f"source: loopd (pid {daemon_doc.get('pid')}, "
                       f"{len(daemon_doc.get('runs', []))} hosted "
                       "run(s))", err=True)
        _render_placement(doc, topo, fmt)
        return
    # same clamp as fleet health: the breaker must be able to open
    # within the probe rounds requested, or --probes 1 would preview a
    # dead fleet as healthy (and plan slots onto it)
    threshold = max(1, min(BreakerConfig.failure_threshold, probes))
    mon = HealthMonitor(f.driver, config=HealthConfig(
        breaker=BreakerConfig(failure_threshold=threshold)))
    for _ in range(max(1, probes)):
        mon.probe_all()
    workers = mon.workers
    topo = pod_topology(settings.runtime.tpu, len(workers))
    ctx = PlacementContext(
        workers=workers, breaker_state=mon.state,
        latency_s=mon.latency_p50_s, topology=topo)
    eng = get_policy(policy_name)
    try:
        plan = Counter(w.id for w in eng.plan(ctx, n_slots))
    except Exception as e:      # noqa: BLE001 -- preview must still render
        plan = Counter()
        click.echo(f"plan: {e}", err=True)
    live = _scrape_placement_metrics(metrics_url) if metrics_url else {}
    rows = []
    for w in workers:
        coord = topo.coords.get(w.index) if topo.known else None
        rows.append({
            "worker": w.id,
            "state": mon.state(w.id),
            "coord": f"{coord[0]},{coord[1]}" if coord else "-",
            "group": topo.group_of(w.index) if topo.known else "-",
            "probe_p50_ms": round(mon.latency_p50_s(w.id) * 1000, 2),
            "weight": round(ctx.weight(w.id), 2),
            "planned_slots": plan.get(w.id, 0),
            "tokens": (f"{live['inflight'].get(w.id, 0)}"
                       f"/{pdef.max_inflight_per_worker}" if live
                       else f"-/{pdef.max_inflight_per_worker}"),
            "rejections": live.get("rejections", {}).get(w.id, 0)
            if live else 0,
        })
    doc = {
        "policy": policy_name,
        "slots": n_slots,
        "topology": ({"rows": topo.rows, "cols": topo.cols}
                     if topo.known else None),
        "admission": {
            "max_inflight_per_worker": pdef.max_inflight_per_worker,
            "max_pending_per_worker": pdef.max_pending_per_worker,
        },
        "tenants": ({t: {"queue_depth": d}
                     for t, d in live.get("queue_depth", {}).items()}
                    if live else
                    {pdef.tenant: {"weight": pdef.tenant_weight,
                                   "max_inflight": pdef.tenant_max_inflight}}),
        "workers": rows,
    }
    _render_placement(doc, topo, fmt)


def _render_placement(doc: dict, topo, fmt: str) -> None:
    """Shared render + exit contract for both placement sources (CLI
    probe round and loopd status RPC): exits non-zero when any worker's
    breaker is not closed, in both formats, so CI gates identically."""
    import json as _json

    rows = doc["workers"]
    adm = doc["admission"]
    unhealthy = any(r["state"] != "closed" for r in rows)
    if fmt == "json":
        click.echo(_json.dumps(doc, indent=2))
        if unhealthy:
            raise SystemExit(1)
        return
    click.echo(f"policy={doc['policy']} slots={doc['slots']} "
               f"topology={'%dx%d' % (topo.rows, topo.cols) if topo.known else 'unknown (spread fallback)'} "
               f"admission={adm['max_inflight_per_worker']} in-flight / "
               f"{adm['max_pending_per_worker']} pending per worker")
    lines = ["\t".join(_PLACEMENT_COLUMNS)]
    for r in rows:
        lines.append("\t".join(str(x) for x in (
            r["worker"], r["state"], r["coord"], r["group"],
            r["probe_p50_ms"], r["weight"], r["planned_slots"],
            r["tokens"], r["rejections"])))
    for line in lines:
        click.echo(line)
    for t, info in doc["tenants"].items():
        pairs = " ".join(f"{k}={v}" for k, v in info.items())
        click.echo(f"tenant {t}: {pairs}")
    cstats = doc.get("capacity")
    if cstats:
        # the elastic controller's live view (docs/elastic-capacity.md):
        # scaled token caps, shed state, and per-tenant SLO headroom
        click.echo(f"capacity: slo={cstats.get('slo_s') or 'off'} "
                   f"ticks={cstats.get('ticks', 0)} "
                   f"autoscale={'on' if (cstats.get('autoscale') or {}).get('enabled') else 'off'}")
        for wid, cw in sorted((cstats.get("workers") or {}).items()):
            shed = cw.get("shed_retry_after_s", 0.0)
            click.echo(
                f"  {wid}\tcap={cw.get('token_cap') or '-'}\t"
                f"rate={cw.get('arrival_rate', 0.0)}/s\t"
                + (f"SHED retry_after={shed}s" if shed else "queueing"))
        for t, info in sorted((cstats.get("tenants") or {}).items()):
            click.echo(f"  slo {t}: {info.get('slo_s')}s "
                       f"headroom={info.get('headroom_s')}s")
    for g in doc.get("gitguard") or []:
        dec = g.get("decisions") or {}
        tallies = " ".join(f"{k}={v}" for k, v in sorted(dec.items()))
        click.echo(f"gitguard {g.get('run')}: "
                   f"{'up' if g.get('running') else 'DOWN (fail-closed)'}"
                   f" hosts={','.join(g.get('hosts') or []) or '-'}"
                   + (f" {tallies}" if tallies else ""))
        for key in g.get("rules") or []:
            click.echo(f"  rule {key}")
    if unhealthy:
        raise SystemExit(1)


def _scrape_placement_metrics(url: str) -> dict:
    """Pull placement_* gauges/counters off a live run's Prometheus
    endpoint; {} when unreachable (the static view still renders)."""
    from urllib import request as urlrequest

    try:
        with urlrequest.urlopen(url, timeout=3.0) as r:
            text = r.read().decode()
    except Exception as e:      # noqa: BLE001
        click.echo(f"metrics scrape failed: {e}", err=True)
        return {}
    out: dict = {"inflight": {}, "queue_depth": {}, "rejections": {}}
    wanted = {
        "placement_inflight_launches": ("inflight", "worker"),
        "placement_queue_depth": ("queue_depth", "tenant"),
        "admission_rejections_total": ("rejections", "worker"),
    }
    for line in text.splitlines():
        if line.startswith("#") or "{" not in line:
            continue
        name, _, rest = line.partition("{")
        key = wanted.get(name)
        if key is None:
            continue
        labels_raw, _, value = rest.partition("}")
        labels = dict(
            p.split("=", 1) for p in labels_raw.split(",") if "=" in p)
        label = labels.get(key[1], "").strip('"')
        try:
            out[key[0]][label] = int(float(value.strip()))
        except ValueError:
            continue
    return out


@fleet_group.command("warmpool")
@click.option("--metrics-url", default="",
              help="Scrape a running loop's --metrics-port endpoint for "
                   "live per-worker pool depth and hit/miss/refill "
                   "counters.")
@click.option("--run", "run_ref", default="",
              help="Replay a run journal (id, unambiguous prefix, or "
                   "path) and show its journaled pool membership.")
@click.option("--format", "fmt", type=click.Choice(["table", "json"]),
              default="table")
@click.option("--no-daemon", is_flag=True,
              help="Skip loopd discovery; settings/metrics/journal only.")
@pass_factory
def fleet_warmpool(f: Factory, metrics_url, run_ref, fmt, no_daemon):
    """Warm-pool view: settings, live depth/hit counters, membership.

    The warm pool keeps pre-created agent containers per worker that
    loop placements adopt instead of paying a full create
    (docs/loop-warmpool.md).  With a loopd daemon running
    (docs/loopd.md) this shows every hosted run's live pool state over
    the status RPC -- including the elastic controller's adaptive
    TARGET/ACTUAL depth and arrival rate per worker when capacity is
    enabled (docs/elastic-capacity.md); with ``--metrics-url``
    pointing at a live run's metrics port it shows the run's actual
    per-worker depth and hit/miss/refill counters; with ``--run`` it
    replays that run's journal and lists every pool member's journaled
    state (what a ``--resume`` would restore or sweep).
    """
    import json as _json

    wps = f.config.settings.loop.warm_pool
    doc: dict = {
        "settings": {
            "enable": wps.enable,
            "depth": wps.depth,
            "max_age_s": wps.max_age_s,
            "tenant_weight": wps.tenant_weight,
        },
    }
    if not metrics_url and not run_ref:
        daemon_doc = _loopd_status(f, no_daemon)
        if daemon_doc is not None:
            doc["source"] = f"loopd:{daemon_doc.get('pid')}"
            doc["daemon_pools"] = daemon_doc.get("warm_pools", {})
            cstats = daemon_doc.get("capacity") or {}
            if cstats.get("enabled"):
                doc["capacity"] = cstats
    if metrics_url:
        doc["live"] = _scrape_warmpool_metrics(metrics_url)
    if run_ref:
        from .cmd_loop import _resolve_journal
        from ..loop.journal import RunJournal, replay

        image = replay(RunJournal.read(_resolve_journal(f, run_ref)))
        doc["run"] = image.run_id
        doc["members"] = [
            {"agent": m.agent, "worker": m.worker, "cid": m.cid[:12],
             "state": m.state,
             **({"adopted_by": m.adopted_by} if m.adopted_by else {})}
            for m in image.pool.values()
        ]
    if fmt == "json":
        click.echo(_json.dumps(doc, indent=2))
        return
    s = doc["settings"]
    click.echo(f"warm-pool: enable={s['enable']} depth={s['depth']} "
               f"max_age_s={s['max_age_s']} "
               f"tenant_weight={s['tenant_weight']}")
    pools = doc.get("daemon_pools")
    if pools is not None:
        click.echo(f"source: {doc.get('source')}", err=True)
        if not pools:
            click.echo("no pooled runs hosted by loopd")
        for run_id, st in sorted(pools.items()):
            click.echo(f"run {run_id}: target_depth={st['target_depth']}"
                       + (" (adaptive)" if st.get("adaptive") else "")
                       + f" hits={st['hits']} misses={st['misses']} "
                       f"refills={st['refills']} recycled={st['recycled']}")
            # TARGET is the live (possibly capacity-adapted) per-worker
            # target; ACTUAL the adoptable depth right now
            for wid, w in sorted(st.get("workers", {}).items()):
                click.echo(f"  {wid}\ttarget={w.get('target', st['target_depth'])}\t"
                           f"ready={w['ready']}\t"
                           f"inflight={w['inflight']}")
    cstats = doc.get("capacity")
    if cstats:
        click.echo(f"capacity: slo={cstats.get('slo_s') or 'off'} "
                   f"ticks={cstats.get('ticks', 0)}")
        for wid, cw in sorted((cstats.get("workers") or {}).items()):
            click.echo(f"  {wid}\tTARGET={cw.get('pool_target', 0)}\t"
                       f"ACTUAL={cw.get('pool_ready', 0)}\t"
                       f"cap={cw.get('token_cap') or '-'}\t"
                       f"rate={cw.get('arrival_rate', 0.0)}/s")
    live = doc.get("live")
    if live is not None:
        click.echo("WORKER\tDEPTH\tHITS\tMISSES\tREFILLS\tRECYCLED")
        workers = sorted(set(live["depth"]) | set(live["hits"])
                         | set(live["misses"]) | set(live["refills"]))
        for w in workers:
            click.echo("\t".join(str(x) for x in (
                w, live["depth"].get(w, 0), live["hits"].get(w, 0),
                live["misses"].get(w, 0), live["refills"].get(w, 0),
                live["recycled"].get(w, 0))))
    for m in doc.get("members", []):
        by = f" by={m['adopted_by']}" if m.get("adopted_by") else ""
        click.echo(f"member {m['agent']}\t{m['worker']}\t{m['cid']}\t"
                   f"{m['state']}{by}")


def _scrape_warmpool_metrics(url: str) -> dict:
    """Pull warm_pool_* gauges/counters off a live run's Prometheus
    endpoint; zeroed tables when unreachable (settings still render)."""
    from urllib import request as urlrequest

    out: dict = {"depth": {}, "hits": {}, "misses": {}, "refills": {},
                 "recycled": {}}
    try:
        with urlrequest.urlopen(url, timeout=3.0) as r:
            text = r.read().decode()
    except Exception as e:      # noqa: BLE001
        click.echo(f"metrics scrape failed: {e}", err=True)
        return out
    wanted = {
        "warm_pool_depth": "depth",
        "warm_pool_hits_total": "hits",
        "warm_pool_misses_total": "misses",
        "warm_pool_refills_total": "refills",
        "warm_pool_recycled_total": "recycled",
    }
    for line in text.splitlines():
        if line.startswith("#") or "{" not in line:
            continue
        name, _, rest = line.partition("{")
        key = wanted.get(name)
        if key is None:
            continue
        labels_raw, _, value = rest.partition("}")
        labels = dict(
            p.split("=", 1) for p in labels_raw.split(",") if "=" in p)
        worker = labels.get("worker", "").strip('"')
        try:
            val = int(float(value.strip()))
        except ValueError:
            continue
        # recycled carries a reason label too: sum per worker
        out[key][worker] = out[key].get(worker, 0) + val
    return out


_ANOMALY_COLUMNS = ("AGENT", "WORKER", "WINDOWS", "LATEST-Z", "PEAK-Z",
                    "RECORDS", "FLAG")


@fleet_group.command("anomaly")
@click.option("--watch", is_flag=True,
              help="Keep scoring and re-print the table every interval.")
@click.option("--interval", type=float, default=None,
              help="Scoring tick seconds with --watch (default: settings "
                   "sentinel.interval_s).")
@click.option("--ticks", type=int, default=0,
              help="With --watch: stop after N ticks (0 = until Ctrl-C).")
@click.option("--window", type=int, default=None,
              help="Window seconds (default: settings sentinel.window_s).")
@click.option("--train-steps", type=int, default=None,
              help="Denoising fit steps per tick (default: settings "
                   "sentinel.train_steps).")
@click.option("--threshold", type=float, default=None,
              help="Worker-relative robust z past which an agent flags "
                   "(default: settings sentinel.threshold).")
@click.option("--stream", "streams", multiple=True, metavar="WORKER=PATH",
              help="Extra local stream source(s): tail PATH as WORKER's "
                   "egress jsonl (besides the fleet's own streams).")
@click.option("--format", "fmt", type=click.Choice(["table", "json"]),
              default="table")
@click.option("--no-daemon", is_flag=True,
              help="Score locally even when a loopd daemon hosts a "
                   "sentinel.")
@pass_factory
def fleet_anomaly(f: Factory, watch, interval, ticks, window, train_steps,
                  threshold, streams, fmt, no_daemon):
    """Live fleet-wide anomaly scores: every agent's fused egress +
    behavior windows scored as one sharded program per tick.

    One-shot by default: collect every worker's stream (local reads on
    local/fake, ``tail -F`` over the SSH mux for tpu_vm), score once,
    and exit non-zero (2) when any agent's window flags past the
    threshold.  ``--watch`` keeps ticking and re-prints live scores.
    With a loopd daemon hosting a sentinel (settings sentinel.enable,
    docs/loopd.md) the one-shot renders the daemon's LIVE rows instead
    of building a second scorer (docs/analytics-online.md).
    """
    import json as _json
    import time as _time

    ss = f.config.settings.sentinel
    if not watch:
        doc = _loopd_status(f, no_daemon)
        sent = (doc or {}).get("sentinel") if doc else None
        if sent and sent.get("enabled"):
            if fmt == "json":
                click.echo(_json.dumps(
                    {"source": f"loopd:{doc.get('pid')}", **sent}, indent=2))
            else:
                click.echo(f"source: loopd (pid {doc.get('pid')}, run "
                           f"{sent.get('run') or '-'}, "
                           f"{sent.get('ticks', 0)} tick(s))", err=True)
                _render_anomaly_rows(sent.get("rows", []))
            if any(r.get("flagged") for r in sent.get("rows", [])):
                raise SystemExit(2)
            return

    try:
        from ..analytics import runtime as art
    except ImportError:
        raise click.ClickException(
            "fleet anomaly: analytics runtime unavailable on this host "
            "(numpy missing)")
    if not art.jax_available():
        raise click.ClickException(
            "fleet anomaly: jax unavailable on this host -- the scoring "
            "lane needs an accelerator runtime (cpu works)")
    from ..sentinel import FleetSentinel

    sentinel = FleetSentinel(
        f.config, f.driver,
        interval_s=(interval if interval is not None else ss.interval_s),
        window_s=window or ss.window_s,
        train_steps=train_steps or ss.train_steps,
        threshold=(threshold if threshold is not None else ss.threshold),
        baseline_window=ss.baseline_window)
    for kv in streams:
        wid, _, path = kv.partition("=")
        if not wid or not path:
            raise click.BadParameter(f"--stream {kv!r}: expected WORKER=PATH")
        sentinel.collector.add_local(wid, Path(path))

    def render() -> list[dict]:
        rows = sentinel.rows()
        if fmt == "json":
            click.echo(_json.dumps(sentinel.status_doc(), indent=2))
        else:
            _render_anomaly_rows(rows)
        return rows

    try:
        if watch:
            n = 0
            try:
                while True:
                    sentinel.refresh_once()
                    n += 1
                    rep = sentinel.last_tick
                    if fmt == "table":
                        click.echo(f"-- tick {n}: "
                                   f"{rep.windows if rep else 0} window(s)"
                                   + (f" on {rep.device}" if rep else ""),
                                   err=True)
                    rows = render()
                    if ticks and n >= ticks:
                        break
                    _time.sleep(max(0.05, sentinel.interval_s))
            except KeyboardInterrupt:
                rows = sentinel.rows()
        else:
            # remote (tpu_vm) tails replay worker history asynchronously
            # over the SSH mux: let the feed settle before the one
            # verdict tick, or a busy fleet reads as empty
            sentinel.collector.wait_quiescent(2.0)
            n = sentinel.refresh_once()
            if n == 0 and not sentinel.rows():
                click.echo("fleet anomaly: no scorable windows in any "
                           "worker stream", err=True)
                raise SystemExit(1)
            rows = render()
    finally:
        sentinel.stop()
    if any(r.get("flagged") for r in rows):
        raise SystemExit(2)


def _render_anomaly_rows(rows: list[dict]) -> None:
    click.echo("\t".join(_ANOMALY_COLUMNS))
    for r in rows:
        click.echo("\t".join(str(x) for x in (
            r["agent"], r["worker"] or "-", r["windows"],
            r["latest_z"], r["peak_z"], r.get("stream_records", 0),
            "ANOMALOUS" if r.get("flagged") else "-")))


@fleet_group.command("console")
@click.option("--fps", type=float, default=4.0,
              help="Repaint rate for the live view.")
@click.option("--once", is_flag=True,
              help="Render one plain frame and exit (scripts/CI).")
@click.option("--format", "fmt", type=click.Choice(["table", "json"]),
              default="table",
              help="json emits the console feed document -- the same "
                   "schema `clawker loopd status --format json` "
                   "carries under its `console` key.")
@click.option("--no-spans", is_flag=True,
              help="Skip the flight-recorder span waterfalls.")
@pass_factory
def fleet_console(f: Factory, fps, once, fmt, no_spans):
    """Live multi-run fleet console over the loopd status RPC.

    One pane of glass over every run the daemon hosts: per-loop status
    with sentinel ANOM-Z flags, per-worker breaker/admission-token/
    workerd rows, tenant queues, warm pools, ingest state, and span
    waterfalls tailed from each run's flight recorder.  Damage-tracked
    repainting with row virtualization past 64 agents keeps 256 agents
    across 4 hosted runs inside the repaint budget
    (docs/fleet-console.md).  Requires a running loopd
    (`clawker loopd start`); Ctrl-C exits the console, never a run.
    """
    import time as _time

    from ..errors import ClawkerError
    from ..loopd.client import discover_all
    from ..loopd.feed import console_feed, merge_feeds
    from ..ui.fleetconsole import FleetConsole

    try:
        project = f.config.project_name()
    except LookupError:
        project = None
    # every federated pod's daemon (single-pod fleets get exactly the
    # one canonical socket -- the pre-federation behavior)
    clients = discover_all(f.config, require_project=project)
    if not clients:
        click.echo("fleet console: no loopd daemon answering (start one "
                   "with `clawker loopd start`)", err=True)
        raise SystemExit(1)

    def feed_fn() -> dict:
        return merge_feeds([console_feed(c.status()) for c in clients])

    try:
        if fmt == "json":
            click.echo(json.dumps(feed_fn(), indent=2))
            return
        console = FleetConsole(
            f.streams, feed_fn,
            logs_dir=None if no_spans else f.config.logs_dir, fps=fps)
        if once or not f.streams.is_stdout_tty():
            click.echo(console.snapshot())
            return
        try:
            while True:
                console.render_once()
                _time.sleep(1.0 / max(0.5, fps))
        except KeyboardInterrupt:
            pass
    except (ClawkerError, OSError) as e:
        # OSError too: a daemon killed mid-poll surfaces as a raw
        # BrokenPipe from the socket send, not a wrapped protocol error
        raise click.ClickException(f"fleet console: loopd went away ({e})")
    finally:
        for c in clients:
            c.close()


@fleet_group.command("status")
@click.option("--format", "fmt", type=click.Choice(["table", "json"]), default="table")
@pass_factory
def fleet_status(f: Factory, fmt):
    """Per-worker daemon + control-plane health over SSH."""
    rows = []
    for t in _transports(f):
        docker = t.run("docker info --format '{{.ServerVersion}}'", timeout=20.0)
        cp = t.run(
            f"curl -fsS -m 3 http://127.0.0.1:{consts.CP_HEALTH_PORT}/healthz",
            timeout=20.0,
        )
        rows.append({
            "worker": t.index, "host": t.host,
            "docker": docker.out.strip() if docker.rc == 0 else "DOWN",
            "control_plane": "ok" if cp.rc == 0 else "DOWN",
        })
    if fmt == "json":
        click.echo(json.dumps(rows, indent=2))
        return
    for r in rows:
        click.echo(f"{r['worker']}\t{r['host']}\tdocker={r['docker']}\tcp={r['control_plane']}")


def register(cli: click.Group) -> None:
    cli.add_command(fleet_group)
