"""plugin verbs (alias: skill): manage agent skills across harnesses.

Parity reference: internal/cmd/plugin -- NewCmdPlugin (alias skill),
install/show/remove lanes (SURVEY.md 2.4 command groups).
"""

from __future__ import annotations

from pathlib import Path

import click

from .factory import Factory

pass_factory = click.make_pass_decorator(Factory)


@click.group("plugin")
def plugin_group():
    """Manage the agent-skills plugin across host harnesses."""


@plugin_group.command("install")
@click.option("--source", required=True, type=click.Path(exists=True),
              help="Plugin source directory (skills tree or bundle).")
@click.option("--harness", default="claude", show_default=True)
@pass_factory
def plugin_install(f: Factory, source, harness):
    """Copy the source's skills into the harness skills directory."""
    from ..plugin import install

    names = install(Path(source), harness=harness)
    for n in names:
        click.echo(f"installed {n}")


@plugin_group.command("remove")
@click.option("--source", required=True, type=click.Path(exists=True),
              help="Plugin source (enumerates which skills to delete).")
@click.option("--harness", default="claude", show_default=True)
@click.option("--yes", "-y", is_flag=True)
@pass_factory
def plugin_remove(f: Factory, source, harness, yes):
    """Remove exactly the skills the source provides."""
    from ..plugin import remove

    if not f.confirm_destructive(
            f"Remove this source's skills from the {harness} harness?",
            skip=yes):
        raise SystemExit(1)
    for n in remove(Path(source), harness=harness):
        click.echo(f"removed {n}")


@plugin_group.command("show")
@click.option("--harness", default="claude", show_default=True)
@pass_factory
def plugin_show(f: Factory, harness):
    """Print the manual install commands for a harness."""
    from ..plugin import show

    click.echo(show(harness))


@plugin_group.command("list")
@click.option("--harness", default="claude", show_default=True)
@pass_factory
def plugin_list(f: Factory, harness):
    """List skills currently installed for a harness."""
    from ..plugin import discover_skills, skills_dir

    root = skills_dir(harness)
    if not root.is_dir():
        click.echo(f"no skills directory at {root}")
        return
    for s in discover_skills(root):
        click.echo(f"{s.name}\t{s.description}")


def register(cli: click.Group) -> None:
    cli.add_command(plugin_group)
    # reference alias: `clawker skill` == `clawker plugin`
    cli.add_command(plugin_group, "skill")
