"""firewall verbs: the 13 AdminService RPCs from the command line.

Parity reference: internal/cmd/firewall (13 verbs -> AdminService,
SURVEY.md 2.4).  Every verb talks to the control-plane handler over the
admin API when a CP is running; init/enable/status fall back to an
in-process handler for CP-less local use (same fallback the run path's
lifecycle hooks apply).
"""

from __future__ import annotations

import json

import click

from .factory import Factory

pass_factory = click.make_pass_decorator(Factory)


def _call(f: Factory, method: str, payload: dict) -> dict:
    from ..firewall.lifecycle import call_firewall

    return call_firewall(f.config, f.driver, method, payload)


def _echo(res: dict) -> None:
    click.echo(json.dumps(res, indent=2, default=str))


@click.group("firewall")
def fw_group():
    """Manage the egress firewall (eBPF + DNS gate + Envoy)."""


@fw_group.command("init")
@pass_factory
def fw_init(f: Factory):
    """Bring up the data plane and re-enroll running containers."""
    _echo(_call(f, "FirewallInit", {}))


@fw_group.command("enable")
@click.argument("container")
@pass_factory
def fw_enable(f: Factory, container):
    """Enroll CONTAINER's cgroup for enforcement."""
    _echo(_call(f, "FirewallEnable", {"container_id": container}))


@fw_group.command("disable")
@click.argument("container")
@pass_factory
def fw_disable(f: Factory, container):
    _echo(_call(f, "FirewallDisable", {"container_id": container}))


@fw_group.command("bypass")
@click.argument("container")
@click.option("--duration", "duration_s", type=float, default=300.0,
              help="Seconds until the dead-man timer re-engages enforcement.")
@pass_factory
def fw_bypass(f: Factory, container, duration_s):
    """Temporarily allow all egress for CONTAINER (dead-man timed)."""
    _echo(_call(f, "FirewallBypass",
                {"container_id": container, "duration_s": duration_s}))


@fw_group.command("add-rule")
@click.argument("dst")
@click.option("--proto", default="https",
              type=click.Choice(["https", "http", "tcp", "udp", "ssh", "git"]),
              help="tcp is the generic opaque lane (explicit port required).")
@click.option("--port", type=int, default=0, help="0 = protocol default.")
@click.option("--path", "paths", multiple=True,
              help="HTTP path prefix (repeatable; forces MITM inspection).")
@click.option("--deny", is_flag=True,
              help="Domain-level deny (NXDOMAIN carve-out under a wildcard).")
@pass_factory
def fw_add_rule(f: Factory, dst, proto, port, paths, deny):
    """Allow egress to DST (domain or *.wildcard).

    Rules are validated at ingestion: a glob path or a bad action errors
    here, not at traffic time."""
    rule = {"dst": dst, "proto": proto, "port": port, "paths": list(paths),
            "action": "deny" if deny else "allow"}
    _echo(_call(f, "FirewallAddRules", {"rules": [rule]}))


@fw_group.command("remove-rule")
@click.argument("key")
@pass_factory
def fw_remove_rule(f: Factory, key):
    """Remove a dynamic rule by its dst:proto:port key."""
    _echo(_call(f, "FirewallRemoveRule", {"key": key}))


@fw_group.command("rules")
@pass_factory
def fw_rules(f: Factory):
    """List the effective rule set (base + dynamic)."""
    _echo(_call(f, "FirewallListRules", {}))


@fw_group.command("reload")
@pass_factory
def fw_reload(f: Factory):
    """Re-render Envoy/gate/kernel state from the effective rules."""
    _echo(_call(f, "FirewallReload", {}))


@fw_group.command("status")
@pass_factory
def fw_status(f: Factory):
    _echo(_call(f, "FirewallStatus", {}))


@fw_group.command("rotate-ca")
@click.confirmation_option(
    prompt="Rotating the CA invalidates every MITM cert and agent leaf; "
           "images must be rebuilt. Continue?")
@pass_factory
def fw_rotate_ca(f: Factory):
    _echo(_call(f, "FirewallRotateCA", {}))


@fw_group.command("sync-routes")
@pass_factory
def fw_sync_routes(f: Factory):
    """Force a kernel route-table resync."""
    _echo(_call(f, "FirewallSyncRoutes", {}))


@fw_group.command("resolve")
@click.argument("hostname")
@pass_factory
def fw_resolve(f: Factory, hostname):
    """Explain what the policy would do for HOSTNAME."""
    _echo(_call(f, "FirewallResolveHostname", {"hostname": hostname}))


@fw_group.command("remove")
@click.confirmation_option(prompt="Tear down the firewall (detach all, flush maps)?")
@pass_factory
def fw_remove(f: Factory):
    _echo(_call(f, "FirewallRemove", {}))


def register(cli: click.Group) -> None:
    cli.add_command(fw_group)
