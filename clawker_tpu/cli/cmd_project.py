"""Project + worktree verbs (reference: internal/cmd/project,
internal/cmd/worktree).  Registry/worktree domain logic lives in
clawker_tpu.project; these are thin command shims."""

from __future__ import annotations

import json

import click

from .factory import Factory

pass_factory = click.make_pass_decorator(Factory)


@click.group("project")
def project_group():
    """Manage registered projects."""


@project_group.command("register")
@pass_factory
def project_register(f: Factory):
    """Register the current project in the global registry."""
    from ..project.manager import ProjectManager

    pm = ProjectManager(f.config)
    rec = pm.register_current()
    click.echo(f"registered {rec.name} -> {rec.root}")


@project_group.command("list")
@click.option("--format", "fmt", type=click.Choice(["table", "json"]), default="table")
@pass_factory
def project_list(f: Factory, fmt):
    from ..project.manager import ProjectManager

    pm = ProjectManager(f.config)
    projects = pm.list_projects()
    if fmt == "json":
        click.echo(json.dumps([p.__dict__ for p in projects], indent=2, default=str))
        return
    for p in projects:
        click.echo(f"{p.name}\t{p.root}\t{len(p.worktrees)} worktrees")


@project_group.command("edit")
@click.option("--select", "select_mode", is_flag=True,
              help="Numbered-select editor instead of the full browser.")
@pass_factory
def project_edit(f: Factory, select_mode):
    """Interactively browse + edit project config fields (reference
    internal/config/storeui/project)."""
    from ..storeui import EditError
    from ..ui.fieldbrowser import edit_store

    store = f.config.project_store_ref
    if store is None:
        raise EditError("no project config found (run `clawker init` first)")
    n = edit_store(store, f.streams, select_mode=select_mode)
    click.echo(f"{n} field(s) changed")


@project_group.command("remove")
@click.argument("name")
@click.option("--yes", "-y", is_flag=True, help="Skip the confirmation prompt.")
@pass_factory
def project_remove(f: Factory, name, yes):
    from ..project.manager import ProjectManager

    if not f.confirm_destructive(
            f"Remove project {name!r} from the registry?", skip=yes):
        raise SystemExit(1)
    ProjectManager(f.config).remove(name)
    click.echo(name)


@click.group("worktree")
def worktree_group():
    """Manage git worktrees for parallel agents."""


@worktree_group.command("add")
@click.argument("name")
@click.option("--branch", default="", help="Branch name (default: clawker/<name>).")
@pass_factory
def worktree_add(f: Factory, name, branch):
    from ..project.manager import ProjectManager

    pm = ProjectManager(f.config)
    wt = pm.add_worktree(f.config.project_name(), name, branch=branch)
    click.echo(f"{wt.name}\t{wt.path}\t{wt.branch}")


@worktree_group.command("list")
@pass_factory
def worktree_list(f: Factory):
    from ..project.manager import ProjectManager

    pm = ProjectManager(f.config)
    for wt in pm.list_worktrees(f.config.project_name()):
        click.echo(f"{wt.name}\t{wt.path}\t{wt.branch}")


@worktree_group.command("remove")
@click.argument("name")
@click.option("--force", is_flag=True, help="Remove even with local changes.")
@pass_factory
def worktree_remove(f: Factory, name, force):
    from ..project.manager import ProjectManager

    if not f.confirm_destructive(f"Remove worktree {name!r}?", skip=force):
        raise SystemExit(1)
    pm = ProjectManager(f.config)
    pm.remove_worktree(f.config.project_name(), name, force=force)
    click.echo(name)


@worktree_group.command("prune")
@pass_factory
def worktree_prune(f: Factory):
    from ..project.manager import ProjectManager

    pm = ProjectManager(f.config)
    for name in pm.prune_worktrees(f.config.project_name()):
        click.echo(f"pruned {name}")


def register(root: click.Group) -> None:
    root.add_command(project_group)
    root.add_command(worktree_group)
