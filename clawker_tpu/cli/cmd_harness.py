"""harness/stack listing + CLI reference generation.

Parity reference: internal/cmd/{harness,stack} listing verbs and
cmd/gen-docs (cobra -> markdown, SURVEY.md 2.1/2.4).
"""

from __future__ import annotations

from pathlib import Path

import click

from ..bundle.resolver import Resolver
from .factory import Factory

pass_factory = click.make_pass_decorator(Factory)


@click.group("harness")
def harness_group():
    """Agent harness bundles (claude, codex, ...)."""


@harness_group.command("ls")
@pass_factory
def harness_ls(f: Factory):
    for h in Resolver(f.config).list("harness"):
        click.echo(f"{h.name}\t{getattr(h, 'description', '') or ''}")


@click.group("stack")
def stack_group():
    """Language stack bundles (python, go, node, ...)."""


@stack_group.command("ls")
@pass_factory
def stack_ls(f: Factory):
    for s in Resolver(f.config).list("stack"):
        click.echo(f"{s.name}\t{getattr(s, 'base_image', '') or ''}")


@click.command("gen-docs", hidden=True)
@click.option("--out", type=click.Path(), default="docs/cli-reference",
              help="Output directory for markdown files.")
def gen_docs(out):
    """Generate the CLI reference (one markdown file per command)."""
    from ..docs import generate_cli_reference
    from .root import cli as root_cli

    written = generate_cli_reference(root_cli, Path(out))
    click.echo(f"wrote {len(written)} pages under {out}")
    from ..docs import generate_json_schemas

    schemas = generate_json_schemas(Path(out).parent / "schemas")
    click.echo(f"wrote {len(schemas)} JSON schemas under "
               f"{Path(out).parent / 'schemas'}")


def register(cli: click.Group) -> None:
    cli.add_command(harness_group)
    cli.add_command(stack_group)
    cli.add_command(gen_docs)
