"""Factory DI: lazily wired dependencies handed to every command.

Parity reference: internal/cmd/factory/default.go:58 New -- ~14 lazy
closures; here, cached properties.  Commands never construct their own
engine/config; they ask the factory (internal/cmdutil Factory contract).
"""

from __future__ import annotations

import functools
import os
from pathlib import Path

from ..config import Config, load_config
from ..engine.api import Engine
from ..engine.drivers import RuntimeDriver, get_driver
from ..runtime.orchestrate import AgentRuntime

ENV_DRIVER = "CLAWKER_TPU_DRIVER"


class Factory:
    def __init__(
        self,
        *,
        cwd: Path | None = None,
        driver: RuntimeDriver | None = None,
        config: Config | None = None,
    ):
        self.cwd = cwd or Path.cwd()
        self._driver_override = driver
        self._config_override = config

    @functools.cached_property
    def streams(self):
        from ..ui import IOStreams

        return IOStreams()

    @functools.cached_property
    def prompter(self):
        from ..ui import Prompter

        return Prompter(self.streams)

    def confirm_destructive(self, message: str, *, skip: bool = False) -> bool:
        """Gate for destructive verbs (container rm, project remove, ...).

        ``skip`` (a --force/--yes flag) bypasses; non-interactive runs
        proceed (scripts must not hang on a prompt they cannot answer --
        reference prompter is TTY-only); an interactive decline aborts.
        Reference: internal/prompter confirm flows (SURVEY.md 2.4)."""
        if skip or not self.streams.can_prompt():
            return True
        return self.prompter.confirm(message, default=False)

    @functools.cached_property
    def config(self) -> Config:
        if self._config_override is not None:
            return self._config_override
        from ..util import phases

        with phases.phase("config_load"):
            return load_config(self.cwd)

    @functools.cached_property
    def driver(self) -> RuntimeDriver:
        if self._driver_override is not None:
            return self._driver_override
        return get_driver(self.config.settings, override=os.environ.get(ENV_DRIVER, ""))

    def engine(self) -> Engine:
        return self.driver.engine()

    @functools.cached_property
    def agent_registry(self):
        from ..controlplane.registry import Registry

        return Registry(self.config.data_dir / "agents.db")

    def runtime(self, engine: Engine | None = None) -> AgentRuntime:
        eng = engine or self.engine()

        # Lazy: lifecycle/query commands never pay hostproxy startup or
        # tunnel setup; only the create path resolves the callable.
        def channels():
            from ..fleet.channels import open_side_channels

            return open_side_channels(eng, self.config)

        # Deferred so lifecycle/query commands never pay the cryptography
        # import or open agents.db; only the create path invokes this.
        def bootstrap(container_id: str, project: str, agent: str) -> None:
            from ..controlplane.identity import make_bootstrapper

            make_bootstrapper(self.config, eng, self.agent_registry)(
                container_id, project, agent
            )

        return AgentRuntime(
            eng,
            self.config,
            pre_start=self._pre_start_hook(),
            post_start=self._post_start_hook(),
            bootstrap=bootstrap,
            channels=channels,
        )

    # Bootstrap hooks: wired to control-plane/firewall bring-up once those
    # subsystems are configured on (container_start.go:103/:297 parity).
    def _pre_start_hook(self):
        from ..controlplane.bootstrap import pre_start_services

        cfg = self.config
        driver = self.driver

        def hook(container_ref: str) -> None:
            pre_start_services(cfg, driver, container_ref)

        return hook

    def _post_start_hook(self):
        from ..controlplane.bootstrap import post_start_services

        cfg = self.config
        driver = self.driver

        def hook(container_ref: str) -> None:
            post_start_services(cfg, driver, container_ref)

        return hook
