"""Reflection-driven store field editor: ``settings edit`` / ``project edit``.

Walks the store's typed schema (dataclass tree) into a flat list of
dotted fields with current values and provenance, then drives an
interactive select -> edit -> save loop over the Prompter.  Writes are
provenance-routed through the Store (so they land in the layer that owns
the key -- or an explicitly chosen layer) and ride the comment-preserving
YAML editor.

Parity reference: internal/storeui + internal/config/storeui
(reflection-driven TUI editing of Store[T] fields with per-layer save
targeting, SURVEY.md 2.4) -- re-derived as a prompter flow instead of a
BubbleTea browser.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import get_args, get_origin, get_type_hints

from .errors import ClawkerError
from .storage import Store
from .ui.iostreams import IOStreams
from .ui.prompter import Prompter, PromptError


class EditError(ClawkerError):
    pass


@dataclass
class FieldSpec:
    path: str               # dotted
    type: type              # leaf python type (str/int/float/bool/list/dict)
    value: object
    provenance: str         # layer name(s) the value came from, "" = default


def _leaf_type(ft) -> type | None:
    """Editable leaf type, or None for nested dataclasses."""
    if dataclasses.is_dataclass(ft):
        return None
    origin = get_origin(ft)
    if origin is list:
        (elem,) = get_args(ft)
        return None if dataclasses.is_dataclass(elem) else list
    if origin is dict:
        return dict
    if ft in (str, int, float, bool):
        return ft
    return str


def field_specs(store: Store) -> list[FieldSpec]:
    """Flat editable fields from the store's typed view."""
    typed = store.typed()
    if typed is None or not dataclasses.is_dataclass(typed):
        raise EditError("store has no typed schema to edit")
    out: list[FieldSpec] = []

    def walk(obj, prefix: str) -> None:
        hints = get_type_hints(type(obj))
        for f in dataclasses.fields(obj):
            path = f"{prefix}{f.name}"
            val = getattr(obj, f.name)
            leaf = _leaf_type(hints[f.name])
            if leaf is None and dataclasses.is_dataclass(val):
                walk(val, path + ".")
                continue
            if leaf is None:
                continue  # list-of-dataclass (egress rules...): dedicated verbs
            prov = ",".join(store.provenance_of(path))
            out.append(FieldSpec(path=path, type=leaf, value=val,
                                 provenance=prov))

    walk(typed, "")
    return out


def coerce(spec: FieldSpec, raw: str):
    raw = raw.strip()
    if spec.type is bool:
        if raw.lower() in ("true", "yes", "y", "1", "on"):
            return True
        if raw.lower() in ("false", "no", "n", "0", "off"):
            return False
        raise EditError(f"{spec.path}: want true/false, got {raw!r}")
    if spec.type is int:
        try:
            return int(raw)
        except ValueError:
            raise EditError(f"{spec.path}: want an integer, got {raw!r}")
    if spec.type is float:
        try:
            return float(raw)
        except ValueError:
            raise EditError(f"{spec.path}: want a number, got {raw!r}")
    if spec.type is list:
        if raw in ("", "[]"):
            return []
        return [x.strip() for x in raw.split(",") if x.strip()]
    if spec.type is dict:
        if raw in ("", "{}"):
            return {}
        out = {}
        for pair in raw.split(","):
            if not pair.strip():
                continue
            if "=" not in pair:
                raise EditError(f"{spec.path}: want K=V[,K=V...], got {raw!r}")
            k, v = pair.split("=", 1)
            out[k.strip()] = v.strip()
        return out
    return raw


def _fmt(val) -> str:
    """Display form (field listing)."""
    if isinstance(val, list):
        return ",".join(map(str, val)) or "[]"
    if isinstance(val, dict):
        return ",".join(f"{k}={v}" for k, v in val.items()) or "{}"
    return repr(val)


def _raw(spec: FieldSpec) -> str:
    """Editable form: MUST round-trip through coerce back to the same
    value, so accepting the prompt default is a no-op (a repr default
    would write quote-wrapped strings into the store)."""
    v = spec.value
    if spec.type is bool:
        return "true" if v else "false"
    if spec.type is list:
        return ",".join(map(str, v)) if v else "[]"
    if spec.type is dict:
        return ",".join(f"{k}={val}" for k, val in v.items()) if v else "{}"
    return "" if v is None else str(v)


def run_editor(store: Store, streams: IOStreams, *,
               layer: str | None = None,
               prompter: Prompter | None = None) -> int:
    """Interactive loop; returns the number of fields changed."""
    prompter = prompter or Prompter(streams)
    if not streams.can_prompt():
        raise EditError(
            "interactive editor needs a TTY; use `set <path> <value>`")
    changed = 0
    while True:
        specs = field_specs(store)
        options = [
            f"{s.path} = {_fmt(s.value)}"
            + (f"  ({s.provenance})" if s.provenance else "")
            for s in specs
        ] + ["done"]
        try:
            idx = prompter.select("Edit which field?", options,
                                  default=len(options) - 1)
        except PromptError:
            break
        if idx >= len(specs):
            break
        spec = specs[idx]
        try:
            raw = prompter.string(
                f"{spec.path} ({spec.type.__name__})", default=_raw(spec))
            value = coerce(spec, raw)
        except (PromptError, EditError) as e:
            streams.eprintln(str(e))
            continue
        if value == spec.value:
            continue
        store.set(spec.path, value, layer=layer)
        changed += 1
        streams.eprintln(f"set {spec.path} = {_fmt(value)}")
    return changed
