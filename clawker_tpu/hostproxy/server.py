"""Host-proxy: the audited side channel between agents and the host.

Agents live behind default-deny egress, but three interactions
legitimately need the host (reference: internal/hostproxy server.go:38):

- ``POST /open/url``      -- open a URL in the HOST browser (login
  pages, docs); http/https only, never executed in the container.
- ``POST /oauth/listen`` + ``GET /oauth/poll`` -- OAuth device flows:
  the provider redirects the host browser to 127.0.0.1:<port>; a
  one-shot listener captures that callback and the container-side
  forwarder polls it back into the agent's flow (reference: dynamic
  per-port listeners server.go:507-644 + callback-forwarder binary).
- ``POST /git/credential`` -- fill git credentials from the HOST
  credential store (reference: git_credential.go), gated by the egress
  rule set: a host is only fillable if the firewall would let the
  container reach it (reference: egress_check.go).  Secrets flow
  container-ward only, one host at a time, and every fill is logged.

Binds 127.0.0.1 (host side) -- containers reach it via the
host-gateway extra_host mapping the runtime injects; the kernel
firewall's FLAG_HOSTPROXY allows exactly this ip:port and nothing else.
"""

from __future__ import annotations

import json
import secrets
import socket
import subprocess
import threading
import time
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from .. import consts, logsetup
from ..config import Config
from ..config.schema import EgressRule

log = logsetup.get("hostproxy.server")

OAUTH_SESSION_TTL_S = 600
OAUTH_SUCCESS_PAGE = (b"<html><body><h3>Authentication complete.</h3>"
                      b"You can return to your agent terminal.</body></html>")


def default_open_browser(url: str) -> bool:
    import webbrowser

    try:
        return webbrowser.open(url)
    except Exception:
        return False


def default_git_fill(request: str, timeout: float = 10.0) -> str:
    """Run the host's `git credential fill` (keychain/helpers apply)."""
    res = subprocess.run(["git", "credential", "fill"], input=request.encode(),
                         capture_output=True, timeout=timeout)
    if res.returncode != 0:
        return ""
    return res.stdout.decode(errors="replace")


@dataclass
class OAuthSession:
    id: str
    port: int
    created: float = field(default_factory=time.time)
    captured: dict | None = None
    server: ThreadingHTTPServer | None = None


def _host_allowed(host: str, rules: list[EgressRule]) -> bool:
    """Would the firewall let a container reach this host?  Same zone
    semantics as the DNS gate (wildcard admits apex + subdomains)."""
    h = host.strip().lower().rstrip(".")
    for r in rules:
        dst = r.dst.strip().lower()
        if dst.startswith("*."):
            apex = dst[2:]
            if h == apex or h.endswith("." + apex):
                return True
        elif h == dst:
            return True
    return False


class HostProxy:
    def __init__(
        self,
        cfg: Config,
        *,
        host: str = "127.0.0.1",
        port: int | None = None,
        open_browser=default_open_browser,
        git_fill=default_git_fill,
    ):
        self.cfg = cfg
        self.host = host
        self.port = cfg.settings.host_proxy.port if port is None else port
        self.open_browser = open_browser
        self.git_fill = git_fill
        self.bound_port = 0
        self.opened_urls: list[str] = []
        self._sessions: dict[str, OAuthSession] = {}
        self._lock = threading.Lock()
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    # ---------------------------------------------------------- lifecycle

    def start(self) -> None:
        proxy = self

        class _H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                log.debug("hostproxy http: " + fmt, *args)

            def do_GET(self):  # noqa: N802
                proxy._route(self, "GET")

            def do_POST(self):  # noqa: N802
                proxy._route(self, "POST")

        self._httpd = ThreadingHTTPServer((self.host, self.port), _H)
        self.bound_port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="hostproxy", daemon=True)
        self._thread.start()
        log.info("host proxy listening on %s:%d", self.host, self.bound_port)

    def stop(self) -> None:
        with self._lock:
            for s in self._sessions.values():
                self._close_session(s)
            self._sessions.clear()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(3.0)

    # ------------------------------------------------------------ routing

    def _route(self, req: BaseHTTPRequestHandler, method: str) -> None:
        try:
            path = urlparse(req.path).path
            if method == "GET" and path == "/healthz":
                self._reply(req, 200, {"ok": True, "sessions": len(self._sessions)})
            elif method == "POST" and path == "/open/url":
                self._handle_open(req)
            elif method == "POST" and path == "/oauth/listen":
                self._handle_oauth_listen(req)
            elif method == "GET" and path == "/oauth/poll":
                self._handle_oauth_poll(req)
            elif method == "POST" and path == "/git/credential":
                self._handle_git_credential(req)
            else:
                self._reply(req, 404, {"error": "not found"})
        except Exception as e:  # serve-path resilience
            log.error("hostproxy handler failure: %s", e)
            try:
                self._reply(req, 500, {"error": "internal error"})
            except Exception:
                pass

    @staticmethod
    def _body(req: BaseHTTPRequestHandler) -> bytes:
        length = int(req.headers.get("Content-Length") or 0)
        return req.rfile.read(length) if length else b""

    @staticmethod
    def _reply(req, code: int, payload: dict | bytes,
               content_type: str = "application/json") -> None:
        data = payload if isinstance(payload, bytes) else json.dumps(payload).encode()
        req.send_response(code)
        req.send_header("Content-Type", content_type)
        req.send_header("Content-Length", str(len(data)))
        req.end_headers()
        req.wfile.write(data)

    # ----------------------------------------------------------- handlers

    def _handle_open(self, req) -> None:
        try:
            body = json.loads(self._body(req) or b"{}")
        except json.JSONDecodeError:
            self._reply(req, 400, {"error": "invalid JSON"})
            return
        url = str(body.get("url") or "")
        scheme = urlparse(url).scheme.lower()
        if scheme not in ("http", "https"):
            self._reply(req, 400, {"error": f"refusing to open scheme {scheme!r}"})
            return
        self.opened_urls.append(url)
        ok = self.open_browser(url)
        log.info("open-url %s: %s", url, "ok" if ok else "no browser")
        self._reply(req, 200, {"opened": bool(ok)})

    def _handle_oauth_listen(self, req) -> None:
        try:
            body = json.loads(self._body(req) or b"{}")
        except json.JSONDecodeError:
            self._reply(req, 400, {"error": "invalid JSON"})
            return
        port = int(body.get("port") or 0)
        session = OAuthSession(id=secrets.token_urlsafe(16), port=port)
        proxy = self

        class _CB(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.0"

            def log_message(self, fmt, *args):
                pass

            def do_GET(self):  # noqa: N802
                parsed = urlparse(self.path)
                with proxy._lock:
                    # first capture wins: a trailing favicon/asset fetch on
                    # the same listener must not clobber the real callback
                    if session.captured is None:
                        session.captured = {
                            "path": parsed.path,
                            "query": {k: v[0] for k, v in parse_qs(parsed.query).items()},
                        }
                proxy._reply(self, 200, OAUTH_SUCCESS_PAGE, "text/html")

        try:
            srv = ThreadingHTTPServer(("127.0.0.1", port), _CB)
        except OSError as e:
            self._reply(req, 409, {"error": f"port {port}: {e}"})
            return
        session.server = srv
        session.port = srv.server_address[1]
        threading.Thread(target=srv.serve_forever,
                         name=f"oauth-{session.port}", daemon=True).start()
        with self._lock:
            self._gc_sessions()
            self._sessions[session.id] = session
        log.info("oauth session %s listening on 127.0.0.1:%d",
                 session.id[:8], session.port)
        self._reply(req, 200, {"session": session.id, "port": session.port})

    def _handle_oauth_poll(self, req) -> None:
        q = parse_qs(urlparse(req.path).query)
        sid = (q.get("session") or [""])[0]
        with self._lock:
            session = self._sessions.get(sid)
            if session is None:
                self._reply(req, 404, {"error": "unknown session"})
                return
            if session.captured is None:
                # bodyless 204: a body would desync keep-alive clients
                req.send_response(204)
                req.end_headers()
                return
            captured = session.captured
            self._close_session(session)
            del self._sessions[sid]
        self._reply(req, 200, captured)

    def _close_session(self, session: OAuthSession) -> None:
        if session.server is not None:
            srv = session.server
            session.server = None

            def _shutdown():
                srv.shutdown()
                srv.server_close()  # release the listening port too

            threading.Thread(target=_shutdown, daemon=True).start()

    def _gc_sessions(self) -> None:
        now = time.time()
        for sid in [s for s, v in self._sessions.items()
                    if now - v.created > OAUTH_SESSION_TTL_S]:
            self._close_session(self._sessions[sid])
            del self._sessions[sid]

    def _handle_git_credential(self, req) -> None:
        raw = self._body(req).decode(errors="replace")
        fields = dict(
            line.split("=", 1) for line in raw.splitlines() if "=" in line
        )
        host = fields.get("host", "")
        proto = fields.get("protocol", "")
        if proto not in ("https", "http") or not host:
            self._reply(req, 400, {"error": "protocol+host required"})
            return
        if not _host_allowed(host, self.cfg.egress_rules()):
            log.warning("git-credential DENIED for %s (not in egress rules)", host)
            self._reply(req, 403, {"error": f"host {host} not in egress rules"})
            return
        request = f"protocol={proto}\nhost={host}\n\n"
        filled = self.git_fill(request)
        log.info("git-credential fill for %s: %s", host,
                 "hit" if filled else "miss")
        self._reply(req, 200, filled.encode(), "text/plain")
