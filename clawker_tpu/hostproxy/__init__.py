"""Host-proxy side channel (reference: internal/hostproxy, SURVEY.md 2.10)."""
