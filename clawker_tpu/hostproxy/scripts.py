"""Container-side host-proxy scripts, baked into every harness image.

Parity reference: internal/hostproxy/internals (host-open.sh,
git-credential-clawker.sh, callback-forwarder) embedded by the bundler.
All three speak plain HTTP to ``$CLAWKER_HOSTPROXY`` (the host-gateway
address the runtime injects at create time) and degrade to no-ops when
the variable is unset, so images work unchanged with the proxy disabled.
"""

from __future__ import annotations

HOST_OPEN_SH = """#!/bin/sh
# host-open URL -- open a URL in the HOST browser via the clawker proxy.
set -eu
[ -n "${1:-}" ] || { echo "usage: host-open URL" >&2; exit 2; }
[ -n "${CLAWKER_HOSTPROXY:-}" ] || { echo "host-open: no host proxy configured" >&2; exit 1; }
# JSON-encode through python3: quotes/backslashes in URLs must not break the body
payload=$(python3 -c 'import json,sys; print(json.dumps({"url": sys.argv[1]}))' "$1")
curl -fsS -X POST -H 'Content-Type: application/json' \\
    -d "$payload" "$CLAWKER_HOSTPROXY/open/url" >/dev/null
"""

GIT_CREDENTIAL_SH = """#!/bin/sh
# git-credential-clawker -- git credential helper backed by the HOST
# credential store via the clawker proxy (fills only; store/erase no-op).
set -eu
action="${1:-}"
[ "$action" = "get" ] || exit 0
[ -n "${CLAWKER_HOSTPROXY:-}" ] || exit 0
body=$(cat)
curl -fsS -X POST --data-binary "$body" \\
    "$CLAWKER_HOSTPROXY/git/credential" 2>/dev/null || true
"""

OAUTH_FORWARD_SH = """#!/bin/sh
# oauth-forward PORT -- capture one OAuth callback hitting the HOST's
# 127.0.0.1:PORT and print the captured query JSON (polls the proxy).
set -eu
[ -n "${1:-}" ] || { echo "usage: oauth-forward PORT [timeout_s]" >&2; exit 2; }
[ -n "${CLAWKER_HOSTPROXY:-}" ] || { echo "oauth-forward: no host proxy" >&2; exit 1; }
timeout="${2:-300}"
resp=$(curl -fsS -X POST -H 'Content-Type: application/json' \\
    -d "{\\"port\\": $1}" "$CLAWKER_HOSTPROXY/oauth/listen")
session=$(printf '%s' "$resp" | sed -n 's/.*"session": *"\\([^"]*\\)".*/\\1/p')
[ -n "$session" ] || { echo "oauth-forward: listen failed: $resp" >&2; exit 1; }
elapsed=0
while [ "$elapsed" -lt "$timeout" ]; do
    code=$(curl -s -o /tmp/.oauth-cb -w '%{http_code}' \\
        "$CLAWKER_HOSTPROXY/oauth/poll?session=$session")
    if [ "$code" = "200" ]; then cat /tmp/.oauth-cb; rm -f /tmp/.oauth-cb; exit 0; fi
    sleep 1; elapsed=$((elapsed + 1))
done
echo "oauth-forward: timed out after ${timeout}s" >&2
exit 1
"""

# arcname-in-context -> (target path, content)
CONTEXT_SCRIPTS = {
    "hostproxy/host-open": ("/usr/local/bin/host-open", HOST_OPEN_SH),
    "hostproxy/git-credential-clawker": (
        "/usr/local/bin/git-credential-clawker", GIT_CREDENTIAL_SH),
    "hostproxy/oauth-forward": ("/usr/local/bin/oauth-forward", OAUTH_FORWARD_SH),
}
