"""``python -m clawker_tpu.hostproxy`` -- the host-proxy daemon."""

from __future__ import annotations

import os
import signal
import sys
import threading

from .. import logsetup
from ..config import load_config
from .server import HostProxy


def main() -> int:
    logsetup.setup(os.environ.get("CLAWKER_TPU_HOSTPROXY_LOG", "info"))
    cfg = load_config()
    proxy = HostProxy(cfg)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    proxy.start()
    while not stop.is_set():
        stop.wait(1.0)
    proxy.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
