"""Host-proxy daemon lifecycle over the shared DaemonSpec state machine.

Parity reference: internal/hostproxy manager.go:156 daemon spawn.  The
spawn/liveness/terminate discipline lives in util/daemon.py, shared with
the control-plane manager so the two can never diverge.
"""

from __future__ import annotations

from .. import logsetup
from ..config import Config
from ..errors import ClawkerError
from ..util.daemon import DaemonError, DaemonSpec

log = logsetup.get("hostproxy.manager")


class HostProxyError(ClawkerError):
    pass


def _spec(cfg: Config) -> DaemonSpec:
    return DaemonSpec(
        name="host proxy",
        module="clawker_tpu.hostproxy",
        pidfile=cfg.state_dir / "hostproxy.pid",
        logfile=cfg.logs_dir / "hostproxy.log",
        health_url=f"http://127.0.0.1:{cfg.settings.host_proxy.port}/healthz",
        start_deadline_s=10.0,
    )


def health(cfg: Config, timeout: float = 1.5) -> dict | None:
    return _spec(cfg).health(timeout)


def running(cfg: Config) -> bool:
    return _spec(cfg).running()


def ensure_running(cfg: Config) -> None:
    try:
        # pre-start hot path: a proxy proven healthy in the last few
        # seconds is not re-probed for every agent create
        _spec(cfg).ensure_running(log=log, probe_ttl_s=3.0)
    except DaemonError as e:
        raise HostProxyError(str(e)) from None


def stop(cfg: Config) -> bool:
    return _spec(cfg).stop()
