"""Host-proxy daemon lifecycle (reference: hostproxy/manager.go:156 daemon
spawn; server lands in the host-services milestone)."""

from __future__ import annotations

from .. import logsetup
from ..config import Config

log = logsetup.get("hostproxy.manager")

_started_in_process = False


def ensure_running(cfg: Config) -> None:
    """Start the host-proxy HTTP server if not already serving.

    In-process thread for now (daemonization follows with the full server);
    idempotent per process.
    """
    global _started_in_process
    if _started_in_process:
        return
    try:
        from .server import start_background

        start_background(cfg)
        _started_in_process = True
    except ImportError:
        log.debug("hostproxy server not yet available")
