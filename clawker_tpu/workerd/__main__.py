"""``python -m clawker_tpu.workerd``: run the workerd daemon.

Run ON the worker host whose engine it should serve (``clawker workerd
start`` forks this detached).  The config loads from the cwd -- workerd
is project-scoped like loopd: container names and labels key on the
project.  The engine comes from the settings runtime driver's default
worker (override with ``CLAWKER_TPU_WORKERD_DRIVER``, e.g. ``local``
when the provisioned worker settings still name ``tpu_vm``)."""

from __future__ import annotations

import os
import signal
import sys
import threading
from pathlib import Path

from .. import logsetup
from ..config import load_config
from ..engine.drivers import get_driver
from .server import WorkerdServer


def main() -> int:
    cfg = load_config(Path.cwd())
    logsetup.setup(os.environ.get("CLAWKER_TPU_WORKERD_LOG", "info"))
    override = os.environ.get("CLAWKER_TPU_WORKERD_DRIVER", "")
    driver = get_driver(cfg.settings, override=override)
    workers = driver.connect()
    worker = workers[0] if workers else None
    if worker is None or worker.engine is None:
        print("workerd: no local engine to serve", file=sys.stderr)
        return 1
    server = WorkerdServer(cfg, worker.engine, worker_id=worker.id,
                           driver=driver)
    server.start()
    stop = threading.Event()

    def on_term(signum, frame):
        server.stop()
        stop.set()

    signal.signal(signal.SIGTERM, on_term)
    signal.signal(signal.SIGINT, on_term)
    try:
        while not stop.is_set() and not server._stop.is_set():
            stop.wait(0.5)
    finally:
        server.stop()
        driver.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
