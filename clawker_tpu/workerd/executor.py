"""WorkerdExecutor: the scheduler-side half of the workerd data plane.

One executor per worker owns ONE persistent channel to that worker's
workerd (docs/workerd.md).  The scheduler's ``_submit_launch`` routes a
launch through admission exactly as before, but dispatch hands the
work to the executor instead of a local lane: the executor queues an
*intent*, the sender thread coalesces queued intents into one frame
(one WAN crossing per batch), and the reader thread turns the event
stream back into scheduler accounting calls -- created/started/exited
land in the same journal records, spans, and status transitions the
direct path writes, on the same locks.

Failure model:

- **partition** (channel dies, daemon lives): pending intents are KEPT
  for ``intent_deadline_s`` while the monitor thread redials; on
  reconnect it re-sends them (workerd dedups by (kind, agent, epoch,
  iteration) -- no duplicate creates) and ``resync``s the scheduler's
  running view against workerd's local container reality, so exits the
  partition swallowed are accounted exactly once.  The seam
  ``workerd.post_reconnect`` fires at that boundary.
- **daemon death**: redials fail, pending intents hit the deadline and
  strand their loops WITHOUT a breaker penalty (workerd death is not
  engine sickness); ``live()`` reads False, so the scheduler resumes
  direct polling and launches fall back to the in-process lane -- the
  degrade matrix row `fleet health` renders as ``degraded``.
"""

from __future__ import annotations

import queue
import socket
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from pathlib import Path

from .. import logsetup, telemetry
from ..agentd import protocol
from ..chaos.seams import SeamAbort
from ..errors import ClawkerError
from ..tracing.skew import ChannelClock
from . import WorkerdError

log = logsetup.get("workerd.executor")

_RECONNECTS = telemetry.counter(
    "workerd_reconnects_total", "Channel reconnects after a partition",
    labels=("worker",))
_CHANNEL_FAILS = telemetry.counter(
    "workerd_channel_failures_total",
    "Pending intents failed over to the direct path", labels=("worker",))
_INTENT_BATCHES = telemetry.counter(
    "workerd_intent_batches_total",
    "Intent frames sent (intents/batch = coalescing ratio)",
    labels=("worker",))

CONNECT_TIMEOUT_S = 2.0
MONITOR_TICK_S = 0.2


def ping_socket(path: Path) -> bool:
    """True when a workerd answers a ping on ``path``."""
    try:
        with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
            s.settimeout(1.0)
            s.connect(str(path))
            protocol.write_msg(s, {"type": "ping"})
            return protocol.read_msg(s).get("type") == "pong"
    except (OSError, ClawkerError):
        return False


@dataclass
class _Pending:
    """One in-flight intent awaiting its terminal event."""

    seq: int
    kind: str                   # launch | start | create
    doc: dict                   # the full intent (re-sent on reconnect)
    handle: Future
    t_submit: float
    loop: object = None         # AgentLoop for launch/start
    epoch: int = 0
    worker: object = None
    pool_entry: object = None   # warm-pool entry adopted by this launch
    cid: str = ""               # filled by the created event


class WorkerdExecutor:
    """One worker's persistent intent channel + pending-intent table."""

    def __init__(self, worker_id: str, sock_path: Path | str, *,
                 rtt_s: float = 0.0, intent_deadline_s: float = 60.0,
                 connect: bool = True):
        self.worker_id = worker_id
        self.sock_path = Path(sock_path)
        # fake-WAN model (docs/workerd.md#fake-wan): one-way propagation
        # delay paid once per FRAME (rtt/2 before an intent batch goes
        # out, rtt/2 before an event batch dispatches) -- pipelined
        # messages share a batch, so an iteration costs ~1 RTT instead
        # of one RTT per engine call
        self.rtt_s = float(rtt_s)
        self.intent_deadline_s = float(intent_deadline_s)
        self.sched = None
        self._seq = 0
        self._pending: dict[int, _Pending] = {}
        self._plock = threading.Lock()
        self._sendq: queue.SimpleQueue = queue.SimpleQueue()
        self._sock: socket.socket | None = None
        self._wlock = threading.Lock()
        self._live = False
        self._ever_connected = False
        # per-channel clock-skew estimator (docs/tracing.md#clock-skew):
        # fed by the ``ts`` field on hello_ack/resync_ack round-trips
        # this channel already pays -- never a new RPC
        self.clock = ChannelClock()
        self._closed = threading.Event()
        self._dead = threading.Event()      # channel needs a redial
        self.reconnects = 0
        self.stats = {"intents": 0, "batches": 0, "events": 0,
                      "failed_over": 0, "seeds": 0}
        self._seeded: set[str] = set()   # digests already shipped to the
        #                                  worker's seed store (the
        #                                  once-per-(digest,worker) gate)
        threading.Thread(target=self._sender, daemon=True,
                         name=f"workerd-send-{worker_id}").start()
        threading.Thread(target=self._monitor, daemon=True,
                         name=f"workerd-mon-{worker_id}").start()
        if connect and not self._try_connect():
            self._dead.set()        # monitor keeps redialing

    # ------------------------------------------------------------- wiring

    def bind(self, sched) -> None:
        """Attach the scheduler whose accounting the event stream
        drives (one scheduler per executor set; loopd-hosted runs keep
        the in-process path -- docs/workerd.md degrade matrix).

        Re-binding (a resumed generation adopting the channels of the
        one that died) drops the dead generation's pending intents
        without accounting: their loop objects belong to a frozen
        scheduler, and the resume reconcile re-derives everything they
        could have said from engine state + the journal."""
        if self.sched is not None and sched is not self.sched:
            with self._plock:
                stale, self._pending = self._pending, {}
            for p in stale.values():
                if not p.handle.done():
                    p.handle.set_result(None)
        self.sched = sched

    def live(self) -> bool:
        return self._live and not self._closed.is_set()

    def close(self) -> None:
        self._closed.set()
        self._dead.set()
        self._drop_sock()

    def _drop_sock(self) -> None:
        sock, self._sock = self._sock, None
        self._live = False
        if sock is not None:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass

    # ------------------------------------------------------------ connect

    def _try_connect(self) -> bool:
        try:
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            s.settimeout(CONNECT_TIMEOUT_S)
            s.connect(str(self.sock_path))
            t0 = time.time()
            protocol.write_msg(s, {"type": "hello"})
            ack = protocol.read_msg(s)
            if ack.get("type") != "hello_ack":
                s.close()
                return False
            # the handshake round-trip doubles as a clock-skew sample;
            # the resync frame hands the daemon its CUMULATIVE offset to
            # the root clock so its spans carry an auditable ``skew_s``
            self.clock.observe(t0, float(ack.get("ts") or 0.0), time.time())
            view = self._running_view()
            t0 = time.time()
            protocol.write_msg(s, {
                "type": "resync", "running": view,
                "clock_offset_s": round(
                    self.clock.cumulative(self._upstream_offset()), 6)})
            # the resync_ack may be preceded by event frames the server
            # flushes the moment the sink opens: dispatch them in order
            while True:
                msg = protocol.read_msg(s)
                if msg.get("type") == "resync_ack":
                    self.clock.observe(t0, float(msg.get("ts") or 0.0),
                                       time.time())
                    break
                if msg.get("type") == "events":
                    self._dispatch_events(msg)
            s.settimeout(None)
        except (OSError, ClawkerError):
            try:
                s.close()
            except OSError:
                pass
            return False
        self._sock = s
        self._dead.clear()
        self._live = True
        reconnect = self._ever_connected
        self._ever_connected = True
        threading.Thread(target=self._reader, args=(s,), daemon=True,
                         name=f"workerd-read-{self.worker_id}").start()
        # re-send every pending intent: undelivered ones execute now,
        # delivered ones dedup server-side and their (buffered) events
        # arrive via the stream either way
        with self._plock:
            pend = [p.doc for p in self._pending.values()]
        for doc in pend:
            self._sendq.put(doc)
        if reconnect:
            self.reconnects += 1
            _RECONNECTS.labels(self.worker_id).inc()
            log.info("workerd channel to %s re-established (%d pending "
                     "re-synced)", self.worker_id, len(pend))
            self._fire_seam("workerd.post_reconnect")
        return True

    def _running_view(self) -> list[dict]:
        sched = self.sched
        if sched is None:
            return []
        return sched._workerd_running_view(self.worker_id)

    def _upstream_offset(self) -> float:
        """The scheduler's own cumulative offset to the root clock (0
        when the scheduler IS the root viewer; the loopd-supplied value
        on a federated run) -- chained into this channel's estimate."""
        return float(getattr(self.sched, "_trace_offset_s", 0.0) or 0.0)

    def _tp(self, loop) -> str:
        """The traceparent for one loop's intents: run trace id plus the
        open iteration-root span id when the scheduler has opened it
        (adopt intents); "" when tracing is off or no scheduler bound."""
        fn = getattr(self.sched, "_trace_tp", None)
        if fn is None:
            return ""
        try:
            return fn(loop)
        except Exception:   # noqa: BLE001 -- tracing never fails a launch
            return ""

    def _fire_seam(self, name: str) -> None:
        sched = self.sched
        if sched is None:
            return
        try:
            sched.seams.fire(name)
        except SeamAbort:
            pass        # the armed kill already froze the scheduler

    def _monitor(self) -> None:
        """Redial a dead channel; expire pending intents past the
        deadline (a wedged/killed daemon must not hang a launch
        forever -- the loop strands into the normal rescue path).

        The body is hardened per tick: if this thread died, pending
        intents would never expire and their loops would stay busy
        forever -- the one failure mode the degrade matrix cannot
        absorb."""
        backoff = 0.05
        while not self._closed.is_set():
            self._dead.wait(MONITOR_TICK_S)
            if self._closed.is_set():
                return
            try:
                if self._dead.is_set():
                    if self._try_connect():
                        backoff = 0.05
                    else:
                        time.sleep(backoff)
                        backoff = min(backoff * 2, 0.5)
                self._expire_pending()
            except Exception:   # noqa: BLE001 -- keep the lifeline up
                log.exception("workerd monitor tick failed (%s)",
                              self.worker_id)

    def _expire_pending(self) -> None:
        now = time.monotonic()
        expired: list[_Pending] = []
        with self._plock:
            for seq, p in list(self._pending.items()):
                if now - p.t_submit >= self.intent_deadline_s:
                    expired.append(self._pending.pop(seq))
        for p in expired:
            self._fail_pending(p, "workerd intent deadline exceeded "
                                  "(daemon dead or wedged)")

    def _fail_pending(self, p: _Pending, reason: str) -> None:
        self.stats["failed_over"] += 1
        _CHANNEL_FAILS.labels(self.worker_id).inc()
        sched = self.sched
        if p.kind in ("launch", "start") and sched is not None:
            sched._workerd_failed(p.loop, p.epoch, p.worker, "channel",
                                  reason, driverish=True, penalize=False,
                                  pool_entry=p.pool_entry)
            if not p.handle.done():
                p.handle.set_result(None)
        else:
            if not p.handle.done():
                p.handle.set_exception(WorkerdError(reason))

    # -------------------------------------------------------------- sends

    def _sender(self) -> None:
        """Coalesce queued intents into one frame per flush: the send
        half of O(1) WAN crossings per batch."""
        while not self._closed.is_set():
            try:
                first = self._sendq.get(timeout=0.5)
            except queue.Empty:
                continue
            batch = [first]
            while True:
                try:
                    batch.append(self._sendq.get_nowait())
                except queue.Empty:
                    break
            if self.rtt_s > 0:
                # one-way propagation: intents queued during the flight
                # ride the same batch (the drain below)
                time.sleep(self.rtt_s / 2)
                while True:
                    try:
                        batch.append(self._sendq.get_nowait())
                    except queue.Empty:
                        break
            sock = self._sock
            if sock is None:
                continue    # link down: pending re-send covers these
            try:
                with self._wlock:
                    protocol.write_msg(sock, {"type": "intents",
                                              "batch": batch})
                self.stats["batches"] += 1
                _INTENT_BATCHES.labels(self.worker_id).inc()
            except (OSError, ClawkerError):
                self._drop_sock()
                self._dead.set()

    def _next_seq(self) -> int:
        self._seq += 1
        return self._seq

    def _submit(self, doc: dict, pending: _Pending) -> Future:
        with self._plock:
            self._pending[pending.seq] = pending
        self.stats["intents"] += 1
        self._sendq.put(doc)
        return pending.handle

    def submit_launch(self, loop, epoch: int, worker, *, opts_doc: dict,
                      state: dict | None = None, pool_cid: str = "",
                      pool_entry=None) -> Future:
        seq = self._next_seq()
        doc = {"kind": "launch", "seq": seq, "agent": loop.agent,
               "epoch": epoch, "iteration": loop.iteration,
               "opts": opts_doc, "pool_cid": pool_cid, "state": state,
               "tp": self._tp(loop)}
        return self._submit(doc, _Pending(
            seq=seq, kind="launch", doc=doc, handle=Future(),
            t_submit=time.monotonic(), loop=loop, epoch=epoch,
            worker=worker, pool_entry=pool_entry))

    def submit_start(self, loop, epoch: int, worker, *, cid: str,
                     fresh: bool, state: dict | None = None) -> Future:
        seq = self._next_seq()
        doc = {"kind": "start", "seq": seq, "agent": loop.agent,
               "epoch": epoch, "iteration": loop.iteration, "cid": cid,
               "fresh": fresh, "state": state, "tp": self._tp(loop)}
        return self._submit(doc, _Pending(
            seq=seq, kind="start", doc=doc, handle=Future(),
            t_submit=time.monotonic(), loop=loop, epoch=epoch,
            worker=worker))

    def submit_pool_fill(self, pool_agent: str, opts_doc: dict) -> Future:
        """Warm-pool refill executed worker-side; resolves to the cid."""
        seq = self._next_seq()
        doc = {"kind": "create", "seq": seq, "agent": pool_agent,
               "epoch": -1, "iteration": 0, "opts": opts_doc}
        return self._submit(doc, _Pending(
            seq=seq, kind="create", doc=doc, handle=Future(),
            t_submit=time.monotonic()))

    def submit_adopt(self, loop, epoch: int) -> None:
        """Arm a worker-local exit waiter on an adopted container
        (--resume: the iteration keeps streaming its exit despite the
        scheduler never polling this worker over the WAN)."""
        self._sendq.put({"kind": "adopt", "seq": self._next_seq(),
                         "agent": loop.agent, "epoch": epoch,
                         "iteration": loop.iteration,
                         "cid": loop.container_id,
                         "tp": self._tp(loop)})

    def submit_halt(self, cid: str, timeout: int = 2) -> None:
        self._sendq.put({"kind": "halt", "seq": self._next_seq(),
                         "cid": cid, "timeout": timeout})

    def seeded(self, digest: str) -> bool:
        """Has this channel already shipped ``digest`` to the worker?"""
        return digest in self._seeded

    def submit_seed(self, digest: str, tar: bytes) -> bool:
        """Ship a workspace seed to the worker's seed store, at most once
        per digest per channel (docs/loop-worktrees.md#worker-resident-
        seeds).  Fire-and-forget on the ordered intent queue: the
        server's serial lane stores the seed before it executes any
        launch queued after this call, so launches referencing the
        digest hit the store.  A transfer lost to a dead link simply
        degrades those launches to the per-create fallback -- seeding is
        an optimization, never a correctness dependency.  Returns True
        when a transfer was actually queued."""
        if digest in self._seeded:
            return False
        self._seeded.add(digest)
        self.stats["seeds"] += 1
        self._sendq.put({"kind": "seed", "seq": self._next_seq(),
                         "digest": digest, "tar": protocol.b64(tar)})
        return True

    # ------------------------------------------------------------- events

    def _reader(self, sock: socket.socket) -> None:
        while not self._closed.is_set() and self._sock is sock:
            try:
                msg = protocol.read_msg(sock)
            except (protocol.ProtocolError, ClawkerError, OSError):
                if self._sock is sock:
                    self._drop_sock()
                    self._dead.set()
                return
            if msg.get("type") == "events":
                if self.rtt_s > 0:
                    time.sleep(self.rtt_s / 2)   # one-way propagation
                self._dispatch_events(msg)

    def _dispatch_events(self, msg: dict) -> None:
        for ev in msg.get("batch") or []:
            self.stats["events"] += 1
            try:
                self._dispatch_one(ev)
            except SeamAbort:
                return      # armed chaos kill fired in a handler
            except Exception:   # noqa: BLE001 -- one bad event must not
                log.exception("workerd event dispatch failed: %r", ev)

    @staticmethod
    def _wan_ms(p: _Pending, ev: dict) -> float:
        """Per-hop WAN wait: client wall elapsed since submit minus the
        server-side ms the event reports -- queueing + propagation +
        batching for this intent, attributed on the scheduler's span."""
        elapsed_ms = (time.monotonic() - p.t_submit) * 1000.0
        return max(0.0, round(elapsed_ms - float(ev.get("ms", 0.0)), 3))

    def _dispatch_one(self, ev: dict) -> None:
        kind = str(ev.get("ev", ""))
        sched = self.sched
        if kind == "exited":
            if sched is not None:
                sched._workerd_exited(
                    str(ev.get("agent", "")), int(ev.get("epoch", 0)),
                    int(ev.get("iteration", 0)), ev.get("code"),
                    str(ev.get("detail", "")))
            return
        seq = int(ev.get("seq", 0))
        with self._plock:
            p = self._pending.get(seq)
        if p is None:
            return      # already resolved (dedup echo, late duplicate)
        if kind == "created":
            p.cid = str(ev.get("cid", ""))
            entry, p.pool_entry = p.pool_entry, None
            # pool_entry cleared BEFORE the handler: the created
            # handler fully accounts the member (adopted, or recycled
            # on a remote adoption failure), so a later failed/expiry
            # on this same intent must not recycle it a second time
            if sched is not None:
                sched._workerd_created(
                    p.loop, p.epoch, p.worker, p.cid,
                    bool(ev.get("pool")), str(ev.get("pool_error", "")),
                    entry, float(ev.get("ms", 0.0)),
                    wan_ms=self._wan_ms(p, ev))
        elif kind == "started":
            with self._plock:
                self._pending.pop(seq, None)
            if sched is not None:
                sched._workerd_started(p.loop, p.epoch, p.worker,
                                       float(ev.get("ms", 0.0)),
                                       wan_ms=self._wan_ms(p, ev))
            if not p.handle.done():
                p.handle.set_result(None)
        elif kind == "pool_ready":
            with self._plock:
                self._pending.pop(seq, None)
            if not p.handle.done():
                p.handle.set_result(str(ev.get("cid", "")))
        elif kind == "failed":
            with self._plock:
                self._pending.pop(seq, None)
            if p.kind == "create":
                if not p.handle.done():
                    p.handle.set_exception(WorkerdError(
                        f"{ev.get('phase')}: {ev.get('error')}"))
            else:
                if sched is not None:
                    sched._workerd_failed(
                        p.loop, p.epoch, p.worker,
                        str(ev.get("phase", "?")),
                        str(ev.get("error", "")),
                        driverish=bool(ev.get("driverish")),
                        pool_entry=p.pool_entry)
                if not p.handle.done():
                    p.handle.set_result(None)


class ExecutorSet:
    """worker id -> WorkerdExecutor, plus the degrade seam: a worker
    with no live executor (absent, partitioned past deadline, killed)
    transparently uses the direct in-process path."""

    def __init__(self, executors: dict[str, WorkerdExecutor] | None = None):
        self.executors: dict[str, WorkerdExecutor] = dict(executors or {})

    def bind(self, sched) -> None:
        for ex in self.executors.values():
            ex.bind(sched)

    def for_worker(self, worker_id: str) -> WorkerdExecutor | None:
        """The worker's executor, only while its channel is LIVE."""
        ex = self.executors.get(worker_id)
        return ex if ex is not None and ex.live() else None

    def any_for(self, worker_id: str) -> WorkerdExecutor | None:
        """The executor regardless of liveness (liveness views)."""
        return self.executors.get(worker_id)

    def sockets(self) -> dict[str, Path]:
        return {wid: ex.sock_path for wid, ex in self.executors.items()}

    def close_all(self) -> None:
        for ex in self.executors.values():
            ex.close()

    def __len__(self) -> int:
        return len(self.executors)

    def __bool__(self) -> bool:
        return bool(self.executors)


def discover_executors(cfg, driver) -> ExecutorSet:
    """Build executors for every worker whose workerd answers
    (docs/workerd.md#discovery): the transport-forwarded socket for
    ``tpu_vm`` workers (tunneled over the existing SSH mux), the host's
    canonical socket for the single local worker.  Workers with nothing
    answering get no executor -- the scheduler's direct path serves
    them unchanged."""
    from . import socket_path

    ws = cfg.settings.workerd
    out: dict[str, WorkerdExecutor] = {}
    if not ws.enable:
        return ExecutorSet(out)
    for worker in driver.workers():
        sock: Path | None = None
        transport = getattr(worker.engine, "transport", None)
        if transport is not None:
            try:
                sock = transport.forward_workerd()
            except ClawkerError:
                sock = None
        elif getattr(driver, "name", "") == "local":
            cand = socket_path(cfg)
            sock = cand if cand.exists() else None
        if sock is None or not ping_socket(sock):
            continue
        out[worker.id] = WorkerdExecutor(
            worker.id, sock, intent_deadline_s=ws.intent_deadline_s)
    return ExecutorSet(out)
