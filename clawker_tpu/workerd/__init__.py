"""workerd: the worker-resident launch-executor daemon (docs/workerd.md).

loopd (docs/loopd.md) centralized admission, fairness, and run
supervision on the CLIENT host -- but every engine mutation still
dials the worker's daemon from there, so on a real ``tpu_vm`` pod each
create/start/wait/logs call crosses the SSH mux and pays a host<->worker
WAN round trip.  An N-iteration loop costs O(calls-per-iteration) RTTs.

workerd moves the launch **data plane** onto the worker host while the
scheduler/loopd keep the **control plane** (placement, admission,
fairness, durable intent):

- the scheduler sends batched *intents* (``launch`` / ``start`` /
  ``create`` (pool fill) / ``adopt`` / ``halt`` / ``resync``), each
  carrying the journaled placement epoch + tenant, over ONE persistent
  channel per worker (the agentd length-prefixed JSON framing --
  ``agentd/protocol.py`` -- on a 0600 unix socket, tunneled over the
  existing SSH mux for ``tpu_vm``, dialed directly on local/fake);
- workerd executes create/start/wait/pool-refill against its LOCAL
  engine socket on a local serial lane and streams batched typed
  events (created/started/exited/pool_ready, exit codes, span timings)
  back on the same channel;
- an iteration therefore costs O(1) WAN round trips (one intent batch
  out, one event batch back) instead of O(4+) blocking RTTs.

workerd is stateless-restartable: the journal write-ahead stays on the
scheduler side, and on reconnect the scheduler re-syncs its intent view
(``resync``) while workerd reports its label-scoped local container
reality -- reconciling exactly like ``--resume`` does.  No daemon (or a
dead one) degrades transparently to the in-process direct executor:
today's behavior, unchanged (the degrade matrix in docs/workerd.md).

Layout (on the WORKER host)::

    <state>/workerd/           runtime dir, chmod 0700 (fs perms ARE
        workerd.sock           the auth -- the loopd/bksession pattern)
        workerd.pid
    <state>/logs/workerd.log   daemon stdout/stderr
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from pathlib import Path

from ..errors import ClawkerError

WORKERD_DIR = "workerd"             # under Config.state_dir
SOCKET_NAME = "workerd.sock"
PIDFILE_NAME = "workerd.pid"
LOGFILE_NAME = "workerd.log"        # under Config.logs_dir

# per-worker liveness states rendered by `fleet health` / loopd status
LIVE = "live"           # socket answers the ping
DEGRADED = "degraded"   # socket exists but nothing answers (daemon died;
#                         the data plane silently fell back to the WAN path)
ABSENT = "absent"       # no workerd was ever provisioned here


class WorkerdError(ClawkerError):
    pass


def runtime_dir(cfg) -> Path:
    """The daemon's 0700 runtime dir (socket + pidfile)."""
    return Path(cfg.state_dir) / WORKERD_DIR


def socket_path(cfg) -> Path:
    """The daemon control socket: settings ``workerd.socket`` override
    or the canonical runtime-dir location."""
    override = cfg.settings.workerd.socket
    if override:
        return Path(override)
    return runtime_dir(cfg) / SOCKET_NAME


def pidfile_path(cfg) -> Path:
    return runtime_dir(cfg) / PIDFILE_NAME


def logfile_path(cfg) -> Path:
    return Path(cfg.logs_dir) / LOGFILE_NAME


def spawn_daemon(cfg, *, cwd: Path | None = None,
                 driver_override: str = "") -> int:
    """Fork ``python -m clawker_tpu.workerd`` detached; wait until its
    socket answers a ping or the settings deadline passes.  Returns the
    daemon pid.  Run this ON the worker host that should own the data
    plane (for ``tpu_vm`` the provisioning payload carries the package;
    for the local/laptop engine it serves /var/run/docker.sock)."""
    from .executor import ping_socket

    sock = socket_path(cfg)
    log_path = logfile_path(cfg)
    log_path.parent.mkdir(parents=True, exist_ok=True)
    runtime_dir(cfg).mkdir(parents=True, exist_ok=True)
    os.chmod(runtime_dir(cfg), 0o700)
    env = os.environ.copy()
    if driver_override:
        env["CLAWKER_TPU_WORKERD_DRIVER"] = driver_override
    # the child's cwd is the project dir, not the repo: make the
    # package importable there (the nsd/bench subprocess pattern)
    pkg_root = str(Path(__file__).resolve().parents[2])
    env["PYTHONPATH"] = (pkg_root + os.pathsep + env["PYTHONPATH"]
                         if env.get("PYTHONPATH") else pkg_root)
    with open(log_path, "ab") as logf:
        proc = subprocess.Popen(
            [sys.executable, "-m", "clawker_tpu.workerd"],
            stdout=logf, stderr=subprocess.STDOUT,
            stdin=subprocess.DEVNULL,
            start_new_session=True,         # survive the invoking CLI
            cwd=str(cwd) if cwd is not None else None,
            env=env,
        )
    deadline = time.monotonic() + cfg.settings.workerd.start_deadline_s
    while time.monotonic() < deadline:
        if ping_socket(sock):
            return proc.pid
        if proc.poll() is not None:
            raise WorkerdError(
                f"workerd exited during start (rc={proc.returncode}); "
                f"see {log_path}")
        time.sleep(0.1)
    try:
        proc.terminate()
        proc.wait(timeout=3)
    except Exception:       # noqa: BLE001 -- best effort by design
        pass
    raise WorkerdError(
        f"workerd did not answer on {sock} within "
        f"{cfg.settings.workerd.start_deadline_s:.0f}s; see {log_path}")


def liveness(cfg, driver, *, sock_by_worker: dict | None = None) -> dict:
    """Per-worker workerd liveness: worker id -> live|degraded|absent.

    The ``fleet health`` / loopd-status satellite: a worker whose
    workerd died silently degrades every loop on it back to the WAN
    path -- visibly slower but otherwise healthy, exactly the failure
    a fleet view must surface instead of hiding.

    Resolution order per worker: an explicit ``sock_by_worker`` entry
    (tests, loop --workerd wiring), else the transport-forwarded socket
    a tpu_vm engine carries, else -- for the single local worker -- the
    host's canonical socket path.  Fake workers with no mapping read
    ``absent`` (no daemon was ever provisioned)."""
    from .executor import ping_socket

    out: dict[str, str] = {}
    for worker in driver.workers():
        sock = (sock_by_worker or {}).get(worker.id)
        if sock is None:
            transport = getattr(worker.engine, "transport", None)
            if transport is not None:
                local = transport.mux_dir / f"workerd-{transport.index}.sock"
                sock = local if local.exists() else None
            elif getattr(driver, "name", "") == "local":
                sock = socket_path(cfg)
        if sock is None or not Path(sock).exists():
            out[worker.id] = ABSENT
        elif ping_socket(Path(sock)):
            out[worker.id] = LIVE
        else:
            out[worker.id] = DEGRADED
    return out
