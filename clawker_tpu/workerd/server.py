"""The workerd server: the worker-resident launch data plane.

One :class:`WorkerdServer` per worker host owns a serial *local lane*
against that host's engine socket.  The scheduler (directly, or via
loopd) sends batched intents over one persistent channel; the server
executes them locally -- the whole create/start/wait burst that used to
cross the WAN per engine call now happens daemon-to-daemon over a unix
socket -- and streams batched typed events back.

Wire protocol (agentd length-prefixed JSON framing; docs/workerd.md):

==============  ========================================================
frame           meaning
==============  ========================================================
``hello``       client handshake -> ``hello_ack`` {pid, version, worker}
``ping``        liveness -> ``pong``
``status``      stats doc (executed/queued/buffered counts)
``intents``     {batch: [intent...]}; fire-and-forget, executed in order
                on the local lane.  Intent kinds: ``launch`` (create +
                first start), ``start`` (restart an existing container),
                ``create`` (create only -- warm-pool fill), ``adopt``
                (arm an exit waiter on a live container), ``halt``
                (stop a container), ``seed`` (stage a workspace seed
                tar by content digest in the worker-local seed store so
                later launches fan it out from the local socket --
                docs/loop-worktrees.md#worker-resident-seeds).
``resync``      {running: [...]}: the reconnect handshake -- workerd
                compares the scheduler's intent view against its LOCAL
                container reality, re-arms waiters for still-running
                containers, reports exits the partition swallowed, and
                then flushes every event buffered while the link was
                down -> ``resync_ack``
``shutdown``    graceful stop -> ``ok``
==============  ========================================================

Events (batched into ``{"type": "events", "batch": [...]}`` frames; one
WAN crossing per batch): ``created`` / ``started`` / ``pool_ready`` /
``failed`` echo the intent's ``seq``; ``exited`` is unsolicited and
keyed by (agent, epoch, iteration).  All carry worker-side span timings
(``ms``).

Crash safety: workerd holds NO durable state -- the write-ahead journal
stays with the scheduler.  Events that cannot be delivered (link down)
are buffered (bounded) and flushed after the next ``resync``; a killed
workerd loses its buffer, which the scheduler covers by degrading to
direct polling (the same engine socket is still forwarded).  Intents
are deduplicated by (kind, agent, epoch, iteration) so a client that
ever re-sends cannot double-create.
"""

from __future__ import annotations

import collections
import contextlib
import os
import queue
import socket
import threading
import time

from .. import __version__, logsetup, telemetry
from ..agentd import protocol
from ..chaos.seams import NULL_SEAMS
from ..errors import ClawkerError, DriverError, NotFoundError
from ..tracing.names import (SPAN_WORKERD_CREATE, SPAN_WORKERD_START,
                             SPAN_WORKERD_WAIT)
from . import WorkerdError

log = logsetup.get("workerd.server")

_INTENTS = telemetry.counter(
    "workerd_intents_total", "Intents executed by workerd",
    labels=("worker", "kind"))
_EVENTS = telemetry.counter(
    "workerd_events_total", "Typed events emitted by workerd",
    labels=("worker", "kind"))
_BATCHES = telemetry.counter(
    "workerd_event_batches_total",
    "Event frames flushed by workerd (events/batch = coalescing ratio)",
    labels=("worker",))
_BUFFERED_DROPS = telemetry.counter(
    "workerd_events_dropped_total",
    "Events dropped off a full link-down buffer", labels=("worker",))

INTENT_KINDS = ("launch", "start", "create", "adopt", "halt", "seed")
EVENT_BUFFER = 4096             # events held while the link is down
FLUSH_WINDOW_S = 0.002          # coalesce window per event batch
DEDUP_KEYS_KEPT = 4096          # executed-intent keys retained; dedup
#                                 only needs the client-retry window, and
#                                 a daemon that outlives many runs must
#                                 not grow a key per intent forever


class SeedStore:
    """Worker-local content-addressed seed cache: digest -> tar bytes.

    Bounded by TOTAL bytes (``workerd.seed_cache_bytes``), evicting
    least-recently-used digests -- a long-lived daemon hosting many runs
    must not pin every seed it ever saw.  In-memory only: a killed
    daemon loses the store, and the launch path degrades to the
    per-create fallback (the scheduler's host-side cache still bounds
    the cost to one tar build)."""

    def __init__(self, max_bytes: int):
        self.max_bytes = max(0, int(max_bytes))
        self._entries: collections.OrderedDict[str, bytes] = \
            collections.OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()

    def put(self, digest: str, tar: bytes) -> bool:
        """Store one seed; returns False when the tar alone exceeds the
        cap (stored nothing -- callers fall back per-create)."""
        if len(tar) > self.max_bytes:
            return False
        with self._lock:
            old = self._entries.pop(digest, None)
            if old is not None:
                self._bytes -= len(old)
            while self._entries and self._bytes + len(tar) > self.max_bytes:
                _, evicted = self._entries.popitem(last=False)
                self._bytes -= len(evicted)
            self._entries[digest] = tar
            self._bytes += len(tar)
            return True

    def get(self, digest: str) -> bytes | None:
        with self._lock:
            tar = self._entries.get(digest)
            if tar is not None:
                self._entries.move_to_end(digest)
            return tar

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0

    @property
    def bytes_held(self) -> int:
        with self._lock:
            return self._bytes

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


class WorkerdServer:
    """Serve one worker's launch data plane on a unix socket.

    ``engine`` must be the LOCAL view of the worker's daemon: the
    direct unix socket on a real host, ``FakeDriver.local_engine(i)``
    on the fake pod (pays injected faults, never the injected WAN rtt).
    ``driver`` is optional; when given, creates run the same
    pre/post-start bootstrap hooks the in-process scheduler wires.
    """

    def __init__(self, cfg, engine, *, worker_id: str = "worker",
                 sock_path=None, driver=None, seams=None,
                 flush_window_s: float = FLUSH_WINDOW_S):
        from . import socket_path as default_sock

        self.cfg = cfg
        self.engine = engine
        self.driver = driver
        self.worker_id = worker_id
        self.sock_path = (sock_path if sock_path is not None
                          else default_sock(cfg))
        self.seams = seams if seams is not None else NULL_SEAMS
        self.flush_window_s = flush_window_s
        self.executed: dict[tuple, str] = {}    # dedup: intent key -> state
        try:
            seed_cap = int(cfg.settings.workerd.seed_cache_bytes)
        except AttributeError:
            seed_cap = 64 * 1024 * 1024
        self.seeds = SeedStore(seed_cap)
        # distributed tracing (docs/tracing.md): worker-side phase
        # timings become real remote SpanRecords in a per-daemon flight
        # recorder; the cumulative clock offset to the root clock
        # arrives on resync frames and is stamped on every span as
        # ``skew_s`` so the merge's adjustment is auditable
        self.trace_skew_s = 0.0
        self.flight = None
        try:
            tele = cfg.settings.telemetry
            if tele.tracing.enable and tele.flight_recorder.enable:
                from pathlib import Path as _P

                from ..monitor.ledger import FLIGHT_DIR, FlightRecorder
                self.flight = FlightRecorder(
                    _P(cfg.logs_dir) / FLIGHT_DIR
                    / f"workerd-{worker_id}.jsonl",
                    max_bytes=tele.flight_recorder.max_bytes)
        except AttributeError:
            self.flight = None
        self.stats = {"intents": 0, "events": 0, "batches": 0,
                      "dedup_hits": 0, "resyncs": 0,
                      "seeds_stored": 0, "seed_hits": 0, "seed_misses": 0}
        self._q: queue.SimpleQueue = queue.SimpleQueue()   # the local lane
        self._events: collections.deque = collections.deque()
        self._ev_lock = threading.Lock()
        self._ev_cond = threading.Condition(self._ev_lock)
        self._sink: socket.socket | None = None   # the live event channel
        self._sink_lock = threading.Lock()        # guards the POINTER only
        self._write_lock = threading.Lock()       # serializes frame writes
        #   (a length-prefixed stream corrupts if two writers interleave).
        #   Kept separate from _sink_lock on purpose: a writer can block
        #   inside write_msg when the peer stalls, and drop_conns/stop
        #   must still be able to clear the pointer and shut the socket
        #   down -- the shutdown is what unblocks the writer
        self._listener: socket.socket | None = None
        self._conns: set[socket.socket] = set()
        self._conns_lock = threading.Lock()
        self._stop = threading.Event()
        self._aborted = False
        self._waited: set[tuple[str, int]] = set()   # (cid, iteration)
        self._started_at = 0.0

    # ----------------------------------------------------------- lifecycle

    def start(self) -> "WorkerdServer":
        rt = self.sock_path.parent
        rt.mkdir(parents=True, exist_ok=True)
        os.chmod(rt, 0o700)
        if self.sock_path.exists():
            if self._socket_answers():
                raise WorkerdError(
                    f"workerd already running on {self.sock_path}")
            self.sock_path.unlink()
        listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        old_umask = os.umask(0o177)     # cover the bind itself
        try:
            listener.bind(str(self.sock_path))
        finally:
            os.umask(old_umask)
        os.chmod(self.sock_path, 0o600)
        listener.listen(16)
        self._listener = listener
        self._started_at = time.monotonic()
        try:
            from . import pidfile_path, socket_path

            # only the canonical one-daemon-per-host deployment owns
            # the pidfile (the wedged-daemon stop fallback); in-process
            # pods on explicit sockets share a cfg and must not clobber
            if self.sock_path == socket_path(self.cfg):
                pidfile_path(self.cfg).parent.mkdir(parents=True,
                                                    exist_ok=True)
                pidfile_path(self.cfg).write_text(str(os.getpid()))
                self._owns_pidfile = True
        except OSError:
            pass        # never a startup requirement
        threading.Thread(target=self._lane, daemon=True,
                         name=f"workerd-lane-{self.worker_id}").start()
        threading.Thread(target=self._flusher, daemon=True,
                         name=f"workerd-flush-{self.worker_id}").start()
        threading.Thread(target=self._accept_loop, daemon=True,
                         name=f"workerd-accept-{self.worker_id}").start()
        log.info("workerd for %s listening on %s (pid %d)",
                 self.worker_id, self.sock_path, os.getpid())
        return self

    def _socket_answers(self) -> bool:
        try:
            with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
                s.settimeout(1.0)
                s.connect(str(self.sock_path))
                protocol.write_msg(s, {"type": "ping"})
                return protocol.read_msg(s).get("type") == "pong"
        except (OSError, ClawkerError):
            return False

    def stop(self) -> None:
        """Graceful stop: close the listener, unlink the socket, let the
        lane drain.  In-flight waiters die with the process; the
        scheduler's degrade path (direct polling) covers their exits."""
        if self._stop.is_set():
            return
        self._stop.set()
        self._q.put(None)
        self._close_listener(unlink=True)
        self.drop_conns()
        with self._ev_cond:
            self._ev_cond.notify_all()
        if getattr(self, "_owns_pidfile", False):
            try:
                from . import pidfile_path

                pidfile_path(self.cfg).unlink(missing_ok=True)
            except OSError:
                pass
        if self.flight is not None:
            self.flight.close()
        log.info("workerd for %s stopped", self.worker_id)

    def kill(self) -> None:
        """Simulate daemon SIGKILL (the chaos ``workerd_kill`` fault):
        freeze execution and drop every connection mid-frame.  The
        socket FILE stays behind, exactly as a real SIGKILL leaves it --
        liveness probes read it as ``degraded``."""
        self._aborted = True
        self._stop.set()
        self._q.put(None)
        self._close_listener(unlink=False)
        self.drop_conns()
        with self._ev_cond:
            self._events.clear()        # a killed process loses its buffer
            self._ev_cond.notify_all()
        self.seeds.clear()              # ...and its in-memory seed store
        if self.flight is not None:
            # the recorder FILE stays behind (a real SIGKILL leaves it);
            # spans already flushed are the surviving trace segment, and
            # anything in flight is the gap the merge marks
            self.flight.close()

    def drop_conns(self) -> None:
        """Hard-drop every client connection (the chaos
        ``workerd_partition`` fault: the mux channel dies, the daemon
        lives).  Buffered events survive and flush after resync."""
        with self._conns_lock:
            conns, self._conns = set(self._conns), set()
        with self._sink_lock:
            self._sink = None
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass

    def _close_listener(self, *, unlink: bool) -> None:
        listener, self._listener = self._listener, None
        if listener is not None:
            try:
                with socket.socket(socket.AF_UNIX,
                                   socket.SOCK_STREAM) as s:
                    s.settimeout(0.5)
                    s.connect(str(self.sock_path))
            except OSError:
                pass
            try:
                listener.close()
            except OSError:
                pass
        if unlink:
            try:
                self.sock_path.unlink(missing_ok=True)
            except OSError:
                pass

    # ------------------------------------------------------------- serving

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            listener = self._listener
            if listener is None:
                return
            try:
                conn, _ = listener.accept()
            except OSError:
                return
            if self._stop.is_set() or self._listener is None:
                try:
                    conn.close()
                except OSError:
                    pass
                return
            with self._conns_lock:
                self._conns.add(conn)
            threading.Thread(target=self._handle_conn, args=(conn,),
                             daemon=True, name="workerd-conn").start()

    def _handle_conn(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(None)
            while not self._stop.is_set():
                try:
                    msg = protocol.read_msg(conn)
                except (protocol.ConnectionClosed, OSError):
                    return
                kind = msg.get("type", "")
                if kind == "hello":
                    # NOTE: the event sink opens at resync, not hello --
                    # the client's handshake reads deterministically
                    # (hello_ack, then events*, then resync_ack).  ``ts``
                    # turns the round-trip the client already pays into
                    # one clock-skew sample (docs/tracing.md#clock-skew)
                    self._reply(conn, {
                        "type": "hello_ack", "pid": os.getpid(),
                        "version": __version__, "worker": self.worker_id,
                        "ts": time.time()})
                elif kind == "ping":
                    self._reply(conn, {"type": "pong", "pid": os.getpid(),
                                       "worker": self.worker_id,
                                       "ts": time.time()})
                elif kind == "status":
                    self._reply(conn, self._status_doc())
                elif kind == "intents":
                    for intent in msg.get("batch") or []:
                        self._q.put(intent)
                elif kind == "resync":
                    self._handle_resync(conn, msg)
                elif kind == "shutdown":
                    self._reply(conn, {"type": "ok"})
                    threading.Thread(target=self.stop, daemon=True,
                                     name="workerd-shutdown").start()
                    return
                else:
                    self._reply(conn, {"type": "error",
                                       "error": f"unknown frame {kind!r}"})
        except (protocol.ProtocolError, OSError) as e:
            log.info("workerd connection dropped: %s", e)
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            with self._sink_lock:
                if self._sink is conn:
                    self._sink = None
            try:
                conn.close()
            except OSError:
                pass

    def _reply(self, conn, doc: dict) -> None:
        # unary replies share the frame-write lock with the event
        # flusher: two writers interleaving a length-prefixed stream
        # would corrupt it for good
        with self._write_lock:
            protocol.write_msg(conn, doc)

    def _status_doc(self) -> dict:
        with self._ev_lock:
            buffered = len(self._events)
        return {
            "type": "status", "pid": os.getpid(), "version": __version__,
            "worker": self.worker_id, "socket": str(self.sock_path),
            "uptime_s": round(time.monotonic() - self._started_at, 1),
            "buffered_events": buffered,
            "seed_store_bytes": self.seeds.bytes_held,
            "seed_store_entries": len(self.seeds),
            **{k: v for k, v in self.stats.items()},
        }

    def undelivered(self) -> int:
        """Events still waiting for a live channel (chaos invariant: a
        healed link must drain this to zero)."""
        with self._ev_lock:
            return len(self._events)

    # ------------------------------------------------------------- resync

    def _handle_resync(self, conn, msg: dict) -> None:
        """The reconnect handshake: compare the scheduler's view of
        running iterations against local container reality.  Still
        running -> re-arm the exit waiter; stopped -> report the exit
        the partition swallowed.  The ack precedes the buffered-event
        flush so the client can fire ``workerd.post_reconnect`` at the
        boundary the events replay across."""
        self.stats["resyncs"] += 1
        if msg.get("clock_offset_s") is not None:
            # the client's cumulative estimate of THIS daemon's clock
            # offset to the root clock (upstream offsets chained in) --
            # stamped on every span this daemon records from here on
            try:
                self.trace_skew_s = float(msg["clock_offset_s"])
            except (TypeError, ValueError):
                pass
        with self._sink_lock:
            self._sink = conn
        healed = 0
        for entry in msg.get("running") or []:
            agent = str(entry.get("agent", ""))
            epoch = int(entry.get("epoch", 0))
            iteration = int(entry.get("iteration", 0))
            cid = str(entry.get("cid", ""))
            if not cid:
                continue
            try:
                info = self.engine.inspect_container(cid)
                state = info.get("State") or {}
                running = bool(state.get("Running"))
            except NotFoundError:
                self._emit({"ev": "exited", "agent": agent, "epoch": epoch,
                            "iteration": iteration, "code": None,
                            "detail": "container vanished"})
                healed += 1
                continue
            except ClawkerError:
                continue        # local engine hiccup: the waiter retries
            if running:
                self._arm_waiter(agent, epoch, iteration, cid)
            else:
                code = state.get("ExitCode")
                self._emit({"ev": "exited", "agent": agent, "epoch": epoch,
                            "iteration": iteration,
                            "code": int(code) if code is not None else None,
                            "detail": ("" if code is not None
                                       else "stopped without exit code")})
                healed += 1
        self._reply(conn, {"type": "resync_ack", "healed": healed,
                           "buffered": self.undelivered(),
                           "ts": time.time()})
        with self._ev_cond:
            self._ev_cond.notify_all()      # flush the link-down backlog

    # ----------------------------------------------------------- the lane

    def _lane(self) -> None:
        """The worker-local serial lane: every engine mutation this
        daemon performs runs here, in intent order -- the same
        serialization contract the scheduler's per-worker lanes give
        the direct path."""
        while not self._stop.is_set():
            intent = self._q.get()
            if intent is None:
                return
            try:
                self._execute(intent)
            except Exception:       # noqa: BLE001 -- the lane must live
                log.exception("workerd intent crashed: %r", intent)

    def _execute(self, intent: dict) -> None:
        kind = str(intent.get("kind", ""))
        seq = int(intent.get("seq", 0))
        agent = str(intent.get("agent", ""))
        epoch = int(intent.get("epoch", 0))
        iteration = int(intent.get("iteration", 0))
        if kind not in INTENT_KINDS:
            self._emit({"ev": "failed", "seq": seq, "phase": "dispatch",
                        "error": f"unknown intent kind {kind!r}",
                        "driverish": False})
            return
        if kind == "seed":
            # naturally idempotent (a content-addressed put): skips the
            # positional dedup table, whose (agent, epoch, iteration) key
            # is meaningless for a digest-keyed transfer
            self.stats["intents"] += 1
            _INTENTS.labels(self.worker_id, kind).inc()
            self._do_seed(intent, seq)
            return
        key = (kind, agent, epoch, iteration)
        if kind in ("launch", "start", "create") and key in self.executed:
            # idempotence: a re-sent intent (client retry across a
            # partition) must never double-create or double-start
            self.stats["dedup_hits"] += 1
            return
        while len(self.executed) >= DEDUP_KEYS_KEPT:
            # FIFO eviction (dict order = insertion order): retries only
            # ever re-send RECENT intents, so the oldest keys are dead
            self.executed.pop(next(iter(self.executed)))
        self.executed[key] = "running"
        self.stats["intents"] += 1
        _INTENTS.labels(self.worker_id, kind).inc()
        try:
            if kind == "launch":
                self._do_launch(intent, seq, agent, epoch, iteration)
            elif kind == "start":
                self._do_start(intent, seq, agent, epoch, iteration)
            elif kind == "create":
                self._do_create_only(intent, seq)
            elif kind == "adopt":
                self._arm_waiter(agent, epoch, iteration,
                                 str(intent.get("cid", "")),
                                 tp=str(intent.get("tp", "")))
            elif kind == "halt":
                self._do_halt(intent)
        finally:
            self.executed[key] = "done"

    def _runtime(self):
        from ..runtime.orchestrate import AgentRuntime

        if self.driver is None:
            # in-process pods (tests/bench/chaos): the plain create path
            return AgentRuntime(self.engine, self.cfg)
        from ..controlplane.bootstrap import (
            post_start_services,
            pre_start_services,
        )
        from ..fleet.channels import open_side_channels

        channels = None
        try:
            channels = open_side_channels(self.engine, self.cfg)
        except Exception as e:      # noqa: BLE001 -- channels are optional
            log.info("workerd side channels unavailable: %s", e)
        return AgentRuntime(
            self.engine, self.cfg,
            pre_start=lambda ref: pre_start_services(
                self.cfg, self.driver, ref),
            post_start=lambda ref: post_start_services(
                self.cfg, self.driver, ref),
            channels=channels)

    def _do_seed(self, intent: dict, seq: int) -> None:
        """Stage a workspace seed in the worker-local store.  The ONE
        WAN transfer per (digest, worker): every launch that references
        the digest afterwards fans out over the local engine socket."""
        digest = str(intent.get("digest", ""))
        try:
            tar = protocol.unb64(str(intent.get("tar", "")))
        except (ValueError, TypeError):
            self._emit({"ev": "failed", "seq": seq, "phase": "seed",
                        "error": "undecodable seed tar", "driverish": False})
            return
        if not digest or not tar:
            self._emit({"ev": "failed", "seq": seq, "phase": "seed",
                        "error": "seed intent missing digest or tar",
                        "driverish": False})
            return
        stored = self.seeds.put(digest, tar)
        if stored:
            self.stats["seeds_stored"] += 1
        self._emit({"ev": "seeded", "seq": seq, "digest": digest,
                    "bytes": len(tar), "stored": stored})

    def drop_seeds(self) -> None:
        """Evict the whole seed store (the chaos ``seed_cache_evict``
        fault): later launches referencing a digest degrade to the
        per-create fallback path, never to an error."""
        self.seeds.clear()

    def _opts(self, doc: dict):
        from ..runtime.orchestrate import CreateOptions

        seed_digest = str(doc.get("seed_digest", ""))
        seed_tar = None
        if seed_digest:
            seed_tar = self.seeds.get(seed_digest)
            self.stats["seed_hits" if seed_tar is not None
                       else "seed_misses"] += 1
        return CreateOptions(
            agent=str(doc.get("agent", "dev")),
            image=str(doc.get("image", "@")),
            env={str(k): str(v) for k, v in (doc.get("env") or {}).items()},
            tty=bool(doc.get("tty", False)),
            workspace_mode=str(doc.get("workspace_mode", "")),
            worker=str(doc.get("worker", self.worker_id)),
            loop_id=str(doc.get("loop_id", "")),
            extra_labels={str(k): str(v) for k, v in
                          (doc.get("extra_labels") or {}).items()},
            replace=bool(doc.get("replace", True)),
            seed_digest=seed_digest,
            seed_tar=seed_tar)

    def _do_launch(self, intent: dict, seq: int, agent: str, epoch: int,
                   iteration: int) -> None:
        """create (or warm-pool adopt) + first start + exit waiter: the
        whole burst the direct path paid O(engine calls) WAN RTTs for,
        executed against the local socket."""
        opts = self._opts(intent.get("opts") or {})
        rt = self._runtime()
        tp = str(intent.get("tp", ""))
        t0 = time.monotonic()
        t0_wall = time.time()
        pool_cid = str(intent.get("pool_cid", ""))
        cid = ""
        pool_hit = False
        pool_error = ""
        sid = self._span_id(tp)
        try:
            with self._engine_ctx(tp, agent, sid):
                if pool_cid:
                    try:
                        # analyze: allow(wal-before-mutation): workerd
                        # executes intents the scheduler journaled
                        # write-ahead (REC_PLACEMENT durable before
                        # dispatch, the workerd.pre_dispatch seam) -- the
                        # WAL lives on the control-plane side of the channel
                        rt.adopt_pooled(pool_cid, opts)
                        cid = pool_cid
                        pool_hit = True
                    except ClawkerError as e:
                        pool_error = str(e)  # cold-create fallback below
                if not cid:
                    # analyze: allow(wal-before-mutation): intent WAL'd by
                    # the dispatching scheduler (see above)
                    cid = rt.create(opts)
        except ClawkerError as e:
            self._emit({"ev": "failed", "seq": seq, "phase": "create",
                        "error": str(e),
                        "driverish": isinstance(e, DriverError)})
            return
        self._emit({"ev": "created", "seq": seq, "cid": cid,
                    "pool": pool_hit, "pool_error": pool_error,
                    "ms": round((time.monotonic() - t0) * 1000, 3)})
        self._record_span(tp, SPAN_WORKERD_CREATE, agent, iteration,
                          t0_wall, time.time(), span_id=sid,
                          cid=cid, pool=pool_hit)
        self._start_cid(rt, seq, agent, epoch, iteration, cid, fresh=True,
                        state_doc=intent.get("state"), tp=tp)

    def _do_start(self, intent: dict, seq: int, agent: str, epoch: int,
                  iteration: int) -> None:
        cid = str(intent.get("cid", ""))
        rt = self._runtime()
        self._start_cid(rt, seq, agent, epoch, iteration, cid,
                        fresh=bool(intent.get("fresh", False)),
                        state_doc=intent.get("state"),
                        tp=str(intent.get("tp", "")))

    def _start_cid(self, rt, seq: int, agent: str, epoch: int,
                   iteration: int, cid: str, *, fresh: bool,
                   state_doc=None, tp: str = "") -> None:
        t0 = time.monotonic()
        t0_wall = time.time()
        sid = self._span_id(tp)
        try:
            with self._engine_ctx(tp, agent, sid):
                if state_doc:
                    # the per-iteration context file (scheduler's
                    # _write_iteration): advisory, never fatal
                    try:
                        # analyze: allow(wal-before-mutation): advisory
                        # write into a cid whose REC_CREATED the scheduler
                        # already journaled
                        self.engine.put_archive(
                            cid, str(state_doc.get("dir", "/run/clawker")),
                            protocol.unb64(str(state_doc.get("tar", ""))))
                    except ClawkerError:
                        pass
                if fresh:
                    # analyze: allow(wal-before-mutation): start intents
                    # are WAL'd scheduler-side before dispatch
                    # (docs/workerd.md)
                    rt.start(cid)
                else:
                    # analyze: allow(wal-before-mutation): same contract
                    # as the fresh branch above
                    self.engine.start_container(cid)
                    if rt.post_start:
                        rt.post_start(cid)
        except ClawkerError as e:
            self._emit({"ev": "failed", "seq": seq, "phase": "start",
                        "error": str(e),
                        "driverish": isinstance(e, DriverError)})
            return
        self._emit({"ev": "started", "seq": seq, "cid": cid,
                    "ms": round((time.monotonic() - t0) * 1000, 3)})
        self._record_span(tp, SPAN_WORKERD_START, agent, iteration,
                          t0_wall, time.time(), span_id=sid, cid=cid)
        self._arm_waiter(agent, epoch, iteration, cid, tp=tp)

    def _do_create_only(self, intent: dict, seq: int) -> None:
        """Warm-pool fill: the expensive create-time stages, no start."""
        opts = self._opts(intent.get("opts") or {})
        rt = self._runtime()
        t0 = time.monotonic()
        try:
            # analyze: allow(wal-before-mutation): pool-fill intents carry
            # a durable REC_POOL_ADD journaled by warmpool.begin_refill
            # before dispatch (docs/loop-warmpool.md)
            cid = rt.create(opts)
        except ClawkerError as e:
            self._emit({"ev": "failed", "seq": seq, "phase": "create",
                        "error": str(e),
                        "driverish": isinstance(e, DriverError)})
            return
        self._emit({"ev": "pool_ready", "seq": seq, "cid": cid,
                    "ms": round((time.monotonic() - t0) * 1000, 3)})

    def _do_halt(self, intent: dict) -> None:
        cid = str(intent.get("cid", ""))
        try:
            self.engine.stop_container(cid,
                                       timeout=int(intent.get("timeout", 2)))
        except ClawkerError:
            pass        # best effort, like the scheduler's own halts

    def _arm_waiter(self, agent: str, epoch: int, iteration: int,
                    cid: str, *, tp: str = "") -> None:
        """Local blocking wait -> unsolicited ``exited`` event.  The
        waiter is worker-resident, so an iteration's whole execute
        window costs the WAN nothing."""
        key = (cid, iteration)
        if not cid or key in self._waited:
            return
        self._waited.add(key)

        def wait() -> None:
            t0 = time.monotonic()
            t0_wall = time.time()
            code: int | None
            detail = ""
            try:
                code = int(self.engine.wait_container(cid))
            except NotFoundError:
                code, detail = None, "container vanished"
            except ClawkerError:
                # wait hiccup: one inspect decides (mirrors _read_exit)
                try:
                    state = self.engine.inspect_container(cid).get(
                        "State") or {}
                    raw = state.get("ExitCode")
                    code = int(raw) if raw is not None else None
                    detail = "" if raw is not None else \
                        "stopped without exit code"
                except ClawkerError as e:
                    code, detail = None, f"exit unreadable: {e}"
            self._waited.discard(key)
            self._emit({"ev": "exited", "agent": agent, "epoch": epoch,
                        "iteration": iteration, "code": code,
                        "detail": detail,
                        "wait_ms": round((time.monotonic() - t0) * 1000, 1)})
            self._record_span(
                tp, SPAN_WORKERD_WAIT, agent, iteration, t0_wall,
                time.time(), cid=cid,
                status="ok" if code == 0 else "failed")

        threading.Thread(target=wait, daemon=True,
                         name=f"workerd-wait-{cid[:12]}").start()

    # ------------------------------------------------------------ events

    def _record_span(self, tp: str, name: str, agent: str, iteration: int,
                     t_start: float, t_end: float, *, status: str = "ok",
                     span_id: str = "", **attrs) -> None:
        """One remote SpanRecord into the per-daemon flight recorder.
        ``tp`` is the intent's propagated traceparent (trace id = the
        run id; span id = the upstream parent, often "" because the
        scheduler opens the iteration root only when the created event
        lands -- the merge then attaches by (agent, iteration)).  An
        explicit ``span_id`` lets the engine-context path pre-announce
        this span's id to its own children.  No recorder / no context =
        no work."""
        if self.flight is None or self._aborted or not tp:
            return
        from ..telemetry.spans import SpanRecord
        from ..tracing.context import TraceContext
        from ..util import ids

        ctx = TraceContext.from_header(tp)
        if ctx is None:
            return
        self.flight.append(SpanRecord(
            trace_id=ctx.trace_id, span_id=span_id or ids.short_id(16),
            parent_id=ctx.span_id, name=name, agent=agent,
            worker=self.worker_id, t_start=t_start, t_end=t_end,
            status=status,
            attrs={"iteration": iteration,
                   "skew_s": round(self.trace_skew_s, 6),
                   **attrs}).to_json())

    def _span_id(self, tp: str) -> str:
        """Pre-generated span id for a phase about to run, or "" when
        its span would not record anyway."""
        if self.flight is None or self._aborted or not tp:
            return ""
        from ..util import ids
        return ids.short_id(16)

    def _engine_ctx(self, tp: str, agent: str, span_id: str):
        """Ambient trace context around one phase's LOCAL engine work:
        httpapi records ``engine.request`` children into this daemon's
        recorder, parented to the phase span whose id was pre-generated
        via :meth:`_span_id` and recorded when the phase ends."""
        if not span_id:
            return contextlib.nullcontext()
        from ..tracing.context import TraceContext, use

        ctx = TraceContext.from_header(tp)
        if ctx is None:
            return contextlib.nullcontext()
        return use(TraceContext(ctx.trace_id, span_id, agent=agent,
                                worker=self.worker_id,
                                sink=self._engine_sink))

    def _engine_sink(self, rec) -> None:
        if self.flight is None or self._aborted:
            return
        doc = rec.to_json()
        doc["attrs"] = {"skew_s": round(self.trace_skew_s, 6),
                        **doc["attrs"]}
        self.flight.append(doc)

    def _emit(self, ev: dict) -> None:
        if self._aborted:
            return      # a killed daemon publishes nothing
        self.stats["events"] += 1
        _EVENTS.labels(self.worker_id, str(ev.get("ev", "?"))).inc()
        with self._ev_cond:
            if len(self._events) >= EVENT_BUFFER:
                # bound the link-down backlog; exits dropped here are
                # re-derived by resync (engine state is the authority)
                self._events.popleft()
                _BUFFERED_DROPS.labels(self.worker_id).inc()
            self._events.append(ev)
            self._ev_cond.notify_all()

    def _flusher(self) -> None:
        """Coalesce buffered events into one frame per flush window --
        the O(1)-round-trips-per-batch half of the contract."""
        while not self._stop.is_set():
            with self._ev_cond:
                while not self._events and not self._stop.is_set():
                    self._ev_cond.wait(0.5)
                if self._stop.is_set():
                    return
            # coalesce: events landing inside the window join this batch
            if self.flush_window_s > 0:
                time.sleep(self.flush_window_s)
            with self._sink_lock:
                sink = self._sink
            if sink is not None:
                with self._ev_cond:
                    batch = list(self._events)
                    self._events.clear()
                if batch:
                    try:
                        with self._write_lock:
                            protocol.write_msg(
                                sink, {"type": "events", "batch": batch})
                        self.stats["batches"] += 1
                        _BATCHES.labels(self.worker_id).inc()
                    except (OSError, ClawkerError):
                        # channel died mid-write: put the batch back
                        # in order; resync will re-open the sink
                        with self._ev_cond:
                            self._events.extendleft(reversed(batch))
                        with self._sink_lock:
                            if self._sink is sink:
                                self._sink = None
            if self._sink is None:
                # link down: wait for a resync instead of spinning
                with self._ev_cond:
                    self._ev_cond.wait(0.05)
