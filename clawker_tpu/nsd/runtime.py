"""nsd container runtime: overlay rootfs + namespaced processes + IO hub.

One NsContainer per create.  The daemon (server.py) owns the registry;
this module owns everything that touches the kernel: overlay mounts,
the unshare+shim spawn, cgroup placement, signal-based stop semantics,
nsenter execs, archive IO against the merged rootfs and the multi-client
attach hub with Docker stdcopy framing.

Parity reference: the engine-facing behavior mirrors what the docker
middleware expects from dockerd (SURVEY.md 2.3); the runtime mechanics
are first-party (see package docstring).
"""

from __future__ import annotations

import ctypes
import fcntl
import io
import json
import os
import pty
import select
import shutil
import signal
import struct
import subprocess
import sys
import tarfile
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path

_libc = ctypes.CDLL(None, use_errno=True)

REPO_ROOT = str(Path(__file__).resolve().parent.parent.parent)

STDOUT, STDERR = 1, 2


def _now() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


def _cgroup_preexec(cg: Path | None):
    """Fork-time cgroup migration for container init AND execs.  Failing
    to join is FATAL (the exec aborts): proceeding outside the cgroup
    would silently escape the firewall's enforcement scope."""

    def pre_exec() -> None:
        if cg is not None:
            (cg / "cgroup.procs").write_text(str(os.getpid()))

    return pre_exec


def frame(stream: int, payload: bytes) -> bytes:
    """Docker stdcopy framing: [stream, 0, 0, 0, len_be32, payload]."""
    return bytes([stream, 0, 0, 0]) + struct.pack(">I", len(payload)) + payload


def _inside(p: Path | str, base: str) -> bool:
    """True when p is base or under base (separator-aware: /a/bc is NOT
    inside /a/b)."""
    sp = str(p)
    return sp == base or sp.startswith(base.rstrip("/") + "/")


class Hub:
    """Fan-out for one container's output + fan-in for its stdin.

    Clients attach before or after start; each gets the framed (or raw,
    for tty) byte stream from the moment it attached.  ``logs`` readers
    get the persisted file instead.
    """

    def __init__(self, log_path: Path, tty: bool):
        self.log_path = log_path
        self.tty = tty
        self._clients: list = []            # socket-like objects
        self._stdin = None                  # container stdin fd (master/pipe)
        self._log_f = None                  # persistent append handle
        self._lock = threading.Lock()

    def set_stdin(self, fd: int | None) -> None:
        with self._lock:
            self._stdin = fd

    def add_client(self, sock) -> None:
        with self._lock:
            self._clients.append(sock)

    def remove_client(self, sock) -> None:
        with self._lock:
            if sock in self._clients:
                self._clients.remove(sock)

    def write_stdin(self, data: bytes) -> None:
        with self._lock:
            fd = self._stdin
        if fd is not None:
            try:
                os.write(fd, data)
            except OSError:
                pass

    def broadcast(self, stream: int, payload: bytes) -> None:
        data = payload if self.tty else frame(stream, payload)
        with self._lock:
            if self._log_f is None:
                self._log_f = open(self.log_path, "ab")
            self._log_f.write(data)
            self._log_f.flush()
            clients = list(self._clients)
        for c in clients:
            try:
                c.sendall(data)
            except OSError:
                self.remove_client(c)

    def close_clients(self) -> None:
        with self._lock:
            clients, self._clients = list(self._clients), []
            if self._log_f is not None:
                try:
                    self._log_f.close()
                except OSError:
                    pass
                self._log_f = None
        for c in clients:
            try:
                c.shutdown(2)
            except OSError:
                pass


@dataclass
class NsContainer:
    id: str
    name: str
    config: dict                    # docker-shaped create config
    dir: Path                       # state dir: upper/work/merged/...
    cgroup_dir: Path | None
    state: str = "created"          # created|running|exited
    exit_code: int = 0
    created_at: str = field(default_factory=_now)
    started_at: str = ""
    finished_at: str = ""
    proc: subprocess.Popen | None = None
    init_pid: int = 0
    hub: Hub | None = None
    _waiter: threading.Thread | None = None
    _pumper: threading.Thread | None = None
    _exited: threading.Event = field(default_factory=threading.Event)

    # ------------------------------------------------------------- helpers

    @property
    def merged(self) -> Path:
        return self.dir / "merged"

    @property
    def labels(self) -> dict:
        return self.config.get("Labels") or {}

    @property
    def tty(self) -> bool:
        return bool(self.config.get("Tty"))

    def binds(self) -> list[str]:
        return list((self.config.get("HostConfig") or {}).get("Binds") or [])

    # ------------------------------------------------------------- inspect

    def inspect(self) -> dict:
        return {
            "Id": self.id,
            "Name": "/" + self.name,
            # first-party extension: where this container's cgroup lives,
            # so the firewall's CgroupResolver enrolls nsd containers
            # without daemon-specific path guessing
            "NsdCgroupDir": str(self.cgroup_dir) if self.cgroup_dir else "",
            "Created": self.created_at,
            "Config": json.loads(json.dumps(self.config)),
            "State": {
                "Status": self.state,
                "Running": self.state == "running",
                "Paused": False,
                "ExitCode": self.exit_code,
                "Pid": self.init_pid if self.state == "running" else 0,
                "StartedAt": self.started_at,
                "FinishedAt": self.finished_at,
            },
            "HostConfig": json.loads(json.dumps(
                self.config.get("HostConfig") or {})),
            "Mounts": [self._mount_inspect(b) for b in self.binds()],
            "NetworkSettings": {"Networks": {}, "IPAddress": "127.0.0.1"},
        }

    @staticmethod
    def _mount_inspect(bind: str) -> dict:
        parts = bind.split(":")
        src = parts[0]
        dst = parts[1] if len(parts) > 1 else parts[0]
        ro = len(parts) > 2 and "ro" in parts[2].split(",")
        return {"Type": "bind", "Source": src, "Destination": dst, "RW": not ro}

    def summary(self) -> dict:
        return {
            "Id": self.id,
            "Names": ["/" + self.name],
            "Image": self.config.get("Image", ""),
            "Labels": dict(self.labels),
            "State": self.state,
            "Status": self.state,
        }


class NsRuntime:
    """Kernel-facing operations for NsContainer instances."""

    def __init__(self, state_dir: Path, *, cgroup_root: Path | None = None):
        self.state_dir = Path(state_dir)
        self.state_dir.mkdir(parents=True, exist_ok=True)
        self.cgroup_root = cgroup_root

    # -------------------------------------------------------------- create

    def prepare(self, c: NsContainer) -> None:
        """Directories + overlay mount; the container gets a live merged
        rootfs at create time so put_archive works before start (the
        identity bootstrap tars material into created containers)."""
        for sub in ("upper", "work", "merged"):
            (c.dir / sub).mkdir(parents=True, exist_ok=True)
        self._mount_overlay(c)
        c.hub = Hub(c.dir / "container.log", c.tty)
        if c.cgroup_dir is not None:
            c.cgroup_dir.mkdir(parents=True, exist_ok=True)

    def _mount_overlay(self, c: NsContainer) -> None:
        if os.path.ismount(c.merged):
            return
        opts = (f"lowerdir=/,upperdir={c.dir / 'upper'},"
                f"workdir={c.dir / 'work'}")
        res = subprocess.run(
            ["mount", "-t", "overlay", "overlay", "-o", opts, str(c.merged)],
            capture_output=True, text=True)
        if res.returncode != 0:
            raise RuntimeError(f"overlay mount failed: {res.stderr.strip()}")

    # --------------------------------------------------------------- start

    def start(self, c: NsContainer, on_exit=None) -> None:
        if c.state == "running":
            return
        self._mount_overlay(c)
        shim_cfg = {
            "merged": str(c.merged),
            "binds": c.binds(),
            "hostname": c.config.get("Hostname") or c.name,
            "env": self._env_dict(c),
            "workdir": c.config.get("WorkingDir") or "/",
            "cmd": self._cmd(c),
            "tty": c.tty,
        }
        cfg_path = c.dir / "shim.json"
        cfg_path.write_text(json.dumps(shim_cfg))

        # --cgroup: the namespace captures at unshare time, AFTER the
        # preexec joined the container cgroup -- so the container's
        # cgroup view is rooted at its OWN cgroup and even a fresh
        # cgroup2 mount inside cannot reach (or move processes to) any
        # ancestor, sealing the move-yourself-out firewall escape
        argv = ["unshare", "--fork", "--pid", "--mount", "--uts", "--ipc",
                "--cgroup", "--kill-child",
                sys.executable, "-m", "clawker_tpu.nsd.shim", str(cfg_path)]
        spawn_env = {"PATH": os.environ.get("PATH", "/usr/bin:/bin"),
                     "PYTHONPATH": REPO_ROOT}
        pre_exec = _cgroup_preexec(c.cgroup_dir)

        if c.tty:
            master, slave = pty.openpty()
            c.proc = subprocess.Popen(
                argv, stdin=slave, stdout=slave, stderr=slave,
                env=spawn_env, start_new_session=True, preexec_fn=pre_exec,
                close_fds=True)
            os.close(slave)
            c.hub.set_stdin(master)
            pump_fds = [(master, STDOUT)]
        else:
            stdin_r, stdin_w = os.pipe()
            out_r, out_w = os.pipe()
            err_r, err_w = os.pipe()
            c.proc = subprocess.Popen(
                argv, stdin=stdin_r, stdout=out_w, stderr=err_w,
                env=spawn_env, start_new_session=True, preexec_fn=pre_exec,
                close_fds=True)
            for fd in (stdin_r, out_w, err_w):
                os.close(fd)
            c.hub.set_stdin(stdin_w)
            pump_fds = [(out_r, STDOUT), (err_r, STDERR)]

        c.state = "running"
        c.started_at = _now()
        c._exited.clear()
        c.init_pid = self._find_init_pid(c.proc.pid)
        c._pumper = threading.Thread(target=self._pump, args=(c, pump_fds),
                                     name=f"nsd-io-{c.id[:8]}", daemon=True)
        c._pumper.start()
        c._waiter = threading.Thread(target=self._wait, args=(c, on_exit),
                                     name=f"nsd-wait-{c.id[:8]}", daemon=True)
        c._waiter.start()

    def _env_dict(self, c: NsContainer) -> dict:
        out: dict[str, str] = {}
        for kv in c.config.get("Env") or []:
            k, _, v = kv.partition("=")
            out[k] = v
        return out

    def _cmd(self, c: NsContainer) -> list[str]:
        entry = c.config.get("Entrypoint") or []
        cmd = c.config.get("Cmd") or []
        argv = list(entry) + list(cmd)
        return argv or ["/bin/sh"]

    @staticmethod
    def _find_init_pid(unshare_pid: int, timeout: float = 3.0) -> int:
        """The container init = unshare's forked child (host-ns view)."""
        deadline = time.monotonic() + timeout
        children = Path(f"/proc/{unshare_pid}/task/{unshare_pid}/children")
        while time.monotonic() < deadline:
            try:
                kids = children.read_text().split()
            except OSError:
                return 0
            if kids:
                return int(kids[0])
            time.sleep(0.005)
        return 0

    def _pump(self, c: NsContainer, fds: list[tuple[int, int]]) -> None:
        open_fds = dict(fds)
        while open_fds:
            try:
                ready, _, _ = select.select(list(open_fds), [], [], 0.5)
            except OSError:
                break
            for fd in ready:
                try:
                    chunk = os.read(fd, 65536)
                except OSError:
                    chunk = b""
                if not chunk:
                    os.close(fd)
                    del open_fds[fd]
                    continue
                c.hub.broadcast(open_fds[fd], chunk)

    def _wait(self, c: NsContainer, on_exit) -> None:
        code = c.proc.wait()
        # dockerd convention: signal deaths report as 128+signum
        c.exit_code = code if code >= 0 else 128 - code
        c.state = "exited"
        c.finished_at = _now()
        stdin = c.hub._stdin
        c.hub.set_stdin(None)
        if stdin is not None:
            try:
                os.close(stdin)
            except OSError:
                pass
        # drain: the pump ends at fd EOF, which the exit guarantees
        if c._pumper is not None:
            c._pumper.join(timeout=2.0)
        c.hub.close_clients()
        c._exited.set()
        if on_exit:
            on_exit(c)

    # ------------------------------------------------------------- signals

    def stop(self, c: NsContainer, timeout: int = 10) -> None:
        """SIGTERM to the container init, SIGKILL after the grace period
        (kernel rule: only KILL/STOP reach a namespace init from outside
        unless it installed handlers -- same grace dance as dockerd)."""
        if c.state != "running":
            return
        if c.init_pid:
            try:
                os.kill(c.init_pid, signal.SIGTERM)
            except OSError:
                pass
        if not c._exited.wait(timeout):
            self.kill(c)
            c._exited.wait(5)

    def kill(self, c: NsContainer, sig: int = signal.SIGKILL) -> None:
        if c.state != "running":
            return
        for pid in (c.init_pid, c.proc.pid if c.proc else 0):
            if pid:
                try:
                    os.kill(pid, sig)
                except OSError:
                    pass

    def wait(self, c: NsContainer, timeout: float | None = None) -> int:
        c._exited.wait(timeout)
        return c.exit_code

    # -------------------------------------------------------------- remove

    def remove(self, c: NsContainer) -> None:
        if c.state == "running":
            self.kill(c)
            c._exited.wait(5)
        subprocess.run(["umount", "-l", str(c.merged)], capture_output=True)
        shutil.rmtree(c.dir, ignore_errors=True)
        if c.cgroup_dir is not None:
            try:
                c.cgroup_dir.rmdir()
            except OSError:
                pass

    # ------------------------------------------------------------- archive

    def put_archive(self, c: NsContainer, path: str, tar_bytes: bytes) -> None:
        self._mount_overlay(c)
        base, dest, ro = self._resolve_in_rootfs(c, path)
        if ro:
            # a `:ro` bind is a promise to the HOST: archive writes
            # resolve to the bind source, so honoring the flag here is
            # what keeps a read-only mount from being writable through
            # the API (ADVICE r5; dockerd 403s the same way)
            raise PermissionError(
                f"bind mounted read-only: {path}")
        dest.mkdir(parents=True, exist_ok=True)
        with tarfile.open(fileobj=io.BytesIO(tar_bytes)) as tf:
            for m in tf.getmembers():
                target = (dest / m.name).resolve()
                if not _inside(target, base):
                    raise RuntimeError(f"archive member escapes rootfs: {m.name}")
            # filter="data" closes the tar-slip TOCTOU the pre-check
            # cannot (symlink member + path THROUGH it resolves clean
            # before extraction creates the link)
            tf.extractall(dest, filter="data")

    def get_archive(self, c: NsContainer, path: str) -> bytes:
        _, src, _ro = self._resolve_in_rootfs(c, path)
        if not src.exists():
            raise FileNotFoundError(path)
        buf = io.BytesIO()
        with tarfile.open(fileobj=buf, mode="w") as tf:
            tf.add(src, arcname=src.name)
        return buf.getvalue()

    def _resolve_in_rootfs(self, c: NsContainer, path: str
                           ) -> tuple[str, Path, bool]:
        """-> (guard base, resolved host path, read_only).  Bind
        destinations shadow the overlay inside the container, so archive
        ops under a bind go to the bind SOURCE (dockerd resolves mounts
        the same way -- that is how volume seeding lands in the volume,
        not under the future mount point).  ``read_only`` reports the
        winning bind's ``:ro`` option so writers can refuse instead of
        writing through to the host source."""
        norm = "/" + path.strip("/")
        best: tuple[str, str, bool] | None = None
        for b in c.binds():
            parts = b.split(":")
            if len(parts) < 2 or not parts[0].startswith("/"):
                continue
            src, dst = parts[0], "/" + parts[1].strip("/")
            opts = parts[2] if len(parts) > 2 else ""
            if norm == dst or norm.startswith(dst + "/"):
                if best is None or len(dst) > len(best[1]):
                    best = (src, dst, "ro" in opts.split(","))
        if best is not None:
            base = str(Path(best[0]).resolve())
            p = (Path(base) / norm[len(best[1]):].lstrip("/")).resolve()
            ro = best[2]
        else:
            base = str(c.merged.resolve())
            p = (c.merged / norm.lstrip("/")).resolve()
            ro = False
        if not _inside(p, base):
            raise RuntimeError(f"path escapes rootfs: {path}")
        return base, p, ro

    # ---------------------------------------------------------------- exec

    def exec_spawn(self, c: NsContainer, config: dict) -> subprocess.Popen:
        """nsenter into the container's namespaces; caller pumps IO."""
        if c.state != "running" or not c.init_pid:
            raise RuntimeError("container is not running")
        cmd = config.get("Cmd") or ["/bin/sh"]
        wd = config.get("WorkingDir") or "/"
        env = {}
        for kv in config.get("Env") or []:
            k, _, v = kv.partition("=")
            env[k] = v
        argv = ["nsenter", "-t", str(c.init_pid), "-m", "-u", "-i", "-p",
                f"--wdns={wd}", "env", "-"]
        base_env = self._env_dict(c)
        base_env.setdefault("PATH", "/usr/local/sbin:/usr/local/bin:"
                                    "/usr/sbin:/usr/bin:/sbin:/bin")
        for k, v in {**base_env, **env}.items():
            argv.append(f"{k}={v}")
        argv += list(cmd)
        tty = bool(config.get("Tty"))
        # execs belong to the CONTAINER's cgroup (docker semantics):
        # the egress firewall keys enforcement on it
        pre_exec = _cgroup_preexec(c.cgroup_dir)

        if tty:
            master, slave = pty.openpty()
            p = subprocess.Popen(argv, stdin=slave, stdout=slave,
                                 stderr=slave, start_new_session=True,
                                 preexec_fn=pre_exec, close_fds=True)
            os.close(slave)
            p.nsd_io = (master, None, None)  # type: ignore[attr-defined]
        else:
            p = subprocess.Popen(argv, stdin=subprocess.PIPE,
                                 stdout=subprocess.PIPE,
                                 stderr=subprocess.PIPE,
                                 preexec_fn=pre_exec, close_fds=True)
            p.nsd_io = None  # type: ignore[attr-defined]
        return p

    # ----------------------------------------------------------------- tty

    def resize(self, c: NsContainer, rows: int, cols: int) -> None:
        fd = c.hub._stdin if c.tty else None
        if fd is None:
            return
        try:
            fcntl.ioctl(fd, 0x5414,  # TIOCSWINSZ
                        struct.pack("HHHH", rows, cols, 0, 0))
        except OSError:
            pass
