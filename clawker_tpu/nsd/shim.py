"""nsd in-container bootstrap: runs as PID 1 inside the fresh namespaces.

Invoked by runtime.py as::

    unshare --fork --pid --mount --uts --ipc --kill-child \
        python -m clawker_tpu.nsd.shim <config.json>

By the time this module runs, the kernel has already given us new PID /
mount / UTS / IPC namespaces.  The shim finishes the container: private
mount propagation, bind mounts (volumes + user binds) into the merged
overlay rootfs, fresh /proc, host /dev, pivot_root, hostname, env, cwd,
then exec of the container command -- which therefore IS PID 1's
process image, exactly like the reference's clawkerd-as-PID-1 model.

Everything here must stay dependency-free (json/os/ctypes only): it
executes before the container exists.
"""

from __future__ import annotations

import ctypes
import json
import os
import sys

MS_BIND = 0x1000
MS_REC = 0x4000
MS_PRIVATE = 0x40000
MS_RDONLY = 0x1
MS_REMOUNT = 0x20
MNT_DETACH = 0x2

_libc = ctypes.CDLL(None, use_errno=True)


def _mount(src: str, dst: str, fstype: str, flags: int, data: str = "") -> None:
    ret = _libc.mount(src.encode(), dst.encode(), fstype.encode() or None,
                      flags, data.encode() or None)
    if ret != 0:
        err = ctypes.get_errno()
        raise OSError(err, f"mount {src} -> {dst} ({fstype}): {os.strerror(err)}")


def _umount2(target: str, flags: int) -> None:
    if _libc.umount2(target.encode(), flags) != 0:
        err = ctypes.get_errno()
        raise OSError(err, f"umount {target}: {os.strerror(err)}")


def _remount_ro(target: str) -> None:
    """Remount a just-bound target read-only, recursively where the
    kernel can.  MS_REMOUNT|MS_BIND with MS_REC needs Linux >= 4.10 (and
    some LTS kernels reject it with EINVAL regardless); on those the
    non-recursive remount still protects the bind itself -- better than
    aborting container start over an `:ro` option (ADVICE r5)."""
    import errno

    try:
        _mount("none", target, "", MS_BIND | MS_REMOUNT | MS_RDONLY | MS_REC)
    except OSError as e:
        if e.errno != errno.EINVAL:
            raise
        _mount("none", target, "", MS_BIND | MS_REMOUNT | MS_RDONLY)


def _pivot_root(new_root: str, put_old: str) -> None:
    SYS_pivot_root = 155  # x86_64
    if _libc.syscall(SYS_pivot_root, new_root.encode(), put_old.encode()) != 0:
        err = ctypes.get_errno()
        raise OSError(err, f"pivot_root: {os.strerror(err)}")


def main(argv: list[str]) -> int:
    cfg = json.loads(open(argv[0], encoding="utf-8").read())
    merged = cfg["merged"]

    # 1. nothing we mount may leak back to the host
    _mount("none", "/", "", MS_REC | MS_PRIVATE)

    # 2. essential kernel filesystems inside the new rootfs
    _mount("proc", os.path.join(merged, "proc"), "proc", 0)
    _mount("/dev", os.path.join(merged, "dev"), "", MS_BIND | MS_REC)
    try:
        # /sys NON-recursively (host cgroupfs and friends stay OUT of
        # the container) and read-only: a root process writing host
        # cgroup.procs through a recursive RW bind could move itself
        # out of its enforcement cgroup (docker mounts sysfs ro too)
        sys_dst = os.path.join(merged, "sys")
        _mount("/sys", sys_dst, "", MS_BIND)
        _mount("none", sys_dst, "", MS_BIND | MS_REMOUNT | MS_RDONLY)
    except OSError:
        pass  # sysfs is a nicety, not a requirement

    # 3. volumes + user binds ("src:dst[:opts]")
    for bind in cfg.get("binds", []):
        parts = bind.split(":")
        if len(parts) < 2:
            continue
        src, dst = parts[0], parts[1]
        opts = parts[2] if len(parts) > 2 else ""
        target = os.path.join(merged, dst.lstrip("/"))
        if os.path.isdir(src):
            os.makedirs(target, exist_ok=True)
        else:
            os.makedirs(os.path.dirname(target), exist_ok=True)
            if not os.path.exists(target):
                open(target, "a").close()
        _mount(src, target, "", MS_BIND | MS_REC)
        if "ro" in opts.split(","):
            _remount_ro(target)

    # 4. become the rootfs
    old = os.path.join(merged, ".old_root")
    os.makedirs(old, exist_ok=True)
    os.chdir(merged)
    _pivot_root(".", ".old_root")
    os.chdir("/")
    _umount2("/.old_root", MNT_DETACH)
    try:
        os.rmdir("/.old_root")
    except OSError:
        pass

    # 5. identity + environment
    hostname = cfg.get("hostname", "")
    if hostname:
        _libc.sethostname(hostname.encode(), len(hostname))
    env = dict(cfg.get("env") or {})
    env.setdefault("PATH", "/usr/local/sbin:/usr/local/bin:/usr/sbin:"
                           "/usr/bin:/sbin:/bin")
    env.setdefault("HOSTNAME", hostname)
    workdir = cfg.get("workdir") or "/"
    os.makedirs(workdir, exist_ok=True)
    os.chdir(workdir)
    if cfg.get("tty"):
        import fcntl
        import termios

        try:
            fcntl.ioctl(0, termios.TIOCSCTTY, 1)
        except OSError:
            pass

    argv_out = cfg["cmd"]
    try:
        os.execvpe(argv_out[0], argv_out, env)
    except OSError as e:
        sys.stderr.write(f"nsd shim: exec {argv_out[0]!r}: {e}\n")
        return 127


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
