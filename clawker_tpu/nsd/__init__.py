"""nsd: a native namespace container daemon speaking the Docker API.

The e2e tier (tests/e2e) drives the real CLI against "one real local
daemon" -- the reference assumes dockerd.  TPU-VM worker images (and
this build environment) often have no Docker at all, but they DO have a
root Linux kernel, which is all a container runtime actually needs.
nsd serves the Docker Engine REST API subset the framework's client
(engine/httpapi.py) speaks, over a unix socket, backed by first
principles:

  rootfs     overlayfs upper/work per container over the host root
             (copy-on-write: container writes never touch the host)
  isolation  unshare(1): PID + mount + UTS + IPC namespaces; pivot_root
             into the merged rootfs; fresh /proc; host /dev bind
  cgroups    one cgroup-v2 dir per container (joined pre-exec, so the
             egress firewall's BPF programs attach to real containers)
  lifecycle  create/start/stop/kill/wait/rm/rename/inspect/list
  io         PTY or pipe pumping into stdcopy-framed logs; multi-client
             attach (before or after start); resize; exec via nsenter
  data       put/get archive against the merged rootfs; named volumes
             as bind-mounted directories; events stream

This is an e2e/dev runtime for disposable hosts (it runs containers as
root with the HOST filesystem as the read-only lower layer), not a
production substitute for the hardened docker/TPU-VM drivers -- the
point is that `CLAWKER_TPU_E2E=1 pytest tests/e2e` executes REAL
create/attach/exec/rm against a real kernel with zero external daemons.

Parity reference: the reference's e2e confidence comes from suites run
against dockerd (test/e2e/harness/factory.go:95); nsd replaces that
external dependency with ~1k lines of first-party runtime, the way the
rest of this framework replaces Ory/CoreDNS with first-party designs.
"""

from .server import NsDaemon, serve  # noqa: F401
