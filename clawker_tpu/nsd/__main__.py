"""``python -m clawker_tpu.nsd`` -- run the namespace container daemon.

Serves the Docker Engine API subset on a unix socket; point DOCKER_HOST
(or settings runtime.docker_host) at it and the ``local`` driver works
unchanged:

    python -m clawker_tpu.nsd --socket /run/clawker/nsd.sock \
        --state-dir /var/lib/clawker-nsd

Root + cgroup-v2 + overlayfs are required (see package docstring).
"""

from __future__ import annotations

import argparse
import os
import sys

from .server import serve


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="python -m clawker_tpu.nsd")
    ap.add_argument("--socket", default=os.environ.get(
        "CLAWKER_TPU_NSD_SOCKET", "/run/clawker/nsd.sock"))
    ap.add_argument("--state-dir", default=os.environ.get(
        "CLAWKER_TPU_NSD_STATE", "/var/lib/clawker-nsd"))
    args = ap.parse_args(argv)
    if os.geteuid() != 0:
        print("nsd: must run as root (namespaces + overlay + cgroups)",
              file=sys.stderr)
        return 1
    print(f"nsd: serving {args.socket} (state {args.state_dir})",
          file=sys.stderr)
    serve(args.state_dir, args.socket)
    return 0


if __name__ == "__main__":
    sys.exit(main())
