"""nsd HTTP server: the Docker Engine REST surface over a unix socket.

A deliberately small, dependency-free HTTP/1.1 server (http.server
cannot hijack connections, which attach/exec require): one thread per
connection, regex routing, JSON responses, raw-stream upgrades.

Surface implemented = exactly what engine/httpapi.py speaks (the
framework's own client); anything else 404s loudly.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import re
import secrets
import select
import signal
import socket
import struct
import threading
import time
import urllib.parse
from pathlib import Path

from .runtime import NsContainer, NsRuntime, frame

_REQ_LINE = re.compile(rb"^(\w+) ([^ ]+) HTTP/1\.[01]$")


def _now() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


class HttpError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status


class Request:
    def __init__(self, method: str, path: str, query: dict, headers: dict,
                 body: bytes, sock: socket.socket):
        self.method = method
        self.path = path
        self.query = query
        self.headers = headers
        self.body = body
        self.sock = sock
        self.hijacked = False

    def json(self):
        return json.loads(self.body) if self.body else {}

    def qbool(self, key: str, default: bool = False) -> bool:
        v = self.query.get(key)
        if v is None:
            return default
        return v not in ("0", "false", "")

    # ------------------------------------------------------------ hijack

    def upgrade(self) -> socket.socket:
        """Answer 101 (dockerd's upgrade form) and hand over the socket.
        The client side reads the raw stream past the headers
        (HijackedStream handles the 1xx zero-length quirk)."""
        self.sock.sendall(
            b"HTTP/1.1 101 UPGRADED\r\n"
            b"Content-Type: application/vnd.docker.raw-stream\r\n"
            b"Connection: Upgrade\r\nUpgrade: tcp\r\n\r\n")
        self.hijacked = True
        return self.sock

    def stream_headers(self, content_type: str = "application/octet-stream") -> None:
        """Answer 200 with no length: body streams until close."""
        self.sock.sendall(
            f"HTTP/1.1 200 OK\r\nContent-Type: {content_type}\r\n"
            f"Connection: close\r\n\r\n".encode())
        self.hijacked = True  # caller owns the socket from here


class NsDaemon:
    """Registry + router.  One instance == one daemon endpoint."""

    def __init__(self, state_dir: str | Path, socket_path: str | Path,
                 *, cgroup_root: str | Path | None = None):
        self.state_dir = Path(state_dir)
        self.socket_path = Path(socket_path)
        cgr = cgroup_root if cgroup_root is not None else self._find_cgroup_root()
        self.runtime = NsRuntime(self.state_dir / "containers",
                                 cgroup_root=Path(cgr) if cgr else None)
        self.containers: dict[str, NsContainer] = {}
        self.volumes: dict[str, dict] = {}
        self.images: dict[str, dict] = {}
        self.networks: dict[str, dict] = {}
        self.execs: dict[str, dict] = {}
        self._subscribers: list = []
        self._lock = threading.RLock()
        self._server_sock: socket.socket | None = None
        self._stop = threading.Event()

    @staticmethod
    def _find_cgroup_root() -> Path | None:
        try:
            from ..firewall.bpfkern import cgroup2_root

            root = cgroup2_root()
        except Exception:  # noqa: BLE001
            return None
        if root is None:
            return None
        d = root / "clawker-nsd"
        try:
            d.mkdir(exist_ok=True)
        except OSError:
            return None
        return d

    # ------------------------------------------------------------- events

    def _event(self, typ: str, action: str, actor_id: str,
               attrs: dict | None = None) -> None:
        ev = {"Type": typ, "Action": action, "status": action,
              "id": actor_id, "time": int(time.time()),
              "Actor": {"ID": actor_id, "Attributes": attrs or {}}}
        data = json.dumps(ev).encode() + b"\n"
        with self._lock:
            subs = list(self._subscribers)
        for s in subs:
            try:
                s.sendall(data)
            except OSError:
                with self._lock:
                    if s in self._subscribers:
                        self._subscribers.remove(s)

    # ------------------------------------------------------------ helpers

    def _find(self, ref: str) -> NsContainer:
        with self._lock:
            c = self.containers.get(ref)
            if c is not None:
                return c
            for c in self.containers.values():
                if c.name == ref or c.id.startswith(ref):
                    return c
        raise HttpError(404, f"No such container: {ref}")

    def _match_filters(self, c: NsContainer, filters: dict) -> bool:
        for key, wants in (filters or {}).items():
            if isinstance(wants, dict):  # docker also allows map form
                wants = [k for k, v in wants.items() if v]
            if key == "label":
                for want in wants:
                    k, _, v = want.partition("=")
                    if k not in c.labels or (v and c.labels[k] != v):
                        return False
            elif key == "name":
                if not any(w in c.name for w in wants):
                    return False
            elif key == "status":
                if c.state not in wants:
                    return False
        return True

    def _resolve_bind(self, bind: str) -> str:
        """Volume-name sources become their mountpoints (auto-created,
        docker semantics); absolute paths pass through."""
        src, sep, rest = bind.partition(":")
        if src.startswith("/") or not sep:
            return bind
        vol = self._ensure_volume(src, {})
        return vol["Mountpoint"] + sep + rest

    def _ensure_volume(self, name: str, labels: dict) -> dict:
        with self._lock:
            vol = self.volumes.get(name)
            if vol is None:
                mp = self.state_dir / "volumes" / name
                mp.mkdir(parents=True, exist_ok=True)
                vol = {"Name": name, "Driver": "local",
                       "Mountpoint": str(mp), "Labels": labels or {},
                       "CreatedAt": _now(), "Scope": "local"}
                self.volumes[name] = vol
            return vol

    # ---------------------------------------------------------- lifecycle

    # shared parent dirs whose modes nsd must never narrow (a socket
    # configured directly under one of these is the operator's call;
    # the DEFAULT layout is a dedicated /run/clawker)
    _SHARED_DIRS = frozenset(
        {"/", "/run", "/var", "/var/run", "/var/lib", "/tmp", "/var/tmp",
         "/dev", "/dev/shm", "/home", "/root"})

    def serve(self) -> None:
        # The socket is ROOT-EQUIVALENT (full container control on a
        # daemon that runs as root with namespaces): it must never
        # inherit a permissive umask.  Bind under umask 0o177 (no
        # group/other bits even for the creation instant), then pin the
        # socket to 0600 and its dedicated parent dir to 0700 --
        # ADVICE round 5.
        parent = self.socket_path.parent
        parent.mkdir(parents=True, exist_ok=True)
        if self.socket_path.exists():
            self.socket_path.unlink()
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        old_umask = os.umask(0o177)
        try:
            srv.bind(str(self.socket_path))
        finally:
            os.umask(old_umask)
        os.chmod(self.socket_path, 0o600)
        if str(parent) not in self._SHARED_DIRS:
            try:
                os.chmod(parent, 0o700)
            except OSError:
                pass    # not ours to narrow (ro mount, foreign owner)
        srv.listen(64)
        srv.settimeout(0.5)
        self._server_sock = srv
        while not self._stop.is_set():
            try:
                conn, _ = srv.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            threading.Thread(target=self._handle_conn, args=(conn,),
                             daemon=True).start()
        srv.close()

    def shutdown(self) -> None:
        self._stop.set()
        # full teardown: overlay mounts must not outlive the daemon (a
        # leftover merged mount makes the state dir un-removable)
        for c in list(self.containers.values()):
            try:
                self.runtime.remove(c)
            except Exception:  # noqa: BLE001 - best-effort teardown
                if c.state == "running":
                    self.runtime.kill(c)
        self.containers.clear()

    # ----------------------------------------------------------- http i/o

    def _handle_conn(self, sock: socket.socket) -> None:
        try:
            req = self._read_request(sock)
            if req is None:
                return
            try:
                self._route(req)
            except HttpError as e:
                if not req.hijacked:
                    self._respond(sock, e.status, {"message": str(e)})
            except Exception as e:  # noqa: BLE001 - daemon must survive
                if not req.hijacked:
                    self._respond(sock, 500, {"message": f"{e.__class__.__name__}: {e}"})
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def _read_request(self, sock: socket.socket) -> Request | None:
        sock.settimeout(30)
        buf = b""
        while b"\r\n\r\n" not in buf:
            chunk = sock.recv(65536)
            if not chunk:
                return None
            buf += chunk
            if len(buf) > 1 << 20:
                return None
        head, _, rest = buf.partition(b"\r\n\r\n")
        lines = head.split(b"\r\n")
        m = _REQ_LINE.match(lines[0])
        if m is None:
            return None
        method = m.group(1).decode()
        target = m.group(2).decode()
        headers = {}
        for ln in lines[1:]:
            k, _, v = ln.decode("latin-1").partition(":")
            headers[k.strip().lower()] = v.strip()
        length = int(headers.get("content-length", "0") or "0")
        body = rest
        while len(body) < length:
            chunk = sock.recv(min(1 << 20, length - len(body)))
            if not chunk:
                break
            body += chunk
        parsed = urllib.parse.urlsplit(target)
        path = re.sub(r"^/v\d+\.\d+", "", parsed.path)
        multi = urllib.parse.parse_qs(parsed.query)
        query = {k: v[-1] for k, v in multi.items()}
        sock.settimeout(None)
        req = Request(method, path, query, headers, body, sock)
        req.query_multi = multi
        return req

    @staticmethod
    def _respond(sock: socket.socket, status: int, body=None, *,
                 raw: bytes | None = None,
                 content_type: str = "application/json") -> None:
        reasons = {200: "OK", 201: "Created", 204: "No Content",
                   304: "Not Modified", 403: "Forbidden",
                   404: "Not Found", 409: "Conflict",
                   500: "Internal Server Error"}
        if raw is not None:
            payload = raw
        elif body is None:
            payload = b""
        else:
            payload = json.dumps(body).encode()
        head = (f"HTTP/1.1 {status} {reasons.get(status, 'X')}\r\n"
                f"Content-Type: {content_type}\r\n"
                f"Content-Length: {len(payload)}\r\n"
                f"Connection: close\r\n\r\n").encode()
        try:
            sock.sendall(head + payload)
        except OSError:
            pass

    # -------------------------------------------------------------- routes

    _ROUTES = []  # populated below

    def _route(self, req: Request) -> None:
        for method, pattern, handler in self._ROUTES:
            if req.method != method:
                continue
            m = pattern.match(req.path)
            if m:
                handler(self, req, *m.groups())
                return
        raise HttpError(404, f"nsd: no route {req.method} {req.path}")

    # system ------------------------------------------------------------

    def h_ping(self, req: Request) -> None:
        self._respond(req.sock, 200, raw=b"OK", content_type="text/plain")

    def h_info(self, req: Request) -> None:
        self._respond(req.sock, 200, {
            "Name": "nsd", "ServerVersion": "nsd-0.1",
            "Containers": len(self.containers), "OperatingSystem": "linux",
            "OSType": "linux", "BuilderVersion": "1"})

    def h_version(self, req: Request) -> None:
        self._respond(req.sock, 200,
                      {"Version": "nsd-0.1", "ApiVersion": "1.43"})

    # containers --------------------------------------------------------

    def h_create(self, req: Request) -> None:
        name = req.query.get("name") or f"nsd-{secrets.token_hex(6)}"
        config = req.json()
        with self._lock:
            for c in self.containers.values():
                if c.name == name:
                    raise HttpError(409, f"container name {name} already in use")
            image = config.get("Image", "")
            if image and image not in self.images:
                raise HttpError(404, f"No such image: {image}")
            cid = secrets.token_hex(32)
            cg_root = self.runtime.cgroup_root
            # volume names resolve to mountpoints NOW so archive ops can
            # map bind-shadowed paths to their sources before start
            hc = config.setdefault("HostConfig", {})
            hc["Binds"] = [self._resolve_bind(b) for b in (hc.get("Binds") or [])]
            c = NsContainer(
                id=cid, name=name, config=config,
                dir=self.runtime.state_dir / cid[:24],
                cgroup_dir=(cg_root / cid[:24]) if cg_root else None)
            self.runtime.prepare(c)
            self.containers[cid] = c
        self._event("container", "create", cid, {"name": name})
        self._respond(req.sock, 201, {"Id": cid, "Warnings": []})

    def h_start(self, req: Request, ref: str) -> None:
        c = self._find(ref)
        if c.state == "running":
            self._respond(req.sock, 304)
            return
        self.runtime.start(c, on_exit=self._die_event)
        self._event("container", "start", c.id, {"name": c.name})
        self._respond(req.sock, 204)

    def _die_event(self, c) -> None:
        self._event("container", "die", c.id,
                    {"name": c.name, "exitCode": str(c.exit_code)})

    def h_stop(self, req: Request, ref: str) -> None:
        c = self._find(ref)
        self.runtime.stop(c, timeout=int(req.query.get("t", "10")))
        self._event("container", "stop", c.id, {"name": c.name})
        self._respond(req.sock, 204)

    def h_kill(self, req: Request, ref: str) -> None:
        c = self._find(ref)
        sig = req.query.get("signal", "KILL")
        if sig.isdigit():
            num = int(sig)
        else:
            name = sig.upper()
            name = name if name.startswith("SIG") else f"SIG{name}"
            num = getattr(signal, name, signal.SIGKILL)
        self.runtime.kill(c, num)
        self._respond(req.sock, 204)

    def h_restart(self, req: Request, ref: str) -> None:
        c = self._find(ref)
        self.runtime.stop(c, timeout=int(req.query.get("t", "10")))
        self.runtime.start(c, on_exit=self._die_event)
        self._event("container", "start", c.id, {"name": c.name})
        self._respond(req.sock, 204)

    def h_remove(self, req: Request, ref: str) -> None:
        c = self._find(ref)
        if c.state == "running" and not req.qbool("force"):
            raise HttpError(409, "container is running (use force)")
        with self._lock:
            self.containers.pop(c.id, None)
        self.runtime.remove(c)
        # ?v=1 is docker's ANONYMOUS-volume cleanup; nsd has none, so it
        # is a no-op here.  Named agent volumes are removed by the engine
        # layer's label-scoped sweep (engine/api.py remove_container).
        self._event("container", "destroy", c.id, {"name": c.name})
        self._respond(req.sock, 204)

    def h_rename(self, req: Request, ref: str) -> None:
        c = self._find(ref)
        new = req.query.get("name", "")
        if not new:
            raise HttpError(400, "rename: name required")
        with self._lock:
            if any(o.name == new for o in self.containers.values()):
                raise HttpError(409, f"name {new} already in use")
            c.name = new
        self._respond(req.sock, 204)

    def h_inspect(self, req: Request, ref: str) -> None:
        self._respond(req.sock, 200, self._find(ref).inspect())

    def h_list(self, req: Request) -> None:
        filters = json.loads(req.query.get("filters") or "{}")
        show_all = req.qbool("all")
        out = []
        with self._lock:
            for c in self.containers.values():
                if not show_all and c.state != "running":
                    continue
                if self._match_filters(c, filters):
                    out.append(c.summary())
        self._respond(req.sock, 200, out)

    def h_wait(self, req: Request, ref: str) -> None:
        c = self._find(ref)
        code = self.runtime.wait(c) if c.state != "created" else 0
        self._respond(req.sock, 200, {"StatusCode": code})

    def h_resize(self, req: Request, ref: str) -> None:
        c = self._find(ref)
        self.runtime.resize(c, int(req.query.get("h", "24")),
                            int(req.query.get("w", "80")))
        self._respond(req.sock, 200)

    def h_attach(self, req: Request, ref: str) -> None:
        c = self._find(ref)
        sock = req.upgrade()
        if req.qbool("logs") and c.hub.log_path.exists():
            try:
                sock.sendall(c.hub.log_path.read_bytes())
            except OSError:
                return
        c.hub.add_client(sock)
        try:
            while True:
                try:
                    data = sock.recv(65536)
                except OSError:
                    break
                if not data:
                    # client finished WRITING (stdin EOF); it still reads
                    # output -- stay attached until the container exits
                    # or is removed (hub.close_clients shuts the socket)
                    while (self.containers.get(c.id) is c
                           and c.state in ("created", "running")):
                        if c.state == "running" and c._exited.wait(0.2):
                            break
                        if c.state == "created":
                            time.sleep(0.05)
                    break
                c.hub.write_stdin(data)
        finally:
            c.hub.remove_client(sock)

    def h_logs(self, req: Request, ref: str) -> None:
        c = self._find(ref)
        req.stream_headers()
        sock = req.sock
        try:
            if c.hub.log_path.exists():
                sock.sendall(c.hub.log_path.read_bytes())
        except OSError:
            return
        if req.qbool("follow") and c.state == "running":
            c.hub.add_client(sock)
            try:
                while c.state == "running":
                    try:
                        if not sock.recv(4096):
                            break
                    except OSError:
                        break
            finally:
                c.hub.remove_client(sock)

    def h_put_archive(self, req: Request, ref: str) -> None:
        c = self._find(ref)
        try:
            self.runtime.put_archive(c, req.query.get("path", "/"),
                                     req.body)
        except PermissionError as e:
            # archive write into a `:ro` bind resolves to the HOST
            # source: refuse like dockerd does (ADVICE r5)
            raise HttpError(403, str(e)) from None
        self._respond(req.sock, 200)

    def h_get_archive(self, req: Request, ref: str) -> None:
        c = self._find(ref)
        try:
            data = self.runtime.get_archive(c, req.query.get("path", "/"))
        except FileNotFoundError as e:
            raise HttpError(404, f"no such path: {e}") from None
        self._respond(req.sock, 200, raw=data,
                      content_type="application/x-tar")

    # exec --------------------------------------------------------------

    def h_exec_create(self, req: Request, ref: str) -> None:
        c = self._find(ref)
        eid = secrets.token_hex(32)
        with self._lock:
            self.execs[eid] = {"container": c.id, "config": req.json(),
                               "exit": None, "running": False}
        self._respond(req.sock, 201, {"Id": eid})

    def h_exec_start(self, req: Request, eid: str) -> None:
        with self._lock:
            e = self.execs.get(eid)
        if e is None:
            raise HttpError(404, f"no such exec: {eid}")
        body = req.json()
        cfg = dict(e["config"])
        cfg["Tty"] = body.get("Tty", cfg.get("Tty", False))
        c = self._find(e["container"])
        if body.get("Detach"):
            p = self.runtime.exec_spawn(c, cfg)
            e["running"] = True

            def reap():
                e["exit"] = p.wait()
                e["running"] = False

            threading.Thread(target=reap, daemon=True).start()
            self._respond(req.sock, 200, {})
            return
        sock = req.upgrade()
        try:
            p = self.runtime.exec_spawn(c, cfg)
        except (RuntimeError, OSError):
            # hijacked already: record the failure so exec_inspect
            # reports it (126 = command cannot execute), then close
            e["exit"] = 126
            return
        e["running"] = True
        self._pump_exec(p, sock, bool(cfg.get("Tty")))
        e["exit"] = p.wait()
        e["running"] = False

    def _pump_exec(self, p, sock: socket.socket, tty: bool) -> None:
        if getattr(p, "nsd_io", None):  # pty mode
            master = p.nsd_io[0]
            fds = {master: 1}
            stdin_fd = master
        else:
            fds = {p.stdout.fileno(): 1, p.stderr.fileno(): 2}
            stdin_fd = p.stdin.fileno()
        # the socket stays BLOCKING: select gates reads (no spurious
        # blocking recv), and sendall on a non-blocking socket could
        # raise mid-frame and corrupt the stdcopy stream
        sfd = sock.fileno()
        while fds:
            ready, _, _ = select.select(list(fds) + [sfd], [], [], 0.5)
            for fd in ready:
                if fd == sfd:
                    try:
                        data = sock.recv(65536)
                    except (BlockingIOError, OSError):
                        continue
                    if not data:
                        # pipe mode: close stdin so the command sees EOF.
                        # tty mode: the master is ALSO the output fd --
                        # never close it here, just stop forwarding.
                        if not tty:
                            try:
                                p.stdin.close()
                            except OSError:
                                pass
                        stdin_fd = -1
                        continue
                    if stdin_fd >= 0:
                        try:
                            os.write(stdin_fd, data)
                        except OSError:
                            pass
                    continue
                try:
                    chunk = os.read(fd, 65536)
                except OSError:
                    chunk = b""
                if not chunk:
                    del fds[fd]
                    continue
                data = chunk if tty else frame(fds[fd], chunk)
                try:
                    sock.sendall(data)
                except OSError:
                    fds.clear()
            if p.poll() is not None and not fds:
                break

    def h_exec_inspect(self, req: Request, eid: str) -> None:
        with self._lock:
            e = self.execs.get(eid)
        if e is None:
            raise HttpError(404, f"no such exec: {eid}")
        self._respond(req.sock, 200,
                      {"ExitCode": e["exit"] if e["exit"] is not None else 0,
                       "Running": e["running"]})

    # images ------------------------------------------------------------

    def _register_image(self, ref: str, labels: dict | None = None) -> dict:
        digest = hashlib.sha256(ref.encode()).hexdigest()
        img = {"Id": f"sha256:{digest}", "RepoTags": [ref],
               "Labels": labels or {}, "Created": _now(),
               "Config": {"Labels": labels or {}}, "Size": 0}
        with self._lock:
            self.images[ref] = img
        return img

    def h_image_list(self, req: Request) -> None:
        filters = json.loads(req.query.get("filters") or "{}")
        wants = filters.get("label") or []
        if isinstance(wants, dict):
            wants = [k for k, v in wants.items() if v]
        out = []
        with self._lock:
            for img in self.images.values():
                ok = True
                for want in wants:
                    k, _, v = want.partition("=")
                    lv = (img.get("Labels") or {}).get(k)
                    if lv is None or (v and lv != v):
                        ok = False
                if ok:
                    out.append(img)
        self._respond(req.sock, 200, out)

    def h_image_inspect(self, req: Request, ref: str) -> None:
        ref = urllib.parse.unquote(ref)
        with self._lock:
            img = self.images.get(ref)
            if img is None:
                for i in self.images.values():
                    if i["Id"] == ref or ref in (i.get("RepoTags") or []):
                        img = i
                        break
        if img is None:
            raise HttpError(404, f"No such image: {ref}")
        self._respond(req.sock, 200, img)

    def h_image_tag(self, req: Request, ref: str) -> None:
        ref = urllib.parse.unquote(ref)
        with self._lock:
            img = self.images.get(ref)
            if img is None:
                raise HttpError(404, f"No such image: {ref}")
            new_ref = f"{req.query.get('repo', '')}:{req.query.get('tag', 'latest')}"
            clone = dict(img)
            clone["RepoTags"] = [new_ref]
            self.images[new_ref] = clone
        self._respond(req.sock, 201)

    def h_image_remove(self, req: Request, ref: str) -> None:
        ref = urllib.parse.unquote(ref)
        with self._lock:
            if ref not in self.images:
                raise HttpError(404, f"No such image: {ref}")
            del self.images[ref]
        self._respond(req.sock, 200, [{"Deleted": ref}])

    def h_image_pull(self, req: Request) -> None:
        """'Pulling' = registering the ref over the host rootfs: every
        image shares the host lower layer in this runtime."""
        name = req.query.get("fromImage", "")
        tag = req.query.get("tag", "latest")
        ref = f"{name}:{tag}" if name else ""
        if not name:
            raise HttpError(400, "fromImage required")
        self._register_image(ref)
        req.stream_headers("application/json")
        try:
            req.sock.sendall(json.dumps(
                {"status": f"Pull complete (host-rootfs): {ref}"}).encode() + b"\n")
        except OSError:
            pass

    def h_build(self, req: Request) -> None:
        """Synthetic build: tags are registered with their labels; the
        Dockerfile is not executed (every nsd image is host-rootfs)."""
        labels = json.loads(req.query.get("labels") or "{}")
        tags = list(getattr(req, "query_multi", {}).get("t") or [])
        for t in tags:
            self._register_image(t, labels)
        req.stream_headers("application/json")
        try:
            for t in tags:
                req.sock.sendall(json.dumps(
                    {"stream": f"nsd: tagged {t} (host-rootfs image)\n"}
                ).encode() + b"\n")
            req.sock.sendall(json.dumps(
                {"aux": {"ID": "sha256:" + hashlib.sha256(
                    ",".join(tags).encode()).hexdigest()}}).encode() + b"\n")
        except OSError:
            pass

    # volumes -----------------------------------------------------------

    def h_volume_create(self, req: Request) -> None:
        body = req.json()
        vol = self._ensure_volume(body.get("Name") or secrets.token_hex(8),
                                  body.get("Labels") or {})
        self._respond(req.sock, 201, vol)

    def h_volume_list(self, req: Request) -> None:
        filters = json.loads(req.query.get("filters") or "{}")
        wants = filters.get("label") or []
        if isinstance(wants, dict):
            wants = [k for k, v in wants.items() if v]
        out = []
        with self._lock:
            for vol in self.volumes.values():
                ok = True
                for want in wants:
                    k, _, v = want.partition("=")
                    lv = (vol.get("Labels") or {}).get(k)
                    if lv is None or (v and lv != v):
                        ok = False
                if ok:
                    out.append(vol)
        self._respond(req.sock, 200, {"Volumes": out, "Warnings": []})

    def h_volume_inspect(self, req: Request, name: str) -> None:
        with self._lock:
            vol = self.volumes.get(name)
        if vol is None:
            raise HttpError(404, f"no such volume: {name}")
        self._respond(req.sock, 200, vol)

    def h_volume_remove(self, req: Request, name: str) -> None:
        with self._lock:
            vol = self.volumes.get(name)
            if vol is None:
                raise HttpError(404, f"no such volume: {name}")
            mp = vol["Mountpoint"]
            for c in self.containers.values():
                if any(b.split(":")[0] == mp for b in c.binds()):
                    raise HttpError(
                        409, f"volume {name} is in use by {c.name}")
            self.volumes.pop(name)
        import shutil

        shutil.rmtree(mp, ignore_errors=True)
        self._respond(req.sock, 204)

    # networks (records only: nsd containers share the host network) ----

    def h_network_create(self, req: Request) -> None:
        body = req.json()
        name = body.get("Name") or secrets.token_hex(8)
        net = {"Name": name, "Id": secrets.token_hex(32),
               "Labels": body.get("Labels") or {}, "Driver": "host-shared",
               "IPAM": body.get("IPAM") or {}, "Containers": {}}
        with self._lock:
            self.networks[name] = net
        self._respond(req.sock, 201, {"Id": net["Id"]})

    def h_network_list(self, req: Request) -> None:
        with self._lock:
            self._respond(req.sock, 200, list(self.networks.values()))

    def h_network_inspect(self, req: Request, ref: str) -> None:
        with self._lock:
            net = self.networks.get(ref)
            if net is None:
                net = next((n for n in self.networks.values()
                            if n["Id"].startswith(ref)), None)
        if net is None:
            raise HttpError(404, f"no such network: {ref}")
        self._respond(req.sock, 200, net)

    def h_network_remove(self, req: Request, ref: str) -> None:
        with self._lock:
            self.networks.pop(ref, None)
        self._respond(req.sock, 204)

    def h_network_connect(self, req: Request, ref: str) -> None:
        self._respond(req.sock, 200)

    def h_network_disconnect(self, req: Request, ref: str) -> None:
        self._respond(req.sock, 200)

    # events ------------------------------------------------------------

    def h_events(self, req: Request) -> None:
        req.stream_headers("application/json")
        with self._lock:
            self._subscribers.append(req.sock)
        # connection stays open; writes happen from _event; reads detect close
        try:
            while True:
                try:
                    if not req.sock.recv(4096):
                        break
                except OSError:
                    break
        finally:
            with self._lock:
                if req.sock in self._subscribers:
                    self._subscribers.remove(req.sock)


def _r(method: str, pattern: str, handler) -> tuple:
    return (method, re.compile(pattern), handler)


NsDaemon._ROUTES = [
    _r("GET", r"^/_ping$", NsDaemon.h_ping),
    _r("GET", r"^/info$", NsDaemon.h_info),
    _r("GET", r"^/version$", NsDaemon.h_version),
    _r("POST", r"^/containers/create$", NsDaemon.h_create),
    _r("GET", r"^/containers/json$", NsDaemon.h_list),
    _r("POST", r"^/containers/([^/]+)/start$", NsDaemon.h_start),
    _r("POST", r"^/containers/([^/]+)/stop$", NsDaemon.h_stop),
    _r("POST", r"^/containers/([^/]+)/kill$", NsDaemon.h_kill),
    _r("POST", r"^/containers/([^/]+)/restart$", NsDaemon.h_restart),
    _r("POST", r"^/containers/([^/]+)/rename$", NsDaemon.h_rename),
    _r("POST", r"^/containers/([^/]+)/wait$", NsDaemon.h_wait),
    _r("POST", r"^/containers/([^/]+)/resize$", NsDaemon.h_resize),
    _r("POST", r"^/containers/([^/]+)/attach$", NsDaemon.h_attach),
    _r("GET", r"^/containers/([^/]+)/logs$", NsDaemon.h_logs),
    _r("GET", r"^/containers/([^/]+)/json$", NsDaemon.h_inspect),
    _r("DELETE", r"^/containers/([^/]+)$", NsDaemon.h_remove),
    _r("PUT", r"^/containers/([^/]+)/archive$", NsDaemon.h_put_archive),
    _r("GET", r"^/containers/([^/]+)/archive$", NsDaemon.h_get_archive),
    _r("POST", r"^/containers/([^/]+)/exec$", NsDaemon.h_exec_create),
    _r("POST", r"^/exec/([^/]+)/start$", NsDaemon.h_exec_start),
    _r("GET", r"^/exec/([^/]+)/json$", NsDaemon.h_exec_inspect),
    _r("GET", r"^/images/json$", NsDaemon.h_image_list),
    _r("GET", r"^/images/([^/]+)/json$", NsDaemon.h_image_inspect),
    _r("POST", r"^/images/([^/]+)/tag$", NsDaemon.h_image_tag),
    _r("DELETE", r"^/images/([^/]+)$", NsDaemon.h_image_remove),
    _r("POST", r"^/images/create$", NsDaemon.h_image_pull),
    _r("POST", r"^/build$", NsDaemon.h_build),
    _r("POST", r"^/volumes/create$", NsDaemon.h_volume_create),
    _r("GET", r"^/volumes$", NsDaemon.h_volume_list),
    _r("GET", r"^/volumes/([^/]+)$", NsDaemon.h_volume_inspect),
    _r("DELETE", r"^/volumes/([^/]+)$", NsDaemon.h_volume_remove),
    _r("POST", r"^/networks/create$", NsDaemon.h_network_create),
    _r("GET", r"^/networks$", NsDaemon.h_network_list),
    _r("GET", r"^/networks/([^/]+)$", NsDaemon.h_network_inspect),
    _r("DELETE", r"^/networks/([^/]+)$", NsDaemon.h_network_remove),
    _r("POST", r"^/networks/([^/]+)/connect$", NsDaemon.h_network_connect),
    _r("POST", r"^/networks/([^/]+)/disconnect$", NsDaemon.h_network_disconnect),
    _r("GET", r"^/events$", NsDaemon.h_events),
]


def serve(state_dir: str, socket_path: str) -> None:
    daemon = NsDaemon(state_dir, socket_path)
    try:
        daemon.serve()
    finally:
        daemon.shutdown()
