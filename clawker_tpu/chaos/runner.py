"""Chaos soak runner: execute fault plans against a fake pod, audit,
shrink failures to minimal repros.

One :class:`ChaosRunner` executes one :class:`~.plan.FaultPlan` end to
end: build the fake pod, start the scheduler, walk the injection
schedule (worker faults through the driver's fault gates; CLI SIGKILLs
through armed crash seams followed by ``--resume`` reconciliation,
kill/resume cycles included), drive the run to completion, clean up,
then run :func:`~.invariants.check_invariants`.  ``run_soak`` iterates
N seeded scenarios and, on the first failure, calls
:func:`shrink_plan` -- greedy delta-debugging over the event list -- so
the report carries the SMALLEST schedule that still breaks an
invariant, plus the exact ``--seed``/``--scenario`` repro.

:class:`ChaosController` is the ``clawker loop --chaos-plan`` dev hook:
it applies a plan's schedule to a LIVE scheduler the CLI already built
(worker faults only where the driver supports injection; ``cli_sigkill``
events deliver a real SIGKILL so ``--resume`` can be crash-tested
against a genuine process death).
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time
from dataclasses import dataclass, field

from .. import logsetup, telemetry
from ..errors import ClawkerError
from .invariants import (
    check_invariants,
    observe_only_violations,
    scheduling_outcome,
)
from .plan import GATE_MODE, POD_GATE_MODE, FaultEvent, FaultPlan, generate_plan
from .seams import SeamAbort, SeamRegistry

log = logsetup.get("chaos.runner")

_INJECTIONS = telemetry.counter(
    "chaos_injections_total", "Fault events injected by the chaos runner",
    labels=("kind",))
_SCENARIOS = telemetry.counter(
    "chaos_scenarios_total", "Chaos scenarios executed",
    labels=("result",))         # result: ok | violated | error
_VIOLATIONS = telemetry.counter(
    "chaos_invariant_violations_total",
    "Invariant violations found by chaos scenarios",
    labels=("invariant",))

def apply_fault(driver, ev: FaultEvent) -> None:
    """Apply one worker-fault event to an injectable driver -- the ONE
    event-kind -> fault-gate mapping shared by the soak runner and the
    live `loop --chaos-plan` controller."""
    if ev.kind == "worker_revive":
        driver.clear_fault(ev.worker)
        return
    if ev.kind in POD_GATE_MODE:
        # pod-scope faults hit EVERY worker's gate at once (the whole
        # pod's control plane dies / partitions; docs/federation.md).
        # The all-workers view keeps fixed-seed schedules meaningful
        # when an earlier scale_down shrank workers()
        all_workers = getattr(driver, "all_workers", None)
        n = len(all_workers() if all_workers is not None
                else driver.workers())
        for i in range(n):
            driver.inject_fault(i, POD_GATE_MODE[ev.kind])
        return
    kw = {}
    if ev.kind == "worker_slow":
        kw["delay_s"] = float(ev.arg or 0.1)
    elif ev.kind == "engine_burst":
        kw["count"] = int(ev.arg or 3)
    driver.inject_fault(ev.worker, GATE_MODE[ev.kind], **kw)


IMAGE = "clawker-chaos:default"
# generous end-to-end ceiling per scenario: a scenario that cannot
# drain within this is itself an invariant violation (stuck-run)
SCENARIO_DEADLINE_S = 60.0
MAX_GENERATIONS = 4             # sigkill/resume cycles per scenario bound

# gitguard scenarios: the run name + agent pool the deterministic
# push-probe schedule draws identities/refs from (docs/git-policy.md)
GITGUARD_RUN = "chaosrun"
GITGUARD_AGENTS = 3
GITGUARD_PROBES = 8


def gitguard_probe_script(seed: int,
                          scenario: int) -> list[tuple[str, str, str, str]]:
    """Deterministic push-probe schedule for a gitguard scenario:
    ``(kind, identity_header, ref, new_sha)`` per probe, drawn from the
    (seed, scenario) pair alone -- same plan, same probes, every
    machine.  Kinds: own-namespace push (must land), sibling-namespace
    and integration-branch pushes (must be refused at the proxy), and
    an occasional merge-queue landing (the ONE identity allowed onto
    the integration branch)."""
    import random

    rng = random.Random(
        (int(seed) & 0xFFFFFFFF) * 7_919 + int(scenario) + 1)
    probes: list[tuple[str, str, str, str]] = []
    for _ in range(GITGUARD_PROBES):
        kind = rng.choice(("own", "own", "own", "sibling", "sibling",
                           "integration", "mergeq"))
        a = rng.randrange(GITGUARD_AGENTS)
        sha = format(rng.getrandbits(160), "040x")
        if kind == "own":
            ident = f"{GITGUARD_RUN}/agent-{a}"
            ref = f"refs/heads/loop/{GITGUARD_RUN}/agent-{a}/work"
        elif kind == "sibling":
            other = (a + 1) % GITGUARD_AGENTS
            ident = f"{GITGUARD_RUN}/agent-{a}"
            ref = f"refs/heads/loop/{GITGUARD_RUN}/agent-{other}/work"
        elif kind == "integration":
            ident = f"{GITGUARD_RUN}/agent-{a}"
            ref = f"refs/heads/loop/{GITGUARD_RUN}/merged"
        else:
            ident = f"{GITGUARD_RUN}/queue/mergeq"
            ref = f"refs/heads/loop/{GITGUARD_RUN}/merged"
        probes.append((kind, ident, ref, sha))
    return probes
SENTINEL_TRAIN_STEPS = 20       # one shape for every chaos sentinel fit:
#                                 the soak and the observe-only twin share
#                                 a single jit compilation per process


class _EgressFeeder:
    """Synthetic per-worker egress streams for sentinel scenarios.

    Writes benign netlogger-shaped records into each worker's
    ``ebpf-egress-<worker>.jsonl`` under the scenario's logs dir (the
    sentinel collector's fake-pod convention) on a feeder thread.
    ``silence(i)`` stops worker i's stream mid-run; ``flood(i, n)``
    bursts n records at once -- the two stream-level faults the
    ``sentinel`` chaos scenario injects."""

    def __init__(self, cfg, worker_ids: list[str], *, hz: float = 20.0):
        self.cfg = cfg
        self.worker_ids = list(worker_ids)
        self.hz = hz
        self._silent: set[str] = set()
        self._stop = threading.Event()
        self._n = 0
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="chaos-egress-feeder")

    def path(self, wid: str):
        return self.cfg.logs_dir / f"ebpf-egress-{wid}.jsonl"

    def _record(self, wid: str) -> dict:
        self._n += 1
        return {
            "@timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "service": "ebpf-egress",
            "container": f"clawker.chaosproj.{wid}-agent{self._n % 3}",
            "worker": wid, "dst_ip": "198.51.100.9",
            "dst_port": 443, "proto": 6, "verdict": "ALLOW",
            "reason": "ROUTE", "zone": "example.com",
        }

    def _append(self, wid: str, n: int) -> None:
        try:
            with open(self.path(wid), "a", encoding="utf-8") as f:
                for _ in range(n):
                    f.write(json.dumps(self._record(wid)) + "\n")
        except OSError:
            pass

    def _run(self) -> None:
        while not self._stop.wait(1.0 / self.hz):
            for wid in self.worker_ids:
                if wid not in self._silent:
                    self._append(wid, 1)

    def start(self) -> "_EgressFeeder":
        self._thread.start()
        return self

    def silence(self, index: int) -> None:
        if 0 <= index < len(self.worker_ids):
            self._silent.add(self.worker_ids[index])

    def flood(self, index: int, n: int) -> None:
        if 0 <= index < len(self.worker_ids):
            self._append(self.worker_ids[index], max(1, n))

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(1.0)


@dataclass
class ScenarioResult:
    seed: int
    scenario: int
    ok: bool
    violations: list[str] = field(default_factory=list)
    wall_s: float = 0.0
    kills: int = 0
    generations: int = 1
    injected: int = 0
    run_id: str = ""
    plan_doc: dict = field(default_factory=dict)

    def to_doc(self) -> dict:
        return {
            "seed": self.seed, "scenario": self.scenario, "ok": self.ok,
            "violations": list(self.violations),
            "wall_s": round(self.wall_s, 3), "kills": self.kills,
            "generations": self.generations, "injected": self.injected,
            "run_id": self.run_id,
        }


class ChaosRunner:
    """Execute one fault plan against a fresh fake pod."""

    def __init__(self, cfg, plan: FaultPlan, *, on_event=None,
                 behavior=None, poll_s: float = 0.05):
        from ..engine.drivers import FakeDriver
        from ..engine.fake import exit_behavior
        from ..health import BreakerConfig, HealthConfig

        self.cfg = cfg
        self.plan = plan
        self.on_event = on_event
        self.poll_s = poll_s
        self.driver = FakeDriver(n_workers=plan.n_workers)
        for api in self.driver.apis:
            api.add_image(IMAGE)
            api.set_behavior(IMAGE,
                             behavior or exit_behavior(b"", 0, delay=0.02))
        # fast verdicts: the scenario horizon is under a second, so
        # probes and breaker backoff must be an order faster than that
        self.health_config = HealthConfig(
            probe_interval_s=0.05, probe_deadline_s=0.5,
            breaker=BreakerConfig(failure_threshold=2,
                                  backoff_base_s=0.05, backoff_max_s=0.2))
        self.kills = 0
        self.generations = 0
        self.injected = 0
        self._sched = None
        self._run_done = threading.Event()
        self._run_exc: list[BaseException] = []
        self._armed: list[tuple] = []   # (sched, seam, event) pending arms
        # storage scenarios (disk_full/io_error/fsync_fail/torn_record):
        # faults hit the run journal's OWN fd via testenv.FaultFS, never
        # an engine -- the workers stay unfaulted.  The audit compares
        # the shims' fired counts against the scheduler's fault
        # accounting and storage.fault bus events (no-silent-drop), and
        # the checksum verify verdict against the injections
        # (replay-integrity); both counters accumulate across
        # kill/resume generations
        self._storage_injected: list[str] = []
        self._storage_shims: list = []
        self._torn_injected = False
        self._storage_events = 0        # storage.fault frames, all gens
        self._storage_faults_base = 0   # dead generations' fault counts
        # sentinel scenarios (plan.sentinel): the fleet sentinel rides
        # the run, fed by synthetic per-worker egress streams; the
        # standard invariants must hold WITH it attached, its audit
        # counters must stay zero, and egress_*/sentinel_kill events
        # fault the streams/collector instead of the workers
        self.sentinel = None
        self.feeder = None
        if plan.sentinel and self._sentinel_available():
            self.feeder = _EgressFeeder(
                cfg, [w.id for w in self.driver.workers()]).start()
            from ..sentinel import FleetSentinel

            self.sentinel = FleetSentinel(
                cfg, self.driver, interval_s=0.15,
                train_steps=SENTINEL_TRAIN_STEPS, threshold=3.5).start()
        # workerd scenarios (plan.workerd): per-worker launch daemons on
        # the fake pod's LOCAL engine views + an executor per channel;
        # the scheduler's data plane rides them, and
        # workerd_partition/workerd_kill events fault the channels
        # while every standard invariant must keep holding
        self.workerd_servers: list = []
        self.executors = None
        if plan.workerd:
            from ..workerd.executor import ExecutorSet, WorkerdExecutor
            from ..workerd.server import WorkerdServer

            exs = {}
            for i, w in enumerate(self.driver.workers()):
                sock = cfg.state_dir / "chaos-wd" / f"wd-{i}.sock"
                srv = WorkerdServer(cfg, self.driver.local_engine(i),
                                    worker_id=w.id, sock_path=sock).start()
                self.workerd_servers.append(srv)
                # a killed daemon must strand its pending intents well
                # inside the scenario deadline
                exs[w.id] = WorkerdExecutor(w.id, sock,
                                            intent_deadline_s=2.0)
            self.executors = ExecutorSet(exs)
        # shipper scenarios (plan.shipper): the telemetry shipper rides
        # every generation against an in-memory fake bulk index;
        # index_down events take the index down (or wedge it inside the
        # sink deadline) while the standard invariants must keep
        # holding and the shipper audit proves the bounded-buffer,
        # drop-oldest, never-blocks degradation
        self.index = None
        self.shipper = None
        self._index_downed = False
        if plan.shipper:
            from ..monitor.shipper import TelemetryShipper
            from ..testenv import FakeBulkIndex

            self.index = FakeBulkIndex(stall_timeout_s=0.2)
            self.shipper = TelemetryShipper(
                self.index, interval_s=0.05, batch_docs=16,
                max_batches=4, source="chaos").start()
        # capacity scenarios (plan.capacity): the elastic controller
        # rides each generation (re-attached like the sentinel; its
        # journaled state survives the kill/resume cycle via
        # RunImage.capacity).  traffic_burst events spike admission
        # queues open-loop; scale_down events request drains whose
        # firing stays gated on journal replay -- every standard
        # invariant must keep holding, and stranded-by-drain audits
        # the drains that fired.  Autoscale GROWTH stays off in chaos:
        # drains are event-driven, so the scenario shape stays the
        # plan's.
        self.capacity_ctrl = None
        self.capacity_scaler = None
        self._drain_requests: list[str] = []
        if plan.capacity:
            from ..capacity import FakeFleetScaler
            from ..config.schema import (
                CapacityAutoscaleSettings,
                CapacitySettings,
            )

            self.capacity_scaler = FakeFleetScaler(
                self.driver, max_workers=plan.n_workers)
            self._cap_settings = CapacitySettings(
                enable=True, interval_s=0.05,
                pool_min_depth=0,
                pool_max_depth=max(2, plan.warm_pool_depth),
                autoscale=CapacityAutoscaleSettings(
                    enable=True, min_workers=1,
                    max_workers=plan.n_workers,
                    queue_high=10_000,      # growth off: event-driven only
                    idle_low=0.0,           # idle drains off: ditto
                    sustain_s=3600.0))
        # gitguard scenarios (plan.gitguard): the run's git firewall
        # proxy rides the scenario over an in-memory upstream,
        # exercised by a deterministic protocol-level push-probe
        # schedule (own-namespace allow, sibling deny, integration
        # deny, an occasional merge-queue landing).  gitguard_down
        # kills the proxy mid-run; every later probe must fail CLOSED
        # (connection refused, recorded as such) -- the invariant
        # audits the upstream's acknowledged log as ground truth
        # (docs/git-policy.md; ref-isolation-at-proxy)
        self.gitguard_srv = None
        self.gitguard_upstream = None
        self._gitguard_decisions: list[tuple[float, dict]] = []
        self._gitguard_probes: list[dict] = []
        self._gitguard_script: list[tuple[str, str, str, str]] = []
        self._gitguard_downed_at: float | None = None
        if plan.gitguard:
            from ..gitguard import FakeGitUpstream, GitguardServer, RefPolicy

            self.gitguard_upstream = FakeGitUpstream(
                refs={"refs/heads/main": "a" * 40})
            self.gitguard_srv = GitguardServer(
                self.gitguard_upstream, RefPolicy(run=GITGUARD_RUN),
                tcp_addr=("127.0.0.1", 0),
                on_decision=lambda d: self._gitguard_decisions.append(
                    (time.monotonic(), d.to_doc())))
            self.gitguard_srv.start()
            self._gitguard_script = gitguard_probe_script(
                plan.seed, plan.scenario)

    @staticmethod
    def _sentinel_available() -> bool:
        try:
            from ..analytics import runtime as art

            return art.jax_available()
        except ImportError:
            return False

    # ------------------------------------------------------------ lifecycle

    def _spec(self):
        from ..loop import LoopSpec

        p = self.plan
        return LoopSpec(
            parallel=p.n_loops, iterations=p.iterations,
            failover=p.failover, warm_pool_depth=p.warm_pool_depth,
            max_inflight_per_worker=p.max_inflight_per_worker,
            image=IMAGE, agent_prefix="chaos", orphan_grace_s=20.0)

    def _start_generation(self, *, resume_of=None,
                          arm_events: list | None = None):
        """Build + start generation 1, or resume generation N+1 from the
        dead generation's journal (kill/resume cycle).  ``arm_events``
        re-arms surviving sigkill seams on the FRESH registry before the
        generation starts driving -- resume.* seams fire during
        reconcile, so arming after the thread started would race the
        window."""
        from ..loop import LoopScheduler
        from ..loop.journal import RunJournal, journal_path, replay

        self.generations += 1
        if self._sched is not None:
            # the dead generation's storage-fault count survives into
            # the audit (its bus history does not)
            self._storage_faults_base += getattr(
                self._sched, "storage_faults", 0)
        seams = SeamRegistry()
        if resume_of is None:
            sched = LoopScheduler(self.cfg, self.driver, self._spec(),
                                  on_event=self.on_event,
                                  health_config=self.health_config,
                                  seams=seams, executors=self.executors)
        else:
            image = replay(RunJournal.read(
                journal_path(self.cfg.logs_dir, resume_of)))
            if not image.run_id:
                raise ClawkerError(
                    "chaos: resume found no run header -- the kill beat "
                    "the first journal record (seam fired too early?)")
            sched = LoopScheduler.resume(
                self.cfg, self.driver, image, on_event=self.on_event,
                health_config=self.health_config, seams=seams,
                executors=self.executors)
        self._sched = sched
        sched.events.add_tap(self._storage_tap)
        if self.sentinel is not None:
            # re-attached per generation: each generation owns a fresh
            # bus/flight recorder, while the sentinel's baselines and
            # flagged set persist across the kill/resume cycle via its
            # run-keyed state file (the --resume persistence contract)
            sched.attach_sentinel(self.sentinel)
        if self.shipper is not None:
            # one shipper across generations, like loopd hosting it
            # across runs: the bounded buffer and drop accounting span
            # the kill/resume cycle
            sched.attach_shipper(self.shipper)
        if self.plan.capacity:
            # a fresh controller per generation, bound to the fresh
            # scheduler's hooks; journaled targets restore through
            # RunImage.capacity, and un-fired drain requests re-queue
            # so a kill between request and gate cannot lose the drain
            from ..capacity import CapacityController

            self.capacity_ctrl = CapacityController(
                self._cap_settings, scaler=self.capacity_scaler)
            sched.attach_capacity(self.capacity_ctrl)
            drained = set(self.capacity_scaler.drained)
            for wid in self._drain_requests:
                if wid not in drained:
                    self.capacity_ctrl.request_drain(wid)
        # per-GENERATION completion state: the closure binds these
        # locals, not self, so a stale gen-N thread that finally
        # unblocks (e.g. out of a wedge after the 5s kill wait gave up
        # on it) completes only its own dead generation -- it can
        # neither mark the live one done nor pin its crash on it
        done = self._run_done = threading.Event()
        exc = self._run_exc = []
        for ev in arm_events or []:
            self._arm_sigkill(ev, sched)

        def drive() -> None:
            try:
                if resume_of is None:
                    sched.start()
                else:
                    sched.reconcile()
                sched.run(poll_s=self.poll_s)
            except SeamAbort:
                pass            # the armed kill fired on this thread
            except BaseException as e:  # noqa: BLE001 -- surfaced as error
                exc.append(e)
            finally:
                done.set()

        threading.Thread(target=drive, daemon=True,
                         name=f"chaos-run-g{self.generations}").start()
        return sched

    # ------------------------------------------------------------ injection

    def _apply_worker_fault(self, ev: FaultEvent) -> None:
        apply_fault(self.driver, ev)
        _INJECTIONS.labels(ev.kind).inc()
        self.injected += 1

    def _apply_stream_fault(self, ev: FaultEvent) -> None:
        """Sentinel-scenario faults: silence/flood a worker's egress
        stream, or SIGKILL the sentinel's collector.  No-ops (but still
        counted) when the sentinel could not start -- the schedule must
        not depend on jax availability."""
        if ev.kind == "egress_silent" and self.feeder is not None:
            self.feeder.silence(ev.worker)
        elif ev.kind == "egress_flood" and self.feeder is not None:
            self.feeder.flood(ev.worker, int(ev.arg or 100))
        elif ev.kind == "sentinel_kill" and self.sentinel is not None:
            self.sentinel.kill_collector()
        _INJECTIONS.labels(ev.kind).inc()
        self.injected += 1

    def _apply_index_fault(self, ev: FaultEvent) -> None:
        """Monitor-stack faults: the bulk index refuses (down) or
        wedges inside the sink deadline (``arg: "stall"``).  Hits only
        the shipper's SINK -- workers, bus, and lanes stay untouched,
        so the standard invariants double as the never-stalls proof."""
        self._index_downed = True
        if self.index is not None:
            if ev.arg == "stall":
                self.index.stall()
            else:
                self.index.down = True
        _INJECTIONS.labels(ev.kind).inc()
        self.injected += 1

    def _shipper_audit(self) -> dict | None:
        """Shipper evidence for the invariant checker: intake/flush/
        drop accounting plus what the fake index actually holds.  None
        when the scenario ran without a shipper."""
        if self.shipper is None:
            return None
        audit = self.shipper.stats()
        audit["down_injected"] = self._index_downed
        audit["indexed_docs"] = (
            sum(len(v) for v in self.index.docs.values())
            if self.index is not None else 0)
        return audit

    def _workerd_audit(self) -> list[dict] | None:
        """Per-worker workerd evidence for the invariant checker: the
        channel's end-of-scenario liveness plus the server's
        undelivered-event and intent-dedup counters.  None when the
        scenario ran without workerd."""
        if self.executors is None:
            return None
        out = []
        for srv in self.workerd_servers:
            ex = self.executors.any_for(srv.worker_id)
            out.append({
                "worker": srv.worker_id,
                "alive": not srv._stop.is_set(),
                "channel_live": bool(ex is not None and ex.live()),
                "undelivered": srv.undelivered(),
                "intents": srv.stats["intents"],
                "dedup_hits": srv.stats["dedup_hits"],
            })
        return out

    def _apply_workerd_fault(self, ev: FaultEvent) -> None:
        """Data-plane faults: partition a channel (the daemon lives;
        the executor redials + resyncs) or SIGKILL the daemon itself
        (pending intents strand, the worker degrades to the direct WAN
        path).  Neither touches the worker's ENGINE -- the worker stays
        in the unfaulted set, so spurious-quarantine also proves
        workerd chaos can never open a breaker."""
        if 0 <= ev.worker < len(self.workerd_servers):
            srv = self.workerd_servers[ev.worker]
            if ev.kind == "workerd_partition":
                srv.drop_conns()
            else:
                srv.kill()
        _INJECTIONS.labels(ev.kind).inc()
        self.injected += 1

    def _apply_seed_fault(self, ev: FaultEvent) -> None:
        """Workspace-seed cache faults: drop the worker's resident seed
        store mid-run (restart-equivalent cold cache).  Touches only
        workerd's content-addressed store -- the engine stays unfaulted,
        so spurious-quarantine also proves a cold seed cache can never
        open a breaker; later creates referencing the digest degrade to
        the per-create fallback walk (docs/loop-worktrees.md)."""
        if 0 <= ev.worker < len(self.workerd_servers):
            self.workerd_servers[ev.worker].drop_seeds()
        _INJECTIONS.labels(ev.kind).inc()
        self.injected += 1

    def _apply_capacity_fault(self, ev: FaultEvent) -> None:
        """Capacity-scenario faults: an open-loop traffic burst against
        one worker's admission queue, or a scale-down request.  Neither
        touches a worker's ENGINE -- the worker stays in the unfaulted
        set, so spurious-quarantine also proves capacity chaos can
        never open a breaker."""
        sched = self._sched
        workers = self.driver.all_workers()
        if sched is None or not 0 <= ev.worker < len(workers):
            return
        wid = workers[ev.worker].id
        if ev.kind == "traffic_burst":
            # open-loop synthetic arrivals: each holds a token briefly
            # (like a short launch) so the queue genuinely deepens, but
            # performs no engine call -- pure admission pressure
            def hold(release) -> None:
                t = threading.Timer(0.03, release)
                t.daemon = True
                t.start()

            for _ in range(int(ev.arg or 10)):
                sched.admission.submit(wid, "~burst", hold)
        elif ev.kind == "scale_down":
            self._drain_requests.append(wid)
            if self.capacity_ctrl is not None:
                self.capacity_ctrl.request_drain(wid)
        _INJECTIONS.labels(ev.kind).inc()
        self.injected += 1

    def _storage_tap(self, rec) -> None:
        """Bus tap counting storage.fault frames across generations --
        the no-silent-drop audit's event half."""
        from ..monitor.events import STORAGE_FAULT

        if rec.event == STORAGE_FAULT:
            self._storage_events += 1

    def _apply_storage_fault(self, ev: FaultEvent) -> None:
        """Storage faults hit the run journal's own fd
        (testenv.FaultFS) or its bytes on disk, never an engine: the
        workers stay unfaulted, so spurious-quarantine also proves a
        dying disk cannot open a breaker."""
        import errno

        from ..testenv import FaultFS

        self._storage_injected.append(ev.kind)
        if ev.kind == "torn_record":
            self._inject_torn(ev)
        else:
            journal = getattr(self._sched, "journal", None)
            shim = FaultFS.install(journal) if journal is not None else None
            if shim is None:
                return      # journal disabled/unhealthy: nothing to arm
            self._storage_shims.append(shim)
            n = max(1, int(ev.arg or 1))
            if ev.kind == "disk_full":
                shim.fail_writes(n, errno_=errno.ENOSPC)
            elif ev.kind == "io_error":
                shim.fail_writes(n, errno_=errno.EIO)
            elif ev.kind == "fsync_fail":
                shim.fail_fsyncs(n)
        _INJECTIONS.labels(ev.kind).inc()
        self.injected += 1

    def _inject_torn(self, ev: FaultEvent) -> None:
        """torn_record: corrupt journal bytes in place -- a bit-flip
        (``arg: "flip"``) or a crash-torn cut truncating into the last
        record (``arg: "cut"``).  A sacrificial probe record takes the
        damage: the corruption is real (verify must flag it, the
        durable fold must stop at it) without destroying a record the
        OTHER invariants cross-audit -- the mid-run process stays
        alive, so a damaged placement/exit record would never be
        re-journaled the way a kill/resume cycle heals a torn tail.
        The replay-integrity invariant tolerates the corruption ONLY
        because ``torn_injected`` declares it."""
        from pathlib import Path

        from ..testenv import FaultFS

        journal = getattr(self._sched, "journal", None)
        if journal is None or not journal.healthy:
            return
        rcpt = journal.append("chaos_torn_probe", durable=True,
                              mode=str(ev.arg))
        if not rcpt.synced:
            return      # the disk is already faulted: nothing settled
        jp = Path(journal.path)
        try:
            size = jp.stat().st_size
        except OSError:
            return
        self._torn_injected = True
        if ev.arg == "cut":
            # power cut: the probe's unsynced-looking tail vanishes;
            # terminating the torn fragment keeps later appends on a
            # fresh line, so the fragment reads as one garbled
            # mid-file line the fold must stop before
            try:
                os.truncate(jp, size - 4)
                with open(jp, "a", encoding="utf-8") as fh:
                    fh.write("\n")
            except OSError:
                self._torn_injected = False
        else:
            # flip one bit inside the probe line (clear of its newline):
            # the record still parses but its CRC lies, or stops
            # parsing at all -- either way checksum-verify must flag it
            if not FaultFS.flip_bit_in_file(jp, size - 10):
                self._torn_injected = False

    def _storage_audit(self) -> dict | None:
        """Evidence for the storage invariants (None when the plan
        injected no storage fault): shim fired counts vs scheduler
        fault accounting vs storage.fault events, plus the checksum
        verify verdict and the run id the verified prefix folds to."""
        if not self._storage_injected:
            return None
        from pathlib import Path

        from ..loop.journal import journal_path, replay
        from ..monitor.ledger import read_verified_prefix, verify_jsonl

        sched = self._sched
        journal = getattr(sched, "journal", None)
        fired = sum(s.failed_writes + s.failed_fsyncs
                    for s in self._storage_shims)
        audit = {
            "injected": list(self._storage_injected),
            "torn_injected": self._torn_injected,
            "fired": fired,
            "faults": (self._storage_faults_base
                       + getattr(sched, "storage_faults", 0)),
            "durability": getattr(sched, "durability", "ok"),
            "dropped": getattr(journal, "dropped", 0) or 0,
            "poisoned": getattr(journal, "poisoned", 0) or 0,
            "events": self._storage_events,
            "verify": None,
            "folded_run_id": None,
        }
        jp = Path(journal_path(self.cfg.logs_dir, sched.loop_id))
        if jp.exists():
            audit["verify"] = verify_jsonl(jp).to_doc()
            records, _report = read_verified_prefix(jp)
            audit["folded_run_id"] = replay(records).run_id
        return audit

    def _gitguard_probe(self) -> None:
        """Fire the next scheduled push probe at the gitguard proxy:
        one receive-pack POST carrying one ref update, identity in the
        header (the shape Envoy stamps in production).  A probe against
        a killed proxy must dial ECONNREFUSED -- recorded as
        ``refused`` so the invariant can prove nothing landed after
        the down (fail-closed, docs/git-policy.md)."""
        if self.gitguard_srv is None or not self._gitguard_script:
            return
        import http.client

        from ..gitguard.pktline import FLUSH_PKT, encode_pkt
        from ..gitguard.refpolicy import IDENTITY_HEADER

        kind, ident, ref, sha = self._gitguard_script.pop(0)
        body = encode_pkt(
            f"{'0' * 40} {sha} {ref}".encode() + b"\x00report-status\n"
        ) + FLUSH_PKT
        try:
            conn = http.client.HTTPConnection(
                "127.0.0.1", self.gitguard_srv.port, timeout=2.0)
            conn.request(
                "POST", "/chaos/git-receive-pack", body=body,
                headers={IDENTITY_HEADER: ident, "Content-Type":
                         "application/x-git-receive-pack-request"})
            resp = conn.getresponse()
            resp.read()
            conn.close()
            outcome = f"http_{resp.status}"
        except OSError:
            outcome = "refused"
        self._gitguard_probes.append({
            "kind": kind, "identity": ident, "ref": ref,
            "t": time.monotonic(), "outcome": outcome})

    def _apply_gitguard_fault(self, ev: FaultEvent) -> None:
        """Kill the git firewall proxy mid-run.  The guard is the ONLY
        git path (the co-installed egress rules pin ssh/22 + git/9418
        shut), so a dead guard means pushes fail CLOSED -- later
        probes must dial ECONNREFUSED and the invariant proves nothing
        was acknowledged after this moment.  Never touches a worker's
        engine: spurious-quarantine also proves a dead git proxy
        cannot open a breaker."""
        if self.gitguard_srv is not None:
            self.gitguard_srv.close()
            self._gitguard_downed_at = time.monotonic()
        _INJECTIONS.labels(ev.kind).inc()
        self.injected += 1

    def _gitguard_audit(self) -> dict | None:
        """Gitguard evidence for the invariant checker: the upstream's
        acknowledged-update log (ground truth), the proxy's decision
        stream, the probe outcomes, and when (if ever) the proxy was
        killed.  None when the scenario ran without gitguard."""
        if self.gitguard_upstream is None:
            return None
        return {
            "run": GITGUARD_RUN,
            "branch_prefix": "loop",
            "downed_at": self._gitguard_downed_at,
            "acknowledged": list(self.gitguard_upstream.acknowledged),
            "decisions": list(self._gitguard_decisions),
            "probes": list(self._gitguard_probes),
        }

    def _arm_sigkill(self, ev: FaultEvent, sched=None) -> None:
        """Arm a crash seam on the current (or given) generation.
        Several seams may be armed at once -- whichever fires first
        kills the generation, and the survivors re-arm on the resumed
        one (that is how resume.* seams become reachable).  Arming is
        NOT counted as an injection -- a sigkill counts when its seam
        fires (_service_kill), so re-arms on resumed generations and
        seams the run never reaches don't inflate the report."""
        sched = sched if sched is not None else self._sched
        seam = str(ev.arg)
        if any(s is sched and sm == seam for s, sm, _e in self._armed):
            return              # same seam twice on one generation: one kill

        def die() -> None:
            sched.kill()
            raise SeamAbort(f"chaos sigkill at {seam}")

        sched.seams.arm(seam, die)
        self._armed.append((sched, seam, ev))

    def _service_kill(self) -> bool:
        """If any armed seam fired, finish its kill/resume cycle (tear
        the journal tail when the plan says so, resume as a fresh
        generation, re-arm surviving seams on it).  Returns True when a
        resume happened."""
        fired_idx = next(
            (i for i, (s, seam, _e) in enumerate(self._armed)
             if seam in s.seams.fired), None)
        if fired_idx is None:
            return False
        sched, _seam, ev = self._armed.pop(fired_idx)
        survivors = [e for s, _sm, e in self._armed if s is sched]
        self._armed = [entry for entry in self._armed
                       if entry[0] is not sched]
        self.kills += 1
        _INJECTIONS.labels(ev.kind).inc()
        self.injected += 1
        sched.kill()            # idempotent; covers seams on the run thread
        self._run_done.wait(5.0)
        # give in-flight lane tasks a beat to hit their epoch guards
        # (daemon threads survive a simulated SIGKILL; a real one's
        # threads would be gone, so we only need them to stop mutating)
        time.sleep(0.05)
        if ev.torn_tail > 0:
            self._tear_journal_tail(sched.loop_id, ev.torn_tail)
        if self.generations < MAX_GENERATIONS:
            self._start_generation(resume_of=sched.loop_id,
                                   arm_events=survivors)
        return True

    def _tear_journal_tail(self, run_id: str, n_bytes: int) -> None:
        from ..loop.journal import journal_path

        path = journal_path(self.cfg.logs_dir, run_id)
        try:
            size = path.stat().st_size
            with open(path, "rb+") as fh:
                fh.truncate(max(0, size - int(n_bytes)))
        except OSError:
            pass                # no journal to tear is not a failure

    # ------------------------------------------------------------- scenario

    def run_scenario(self) -> ScenarioResult:
        t0 = time.monotonic()
        deadline = t0 + SCENARIO_DEADLINE_S
        result = ScenarioResult(seed=self.plan.seed,
                                scenario=self.plan.scenario, ok=False,
                                plan_doc=self.plan.to_doc())
        faulted: set[int] = set()
        runner_error = False
        try:
            # inside the try: a scheduler that refuses the plan's spec
            # must still close the driver (lane/wedge daemon threads)
            # and report through the per-scenario violation path
            sched = self._start_generation()
            result.run_id = sched.loop_id
            for ev in sorted(self.plan.events, key=lambda e: e.at_s):
                # poll toward the event's time, servicing any fired
                # crash seam (kill -> torn tail -> resume) along the way
                while True:
                    self._service_kill()
                    now = time.monotonic()
                    if now >= t0 + ev.at_s:
                        break
                    time.sleep(min(0.01, t0 + ev.at_s - now))
                # gitguard scenarios interleave the deterministic push
                # probes with the schedule: one probe per event slot,
                # the remainder flushed after the heal -- probes before
                # a gitguard_down exercise enforcement, probes after it
                # prove fail-closed
                self._gitguard_probe()
                if ev.kind == "cli_sigkill":
                    self._arm_sigkill(ev)
                elif ev.kind == "gitguard_down":
                    # git-proxy faults hit the guard, never an engine:
                    # the worker stays unfaulted
                    self._apply_gitguard_fault(ev)
                elif ev.kind in ("workerd_partition", "workerd_kill"):
                    # data-plane faults hit the workerd channel/daemon,
                    # never the engine: the worker stays unfaulted
                    self._apply_workerd_fault(ev)
                elif ev.kind == "seed_cache_evict":
                    # seed-store faults hit workerd's resident cache,
                    # never the engine: the worker stays unfaulted
                    self._apply_seed_fault(ev)
                elif ev.kind == "index_down":
                    # monitor-stack faults hit the shipper's sink,
                    # never a worker: the fleet stays unfaulted
                    self._apply_index_fault(ev)
                elif ev.kind in ("traffic_burst", "scale_down"):
                    # capacity faults hit the admission queue / the
                    # elastic controller, never an engine: the worker
                    # stays unfaulted
                    self._apply_capacity_fault(ev)
                elif ev.kind in ("disk_full", "io_error", "fsync_fail",
                                 "torn_record"):
                    # storage faults hit the run journal's fd / bytes,
                    # never an engine: the worker stays unfaulted
                    self._apply_storage_fault(ev)
                elif ev.kind in ("egress_silent", "egress_flood",
                                 "sentinel_kill"):
                    # stream/collector faults: they hit the SENTINEL's
                    # inputs, never the workers -- the workers stay in
                    # the unfaulted set, so spurious-quarantine also
                    # proves stream chaos cannot open a breaker
                    self._apply_stream_fault(ev)
                elif ev.kind in ("pod_down", "pod_partition"):
                    # pod-scope faults gate EVERY worker at once: the
                    # whole fleet is faulted (the unfaulted set empties,
                    # so spurious-quarantine is vacuously satisfied) and
                    # the end-of-schedule heal revives the pod
                    faulted.update(range(self.plan.n_workers))
                    self._apply_worker_fault(ev)
                else:
                    if ev.kind != "worker_revive":
                        faulted.add(ev.worker)
                    self._apply_worker_fault(ev)
            # end of schedule: heal the fleet so the run can drain,
            # servicing seams fired late (and the resumes they trigger)
            for i in range(self.plan.n_workers):
                self.driver.clear_fault(i)
            # flush the rest of the push-probe script (a gitguard_down
            # in the schedule leaves these proving fail-closed); the
            # guard itself is NOT healed -- a dead guard stays dead for
            # the scenario, exactly the degrade the docs promise
            while self._gitguard_script:
                self._gitguard_probe()
            while time.monotonic() < deadline:
                self._service_kill()
                if self._run_done.is_set():
                    # armed seams the drained run never reached (e.g. a
                    # pool seam with the pool disabled) are not
                    # failures: disarm, then re-check for a fire that
                    # raced the disarm
                    for armed_sched, seam, _ev in self._armed:
                        armed_sched.seams.disarm(seam)
                    self._armed = [
                        e for e in self._armed if e[1] in e[0].seams.fired]
                    if not self._armed:
                        break
                time.sleep(0.01)
            else:
                self._sched.stop()
                self._run_done.wait(10.0)
                result.violations.append(
                    "stuck-run: the scenario did not drain within "
                    f"{SCENARIO_DEADLINE_S:.0f}s")
            if self._run_exc:
                result.violations.append(
                    f"scheduler-crash: {self._run_exc[0]!r}")
            final = self._sched
            if self.feeder is not None:
                self.feeder.stop()
            if self.sentinel is not None:
                self.sentinel.stop()
            if self.shipper is not None:
                # stop the pump, then one deterministic snapshot+flush
                # so the audit never races the tick cadence: a downed
                # index records its failed flush, a healthy one lands
                # the final docs, either way before the counters are
                # read.  A pump wedged in the sink (kill() False) must
                # NOT be raced -- the fake sink's stall bound drains it
                # well inside the scenario deadline, so retry once.
                if not self.shipper.kill():
                    if self.index is not None:
                        self.index.unstall()
                    self.shipper.kill()
                self.shipper.snapshot_once()
                self.shipper.flush_once(budget_s=0.5)
            final.cleanup(remove_containers=True)
            unfaulted = {w.id for i, w in enumerate(self.driver.workers())
                         if i not in faulted}
            result.violations.extend(check_invariants(
                self.driver, self.cfg, final.loop_id,
                loops=final.loops, cap=self.plan.max_inflight_per_worker,
                unfaulted=unfaulted, health=final.health,
                kills=self.kills, sentinel=self.sentinel,
                workerd=self._workerd_audit(),
                shipper=self._shipper_audit(),
                gitguard=self._gitguard_audit(),
                storage=self._storage_audit()))
        except ClawkerError as e:
            runner_error = True
            result.violations.append(f"runner-error: {e}")
        finally:
            if self.feeder is not None:
                self.feeder.stop()
            if self.sentinel is not None:
                self.sentinel.stop()
            if self.shipper is not None:
                self.shipper.kill()
            if self.index is not None:
                self.index.unstall()    # release any wedged sink thread
            if self.gitguard_srv is not None:
                self.gitguard_srv.close()
            if self.executors is not None:
                self.executors.close_all()
            for srv in self.workerd_servers:
                srv.stop()
            self.driver.close()
        result.kills = self.kills
        result.generations = self.generations
        result.injected = self.injected
        result.wall_s = time.monotonic() - t0
        result.ok = not result.violations
        for v in result.violations:
            _VIOLATIONS.labels(v.split(":", 1)[0]).inc()
        _SCENARIOS.labels(
            "ok" if result.ok
            else ("error" if runner_error else "violated")).inc()
        return result


# ------------------------------------------------------------------- soak


def _fresh_cfg():
    """An isolated project config per scenario: each scenario gets its
    own logs/journal tree so invariant audits never cross-read."""
    from .. import consts
    from ..config import load_config
    from ..testenv import TestEnv

    env = TestEnv()
    env.__enter__()
    proj = env.base / "proj"
    proj.mkdir()
    (proj / consts.PROJECT_FLAT_FORM).write_text("project: chaosproj\n")
    return env, load_config(proj)


def run_plan(plan: FaultPlan, *, cfg=None, on_event=None) -> ScenarioResult:
    """Execute ONE plan (replay entry point).  With no ``cfg`` a
    throwaway isolated project is created and torn down."""
    env = None
    if cfg is None:
        env, cfg = _fresh_cfg()
    try:
        return ChaosRunner(cfg, plan, on_event=on_event).run_scenario()
    finally:
        if env is not None:
            env.__exit__(None, None, None)


def shrink_plan(plan: FaultPlan, *, rounds: int = 2,
                budget_s: float = 120.0) -> tuple[FaultPlan,
                                                  ScenarioResult]:
    """Greedy delta-debug a FAILING plan down to a minimal repro: try
    dropping one event at a time; keep any reduction that still
    violates an invariant.  Returns (smallest failing plan, its
    result).  Bounded two ways: at most ``rounds`` full passes over the
    event list (each event re-runs one scenario), and at most
    ``budget_s`` of wall clock -- a stuck-run failure burns the full
    scenario deadline PER TRIAL, and a shrink that outlives the caller's
    timeout would discard the very report it exists to produce; on
    budget exhaustion the smallest plan found so far is returned."""
    import dataclasses

    t0 = time.monotonic()
    best = plan
    best_result = run_plan(plan)
    if best_result.ok:
        return plan, best_result    # not failing (flaky?); nothing to shrink
    for _ in range(rounds):
        reduced_any = False
        i = 0
        while i < len(best.events):
            if time.monotonic() - t0 > budget_s:
                return best, best_result
            trial = dataclasses.replace(
                best, events=best.events[:i] + best.events[i + 1:])
            res = run_plan(trial)
            if not res.ok:
                best, best_result = trial, res
                reduced_any = True      # same index now names the next event
            else:
                i += 1
        if not reduced_any:
            break
    return best, best_result


def run_observe_only_check(seed: int = 20260803, *, n_workers: int = 4,
                           n_loops: int = 6, iterations: int = 1,
                           ) -> list[str] | None:
    """The observe-only TWIN check: run the same fixed-seed benign fleet
    twice -- once bare, once with the sentinel attached AND its streams
    chaosed (silence + flood mid-run) -- and require byte-identical
    scheduling outcomes (journaled placements, daemon-side create
    counts, terminal statuses; invariants.scheduling_outcome).  No
    worker faults: with a healthy fleet the scheduler is deterministic,
    so ANY divergence is the sentinel leaking into scheduling.
    Returns violations ([] = the observe-only contract holds), or
    ``None`` when the sentinel cannot attach on this host (no jax) --
    a contract that was never exercised must report SKIPPED, never
    verified.  Runs in the fixed-seed soak (run_soak) and
    tests/test_sentinel.py.
    """
    if not ChaosRunner._sentinel_available():
        return None
    from ..engine.fake import exit_behavior
    from ..loop import LoopScheduler, LoopSpec

    def one(with_sentinel: bool) -> dict:
        from ..engine.drivers import FakeDriver

        env, cfg = _fresh_cfg()
        driver = FakeDriver(n_workers=n_workers)
        sentinel = feeder = None
        try:
            for api in driver.apis:
                api.add_image(IMAGE)
                api.set_behavior(IMAGE, exit_behavior(b"", 0, delay=0.02))
            spec = LoopSpec(parallel=n_loops, iterations=iterations,
                            image=IMAGE, agent_prefix="twin",
                            orphan_grace_s=20.0)
            sched = LoopScheduler(cfg, driver, spec)
            if with_sentinel and ChaosRunner._sentinel_available():
                from ..sentinel import FleetSentinel

                feeder = _EgressFeeder(
                    cfg, [w.id for w in driver.workers()]).start()
                sentinel = FleetSentinel(
                    cfg, driver, interval_s=0.1,
                    train_steps=SENTINEL_TRAIN_STEPS).start()
                sched.attach_sentinel(sentinel)
            sched.start()
            if feeder is not None:
                # stream chaos mid-run: silence one worker, flood another
                feeder.silence(0)
                feeder.flood(min(1, n_workers - 1), 120)
            loops = sched.run(poll_s=0.05)
            if sentinel is not None:
                sentinel.refresh_once()     # at least one scored tick
                sentinel.stop()
            if feeder is not None:
                feeder.stop()
            sched.cleanup(remove_containers=True)
            return scheduling_outcome(driver, cfg, sched.loop_id, loops)
        finally:
            if sentinel is not None:
                sentinel.stop()
            if feeder is not None:
                feeder.stop()
            driver.close()
            env.__exit__(None, None, None)

    del seed  # the twin fleet is deterministic; kept for repro symmetry
    baseline = one(False)
    with_sentinel = one(True)
    return observe_only_violations(baseline, with_sentinel)


def run_soak(scenarios: int, seed: int, *, n_workers: int = 4,
             n_loops: int = 6, iterations: int = 2, on_event=None,
             shrink: bool = True, keep_going: bool = False,
             on_progress=None, cfg=None) -> dict:
    """Run ``scenarios`` seeded scenarios; stop at (and shrink) the
    first failure unless ``keep_going``.  Returns the soak report doc
    ``{ok, scenarios, passed, failures: [...]}``.  With ``cfg`` the
    scenarios journal under that project's logs dir (run ids keep them
    apart); otherwise each gets a throwaway isolated environment."""
    report: dict = {"seed": seed, "scenarios": scenarios, "passed": 0,
                    "failures": [], "wall_s": 0.0, "kills": 0,
                    "injected": 0}
    t0 = time.monotonic()
    for i in range(scenarios):
        plan = generate_plan(seed, i, n_workers=n_workers, n_loops=n_loops,
                             iterations=iterations)
        env = None
        scen_cfg = cfg
        if scen_cfg is None:
            env, scen_cfg = _fresh_cfg()
        try:
            result = ChaosRunner(scen_cfg, plan,
                                 on_event=on_event).run_scenario()
        finally:
            if env is not None:
                env.__exit__(None, None, None)
        report["kills"] += result.kills
        report["injected"] += result.injected
        if on_progress is not None:
            on_progress(result)
        if result.ok:
            report["passed"] += 1
            continue
        failure = result.to_doc()
        # the repro must pin the FLEET SHAPE too: generate_plan draws
        # victims from range(n_workers), so replaying a non-default
        # soak's (seed, i) under default shape yields a different
        # schedule entirely
        failure["repro"] = (
            f"clawker chaos replay --seed {seed} --scenario {i} "
            f"--workers {n_workers} --parallel {n_loops} "
            f"--iterations {iterations}")
        if shrink:
            minimal, min_result = shrink_plan(plan)
            failure["minimal_plan"] = minimal.to_doc()
            failure["minimal_violations"] = list(min_result.violations)
        report["failures"].append(failure)
        if not keep_going:
            break
    # the observe-only twin rides every soak (fixed-seed sentinel
    # scenarios prove invariants hold WITH the sentinel; the twin proves
    # the sentinel changed nothing) -- skipped only when a failure
    # already stopped the soak early
    if not report["failures"] or keep_going:
        violations = run_observe_only_check(seed, n_workers=n_workers)
        if violations is None:
            report["observe_only"] = {"ok": None,
                                      "skipped": "jax unavailable -- "
                                                 "sentinel never attached"}
            violations = []
        else:
            report["observe_only"] = {"ok": not violations,
                                      "violations": violations}
        if violations:
            report["failures"].append({
                "scenario": "observe-only-twin",
                "violations": violations,
                "repro": "python -c 'from clawker_tpu.chaos.runner import "
                         "run_observe_only_check; "
                         "print(run_observe_only_check())'",
            })
    report["wall_s"] = round(time.monotonic() - t0, 2)
    report["ok"] = (not report["failures"]
                    and report["passed"] == scenarios)
    return report


# ---------------------------------------------------- live-run controller


class ChaosController:
    """Apply a fault plan to a LIVE scheduler (``loop --chaos-plan``).

    Worker fault events need an injectable driver (the fake pod); on
    real drivers they are skipped with a scheduler event.  A
    ``cli_sigkill`` event arms its crash seam with a REAL
    ``os.kill(getpid(), SIGKILL)`` -- the dev workflow for crash-testing
    ``--resume`` against a genuine process death."""

    def __init__(self, sched, driver, plan: FaultPlan):
        self.sched = sched
        self.driver = driver
        self.plan = plan
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        if not isinstance(sched.seams, SeamRegistry):
            sched.seams = SeamRegistry()

    def start(self) -> "ChaosController":
        self._thread = threading.Thread(target=self._drive, daemon=True,
                                        name="chaos-controller")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def _drive(self) -> None:
        injectable = hasattr(self.driver, "inject_fault")
        t0 = time.monotonic()
        for ev in sorted(self.plan.events, key=lambda e: e.at_s):
            if self._stop.wait(max(0.0, t0 + ev.at_s - time.monotonic())):
                return
            if ev.kind == "cli_sigkill":
                seam = str(ev.arg)

                def die(seam: str = seam) -> None:
                    log.warning("chaos: SIGKILL at seam %s", seam)
                    os.kill(os.getpid(), signal.SIGKILL)

                self.sched.seams.arm(seam, die)
                _INJECTIONS.labels(ev.kind).inc()
                continue
            if ev.kind in ("traffic_burst", "scale_down"):
                # capacity events act on the live scheduler's admission
                # queue / attached controller, not the driver.  Index
                # into the ALL-workers view where the driver has one: a
                # scale_down earlier in this same plan may have shrunk
                # workers(), and the fixed-seed schedule's indices must
                # keep naming the workers the generator chose
                all_workers = getattr(self.driver, "all_workers", None)
                workers = (all_workers() if all_workers is not None
                           else self.driver.workers())
                if not 0 <= ev.worker < len(workers):
                    self.sched.on_event(
                        "chaos", "skipped",
                        f"{ev.kind} worker={ev.worker}: outside the "
                        f"{len(workers)}-worker fleet")
                    continue
                wid = workers[ev.worker].id
                if ev.kind == "traffic_burst":
                    # each synthetic arrival holds its token briefly
                    # (like a short launch) so the queue genuinely
                    # deepens -- an instant release would exert zero
                    # admission pressure
                    def hold(release) -> None:
                        t = threading.Timer(0.03, release)
                        t.daemon = True
                        t.start()

                    for _ in range(int(ev.arg or 10)):
                        self.sched.admission.submit(wid, "~burst", hold)
                    _INJECTIONS.labels(ev.kind).inc()
                elif self.sched.capacity is not None:
                    self.sched.capacity.request_drain(wid)
                    _INJECTIONS.labels(ev.kind).inc()
                else:
                    self.sched.on_event(
                        "chaos", "skipped",
                        f"{ev.kind}: no capacity controller attached")
                continue
            if ev.kind == "seed_cache_evict":
                # the seed store lives inside the worker's workerd
                # daemon; a live CLI run does not own those processes
                self.sched.on_event(
                    "chaos", "skipped",
                    f"{ev.kind}: seed stores are workerd-resident "
                    "(use the soak runner / `clawker chaos run`)")
                continue
            if ev.kind in ("disk_full", "io_error", "fsync_fail"):
                # storage faults arm the LIVE run's journal fd (the
                # fail-loud contract under test end-to-end); the
                # journal recovers on a fresh fd and the run degrades
                # per settings loop.journal.on_fault
                import errno

                from ..testenv import FaultFS

                journal = getattr(self.sched, "journal", None)
                shim = (FaultFS.install(journal)
                        if journal is not None else None)
                if shim is None:
                    self.sched.on_event(
                        "chaos", "skipped",
                        f"{ev.kind}: no healthy journal on this run")
                    continue
                n = max(1, int(ev.arg or 1))
                if ev.kind == "fsync_fail":
                    shim.fail_fsyncs(n)
                else:
                    shim.fail_writes(n, errno_=(
                        errno.ENOSPC if ev.kind == "disk_full"
                        else errno.EIO))
                _INJECTIONS.labels(ev.kind).inc()
                self.sched.on_event("chaos", "injected",
                                    f"{ev.kind} n={n}")
                continue
            if ev.kind == "torn_record":
                # corrupting a LIVE user journal in place would destroy
                # real crash evidence: soak-runner only
                self.sched.on_event(
                    "chaos", "skipped",
                    f"{ev.kind}: destructive to a live journal (use "
                    "the soak runner / `clawker chaos run`)")
                continue
            if ev.kind == "gitguard_down":
                # kill the live run's git firewall proxy: every later
                # agent push must fail CLOSED (the egress lane pins
                # leave no other git path; docs/git-policy.md)
                guard = getattr(self.sched, "gitguard", None)
                if guard is not None:
                    guard.close()
                    _INJECTIONS.labels(ev.kind).inc()
                    self.sched.on_event("chaos", "injected",
                                        "gitguard_down (fail-closed)")
                else:
                    self.sched.on_event(
                        "chaos", "skipped",
                        f"{ev.kind}: no gitguard attached to this run")
                continue
            if ev.kind in POD_GATE_MODE:
                # pod-scope faults target every worker, no index check
                if not injectable:
                    self.sched.on_event(
                        "chaos", "skipped",
                        f"{ev.kind}: driver "
                        f"{getattr(self.driver, 'name', '?')} is not "
                        "fault-injectable")
                    continue
                apply_fault(self.driver, ev)
                _INJECTIONS.labels(ev.kind).inc()
                self.sched.on_event("chaos", "injected",
                                    f"{ev.kind} (whole pod)")
                continue
            if not injectable:
                self.sched.on_event(
                    "chaos", "skipped",
                    f"{ev.kind} on worker {ev.worker}: driver "
                    f"{getattr(self.driver, 'name', '?')} is not "
                    "fault-injectable")
                continue
            if not 0 <= ev.worker < len(self.driver.workers()):
                # a plan generated for a different fleet shape: skip
                # visibly instead of letting an IndexError kill this
                # thread and silently drop the rest of the schedule
                self.sched.on_event(
                    "chaos", "skipped",
                    f"{ev.kind} worker={ev.worker}: outside the "
                    f"{len(self.driver.workers())}-worker fleet")
                continue
            apply_fault(self.driver, ev)
            _INJECTIONS.labels(ev.kind).inc()
            self.sched.on_event("chaos", "injected",
                                f"{ev.kind} worker={ev.worker}")
