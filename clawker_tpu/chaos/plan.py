"""Seeded, serializable fault plans: every soak failure is a repro.

A :class:`FaultPlan` is the complete description of one chaos scenario:
the fleet shape (workers/loops/iterations/warm-pool/failover) plus a
time-ordered schedule of :class:`FaultEvent` injections.  Plans are
generated deterministically from ``(seed, scenario)`` --
``generate_plan(seed, i)`` always yields the same plan on every
machine -- and serialize to/from JSON, so a failure found during a
1000-scenario soak replays from either its ``--seed``/``--scenario``
pair or its saved plan file (``clawker chaos replay``).

Event kinds and where they inject:

======================  ====================================================
kind                    injection point
======================  ====================================================
``worker_kill``         _FaultGate ``refuse``: every call dials ECONNREFUSED
``worker_wedge``        _FaultGate ``wedge``: every call hangs until revive
``worker_flap``         _FaultGate ``flap``: every other call refused
``worker_slow``         _FaultGate ``slow``: slow-loris, +``arg`` s per call
``engine_burst``        _FaultGate ``burst``: next ``arg`` calls fail like a
                        daemon 5xx / mid-response ECONNRESET, then self-heal
``probe_drop``          _FaultGate ``probe_drop``: ``ping`` fails (dropped
                        SSH-mux probe), data-path calls still succeed
``worker_revive``       clear the worker's fault
``cli_sigkill``         arm crash seam ``arg`` (chaos/seams.py): the
                        scheduler dies there mid-flight, optionally with
                        ``torn_tail`` bytes truncated off the journal, and
                        the runner resumes the run (`--resume` semantics)
``egress_silent``       sentinel scenarios: the worker's egress stream
                        stops mid-run (netlogger death / firewall gap)
``egress_flood``        sentinel scenarios: the worker's stream bursts
                        ``arg`` records at once (log storm)
``sentinel_kill``       SIGKILL the sentinel's collector mid-run: scoring
                        degrades to the stale buffer; the fleet must not
                        notice (observe-only invariant)
``workerd_partition``   drop the worker's workerd intent channel mid-run
                        (the daemon lives): pending intents survive, the
                        executor redials + resyncs, buffered events
                        replay -- no duplicate creates, no lost exits
``workerd_kill``        SIGKILL the worker's workerd: pending intents hit
                        the deadline and strand their loops WITHOUT a
                        breaker penalty; the fleet degrades that worker
                        to the direct WAN path and still drains
``index_down``          shipper scenarios: the monitor stack's bulk index
                        goes down (``arg: "stall"`` wedges it inside the
                        sink deadline instead) mid-run -- the telemetry
                        shipper must degrade observe-only: bounded buffer,
                        oldest batches dropped and counted, the bus and
                        every scheduler lane untouched
``traffic_burst``       capacity scenarios: ``arg`` open-loop synthetic
                        arrivals spike the worker's admission queue (the
                        bursty production shape the elastic controller
                        exists for); real launches must still drain and
                        every standard invariant hold
``scale_down``          capacity scenarios: ask the elastic controller to
                        drain the worker -- the drain must stay gated on
                        journal replay proving zero live placements
                        (``stranded-by-drain`` invariant), deferring for
                        as long as the run keeps the worker busy
``seed_cache_evict``    workerd scenarios: drop the worker's resident
                        workspace-seed store mid-run (restart-equivalent
                        cold cache) -- later creates referencing the
                        digest must degrade to the per-create fallback
                        walk, never fail or cross-seed another agent
``pod_down``            federation scenarios: EVERY worker's daemon dials
                        ECONNREFUSED at once (the whole pod's control
                        plane dies -- VM group preempted, loopd host
                        gone); the federation router must migrate the
                        pod's runs onto survivors exactly-once
``pod_partition``       federation scenarios: every worker's probe
                        channel drops while data paths stay up (DCN
                        partition between front tier and pod): health
                        must not condemn the whole pod without
                        corroboration, lease renews lapse and recover
``gitguard_down``       gitguard scenarios: kill the run's git firewall
                        proxy mid-run -- every later push attempt must
                        fail CLOSED (connection refused, journaled
                        ``down_refused``), never fall through to an
                        unguarded path (``ref-isolation-at-proxy``)
``disk_full``           storage scenarios: the run journal's fd starts
                        returning ENOSPC for ``arg`` writes
                        (testenv.FaultFS) -- durable appends must fail
                        LOUDLY (storage.fault event, degraded
                        durability, strand-without-penalty on
                        placement WAL), never silently succeed
``io_error``            storage scenarios: like ``disk_full`` but EIO
                        -- the generic dying-disk write error
``fsync_fail``          storage scenarios: the next ``arg`` fsyncs on
                        the journal fd raise EIO; the writer must
                        reopen + re-append the unsynced ring, NEVER
                        retry fsync on the poisoned fd
``torn_record``         storage scenarios: flip one bit mid-journal
                        (``arg: "flip"``) or truncate at the last
                        synced offset (power cut) -- ``journal
                        verify`` must flag it and a resume must fold
                        only the verified prefix
======================  ====================================================

Plans with ``sentinel: true`` run with the fleet sentinel attached to
the scheduler (and per-worker synthetic egress feeders for the
``egress_*`` events); the standard invariant audit then proves the
robustness stack holds WITH the sentinel riding along, and the
dedicated observe-only check (runner.run_observe_only_check) proves
sentinel presence changes no scheduling outcome.
"""

from __future__ import annotations

import json
import random
from dataclasses import dataclass, field
from pathlib import Path

from ..errors import ClawkerError
from .seams import SEAM_NAMES

EVENT_KINDS = (
    "worker_kill", "worker_wedge", "worker_flap", "worker_slow",
    "engine_burst", "probe_drop", "worker_revive", "cli_sigkill",
    "egress_silent", "egress_flood", "sentinel_kill",
    "workerd_partition", "workerd_kill", "index_down",
    "traffic_burst", "scale_down", "seed_cache_evict",
    "pod_down", "pod_partition", "gitguard_down",
    "disk_full", "io_error", "fsync_fail", "torn_record",
)

# event kinds that target no worker (worker index is ignored)
_WORKERLESS_KINDS = ("cli_sigkill", "sentinel_kill", "index_down",
                     "pod_down", "pod_partition", "gitguard_down",
                     "disk_full", "io_error", "fsync_fail", "torn_record")

# fault gate modes the worker_* / engine_* / probe_* kinds map onto
GATE_MODE = {
    "worker_kill": "refuse",
    "worker_wedge": "wedge",
    "worker_flap": "flap",
    "worker_slow": "slow",
    "engine_burst": "burst",
    "probe_drop": "probe_drop",
}

# fault gate modes the pod-scope kinds map onto, applied to EVERY
# worker's gate at once (docs/federation.md#chaos): a dead pod refuses
# all dials; a partitioned pod drops probes while data paths serve
POD_GATE_MODE = {
    "pod_down": "refuse",
    "pod_partition": "probe_drop",
}


@dataclass
class FaultEvent:
    """One injection: ``at_s`` seconds into the scenario, ``kind``
    against worker index ``worker`` (ignored for ``cli_sigkill``).
    ``arg`` is kind-specific: burst length for ``engine_burst``,
    per-call delay for ``worker_slow``, seam name for ``cli_sigkill``.
    ``torn_tail`` (cli_sigkill only) truncates that many bytes off the
    journal tail after the kill -- the host-crash torn-write case."""

    at_s: float
    kind: str
    worker: int = 0
    arg: object = None
    torn_tail: int = 0

    def to_doc(self) -> dict:
        doc = {"at_s": round(self.at_s, 3), "kind": self.kind,
               "worker": self.worker}
        if self.arg is not None:
            doc["arg"] = self.arg
        if self.torn_tail:
            doc["torn_tail"] = self.torn_tail
        return doc

    @classmethod
    def from_doc(cls, doc: dict) -> "FaultEvent":
        kind = str(doc.get("kind", ""))
        if kind not in EVENT_KINDS:
            raise ClawkerError(
                f"chaos plan: unknown event kind {kind!r} "
                f"(expected {'|'.join(EVENT_KINDS)})")
        return cls(at_s=float(doc.get("at_s", 0.0)), kind=kind,
                   worker=int(doc.get("worker", 0)),
                   arg=doc.get("arg"),
                   torn_tail=int(doc.get("torn_tail", 0)))


@dataclass
class FaultPlan:
    """One scenario: fleet shape + injection schedule."""

    seed: int
    scenario: int = 0
    n_workers: int = 4
    n_loops: int = 6
    iterations: int = 2
    failover: str = "migrate"
    warm_pool_depth: int = 0
    max_inflight_per_worker: int = 2
    sentinel: bool = False          # run with the fleet sentinel attached
    workerd: bool = False           # run with per-worker workerd executors
    shipper: bool = False           # run with the telemetry shipper attached
    capacity: bool = False          # run with the elastic-capacity
    #                                 controller attached
    gitguard: bool = False          # run with a git firewall proxy + a
    #                                 deterministic push probe schedule
    #                                 (docs/git-policy.md)
    events: list[FaultEvent] = field(default_factory=list)

    @property
    def name(self) -> str:
        return f"chaos-s{self.seed}-{self.scenario}"

    def to_doc(self) -> dict:
        return {
            "seed": self.seed, "scenario": self.scenario,
            "n_workers": self.n_workers, "n_loops": self.n_loops,
            "iterations": self.iterations, "failover": self.failover,
            "warm_pool_depth": self.warm_pool_depth,
            "max_inflight_per_worker": self.max_inflight_per_worker,
            "sentinel": self.sentinel,
            "workerd": self.workerd,
            "shipper": self.shipper,
            "capacity": self.capacity,
            "gitguard": self.gitguard,
            "events": [e.to_doc() for e in sorted(self.events,
                                                  key=lambda e: e.at_s)],
        }

    def to_json(self) -> str:
        return json.dumps(self.to_doc(), indent=2) + "\n"

    @classmethod
    def from_doc(cls, doc: dict) -> "FaultPlan":
        plan = cls(
            seed=int(doc.get("seed", 0)),
            scenario=int(doc.get("scenario", 0)),
            n_workers=max(1, int(doc.get("n_workers", 4))),
            n_loops=max(1, int(doc.get("n_loops", 6))),
            iterations=max(1, int(doc.get("iterations", 2))),
            failover=str(doc.get("failover", "migrate")),
            warm_pool_depth=int(doc.get("warm_pool_depth", 0)),
            max_inflight_per_worker=int(
                doc.get("max_inflight_per_worker", 2)),
            sentinel=bool(doc.get("sentinel", False)),
            workerd=bool(doc.get("workerd", False)),
            shipper=bool(doc.get("shipper", False)),
            capacity=bool(doc.get("capacity", False)),
            gitguard=bool(doc.get("gitguard", False)),
            events=[FaultEvent.from_doc(e) for e in doc.get("events") or []],
        )
        _validate(plan)
        return plan

    @classmethod
    def load(cls, path: Path | str) -> "FaultPlan":
        try:
            doc = json.loads(Path(path).read_text(encoding="utf-8"))
        except (OSError, ValueError) as e:
            raise ClawkerError(f"chaos plan {path}: {e}") from e
        if not isinstance(doc, dict):
            raise ClawkerError(f"chaos plan {path}: expected a JSON object")
        return cls.from_doc(doc)

    def save(self, path: Path | str) -> Path:
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(self.to_json(), encoding="utf-8")
        return path


# the sigkill seams worth crashing at, weighted toward the WAL-to-engine
# gaps that historically hid bugs (ISSUE 8); resume.* seams only make
# sense once a generation is already a resume, so the generator uses
# them for the SECOND kill of a scenario
_KILL_SEAMS_GEN1 = ("run.post_placement", "launch.pre_create",
                    "launch.post_create", "launch.pre_start",
                    "launch.post_start", "iteration.post_exit",
                    "pool.post_fill")
_KILL_SEAMS_GEN2 = ("resume.pre_reconcile", "resume.post_adopt",
                    "launch.post_start", "iteration.post_exit")


def generate_plan(seed: int, scenario: int = 0, *, n_workers: int = 4,
                  n_loops: int = 6, iterations: int = 2,
                  horizon_s: float = 0.9) -> FaultPlan:
    """Deterministic plan for ``(seed, scenario)``.

    Every scenario gets 1-4 fault events inside ``horizon_s``; kills and
    wedges are always paired with a revive so the fleet can finish, and
    roughly half the scenarios include a CLI SIGKILL at a named crash
    seam (with a resume leg), a third of those with a torn journal
    tail.  ``random.Random`` is seeded from the (seed, scenario) pair
    alone -- no global state, no time, no machine dependence.
    """
    rng = random.Random((int(seed) & 0xFFFFFFFF) * 100_003 + int(scenario))
    plan = FaultPlan(
        seed=int(seed), scenario=int(scenario), n_workers=n_workers,
        n_loops=n_loops, iterations=iterations,
        failover=rng.choice(("migrate", "migrate", "wait")),
        warm_pool_depth=rng.choice((0, 0, 1)),
        max_inflight_per_worker=rng.choice((2, 2, 3)),
    )
    events: list[FaultEvent] = []
    n_worker_faults = rng.randint(1, 2)
    victims = rng.sample(range(n_workers), k=min(n_worker_faults, n_workers))
    for victim in victims:
        kind = rng.choice(("worker_kill", "worker_kill", "worker_wedge",
                           "worker_flap", "worker_slow", "engine_burst",
                           "probe_drop"))
        at = rng.uniform(0.05, horizon_s * 0.6)
        arg = None
        if kind == "worker_slow":
            arg = round(rng.uniform(0.05, 0.2), 3)
        elif kind == "engine_burst":
            arg = rng.randint(2, 6)
        events.append(FaultEvent(at_s=at, kind=kind, worker=victim, arg=arg))
        if kind in ("worker_kill", "worker_wedge", "worker_flap",
                    "worker_slow", "probe_drop"):
            # bounded outage: the scenario must be able to drain
            events.append(FaultEvent(
                at_s=at + rng.uniform(0.2, horizon_s * 0.5),
                kind="worker_revive", worker=victim))
    if rng.random() < 0.6:
        # early arms catch the run while launches are still in flight;
        # seams that never fire (the run drained first) are benign
        seam = rng.choice(_KILL_SEAMS_GEN1)
        torn = rng.choice((0, 0, rng.randint(1, 40)))
        events.append(FaultEvent(
            at_s=rng.uniform(0.02, horizon_s * 0.5), kind="cli_sigkill",
            worker=-1, arg=seam, torn_tail=torn))
        if rng.random() < 0.4:
            seam2 = rng.choice(_KILL_SEAMS_GEN2)
            events.append(FaultEvent(
                at_s=rng.uniform(0.05, horizon_s * 0.6), kind="cli_sigkill",
                worker=-1, arg=seam2))
    # sentinel rider (drawn strictly AFTER every pre-existing draw, so
    # the worker-fault/sigkill schedule of a (seed, scenario) pair is
    # byte-identical to what it was before the sentinel existed): about
    # a third of scenarios run with the fleet sentinel attached, plus
    # stream chaos against it -- silence, floods, a collector SIGKILL
    if rng.random() < 0.35:
        plan.sentinel = True
        victim = rng.randrange(n_workers)
        kind = rng.choice(("egress_silent", "egress_flood", "egress_flood"))
        events.append(FaultEvent(
            at_s=rng.uniform(0.05, horizon_s * 0.6), kind=kind,
            worker=victim,
            arg=rng.randint(50, 200) if kind == "egress_flood" else None))
        if rng.random() < 0.4:
            events.append(FaultEvent(
                at_s=rng.uniform(0.1, horizon_s * 0.7),
                kind="sentinel_kill", worker=-1))
    # workerd rider (again drawn strictly AFTER every pre-existing draw,
    # sentinel's included -- the worker-fault/sigkill/sentinel schedule
    # of a (seed, scenario) pair is byte-identical to the pre-workerd
    # generator): about a third of scenarios run with per-worker
    # workerd executors attached, most of those with data-plane chaos
    # against one channel -- a partition (heals via redial + resync) or
    # a daemon SIGKILL (degrades that worker to the direct WAN path).
    # The generated cli_sigkill seams above stay drawn from the
    # pre-workerd pools for the same reason; the workerd.* seams are
    # reachable via hand-written plans and the optional draw below.
    if rng.random() < 0.35:
        plan.workerd = True
        if rng.random() < 0.75:
            victim = rng.randrange(n_workers)
            kind = rng.choice(("workerd_partition", "workerd_partition",
                               "workerd_kill"))
            events.append(FaultEvent(
                at_s=rng.uniform(0.05, horizon_s * 0.6), kind=kind,
                worker=victim))
        if rng.random() < 0.25:
            events.append(FaultEvent(
                at_s=rng.uniform(0.02, horizon_s * 0.4),
                kind="cli_sigkill", worker=-1,
                arg="workerd.pre_dispatch"))
    # shipper rider (drawn strictly AFTER every pre-existing draw, so
    # the worker-fault/sigkill/sentinel/workerd schedule of a (seed,
    # scenario) pair is byte-identical to the pre-shipper generator):
    # about a quarter of scenarios run with the telemetry shipper
    # attached to a fake bulk index, most of those with the index going
    # down (or wedging) mid-run -- the observe-only degradation the
    # fleet-console ingestion contract promises
    if rng.random() < 0.25:
        plan.shipper = True
        if rng.random() < 0.8:
            events.append(FaultEvent(
                at_s=rng.uniform(0.05, horizon_s * 0.6),
                kind="index_down", worker=-1,
                arg="stall" if rng.random() < 0.3 else None))
    # capacity rider (drawn strictly AFTER every pre-existing draw, so
    # the worker-fault/sigkill/sentinel/workerd/shipper schedule of a
    # (seed, scenario) pair is byte-identical to the pre-capacity
    # generator): about a third of scenarios run with the elastic
    # controller attached -- most with an open-loop traffic burst
    # spiking one worker's admission queue, and roughly half asking
    # for a scale-down whose drain must stay gated on journal replay
    # (the stranded-by-drain invariant audits every drain that fires)
    if rng.random() < 0.35:
        plan.capacity = True
        if rng.random() < 0.8:
            events.append(FaultEvent(
                at_s=rng.uniform(0.05, horizon_s * 0.5),
                kind="traffic_burst", worker=rng.randrange(n_workers),
                arg=rng.randint(6, 18)))
        if rng.random() < 0.5:
            events.append(FaultEvent(
                at_s=rng.uniform(0.1, horizon_s * 0.7),
                kind="scale_down", worker=rng.randrange(n_workers)))
    # seed-cache rider (drawn strictly AFTER every pre-existing draw, so
    # the worker-fault/sigkill/sentinel/workerd/shipper/capacity
    # schedule of a (seed, scenario) pair is byte-identical to the
    # pre-seed-cache generator): scenarios already running workerd get
    # their resident workspace-seed store dropped mid-run about a third
    # of the time -- later digest-referencing creates must degrade to
    # the per-create fallback walk, and no agent may ever see another
    # agent's workspace content (the cross-agent-write invariant)
    if plan.workerd and rng.random() < 0.35:
        events.append(FaultEvent(
            at_s=rng.uniform(0.05, horizon_s * 0.6),
            kind="seed_cache_evict", worker=rng.randrange(n_workers)))
    # pod rider (drawn strictly AFTER every pre-existing draw, so the
    # worker-fault/sigkill/sentinel/workerd/shipper/capacity/seed-cache
    # schedule of a (seed, scenario) pair is byte-identical to the
    # pre-federation generator): about a fifth of scenarios lose the
    # WHOLE pod at once -- every daemon refusing dials (pod_down) or
    # every probe channel dropping while data paths serve
    # (pod_partition).  Both revive at half-horizon + the usual bounded
    # outage via the runner's end-of-schedule heal, and the standard
    # invariant audit (exactly-once accounting included) must hold
    if rng.random() < 0.20:
        kind = "pod_down" if rng.random() < 0.5 else "pod_partition"
        events.append(FaultEvent(
            at_s=rng.uniform(0.1, horizon_s * 0.5), kind=kind, worker=-1))
    # gitguard rider (drawn strictly AFTER every pre-existing draw, so
    # the worker-fault/sigkill/sentinel/workerd/shipper/capacity/
    # seed-cache/pod schedule of a (seed, scenario) pair is
    # byte-identical to the pre-gitguard generator): about a third of
    # scenarios run a git firewall proxy with a deterministic push-probe
    # schedule riding the run (own-namespace allow, sibling deny,
    # integration-branch deny, an occasional merge-queue landing), and
    # roughly 40% of those kill the proxy mid-run -- every later probe
    # must fail CLOSED, never land an out-of-namespace update
    # (docs/git-policy.md; the ref-isolation-at-proxy invariant)
    if rng.random() < 0.35:
        plan.gitguard = True
        if rng.random() < 0.4:
            events.append(FaultEvent(
                at_s=rng.uniform(0.1, horizon_s * 0.6),
                kind="gitguard_down", worker=-1))
    # storage rider (drawn strictly AFTER every pre-existing draw, so
    # the worker-fault/sigkill/sentinel/workerd/shipper/capacity/
    # seed-cache/pod/gitguard schedule of a (seed, scenario) pair is
    # byte-identical to the pre-storage generator): about a third of
    # scenarios hit the run journal's own disk -- write errors
    # (ENOSPC/EIO), an fsync-fail burst (the reopen-not-retry proof),
    # or a torn record (bit-flip/power-cut, audited by the
    # replay-integrity invariant).  Every fault must surface as a
    # storage.fault event + metric (the no-silent-drop invariant)
    if rng.random() < 0.35:
        kind = rng.choice(("disk_full", "io_error", "fsync_fail",
                           "fsync_fail", "torn_record"))
        arg = None
        if kind in ("disk_full", "io_error", "fsync_fail"):
            arg = rng.randint(1, 4)
        elif kind == "torn_record":
            arg = "flip" if rng.random() < 0.5 else "cut"
        events.append(FaultEvent(
            at_s=rng.uniform(0.05, horizon_s * 0.6), kind=kind,
            worker=-1, arg=arg))
    plan.events = sorted(events, key=lambda e: e.at_s)
    _validate(plan)
    return plan


def _validate(plan: FaultPlan) -> None:
    from ..loop.scheduler import FAILOVER_POLICIES

    if plan.failover not in FAILOVER_POLICIES:
        raise ClawkerError(
            f"chaos plan: unknown failover policy {plan.failover!r} "
            f"(expected {'|'.join(FAILOVER_POLICIES)})")
    for e in plan.events:
        if e.kind == "cli_sigkill" and e.arg not in SEAM_NAMES:
            raise ClawkerError(
                f"chaos plan: cli_sigkill at unknown seam {e.arg!r}")
        if e.kind not in _WORKERLESS_KINDS and not (
                -1 < e.worker < plan.n_workers):
            raise ClawkerError(
                f"chaos plan: event {e.kind} targets worker {e.worker} "
                f"outside the {plan.n_workers}-worker fleet")
