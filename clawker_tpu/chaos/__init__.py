"""Deterministic chaos injection + fleet invariant checking.

PRs 3-7 built the robustness layers one at a time (breakers/failover,
the run journal + ``--resume`` adoption, admission backpressure, warm
pools); this package proves the COMPOSITION survives compound faults.
Three pieces (docs/chaos.md):

- :mod:`.plan` -- a seeded, serializable **fault plan**: a schedule of
  injection events (worker kill/wedge/flap/slow-loris, engine 5xx /
  ECONNRESET bursts, probe drops, CLI SIGKILL at named crash seams with
  journal torn-tail truncation) generated deterministically from
  ``(seed, scenario)`` so every failure found in soak is a one-command
  repro.
- :mod:`.invariants` -- the post-scenario **cross-audit** of engine
  state vs journal replay vs telemetry: zero duplicate creates per
  (run, slot), zero leaked containers after cleanup (warm-pool members
  included), admission high-water <= cap per worker, no spurious
  quarantine, every loop terminally accounted exactly once, every exit
  accounted exactly once, span trees complete.
- :mod:`.runner` -- the **soak runner** behind ``clawker chaos run``:
  executes N seeded scenarios against a fake pod with kill/resume
  cycles, and shrinks a failing schedule to a minimal repro before
  reporting.

:mod:`.seams` holds the named crash-seam registry the scheduler fires
through (``loop/scheduler.py``): the enumerable replacement for ad-hoc
``kill()`` stubbing in crash tests.
"""

from .plan import EVENT_KINDS, FaultEvent, FaultPlan, generate_plan
from .seams import NULL_SEAMS, SEAM_NAMES, SeamAbort, SeamRegistry

__all__ = [
    "EVENT_KINDS", "FaultEvent", "FaultPlan", "generate_plan",
    "check_invariants",
    "ChaosController", "ChaosRunner", "ScenarioResult", "run_soak",
    "shrink_plan",
    "NULL_SEAMS", "SEAM_NAMES", "SeamAbort", "SeamRegistry",
]

_LAZY = {
    # the runner and invariant checker import the loop package, which
    # itself imports .seams at module load: resolving these lazily
    # keeps that edge acyclic
    "ChaosController": "runner", "ChaosRunner": "runner",
    "ScenarioResult": "runner", "run_soak": "runner", "shrink_plan": "runner",
    "check_invariants": "invariants",
}


def __getattr__(name: str):
    mod = _LAZY.get(name)
    if mod is not None:
        import importlib

        return getattr(importlib.import_module(f".{mod}", __name__), name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
