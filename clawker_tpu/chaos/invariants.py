"""Post-scenario fleet invariants: engine vs journal vs telemetry.

Each invariant cross-audits two independent records of the same run --
what the (fake) daemons actually executed (call recorder + live
container tables), what the write-ahead journal claims happened
(``replay()``), and what telemetry observed (flight-recorder span
trees, admission/gate high-water marks).  A violation means the
robustness composition (breakers + journal/resume + admission + warm
pools) lost track of reality under the injected faults -- exactly the
class of bug no single-layer test catches.

Invariant catalogue (names are the strings violations are prefixed
with; docs/chaos.md#invariants):

- ``terminal-accounting``: every (run, slot) loop ends in exactly one
  terminal state (done|failed|stopped), and the journal's last word per
  agent agrees with the scheduler's.
- ``exit-accounted-once``: no (agent, iteration) exit is journaled
  twice -- the double-accounting a kill/resume cycle must never cause.
- ``duplicate-create``: per worker daemon, container creates for one
  agent name never exceed that agent's journaled placements onto the
  worker (pool members: their journaled refills) -- every real create
  has a write-ahead record that authorized it.
- ``leaked-container``: after cleanup, no daemon holds ANY container
  labeled with the run id (warm-pool members included).
- ``admission-cap``: no worker daemon ever saw more concurrent
  create/start calls than the admission token bucket allows (gate
  high-water mark, measured daemon-side).
- ``spurious-quarantine``: a worker the plan never faulted ends with a
  CLOSED breaker -- faults must not splash onto healthy workers.
- ``span-tree``: the flight record parses, and (for scenarios without
  CLI kills) every span tree is rooted at a terminally-statused
  iteration root.
- ``trace-completeness``: the cross-process trace merge
  (docs/tracing.md) resolves the run to rooted trees.  Kill-free
  scenarios may not leave any bare root below the real top of the
  submit chain; under kills the bare-root audit loosens, but an
  iteration whose children prove a workerd launch must STILL hold
  either its remote segment or an explicit gap span -- a dead workerd
  degrades to a gap, never to a broken tree.
- ``sentinel-observe-only``: the fleet sentinel changes NO scheduling
  outcome.  Two halves: scenarios that ran with a sentinel attached
  audit its mutation counters (zero engine/breaker/placement calls --
  checked here via the ``sentinel`` param), and the dedicated twin
  check (:func:`observe_only_violations`, driven by
  ``runner.run_observe_only_check``) compares a fixed-seed run's
  journaled placements and daemon-side create counts with and without
  ``--sentinel``: they must be identical.
- ``worktree-isolation``: branch-per-agent provisioning never crosses
  agents.  Every journaled ``seed_worktree`` record maps one agent to
  exactly one (path, branch) pair, and no path or branch is ever
  claimed by two agents -- the zero-cross-agent-writes guarantee the
  swarm scenario rests on (docs/loop-worktrees.md).  A kill/resume
  cycle re-attaching worktrees must fold to the same single claim.
- ``stranded-by-drain``: a capacity scale-down never strands a
  journaled run (docs/elastic-capacity.md).  Folding the record stream
  in order with the same liveness rule the controller's journal-replay
  gate uses, every ``capacity_scale`` drain-done record must land at a
  point where its victim hosts no live loop or pool member.
- ``workerd-reconcile``: journaled intent reconciles on link heal.
  A channel that ends the scenario LIVE (any partition healed) must
  leave zero undelivered events on its daemon -- no lost exits -- and
  the standard ``duplicate-create`` audit above already proves no
  workerd-executed create exceeded its write-ahead placements (the
  worker-resident daemon mutates the same fake engine the recorder
  watches).  Intent dedup hits are legitimate (a re-sent intent across
  a partition); an intent executed with no placement to authorize it
  is not, and surfaces as duplicate-create.
- ``ref-isolation-at-proxy``: branch-per-agent ref isolation holds AT
  THE GIT PROXY (docs/git-policy.md).  Ground truth is the upstream's
  acknowledged-update log: no acknowledged update may ever name a ref
  outside its pusher's branch namespace (the sole exception being the
  merge-queue identity landing the integration branch), no allow
  verdict in the proxy's decision stream may name an out-of-namespace
  ref, and after a ``gitguard_down`` kill NOTHING may be acknowledged
  at all -- a dead guard fails closed, it never falls open.
- ``no-silent-drop``: a storage fault that actually fired (FaultFS
  shim counters) must surface -- as a counted scheduler fault AND a
  ``storage.fault`` bus event; a journal that dropped records must
  show degraded durability.  A poisoned or dropped write that
  surfaces nowhere is exactly the silent data loss the fail-loud WAL
  contract forbids (docs/durability.md).
- ``replay-integrity``: the checksummed journal fold reproduces the
  daemon's view of the run up to the declared fault point.  A verify
  pass reporting corruption is legitimate ONLY when the plan injected
  a torn record (bit-flip/power-cut), and the verified prefix must
  still fold to the run's own header -- a fold that lost the run id
  lost the WAL itself.
"""

from __future__ import annotations

from pathlib import Path

from .. import consts
from ..health import BREAKER_CLOSED

TERMINAL_STATUSES = ("done", "failed", "stopped")


def _daemon_view(driver) -> list:
    """(worker, api) pairs for every daemon the scenario ever had --
    the audit must include workers the capacity controller drained
    mid-run (their call recorders survive the fake VM deletion)."""
    all_workers = getattr(driver, "all_workers", None)
    workers = all_workers() if all_workers is not None else driver.workers()
    return list(zip(workers, driver.apis))


def check_invariants(driver, cfg, run_id: str, *, loops=None,
                     cap: int = 0, unfaulted: set[str] | None = None,
                     health=None, kills: int = 0,
                     sentinel=None, workerd=None,
                     shipper=None, gitguard=None,
                     storage=None) -> list[str]:
    """Audit one finished scenario; returns human-readable violations
    (empty list = all invariants hold).

    ``driver`` must be a :class:`~...engine.drivers.FakeDriver` (the
    call recorder and fault gates are the daemon-side evidence).
    ``loops`` are the FINAL generation's AgentLoop objects; ``cap`` the
    admission ``max_inflight_per_worker`` (0 skips the cap audit);
    ``unfaulted`` the worker ids the plan never touched; ``kills`` how
    many CLI SIGKILLs the scenario injected (crashed generations
    legitimately lose un-flushed spans, so the span audit loosens).
    """
    from ..loop.journal import (
        REC_CAPACITY_SCALE,
        REC_CAPACITY_TOKENS,
        REC_EXITED,
        REC_LOOP_END,
        REC_MIGRATED,
        REC_PLACEMENT,
        REC_POOL_ADD,
        REC_POOL_ADOPT,
        REC_POOL_READY,
        REC_POOL_REMOVE,
        REC_SEED_WORKTREE,
        RunJournal,
        journal_path,
        replay,
    )
    from ..monitor.ledger import flight_path
    from ..runtime.names import container_name
    from ..telemetry.spans import (
        SPAN_ITERATION,
        STANDALONE_SPANS,
        build_trees,
        load_spans,
    )

    violations: list[str] = []
    records = RunJournal.read(journal_path(cfg.logs_dir, run_id))
    image = replay(records)
    project = cfg.project_name()
    loops = list(loops or [])

    # --- terminal-accounting: scheduler statuses x journal last word
    for loop in loops:
        if loop.status not in TERMINAL_STATUSES:
            violations.append(
                f"terminal-accounting: loop {loop.agent} ended "
                f"{loop.status!r}, not a terminal state")
    by_agent_end: dict[str, list[str]] = {}
    for rec in records:
        if rec.get("kind") == REC_LOOP_END:
            by_agent_end.setdefault(str(rec.get("agent", "")), []).append(
                str(rec.get("status", "")))
    for loop in loops:
        ends = by_agent_end.get(loop.agent, [])
        if loop.status in TERMINAL_STATUSES and ends and \
                ends[-1] != loop.status:
            violations.append(
                f"terminal-accounting: journal says {loop.agent} ended "
                f"{ends[-1]!r} but the scheduler says {loop.status!r}")

    # --- exit-accounted-once: no (agent, iteration) journaled twice
    seen_exits: dict[tuple[str, int], int] = {}
    for rec in records:
        if rec.get("kind") == REC_EXITED:
            key = (str(rec.get("agent", "")), int(rec.get("iteration", -1)))
            seen_exits[key] = seen_exits.get(key, 0) + 1
    for (agent, iteration), n in sorted(seen_exits.items()):
        if n > 1:
            violations.append(
                f"exit-accounted-once: {agent} iteration {iteration} "
                f"accounted {n} times")

    # --- duplicate-create: daemon-side creates vs write-ahead records
    placements: dict[tuple[str, str], int] = {}   # (agent, worker) -> n
    for rec in records:
        if rec.get("kind") == REC_PLACEMENT:
            key = (str(rec.get("agent", "")), str(rec.get("worker", "")))
            placements[key] = placements.get(key, 0) + 1
        elif rec.get("kind") == REC_POOL_ADD:
            key = (str(rec.get("agent", "")), str(rec.get("worker", "")))
            placements[key] = placements.get(key, 0) + 1
    name_to_agent = {}
    for (agent, _w) in placements:
        name_to_agent[container_name(project, agent)] = agent
    for worker, api in _daemon_view(driver):
        creates: dict[str, int] = {}
        for (args, _kw) in api.calls_named("container_create"):
            cname = str(args[0]) if args else ""
            creates[cname] = creates.get(cname, 0) + 1
        for cname, n in sorted(creates.items()):
            agent = name_to_agent.get(cname)
            if agent is None:
                continue        # not this run's container
            allowed = placements.get((agent, worker.id), 0)
            if n > allowed:
                violations.append(
                    f"duplicate-create: {worker.id} executed {n} creates "
                    f"for {agent} but only {allowed} journaled "
                    "placement(s) authorized one")

    # --- leaked-container: nothing labeled with the run id survives
    for worker, api in _daemon_view(driver):
        for c in list(api.containers.values()):
            if c.labels.get(consts.LABEL_LOOP) == run_id:
                violations.append(
                    f"leaked-container: {worker.id} still holds "
                    f"{c.name} ({c.state}) after cleanup"
                    + (" [warm-pool]" if consts.LABEL_WARMPOOL in c.labels
                       else ""))

    # --- admission-cap: daemon-side concurrency high-water vs the bucket
    if cap > 0:
        all_workers = getattr(driver, "all_workers", None)
        audit_workers = (all_workers() if all_workers is not None
                         else driver.workers())
        # the SLO loop may legitimately scale a worker's bucket above
        # the static cap; journaled REC_CAPACITY_TOKENS records bound
        # how far (the audit stays falsifiable -- an unjournaled
        # overshoot is still a violation)
        cap_by_worker: dict[str, int] = {}
        for rec in records:
            if rec.get("kind") == REC_CAPACITY_TOKENS:
                wid = str(rec.get("worker", ""))
                c = int(rec.get("cap", 0))
                cap_by_worker[wid] = max(cap_by_worker.get(wid, cap), c)
        for worker, gate in zip(audit_workers, driver.gates):
            allowed = max(cap, cap_by_worker.get(worker.id, cap))
            if gate.launch_hwm > allowed:
                violations.append(
                    f"admission-cap: {worker.id} daemon saw "
                    f"{gate.launch_hwm} concurrent launches "
                    f"(cap {allowed})")

    # --- worktree-isolation: one agent, one (path, branch); no sharing.
    # Folded from the write-ahead ``seed_worktree`` records: an agent
    # that re-attaches after kill/resume journals the same claim (WAL
    # dedup), so >1 distinct claim per agent, or any path/branch shared
    # across agents, means two containers could write the same tree.
    claims: dict[str, set[tuple[str, str]]] = {}
    for rec in records:
        if rec.get("kind") == REC_SEED_WORKTREE:
            agent = str(rec.get("agent", ""))
            claims.setdefault(agent, set()).add(
                (str(rec.get("path", "")), str(rec.get("branch", ""))))
    for agent, pairs in sorted(claims.items()):
        if len(pairs) > 1:
            violations.append(
                f"worktree-isolation: {agent} journaled {len(pairs)} "
                f"distinct worktree claims: {sorted(pairs)}")
    by_path: dict[str, str] = {}
    by_branch: dict[str, str] = {}
    for agent, pairs in sorted(claims.items()):
        for path, branch in sorted(pairs):
            if path and path in by_path and by_path[path] != agent:
                violations.append(
                    f"worktree-isolation: path {path} claimed by both "
                    f"{by_path[path]} and {agent} (cross-agent writes)")
            elif path:
                by_path[path] = agent
            if branch and branch in by_branch and by_branch[branch] != agent:
                violations.append(
                    f"worktree-isolation: branch {branch} claimed by both "
                    f"{by_branch[branch]} and {agent}")
            elif branch:
                by_branch[branch] = agent

    # --- stranded-by-drain: a capacity scale-down must never strand a
    # journaled run.  Fold the record stream in order, tracking which
    # loops and pool members are live on which worker at every point --
    # the SAME liveness rule the controller's journal-replay gate uses
    # (non-terminal loops count; done/failed do not; pending/ready pool
    # members count) -- and require that every ``drain done`` record
    # lands at a point where its victim hosts nothing live.
    placed_on: dict[str, str] = {}      # agent -> worker
    live_agents: set[str] = set()
    pool_on: dict[str, str] = {}        # pool member -> worker, while live
    for rec in records:
        kind = rec.get("kind", "")
        agent = str(rec.get("agent", ""))
        if kind == REC_PLACEMENT and agent:
            placed_on[agent] = str(rec.get("worker", ""))
            live_agents.add(agent)
        elif kind == REC_MIGRATED and agent:
            placed_on[agent] = str(rec.get("dst",
                                           placed_on.get(agent, "")))
        elif kind == REC_LOOP_END and agent:
            if str(rec.get("status", "")) in ("done", "failed"):
                live_agents.discard(agent)
            # "stopped" stays live: the run resumes onto that worker
        elif kind in (REC_POOL_ADD, REC_POOL_READY) and agent:
            pool_on[agent] = str(rec.get("worker", pool_on.get(agent, "")))
        elif kind in (REC_POOL_ADOPT, REC_POOL_REMOVE) and agent:
            pool_on.pop(agent, None)
        elif kind == REC_CAPACITY_SCALE \
                and str(rec.get("action", "")) == "drain" \
                and str(rec.get("phase", "")) == "done":
            wid = str(rec.get("worker", ""))
            stranded = sorted(
                [a for a in live_agents if placed_on.get(a) == wid]
                + [p for p, w in pool_on.items() if w == wid])
            for victim in stranded:
                violations.append(
                    f"stranded-by-drain: capacity drained {wid} while "
                    f"the journal shows {victim} still live on it")

    # --- spurious-quarantine: untouched workers end healthy
    if health is not None and unfaulted:
        for wid in sorted(unfaulted):
            state = health.state(wid)
            if state != BREAKER_CLOSED:
                violations.append(
                    f"spurious-quarantine: {wid} was never faulted but "
                    f"its breaker reads {state!r}")

    # --- sentinel-observe-only (counter half): a scenario that ran with
    # the sentinel attached must show ZERO mutations in its audit --
    # the sentinel has no code path that could increment these, and the
    # invariant keeps it that way
    if sentinel is not None:
        for name, count in sorted(sentinel.audit().items()):
            if count:
                violations.append(
                    f"sentinel-observe-only: sentinel performed "
                    f"{count} {name}")

    # --- workerd-reconcile: a healed link leaves nothing undelivered.
    # ``workerd`` rows come from the runner's audit (worker, alive,
    # channel_live, undelivered).  A dead daemon / never-healed channel
    # is the DEGRADE case, covered by the drain + accounting checks
    # above; only a live channel owes an empty buffer.
    for row in workerd or []:
        if row.get("alive") and row.get("channel_live") \
                and int(row.get("undelivered", 0)) > 0:
            violations.append(
                f"workerd-reconcile: {row.get('worker')} channel healed "
                f"but {row['undelivered']} event(s) were never delivered "
                "(lost exits)")

    # --- shipper-*: the telemetry shipper's bounded-ingestion contract
    # (docs/fleet-console.md#degrade-matrix).  ``shipper`` is the
    # runner's audit dict (shipper.stats() + down_injected +
    # indexed_docs from the fake index).  Three falsifiable halves:
    # accounting (every ingested doc is flushed, dropped, or still
    # buffered -- nothing vanishes uncounted, which is exactly what a
    # lossy drop path that forgets to count would violate), delivery
    # (every doc the sink ACKED is actually in the index -- catches a
    # corrupt bulk payload read as success), and bounded (the buffer
    # never exceeded its cap, so a down index cannot grow memory).
    if shipper is not None:
        accounted = (shipper["flushed_docs"] + shipper["dropped_docs"]
                     + shipper["pending_docs"] + shipper["open_docs"])
        if accounted != shipper["ingested_docs"]:
            violations.append(
                f"shipper-accounting: {shipper['ingested_docs']} doc(s) "
                f"ingested but only {accounted} accounted "
                f"(flushed {shipper['flushed_docs']} + dropped "
                f"{shipper['dropped_docs']} + buffered "
                f"{shipper['pending_docs'] + shipper['open_docs']})")
        if shipper["flushed_docs"] != shipper.get("indexed_docs", 0):
            violations.append(
                f"shipper-delivery: sink acked {shipper['flushed_docs']} "
                f"doc(s) but the index holds "
                f"{shipper.get('indexed_docs', 0)}")
        if shipper["pending_batches"] > shipper["max_batches"]:
            violations.append(
                f"shipper-bounded: {shipper['pending_batches']} pending "
                f"batch(es) exceed the {shipper['max_batches']}-batch "
                "buffer cap")
        if shipper.get("down_injected") and shipper["failed_flushes"] == 0 \
                and shipper["dropped_docs"] == 0 \
                and shipper["ingested_docs"] > 0:
            violations.append(
                "shipper-backpressure: the index went down but the "
                "shipper recorded neither a failed flush nor a drop -- "
                "the fault never reached the sink path")

    # --- ref-isolation-at-proxy: branch-per-agent isolation, audited
    # against the UPSTREAM's acknowledged log (docs/git-policy.md).
    # ``gitguard`` is the runner's audit dict: run/branch_prefix name
    # the namespace scheme, ``acknowledged`` is (ts, identity_header,
    # ref) per update the upstream actually applied, ``decisions`` is
    # (ts, decision_doc) off the proxy, ``downed_at`` when (if ever)
    # the proxy was killed.  Three falsifiable halves: nothing landed
    # out of namespace, the proxy never SAID allow out of namespace
    # (catches a verdict/forward mismatch the first half would miss
    # when the upstream also refuses), and nothing at all landed after
    # the kill (fail-closed, never fail-open).
    if gitguard is not None:
        from ..gitguard.refpolicy import AgentIdentity, RefPolicy

        policy = RefPolicy(
            run=str(gitguard.get("run", "")),
            branch_prefix=str(gitguard.get("branch_prefix", "loop")))
        integration = policy.integration_ref()

        def in_namespace(ident_header: str, ref: str) -> bool:
            ident = AgentIdentity.from_header(ident_header)
            if ident is None:
                return False
            if ref == integration:
                return ident.merge_queue
            ns = policy.namespace(ident)
            return ref == ns or ref.startswith(ns + "/")

        downed_at = gitguard.get("downed_at")
        for ts, ident_header, ref in gitguard.get("acknowledged") or []:
            if not in_namespace(str(ident_header), str(ref)):
                violations.append(
                    f"ref-isolation-at-proxy: upstream acknowledged "
                    f"{ref} pushed by {ident_header!r} -- an "
                    "out-of-namespace update landed")
            if downed_at is not None and ts > downed_at:
                violations.append(
                    f"ref-isolation-at-proxy: upstream acknowledged "
                    f"{ref} AFTER the guard was killed -- a dead guard "
                    "must fail closed, not open")
        # decision docs carry (run, agent) but not role, so the
        # integration ref is checked by the acknowledged-log half
        # above (only the merge-queue role may land it); here an allow
        # verdict must name the integration ref or the agent's own
        # namespace -- anything else is a verdict the policy can never
        # legitimately produce
        for _ts, doc in gitguard.get("decisions") or []:
            if doc.get("verdict") != "allow":
                continue
            ident_header = "/".join(
                p for p in (doc.get("run", ""), doc.get("agent", ""))
                if p)
            ref = str(doc.get("ref", ""))
            if ref and ref != integration \
                    and not in_namespace(ident_header, ref):
                violations.append(
                    f"ref-isolation-at-proxy: proxy journaled an allow "
                    f"verdict for out-of-namespace ref {ref} "
                    f"(identity {ident_header!r})")

    # --- no-silent-drop / replay-integrity: the storage-fault contract
    # (docs/durability.md).  ``storage`` is the runner's audit dict:
    # ``fired`` counts faults the FaultFS shims actually raised,
    # ``faults``/``events`` what the scheduler surfaced (its counter
    # and storage.fault bus frames across generations), ``dropped``/
    # ``durability`` the journal's own accounting, ``verify`` the
    # checksum scan of the final journal, ``torn_injected`` whether
    # the plan corrupted bytes on purpose, and ``folded_run_id`` what
    # the verified-prefix fold thinks the run is.
    if storage is not None:
        fired = int(storage.get("fired", 0))
        if fired and not int(storage.get("faults", 0)):
            violations.append(
                f"no-silent-drop: {fired} injected storage fault(s) "
                "fired but the scheduler counted none")
        if fired and not int(storage.get("events", 0)):
            violations.append(
                f"no-silent-drop: {fired} injected storage fault(s) "
                "fired but no storage.fault event reached the bus")
        if int(storage.get("dropped", 0)) \
                and storage.get("durability") == "ok":
            violations.append(
                f"no-silent-drop: {storage.get('dropped')} journal "
                "record(s) dropped but durability still reads ok")
        verify = storage.get("verify") or {}
        if int(verify.get("corrupt", 0)) \
                and not storage.get("torn_injected"):
            violations.append(
                f"replay-integrity: journal verify found "
                f"{verify.get('corrupt')} corrupt record(s) without a "
                "torn-record injection")
        folded = storage.get("folded_run_id")
        if folded is not None and folded != run_id:
            violations.append(
                "replay-integrity: the checksummed fold lost the run "
                f"header (folded {folded!r}, expected {run_id!r})")

    # --- span-tree: flight record parses; kill-free runs close every root
    from ..monitor.ledger import read_rotated_lines

    fpath = Path(flight_path(cfg.logs_dir, run_id))
    if fpath.exists():
        try:
            spans = load_spans(read_rotated_lines(fpath))
        except Exception as e:      # noqa: BLE001 -- corruption IS a finding
            violations.append(f"span-tree: flight record unreadable: {e}")
            spans = []
        if spans and kills == 0:
            for tree in build_trees(spans):
                rec = tree.record
                if rec.name in STANDALONE_SPANS:
                    continue    # run-level spans (sentinel ticks) are
                    #             legitimate non-iteration roots
                if rec.name != SPAN_ITERATION:
                    violations.append(
                        f"span-tree: {rec.agent} span {rec.name!r} has no "
                        "iteration root (writer died mid-flush?)")
                elif rec.status not in ("ok", "failed", "orphaned",
                                        "stopped"):
                    violations.append(
                        f"span-tree: {rec.agent} iteration root ended "
                        f"with status {rec.status!r}")

    # --- trace-completeness: the cross-process merge resolves every
    # iteration to a ROOTED tree whose remote segments are complete or
    # explicitly gap-marked (docs/tracing.md#chaos).  Kills loosen the
    # bare-root audit exactly as span-tree loosens (a SIGKILLed writer
    # legitimately loses its unflushed tail), but never the shape rule:
    # a remote segment that DID survive must merge gap-marked or
    # hosted, never as a broken tree.
    try:
        from ..tracing.merge import merge_run
        from ..tracing.names import SPAN_LOOPD_SUBMIT, SPAN_ROUTER_SUBMIT

        merged = merge_run(Path(cfg.logs_dir), run_id)
    except Exception as e:      # noqa: BLE001 -- a merge crash IS a finding
        violations.append(f"trace-completeness: merge failed: {e}")
        merged = None
    if merged is not None and merged.spans:
        # legitimate top-of-chain roots: the hop a submit REALLY started
        # at (router when federated, loopd when daemon-direct, iteration
        # when in-process) plus standalone run-level spans and the
        # merge's own gap placeholders
        root_ok = {SPAN_ITERATION, SPAN_ROUTER_SUBMIT, SPAN_LOOPD_SUBMIT}

        def _walk(nodes):
            for n in nodes:
                yield n
                yield from _walk(n.children)

        for root in merged.roots:
            rec = root.record
            if (rec.name in root_ok or rec.name in STANDALONE_SPANS
                    or rec.attrs.get("gap")):
                continue
            if kills == 0:
                violations.append(
                    f"trace-completeness: span {rec.name!r} "
                    f"({rec.agent or rec.worker}) merges as a bare root "
                    "-- its upstream segment is missing and not "
                    "gap-marked")
        for node in _walk(merged.roots):
            rec = node.record
            if rec.name != SPAN_ITERATION:
                continue
            via = any(c.record.attrs.get("workerd") for c in node.children)
            resolved = any(c.record.name.startswith("workerd.")
                           or c.record.attrs.get("gap")
                           for c in node.children)
            if via and not resolved:
                violations.append(
                    f"trace-completeness: {rec.agent} iteration "
                    f"{rec.attrs.get('iteration')} launched via workerd "
                    "but its remote segment is neither present nor "
                    "gap-marked")
    return violations


# ------------------------------------------------------ cross-pod audit


def cross_pod_exactly_once(pods: dict, cfg, run_id: str) -> list[str]:
    """The federation migration invariant (docs/federation.md#chaos):
    a run that moved between pods is accounted EXACTLY ONCE across the
    whole federation.

    ``pods`` maps pod name -> that pod's FakeDriver (dead pods
    included: their call recorders are the evidence the run really
    left).  All pods share one journal (federation requires shared run
    storage), so the union audit folds every pod's daemon-side creates
    against the single write-ahead record:

    - ``cross-pod-duplicate-create``: per (agent, worker) -- worker ids
      are pod-prefixed, so the key is federation-global -- creates
      never exceed journaled placements/pool-adds.  A run adopted twice
      (or a zombie generation still launching on the dead pod) double-
      creates and trips this.
    - ``cross-pod-exit-once``: no (agent, iteration) exit journaled
      twice across all generations/pods.
    - ``cross-pod-single-home``: folding the record stream, each
      agent's placements land on ONE pod at a time; after the run's
      final record every agent's last placement names a worker that
      belongs to exactly one registered pod.
    """
    from ..loop.journal import (
        REC_EXITED,
        REC_PLACEMENT,
        REC_POOL_ADD,
        RunJournal,
        journal_path,
    )
    from ..runtime.names import container_name

    violations: list[str] = []
    records = RunJournal.read(journal_path(cfg.logs_dir, run_id))
    project = cfg.project_name()

    worker_pod: dict[str, str] = {}     # worker id -> owning pod
    for pod_name, driver in pods.items():
        for worker, _api in _daemon_view(driver):
            if worker.id in worker_pod:
                violations.append(
                    f"cross-pod-single-home: worker id {worker.id} is "
                    f"registered by both {worker_pod[worker.id]} and "
                    f"{pod_name} -- pod worker namespaces must not alias")
            worker_pod[worker.id] = pod_name

    placements: dict[tuple[str, str], int] = {}
    last_home: dict[str, str] = {}      # agent -> last placed worker
    for rec in records:
        if rec.get("kind") in (REC_PLACEMENT, REC_POOL_ADD):
            agent = str(rec.get("agent", ""))
            wid = str(rec.get("worker", ""))
            placements[(agent, wid)] = placements.get((agent, wid), 0) + 1
            if rec.get("kind") == REC_PLACEMENT:
                last_home[agent] = wid
    name_to_agent = {container_name(project, a): a
                     for (a, _w) in placements}

    for pod_name, driver in pods.items():
        for worker, api in _daemon_view(driver):
            creates: dict[str, int] = {}
            for (args, _kw) in api.calls_named("container_create"):
                cname = str(args[0]) if args else ""
                creates[cname] = creates.get(cname, 0) + 1
            for cname, n in sorted(creates.items()):
                agent = name_to_agent.get(cname)
                if agent is None:
                    continue
                allowed = placements.get((agent, worker.id), 0)
                if n > allowed:
                    violations.append(
                        f"cross-pod-duplicate-create: pod {pod_name} "
                        f"worker {worker.id} executed {n} creates for "
                        f"{agent} but only {allowed} journaled "
                        "placement(s) authorized one")

    seen_exits: dict[tuple[str, int], int] = {}
    for rec in records:
        if rec.get("kind") == REC_EXITED:
            key = (str(rec.get("agent", "")), int(rec.get("iteration", -1)))
            seen_exits[key] = seen_exits.get(key, 0) + 1
    for (agent, iteration), n in sorted(seen_exits.items()):
        if n > 1:
            violations.append(
                f"cross-pod-exit-once: {agent} iteration {iteration} "
                f"accounted {n} times across the federation")

    for agent, wid in sorted(last_home.items()):
        if wid and wid not in worker_pod:
            violations.append(
                f"cross-pod-single-home: {agent} last placed on "
                f"{wid}, a worker no registered pod owns")
    return violations


# ------------------------------------------------------- observe-only twin


def scheduling_outcome(driver, cfg, run_id: str, loops=None) -> dict:
    """The scheduling-outcome fingerprint the observe-only invariant
    compares: journaled placements per agent, daemon-side create counts
    per worker, and terminal statuses.  Everything the sentinel could
    conceivably have perturbed if it were not observe-only."""
    from ..loop.journal import REC_PLACEMENT, RunJournal, journal_path

    # agent and container names embed the run id (deterministic per
    # (run, slot)); the twin runs under two ids, so names normalize to
    # their slot before comparison
    def norm(name: str) -> str:
        return name.replace(run_id[:6], "RUN") if run_id else name

    records = RunJournal.read(journal_path(cfg.logs_dir, run_id))
    placements: dict[str, list[str]] = {}
    for rec in records:
        if rec.get("kind") == REC_PLACEMENT:
            placements.setdefault(norm(str(rec.get("agent", ""))),
                                  []).append(str(rec.get("worker", "")))
    creates: dict[str, dict[str, int]] = {}
    for worker, api in zip(driver.workers(), driver.apis):
        counts: dict[str, int] = {}
        for (args, _kw) in api.calls_named("container_create"):
            cname = norm(str(args[0])) if args else ""
            counts[cname] = counts.get(cname, 0) + 1
        creates[worker.id] = counts
    statuses = {norm(l.agent): l.status for l in (loops or [])}
    return {"placements": placements, "creates": creates,
            "statuses": statuses}


def observe_only_violations(baseline: dict, with_sentinel: dict) -> list[str]:
    """Compare two fixed-seed runs' scheduling outcomes -- one without
    and one with the sentinel attached.  Any difference is a violation:
    an observe-only subsystem may add events, metrics, and spans, but
    never a placement, a create, or a status."""
    out: list[str] = []
    for field_name in ("placements", "creates", "statuses"):
        a, b = baseline.get(field_name), with_sentinel.get(field_name)
        if a != b:
            keys = sorted(set(a or {}) | set(b or {}))
            diff = [k for k in keys if (a or {}).get(k) != (b or {}).get(k)]
            out.append(
                f"sentinel-observe-only: {field_name} differ with the "
                f"sentinel attached (changed: {', '.join(diff[:6])}"
                + ("..." if len(diff) > 6 else "") + ")")
    return out
