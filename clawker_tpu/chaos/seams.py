"""Named crash seams: the enumerable registry behind CLI-SIGKILL chaos.

The resume test suite used to reach its kill points by wrapping
scheduler internals ad hoc; every new crash test invented its own
monkeypatch.  The scheduler now FIRES a named seam at each journaled
state transition boundary (``self.seams.fire("launch.post_create")``)
and anything -- the chaos runner, a test, ``loop --chaos-plan`` -- arms
a hook on that name.  Un-armed seams cost one attribute read plus a
falsy check, so the registry stays on by default.

A hook that wants to simulate SIGKILL at its seam calls
``scheduler.kill()`` and raises :class:`SeamAbort`: kill() freezes all
scheduler bookkeeping the way process death would, and the raise aborts
the in-flight code path mid-operation -- the instruction pointer stops
exactly where SIGKILL would have stopped it.  ``SeamAbort`` derives
from ``BaseException`` on purpose: the scheduler's own error handling
(strand/fail accounting) must NOT observe it, because a killed process
does no accounting.
"""

from __future__ import annotations

import threading
from typing import Callable

# every seam the scheduler fires, in rough lifecycle order.  Adding a
# fire site means adding its name here: the chaos plan generator and
# `clawker chaos plan` enumerate this tuple.
SEAM_NAMES = (
    "run.post_placement",       # run header + placements journaled, no
    #                             launch submitted yet
    "launch.pre_create",        # placement WAL durable; engine create next
    "launch.post_create",       # engine returned a cid; REC_CREATED durable
    "launch.pre_start",         # container exists; engine start next
    "launch.post_start",        # REC_STARTED journaled, iteration running
    "iteration.post_exit",      # REC_EXITED journaled for an iteration
    "resume.pre_reconcile",     # resume generation built, nothing adopted
    "resume.post_adopt",        # one container adopted in place
    "pool.post_fill",           # a warm-pool member created (REC_POOL_READY)
    # loopd transition boundaries (docs/loopd.md): the daemon fires
    # these around run registration so daemon crashes are soak-testable
    # exactly like CLI crashes -- a kill here leaves a journaled run
    # whose submitting client may or may not have seen the ack
    "loopd.post_submit",        # run registered in the daemon's table,
    #                             ack NOT yet sent to the client
    "loopd.post_ack",           # ack sent; scheduler start + streaming
    #                             not begun
    # workerd data-plane boundaries (docs/workerd.md): an intent is
    # about to leave the scheduler for the worker-resident daemon, and
    # a partitioned channel has just re-synced -- the two places a
    # crash interleaves with remote execution
    "workerd.pre_dispatch",     # placement WAL durable; intent about to
    #                             enter the channel send queue
    "workerd.post_reconnect",   # channel healed + resync done; buffered
    #                             events about to replay
)


class SeamAbort(BaseException):
    """Raised by a crash hook to stop the in-flight path like SIGKILL
    would.  BaseException: must never be absorbed by ClawkerError /
    Exception handlers that would account the 'failure'."""


class SeamRegistry:
    """Arm/fire named crash seams.  Thread-safe; hooks fire at most once
    per arm (one SIGKILL per arm) unless re-armed."""

    def __init__(self):
        self._lock = threading.Lock()
        self._armed: dict[str, Callable[[], None]] = {}
        self.fired: list[str] = []      # fire log, in order (tests/report)

    def arm(self, name: str, hook: Callable[[], None]) -> None:
        if name not in SEAM_NAMES:
            raise ValueError(
                f"unknown crash seam {name!r} (known: {', '.join(SEAM_NAMES)})")
        with self._lock:
            self._armed[name] = hook

    def disarm(self, name: str | None = None) -> None:
        with self._lock:
            if name is None:
                self._armed.clear()
            else:
                self._armed.pop(name, None)

    def armed(self) -> list[str]:
        with self._lock:
            return sorted(self._armed)

    def fire(self, name: str) -> None:
        """Run (and consume) the hook armed on ``name``, if any.  The
        hook may raise :class:`SeamAbort`; anything else it raises
        propagates too -- a crash hook is test machinery, not a place
        to swallow bugs."""
        with self._lock:
            hook = self._armed.pop(name, None)
            if hook is not None:
                self.fired.append(name)
        if hook is not None:
            hook()


class _NullSeams:
    """The default, never-armed registry: fire() is one falsy check."""

    __slots__ = ()
    fired: list = []

    def arm(self, name: str, hook) -> None:
        raise RuntimeError(
            "cannot arm the shared null seam registry; construct the "
            "scheduler with seams=SeamRegistry()")

    def disarm(self, name: str | None = None) -> None:
        pass

    def armed(self) -> list:
        return []

    def fire(self, name: str) -> None:
        pass


NULL_SEAMS = _NullSeams()
