"""Merged multi-worker egress feed for the loop dashboard ticker.

``loop --parallel N`` on remote workers left the dashboard's egress
ticker blind: each worker's control plane writes ``ebpf-egress.jsonl``
on ITS host, while the dashboard tailed the laptop's copy (round-3
verdict weak #5).  This module tails every worker's stream -- a plain
file tail for local workers, a ``tail -F`` ridden over the worker's SSH
ControlMaster for remote ones (the same mux the side channels use) --
and merges the records into one bounded feed, each tagged with the
worker id.

North-star parity: "tunnel monitor/TUI streams back" (BASELINE.json);
reference transport substrate SURVEY.md 2.13.
"""

from __future__ import annotations

import collections
import json
import threading
import time
from pathlib import Path

from .. import logsetup

log = logsetup.get("fleet.egress")

# Worker-side egress log location: the per-worker CP (systemd unit,
# fleet/provision.py) runs with default XDG dirs, so the path resolves
# through the remote shell, not ours.
REMOTE_EGRESS_LOG = (
    "${XDG_STATE_HOME:-$HOME/.local/state}/clawker-tpu/logs/ebpf-egress.jsonl")


class EgressFeed:
    """Thread-safe bounded merge of per-worker egress jsonl streams."""

    def __init__(self, maxlen: int = 256):
        self._buf: collections.deque = collections.deque(maxlen=maxlen)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._procs: list = []

    # ------------------------------------------------------------ sources

    def add_worker(self, worker, *, local_path: Path) -> None:
        """Wire one worker: remote engines (a transport on the engine)
        tail worker-side over SSH; local ones tail the local file."""
        transport = getattr(worker.require_engine(), "transport", None)
        if transport is not None:
            self.add_remote(worker.id, transport)
        else:
            self.add_local(worker.id, local_path)

    def add_local(self, worker_id: str, path: Path) -> None:
        t = threading.Thread(target=self._tail_local, args=(worker_id, path),
                             name=f"egress-{worker_id}", daemon=True)
        t.start()
        self._threads.append(t)

    def add_remote(self, worker_id: str, transport) -> None:
        """``tail -F`` over the worker's SSH mux.  ``-n +1`` replays the
        existing records so a late-joining dashboard still sees history;
        the remote shell resolves the worker-side XDG path."""
        cmd = transport.ssh_base() + [
            f"tail -n +1 -F {REMOTE_EGRESS_LOG} 2>/dev/null"]
        try:
            proc = transport.runner.spawn_piped(cmd)
        except OSError as e:
            log.warning("egress tail for %s failed to start: %s", worker_id, e)
            return
        self._procs.append(proc)
        t = threading.Thread(target=self._pump_proc, args=(worker_id, proc),
                             name=f"egress-{worker_id}", daemon=True)
        t.start()
        self._threads.append(t)

    # ------------------------------------------------------------- pumps

    def _push(self, worker_id: str, line: str) -> None:
        line = line.strip()
        if not line:
            return
        try:
            rec = json.loads(line)
        except ValueError:
            return
        rec.setdefault("worker", worker_id)
        with self._lock:
            self._buf.append(rec)

    def _tail_local(self, worker_id: str, path: Path) -> None:
        pos = 0
        while not self._stop.is_set():
            try:
                with path.open("rb") as fh:
                    size = path.stat().st_size
                    if size < pos:
                        pos = 0   # rotated/truncated: replay from the top
                    fh.seek(pos)
                    for raw in fh:
                        if not raw.endswith(b"\n"):
                            # partial line mid-write: leave it for the
                            # next poll (consuming a split record would
                            # drop BOTH halves as unparseable)
                            break
                        pos = fh.tell()
                        self._push(worker_id, raw.decode("utf-8", "replace"))
            except OSError:
                pass
            self._stop.wait(0.5)

    def _pump_proc(self, worker_id: str, proc) -> None:
        try:
            for raw in iter(proc.stdout.readline, b""):
                if self._stop.is_set():
                    break
                self._push(worker_id,
                           raw.decode("utf-8", "replace")
                           if isinstance(raw, bytes) else raw)
        except (OSError, ValueError):
            pass

    # ------------------------------------------------------------- reads

    def tail(self, max_lines: int = 64) -> list[dict]:
        with self._lock:
            return list(self._buf)[-max_lines:]

    def stop(self) -> None:
        self._stop.set()
        for proc in self._procs:
            try:
                proc.terminate()
            except OSError:
                pass
        for t in self._threads:
            t.join(1.0)
        self._threads.clear()
        self._procs.clear()

    def __enter__(self) -> "EgressFeed":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
