"""Fleet: TPU-pod worker discovery, SSH transport, remote provisioning.

The tpu_vm runtime driver's substrate (SURVEY.md 2.13): every worker VM
of a TPU pod runs its own Docker daemon + control plane; the laptop CLI
reaches them over SSH (DCN) with the docker socket and CP ports
forwarded through a ControlMaster mux.  ICI never carries control
traffic -- pod topology only informs loop-scheduler placement.
"""

from .inventory import discover_workers
from .transport import SSHTransport, connect_worker_engine

__all__ = ["discover_workers", "SSHTransport", "connect_worker_engine"]
