"""Worker discovery: which hosts make up the TPU pod.

Resolution order (first hit wins):

1. ``runtime.tpu.workers`` in settings -- explicit host list, the
   escape hatch that also serves CI and non-GCP fleets.
2. The GCE metadata server (only answers ON a TPU-VM): the
   ``worker-network-endpoints`` instance attribute lists every worker
   of the pod this VM belongs to.
3. ``gcloud compute tpus tpu-vm describe`` on the operator machine.

Parity note: the reference has no analogue (single local daemon); this
is the net-new inventory half of the BASELINE.json north star.
"""

from __future__ import annotations

import json
import subprocess
from urllib import error as urlerror
from urllib import request as urlrequest

from .. import consts, logsetup
from ..config.schema import TPUSettings
from ..errors import DriverError

log = logsetup.get("fleet.inventory")

METADATA_URL = (
    f"http://{consts.TPU_METADATA_HOST}/computeMetadata/v1/instance/attributes/"
    "worker-network-endpoints"
)


def parse_worker_endpoints(raw: str) -> list[str]:
    """The metadata attribute is comma-separated ``ip:port:index`` triples
    (historically) or plain IPs; accept both."""
    hosts = []
    for part in raw.strip().split(","):
        part = part.strip()
        if not part:
            continue
        hosts.append(part.split(":")[0])
    return hosts


def parse_describe_json(raw: str) -> list[str]:
    """gcloud describe --format=json -> worker IPs, pod order preserved."""
    data = json.loads(raw)
    out = []
    for ep in data.get("networkEndpoints") or []:
        ip = (ep.get("accessConfig") or {}).get("externalIp") or ep.get("ipAddress")
        if ip:
            out.append(ip)
    return out


def _from_metadata(timeout: float = 2.0) -> list[str]:
    req = urlrequest.Request(METADATA_URL, headers={"Metadata-Flavor": "Google"})
    try:
        with urlrequest.urlopen(req, timeout=timeout) as r:
            return parse_worker_endpoints(r.read().decode())
    except (urlerror.URLError, OSError):
        return []


def _from_gcloud(tpu: TPUSettings, timeout: float = 30.0) -> list[str]:
    if not tpu.pod:
        return []
    cmd = ["gcloud", "compute", "tpus", "tpu-vm", "describe", tpu.pod,
           "--format", "json"]
    if tpu.zone:
        cmd += ["--zone", tpu.zone]
    if tpu.project:
        cmd += ["--project", tpu.project]
    try:
        res = subprocess.run(cmd, capture_output=True, text=True, timeout=timeout)
    except (OSError, subprocess.TimeoutExpired) as e:
        raise DriverError(f"gcloud describe failed: {e}") from None
    if res.returncode != 0:
        raise DriverError(f"gcloud describe {tpu.pod}: {res.stderr.strip()}")
    return parse_describe_json(res.stdout)


def discover_workers(tpu: TPUSettings) -> list[str]:
    if tpu.workers:
        return list(tpu.workers)
    hosts = _from_metadata()
    if hosts:
        log.info("discovered %d workers via metadata server", len(hosts))
        return hosts
    hosts = _from_gcloud(tpu)
    if hosts:
        log.info("discovered %d workers via gcloud for pod %s", len(hosts), tpu.pod)
    return hosts
