"""Worker discovery + pod topology: which hosts make up the TPU pod,
and how they sit on the ICI mesh.

Resolution order for hosts (first hit wins):

1. ``runtime.tpu.workers`` in settings -- explicit host list, the
   escape hatch that also serves CI and non-GCP fleets.
2. The GCE metadata server (only answers ON a TPU-VM): the
   ``worker-network-endpoints`` instance attribute lists every worker
   of the pod this VM belongs to.
3. ``gcloud compute tpus tpu-vm describe`` on the operator machine.

Topology (:func:`pod_topology`) feeds the loop scheduler's ``topology``
placement policy (docs/loop-placement.md): workers are modeled on a 2-D
grid in pod order -- ``runtime.tpu.topology`` ("RxC") when set, else a
near-square grid inferred from the worker count.  Workers sharing a
grid row form one ICI group (co-located on the fast interconnect);
cross-row hops are costed a full row width.  Unknown shapes degrade to
``known=False`` and topology-aware placement falls back to spread.

Parity note: the reference has no analogue (single local daemon); this
is the net-new inventory half of the BASELINE.json north star.
"""

from __future__ import annotations

import json
import subprocess
from dataclasses import dataclass, field
from urllib import error as urlerror
from urllib import request as urlrequest

from .. import consts, logsetup
from ..config.schema import TPUSettings
from ..errors import DriverError

log = logsetup.get("fleet.inventory")

METADATA_URL = (
    f"http://{consts.TPU_METADATA_HOST}/computeMetadata/v1/instance/attributes/"
    "worker-network-endpoints"
)


def parse_worker_endpoints(raw: str) -> list[str]:
    """The metadata attribute is comma-separated ``ip:port:index`` triples
    (historically) or plain IPs; accept both."""
    hosts = []
    for part in raw.strip().split(","):
        part = part.strip()
        if not part:
            continue
        hosts.append(part.split(":")[0])
    return hosts


def parse_describe_json(raw: str) -> list[str]:
    """gcloud describe --format=json -> worker IPs, pod order preserved."""
    data = json.loads(raw)
    out = []
    for ep in data.get("networkEndpoints") or []:
        ip = (ep.get("accessConfig") or {}).get("externalIp") or ep.get("ipAddress")
        if ip:
            out.append(ip)
    return out


def _from_metadata(timeout: float = 2.0) -> list[str]:
    req = urlrequest.Request(METADATA_URL, headers={"Metadata-Flavor": "Google"})
    try:
        with urlrequest.urlopen(req, timeout=timeout) as r:
            return parse_worker_endpoints(r.read().decode())
    except (urlerror.URLError, OSError):
        return []


def _from_gcloud(tpu: TPUSettings, timeout: float = 30.0) -> list[str]:
    if not tpu.pod:
        return []
    cmd = ["gcloud", "compute", "tpus", "tpu-vm", "describe", tpu.pod,
           "--format", "json"]
    if tpu.zone:
        cmd += ["--zone", tpu.zone]
    if tpu.project:
        cmd += ["--project", tpu.project]
    try:
        res = subprocess.run(cmd, capture_output=True, text=True, timeout=timeout)
    except (OSError, subprocess.TimeoutExpired) as e:
        raise DriverError(f"gcloud describe failed: {e}") from None
    if res.returncode != 0:
        raise DriverError(f"gcloud describe {tpu.pod}: {res.stderr.strip()}")
    return parse_describe_json(res.stdout)


# ---------------------------------------------------------------- topology


@dataclass(frozen=True)
class WorkerTopology:
    """Pod workers on a 2-D grid, row-major in pod worker order.

    ``coords[i]`` is worker i's (row, col); workers on one row share an
    ICI group.  ``known=False`` means no usable shape could be derived
    -- consumers must degrade (the topology placement policy falls back
    to spread), never fail.
    """

    known: bool = False
    rows: int = 0
    cols: int = 0
    coords: dict[int, tuple[int, int]] = field(default_factory=dict)

    def group_of(self, index: int) -> int:
        """ICI group id (grid row) for a worker index; workers beyond
        the known grid get their own singleton groups."""
        c = self.coords.get(index)
        return c[0] if c is not None else self.rows + index

    def distance(self, a: int, b: int) -> int:
        """ICI hop cost between two workers: intra-row hops are cheap,
        a row change costs a full row width (the group boundary)."""
        ca, cb = self.coords.get(a), self.coords.get(b)
        if ca is None or cb is None:
            return 1 << 16
        return abs(ca[0] - cb[0]) * max(1, self.cols) + abs(ca[1] - cb[1])


def _parse_shape(raw: str) -> tuple[int, int] | None:
    """"RxC" -> (rows, cols); None on anything unparseable."""
    parts = raw.lower().replace("*", "x").split("x")
    if len(parts) != 2:
        return None
    try:
        r, c = int(parts[0]), int(parts[1])
    except ValueError:
        return None
    return (r, c) if r > 0 and c > 0 else None


def _near_square(n: int) -> tuple[int, int]:
    """Largest factor pair (rows <= cols) -- 8 -> 2x4, 16 -> 4x4.
    Primes degrade to 1xN (one ICI group, which is truthful: a ring)."""
    best = (1, n)
    r = 1
    while r * r <= n:
        if n % r == 0:
            best = (r, n // r)
        r += 1
    return best


def pod_topology(tpu: TPUSettings, n_workers: int) -> WorkerTopology:
    """Best-effort worker grid for the pod; ``known=False`` when no
    shape fits (zero/one worker, or an explicit shape that does not
    match the worker count -- a wrong topology is worse than none)."""
    if n_workers <= 1:
        return WorkerTopology()
    shape = _parse_shape(tpu.topology) if tpu.topology else None
    if tpu.topology and shape is None:
        log.warning("runtime.tpu.topology %r unparseable (want RxC); "
                    "topology placement falls back to spread", tpu.topology)
        return WorkerTopology()
    if shape is not None and shape[0] * shape[1] != n_workers:
        log.warning("runtime.tpu.topology %r does not cover %d workers; "
                    "topology placement falls back to spread",
                    tpu.topology, n_workers)
        return WorkerTopology()
    rows, cols = shape if shape is not None else _near_square(n_workers)
    coords = {i: (i // cols, i % cols) for i in range(n_workers)}
    return WorkerTopology(known=True, rows=rows, cols=cols, coords=coords)


def federation_topology(shape: str, n_pods: int) -> WorkerTopology:
    """Pod-tier topology for the federation router (docs/federation.md):
    the same 2-D grid model one level up -- grid cells are PODS, a row
    is a DCN-adjacent pod group (co-located pods share the cheaper DCN
    tier the way co-located workers share ICI).  ``shape`` is the
    ``federation.shape`` setting ("RxC"); empty/unparseable/mismatched
    shapes degrade to ``known=False`` exactly like :func:`pod_topology`
    and pod placement falls back to spread."""
    if n_pods <= 1:
        return WorkerTopology()
    parsed = _parse_shape(shape) if shape else None
    if shape and parsed is None:
        log.warning("federation.shape %r unparseable (want RxC); "
                    "pod placement falls back to spread", shape)
        return WorkerTopology()
    if parsed is not None and parsed[0] * parsed[1] != n_pods:
        log.warning("federation.shape %r does not cover %d pods; "
                    "pod placement falls back to spread", shape, n_pods)
        return WorkerTopology()
    rows, cols = parsed if parsed is not None else _near_square(n_pods)
    coords = {i: (i // cols, i % cols) for i in range(n_pods)}
    return WorkerTopology(known=True, rows=rows, cols=cols, coords=coords)


def discover_workers(tpu: TPUSettings) -> list[str]:
    if tpu.workers:
        return list(tpu.workers)
    hosts = _from_metadata()
    if hosts:
        log.info("discovered %d workers via metadata server", len(hosts))
        return hosts
    hosts = _from_gcloud(tpu)
    if hosts:
        log.info("discovered %d workers via gcloud for pod %s", len(hosts), tpu.pod)
    return hosts
