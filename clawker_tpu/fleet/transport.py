"""SSH transport to TPU-VM workers: exec, file push, socket forwarding.

One ``SSHTransport`` per worker host.  All sessions ride a shared
OpenSSH ControlMaster mux (ControlPersist keeps the TCP+auth warm, so
per-command latency is one round trip -- the property the <10s
cold-start budget depends on).  The Docker Engine API is reached by
forwarding the worker's ``/var/run/docker.sock`` to a local unix socket
and pointing ``HTTPDockerAPI``'s socket factory at it: the whole engine
stack (label jail, PTY attach, build streaming) works unchanged against
a remote daemon -- the graft is a transport substitution, exactly as
SURVEY.md 2.13 frames it.

The ``Runner`` seam (subprocess ssh vs ``FakeRunner`` scripted
transcripts) is the fleet's fake-engine analogue: every provisioning and
transport decision is unit-testable with no SSH or TPU in sight
(SURVEY.md 4's "multi-node-without-a-cluster" strategy).
"""

from __future__ import annotations

import os
import shlex
import subprocess
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path

from .. import consts, logsetup
from ..config.schema import TPUSettings
from ..errors import DriverError

log = logsetup.get("fleet.transport")

FORWARD_READY_DEADLINE_S = 10.0


class TransportError(DriverError):
    pass


@dataclass
class RunResult:
    rc: int
    out: str
    err: str


class Runner:
    """Executes ssh invocations (seam for tests)."""

    def run(self, argv: list[str], *, input_bytes: bytes | None = None,
            timeout: float = 60.0) -> RunResult:
        try:
            res = subprocess.run(argv, input=input_bytes, capture_output=True,
                                 timeout=timeout)
        except (OSError, subprocess.TimeoutExpired) as e:
            raise TransportError(f"{argv[0]}: {e}") from None
        return RunResult(res.returncode, res.stdout.decode(errors="replace"),
                         res.stderr.decode(errors="replace"))

    def spawn(self, argv: list[str]) -> subprocess.Popen:
        return subprocess.Popen(argv, stdout=subprocess.DEVNULL,
                                stderr=subprocess.DEVNULL)

    def spawn_piped(self, argv: list[str]) -> subprocess.Popen:
        """Long-lived stream whose stdout the caller consumes (egress
        tails riding the SSH mux -- fleet/egress_tail.py)."""
        return subprocess.Popen(argv, stdout=subprocess.PIPE,
                                stderr=subprocess.DEVNULL)


class FakeRunner(Runner):
    """Scripted transcripts: remote command string -> (rc, out).

    Keys are matched as substrings of the joined remote command (or the
    local argv for spawns); unmatched commands succeed empty, so scripts
    only state what they care about.  Every invocation is recorded.
    """

    def __init__(self, script: dict[str, tuple[int, str]] | None = None,
                 stream_script: dict[str, list[str]] | None = None):
        self.script = dict(script or {})
        # needle -> lines a spawn_piped stream yields before EOF
        self.stream_script = dict(stream_script or {})
        self.calls: list[list[str]] = []
        self.pushed: dict[str, bytes] = {}   # remote path -> tar bytes
        self.spawned: list[list[str]] = []

    def run(self, argv, *, input_bytes=None, timeout=60.0):
        self.calls.append(list(argv))
        joined = " ".join(argv)
        if input_bytes is not None and "tar" in joined:
            # record pushes by their extraction directory
            dst = argv[-1].split("-C ")[-1].split(" ")[0] if "-C " in argv[-1] else joined
            self.pushed[dst] = input_bytes
        for needle, (rc, out) in self.script.items():
            if needle in joined:
                return RunResult(rc, out, "" if rc == 0 else out)
        return RunResult(0, "", "")

    def spawn(self, argv):
        self.spawned.append(list(argv))

        class _P:
            def poll(self):
                return None

            def terminate(self):
                pass

            def wait(self, timeout=None):
                return 0

        return _P()

    def spawn_piped(self, argv):
        import io as _io

        self.spawned.append(list(argv))
        joined = " ".join(argv)
        lines: list[str] = []
        for needle, out in self.stream_script.items():
            if needle in joined:
                lines = out
        body = "".join(l + "\n" for l in lines).encode()

        class _P:
            stdout = _io.BytesIO(body)

            def poll(self):
                return 0

            def terminate(self):
                pass

            def wait(self, timeout=None):
                return 0

        return _P()


class SSHTransport:
    def __init__(self, tpu: TPUSettings, host: str, index: int,
                 *, mux_dir: Path, runner: Runner | None = None):
        self.tpu = tpu
        self.host = host
        self.index = index
        self.mux_dir = Path(mux_dir)
        self.runner = runner or Runner()
        # injectable per-call RTT (the fake-WAN harness for REAL
        # transports; docs/workerd.md#fake-wan): every mux command pays
        # this before dispatch, so a bench/test can make a local ssh
        # target behave like a cross-continent worker deterministically
        self.rtt_s = 0.0
        self._forwards: list[subprocess.Popen] = []
        self._rev_tags: set[str] = set()
        self._lock = threading.Lock()
        # once, not per ssh invocation: every command used to re-mkdir the
        # mux dir and rebuild the same argv
        self.mux_dir.mkdir(parents=True, exist_ok=True)
        base = [
            "ssh",
            "-o", "BatchMode=yes",
            "-o", "StrictHostKeyChecking=accept-new",
            "-o", "ControlMaster=auto",
            "-o", f"ControlPath={self.mux_dir}/%r@%h:%p",
            "-o", "ControlPersist=300",
            "-o", "ServerAliveInterval=30",
        ]
        if self.tpu.ssh_key:
            base += ["-i", self.tpu.ssh_key]
        user = self.tpu.ssh_user or consts.TPU_SSH_USER_DEFAULT
        self._ssh_base = base + [f"{user}@{self.host}"]

    # ------------------------------------------------------------ command

    def ssh_base(self) -> list[str]:
        return list(self._ssh_base)

    def run(self, remote_cmd: str, *, input_bytes: bytes | None = None,
            timeout: float = 120.0) -> RunResult:
        if self.rtt_s > 0:
            time.sleep(self.rtt_s)      # injected fake-WAN round trip
        return self.runner.run(self.ssh_base() + [remote_cmd],
                               input_bytes=input_bytes, timeout=timeout)

    def probe(self, *, timeout: float = 5.0) -> float:
        """One control-channel round trip (``true`` over the mux);
        returns latency in seconds, raises TransportError on failure.
        The fleet health prober's SSH-level signal: distinguishes a dead
        forwarded daemon (engine probe fails, this succeeds) from a dead
        worker VM (both fail)."""
        t0 = time.monotonic()
        res = self.run("true", timeout=timeout)
        if res.rc != 0:
            raise TransportError(
                f"worker {self.index} ({self.host}): ssh probe rc={res.rc}: "
                f"{res.err.strip() or res.out.strip()}")
        return time.monotonic() - t0

    def check(self, remote_cmd: str, *, timeout: float = 120.0) -> str:
        res = self.run(remote_cmd, timeout=timeout)
        if res.rc != 0:
            raise TransportError(
                f"worker {self.index} ({self.host}): `{remote_cmd}` "
                f"rc={res.rc}: {res.err.strip() or res.out.strip()}"
            )
        return res.out

    # --------------------------------------------------------------- push

    def push_tar(self, tar_bytes: bytes, remote_dir: str, *,
                 sudo: bool = False) -> None:
        """Stream a tarball over stdin and extract it on the worker --
        one round trip, no scp dependency.  ``sudo`` creates root-owned
        target dirs (e.g. /opt) and hands them to the SSH user so later
        unprivileged builds can write there."""
        quoted = shlex.quote(remote_dir)
        if sudo:
            setup = (f"sudo mkdir -p {quoted} && "
                     f"sudo chown \"$(id -u):$(id -g)\" {quoted}")
        else:
            setup = f"mkdir -p {quoted}"
        res = self.run(
            f"{setup} && tar -xzf - -C {quoted}",
            input_bytes=tar_bytes, timeout=300.0,
        )
        if res.rc != 0:
            raise TransportError(
                f"worker {self.index}: push to {remote_dir} failed: {res.err.strip()}"
            )

    def push_paths(self, paths: dict[str, str | Path], remote_dir: str) -> None:
        """{archive-name: local path} -> tar.gz -> remote_dir."""
        import io
        import tarfile

        buf = io.BytesIO()
        with tarfile.open(fileobj=buf, mode="w:gz") as tf:
            for arcname, local in sorted(paths.items()):
                tf.add(str(local), arcname=arcname)
        self.push_tar(buf.getvalue(), remote_dir)

    # ----------------------------------------------------------- forwards

    def remote_loopd_sock(self) -> str:
        """The worker's canonical loopd control-socket path
        (docs/loopd.md): ``<XDG state>/loopd/loopd.sock`` under the ssh
        user's home.  Absolute on purpose -- sshd does not tilde-expand
        direct-streamlocal forward targets."""
        user = self.tpu.ssh_user or consts.TPU_SSH_USER_DEFAULT
        home = "/root" if user == "root" else f"/home/{user}"
        return (f"{home}/.local/state/{consts.PRODUCT}/"
                "loopd/loopd.sock")

    def forward_loopd(self, remote_sock: str = "") -> Path:
        """Tunnel the worker-resident loopd control socket over the SSH
        mux; returns the local socket path to point ``loopd.socket`` at
        (the JSON-frame protocol is transport agnostic, so a LoopdClient
        on the forwarded path behaves identically to a local one)."""
        return self.forward_unix(remote_sock or self.remote_loopd_sock(),
                                 tag="loopd")

    def remote_workerd_sock(self) -> str:
        """The worker's canonical workerd data-plane socket
        (docs/workerd.md).  Absolute on purpose -- sshd does not
        tilde-expand direct-streamlocal forward targets."""
        user = self.tpu.ssh_user or consts.TPU_SSH_USER_DEFAULT
        home = "/root" if user == "root" else f"/home/{user}"
        return (f"{home}/.local/state/{consts.PRODUCT}/"
                "workerd/workerd.sock")

    def forward_workerd(self, remote_sock: str = "") -> Path:
        """Tunnel the worker-resident workerd intent channel over the
        existing SSH mux; returns the local socket the scheduler's
        WorkerdExecutor dials.  One persistent channel rides this
        forward -- the whole point is that per-engine-call WAN round
        trips collapse onto it (docs/workerd.md)."""
        return self.forward_unix(remote_sock or self.remote_workerd_sock(),
                                 tag="workerd")

    def forward_unix(self, remote_sock: str, tag: str = "docker") -> Path:
        """Forward a remote unix socket to a local one; returns the local
        path once it accepts connections."""
        local = self.mux_dir / f"{tag}-{self.index}.sock"
        with self._lock:
            if local.exists() and self._probe(local):
                return local
            local.unlink(missing_ok=True)
            argv = self.ssh_base()[:-1] + [
                "-N", "-L", f"{local}:{remote_sock}", self.ssh_base()[-1],
            ]
            proc = self.runner.spawn(argv)
            self._forwards.append(proc)
        deadline = time.monotonic() + FORWARD_READY_DEADLINE_S
        while time.monotonic() < deadline:
            if local.exists() and self._probe(local):
                return local
            if proc.poll() is not None:
                break
            time.sleep(0.1)
        raise TransportError(
            f"worker {self.index}: socket forward {remote_sock} -> {local} "
            "did not come up"
        )

    def reverse_forward_tcp(self, remote_bind: str, remote_port: int,
                            local_host: str, local_port: int,
                            tag: str = "rev") -> None:
        """Expose a laptop service on the WORKER: ``ssh -R`` so worker-side
        connections to remote_bind:remote_port land on
        local_host:local_port here.

        This is the side-channel substrate (north star: "tunnel
        monitor/TUI streams back"): the host proxy and the monitor OTLP
        collector run on the laptop, and containers on every worker reach
        them through these forwards.  Binding a non-loopback remote_bind
        (the worker's clawker-net gateway, so containers can reach it)
        requires ``GatewayPorts clientspecified`` on the worker sshd --
        ensured by the provisioning plan.
        """
        key = f"R:{tag}"
        with self._lock:
            if key in self._rev_tags:
                return
            argv = self.ssh_base()[:-1] + [
                # a refused -R bind must kill the process (otherwise ssh
                # only warns and poll() can never detect the failure)
                "-o", "ExitOnForwardFailure=yes",
                "-N", "-R",
                f"{remote_bind}:{remote_port}:{local_host}:{local_port}",
                self.ssh_base()[-1],
            ]
            proc = self.runner.spawn(argv)
            self._forwards.append(proc)
            self._rev_tags.add(key)
        deadline = time.monotonic() + FORWARD_READY_DEADLINE_S
        probe = (f"timeout 2 bash -c 'exec 3<>/dev/tcp/{remote_bind}/"
                 f"{remote_port}' 2>/dev/null")
        while time.monotonic() < deadline:
            if self.run(probe, timeout=5.0).rc == 0:
                return
            if proc is not None and proc.poll() is not None:
                break
            time.sleep(0.2)
        with self._lock:
            self._rev_tags.discard(key)
            if proc in self._forwards:
                self._forwards.remove(proc)
        # reap the dead/stale tunnel so a retry doesn't lose the bind
        # race against a leaked first attempt -- outside the lock: the
        # wait can take seconds and every other transport caller
        # contends this lock
        try:
            proc.terminate()
            proc.wait(timeout=3)
        except Exception:
            pass
        raise TransportError(
            f"worker {self.index}: reverse forward {remote_bind}:{remote_port}"
            f" -> {local_host}:{local_port} did not come up"
        )

    def drop_mux(self) -> None:
        """Tear down the ControlMaster session; the next command redials.
        Needed after remote sshd config changes (GatewayPorts): a reload
        only affects NEW connections, and every session rides the mux."""
        argv = self.ssh_base()[:-1] + ["-O", "exit", self.ssh_base()[-1]]
        try:
            self.runner.run(argv, timeout=10.0)
        except TransportError:
            pass

    @staticmethod
    def _probe(path: Path) -> bool:
        import socket as _s

        try:
            with _s.socket(_s.AF_UNIX, _s.SOCK_STREAM) as s:
                s.settimeout(1.0)
                s.connect(str(path))
                return True
        except OSError:
            return False

    def close(self) -> None:
        # snapshot under the lock, reap outside it: each wait can take
        # up to 3s per tunnel, and holding the lock through that wedges
        # every concurrent run()/forward caller
        with self._lock:
            procs, self._forwards = list(self._forwards), []
            self._rev_tags.clear()
        for p in procs:
            try:
                p.terminate()
                p.wait(timeout=3)
            except Exception:
                pass


def connect_worker_engine(tpu: TPUSettings, host: str, index: int,
                          *, mux_dir: Path | None = None,
                          runner: Runner | None = None):
    """Worker host -> jailed Engine over the forwarded docker socket."""
    from ..engine.api import Engine
    from ..engine.httpapi import HTTPDockerAPI, unix_socket_factory
    from ..util.xdg import state_dir

    mux = mux_dir if mux_dir is not None else state_dir() / consts.TPU_SSH_MUX_DIR
    transport = SSHTransport(tpu, host, index, mux_dir=mux, runner=runner)
    engine = None
    try:
        local_sock = transport.forward_unix("/var/run/docker.sock")
        engine = Engine(HTTPDockerAPI(unix_socket_factory(local_sock)))
        if not engine.ping():
            raise TransportError(
                f"worker {index} ({host}): forwarded docker daemon not answering"
            )
    except Exception:
        if engine is not None:
            engine.close()  # drain any keep-alive socket on the forward
        transport.close()  # never orphan the ssh -N forward process
        raise
    engine.transport = transport  # keep the mux alive with the engine
    return engine
