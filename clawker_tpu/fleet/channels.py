"""Per-worker side channels: host proxy + monitor streams over SSH -R.

The host proxy (browser-open / OAuth / git-credential --
hostproxy/server.py) and the monitor stack's OTLP collector run on the
LAPTOP.  Containers on a remote TPU-VM worker reach them through reverse
forwards bound to the worker's clawker-net gateway address, so the
in-container URLs look exactly like the local-Docker case -- the
firewall's FW_R_HOSTPROXY lane (fw_decide step 6) and the netlogger's
OTLP lane work unchanged on remote workers.

Parity reference: internal/hostproxy/server.go:38 serves only
127.0.0.1:18374 -- the reference never runs containers off-host; this
module is what makes BASELINE configs 2-4 (remote workers with the full
side channel) possible.  north_star: "tunnel monitor/TUI streams back".
"""

from __future__ import annotations

from dataclasses import dataclass

from .. import consts, logsetup
from ..config import Config

log = logsetup.get("fleet.channels")

OTLP_HTTP_PORT = consts.OTLP_HTTP_PORT


@dataclass
class SideChannels:
    """Worker-side URLs for the laptop services (empty = unavailable)."""

    hostproxy_url: str = ""
    otlp_endpoint: str = ""
    remote: bool = False


def open_side_channels(engine, cfg: Config) -> SideChannels:
    """Ensure the laptop services are reachable from containers on the
    worker behind ``engine``; idempotent per engine (cached).

    Local/fake engines (no SSH transport) get the host-gateway URLs the
    create path already uses; remote engines get reverse forwards bound
    to the worker's clawker-net gateway.
    """
    cached = getattr(engine, "_side_channels", None)
    if cached is not None:
        return cached

    transport = getattr(engine, "transport", None)
    ch = SideChannels()
    hp = cfg.settings.host_proxy
    mon = cfg.settings.monitoring

    if transport is None:
        if hp.enable:
            ch.hostproxy_url = f"http://host.docker.internal:{hp.port}"
        if mon.enable:
            ch.otlp_endpoint = f"http://host.docker.internal:{OTLP_HTTP_PORT}"
        engine._side_channels = ch
        return ch

    ch.remote = True
    # the network may not exist yet on a fresh worker (firewall bring-up
    # creates it during start; this runs before create)
    engine.ensure_network(consts.NETWORK_NAME)
    gateway = engine.network_static_ip(consts.NETWORK_NAME, 1)
    if hp.enable:
        from ..hostproxy import manager as hostproxy_manager

        hostproxy_manager.ensure_running(cfg)
        transport.reverse_forward_tcp(gateway, hp.port, "127.0.0.1", hp.port,
                                      tag="hostproxy")
        ch.hostproxy_url = f"http://{gateway}:{hp.port}"
        log.info("worker %s: hostproxy channel %s -> laptop :%d",
                 transport.index, ch.hostproxy_url, hp.port)
    if mon.enable:
        # worker CP netlogger + harness OTLP -> laptop collector.  Two
        # binds: the gateway (for containers) and worker loopback (for the
        # worker-resident CP daemon, whose default endpoint is loopback).
        transport.reverse_forward_tcp(gateway, OTLP_HTTP_PORT,
                                      "127.0.0.1", OTLP_HTTP_PORT, tag="otlp")
        transport.reverse_forward_tcp("127.0.0.1", OTLP_HTTP_PORT,
                                      "127.0.0.1", OTLP_HTTP_PORT,
                                      tag="otlp-local")
        ch.otlp_endpoint = f"http://{gateway}:{OTLP_HTTP_PORT}"
        log.info("worker %s: otlp channel %s -> laptop :%d",
                 transport.index, ch.otlp_endpoint, OTLP_HTTP_PORT)
    engine._side_channels = ch
    return ch
