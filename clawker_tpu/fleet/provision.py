"""Worker provisioning: turn a bare TPU-VM into a clawker-tpu worker.

A provisioning *plan* is data -- an ordered list of steps, each one
remote command with a human name -- executed over any transport runner,
so the full sequence is unit-testable against scripted transcripts and
auditable before it touches a fleet (``clawker fleet provision
--dry-run`` prints it).

Steps (mirroring what the reference gets from its multi-stage
Dockerfile.controlplane build + local installs, re-shaped for remote
workers -- SURVEY.md 7 step 7):

1. preflight: docker daemon present + cgroup2 + bpffs mounted
2. toolchain: python3, g++, make (+ clang/libbpf-dev for the kernel half)
3. push the source payload (native/ + the clawker_tpu package)
4. build: supervisor binary, fw.o + fwctl (skipped without clang)
5. install: binaries onto PATH, package into a venv-less site dir
6. kernel: fwctl load (pin maps+programs) -- skipped without clang
7. control plane: systemd unit (or nohup fallback) running
   ``python3 -m clawker_tpu.controlplane`` per worker
8. verify: healthz answers on the worker

Failure of any step aborts the remaining steps for that worker and
reports; other workers proceed independently (per-worker isolation).
"""

from __future__ import annotations

import io
import tarfile
from dataclasses import dataclass, field
from pathlib import Path

from .. import consts, logsetup
from .transport import SSHTransport, TransportError

log = logsetup.get("fleet.provision")

REMOTE_ROOT = "/opt/clawker-tpu"

def systemd_unit(*, monitor: bool = False) -> str:
    """The per-worker CP unit.  With ``monitor``, CLAWKER_TPU_OTLP points
    the worker netlogger at the laptop collector behind the SSH -R
    loopback tunnel (fleet/channels.py); without it the env is absent so
    disabled telemetry generates zero failed connects."""
    otlp = (f"Environment=CLAWKER_TPU_OTLP=http://127.0.0.1:"
            f"{consts.OTLP_HTTP_PORT}\n" if monitor else "")
    return f"""[Unit]
Description=clawker-tpu per-worker control plane
After=docker.service
[Service]
Environment=PYTHONPATH={REMOTE_ROOT}/src
{otlp}ExecStart=/usr/bin/python3 -m clawker_tpu.controlplane
Restart=on-failure
RestartSec=3
[Install]
WantedBy=multi-user.target
"""


@dataclass
class Step:
    name: str
    cmd: str
    optional: bool = False      # failure logs but does not abort the plan
    timeout: float = 300.0


@dataclass
class StepResult:
    name: str
    ok: bool
    detail: str = ""


@dataclass
class ProvisionReport:
    host: str
    index: int
    results: list[StepResult] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return all(r.ok for r in self.results)


def build_plan(*, with_firewall: bool = True, with_cp: bool = True) -> list[Step]:
    steps = [
        Step("preflight-docker", "docker info --format '{{.ServerVersion}}'"),
        Step("preflight-cgroup2",
             "test -f /sys/fs/cgroup/cgroup.controllers"),
        Step("preflight-bpffs",
             "mountpoint -q /sys/fs/bpf || sudo mount -t bpf bpf /sys/fs/bpf"),
        Step("toolchain",
             "which python3 g++ make || sudo apt-get install -y -q "
             "python3 g++ make"),
        # Reverse forwards for the side channel (hostproxy/OTLP tunnels,
        # fleet/channels.py) must bind the worker's docker-gateway address
        # so containers can reach them; sshd only honors non-loopback -R
        # binds with GatewayPorts clientspecified.
        Step("sshd-gateway-ports",
             "test -f /etc/ssh/sshd_config.d/60-clawker.conf || "
             "(echo 'GatewayPorts clientspecified' | sudo tee "
             "/etc/ssh/sshd_config.d/60-clawker.conf >/dev/null && "
             "(sudo systemctl reload sshd || sudo systemctl reload ssh))",
             optional=True),
    ]
    if with_firewall:
        steps.append(Step(
            "toolchain-bpf",
            "which clang || sudo apt-get install -y -q clang libbpf-dev",
            optional=True,
        ))
    steps += [
        # (payload push happens between these steps; see provision_worker)
        Step("build-native", f"make -C {REMOTE_ROOT}/src/native"),
    ]
    if with_firewall:
        steps += [
            Step("build-ebpf",
                 f"which clang && make -C {REMOTE_ROOT}/src/native/ebpf all",
                 optional=True),
            Step("install-fwctl",
                 f"test -f {REMOTE_ROOT}/src/native/ebpf/build/fwctl && "
                 f"sudo install {REMOTE_ROOT}/src/native/ebpf/build/fwctl "
                 "/usr/local/bin/clawker-fwctl",
                 optional=True),
            Step("kernel-load",
                 f"test -f {REMOTE_ROOT}/src/native/ebpf/build/fw.o && "
                 "sudo clawker-fwctl load "
                 f"--obj {REMOTE_ROOT}/src/native/ebpf/build/fw.o "
                 f"--pin-dir {consts.BPF_PIN_DIR}",
                 optional=True),
        ]
    steps.append(Step(
        "install-supervisor",
        f"sudo install {REMOTE_ROOT}/src/native/build/clawker-supervisord "
        "/usr/local/bin/clawker-supervisord",
    ))
    if with_cp:
        steps += [
            Step("cp-unit",
                 f"sudo cp {REMOTE_ROOT}/clawker-cp.service "
                 "/etc/systemd/system/ && sudo systemctl daemon-reload && "
                 "sudo systemctl enable --now clawker-cp.service || "
                 f"(PYTHONPATH={REMOTE_ROOT}/src nohup python3 -m "
                 "clawker_tpu.controlplane >/tmp/clawker-cp.log 2>&1 &)"),
            Step("verify-healthz",
                 "for i in $(seq 1 30); do "
                 f"curl -fsS http://127.0.0.1:{consts.CP_HEALTH_PORT}/healthz "
                 "&& exit 0; sleep 1; done; exit 1",
                 timeout=60.0),
        ]
    # real-daemon smoke: the worker carries dockerd, so the e2e suite
    # (tests/e2e, reference test/e2e harness) actually runs here -- the
    # one place a real daemon exists in the fleet
    steps.append(Step(
        "e2e-smoke",
        f"cd {REMOTE_ROOT}/src && CLAWKER_TPU_E2E=1 "
        "python3 -m pytest tests/e2e -q",
        optional=True, timeout=300.0,
    ))
    return steps


def payload_tar(repo_root: Path, *, monitor: bool = False) -> bytes:
    """Source payload: the package + native tree + the CP systemd unit."""
    buf = io.BytesIO()

    def _clean(ti: tarfile.TarInfo) -> tarfile.TarInfo | None:
        name = Path(ti.name).name
        if name in ("__pycache__", ".pytest_cache", "build") or name.endswith(".pyc"):
            return None
        return ti

    with tarfile.open(fileobj=buf, mode="w:gz") as tf:
        tf.add(str(repo_root / "clawker_tpu"), arcname="src/clawker_tpu",
               filter=_clean)
        tf.add(str(repo_root / "native"), arcname="src/native", filter=_clean)
        e2e = repo_root / "tests" / "e2e"
        if e2e.is_dir():
            # the worker is where a real daemon lives: ship the e2e suite
            tf.add(str(e2e), arcname="src/tests/e2e", filter=_clean)
        unit = systemd_unit(monitor=monitor).encode()
        ti = tarfile.TarInfo("clawker-cp.service")
        ti.size = len(unit)
        tf.addfile(ti, io.BytesIO(unit))
    return buf.getvalue()


def provision_worker(
    transport: SSHTransport,
    repo_root: Path,
    *,
    with_firewall: bool = True,
    with_cp: bool = True,
    monitor: bool = False,
    payload: bytes | None = None,
    on_step=None,
) -> ProvisionReport:
    """Run the plan against one worker.

    ``payload``: pre-built :func:`payload_tar` bytes -- fleet callers
    build the tar ONCE and share it across every worker
    (:func:`provision_fleet`); a standalone call may omit it and pay the
    build here.  ``on_step(worker_index, StepResult)`` streams each step
    result the moment it lands (CLI progress while other workers are
    still mid-plan).
    """
    report = ProvisionReport(transport.host, transport.index)
    plan = build_plan(with_firewall=with_firewall, with_cp=with_cp)

    def record(res: StepResult) -> None:
        report.results.append(res)
        if on_step is not None:
            try:
                on_step(transport.index, res)
            except Exception:
                # a broken progress consumer must not abort provisioning
                log.exception("on_step callback failed (worker %d)",
                              transport.index)

    pushed = False
    for step in plan:
        # the payload rides in right before the first build step
        if step.name == "build-native" and not pushed:
            try:
                blob = (payload if payload is not None
                        else payload_tar(repo_root, monitor=monitor))
                transport.push_tar(blob, REMOTE_ROOT, sudo=True)
                record(StepResult("push-payload", True))
            except TransportError as e:
                record(StepResult("push-payload", False, str(e)))
                return report
            pushed = True
        res = transport.run(step.cmd, timeout=step.timeout)
        ok = res.rc == 0
        detail = (res.err or res.out).strip()[:500]
        record(StepResult(step.name, ok or step.optional,
                          "" if ok else detail))
        log.info("worker %d %s: %s", transport.index, step.name,
                 "ok" if ok else f"FAILED ({detail[:120]})" if not step.optional
                 else f"skipped ({detail[:120]})")
        if step.name == "sshd-gateway-ports" and ok:
            # sshd reload only affects NEW connections; drop the mux so
            # later -R forwards (which ride it) see GatewayPorts
            transport.drop_mux()
        if not ok and not step.optional:
            return report
    return report


def provision_fleet(
    transports: list[SSHTransport],
    repo_root: Path,
    *,
    with_firewall: bool = True,
    with_cp: bool = True,
    monitor: bool = False,
    max_workers: int = 8,
    on_step=None,
    on_report=None,
) -> list[ProvisionReport]:
    """Provision every worker concurrently, one-pass.

    The payload is tarred ONCE and shared (provisioning K workers used
    to tar the repo K times), and the per-worker plans run over a
    bounded thread pool -- the same idiom as the tpu_vm driver's
    parallel dial (engine/drivers/tpu_vm.py), so wall time no longer
    stacks O(K * RTT) with pod size.  ``on_report(report)`` fires the
    moment each worker finishes (streaming CLI output); the returned
    list is in transport order regardless of completion order.  One
    worker's transport blowing up becomes a failed report for that
    worker, never an abort of the rest (per-worker isolation).
    """
    from concurrent.futures import ThreadPoolExecutor, as_completed

    if not transports:
        return []
    payload = payload_tar(repo_root, monitor=monitor)

    def one(t: SSHTransport) -> ProvisionReport:
        try:
            return provision_worker(
                t, repo_root, with_firewall=with_firewall, with_cp=with_cp,
                monitor=monitor, payload=payload, on_step=on_step)
        except Exception as e:    # transport layer raised past the plan
            rep = ProvisionReport(t.host, t.index)
            rep.results.append(StepResult("transport", False, str(e)))
            return rep

    by_index: dict[int, ProvisionReport] = {}
    with ThreadPoolExecutor(
            max_workers=min(max_workers, len(transports))) as pool:
        futs = [pool.submit(one, t) for t in transports]
        for fut in as_completed(futs):
            rep = fut.result()
            by_index[rep.index] = rep
            if on_report is not None:
                try:
                    on_report(rep)
                except Exception:
                    log.exception("on_report callback failed (worker %d)",
                                  rep.index)
    return [by_index[t.index] for t in transports]
