"""CLI reference generation: the click tree -> markdown pages.

Parity reference: internal/docs (cobra -> markdown/mintlify +
cmd/gen-docs, SURVEY.md 2.1/2.4).  One page per command, named
``clawker_<path>.md`` like the reference's ``docs/cli-reference``, plus
an index page; regeneration is deterministic so docs drift shows up as
a diff.
"""

from __future__ import annotations

from pathlib import Path

import click


def _page_name(path: list[str]) -> str:
    return "clawker" + ("_" + "_".join(path) if path else "") + ".md"


def _render_command(cmd: click.Command, path: list[str]) -> str:
    full = " ".join(["clawker", *path])
    lines = [f"# {full}", ""]
    if cmd.help:
        lines += [cmd.help.strip(), ""]
    ctx = click.Context(cmd, info_name=full)
    usage = cmd.collect_usage_pieces(ctx)
    lines += ["```", f"{full} {' '.join(usage)}".rstrip(), "```", ""]
    params = [p for p in cmd.params if isinstance(p, click.Option) and not p.hidden]
    if params:
        lines += ["## Options", ""]
        for p in sorted(params, key=lambda p: p.opts[0]):
            names = ", ".join(p.opts + p.secondary_opts)
            lines.append(f"- `{names}` — {p.help or ''}".rstrip(" —"))
        lines.append("")
    if isinstance(cmd, click.Group):
        subs = [(n, c) for n, c in sorted(cmd.commands.items()) if not c.hidden]
        if subs:
            lines += ["## Subcommands", ""]
            for name, sub in subs:
                short = (sub.get_short_help_str(limit=80) or "").strip()
                lines.append(f"- [`{name}`]({_page_name(path + [name])}) — {short}".rstrip(" —"))
            lines.append("")
    return "\n".join(lines) + "\n"


def generate_cli_reference(root: click.Group, out_dir: Path) -> list[Path]:
    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    written: list[Path] = []

    def walk(cmd: click.Command, path: list[str]) -> None:
        page = out_dir / _page_name(path)
        page.write_text(_render_command(cmd, path))
        written.append(page)
        if isinstance(cmd, click.Group):
            for name, sub in sorted(cmd.commands.items()):
                if sub.hidden:
                    continue
                walk(sub, path + [name])

    walk(root, [])
    index = ["# clawker CLI reference", ""]
    for page in sorted(written):
        title = page.stem.replace("clawker_", "clawker ").replace("_", " ")
        if page.stem == "clawker":
            title = "clawker"
        index.append(f"- [{title}]({page.name})")
    (out_dir / "README.md").write_text("\n".join(index) + "\n")
    written.append(out_dir / "README.md")
    return written
