"""CLI reference generation: the click tree -> markdown pages.

Parity reference: internal/docs (cobra -> markdown/mintlify +
cmd/gen-docs, SURVEY.md 2.1/2.4).  One page per command, named
``clawker_<path>.md`` like the reference's ``docs/cli-reference``, plus
an index page; regeneration is deterministic so docs drift shows up as
a diff.
"""

from __future__ import annotations

from pathlib import Path

import click


def _page_name(path: list[str]) -> str:
    return "clawker" + ("_" + "_".join(path) if path else "") + ".md"


def _render_command(cmd: click.Command, path: list[str]) -> str:
    full = " ".join(["clawker", *path])
    lines = [f"# {full}", ""]
    if cmd.help:
        lines += [cmd.help.strip(), ""]
    ctx = click.Context(cmd, info_name=full)
    usage = cmd.collect_usage_pieces(ctx)
    lines += ["```", f"{full} {' '.join(usage)}".rstrip(), "```", ""]
    params = [p for p in cmd.params if isinstance(p, click.Option) and not p.hidden]
    if params:
        lines += ["## Options", ""]
        for p in sorted(params, key=lambda p: p.opts[0]):
            names = ", ".join(p.opts + p.secondary_opts)
            lines.append(f"- `{names}` — {p.help or ''}".rstrip(" —"))
        lines.append("")
    if isinstance(cmd, click.Group):
        subs = [(n, c) for n, c in sorted(cmd.commands.items()) if not c.hidden]
        if subs:
            lines += ["## Subcommands", ""]
            for name, sub in subs:
                short = (sub.get_short_help_str(limit=80) or "").strip()
                lines.append(f"- [`{name}`]({_page_name(path + [name])}) — {short}".rstrip(" —"))
            lines.append("")
    return "\n".join(lines) + "\n"


def generate_cli_reference(root: click.Group, out_dir: Path) -> list[Path]:
    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    written: list[Path] = []

    def walk(cmd: click.Command, path: list[str]) -> None:
        page = out_dir / _page_name(path)
        page.write_text(_render_command(cmd, path))
        written.append(page)
        if isinstance(cmd, click.Group):
            for name, sub in sorted(cmd.commands.items()):
                if sub.hidden:
                    continue
                walk(sub, path + [name])

    walk(root, [])
    index = ["# clawker CLI reference", ""]
    for page in sorted(written):
        title = page.stem.replace("clawker_", "clawker ").replace("_", " ")
        if page.stem == "clawker":
            title = "clawker"
        index.append(f"- [{title}]({page.name})")
    (out_dir / "README.md").write_text("\n".join(index) + "\n")
    written.append(out_dir / "README.md")
    return written


# --------------------------------------------------------------- schemas

def _schema_for(ft, descriptions: dict | None = None) -> dict:
    """Dataclass/typing tree -> JSON Schema fragment."""
    import dataclasses
    from typing import get_args, get_origin, get_type_hints

    origin = get_origin(ft)
    if dataclasses.is_dataclass(ft):
        hints = get_type_hints(ft)
        props = {}
        for f in dataclasses.fields(ft):
            sub = _schema_for(hints[f.name])
            if f.default is not dataclasses.MISSING:
                sub["default"] = f.default
            props[f.name] = sub
        out = {"type": "object", "properties": props,
               "additionalProperties": False}
        doc = (ft.__doc__ or "").strip().split("\n")[0]
        if doc:
            out["description"] = doc
        return out
    if origin is list:
        (elem,) = get_args(ft)
        return {"type": "array", "items": _schema_for(elem)}
    if origin is dict:
        _, vt = get_args(ft)
        return {"type": "object", "additionalProperties": _schema_for(vt)}
    if ft is str:
        return {"type": "string"}
    if ft is bool:
        return {"type": "boolean"}
    if ft is int:
        return {"type": "integer"}
    if ft is float:
        return {"type": "number"}
    return {}


def generate_json_schemas(out_dir: Path) -> list[Path]:
    """Editor schemas for clawker.yaml + settings.yaml (reference:
    internal/docs JSON schema gen -> docs/schemas/*.json)."""
    import json

    from .config.schema import ProjectConfig, Settings

    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    written = []
    for name, cls in (("clawker.yaml", ProjectConfig),
                      ("settings.yaml", Settings)):
        schema = {
            "$schema": "http://json-schema.org/draft-07/schema#",
            "$id": f"https://clawker-tpu.dev/schemas/{name}.json",
            "title": name,
            **_schema_for(cls),
        }
        path = out_dir / f"{name.replace('.yaml', '')}.schema.json"
        path.write_text(json.dumps(schema, indent=2, sort_keys=True) + "\n")
        written.append(path)
    return written
