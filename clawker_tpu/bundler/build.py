"""Project image build orchestration: base stage then harness stage.

Reference call stack: internal/cmd/image/build/build.go:110 buildRun ->
bundler.GenerateBase/GenerateHarness -> client.BuildImage -> tag
``:<harness>`` + ``:default`` alias (SURVEY.md 3.2).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Iterator

from .. import consts
from ..bundle import Resolver
from ..config import Config
from ..engine.api import Engine
from ..errors import ClawkerError
from .context import build_context
from .dockerfile import CTX_CA_CERT, generate_base, generate_harness
from .payload import agentd_payload


@dataclass
class BuildResult:
    base_ref: str = ""
    harness_ref: str = ""
    default_ref: str = ""
    with_agentd: bool = False
    with_ca: bool = False
    events: list[str] = field(default_factory=list)


class ProjectBuilder:
    def __init__(
        self,
        engine: Engine,
        cfg: Config,
        *,
        ca_cert_pem: bytes | None = None,
        progress: Callable[[str], None] | None = None,
    ):
        self.engine = engine
        self.cfg = cfg
        self.ca_cert_pem = ca_cert_pem
        self.progress = progress or (lambda _line: None)

    def build(self, *, harness_override: str = "", no_cache: bool = False,
              secrets: dict[str, bytes] | None = None,
              ssh_auth_sock: str = "") -> BuildResult:
        """secrets/ssh ride the BuildKit session lane (RUN --mount=type=
        secret|ssh); see engine/bksession.py."""
        pconf = self.cfg.project
        if pconf is None:
            raise ClawkerError("no project config found -- run `clawker init` first")
        project = self.cfg.project_name()
        resolver = Resolver(self.cfg)
        stack = resolver.stack(pconf.build.stack or "python")
        harness = resolver.harness(harness_override or pconf.build.harness or "claude")

        res = BuildResult()
        # ---- stage 1: base
        base_ref = f"{consts.IMAGE_NAME_PREFIX}{project}:{consts.IMAGE_TAG_BASE}"
        self.progress(f"building {base_ref} (stack {stack.name})")
        base_df = generate_base(project, stack, pconf.build)
        self._run_build(
            build_context({"Dockerfile": base_df.encode()}),
            tags=[base_ref],
            labels={consts.LABEL_IMAGE_KIND: "base", consts.LABEL_PROJECT: project},
            res=res,
            no_cache=no_cache,
            secrets=secrets,
            ssh_auth_sock=ssh_auth_sock,
        )
        res.base_ref = base_ref

        # ---- stage 2: harness
        harness_ref = f"{consts.IMAGE_NAME_PREFIX}{project}:{harness.name}"
        self.progress(f"building {harness_ref} (harness {harness.name})")
        agentd = agentd_payload()
        files: dict[str, bytes] = {}
        extra: list[str] = []
        if harness.source_dir is not None:
            src_root = harness.source_dir.resolve()
            for f in harness.files:
                # containment: a third-party bundle manifest must not reach
                # outside its own directory (matches the installer's
                # symlink rejection, bundle/manager.py)
                p = (src_root / f).resolve()
                if not p.is_relative_to(src_root):
                    raise ClawkerError(
                        f"harness {harness.name}: file {f!r} escapes the bundle directory"
                    )
                files[f] = p.read_bytes()
            extra = list(harness.files)
        with_ca = self.ca_cert_pem is not None
        if with_ca:
            files[CTX_CA_CERT] = self.ca_cert_pem  # type: ignore[assignment]
        if agentd is not None:
            files.update(agentd)
        from ..hostproxy.scripts import CONTEXT_SCRIPTS

        for arc, (_target, content) in CONTEXT_SCRIPTS.items():
            files[arc] = content.encode()
        harness_df = generate_harness(
            project,
            harness,
            pconf.build,
            base_ref=base_ref,
            with_ca_cert=with_ca,
            with_agentd=agentd is not None,
            extra_files=extra,
        )
        files["Dockerfile"] = harness_df.encode()
        self._run_build(
            build_context(files),
            tags=[harness_ref],
            labels={
                consts.LABEL_IMAGE_KIND: "harness",
                consts.LABEL_PROJECT: project,
                consts.LABEL_HARNESS: harness.name,
            },
            res=res,
            no_cache=no_cache,
            secrets=secrets,
            ssh_auth_sock=ssh_auth_sock,
        )
        res.harness_ref = harness_ref
        res.with_agentd = agentd is not None
        res.with_ca = with_ca

        # ---- :default alias
        default_ref = f"{consts.IMAGE_NAME_PREFIX}{project}:{consts.IMAGE_TAG_DEFAULT}"
        self.engine.tag_image(harness_ref, f"{consts.IMAGE_NAME_PREFIX}{project}", consts.IMAGE_TAG_DEFAULT)
        res.default_ref = default_ref
        self.progress(f"tagged {default_ref}")
        return res

    def _run_build(
        self, ctx: bytes, *, tags: list[str], labels: dict, res: BuildResult,
        no_cache: bool = False, secrets: dict[str, bytes] | None = None,
        ssh_auth_sock: str = "",
    ) -> None:
        stream: Iterator[dict] = self.engine.build_image(
            ctx, tags=tags, labels=labels, no_cache=no_cache,
            secrets=secrets, ssh_auth_sock=ssh_auth_sock,
        )
        err = ""
        for ev in stream:
            if "stream" in ev:
                line = ev["stream"].rstrip()
                if line:
                    res.events.append(line)
                    self.progress(line)
            elif "errorDetail" in ev or "error" in ev:
                err = (ev.get("errorDetail") or {}).get("message") or ev.get("error", "")
        if err:
            raise ClawkerError(f"build of {tags[0]} failed: {err}")
