"""Dockerfile generation for the two-stage project image.

Stage 1 (``clawker-<project>:base``): stack base image + OS packages +
agent user + workspace.  Stage 2 (``clawker-<project>:<harness>``): harness
install + env + firewall CA + the native supervisor as PID 1 with the
agentd zipapp as its service child.  Generation is deterministic
(sorted packages, stable ordering) so unchanged config hits the daemon's
layer cache end to end.  Reference: internal/bundler/dockerfile.go
GenerateBase :367 / GenerateHarness :407; cache-tail invariant pinned by
the reference's TestBuildContext_LateClawkerBlock.
"""

from __future__ import annotations

import json

from .. import consts
from ..bundle.model import Harness, Stack
from ..config.schema import BuildConfig

AGENT_USER = "agent"
AGENT_UID = 1001

# context-relative paths (fixed; the tar assembler must provide them)
CTX_SUPERVISOR = "clawker-supervisord"
CTX_AGENTD_PYZ = "clawker-agentd.pyz"
CTX_CA_CERT = "clawker-ca.crt"

# The agentd session daemon is a stdlib-only zipapp; python3 in the base
# stage is the one hard package requirement of every agent image.
BASE_REQUIRED_PACKAGES = ("python3", "ca-certificates")


def _env_lines(env: dict[str, str]) -> list[str]:
    return [f"ENV {k}={_quote(v)}" for k, v in sorted(env.items())]


def _quote(v: str) -> str:
    if v and all(c.isalnum() or c in "._-:/" for c in v):
        return v
    return '"' + v.replace("\\", "\\\\").replace('"', '\\"') + '"'


def generate_base(project: str, stack: Stack, build: BuildConfig) -> str:
    """Base-stage Dockerfile: stack image, packages, non-root agent user."""
    base_image = build.image or stack.base_image
    packages = set(stack.packages) | set(build.packages)
    # Stack bases are Debian-family, so the agentd runtime deps ride the
    # same apt layer.  A custom build.image may not have apt at all: the
    # user's image contract then includes python3 (documented in
    # docs/image-requirements) and we emit no unconditional apt RUN.
    if not build.image:
        packages |= set(BASE_REQUIRED_PACKAGES)
    packages = sorted(packages)
    lines = [
        f"# clawker-tpu base image for project {project!r} (stack {stack.name})",
        f"FROM {base_image}",
        "",
        "ARG DEBIAN_FRONTEND=noninteractive",
    ]
    if packages:
        lines += [
            "RUN apt-get update \\",
            "    && apt-get install -y --no-install-recommends \\",
            "       " + " ".join(packages) + " \\",
            "    && rm -rf /var/lib/apt/lists/*",
        ]
    lines += [f"RUN {cmd}" for cmd in stack.install]
    lines += _env_lines(stack.env)
    lines += [
        "",
        f"RUN useradd -m -u {AGENT_UID} -s /bin/bash {AGENT_USER} \\",
        f"    && mkdir -p {consts.WORKSPACE_DIR} \\",
        f"    && chown {AGENT_USER}:{AGENT_USER} {consts.WORKSPACE_DIR} \\",
        "    && mkdir -p /var/run/clawker /var/lib/clawker /run/clawker",
        f"WORKDIR {consts.WORKSPACE_DIR}",
    ]
    lines += _env_lines(build.env)
    lines += build.instructions
    return "\n".join(lines) + "\n"


def generate_harness(
    project: str,
    harness: Harness,
    build: BuildConfig,
    *,
    base_ref: str = "",
    with_ca_cert: bool = False,
    with_agentd: bool = True,
    extra_files: list[str] | None = None,
) -> str:
    """Harness-stage Dockerfile, FROM the project base image.

    The CA cert and the agentd binary are copied at the *tail* so harness
    layer caching survives agentd rebuilds and CA rotation (reference
    cache-tail invariant, bundler/dockerfile.go:550).
    """
    base = base_ref or f"{consts.IMAGE_NAME_PREFIX}{project}:{consts.IMAGE_TAG_BASE}"
    lines = [
        f"# clawker-tpu harness image for project {project!r} (harness {harness.name})",
        f"FROM {base}",
        "",
        "ARG DEBIAN_FRONTEND=noninteractive",
    ]
    packages = sorted(set(harness.packages))
    if packages:
        lines += [
            "RUN apt-get update \\",
            "    && apt-get install -y --no-install-recommends \\",
            "       " + " ".join(packages) + " \\",
            "    && rm -rf /var/lib/apt/lists/*",
        ]
    lines += [f"RUN {cmd}" for cmd in harness.install]
    lines += _env_lines(harness.env)
    # host-proxy side-channel scripts (no-ops when CLAWKER_HOSTPROXY is
    # unset; reference bakes internal/hostproxy/internals the same way)
    from ..hostproxy.scripts import CONTEXT_SCRIPTS

    targets = [t for _, (t, _c) in sorted(CONTEXT_SCRIPTS.items())]
    for arc, (target, _content) in sorted(CONTEXT_SCRIPTS.items()):
        lines.append(f"COPY {arc} {target}")
    lines += [
        f"RUN chmod 0755 {' '.join(targets)} \\",
        "    && git config --system credential.helper "
        "/usr/local/bin/git-credential-clawker || true",
    ]
    for f in extra_files or []:
        lines.append(f"COPY {f} /opt/clawker/{f}")
    # ---- cache tail: frequently-rotated material goes last ----
    if with_ca_cert:
        lines += [
            f"COPY {CTX_CA_CERT} {consts.CA_CERT_PATH}",
            "RUN update-ca-certificates || true",
            # tools that read their own CA bundles need the env hint
            f"ENV NODE_EXTRA_CA_CERTS={consts.CA_CERT_PATH}",
            f"ENV SSL_CERT_FILE={consts.CA_CERT_PATH}",
        ]
    if with_agentd:
        # ENTRYPOINT = native supervisor (PID 1) with the agentd zipapp as
        # its service child; Docker appends CMD to the entrypoint argv, so
        # the user command lands after --default-cmd and agentd stores it
        # to spawn on AgentReady (reference: clawkerd runs the image CMD
        # only when the CP sends AgentReady, SURVEY.md 3.1).
        entry = [
            consts.SUPERVISOR_PATH,
            "--socket", consts.SUPERVISOR_SOCKET,
            "--child",
            "python3", consts.AGENTD_PYZ_PATH,
            "--supervisor-socket", consts.SUPERVISOR_SOCKET,
            "--default-cmd",
        ]
        lines += [
            f"COPY {CTX_SUPERVISOR} {consts.SUPERVISOR_PATH}",
            f"COPY {CTX_AGENTD_PYZ} {consts.AGENTD_PYZ_PATH}",
            f"RUN chmod 0755 {consts.SUPERVISOR_PATH}",
            "ENTRYPOINT " + json.dumps(entry),
        ]
    cmd = build.env.get("CLAWKER_CMD_OVERRIDE", "")  # env override escape hatch
    harness_cmd = [cmd] if cmd else harness.cmd
    lines.append("CMD " + json.dumps(harness_cmd))
    return "\n".join(lines) + "\n"
