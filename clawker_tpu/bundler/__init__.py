"""Image bundler: two-stage Dockerfile generation + build orchestration.

Parity reference: internal/bundler (SURVEY.md 2.6) -- ``GenerateBase`` /
``GenerateHarness`` render ``clawker-<project>:base`` and
``clawker-<project>:<harness>`` stages; the build context carries the
firewall CA cert and the agentd binary as the *last* COPY so agentd
rebuilds never invalidate earlier layers (cache-tail invariant).
"""

from .dockerfile import generate_base, generate_harness
from .context import build_context
from .build import ProjectBuilder, BuildResult
from .egress import compose_egress_rules

__all__ = [
    "generate_base",
    "generate_harness",
    "build_context",
    "ProjectBuilder",
    "BuildResult",
    "compose_egress_rules",
]
