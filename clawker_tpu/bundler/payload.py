"""In-container agentd payload assembly: native supervisor + Python zipapp.

Parity reference: clawkerd/embed/embed.go -- the reference embeds one static
Go binary and the bundler copies it into every agent image as the cache
tail.  This build's daemon is two artifacts: the dependency-free C++
``clawker-supervisord`` (PID 1; native/agentsup) and ``clawker-agentd.pyz``,
a stdlib-only zipapp holding the session daemon (clawker_tpu/agentd plus the
tiny modules it imports).  Both are assembled deterministically so the image
layer cache keys on content.
"""

from __future__ import annotations

import io
import os
import zipfile
from pathlib import Path

ENV_SUPERVISOR_BIN = "CLAWKER_TPU_SUPERVISOR_BIN"

_PKG_ROOT = Path(__file__).resolve().parents[1]  # clawker_tpu/

# The transitive closure of clawker_tpu.agentd imports -- everything must be
# stdlib-only so the pyz runs on a bare python3 in any image.
_PYZ_MODULES = (
    "__init__.py",
    "consts.py",
    "errors.py",
    "agentd/__init__.py",
    "agentd/__main__.py",
    "agentd/daemon.py",
    "agentd/protocol.py",
    "agentd/register.py",
    "agentd/supervisor_client.py",
    # container side of the socket bridge (exec'd with the pyz on sys.path)
    "socketbridge/__init__.py",
    "socketbridge/protocol.py",
    "socketbridge/container.py",
)

_PYZ_MAIN = b"""\
from clawker_tpu.agentd.daemon import main

raise SystemExit(main())
"""


def build_agentd_pyz() -> bytes:
    """Deterministic zipapp of the agentd subset (zeroed timestamps)."""
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", compression=zipfile.ZIP_DEFLATED) as zf:
        entries = {"__main__.py": _PYZ_MAIN}
        for rel in _PYZ_MODULES:
            entries[f"clawker_tpu/{rel}"] = (_PKG_ROOT / rel).read_bytes()
        for name in sorted(entries):
            info = zipfile.ZipInfo(name, date_time=(1980, 1, 1, 0, 0, 0))
            info.external_attr = 0o644 << 16
            zf.writestr(info, entries[name])
    return buf.getvalue()


def find_supervisor_binary() -> bytes | None:
    """The native clawker-supervisord build output (or env-pointed path)."""
    cand = os.environ.get(ENV_SUPERVISOR_BIN, "")
    paths = [Path(cand)] if cand else []
    paths.append(_PKG_ROOT.parent / "native" / "build" / "clawker-supervisord")
    for p in paths:
        if p.is_file():
            return p.read_bytes()
    return None


def agentd_payload() -> dict[str, bytes] | None:
    """Context files for the image tail, or None when the native binary is
    absent (image then runs its harness CMD directly, no supervision)."""
    from .dockerfile import CTX_AGENTD_PYZ, CTX_SUPERVISOR

    sup = find_supervisor_binary()
    if sup is None:
        return None
    return {
        CTX_SUPERVISOR: sup,
        CTX_AGENTD_PYZ: build_agentd_pyz(),
    }
