"""Build-context tar assembly (deterministic).

Entries are emitted in sorted order with zeroed timestamps so an unchanged
context produces byte-identical tars -- the daemon's content-addressed
cache then short-circuits the whole build (reference: internal/bundler tar
context assembly, dockerfile.go:506-565).
"""

from __future__ import annotations

import io
import tarfile


def build_context(files: dict[str, bytes]) -> bytes:
    """files: context-relative path -> content. Must include 'Dockerfile'."""
    from .dockerfile import CTX_SUPERVISOR

    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w") as tf:
        for name in sorted(files):
            data = files[name]
            info = tarfile.TarInfo(name=name)
            info.size = len(data)
            info.mtime = 0
            info.mode = 0o755 if name == CTX_SUPERVISOR else 0o644
            tf.addfile(info, io.BytesIO(data))
    return buf.getvalue()
