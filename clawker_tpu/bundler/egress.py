"""Egress-rule composition: required internal + harness + project rules.

Reference: internal/bundler/egress.go + internal/config EgressRules() --
the effective allowlist for an agent is the union of (a) domains the
framework itself requires, (b) domains the harness declares, and (c) the
project's ``security.egress`` rules, deduped by ``dst:proto:port``.
"""

from __future__ import annotations

from .. import consts
from ..bundle.model import Harness
from ..config.schema import EgressRule, ProjectConfig


def compose_egress_rules(
    project: ProjectConfig | None,
    harness: Harness | None,
) -> list[EgressRule]:
    rules: list[EgressRule] = []
    seen: set[str] = set()

    def add(rule: EgressRule) -> None:
        k = rule.key()
        if k not in seen:
            seen.add(k)
            rules.append(rule)

    for dom in consts.REQUIRED_EGRESS_DOMAINS:
        add(EgressRule(dst=dom, proto="https"))
    if harness is not None:
        for r in harness.egress:
            add(r)
    if project is not None:
        for r in project.security.egress:
            add(r)
    return rules
