"""Ref-level policy: who may see, fetch, and update which git refs.

The swarm contract (docs/loop-worktrees.md) gives every agent exactly
one branch, ``{branch_prefix}/{run}/{agent}``, and routes integration
through a merge queue that alone lands ``{branch_prefix}/{run}/merged``.
This module is the pure-decision half of gitguard: given an agent
identity and a ref name, return an allow/deny :class:`Decision` with a
human-and-git-readable reason.  No I/O, no protocol -- the proxy
(:mod:`.server`) and the chaos invariant both call the same functions,
so the thing the soak audits is the thing production enforces.

Identity binding (docs/git-policy.md): inside a swarm the agent's
container carries the PR-6 mTLS leaf whose CN is ``{project}.{agent}``
and the ``dev.clawker-tpu.agent`` label.  Envoy terminates the MITM'd
TLS, verifies the leaf, and forwards the request over the gitguard unix
socket with the ``X-Clawker-Identity`` header.  gitguard trusts that
header for exactly one reason: the socket is 0600 inside a 0700 runtime
dir, so only the envoy/loopd user can speak to it at all.  Anything
without the header is an unauthenticated peer and gets the empty
namespace (sees the base branch, updates nothing).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..config.schema import EgressRule, PathRule
from ..consts import LABEL_AGENT, LABEL_ROLE

# Header Envoy injects after verifying the client leaf; value is
# "{run}/{agent}" (or "{run}/{agent}/{role}" for the merge queue).
IDENTITY_HEADER = "X-Clawker-Identity"

# The privileged role that alone may fast-forward the integration ref.
MERGE_QUEUE_ROLE = "mergeq"

# Decision verdict strings (journal/bus/metrics vocabulary).
ALLOW = "allow"
DENY = "deny"
DOWN_REFUSED = "down_refused"   # client-observed: guard gone, fail-closed


@dataclass(frozen=True)
class AgentIdentity:
    """A resolved caller: run id, agent name, optional privileged role."""

    run: str
    agent: str
    role: str = ""

    @property
    def merge_queue(self) -> bool:
        return self.role == MERGE_QUEUE_ROLE

    def header_value(self) -> str:
        base = f"{self.run}/{self.agent}"
        return f"{base}/{self.role}" if self.role else base

    @classmethod
    def from_header(cls, value: str) -> "AgentIdentity | None":
        parts = [p for p in (value or "").strip().split("/") if p]
        if len(parts) == 2:
            return cls(run=parts[0], agent=parts[1])
        if len(parts) == 3:
            return cls(run=parts[0], agent=parts[1], role=parts[2])
        return None

    @classmethod
    def from_labels(cls, labels: dict[str, str], run: str,
                    ) -> "AgentIdentity | None":
        """Fallback binding from container labels (no mTLS leaf)."""
        agent = (labels or {}).get(LABEL_AGENT, "")
        if not agent:
            return None
        role = (labels or {}).get(LABEL_ROLE, "")
        return cls(run=run, agent=agent,
                   role=role if role == MERGE_QUEUE_ROLE else "")


@dataclass(frozen=True)
class Decision:
    """One policy verdict, shaped for the journal/bus/metrics."""

    verdict: str                # ALLOW | DENY | DOWN_REFUSED
    reason: str                 # git-readable refusal text ("" on allow)
    service: str = ""           # git-upload-pack | git-receive-pack
    ref: str = ""
    agent: str = ""
    run: str = ""

    @property
    def allowed(self) -> bool:
        return self.verdict == ALLOW

    def to_doc(self) -> dict:
        return {"verdict": self.verdict, "reason": self.reason,
                "service": self.service, "ref": self.ref,
                "agent": self.agent, "run": self.run}


def _bad_ref_name(ref: str) -> str:
    """Syntactic refusal reason for a hostile ref name, or ""."""
    if not ref:
        return "empty ref name"
    if "\x00" in ref:
        return "NUL byte in ref name"
    if any(ord(c) < 0x20 or ord(c) == 0x7F for c in ref):
        return "control byte in ref name"
    if ".." in ref:
        return "'..' in ref name"
    if not ref.startswith("refs/"):
        return "ref outside refs/"
    if ref.endswith("/") or ref.endswith(".lock") or "//" in ref:
        return "malformed ref name"
    return ""


@dataclass(frozen=True)
class RefPolicy:
    """The branch-per-agent namespace rule for one run.

    ``base_refs`` lists refs every agent may fetch (the seed branch and
    anything the operator pins); agents additionally see their own
    namespace, and nothing else.
    """

    run: str
    branch_prefix: str = "loop"
    base_refs: tuple[str, ...] = ("refs/heads/main",)
    merge_ref: str = ""         # "" -> refs/heads/{prefix}/{run}/merged

    def namespace(self, identity: AgentIdentity) -> str:
        return f"refs/heads/{self.branch_prefix}/{self.run}/{identity.agent}"

    def integration_ref(self) -> str:
        if self.merge_ref:
            return self.merge_ref
        return f"refs/heads/{self.branch_prefix}/{self.run}/merged"

    def _in_namespace(self, identity: AgentIdentity, ref: str) -> bool:
        ns = self.namespace(identity)
        return ref == ns or ref.startswith(ns + "/")

    def may_read(self, identity: AgentIdentity | None, ref: str) -> bool:
        """Fetch/advertisement visibility: base refs + own namespace.

        The merge queue sees everything (it must fetch every agent
        branch to land them); HEAD stays visible so clones resolve.
        """
        if ref == "HEAD" or ref in self.base_refs:
            return True
        if identity is None:
            return False
        if identity.merge_queue:
            return True
        return self._in_namespace(identity, ref)

    def may_update(self, identity: AgentIdentity | None, ref: str,
                   *, service: str = "git-receive-pack") -> Decision:
        """Push verdict for one ``old new ref`` command."""
        agent = identity.agent if identity else ""
        run = identity.run if identity else self.run
        bad = _bad_ref_name(ref)
        if bad:
            return Decision(DENY, bad, service=service, ref=ref,
                            agent=agent, run=run)
        if identity is None:
            return Decision(DENY, "unauthenticated push refused",
                            service=service, ref=ref, agent=agent, run=run)
        if identity.run != self.run:
            return Decision(DENY, f"identity run {identity.run!r} does not "
                            f"match guarded run {self.run!r}",
                            service=service, ref=ref, agent=agent, run=run)
        if ref == self.integration_ref():
            if identity.merge_queue:
                return Decision(ALLOW, "", service=service, ref=ref,
                                agent=agent, run=run)
            return Decision(
                DENY, "integration branch is merge-queue only "
                      "(submit via the queue)", service=service, ref=ref,
                agent=agent, run=run)
        if self._in_namespace(identity, ref):
            return Decision(ALLOW, "", service=service, ref=ref,
                            agent=agent, run=run)
        return Decision(
            DENY, f"ref outside agent namespace "
                  f"{self.branch_prefix}/{self.run}/{agent}",
            service=service, ref=ref, agent=agent, run=run)


def git_egress_rules(hosts: list[str]) -> list[EgressRule]:
    """The run-scoped rule set a worktree swarm installs for git hosts.

    For each host: one https rule whose path-ruling forces the MITM +
    gitguard lane, plus explicit ssh/22 and git/9418 deny pins so the
    guarded smart-HTTP lane is the *only* git path even if a broader
    user rule would otherwise allow those ports.  Returned rules are
    added through the normal RulesStore (dedupe key ``dst:proto:port``)
    and removed by key at cleanup.
    """
    rules: list[EgressRule] = []
    for host in hosts:
        rules.append(EgressRule(
            dst=host, proto="https",
            path_rules=[PathRule(path="/", action="allow")]))
        rules.append(EgressRule(dst=host, proto="ssh", port=22,
                                action="deny"))
        rules.append(EgressRule(dst=host, proto="git", port=9418,
                                action="deny"))
    return rules
