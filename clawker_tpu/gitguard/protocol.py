"""Smart-HTTP protocol filter: advertisements in, verdicts out.

Three wire shapes matter to the proxy (git docs: http-protocol.txt,
pack-protocol.txt, protocol-v2.txt):

1. ``GET /info/refs?service=git-upload-pack|git-receive-pack`` -- the
   ref advertisement.  v0: a ``# service=`` header pkt, flush, then
   ``<sha> <ref>`` lines where the FIRST line carries ``\\0``-separated
   capabilities; hidden refs must be dropped *and* the capability
   suffix re-homed onto the first surviving line or the zero-id
   ``capabilities^{}`` placeholder.  v2: a capability listing; the ref
   filtering happens on the later ``ls-refs`` response instead.
2. ``POST /git-receive-pack`` -- a pkt-line command list
   ``<old-sha> <new-sha> <ref>`` (first line again carrying caps),
   flush, then the packfile.  The filter parses the commands, refuses
   a *smuggled second command list* (extra commands after the first
   flush), and never forwards a refused push.
3. The refusal itself -- report-status (``unpack ok`` / ``ng <ref>
   <reason>``), sideband-wrapped when the client asked for
   side-band-64k.  A git client parses this into ``! [remote
   rejected]`` lines; a bare TCP reset would instead retry or surface
   a useless curl error, so the guard always answers in-protocol.

Pure functions over bytes: the server owns sockets, this owns framing.
"""

from __future__ import annotations

from dataclasses import dataclass

from .pktline import (
    DATA,
    DELIM,
    FLUSH,
    FLUSH_PKT,
    Pkt,
    PktError,
    encode_pkt,
    encode_sideband,
    iter_pkts,
)
from .refpolicy import AgentIdentity, Decision, RefPolicy

GIT_UPLOAD_PACK = "git-upload-pack"
GIT_RECEIVE_PACK = "git-receive-pack"
SERVICES = (GIT_UPLOAD_PACK, GIT_RECEIVE_PACK)

ZERO_SHA = "0" * 40

# Capabilities gitguard itself understands in a receive-pack request.
# report-status / side-band are what we need to answer refusals; the
# rest pass through untouched on allowed pushes.
_SIDEBAND_CAPS = ("side-band-64k", "side-band")


@dataclass(frozen=True)
class RefUpdate:
    """One receive-pack command: update ``ref`` from old to new sha."""

    old_sha: str
    new_sha: str
    ref: str
    caps: tuple[str, ...] = ()

    @property
    def is_delete(self) -> bool:
        return self.new_sha == ZERO_SHA


@dataclass(frozen=True)
class PushRequest:
    """A parsed ``POST /git-receive-pack`` body."""

    commands: tuple[RefUpdate, ...]
    caps: tuple[str, ...]
    pack: bytes                 # packfile bytes after the flush (may be b"")

    @property
    def wants_sideband(self) -> bool:
        return any(c in self.caps for c in _SIDEBAND_CAPS)

    @property
    def wants_report_status(self) -> bool:
        return any(c.startswith("report-status") for c in self.caps)


def _split_ref_line(payload: bytes) -> tuple[str, tuple[str, ...]]:
    """Split ``<...> <ref>[\\0caps]`` payload -> (line-sans-caps, caps)."""
    raw = payload.rstrip(b"\n")
    if b"\x00" in raw:
        line, caps = raw.split(b"\x00", 1)
        return (line.decode("utf-8", "replace"),
                tuple(caps.decode("utf-8", "replace").split()))
    return raw.decode("utf-8", "replace"), ()


def filter_advertisement(body: bytes, service: str, policy: RefPolicy,
                         identity: AgentIdentity | None,
                         ) -> tuple[bytes, int]:
    """Rewrite an info/refs advertisement to the caller's visibility.

    Returns ``(new_body, hidden_count)``.  v2 advertisements (a
    capability listing with no ref lines) pass through unchanged --
    their refs travel in the later ``ls-refs`` response, which the
    server filters with :func:`filter_ls_refs`.  Peeled ``<ref>^{}``
    lines follow their parent's visibility.
    """
    pkts = list(iter_pkts(body))
    out = bytearray()
    hidden = 0
    caps: tuple[str, ...] = ()
    caps_homed = False
    saw_ref = False
    i = 0
    # Optional "# service=..." header pkt + flush (smart-HTTP GET only).
    if pkts and pkts[0].kind == DATA and pkts[0].payload.startswith(
            b"# service="):
        out += encode_pkt(pkts[0].payload)
        i = 1
        if i < len(pkts) and pkts[i].kind == FLUSH:
            out += FLUSH_PKT
            i += 1
    body_pkts = pkts[i:]
    if any(p.kind == DATA and p.payload.startswith(b"version 2")
           for p in body_pkts):
        # v2 capability advertisement: no refs here, nothing to hide.
        for p in body_pkts:
            out += _reencode(p)
        return bytes(out), 0
    kept: list[tuple[str, str]] = []       # (sha, ref) lines kept
    for p in body_pkts:
        if p.kind != DATA:
            continue
        line, line_caps = _split_ref_line(p.payload)
        if not caps and line_caps:
            caps = line_caps
        parts = line.split(" ", 1)
        if len(parts) != 2:
            raise PktError(f"malformed advertisement line {line!r}")
        sha, ref = parts
        saw_ref = True
        base_ref = ref[:-3] if ref.endswith("^{}") else ref
        if policy.may_read(identity, base_ref):
            kept.append((sha, ref))
        else:
            hidden += 1
    for sha, ref in kept:
        if not caps_homed:
            payload = f"{sha} {ref}".encode() + b"\x00" + \
                " ".join(caps).encode() + b"\n"
            caps_homed = True
        else:
            payload = f"{sha} {ref}\n".encode()
        out += encode_pkt(payload)
    if saw_ref and not kept:
        # Everything hidden: advertise the standard empty-repo
        # placeholder so the client sees "no refs" rather than an error.
        out += encode_pkt(
            f"{ZERO_SHA} capabilities^{{}}".encode() + b"\x00" +
            " ".join(caps).encode() + b"\n")
    out += FLUSH_PKT
    return bytes(out), hidden


def _reencode(p: Pkt) -> bytes:
    if p.kind == DATA:
        return encode_pkt(p.payload)
    if p.kind == FLUSH:
        return FLUSH_PKT
    if p.kind == DELIM:
        return b"0001"
    return b"0002"


def filter_ls_refs(body: bytes, policy: RefPolicy,
                   identity: AgentIdentity | None) -> tuple[bytes, int]:
    """Filter a protocol-v2 ``ls-refs`` response body.

    Each data pkt is ``<sha> <ref>[ attr...]``; hidden refs drop.
    """
    out = bytearray()
    hidden = 0
    for p in iter_pkts(body):
        if p.kind != DATA:
            out += _reencode(p)
            continue
        line = p.payload.rstrip(b"\n").decode("utf-8", "replace")
        parts = line.split(" ")
        ref = parts[1] if len(parts) > 1 else ""
        base_ref = ref[:-3] if ref.endswith("^{}") else ref
        if base_ref and not policy.may_read(identity, base_ref):
            hidden += 1
            continue
        out += encode_pkt(p.payload)
    return bytes(out), hidden


def parse_receive_commands(body: bytes) -> PushRequest:
    """Parse a receive-pack request body into commands + caps + pack.

    Raises :class:`PktError` on a smuggled second command list (data
    pkt-lines after the first flush that parse as commands -- the
    classic request-smuggling shape for this protocol), hostile ref
    names are NOT rejected here (policy owns that; parsing stays
    total so every command gets a per-ref ``ng`` answer).
    """
    commands: list[RefUpdate] = []
    caps: tuple[str, ...] = ()
    offset = 0
    n = len(body)
    saw_flush = False
    # Walk pkt-lines manually so we know the byte offset of the pack.
    while offset < n:
        head = body[offset:offset + 4]
        if len(head) < 4:
            raise PktError("torn receive-pack command list")
        try:
            length = int(head, 16)
        except ValueError:
            raise PktError(f"bad pkt-line length {head!r} in "
                           "receive-pack request") from None
        if length == 0:
            offset += 4
            saw_flush = True
            break
        if length < 4 or length > 65520:
            raise PktError(f"illegal pkt-line length {length} in "
                           "receive-pack request")
        payload = body[offset + 4:offset + length]
        if len(payload) != length - 4:
            raise PktError("torn receive-pack command list")
        offset += length
        line, line_caps = _split_ref_line(payload)
        if not commands and line_caps:
            caps = line_caps
        if line.startswith(("push-cert", "shallow ", "push-option")):
            # Not ref updates; keep position, pass through on allow.
            continue
        parts = line.split(" ")
        if len(parts) != 3:
            raise PktError(f"malformed receive-pack command {line!r}")
        commands.append(RefUpdate(old_sha=parts[0], new_sha=parts[1],
                                  ref=parts[2], caps=line_caps))
    if not saw_flush and commands:
        raise PktError("receive-pack command list missing flush")
    pack = body[offset:]
    # Smuggling check: the pack section must be a packfile (or empty /
    # a push-cert trailer), never a second pkt-line command list.
    if pack and pack[:4] != b"PACK":
        try:
            trailing = list(iter_pkts(pack, tolerate_truncated=True))
        except PktError:
            trailing = []           # not pkt-lines either; let policy/git cope
        for p in trailing:
            if p.kind != DATA:
                continue
            line, _ = _split_ref_line(p.payload)
            parts = line.split(" ")
            if len(parts) == 3 and len(parts[0]) == 40 \
                    and len(parts[1]) == 40:
                raise PktError("smuggled second command list after flush")
            break
    return PushRequest(commands=tuple(commands), caps=caps, pack=pack)


def refusal_response(push: PushRequest, verdicts: list[Decision],
                     *, unpack_error: str = "") -> bytes:
    """Build the report-status body refusing (part of) a push.

    gitguard never forwards a partially-allowed push: if any command is
    denied, every command answers ``ng`` -- denied refs with their
    policy reason, innocent riders with an atomic-refusal note -- under
    ``unpack ok`` (we never saw a corrupt pack; the *commands* were
    refused).  A malformed request instead reports ``unpack error``.
    Sideband-wrapped iff the client advertised side-band(-64k).
    """
    status = bytearray()
    if unpack_error:
        status += encode_pkt(f"unpack {unpack_error}\n")
    else:
        status += encode_pkt("unpack ok\n")
    denied = {d.ref: d for d in verdicts if not d.allowed}
    for cmd in push.commands:
        d = denied.get(cmd.ref)
        if d is not None:
            status += encode_pkt(f"ng {cmd.ref} {d.reason}\n")
        elif denied:
            status += encode_pkt(
                f"ng {cmd.ref} push refused: out-of-namespace ref in "
                "same push\n")
        else:
            status += encode_pkt(f"ok {cmd.ref}\n")
    if not push.commands and unpack_error:
        status += encode_pkt(f"ng refs/ {unpack_error}\n")
    status += FLUSH_PKT
    if push.wants_sideband:
        return encode_sideband(1, bytes(status)) + FLUSH_PKT
    return bytes(status)


def error_response(message: str) -> bytes:
    """A bare ``ERR`` pkt -- the in-protocol refusal for fetch paths."""
    return encode_pkt(f"ERR {message}\n") + FLUSH_PKT


def parse_upload_pack_wants(body: bytes) -> list[str]:
    """Collect ``want`` object ids from an upload-pack request (v0+v2)."""
    wants: list[str] = []
    for p in iter_pkts(body, tolerate_truncated=True):
        if p.kind != DATA:
            continue
        line = p.payload.rstrip(b"\n").decode("utf-8", "replace")
        if line.startswith("want "):
            parts = line.split(" ")
            if len(parts) >= 2:
                wants.append(parts[1])
    return wants
