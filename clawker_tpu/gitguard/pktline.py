"""git pkt-line codec: the framing layer under every smart transport.

Every smart-HTTP body -- ref advertisements, receive-pack command
lists, report-status responses -- is a sequence of *pkt-lines*: a
4-hex-digit length prefix covering itself plus the payload, or one of
three zero-payload control packets (protocol v2 added two):

    ``0000``  flush-pkt         end of a section / message
    ``0001``  delim-pkt         v2: separates command args from body
    ``0002``  response-end-pkt  v2: end of a stateless-RPC response

The codec here is deliberately strict where git clients are strict and
tolerant where proxies must be tolerant:

- **Oversized length headers** (``> 65520``, i.e. payload over
  ``MAX_PKT_PAYLOAD``) are a protocol violation git itself refuses;
  we raise :class:`PktError` so the filter fails closed instead of
  buffering an attacker-chosen length.
- **Torn frames** (a length prefix promising more bytes than the
  buffer holds) raise :class:`TruncatedPkt` carrying how many bytes
  were cleanly consumed, so a streaming caller can keep the tail and
  retry -- tolerance for re-framing, not for corruption.
- Lengths must be lowercase/uppercase hex only; ``0003`` is reserved
  and rejected (git treats 0003 as an error, not a 0-byte line).

Nothing in this module knows about HTTP, refs, or policy: it is the
leaf the protocol filter and the tests' adversarial corpus both sit on.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator

from ..errors import ClawkerError

# A pkt-line length header covers itself (4 bytes), so the max payload
# is 0xFFFF - 4.  git caps lines at 65520 total; larger is an error.
MAX_PKT_LEN = 65520
MAX_PKT_PAYLOAD = MAX_PKT_LEN - 4

FLUSH_PKT = b"0000"
DELIM_PKT = b"0001"
RESPONSE_END_PKT = b"0002"

# Packet kinds yielded by iter_pkts.
DATA = "data"
FLUSH = "flush"
DELIM = "delim"
RESPONSE_END = "response-end"

_CONTROL = {0: FLUSH, 1: DELIM, 2: RESPONSE_END}

# Sideband channel numbers (side-band-64k capability).
SIDEBAND_DATA = 1
SIDEBAND_PROGRESS = 2
SIDEBAND_ERROR = 3


class PktError(ClawkerError):
    """Malformed pkt-line framing (bad hex, oversized length, reserved)."""


class TruncatedPkt(PktError):
    """A frame's length header promises bytes the buffer does not hold.

    ``consumed`` is the offset of the start of the torn frame: every
    byte before it parsed cleanly, so a streaming caller may keep
    ``buf[consumed:]`` and retry once more bytes arrive.
    """

    def __init__(self, message: str, consumed: int):
        super().__init__(message)
        self.consumed = consumed


@dataclass(frozen=True)
class Pkt:
    """One parsed pkt-line: a control packet or a data payload."""

    kind: str           # DATA | FLUSH | DELIM | RESPONSE_END
    payload: bytes = b""

    @property
    def text(self) -> str:
        return self.payload.decode("utf-8", "replace").rstrip("\n")


def encode_pkt(payload: bytes | str) -> bytes:
    """Frame one payload as a pkt-line (length prefix + bytes)."""
    raw = payload.encode() if isinstance(payload, str) else payload
    if len(raw) > MAX_PKT_PAYLOAD:
        raise PktError(f"pkt-line payload {len(raw)} exceeds "
                       f"{MAX_PKT_PAYLOAD} bytes")
    return f"{len(raw) + 4:04x}".encode() + raw


def iter_pkts(buf: bytes, *, tolerate_truncated: bool = False,
              ) -> Iterator[Pkt]:
    """Yield every pkt-line in ``buf``; strict by default.

    With ``tolerate_truncated`` a torn trailing frame ends iteration
    silently (proxy streaming mode); otherwise it raises
    :class:`TruncatedPkt` with the clean-consumed offset.
    """
    off = 0
    n = len(buf)
    while off < n:
        if n - off < 4:
            if tolerate_truncated:
                return
            raise TruncatedPkt(
                f"torn pkt-line length header at offset {off}", off)
        head = buf[off:off + 4]
        try:
            length = int(head, 16)
        except ValueError:
            raise PktError(
                f"bad pkt-line length header {head!r} at offset {off}"
            ) from None
        if length in _CONTROL:
            yield Pkt(_CONTROL[length])
            off += 4
            continue
        if length == 3:
            raise PktError("reserved pkt-line length 0003")
        if length < 4:
            raise PktError(f"impossible pkt-line length {length:#06x}")
        if length > MAX_PKT_LEN:
            raise PktError(
                f"oversized pkt-line length {length} (> {MAX_PKT_LEN})")
        if off + length > n:
            if tolerate_truncated:
                return
            raise TruncatedPkt(
                f"torn pkt-line at offset {off}: header promises "
                f"{length} bytes, {n - off} remain", off)
        yield Pkt(DATA, buf[off + 4:off + length])
        off += length


def encode_sideband(band: int, data: bytes) -> bytes:
    """Wrap ``data`` in side-band-64k frames on channel ``band``.

    Splits at the 64k pkt boundary minus the 1-byte channel marker so
    arbitrarily long report-status payloads stay legal.
    """
    out = bytearray()
    limit = MAX_PKT_PAYLOAD - 1
    if not data:
        return encode_pkt(bytes([band]))
    for i in range(0, len(data), limit):
        out += encode_pkt(bytes([band]) + data[i:i + limit])
    return bytes(out)


def decode_sideband(body: bytes) -> tuple[bytes, bytes, bytes]:
    """Split a sideband-framed body into (data, progress, error) streams."""
    data, progress, error = bytearray(), bytearray(), bytearray()
    for pkt in iter_pkts(body, tolerate_truncated=True):
        if pkt.kind != DATA or not pkt.payload:
            continue
        band, rest = pkt.payload[0], pkt.payload[1:]
        if band == SIDEBAND_DATA:
            data += rest
        elif band == SIDEBAND_PROGRESS:
            progress += rest
        elif band == SIDEBAND_ERROR:
            error += rest
    return bytes(data), bytes(progress), bytes(error)
