"""gitguard: a git-protocol-aware firewall proxy for worktree swarms.

PR-16's swarm-on-a-repo workload enforces branch-per-agent isolation at
the filesystem layer (one worktree + one ``{prefix}/{run}/{agent}``
branch per agent).  That containment is advisory the moment a harness
shells out to ``git push origin main``: the remote does not know about
worktree boundaries.  gitguard closes the gap at the network layer with
the same posture the firewall already applies to DNS and TLS -- deny by
default, then allow a single protocol-aware lane:

- :mod:`.pktline` -- the git pkt-line codec (v0/v2 framing, flush/delim
  packets, torn-frame and oversized-length tolerance).
- :mod:`.protocol` -- the smart-HTTP filter: rewrite ``info/refs``
  advertisements to hide refs outside the caller's namespace, parse
  ``git-receive-pack`` command lists and build git-readable refusals
  (report-status ``ng`` lines, never a bare TCP reset).
- :mod:`.refpolicy` -- agent identity (mTLS leaf / container labels) ->
  allowed ref namespace; fetch visibility; the privileged merge-queue
  identity that alone may land ``{prefix}/{run}/merged``.
- :mod:`.server` -- the proxy itself on a hardened unix socket
  (0600/0700, same pattern as loopd/workerd); Envoy's MITM chain for
  git hosts routes through it, and swarm runs deny ssh/22 and
  git/9418 so this lane is the only git path.

Fail-closed by construction: if the guard is down the Envoy cluster has
no healthy endpoint and the client sees a connection error -- a push is
refused, never silently passed through.  See docs/git-policy.md.
"""

from __future__ import annotations

from .pktline import (
    DELIM_PKT,
    FLUSH_PKT,
    MAX_PKT_PAYLOAD,
    PktError,
    RESPONSE_END_PKT,
    TruncatedPkt,
    encode_pkt,
    iter_pkts,
)
from .protocol import (
    GIT_RECEIVE_PACK,
    GIT_UPLOAD_PACK,
    filter_advertisement,
    parse_receive_commands,
    refusal_response,
)
from .refpolicy import (
    AgentIdentity,
    Decision,
    RefPolicy,
    git_egress_rules,
)
from .server import (
    FakeGitUpstream,
    GitguardServer,
    LocalRepoUpstream,
)

__all__ = [
    "FLUSH_PKT", "DELIM_PKT", "RESPONSE_END_PKT", "MAX_PKT_PAYLOAD",
    "PktError", "TruncatedPkt", "encode_pkt", "iter_pkts",
    "GIT_UPLOAD_PACK", "GIT_RECEIVE_PACK", "filter_advertisement",
    "parse_receive_commands", "refusal_response",
    "AgentIdentity", "Decision", "RefPolicy", "git_egress_rules",
    "GitguardServer", "LocalRepoUpstream", "FakeGitUpstream",
]
