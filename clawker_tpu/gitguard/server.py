"""The gitguard proxy: smart-HTTP in, policy-filtered git out.

Topology (docs/git-policy.md): an agent container's ``git push`` dials
the MITM'd git host; Envoy terminates TLS, verifies the PR-6 client
leaf, stamps ``X-Clawker-Identity``, and forwards the request over this
server's unix socket (0600 socket / 0700 dir -- the loopd/workerd
hardening pattern, so only the envoy/loopd user can reach it).  The
guard filters the advertisement, judges every receive-pack command,
and only then lets bytes touch the upstream.

Upstreams are pluggable because the two deployment lanes differ:

- :class:`LocalRepoUpstream` -- the swarm-on-a-repo lane.  The "git
  host" is the run's own seed repository on this host; stateless-RPC
  git subprocesses (``upload-pack``/``receive-pack``) serve it exactly
  the way ``git http-backend`` would.
- :class:`FakeGitUpstream` -- an in-memory ref store for the chaos
  soak and the push-overhead bench: no subprocesses, but it *records
  every acknowledged ref update*, which is precisely the evidence the
  ``ref-isolation-at-proxy`` invariant audits.

Fail-closed: the guard is the only allowed git path (ssh/22 and
git/9418 carry run-scoped deny pins), so killing this process turns
every push into a connection error at the client -- refused, never
passed through.  The chaos ``gitguard_down`` fault proves it.
"""

from __future__ import annotations

import os
import socket
import socketserver
import subprocess
import threading
import time
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler
from pathlib import Path
from typing import Callable
from urllib.parse import parse_qs, urlparse

from .. import telemetry
from ..errors import ClawkerError
from .pktline import (
    DATA,
    FLUSH_PKT,
    PktError,
    encode_pkt,
    encode_sideband,
    iter_pkts,
)
from .protocol import (
    GIT_RECEIVE_PACK,
    GIT_UPLOAD_PACK,
    SERVICES,
    PushRequest,
    error_response,
    filter_advertisement,
    parse_receive_commands,
    parse_upload_pack_wants,
    refusal_response,
)
from .refpolicy import (
    ALLOW,
    DENY,
    IDENTITY_HEADER,
    AgentIdentity,
    Decision,
    RefPolicy,
)

M_REQUESTS = telemetry.counter(
    "gitguard_requests_total",
    "smart-HTTP requests through the gitguard proxy", ("service",))
M_REFS_HIDDEN = telemetry.counter(
    "gitguard_refs_hidden_total",
    "refs hidden from advertisements by namespace policy")
M_ALLOWED = telemetry.counter(
    "gitguard_updates_allowed_total",
    "receive-pack ref updates allowed through to the upstream")
M_REFUSED = telemetry.counter(
    "gitguard_updates_refused_total",
    "receive-pack ref updates refused by policy", ("reason",))
M_DECISION_S = telemetry.histogram(
    "gitguard_decision_seconds",
    "policy decision + filter latency per request")


def reason_class(reason: str) -> str:
    """Collapse a free-text refusal reason to a bounded metric label."""
    if not reason:
        return "none"
    if "namespace" in reason:
        return "namespace"
    if "merge-queue" in reason or "integration" in reason:
        return "integration"
    if "unauthenticated" in reason:
        return "unauth"
    if "run" in reason and "match" in reason:
        return "run_mismatch"
    if "ref name" in reason or "refs/" in reason:
        return "badref"
    return "malformed"


class GitguardError(ClawkerError):
    """Proxy-side failure (upstream subprocess died, bad configuration)."""


# --------------------------------------------------------------- upstreams


class LocalRepoUpstream:
    """Serve a local repository over stateless-RPC git subprocesses.

    This is what ``git http-backend`` execs after its CGI parsing; by
    invoking ``upload-pack``/``receive-pack`` directly the guard skips
    the CGI layer (and its env-smuggling surface) entirely.
    """

    def __init__(self, repo: str | Path, *, git_bin: str = "git",
                 timeout_s: float = 30.0):
        self.repo = str(repo)
        self.git_bin = git_bin
        self.timeout_s = timeout_s

    def _run(self, args: list[str], stdin: bytes = b"") -> bytes:
        env = dict(os.environ)
        # Never let a guarded push recurse through hooks into the
        # network, and keep receive-pack quiet about its identity.
        env.setdefault("GIT_CONFIG_NOSYSTEM", "1")
        try:
            proc = subprocess.run(
                [self.git_bin, *args], input=stdin,
                capture_output=True, timeout=self.timeout_s, env=env)
        except (OSError, subprocess.TimeoutExpired) as exc:
            raise GitguardError(f"git upstream failed: {exc}") from exc
        if proc.returncode != 0 and not proc.stdout:
            raise GitguardError(
                "git upstream exited "
                f"{proc.returncode}: {proc.stderr.decode(errors='replace')}")
        return proc.stdout

    def advertise(self, service: str) -> bytes:
        sub = service.removeprefix("git-")
        body = self._run([sub, "--stateless-rpc", "--advertise-refs",
                          self.repo])
        head = encode_pkt(f"# service={service}\n") + FLUSH_PKT
        return head + body

    def call(self, service: str, body: bytes) -> bytes:
        sub = service.removeprefix("git-")
        return self._run([sub, "--stateless-rpc", self.repo], stdin=body)


@dataclass
class FakeGitUpstream:
    """In-memory git host: a ref map + an acknowledged-update log.

    ``acknowledged`` is the ground truth the chaos invariant audits: a
    tuple per ref update the upstream actually applied.  If isolation
    holds at the proxy, no cross-agent ref ever lands here.
    """

    refs: dict[str, str] = field(default_factory=dict)
    acknowledged: list[tuple[float, str, str]] = field(default_factory=list)
    #             (monotonic_ts, identity_header, ref)
    _lock: threading.Lock = field(default_factory=threading.Lock,
                                  repr=False)
    caller: str = ""            # set per-request by the server

    def advertise(self, service: str) -> bytes:
        head = encode_pkt(f"# service={service}\n") + FLUSH_PKT
        caps = ("report-status side-band-64k agent=clawker-fake"
                if service == GIT_RECEIVE_PACK
                else "side-band-64k agent=clawker-fake")
        body = bytearray()
        first = True
        with self._lock:
            items = sorted(self.refs.items())
        for ref, sha in items:
            if first:
                body += encode_pkt(f"{sha} {ref}".encode() + b"\x00" +
                                   caps.encode() + b"\n")
                first = False
            else:
                body += encode_pkt(f"{sha} {ref}\n")
        if first:
            body += encode_pkt(("0" * 40 + " capabilities^{}").encode() +
                               b"\x00" + caps.encode() + b"\n")
        body += FLUSH_PKT
        return head + bytes(body)

    def call(self, service: str, body: bytes) -> bytes:
        if service != GIT_RECEIVE_PACK:
            return error_response("fake upstream serves pushes only")
        push = parse_receive_commands(body)
        status = bytearray()
        status += encode_pkt("unpack ok\n")
        with self._lock:
            for cmd in push.commands:
                self.refs[cmd.ref] = cmd.new_sha
                self.acknowledged.append(
                    (time.monotonic(), self.caller, cmd.ref))
                status += encode_pkt(f"ok {cmd.ref}\n")
        status += FLUSH_PKT
        if push.wants_sideband:
            return encode_sideband(1, bytes(status)) + FLUSH_PKT
        return bytes(status)


# ------------------------------------------------------------------ server


class _UnixHTTPServer(socketserver.ThreadingMixIn, socketserver.TCPServer):
    address_family = socket.AF_UNIX
    allow_reuse_address = False
    daemon_threads = True

    def __init__(self, sock: socket.socket, handler):
        # The hardened, already-bound + listening socket is adopted
        # whole: bind/umask/chmod happen in GitguardServer.start so
        # the 0600 pin covers the bind itself.
        socketserver.BaseServer.__init__(self, sock.getsockname(), handler)
        self.socket = sock

    def get_request(self):
        request, _ = self.socket.accept()
        return request, ("local", 0)


class _TcpHTTPServer(socketserver.ThreadingMixIn, socketserver.TCPServer):
    allow_reuse_address = True
    daemon_threads = True


class GitguardServer:
    """The proxy server: bind, filter, judge, forward (or refuse)."""

    def __init__(self, upstream, policy: RefPolicy, *,
                 socket_path: str | Path | None = None,
                 tcp_addr: tuple[str, int] | None = None,
                 on_decision: Callable[[Decision], None] | None = None):
        if (socket_path is None) == (tcp_addr is None):
            raise GitguardError(
                "exactly one of socket_path / tcp_addr required")
        self.upstream = upstream
        self.policy = policy
        self.socket_path = Path(socket_path) if socket_path else None
        self.tcp_addr = tcp_addr
        self.on_decision = on_decision
        self._httpd: socketserver.TCPServer | None = None
        self._thread: threading.Thread | None = None

    # -- lifecycle

    def start(self) -> "GitguardServer":
        handler = _make_handler(self)
        if self.socket_path is not None:
            rt = self.socket_path.parent
            rt.mkdir(parents=True, exist_ok=True)
            os.chmod(rt, 0o700)
            if self.socket_path.exists():
                self.socket_path.unlink()
            listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            old_umask = os.umask(0o177)     # cover the bind itself
            try:
                listener.bind(str(self.socket_path))
            finally:
                os.umask(old_umask)
            os.chmod(self.socket_path, 0o600)   # umask-proof pin
            listener.listen(64)
            httpd = _UnixHTTPServer(listener, handler)
        else:
            httpd = _TcpHTTPServer(self.tcp_addr, handler)
        self._httpd = httpd
        self._thread = threading.Thread(
            target=httpd.serve_forever, kwargs={"poll_interval": 0.05},
            name="gitguard", daemon=True)
        self._thread.start()
        return self

    @property
    def port(self) -> int:
        """Bound TCP port (tests bind port 0 and read it back here)."""
        if self._httpd is None or self.tcp_addr is None:
            return 0
        return self._httpd.server_address[1]

    def close(self) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        if self.socket_path is not None:
            try:
                self.socket_path.unlink()
            except OSError:
                pass

    @property
    def running(self) -> bool:
        return self._httpd is not None

    # -- decision plumbing

    def _emit(self, d: Decision) -> None:
        if d.verdict == ALLOW:
            M_ALLOWED.labels().inc()
        else:
            M_REFUSED.labels(reason_class(d.reason)).inc()
        if self.on_decision is not None:
            try:
                self.on_decision(d)
            except Exception:
                pass        # observers never take the data plane down

    # -- request handling (called from the HTTP handler)

    def handle_info_refs(self, service: str,
                         identity: AgentIdentity | None,
                         ) -> tuple[int, str, bytes]:
        M_REQUESTS.labels(service).inc()
        t0 = time.monotonic()
        raw = self.upstream.advertise(service)
        body, hidden = filter_advertisement(
            raw, service, self.policy, identity)
        if hidden:
            M_REFS_HIDDEN.labels().inc(hidden)
        M_DECISION_S.labels().observe(time.monotonic() - t0)
        ctype = f"application/x-{service}-advertisement"
        return 200, ctype, body

    def handle_receive_pack(self, body: bytes,
                            identity: AgentIdentity | None,
                            ) -> tuple[int, str, bytes]:
        M_REQUESTS.labels(GIT_RECEIVE_PACK).inc()
        ctype = f"application/x-{GIT_RECEIVE_PACK}-result"
        t0 = time.monotonic()
        try:
            push = parse_receive_commands(body)
        except PktError as exc:
            d = Decision(DENY, f"malformed push: {exc}",
                         service=GIT_RECEIVE_PACK,
                         agent=identity.agent if identity else "",
                         run=self.policy.run)
            self._emit(d)
            M_DECISION_S.labels().observe(time.monotonic() - t0)
            empty = PushRequest(commands=(), caps=(), pack=b"")
            return 200, ctype, refusal_response(
                empty, [d], unpack_error=f"error {exc}")
        verdicts = [self.policy.may_update(identity, cmd.ref)
                    for cmd in push.commands]
        for d in verdicts:
            self._emit(d)
        M_DECISION_S.labels().observe(time.monotonic() - t0)
        if any(not d.allowed for d in verdicts) or not push.commands:
            return 200, ctype, refusal_response(push, verdicts)
        if hasattr(self.upstream, "caller"):
            self.upstream.caller = identity.header_value() if identity \
                else ""
        return 200, ctype, self.upstream.call(GIT_RECEIVE_PACK, body)

    def handle_upload_pack(self, body: bytes,
                           identity: AgentIdentity | None,
                           ) -> tuple[int, str, bytes]:
        M_REQUESTS.labels(GIT_UPLOAD_PACK).inc()
        ctype = f"application/x-{GIT_UPLOAD_PACK}-result"
        t0 = time.monotonic()
        wants = parse_upload_pack_wants(body)
        visible = self._visible_shas(identity)
        hidden_wants = [w for w in wants if visible is not None
                        and w not in visible]
        M_DECISION_S.labels().observe(time.monotonic() - t0)
        if hidden_wants:
            d = Decision(DENY, "want of a hidden ref refused",
                         service=GIT_UPLOAD_PACK, ref=hidden_wants[0],
                         agent=identity.agent if identity else "",
                         run=self.policy.run)
            self._emit(d)
            return 200, ctype, error_response(
                "upload-pack: not our ref " + hidden_wants[0])
        return 200, ctype, self.upstream.call(GIT_UPLOAD_PACK, body)

    def _visible_shas(self, identity: AgentIdentity | None,
                      ) -> set[str] | None:
        """Tip shas the caller may want.  None = cannot determine (then
        depth/tag wants would false-positive, so we do not block)."""
        try:
            raw = self.upstream.advertise(GIT_UPLOAD_PACK)
        except Exception:
            return None
        visible: set[str] = set()
        for p in iter_pkts(raw, tolerate_truncated=True):
            if p.kind != DATA or p.payload.startswith(b"# service="):
                continue
            line = p.payload.split(b"\x00", 1)[0].decode(
                "utf-8", "replace").rstrip("\n")
            parts = line.split(" ", 1)
            if len(parts) != 2:
                continue
            sha, ref = parts
            base_ref = ref[:-3] if ref.endswith("^{}") else ref
            if self.policy.may_read(identity, base_ref):
                visible.add(sha)
        return visible


def _make_handler(guard: GitguardServer):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        server_version = "gitguard"

        def address_string(self):   # unix sockets have no peer addr
            return "local"

        def log_message(self, fmt, *args):
            pass

        def _identity(self) -> AgentIdentity | None:
            # Duplicate identity headers are a smuggling shape (a
            # client-supplied header riding alongside Envoy's): treat
            # conflicting values as no identity at all -- fail closed.
            values = {v.strip() for v in
                      (self.headers.get_all(IDENTITY_HEADER) or [])}
            if len(values) != 1:
                return None
            return AgentIdentity.from_header(next(iter(values)))

        def _read_body(self) -> bytes:
            if (self.headers.get("Transfer-Encoding", "")
                    .lower() == "chunked"):
                chunks = bytearray()
                while True:
                    size_line = self.rfile.readline(64).strip()
                    try:
                        size = int(size_line.split(b";")[0], 16)
                    except ValueError:
                        break
                    if size == 0:
                        self.rfile.readline(8)      # trailing CRLF
                        break
                    chunks += self.rfile.read(size)
                    self.rfile.readline(8)          # chunk CRLF
                return bytes(chunks)
            length = int(self.headers.get("Content-Length", "0") or 0)
            return self.rfile.read(length) if length else b""

        def _respond(self, code: int, ctype: str, body: bytes) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.send_header("Cache-Control", "no-cache")
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            parsed = urlparse(self.path)
            if not parsed.path.endswith("/info/refs"):
                self._respond(404, "text/plain", b"not found\n")
                return
            service = (parse_qs(parsed.query).get("service") or [""])[0]
            if service not in SERVICES:
                # dumb-protocol fallback is an unfiltered lane: refuse.
                self._respond(403, "text/plain",
                              b"smart protocol required\n")
                return
            try:
                code, ctype, body = guard.handle_info_refs(
                    service, self._identity())
            except (PktError, GitguardError) as exc:
                self._respond(502, "text/plain",
                              f"gitguard: {exc}\n".encode())
                return
            self._respond(code, ctype, body)

        def do_POST(self):
            parsed = urlparse(self.path)
            body = self._read_body()
            identity = self._identity()
            try:
                if parsed.path.endswith("/" + GIT_RECEIVE_PACK):
                    code, ctype, out = guard.handle_receive_pack(
                        body, identity)
                elif parsed.path.endswith("/" + GIT_UPLOAD_PACK):
                    code, ctype, out = guard.handle_upload_pack(
                        body, identity)
                else:
                    self._respond(404, "text/plain", b"not found\n")
                    return
            except (PktError, GitguardError) as exc:
                self._respond(502, "text/plain",
                              f"gitguard: {exc}\n".encode())
                return
            self._respond(code, ctype, out)

    return Handler
