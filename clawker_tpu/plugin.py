"""Agent-skills plugin management across host harnesses.

The clawker-support plugin is a directory of skills (each skill a
directory with a SKILL.md).  The claude harness consumes skills from
``${CLAUDE_CONFIG_DIR:-~/.claude}/skills``; other harnesses declare
their own native skills directory.  ``install`` copies a plugin
source's skills into the harness skills dir, ``remove`` deletes exactly
the skills that source provides, ``show`` prints the manual commands.

Zero-egress adaptation of the reference lanes: the reference fetches
the marketplace over git (plugin/shared/copy.go FetchPluginSkills);
here the source is a local directory (an installed bundle, a checkout
of the marketplace, or any skills tree).  The traversal guard is the
same contract (ErrSourceTraversal): a skill name that escapes the
skills dir is refused.

Reference: internal/cmd/plugin (install/show/remove, shared/copy.go).
"""

from __future__ import annotations

import os
import shutil
from dataclasses import dataclass
from pathlib import Path

from .containerfs import expand_host_path
from .errors import ClawkerError

# harness -> native skills directory (host side)
HARNESS_SKILLS_DIRS = {
    "claude": "${CLAUDE_CONFIG_DIR:-~/.claude}/skills",
    "codex": "${CODEX_HOME:-~/.codex}/skills",
}


class PluginError(ClawkerError):
    pass


@dataclass
class Skill:
    name: str
    path: Path
    description: str = ""


def skills_dir(harness: str) -> Path:
    spec = HARNESS_SKILLS_DIRS.get(harness)
    if spec is None:
        raise PluginError(
            f"harness {harness!r} has no skills lane (want one of "
            f"{sorted(HARNESS_SKILLS_DIRS)})")
    return Path(expand_host_path(spec))


def discover_skills(source: Path) -> list[Skill]:
    """Skills in a plugin source: every dir holding a SKILL.md (either
    at the source root or under a ``skills/`` subdir)."""
    source = Path(source)
    roots = [source / "skills", source]
    for root in roots:
        if not root.is_dir():
            continue
        found = []
        for entry in sorted(root.iterdir()):
            if entry.is_dir() and (entry / "SKILL.md").is_file():
                head = (entry / "SKILL.md").read_text(
                    encoding="utf-8", errors="replace").strip().splitlines()
                desc = head[0].lstrip("# ").strip() if head else ""
                found.append(Skill(name=entry.name, path=entry,
                                   description=desc))
        if found:
            return found
    return []


def _guard(dest_root: Path, name: str) -> Path:
    """The traversal guard: a skill name must resolve INSIDE the skills
    dir (reference ErrSourceTraversal)."""
    dest = (dest_root / name).resolve()
    if dest_root.resolve() not in dest.parents:
        raise PluginError(
            f"skill name {name!r} escapes the skills directory")
    return dest


def install(source: Path, *, harness: str = "claude") -> list[str]:
    skills = discover_skills(source)
    if not skills:
        raise PluginError(f"{source}: no skills found (dirs with SKILL.md)")
    dest_root = skills_dir(harness)
    dest_root.mkdir(parents=True, exist_ok=True)
    installed = []
    for skill in skills:
        dest = _guard(dest_root, skill.name)
        if skill.path.is_symlink():
            # a skill dir that IS a symlink would dereference into an
            # arbitrary host tree -- same exfil path as in-tree links;
            # skip it rather than fail the whole plugin
            continue
        src = skill.path.resolve()
        if src == dest or dest in src.parents or src == dest_root.resolve():
            # installing the skills dir onto itself would rmtree the
            # source before copying it -- permanent skill loss
            raise PluginError(
                f"source {skill.path} is already inside the {harness} "
                "skills directory; nothing to install")
        if dest.exists():
            shutil.rmtree(dest)
        # never dereference symlinks in a third-party tree: a link to
        # ~/.ssh/id_rsa would copy the credential INTO the skills dir,
        # from where harness-config staging can carry it into agent
        # containers (same refusal as containerfs._copy_tree)
        shutil.copytree(src, dest, ignore=_ignore_git_and_symlinks)
        installed.append(skill.name)
    return installed


def _ignore_git_and_symlinks(dirpath: str, names: list[str]) -> set[str]:
    skip = {n for n in names if n == ".git"}
    skip |= {n for n in names
             if os.path.islink(os.path.join(dirpath, n))}
    return skip


def remove(source: Path, *, harness: str = "claude") -> list[str]:
    """Delete exactly the skills the source provides (enumerate first,
    like the reference's fetch-to-enumerate remove lane)."""
    skills = discover_skills(source)
    if not skills:
        raise PluginError(f"{source}: no skills found to enumerate removal")
    dest_root = skills_dir(harness)
    removed = []
    for skill in skills:
        dest = _guard(dest_root, skill.name)
        if dest.is_dir():
            shutil.rmtree(dest)
            removed.append(skill.name)
    return removed


def show(harness: str = "claude") -> str:
    """Manual install commands per harness (reference show lane)."""
    if harness == "claude":
        return ("claude plugin marketplace add <marketplace>\n"
                "claude plugin install clawker-support")
    return (f"copy each skill directory into {HARNESS_SKILLS_DIRS.get(harness, '?')}"
            f" (clawker plugin install --source <dir> --harness {harness})")
