"""Stable id / hash helpers."""

from __future__ import annotations

import hashlib
import secrets


def short_id(n: int = 12) -> str:
    """Random hex id (container-id style)."""
    return secrets.token_hex((n + 1) // 2)[:n]


def content_sha(data: bytes) -> str:
    """Content-derived cache key (reference: controlplane/manager content-SHA
    CP image tag ``clawker-controlplane:bin-<sha>``)."""
    return hashlib.sha256(data).hexdigest()[:16]


def domain_hash(domain: str) -> int:
    """64-bit FNV-1a over the lowercase domain.

    Mirrors the kernel-side hashing contract: the DNS plugin writes
    ``ip -> {domain_hash, ttl}`` into the dns_cache map and the route map is
    keyed by ``{domain_hash, dst_port}`` (reference: bpf/common.h dns_cache /
    route_map; internal/dnsbpf bpfmap.go:29-51).  Python and the C eBPF
    source (native/ebpf) must agree on this exact function.
    """
    try:
        encoded = domain.lower().encode("idna")
    except UnicodeError:
        # not a valid IDN label set (e.g. wildcard patterns): hash raw UTF-8
        encoded = domain.lower().encode("utf-8")
    h = 0xCBF29CE484222325
    for b in encoded:
        h ^= b
        h = (h * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h
