"""Dotenv parsing: ``--env-file`` support for agent containers.

Semantics (reference: internal/dotenv, a godotenv derivative -- behavior
re-derived, not translated):

- ``KEY=VALUE`` lines; optional ``export `` prefix; ``#`` comments
  (full-line, or trailing after an unquoted value).
- Double-quoted values process ``\\n``/``\\t``/``\\"``/``\\\\`` escapes
  and expand variables; single-quoted values are literal; unquoted
  values are trimmed and expanded.
- ``$VAR`` / ``${VAR}`` expansion resolves earlier keys in the same
  file first, then the lookup function (default: process env); unknown
  variables expand to "" (godotenv behavior).
"""

from __future__ import annotations

import os
import re
from pathlib import Path
from typing import Callable

from ..errors import ClawkerError

_LINE = re.compile(
    r"""^\s*(?:export\s+)?(?P<key>[A-Za-z_][A-Za-z0-9_.]*)\s*=\s*(?P<rest>.*)$""")
_VAR = re.compile(r"\$(?:\{(?P<braced>[A-Za-z_][A-Za-z0-9_]*)\}"
                  r"|(?P<bare>[A-Za-z_][A-Za-z0-9_]*))")

_ESCAPES = {"n": "\n", "t": "\t", "r": "\r", '"': '"', "\\": "\\", "$": "$"}


class DotenvError(ClawkerError):
    pass


def _expand(value: str, env: dict[str, str],
            lookup: Callable[[str], str | None]) -> str:
    def sub(m: re.Match) -> str:
        name = m.group("braced") or m.group("bare")
        if name in env:
            return env[name]
        got = lookup(name)
        return got if got is not None else ""
    return _VAR.sub(sub, value)


def _unescape(value: str) -> str:
    out = []
    i = 0
    while i < len(value):
        ch = value[i]
        if ch == "\\" and i + 1 < len(value):
            out.append(_ESCAPES.get(value[i + 1], "\\" + value[i + 1]))
            i += 2
        else:
            out.append(ch)
            i += 1
    return "".join(out)


def parse(text: str, *, lookup: Callable[[str], str | None] | None = None,
          source: str = "<dotenv>") -> dict[str, str]:
    lookup = lookup if lookup is not None else os.environ.get
    out: dict[str, str] = {}
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        m = _LINE.match(raw)
        if m is None:
            raise DotenvError(f"{source}:{lineno}: not KEY=VALUE: {line!r}")
        key, rest = m.group("key"), m.group("rest").strip()
        if rest.startswith('"'):
            end = _closing_quote(rest, '"')
            if end < 0:
                raise DotenvError(f"{source}:{lineno}: unterminated double quote")
            # \$ must survive as a literal dollar: protect it BEFORE
            # anything else or pa\$\$wd would expand the unescaped "$wd".
            # Escapes are processed on the LITERAL source text, and only
            # THEN variables expand -- godotenv order: a referenced var
            # whose value contains a literal backslash sequence (e.g.
            # "\\n") must come through verbatim, not escape-processed.
            inner = rest[1:end].replace("\\$", "\x00")
            value = _expand(_unescape(inner), out, lookup).replace("\x00", "$")
        elif rest.startswith("'"):
            end = rest.find("'", 1)
            if end < 0:
                raise DotenvError(f"{source}:{lineno}: unterminated single quote")
            value = rest[1:end]          # literal: no escapes, no expansion
        else:
            # unquoted: strip trailing comment, then expand
            hash_pos = rest.find(" #")
            if rest.startswith("#"):
                rest = ""
            elif hash_pos >= 0:
                rest = rest[:hash_pos]
            value = _expand(rest.strip(), out, lookup)
        out[key] = value
    return out


def _closing_quote(s: str, q: str) -> int:
    i = 1
    while i < len(s):
        if s[i] == "\\":
            i += 2
            continue
        if s[i] == q:
            return i
        i += 1
    return -1


def parse_file(path: str | Path, *,
               lookup: Callable[[str], str | None] | None = None) -> dict[str, str]:
    p = Path(path)
    try:
        text = p.read_text(encoding="utf-8")
    except OSError as e:
        raise DotenvError(f"env file {p}: {e}") from None
    return parse(text, lookup=lookup, source=str(p))
