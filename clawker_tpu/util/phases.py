"""Process-local phase stopwatch: cold-start attribution for bench.py.

Disabled by default and free when off (one truthiness check per phase).
bench.py enables it around each measured `clawker run` and reads the
per-stage totals, so BENCH_r{N}.json can say WHERE the milliseconds
went (config load / mounts / engine create / harness seed / identity
bootstrap / pre-start / engine start / post-start) instead of only the
headline p50 -- the round-4 verdict's "creep with no owner" gap.

Not a tracing system: for spans shipped to the collector use
controlplane/otel.py.  This is a single-process accumulator with zero
dependencies, safe to call from any layer.
"""

from __future__ import annotations

import contextlib
import time

_enabled = False
_totals: dict[str, float] = {}
_counts: dict[str, int] = {}


def enable() -> None:
    global _enabled
    _enabled = True
    _totals.clear()
    _counts.clear()


def disable() -> dict[str, float]:
    """Stop recording; returns {phase: total_seconds}."""
    global _enabled
    _enabled = False
    return dict(_totals)


def totals() -> dict[str, float]:
    return dict(_totals)


def counts() -> dict[str, int]:
    return dict(_counts)


@contextlib.contextmanager
def phase(name: str):
    if not _enabled:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        _totals[name] = _totals.get(name, 0.0) + dt
        _counts[name] = _counts.get(name, 0) + 1
