"""Process-local phase stopwatch: cold-start attribution for bench.py.

Disabled by default and free when off (one truthiness check per phase).
bench.py enables it around each measured `clawker run` and reads the
per-stage totals, so BENCH_r{N}.json can say WHERE the milliseconds
went (config load / mounts / engine create / harness seed / identity
bootstrap / pre-start / engine start / post-start) instead of only the
headline p50 -- the round-4 verdict's "creep with no owner" gap.

Not a tracing system: for spans shipped to the collector use
controlplane/otel.py.  This is a single-process accumulator with zero
dependencies, safe to call from any layer -- including concurrently:
the loop scheduler drives orchestrator create/start on per-worker
threads, so the accumulation (a read-modify-write) rides a lock.
"""

from __future__ import annotations

import contextlib
import threading
import time

_enabled = False
_totals: dict[str, float] = {}
_counts: dict[str, int] = {}
_mutex = threading.Lock()


def enable() -> None:
    global _enabled
    with _mutex:
        _enabled = True
        _totals.clear()
        _counts.clear()


def disable() -> dict[str, float]:
    """Stop recording; returns {phase: total_seconds}."""
    global _enabled
    with _mutex:
        _enabled = False
        return dict(_totals)


def totals() -> dict[str, float]:
    with _mutex:
        return dict(_totals)


def counts() -> dict[str, int]:
    with _mutex:
        return dict(_counts)


def incr(name: str, n: int = 1) -> None:
    """Count-only marker for discrete occurrences (breaker transitions,
    dial retries): shows up in :func:`counts` with no duration half.
    Same contract as :func:`phase` -- free when recording is off."""
    if not _enabled:
        return
    with _mutex:
        _counts[name] = _counts.get(name, 0) + n


@contextlib.contextmanager
def phase(name: str):
    if not _enabled:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        with _mutex:
            _totals[name] = _totals.get(name, 0.0) + dt
            _counts[name] = _counts.get(name, 0) + 1
