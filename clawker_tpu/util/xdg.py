"""XDG base-directory resolution with CLAWKER_TPU_*_DIR overrides.

Parity reference: internal/config path accessors + internal/storage
ValidateDirectories XDG collision check (internal/clawker/cmd.go:31 Main).
"""

from __future__ import annotations

import os
from pathlib import Path

from .. import consts


def _base(env_override: str, xdg_var: str, fallback: str) -> Path:
    if v := os.environ.get(env_override):
        return Path(v)
    if v := os.environ.get(xdg_var):
        return Path(v) / consts.PRODUCT
    return Path.home() / fallback / consts.PRODUCT


def config_dir() -> Path:
    return _base(consts.ENV_CONFIG_DIR, "XDG_CONFIG_HOME", ".config")


def data_dir() -> Path:
    return _base(consts.ENV_DATA_DIR, "XDG_DATA_HOME", ".local/share")


def state_dir() -> Path:
    return _base(consts.ENV_STATE_DIR, "XDG_STATE_HOME", ".local/state")


def cache_dir() -> Path:
    return _base(consts.ENV_CACHE_DIR, "XDG_CACHE_HOME", ".cache")


def validate_directories() -> list[str]:
    """Detect distinct logical dirs resolving to the same physical path.

    Returns human-readable collision warnings (reference: storage
    ValidateDirectories called at CLI start, internal/clawker/cmd.go).
    """
    dirs = {
        "config": config_dir(),
        "data": data_dir(),
        "state": state_dir(),
        "cache": cache_dir(),
    }
    seen: dict[Path, str] = {}
    problems: list[str] = []
    for name, p in dirs.items():
        rp = p.resolve() if p.exists() else p
        if rp in seen:
            problems.append(f"{name} dir and {seen[rp]} dir both resolve to {rp}")
        else:
            seen[rp] = name
    return problems
