"""Filesystem primitives: atomic writes, advisory locks, safe tree ops.

Parity reference: internal/storage atomic temp+rename write path and flock
discipline (SURVEY.md 2.5).
"""

from __future__ import annotations

import contextlib
import errno
import fcntl
import os
import tempfile
from pathlib import Path
from typing import Iterator


def atomic_write(path: Path | str, data: bytes | str, mode: int = 0o644) -> None:
    """Write ``data`` to ``path`` atomically (temp file + fsync + rename).

    Readers never observe a partially written file; on crash the old content
    survives intact.
    """
    path = Path(path)
    if isinstance(data, str):
        data = data.encode("utf-8")
    path.parent.mkdir(parents=True, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=str(path.parent), prefix=f".{path.name}.", suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.chmod(tmp, mode)
        os.replace(tmp, path)
    except BaseException:
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise


@contextlib.contextmanager
def file_lock(path: Path | str, *, shared: bool = False, timeout_s: float | None = None) -> Iterator[None]:
    """Advisory flock on a sidecar ``<path>.lock`` file.

    Exclusive by default; ``shared=True`` takes a read lock.  ``timeout_s``
    bounds the wait (polling, since flock has no native timeout).
    """
    import time

    lock_path = Path(str(path) + ".lock")
    lock_path.parent.mkdir(parents=True, exist_ok=True)
    fd = os.open(lock_path, os.O_CREAT | os.O_RDWR, 0o644)
    op = fcntl.LOCK_SH if shared else fcntl.LOCK_EX
    try:
        if timeout_s is None:
            fcntl.flock(fd, op)
        else:
            deadline = time.monotonic() + timeout_s
            while True:
                try:
                    fcntl.flock(fd, op | fcntl.LOCK_NB)
                    break
                except OSError as e:
                    if e.errno not in (errno.EACCES, errno.EAGAIN):
                        raise
                    if time.monotonic() >= deadline:
                        raise TimeoutError(f"lock {lock_path} busy after {timeout_s}s") from e
                    time.sleep(0.02)
        yield
    finally:
        with contextlib.suppress(OSError):
            fcntl.flock(fd, fcntl.LOCK_UN)
        os.close(fd)


def ensure_dir(path: Path | str, mode: int = 0o755) -> Path:
    p = Path(path)
    p.mkdir(parents=True, exist_ok=True)
    with contextlib.suppress(OSError):
        p.chmod(mode)
    return p


def is_within(root: Path, candidate: Path) -> bool:
    """True if ``candidate`` resolves inside ``root`` (symlink-safe containment).

    Used by the bundle install pipeline to reject symlink escapes
    (reference: internal/bundle install.go symlink-safe install).
    """
    try:
        candidate.resolve().relative_to(root.resolve())
        return True
    except ValueError:
        return False
