"""Small leaf utilities: filesystem, XDG paths, text, ids."""
