"""Text helpers: name validation, truncation, table-ish formatting.

Parity reference: internal/text (SURVEY.md 2, foundation layer).
"""

from __future__ import annotations

import re

# Project and agent names share Docker-compatible constraints: they embed into
# container names `clawker.<project>.<agent>` and image names
# `clawker-<project>:<tag>` (reference: internal/docker/names.go).
_NAME_RE = re.compile(r"^[a-z0-9][a-z0-9_-]{0,62}$")


def valid_name(name: str) -> bool:
    return bool(_NAME_RE.match(name))


def validate_name(kind: str, name: str) -> str:
    if not valid_name(name):
        raise ValueError(
            f"invalid {kind} name {name!r}: must match [a-z0-9][a-z0-9_-]*, max 63 chars"
        )
    return name


def truncate(s: str, n: int) -> str:
    return s if len(s) <= n else s[: max(0, n - 1)] + "…"


def humanize_bytes(n: int) -> str:
    f = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if f < 1024 or unit == "TiB":
            return f"{f:.1f}{unit}" if unit != "B" else f"{int(f)}B"
        f /= 1024
    return f"{n}B"


def humanize_duration(seconds: float) -> str:
    s = int(seconds)
    if s < 60:
        return f"{s}s"
    if s < 3600:
        return f"{s // 60}m{s % 60}s"
    return f"{s // 3600}h{(s % 3600) // 60}m"
