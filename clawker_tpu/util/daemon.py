"""Shared host-daemon lifecycle: spawn, healthz-grounded liveness, stop.

One state machine for every host-side daemon (control plane, host
proxy): liveness is grounded in an HTTP /healthz probe, never the
pidfile; a stale pidfile never blocks bring-up; a wedged process (pid
alive, healthz dead) is terminated -- SIGTERM, bounded wait, SIGKILL --
before a replacement spawns, so the listen port is actually free; a
spawn that times out is torn down the same way so the next attempt
doesn't inherit a half-alive process.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path
from urllib import error as urlerror
from urllib import request as urlrequest

from ..errors import ClawkerError


class DaemonError(ClawkerError):
    pass


# positive health verdicts, keyed by health_url: the create hot path
# probes the host proxy before every agent start, and a live daemon does
# not need re-proving every few milliseconds.  Only positives are cached
# -- a dead daemon must be re-probed so ensure_running can spawn it.
_HEALTH_CACHE: dict[str, tuple[float, dict]] = {}


def invalidate_health_cache(url: str | None = None) -> None:
    if url is None:
        _HEALTH_CACHE.clear()
    else:
        _HEALTH_CACHE.pop(url, None)


class DaemonSpec:
    def __init__(self, *, name: str, module: str, pidfile: Path, logfile: Path,
                 health_url: str, start_deadline_s: float = 15.0):
        self.name = name
        self.module = module
        self.pidfile = pidfile
        self.logfile = logfile
        self.health_url = health_url
        self.start_deadline_s = start_deadline_s

    # ------------------------------------------------------------ probes

    def health(self, timeout: float = 2.0, *,
               cache_ttl_s: float = 0.0) -> dict | None:
        """The health body, or None when nothing answers.  A 503 is a
        live-but-degraded daemon: the body still comes back so callers
        can see which subsystem is down, instead of kill/respawn loops.

        ``cache_ttl_s`` > 0 reuses a recent POSITIVE verdict for this
        url (hot create paths); negatives always re-probe."""
        if cache_ttl_s > 0:
            hit = _HEALTH_CACHE.get(self.health_url)
            if hit is not None and time.monotonic() - hit[0] < cache_ttl_s:
                return hit[1]
        out: dict | None
        try:
            with urlrequest.urlopen(self.health_url, timeout=timeout) as r:
                out = json.loads(r.read() or b"{}")
        except urlerror.HTTPError as e:
            try:
                out = json.loads(e.read() or b"{}")
            except (OSError, json.JSONDecodeError):
                out = {"degraded": True}
        except (urlerror.URLError, OSError, json.JSONDecodeError):
            out = None
        if out is not None:
            _HEALTH_CACHE[self.health_url] = (time.monotonic(), out)
        else:
            _HEALTH_CACHE.pop(self.health_url, None)
        return out

    def running(self, *, cache_ttl_s: float = 0.0) -> bool:
        return self.health(cache_ttl_s=cache_ttl_s) is not None

    def _read_pid(self) -> int:
        try:
            return int(self.pidfile.read_text().strip())
        except (OSError, ValueError):
            return 0

    @staticmethod
    def _pid_alive(pid: int) -> bool:
        if pid <= 0:
            return False
        try:
            os.kill(pid, 0)
            return True
        except OSError:
            return False

    @staticmethod
    def _terminate(pid: int, grace_s: float = 5.0) -> None:
        """SIGTERM, bounded wait, SIGKILL -- the port must actually free."""
        try:
            os.kill(pid, signal.SIGTERM)
        except OSError:
            return
        deadline = time.monotonic() + grace_s
        while time.monotonic() < deadline:
            if not DaemonSpec._pid_alive(pid):
                return
            time.sleep(0.1)
        try:
            os.kill(pid, signal.SIGKILL)
        except OSError:
            pass

    # --------------------------------------------------------- lifecycle

    def ensure_running(self, *, env: dict | None = None, log=None,
                       probe_ttl_s: float = 0.0) -> None:
        if self.running(cache_ttl_s=probe_ttl_s):
            return
        pid = self._read_pid()
        if self._pid_alive(pid):
            if log:
                log.warning("%s pid %d alive but healthz dead; replacing",
                            self.name, pid)
            self._terminate(pid)
        self.logfile.parent.mkdir(parents=True, exist_ok=True)
        self.pidfile.parent.mkdir(parents=True, exist_ok=True)
        with open(self.logfile, "ab") as logf:
            proc = subprocess.Popen(
                [sys.executable, "-m", self.module],
                stdout=logf, stderr=subprocess.STDOUT, stdin=subprocess.DEVNULL,
                start_new_session=True,     # survive the CLI process
                env=env if env is not None else os.environ.copy(),
            )
        self.pidfile.write_text(str(proc.pid))
        deadline = time.monotonic() + self.start_deadline_s
        while time.monotonic() < deadline:
            if self.running():
                if log:
                    log.info("%s up (pid %d)", self.name, proc.pid)
                return
            if proc.poll() is not None:
                self.pidfile.unlink(missing_ok=True)
                raise DaemonError(
                    f"{self.name} exited during start (rc={proc.returncode}); "
                    f"see {self.logfile}"
                )
            time.sleep(0.2)
        # half-alive spawn: tear it down so the next attempt starts clean
        self._terminate(proc.pid)
        self.pidfile.unlink(missing_ok=True)
        raise DaemonError(
            f"{self.name} did not become healthy within "
            f"{self.start_deadline_s:.0f}s; see {self.logfile}"
        )

    def stop(self) -> bool:
        pid = self._read_pid()
        was = self._pid_alive(pid)
        if was:
            self._terminate(pid)
        self.pidfile.unlink(missing_ok=True)
        # the daemon is gone: a cached positive verdict would make the
        # next ensure_running(probe_ttl_s=...) skip the respawn
        invalidate_health_cache(self.health_url)
        return was
