"""Config facade: layered stores for project + settings, path accessors,
egress-rule composition.

Parity reference: internal/config Config interface over Store[Project] +
Store[Settings] with ~40 path accessors and EgressRules() merging required
internal rules with project rules (SURVEY.md 2.5).
"""

from __future__ import annotations

import functools
import re
from dataclasses import dataclass
from pathlib import Path

from .. import consts
from ..util import text, xdg
from ..storage import Layer, Store, discover_project_layers
from .schema import EgressRule, ProjectConfig, Settings, from_dict


def settings_store(config_dir: Path | None = None) -> Store[Settings]:
    base = config_dir or xdg.config_dir()
    layers = [Layer("settings", base / consts.SETTINGS_FILE)]
    return Store(
        layers,
        schema_factory=functools.partial(from_dict, Settings),
        strategies=Settings.merge_strategies(),
    )


def project_store(start: Path | str | None = None) -> Store[ProjectConfig] | None:
    disc = discover_project_layers(start or Path.cwd())
    if disc is None:
        return None
    store: Store[ProjectConfig] = Store(
        disc.layers,
        schema_factory=functools.partial(from_dict, ProjectConfig),
        strategies=ProjectConfig.merge_strategies(),
    )
    store.project_root = disc.root  # type: ignore[attr-defined]
    return store


@dataclass
class Config:
    """Resolved configuration for one CLI invocation."""

    settings: Settings
    project: ProjectConfig | None
    project_root: Path | None
    settings_store_ref: Store[Settings]
    project_store_ref: Store[ProjectConfig] | None

    # ------------------------------------------------------------ paths

    @property
    def data_dir(self) -> Path:
        return xdg.data_dir()

    @property
    def state_dir(self) -> Path:
        return xdg.state_dir()

    @property
    def cache_dir(self) -> Path:
        return xdg.cache_dir()

    @property
    def registry_path(self) -> Path:
        return self.data_dir / consts.REGISTRY_FILE

    @property
    def worktrees_dir(self) -> Path:
        return self.data_dir / "worktrees"

    @property
    def bundles_dir(self) -> Path:
        return self.data_dir / "bundles"

    @property
    def pki_dir(self) -> Path:
        return self.data_dir / "pki"

    @property
    def egress_rules_path(self) -> Path:
        return self.data_dir / consts.EGRESS_RULES_FILE

    @property
    def ssh_mux_dir(self) -> Path:
        return self.state_dir / consts.TPU_SSH_MUX_DIR

    @property
    def logs_dir(self) -> Path:
        return self.state_dir / "logs"

    # ------------------------------------------------------------ domain

    def project_name(self) -> str:
        if self.project and self.project.project:
            return text.validate_name("project", self.project.project)
        if self.project_root is not None:
            # sanitize the directory name into the container-name charset
            raw = self.project_root.name.lower()
            name = re.sub(r"[^a-z0-9_-]+", "-", raw).strip("-_") or "project"
            return text.validate_name("project", name)
        raise LookupError("no project configured here (run `clawker init`)")

    def egress_rules(self) -> list[EgressRule]:
        """Required internal rules + project rules, deduped by rule key.

        Reference: internal/config EgressRules() (SURVEY.md 2.5) -- the
        harness always needs its API endpoints even when the project allows
        nothing else.
        """
        rules: dict[str, EgressRule] = {}
        for dom in consts.REQUIRED_EGRESS_DOMAINS:
            r = EgressRule(dst=dom, proto="https")
            rules[r.key()] = r
        if self.project:
            for r in self.project.security.egress:
                rules.setdefault(r.key(), r)
        return list(rules.values())


def load_config(start: Path | str | None = None) -> Config:
    sstore = settings_store()
    pstore = project_store(start)
    return Config(
        settings=sstore.typed(),
        project=pstore.typed() if pstore else None,
        project_root=getattr(pstore, "project_root", None) if pstore else None,
        settings_store_ref=sstore,
        project_store_ref=pstore,
    )
