"""Configuration subsystem: project + settings schemas over the layered store.

Parity reference: internal/config (SURVEY.md 2.5) -- Config facade over
Store[Project] + Store[Settings], path accessors, EgressRules() composition.
"""

from .schema import (
    AgentConfig,
    BuildConfig,
    EgressRule,
    ProjectConfig,
    SecurityConfig,
    Settings,
    WorkspaceConfig,
    TPUSettings,
    FirewallSettings,
    ControlPlaneSettings,
    MonitoringSettings,
    LoggingSettings,
    HostProxySettings,
    LoopSettings,
    RuntimeSettings,
)
from .config import Config, load_config, project_store, settings_store

__all__ = [
    "AgentConfig",
    "BuildConfig",
    "Config",
    "ControlPlaneSettings",
    "EgressRule",
    "FirewallSettings",
    "HostProxySettings",
    "LoggingSettings",
    "LoopSettings",
    "MonitoringSettings",
    "ProjectConfig",
    "RuntimeSettings",
    "SecurityConfig",
    "Settings",
    "TPUSettings",
    "WorkspaceConfig",
    "load_config",
    "project_store",
    "settings_store",
]
