"""Typed configuration schemas: ``clawker.yaml`` (project) and ``settings.yaml``.

Parity reference: internal/config schemas (SURVEY.md 2.5): project =
build/agent/workspace/security; settings = logging, host_proxy,
firewall.enable, monitoring, control_plane ports.  This build adds the
``runtime`` settings block (driver selection + TPU-pod description) and the
``loop`` block for the autonomous-loop scheduler -- both net-new per
BASELINE.json north_star.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field, fields, is_dataclass
from functools import lru_cache
from typing import Any, get_args, get_origin, get_type_hints


@lru_cache(maxsize=None)
def _hints(cls) -> dict:
    """get_type_hints per dataclass, cached: the schema classes are
    static, and hint resolution dominated config-load time."""
    return get_type_hints(cls)


# --------------------------------------------------------------------------
# generic dict <-> dataclass plumbing
# --------------------------------------------------------------------------

def from_dict(cls, data: Any):
    """Build dataclass ``cls`` from a raw tree, ignoring unknown keys."""
    if data is None:
        return cls()
    if not isinstance(data, dict):
        raise TypeError(f"{cls.__name__}: expected mapping, got {type(data).__name__}")
    hints = _hints(cls)
    kwargs = {}
    for f in fields(cls):
        if f.name not in data:
            continue
        raw = data[f.name]
        ft = hints[f.name]
        kwargs[f.name] = _coerce(ft, raw)
    return cls(**kwargs)


def _coerce(ft, raw):
    origin = get_origin(ft)
    if is_dataclass(ft):
        # a schema class may accept legacy scalar forms (e.g.
        # ``loop.placement: spread`` predating the placement block)
        conv = getattr(ft, "from_raw", None)
        if conv is not None:
            return conv(raw)
        return from_dict(ft, raw)
    if origin is list:
        (elem,) = get_args(ft)
        if raw is None:
            return []
        return [_coerce(elem, r) for r in raw]
    if origin is dict:
        _, vt = get_args(ft)
        if raw is None:
            return {}
        return {k: _coerce(vt, v) for k, v in raw.items()}
    if origin is not None:  # Optional[...] and friends: pass through
        return raw
    if ft is float and isinstance(raw, int):
        return float(raw)
    return raw


def to_dict(obj) -> dict:
    """Dataclass -> plain tree, dropping values equal to the field default."""
    out: dict[str, Any] = {}
    for f in fields(obj):
        v = getattr(obj, f.name)
        if f.default is not dataclasses.MISSING and v == f.default:
            continue
        if f.default_factory is not dataclasses.MISSING and v == f.default_factory():  # type: ignore[misc]
            continue
        if is_dataclass(v):
            sub = to_dict(v)
            if sub:
                out[f.name] = sub
        elif isinstance(v, list):
            out[f.name] = [to_dict(i) if is_dataclass(i) else i for i in v]
        else:
            out[f.name] = v
    return out


# --------------------------------------------------------------------------
# project config (clawker.yaml)
# --------------------------------------------------------------------------

# Characters allowed in an HTTP method.  Deliberately NARROWER than the
# RFC 7230 token charset: methods are interpolated into an Envoy
# safe_regex alternation, and token chars like | + . * ^ are regex
# metacharacters that would widen the route's method match.  Every real
# method (incl. WebDAV) fits [A-Z0-9_-].
_METHOD_TOKEN = frozenset("ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_-")
# RFC 3986 pchar + "/" (plus %-escapes): what a literal route path may hold.
_PATH_CHARS = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789"
    "-._~!$&'()+,;=:@/%")


class RuleValidationError(ValueError):
    """A rule failed ingestion validation; the whole update is rejected
    (reference ValidateRule semantics, rules_store.go /
    controlplane/firewall/envoy_http.go:337)."""


def _validate_action(value: str, *, where: str,
                     allowed: tuple[str, ...]) -> str:
    v = (value or "").strip().lower()
    if v not in allowed:
        raise RuleValidationError(
            f"{where}: unknown action {value!r} (want one of "
            f"{'/'.join(a or chr(34) + chr(34) for a in allowed)}) -- "
            "a typo'd deny must not silently fail open")
    return v


def validate_path(path: str, *, where: str) -> None:
    """Reject paths that cannot mean what the user intended.

    Path rules are literal prefixes (Envoy `prefix:` match).  A glob like
    ``/repos/*`` would match the ``*`` LITERALLY -- denying everything the
    user meant to allow -- so glob metacharacters are rejected outright
    with the prefix-semantics hint (round-3 verdict weak #3; reference
    pathSpecifier requires an explicit regex marker,
    envoy_http.go:337-347)."""
    if not path.startswith("/"):
        raise RuleValidationError(
            f"{where}: path {path!r} must start with '/'")
    for ch in ("*", "?", "["):
        if ch in path:
            raise RuleValidationError(
                f"{where}: path {path!r} contains {ch!r} -- path rules are "
                "literal prefixes, not globs; '/repos/' already matches "
                "everything under /repos/")
    bad = set(path) - _PATH_CHARS
    if bad:
        raise RuleValidationError(
            f"{where}: path {path!r} contains invalid characters "
            f"{sorted(bad)!r}")


@dataclass
class PathRule:
    """One HTTP path verdict inside an egress rule (prefix match, applied
    in declaration order; reference: httpAllowRoute/httpDenyRoute in
    controlplane/firewall/envoy_http.go:296/:314).

    Validation is strict at construction (= ingestion: config parse and
    FirewallAddRules both build these via from_dict): unknown actions,
    non-token methods, and glob/relative paths reject the whole update
    instead of failing open (advisor r3 medium #1)."""

    path: str = ""
    action: str = "allow"           # allow | deny
    methods: list[str] = field(default_factory=list)  # empty = any verb

    def __post_init__(self) -> None:
        self.action = _validate_action(
            self.action or "allow", where=f"path_rule {self.path!r}",
            allowed=("allow", "deny"))
        methods = sorted({m.strip().upper() for m in self.methods if m})
        for m in methods:
            if not m or set(m) - _METHOD_TOKEN:
                raise RuleValidationError(
                    f"path_rule {self.path!r}: method {m!r} is not an "
                    "HTTP token (regex metacharacters would broaden the "
                    "route's method match)")
        self.methods = methods
        if self.path:
            validate_path(self.path, where=f"path_rule {self.path!r}")


@dataclass
class EgressRule:
    """One egress rule.

    ``dst`` is a domain -- exact, or wildcard as ``*.zone`` / leading-dot
    ``.zone`` (reference config syntax, e2e firewall_test.go:678); both
    normalize to the ``*.`` form.  ``proto`` is one of http|https|tcp|udp,
    ``port`` the destination port (0 = protocol default).  ``action: deny``
    carves a more-specific NXDOMAIN zone out of a broader wildcard allow
    (firewall_test.go:653 DenySubdomainUnderWildcard).  ``path_rules`` +
    ``path_default`` gate HTTP paths behind MITM/Host inspection
    (firewall_test.go:842-1320); ``paths`` is the legacy shorthand for
    allow-prefixes with an implied deny default.  Dedupe key is
    ``dst:proto:port`` (reference: controlplane/firewall/rules_store.go).
    """

    dst: str = ""
    proto: str = "https"
    port: int = 0
    action: str = "allow"           # allow | deny (domain-level)
    paths: list[str] = field(default_factory=list)
    path_rules: list[PathRule] = field(default_factory=list)
    path_default: str = ""          # allow | deny; "" = deny when ruled

    def __post_init__(self) -> None:
        dst = (self.dst or "").strip().lower().rstrip(".")
        if dst.startswith(".") and len(dst) > 1:
            dst = "*" + dst         # ".zone" == "*.zone"
        self.dst = dst
        self.action = _validate_action(
            self.action or "allow", where=f"rule {self.dst!r}",
            allowed=("allow", "deny"))
        self.path_default = _validate_action(
            self.path_default, where=f"rule {self.dst!r} path_default",
            allowed=("", "allow", "deny"))
        for p in self.paths:
            validate_path(p, where=f"rule {self.dst!r} paths")

    def key(self) -> str:
        return f"{self.dst}:{self.proto}:{self.effective_port()}"

    def effective_port(self) -> int:
        if self.port:
            return self.port
        return {"https": 443, "http": 80, "ssh": 22, "git": 9418,
                "udp": 0, "tcp": 0}.get(self.proto, 0)

    @property
    def wildcard(self) -> bool:
        return self.dst.startswith("*.")

    @property
    def apex(self) -> str:
        return self.dst[2:] if self.wildcard else self.dst

    def effective_path_rules(self) -> list[PathRule]:
        """Declared path_rules followed by legacy ``paths`` allow-prefixes."""
        out = list(self.path_rules)
        out.extend(PathRule(path=p) for p in self.paths)
        return out

    def effective_path_default(self) -> str:
        if self.path_default in ("allow", "deny"):
            return self.path_default
        return "deny" if self.effective_path_rules() else "allow"

    def needs_inspection(self) -> bool:
        """True when HTTP-layer path/method verdicts exist -- https rules
        must MITM instead of SNI-passthrough."""
        return bool(self.effective_path_rules()) or self.path_default == "deny"


@dataclass
class BuildConfig:
    image: str = ""                 # base image override (else stack default)
    stack: str = ""                 # language stack bundle (python, go, node...)
    harness: str = "claude"         # agent harness bundle
    packages: list[str] = field(default_factory=list)
    env: dict[str, str] = field(default_factory=dict)
    instructions: list[str] = field(default_factory=list)  # extra Dockerfile lines


@dataclass
class AgentConfig:
    default: str = "dev"            # default agent name
    cmd: list[str] = field(default_factory=list)   # override harness CMD
    env: dict[str, str] = field(default_factory=dict)
    memory: str = ""                # container memory limit, e.g. "8g"
    cpus: float = 0.0


@dataclass
class WorkspaceConfig:
    mode: str = "bind"              # bind | snapshot (reference: internal/workspace)
    mount_docker_socket: bool = False
    extra_mounts: list[str] = field(default_factory=list)  # "src:dst[:ro]"


@dataclass
class SecurityConfig:
    egress: list[EgressRule] = field(default_factory=list)
    allow_host_proxy: bool = True
    bypass_firewall: bool = False   # dev-only full bypass


@dataclass
class ProjectConfig:
    project: str = ""
    build: BuildConfig = field(default_factory=BuildConfig)
    agent: AgentConfig = field(default_factory=AgentConfig)
    workspace: WorkspaceConfig = field(default_factory=WorkspaceConfig)
    security: SecurityConfig = field(default_factory=SecurityConfig)

    @staticmethod
    def merge_strategies() -> dict[str, str]:
        """Dotted-path merge strategies for the layered store (union lists)."""
        return {
            "build.packages": "union",
            "build.instructions": "union",
            "security.egress": "union",
            "workspace.extra_mounts": "union",
        }


# --------------------------------------------------------------------------
# settings (settings.yaml)
# --------------------------------------------------------------------------

@dataclass
class LoggingSettings:
    level: str = "info"
    file_enabled: bool = True
    otlp_enabled: bool = False


@dataclass
class HostProxySettings:
    enable: bool = True
    port: int = 18374


@dataclass
class FirewallSettings:
    enable: bool = False
    default_deny: bool = True
    dns_upstreams: list[str] = field(default_factory=list)  # default: consts.UPSTREAM_DNS


@dataclass
class ShipperSettings:
    """Fleet-telemetry bulk ingestion into the monitor stack
    (docs/fleet-console.md#ingestion).

    With ``enable``, a loopd daemon hosts one
    :class:`~clawker_tpu.monitor.shipper.TelemetryShipper` for its
    lifetime (in-process runs attach one with ``clawker loop
    --ship-telemetry``): registry snapshots, typed bus events, and
    flight-recorder spans batch into the OpenSearch bulk API.  Bounded
    by design -- at most ``max_batches`` sealed batches wait in memory;
    a slow or down index drops the OLDEST batches (counted in
    ``monitor_ingest_dropped_total``) and can never stall the event bus
    or a scheduler lane."""

    enable: bool = False
    url: str = ""                   # bulk endpoint override; "" = the
    #                                 local stack's opensearch port
    interval_s: float = 2.0         # snapshot + flush cadence
    batch_docs: int = 256           # docs per sealed bulk batch
    max_batches: int = 64           # sealed batches buffered before
    #                                 drop-oldest backpressure
    timeout_s: float = 5.0          # per-bulk-POST deadline


@dataclass
class MonitoringSettings:
    enable: bool = False
    opensearch_port: int = 9200
    dashboards_port: int = 5601
    prometheus_port: int = 9090
    otlp_grpc_port: int = 4317
    shipper: ShipperSettings = field(default_factory=ShipperSettings)


@dataclass
class ControlPlaneSettings:
    enable: bool = False            # bring the CP up on container start paths
    admin_port: int = 7443
    agent_port: int = 7444
    health_port: int = 7080
    advertise_host: str = ""        # address agents Register back to; "" = bridge gateway
    drain_to_zero: bool = False     # self-shutdown when the last agent exits
    per_worker: bool = True         # tpu_vm: one CP per worker VM + fleet aggregation


@dataclass
class TPUSettings:
    """TPU-pod runtime description (net-new; BASELINE.json north_star)."""

    pod: str = ""                   # TPU name, e.g. "my-v5e-8"
    zone: str = ""
    project: str = ""               # GCP project
    ssh_user: str = "clawker"
    ssh_key: str = ""               # path to private key; empty = agent/default
    workers: list[str] = field(default_factory=list)  # explicit host list override
    accelerator: str = "v5litepod-8"
    topology: str = ""              # worker grid "RxC" (e.g. "2x4") for the
    #                                 topology placement policy; "" = infer a
    #                                 near-square grid from the worker count


@dataclass
class LoopJournalSettings:
    """Durable run journal for ``clawker loop`` (docs/loop-resume.md).

    On by default: the journal exists exactly for the scheduler deaths
    nobody planned for, and its cost is one fsync-batched JSONL append
    per state transition.  ``fsync_batch_n`` / ``fsync_interval_s``
    bound how much un-synced tail a HOST crash may lose (a CLI crash
    loses nothing -- every record is flushed to the OS on append).

    ``on_fault`` is the storage-fault policy (docs/durability.md): a
    durable append that cannot be made durable either journals a
    ``degraded-durability`` state and keeps the run alive (``degrade``,
    the default -- agents keep working, resume fidelity is at risk) or
    fail-stops the run (``fail`` -- the WAL contract is load-bearing,
    running on without it is worse than stopping)."""

    enable: bool = True
    fsync_batch_n: int = 8          # records per group-commit fsync
    fsync_interval_s: float = 0.25  # max age of an un-synced tail
    on_fault: str = "degrade"       # degrade | fail (durable-append fault)


@dataclass
class StoragePressureSettings:
    """Disk-pressure degradation ladder (docs/durability.md#ladder).

    A statvfs watermark monitor ticked by the scheduler and loopd: at
    the SOFT watermark non-durable streams shed first (flight spans ->
    shipper batches -> sentinel state), each shed counted per-stream;
    at the HARD watermark the emergency retention GC deletes journals
    and flight files of done runs past the newest ``retention_runs`` --
    reclaiming space BEFORE a durable append is allowed to fail.
    Watermarks are free-space fractions of the logs filesystem."""

    enable: bool = True
    soft_free_pct: float = 10.0     # shed non-durable streams below this
    hard_free_pct: float = 3.0      # emergency retention GC below this
    check_interval_s: float = 5.0   # statvfs cadence
    retention_runs: int = 64        # newest done-run journals kept by GC


@dataclass
class LoopPlacementSettings:
    """Pod-scale placement & admission defaults (docs/loop-placement.md).

    ``max_inflight_per_worker`` is the per-worker admission token
    bucket: how many create/start launches may be outstanding against
    one daemon at once -- a 64-loop burst drains at each worker's
    sustainable rate instead of flooding its lane.  ``max_pending_per_
    worker`` bounds the admission queue (beyond it, submissions are
    REJECTED and counted, and the scheduler re-places or retries).
    Tenant weight/caps drive the weighted fair queue that keeps two
    runs sharing a pod from starving each other.

    Back-compat: ``loop.placement`` used to be a bare policy string
    (``placement: spread``); that form still parses as
    ``{policy: spread}`` (see ``from_raw``)."""

    policy: str = "spread"          # spread | pack | topology
    max_inflight_per_worker: int = 4
    max_pending_per_worker: int = 256
    tenant: str = "default"         # tenant id new runs bill under
    tenant_weight: float = 1.0      # weighted-fair-queue share
    tenant_max_inflight: int = 0    # per-tenant in-flight launch cap; 0 = none

    @classmethod
    def from_raw(cls, raw) -> "LoopPlacementSettings":
        if isinstance(raw, str):
            return cls(policy=raw)
        return from_dict(cls, raw)


@dataclass
class LoopWarmPoolSettings:
    """Per-worker warm pool of created-not-yet-started agent containers
    (docs/loop-warmpool.md).

    With ``enable``, each worker keeps ``depth`` pre-created containers
    with the expensive create-time stages (engine create, workspace
    seed, harness seed, identity prewarm) already paid; a placement
    ADOPTS one -- relabel/env-fixup + start -- instead of a full
    bootstrap.  Refills bill a dedicated low-weight admission tenant so
    they never starve live placements; ``max_age_s`` bounds how stale a
    pre-staged workspace/harness snapshot may get before the member is
    recycled."""

    enable: bool = False
    depth: int = 2                  # target pool depth per worker
    max_age_s: float = 600.0        # recycle members older than this
    tenant_weight: float = 0.25     # WFQ share of the refill tenant vs
    #                                 real placements (weight 1.0)


@dataclass
class LoopWorktreeSettings:
    """The ``clawker loop --worktrees`` swarm scenario: N agents
    collaborating on ONE repository, branch-per-agent
    (docs/loop-worktrees.md).

    Each agent loop gets its own branch forked from ``base`` and its own
    linked git worktree (never a clone); ``workspace_mode`` picks how
    that tree reaches the container -- ``bind`` mounts the worktree dir
    live (local driver only), ``snapshot`` seeds the container from the
    content-addressed seed cache (one tar per fan-out, workerd-capable,
    warm-pool-capable).  With ``merge_queue``, agent branches land
    serially into a run-scoped integration branch at iteration end;
    conflict losers are resubmitted through admission after
    ``merge_retry_s`` (or the admission controller's ``retry_after_s``
    when it quotes one)."""

    workspace_mode: str = "bind"    # bind | snapshot
    branch_prefix: str = "loop"     # agent branches: <prefix>/<run>/<agent>
    base: str = "HEAD"              # ref agent branches fork from
    merge_queue: bool = True        # land agent branches at iteration end
    merge_into: str = ""            # target branch; "" = run-scoped
    #                                 integration branch <prefix>/<run>/merged
    merge_retry_s: float = 0.5      # conflict-loser resubmit delay when
    #                                 admission quotes no retry_after_s
    merge_attempts: int = 3         # merge tries per branch before the
    #                                 loser is reported failed


@dataclass
class LoopSettings:
    """Autonomous-loop scheduler defaults (net-new)."""

    parallel: int = 1
    max_iterations: int = 0         # 0 = unbounded
    idle_exit_s: float = 300.0
    placement: LoopPlacementSettings = field(
        default_factory=LoopPlacementSettings)
    failover: str = "migrate"       # migrate | wait | fail (worker death)
    journal: LoopJournalSettings = field(default_factory=LoopJournalSettings)
    storage_pressure: StoragePressureSettings = field(
        default_factory=StoragePressureSettings)
    warm_pool: LoopWarmPoolSettings = field(
        default_factory=LoopWarmPoolSettings)
    worktrees: LoopWorktreeSettings = field(
        default_factory=LoopWorktreeSettings)


@dataclass
class RuntimeSettings:
    driver: str = "local"           # local | tpu_vm | nsd | fake
    docker_host: str = ""           # override local daemon address
    tpu: TPUSettings = field(default_factory=TPUSettings)


@dataclass
class FlightRecorderSettings:
    """Per-run / per-daemon span JSONL under logs/flight
    (docs/telemetry.md#flight-recorder).

    Back-compat: ``telemetry.flight_recorder`` used to be a bare bool;
    that form still parses as ``{enable: <bool>}`` (see ``from_raw``).
    ``max_bytes`` size-caps each recorder file: at the cap the current
    file rotates to ``<file>.1`` and a fresh generation starts, so a
    long daemon-hosted run cannot grow logs/flight unboundedly while
    the newest records stay readable (readers cross the boundary)."""

    enable: bool = True
    max_bytes: int = 0              # per-file rotation cap; 0 = unbounded

    @classmethod
    def from_raw(cls, raw) -> "FlightRecorderSettings":
        if isinstance(raw, bool):
            return cls(enable=raw)
        return from_dict(cls, raw)

    def __bool__(self) -> bool:     # legacy truthiness: `if
        return self.enable          # settings.telemetry.flight_recorder:`


@dataclass
class TracingSettings:
    """Cross-process distributed tracing (docs/tracing.md).

    Context propagation rides frame fields on RPCs that already exist,
    so ``enable`` gates only the *recording* side: daemon-side remote
    spans and the per-channel clock-skew estimation.  ``skew_alpha`` is
    the EWMA weight for new midpoint-offset samples."""

    enable: bool = True
    skew_alpha: float = 0.25        # EWMA weight per offset sample


@dataclass
class TelemetrySettings:
    """Fleet telemetry (net-new; docs/telemetry.md).

    Spans + flight recorder are on by default (cheap, and post-mortems
    exist for the runs nobody planned to debug); the Prometheus scrape
    port is opt-in because it opens a listener."""

    metrics_port: int = 0           # 127.0.0.1 scrape port; 0 = off
    otlp: bool = False              # ship registry snapshots over the
    #                                 CP's OTLP lanes during loop runs
    flight_recorder: FlightRecorderSettings = field(
        default_factory=FlightRecorderSettings)
    tracing: TracingSettings = field(default_factory=TracingSettings)


@dataclass
class LoopdSettings:
    """The host-resident loop-supervisor daemon (docs/loopd.md).

    ``clawker loopd start`` brings up one daemon per host; it owns the
    pod-scale state -- ONE admission controller, the per-worker serial
    lanes, its own health breakers -- so two concurrent ``clawker
    loop`` invocations share the per-worker inflight caps and tenant
    fairness ACROSS processes, and runs keep executing after the
    submitting CLI exits (``clawker loop attach <run>`` re-streams).

    With ``enable`` the CLI auto-discovers a running daemon (unix
    socket in a 0700 runtime dir under the state dir) and becomes a
    thin control client; no daemon = today's in-process scheduler,
    unchanged.  ``autostart`` spawns the daemon on first ``clawker
    loop`` when none answers."""

    enable: bool = True             # CLI may discover & use a running daemon
    socket: str = ""                # unix socket path override
    #                                 ("" = <state>/loopd/loopd.sock)
    autostart: bool = False         # `clawker loop` starts loopd if absent
    metrics_port: int = 0           # daemon-owned Prometheus scrape port
    #                                 (127.0.0.1; 0 = off)
    drain_grace_s: float = 10.0     # graceful-stop budget per live run
    start_deadline_s: float = 15.0  # loopd start: socket-answering deadline


@dataclass
class FederationSettings:
    """Multi-pod federation: the front-tier run router (docs/federation.md).

    One loopd daemon serves one pod; with ``pods`` listing several
    daemons' sockets the ``FederationRouter`` places whole runs (or
    shards of one large ``--parallel N`` run) ACROSS pods: a
    ``PodPolicy`` picks pods by locality tier (ICI group < pod < DCN),
    live load, and breaker state from each pod's status RPC, then the
    pod's own per-worker policy places intra-pod, untouched.  Launch
    admission is amortized through bounded, renewable capacity LEASES
    (``lease_tokens`` launch tokens per pod, ``lease_ttl_s`` TTL), so
    the launch hot path pays zero extra WAN hops.  No pods configured
    = the single-pod loopd path, byte-identical (degrade matrix)."""

    enable: bool = False            # `clawker loop --pods` / `clawker fed`
    name: str = ""                  # THIS pod's name in the federation
    #                                 ("" = the socket's directory name)
    pods: list[str] = field(default_factory=list)  # per-pod loopd socket
    #                                 paths the router addresses
    shape: str = ""                 # pod grid "RxC" for locality tiers
    #                                 ("" = flat: every pod equidistant)
    lease_tokens: int = 8           # launch tokens per capacity lease
    lease_ttl_s: float = 5.0        # lease TTL; a partitioned router's
    #                                 tokens lapse back to the pod
    status_interval_s: float = 1.0  # pod status/health poll cadence


@dataclass
class WorkerdSettings:
    """The worker-resident launch daemon (docs/workerd.md).

    ``clawker workerd start`` brings up one daemon per WORKER host; the
    scheduler (or loopd) discovers it -- the transport-forwarded socket
    for ``tpu_vm`` workers, the canonical state-dir socket for the
    local engine -- and moves the launch data plane there: batched
    intents out, batched typed events back, one persistent channel per
    worker, so creates/starts/waits stop paying a host<->worker WAN
    round trip per engine call.  No daemon answering = the in-process
    direct executor, unchanged (`clawker loop --no-workerd` forces it;
    `fleet health` renders per-worker liveness)."""

    enable: bool = True             # scheduler may discover & use workerd
    socket: str = ""                # unix socket path override
    #                                 ("" = <state>/workerd/workerd.sock)
    intent_deadline_s: float = 60.0  # pending intent age before the loop
    #                                  fails over to the direct path
    start_deadline_s: float = 15.0  # workerd start: socket-answer deadline
    seed_cache_bytes: int = 64 * 1024 * 1024  # worker-local seed store
    #                                 cap: content-addressed workspace
    #                                 seed tars kept resident (LRU by
    #                                 bytes) so launch intents reference
    #                                 a digest instead of re-shipping the
    #                                 tree over the WAN per agent
    #                                 (docs/loop-worktrees.md#seed-cache)


@dataclass
class SentinelSettings:
    """The online fleet sentinel (docs/analytics-online.md).

    With ``enable`` (or ``clawker loop --sentinel``), every loop run
    fuses the fleet's egress streams with the scheduler's typed events
    and scores the whole fleet's open windows each ``interval_s`` as
    ONE sharded program -- flags surface as typed ``anomaly.flag`` bus
    events, ``anomaly_score``/``anomaly_flags_total`` metrics, and
    flight-recorder spans.  Strictly observe-only: flags never feed
    breakers or placement.  ``threshold`` is the worker-relative robust
    z past which an agent's window flags; ``baseline_window`` bounds
    the per-worker rolling normal profile (persisted per run, so
    ``--resume`` keeps it)."""

    enable: bool = False
    interval_s: float = 5.0
    window_s: int = 60
    train_steps: int = 40           # denoising fit steps per tick
    threshold: float = 3.5
    baseline_window: int = 256


@dataclass
class ChaosSettings:
    """Defaults for ``clawker chaos run`` (docs/chaos.md).

    ``seed`` pins the soak schedule: scenario ``i`` of a run is fully
    determined by ``(seed, i)``, so a CI failure replays anywhere with
    ``clawker chaos replay --seed S --scenario I``.  The fleet shape
    mirrors the 4-worker fake pod the robustness suites use."""

    scenarios: int = 25             # seeded scenarios per soak
    seed: int = 20260803            # fixed default: CI soaks are repros
    parallel: int = 6               # agent loops per scenario
    workers: int = 4                # fake pod size
    iterations: int = 2             # per-loop iteration budget


@dataclass
class CapacitySloSettings:
    """Per-tenant latency SLOs the admission scaling law targets
    (docs/elastic-capacity.md).  A tenant's SLO bounds the admission
    wait its launches may see; the tightest configured SLO drives the
    per-worker token scaling, and a queue that provably cannot drain
    inside it flips to reject-with-``retry_after_s``.  0 = no SLO."""

    default_s: float = 0.0          # SLO for tenants not listed below
    tenants: dict[str, float] = field(default_factory=dict)


@dataclass
class CapacityAutoscaleSettings:
    """Fleet autoscaling thresholds (docs/elastic-capacity.md).

    Sustained per-worker queue depth past ``queue_high`` provisions a
    worker through the concurrent fleet provisioner; sustained busy
    fraction under ``idle_low`` drains the least-loaded worker --
    gated on journal replay proving zero live placements on the victim
    (a journaled run is never stranded by scale-down)."""

    enable: bool = False
    min_workers: int = 1
    max_workers: int = 8
    queue_high: int = 8             # sustained pending per worker -> grow
    idle_low: float = 0.25          # sustained busy fraction under -> drain
    sustain_s: float = 5.0          # how long a signal must hold


@dataclass
class CapacitySettings:
    """The elastic-capacity controller (docs/elastic-capacity.md).

    With ``enable``, loopd ticks one controller across its hosted runs
    (in-process ``--no-daemon`` runs tick their own): warm-pool depth
    follows the EWMA arrival rate per worker within
    ``[pool_min_depth, pool_max_depth]``, admission tokens scale from
    measured launch latency against the ``slo`` block, and the
    ``autoscale`` block provisions/drains workers.  Every decision is
    journaled (``REC_CAPACITY_*``) and emitted as a typed
    ``capacity.decision`` bus event."""

    enable: bool = False
    interval_s: float = 1.0         # controller tick cadence
    pool_min_depth: int = 0         # adaptive target clamp, per worker
    pool_max_depth: int = 8
    refill_lead_s: float = 0.0      # arrival window one pool member must
    #                                 cover; 0 = use measured launch latency
    alpha_up: float = 0.5           # arrival EWMA: burst response
    alpha_down: float = 0.08        # arrival EWMA: decay to quiet baseline
    token_min: int = 0              # token scaling floor; 0 = the static
    #                                 loop.placement.max_inflight_per_worker
    token_max: int = 16             # token scaling ceiling per worker
    slo: CapacitySloSettings = field(default_factory=CapacitySloSettings)
    autoscale: CapacityAutoscaleSettings = field(
        default_factory=CapacityAutoscaleSettings)


@dataclass
class GitguardSettings:
    """The git-protocol firewall proxy for worktree swarms
    (docs/git-policy.md).

    With ``enable``, a ``clawker loop --worktrees`` run starts a
    gitguard proxy on a hardened unix socket, installs run-scoped
    egress rules (each host in ``hosts`` gets an https lane forced
    through the guard plus ssh/22 and git/9418 deny pins), and tears
    both down at cleanup.  The guard filters ref advertisements and
    refuses out-of-namespace pushes per agent identity -- fail-closed:
    with the guard down, every git path is a connection error."""

    enable: bool = True             # guard worktree swarm runs
    hosts: list[str] = field(default_factory=list)
    #                                 git hosts to route through the guard
    #                                 (empty = the run's own seed repo only)
    socket: str = ""                # unix socket path override
    #                                 ("" = <state>/gitguard/<run>.sock)
    merge_identity: str = "mergeq"  # privileged role that may land the
    #                                 integration branch


@dataclass
class CredentialSettings:
    """Host-credential staging policy (off by default).

    The default contract: credentials are NEVER copied from the host;
    you authenticate once inside the agent container and the token
    family persists across recreates in the per-agent config volume
    (proven by tests/e2e/test_e2e_credentials.py).  ``stage: true``
    opts in to copying the harness manifest's declared credential
    files (staging.credentials) at create time -- the reference's
    keyring behavior -- so fleet fan-outs start pre-authenticated."""

    stage: bool = False


@dataclass
class Settings:
    logging: LoggingSettings = field(default_factory=LoggingSettings)
    host_proxy: HostProxySettings = field(default_factory=HostProxySettings)
    firewall: FirewallSettings = field(default_factory=FirewallSettings)
    monitoring: MonitoringSettings = field(default_factory=MonitoringSettings)
    control_plane: ControlPlaneSettings = field(default_factory=ControlPlaneSettings)
    runtime: RuntimeSettings = field(default_factory=RuntimeSettings)
    loop: LoopSettings = field(default_factory=LoopSettings)
    loopd: LoopdSettings = field(default_factory=LoopdSettings)
    federation: FederationSettings = field(default_factory=FederationSettings)
    workerd: WorkerdSettings = field(default_factory=WorkerdSettings)
    telemetry: TelemetrySettings = field(default_factory=TelemetrySettings)
    credentials: CredentialSettings = field(default_factory=CredentialSettings)
    chaos: ChaosSettings = field(default_factory=ChaosSettings)
    sentinel: SentinelSettings = field(default_factory=SentinelSettings)
    capacity: CapacitySettings = field(default_factory=CapacitySettings)
    gitguard: GitguardSettings = field(default_factory=GitguardSettings)

    @staticmethod
    def merge_strategies() -> dict[str, str]:
        return {
            "firewall.dns_upstreams": "union",
            "runtime.tpu.workers": "union",
            "federation.pods": "union",
            "gitguard.hosts": "union",
        }
