"""Egress featurizer: netlogger JSONL -> per-agent window feature vectors.

This is the host-side half of the anomaly lane (anomaly.py is the TPU
half): it folds the ``ebpf-egress.jsonl`` stream the netlogger writes
(monitor/netlogger.py enrich() record shape) into fixed 60-second
windows per agent and summarizes each window as the 32-dim vector the
autoencoder scores.  numpy only -- no jax import -- so the loop
scheduler and CLI can featurize without touching an accelerator.

Feature layout (FEATURES=32, anomaly.py):

   0     log1p(total decisions)
   1- 4  log1p(count) per verdict: ALLOW, DENY, REDIRECT, REDIRECT_DNS
   5     deny ratio
   6-18  log1p(count) per reason (13 Reason values, model.py order)
  19     log1p(unique dst ips)
  20     log1p(unique dst ports)
  21     log1p(unique zones)
  22-24  log1p(count) per proto: tcp, udp, other
  25     well-known-port flows (<1024, excl. 53/443) log1p
  26     ephemeral-port flows (>=32768) log1p
  27     port-53 flows log1p
  28     port-443 flows log1p
  29     burstiness: max 1-second bucket / total
  30     active seconds / window seconds
  31     log1p(events per active second)

Parity reference: net-new (VERDICT r4 task 2); the reference ships raw
events to OpenSearch and leaves aggregation to dashboards -- here the
fleet-wide scoring IS the TPU workload, so the aggregation is a typed
ABI between stream and model.
"""

from __future__ import annotations

import calendar
import json
import time
from dataclasses import dataclass
from pathlib import Path
from typing import Iterable

import numpy as np

FEATURES = 32
WINDOW_S = 60

_VERDICTS = ("ALLOW", "DENY", "REDIRECT", "REDIRECT_DNS")
_REASONS = ("UNMANAGED", "BYPASS", "LOOPBACK", "DNS", "ENVOY", "HOSTPROXY",
            "ROUTE", "NO_ROUTE", "NO_DNS_ENTRY", "RAW_SOCKET", "IPV6",
            "MONITOR", "INTRA_NET")


@dataclass(frozen=True)
class WindowKey:
    agent: str         # container name (or cgroup id when unresolved)
    start_unix: int    # window start, aligned to WINDOW_S


def parse_ts(ts: str) -> int:
    """Netlogger timestamps: UTC '%Y-%m-%dT%H:%M:%SZ'."""
    try:
        return calendar.timegm(time.strptime(ts, "%Y-%m-%dT%H:%M:%SZ"))
    except (ValueError, TypeError):
        return 0


def load_jsonl(path: str | Path, max_records: int = 200_000) -> list[dict]:
    """Read netlogger records, newest-last; tolerates partial lines."""
    out: list[dict] = []
    try:
        with open(path, encoding="utf-8", errors="replace") as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    out.append(rec)
    except OSError:
        return []
    return out[-max_records:]


def _agent_of(rec: dict) -> str:
    return str(rec.get("container") or rec.get("cgroup_id") or "unknown")


def featurize(records: Iterable[dict], *, window_s: int = WINDOW_S,
              ) -> tuple[list[WindowKey], np.ndarray]:
    """Group records into (agent, aligned-window) buckets and vectorize.

    Returns (keys, X[n, FEATURES]) sorted by (agent, window start).  Rows
    are deterministic for a given record set.
    """
    # buckets carry (ts, rec) so _vectorize never re-parses timestamps
    # (the strptime is the dominant host-side cost at watch scale)
    buckets: dict[WindowKey, list[tuple[int, dict]]] = {}
    for rec in records:
        ts = parse_ts(rec.get("@timestamp", ""))
        if not ts:
            continue
        key = WindowKey(_agent_of(rec), ts - ts % window_s)
        buckets.setdefault(key, []).append((ts, rec))

    keys = sorted(buckets, key=lambda k: (k.agent, k.start_unix))
    X = np.zeros((len(keys), FEATURES), np.float32)
    for i, key in enumerate(keys):
        X[i] = _vectorize(buckets[key], window_s)
    return keys, X


def _vectorize(pairs: list[tuple[int, dict]], window_s: int) -> np.ndarray:
    recs = [rec for _, rec in pairs]
    v = np.zeros(FEATURES, np.float32)
    total = len(recs)
    v[0] = np.log1p(total)

    verdicts = [str(r.get("verdict", "")) for r in recs]
    for j, name in enumerate(_VERDICTS):
        v[1 + j] = np.log1p(verdicts.count(name))
    v[5] = verdicts.count("DENY") / total if total else 0.0

    reasons = [str(r.get("reason", "")) for r in recs]
    for j, name in enumerate(_REASONS):
        v[6 + j] = np.log1p(reasons.count(name))

    v[19] = np.log1p(len({r.get("dst_ip") for r in recs}))
    v[20] = np.log1p(len({r.get("dst_port") for r in recs}))
    v[21] = np.log1p(len({r.get("zone") for r in recs if r.get("zone")}))

    protos = [int(r.get("proto") or 0) for r in recs]
    v[22] = np.log1p(protos.count(6))
    v[23] = np.log1p(protos.count(17))
    v[24] = np.log1p(sum(1 for p in protos if p not in (6, 17)))

    ports = [int(r.get("dst_port") or 0) for r in recs]
    v[25] = np.log1p(sum(1 for p in ports if p < 1024 and p not in (53, 443)))
    v[26] = np.log1p(sum(1 for p in ports if p >= 32768))
    v[27] = np.log1p(ports.count(53))
    v[28] = np.log1p(ports.count(443))

    per_sec: dict[int, int] = {}
    for s, _ in pairs:
        per_sec[s] = per_sec.get(s, 0) + 1
    if total:
        v[29] = max(per_sec.values()) / total
    active = len(per_sec)
    v[30] = active / window_s
    v[31] = np.log1p(total / active) if active else 0.0
    return v


# --------------------------------------------------------------- summaries


@dataclass
class AgentScore:
    agent: str
    windows: int
    latest: float      # score of the newest window
    peak: float        # max score across windows
    latest_start: int  # unix start of the newest window


def summarize(keys: list[WindowKey], scores: np.ndarray) -> list[AgentScore]:
    """Fold per-window scores into per-agent rows (newest window last in
    `keys` per agent, by featurize's sort order)."""
    by_agent: dict[str, list[tuple[int, float]]] = {}
    for key, s in zip(keys, scores):
        by_agent.setdefault(key.agent, []).append((key.start_unix, float(s)))
    out = []
    for agent, rows in sorted(by_agent.items()):
        rows.sort()
        out.append(AgentScore(
            agent=agent, windows=len(rows), latest=rows[-1][1],
            peak=max(s for _, s in rows), latest_start=rows[-1][0]))
    return out
