"""Fleet telemetry analytics on TPU (net-new; no reference counterpart).

The reference ships telemetry to an OpenSearch stack and leaves analysis to
dashboards (SURVEY.md 2.11).  On a TPU pod the chips are idle while agents
think, so this build adds an on-accelerator analytics path: per-agent egress
event windows are scored by a small autoencoder anomaly model, sharded over
the fleet (data) and feature (model) axes of a jax Mesh.  This backs
`clawker monitor anomalies` and the loop scheduler's misbehaving-agent
detection, and is the framework's flagship jittable entry
(__graft_entry__.py).
"""

from .anomaly import (
    AnomalyParams,
    fleet_mesh,
    init_params,
    score,
    shard_batch,
    shard_params,
    train_step,
)

__all__ = [
    "AnomalyParams",
    "fleet_mesh",
    "init_params",
    "score",
    "shard_batch",
    "shard_params",
    "train_step",
]
