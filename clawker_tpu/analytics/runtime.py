"""Anomaly runtime: train-on-the-fleet, score, and watch.

Glue between the host-side featurizer (features.py) and the TPU model
(anomaly.py): ``score_windows`` fits the autoencoder on the window set
(the fleet's behavior is its own normal profile -- self-supervised) and
returns per-window reconstruction-error scores normalized as robust
z-scores; ``AnomalyWatch`` re-scores an egress jsonl on an interval for
the loop dashboard / scheduler without blocking their render paths.

jax is imported lazily inside functions so the CLI, scheduler and
dashboard stay importable (and fast) on hosts without an accelerator;
``jax_available()`` gates callers.

Parity reference: net-new (VERDICT r4 task 2 / __graft_entry__
contract: "the fleet-telemetry anomaly model used by `clawker monitor
anomalies` and the loop scheduler").
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from pathlib import Path

import numpy as np

from . import features as F

TRAIN_STEPS = 120
ANOMALY_Z = 3.5          # robust z-score threshold for "anomalous"


def jax_available() -> bool:
    try:
        import jax  # noqa: F401

        return True
    except Exception:  # noqa: BLE001 - any import failure means "no"
        return False


@dataclass
class ScoreReport:
    keys: list[F.WindowKey]
    raw: np.ndarray          # per-window reconstruction error
    z: np.ndarray            # robust z-score of raw
    agents: list[F.AgentScore]   # per-agent fold of z
    train_steps: int
    train_ms: float
    score_ms: float
    device: str


def _robust_z(raw: np.ndarray) -> np.ndarray:
    """Median/MAD z-scores: a few hot windows must not drag the scale."""
    if raw.size == 0:
        return raw
    med = float(np.median(raw))
    mad = float(np.median(np.abs(raw - med)))
    scale = 1.4826 * mad if mad > 0 else (float(raw.std()) or 1.0)
    return (raw - med) / scale


_PAD_BUCKET = 128    # rows padded up to a multiple of this: stable jit shapes
_jit_cache: dict = {}

# Persistent XLA compilation cache for the anomaly lane (MULTICHIP r05
# root fix): the device leg's budget was eaten by COMPILING the fit scan
# on a tunneled backend, so the suite degraded to CPU every round.  With
# the cache on, the first round pays the compile and every later tick /
# bench round / CLI run loads the executable from disk instead.
# "" disables; unwritable dirs and jax builds without the knob degrade
# silently -- the cache is an accelerator, never a dependency.
_CACHE_DIR_ENV = "CLAWKER_JAX_CACHE_DIR"


def _ensure_compilation_cache() -> None:
    if _jit_cache.get("cache_wired"):
        return
    _jit_cache["cache_wired"] = True
    import os

    cache_dir = os.environ.get(
        _CACHE_DIR_ENV,
        os.path.join(os.path.expanduser("~"), ".cache", "clawker-tpu",
                     "jax-cache"))
    if not cache_dir:
        return
    try:
        import jax

        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # cache even fast compiles: the fit scan is re-jitted per input
        # shape, and a pod of watchers shares one home dir
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:  # noqa: BLE001 -- optional fast path only
        pass


def _standardize(X: np.ndarray) -> np.ndarray:
    """Zero-mean/unit-var per feature over the window set, so the
    reconstruction error weights dimensions evenly."""
    mu = X.mean(axis=0) if len(X) else np.zeros(X.shape[1], np.float32)
    sd = X.std(axis=0) if len(X) else np.ones(X.shape[1], np.float32)
    sd = np.where(sd < 1e-6, 1.0, sd).astype(np.float32)
    return ((X - mu) / sd).astype(np.float32)


def _jitted():
    """Module-level jitted fit/score: one compilation per input shape,
    shared by every AnomalyWatch poll, sentinel tick, and CLI run in
    the process (the sentinel's steady state is exactly this cache --
    every tick after the first reuses the same compiled fit).  The fit
    scan's carry (the params pytree) is DONATED on accelerator
    backends: the caller never reads the pre-fit params again, and the
    donation lets XLA update the carry in place instead of holding both
    generations live across the scan (part of the MULTICHIP r05 fix).
    CPU ignores donation, so it is only requested where it works."""
    if "fit" not in _jit_cache:
        import jax

        from . import anomaly

        _ensure_compilation_cache()

        def fit(params, x, noises, lr):
            # noises: [steps, n, feat], generated host-side -- keeps
            # threefry out of the compiled program (see
            # anomaly.denoise_step_with_noise)
            def body(p, noise):
                p, loss = anomaly.denoise_step_with_noise(p, x, noise, lr=lr)
                return p, loss

            return jax.lax.scan(body, params, noises)

        donate = ()
        try:
            if jax.default_backend() != "cpu":
                donate = (0,)       # params: the scan carry
        except Exception:  # noqa: BLE001 -- backend probe must not fail us
            donate = ()
        _jit_cache["fit"] = jax.jit(fit, donate_argnums=donate)
        _jit_cache["score"] = jax.jit(anomaly.score)
    return _jit_cache["fit"], _jit_cache["score"]


def _fit_and_score(X: np.ndarray, *, train_steps: int, lr: float, seed: int,
                   mesh=None, feat: int | None = None):
    """-> (raw_scores[n], params, x_padded, timings).  Rows are padded by
    edge-replication up to _PAD_BUCKET multiples so a growing stream
    reuses compilations; padded scores are sliced off.

    With ``mesh`` (an :func:`anomaly.fleet_mesh`), params/batch/noise
    are placed with their named shardings before the call, so the ONE
    cached jitted fit runs as a single SPMD program over the whole
    device mesh -- the sentinel's per-tick fleet scoring path.  The
    row pad rounds up to a multiple of the mesh's data-axis size on
    top of the bucket (a 6-device mesh has data=3, which does not
    divide 128 -- sharding would reject the batch), so sharded shapes
    stay stable per mesh too.
    """
    import jax
    import jax.numpy as jnp

    n = len(X)
    width = feat or (X.shape[1] if X.ndim == 2 and X.shape[1] else 32)
    padded = max(_PAD_BUCKET, -(-n // _PAD_BUCKET) * _PAD_BUCKET)
    if mesh is not None:
        data_axis = int(mesh.devices.shape[0])
        padded = -(-padded // data_axis) * data_axis
    Xn = _standardize(X)
    if padded != n:
        pad = Xn[np.arange(padded - n) % max(n, 1)] if n else np.zeros(
            (padded, width), np.float32)
        Xn = np.concatenate([Xn, pad], axis=0) if n else pad

    fit, score_fn = _jitted()
    params = anomaly_init(seed, feat=width)
    x = jnp.asarray(Xn)
    # the whole noise tensor as ONE un-jitted device op: threefry stays
    # out of the compiled scan (pathological compile on tunneled
    # backends) without shipping tens of MB host->device per fit
    noises = jax.random.normal(jax.random.key(seed + 1),
                               (train_steps,) + Xn.shape, jnp.float32)
    mesh_desc = ""
    if mesh is not None:
        from . import anomaly

        params = anomaly.shard_params(params, mesh)
        x = anomaly.shard_batch(x, mesh)
        noises = anomaly.shard_noise(noises, mesh)
        mesh_desc = "x".join(str(s) for s in mesh.devices.shape)

    t0 = time.perf_counter()
    params, losses = fit(params, x, noises, lr)
    jax.block_until_ready(losses)
    train_ms = (time.perf_counter() - t0) * 1000.0

    t0 = time.perf_counter()
    raw = np.asarray(score_fn(params, x))[:n]
    score_ms = (time.perf_counter() - t0) * 1000.0
    dev = next(iter(x.devices()), None) if hasattr(x, "devices") else None
    device = str(dev) if dev else "unknown"
    if mesh_desc:
        device += f" mesh={mesh_desc}"
    return raw, params, x, {"train_ms": train_ms, "score_ms": score_ms,
                            "device": device}


def anomaly_init(seed: int, feat: int | None = None):
    import jax

    from . import anomaly

    return anomaly.init_params(jax.random.key(seed),
                               feat=feat or anomaly.FEATURES)


def score_windows(X: np.ndarray, keys: list[F.WindowKey], *,
                  train_steps: int = TRAIN_STEPS, lr: float = 1e-2,
                  seed: int = 0) -> ScoreReport:
    """Fit on all windows (denoising objective), score all windows."""
    raw, _, _, t = _fit_and_score(X, train_steps=train_steps, lr=lr, seed=seed)
    z = _robust_z(raw)
    return ScoreReport(
        keys=keys, raw=raw, z=z, agents=F.summarize(keys, z),
        train_steps=train_steps, train_ms=t["train_ms"],
        score_ms=t["score_ms"], device=t["device"],
    )


def bench_lane(records: list[dict], *, train_steps: int = 100,
               reps: int = 20) -> dict:
    """Featurize + fit + steady-state score timing for bench.py: the
    SAME pipeline `monitor anomalies` and AnomalyWatch run (denoising
    fit), so the bench cannot drift from the product path.  On a
    multi-device backend the fit/score run sharded over the full
    fleet mesh -- the pod earns its hardware here, not on one chip."""
    import jax

    t0 = time.perf_counter()
    keys, X = F.featurize(records)
    featurize_ms = (time.perf_counter() - t0) * 1000.0
    mesh = None
    if len(jax.devices()) > 1:
        from . import anomaly

        mesh = anomaly.fleet_mesh()
    raw, params, x, t = _fit_and_score(X, train_steps=train_steps,
                                       lr=1e-2, seed=0, mesh=mesh)
    _, score_fn = _jitted()
    jax.block_until_ready(score_fn(params, x))   # warm
    steps = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(score_fn(params, x))
        steps.append(time.perf_counter() - t0)
    steps.sort()
    return {
        "windows": len(keys),
        "featurize_ms": round(featurize_ms, 1),
        "train_ms": round(t["train_ms"], 1),
        "train_steps": train_steps,
        "score_step_us": round(steps[len(steps) // 2] * 1e6, 1),
        "device": t["device"],
    }


def score_file(path: str | Path, *, window_s: int = F.WINDOW_S,
               train_steps: int = TRAIN_STEPS) -> ScoreReport | None:
    """Featurize + score one egress jsonl; None when it yields no windows."""
    keys, X = F.featurize(F.load_jsonl(path), window_s=window_s)
    if not keys:
        return None
    return score_windows(X, keys, train_steps=train_steps)


class AnomalyWatch:
    """Background re-scorer for the loop dashboard / scheduler.

    Tails the egress jsonl incrementally (byte offset remembered across
    polls; cost is O(new bytes), with a bounded record window), keeps
    the latest per-agent z-scores, and records which agents cross
    ANOMALY_Z.  All the render path touches is a dict under a lock.
    """

    MAX_RECORDS = 100_000

    def __init__(self, egress_path: Path, *, interval_s: float = 15.0,
                 window_s: int = F.WINDOW_S, train_steps: int = 60,
                 on_anomaly=None, on_error=None):
        import collections

        self.egress_path = Path(egress_path)
        self.interval_s = interval_s
        self.window_s = window_s
        self.train_steps = train_steps
        self.on_anomaly = on_anomaly or (lambda agent, z: None)
        self.on_error = on_error or (lambda msg: None)
        self._records: collections.deque = collections.deque(
            maxlen=self.MAX_RECORDS)
        from ..monitor.ledger import TailState

        self._tail = TailState()
        self._scores: dict[str, F.AgentScore] = {}
        self._flagged: set[str] = set()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.last_error = ""

    # ------------------------------------------------------------- surface

    def scores(self) -> dict[str, F.AgentScore]:
        with self._lock:
            return dict(self._scores)

    def score_for(self, agent_or_container: str) -> F.AgentScore | None:
        """Match loop agent names against container-named score rows.
        Container names are dot-separated (``clawker.<proj>.<agent>``),
        so match whole segments -- 'loop-1' must never pick up
        'clawker.p.loop-10'."""
        if not agent_or_container:
            return None
        with self._lock:
            hit = self._scores.get(agent_or_container)
            if hit is not None:
                return hit
            for name, sc in self._scores.items():
                if agent_or_container in name.split("."):
                    return sc
        return None

    # ------------------------------------------------------------ lifecycle

    @property
    def _offset(self) -> int:
        """Consumed-bytes cursor (tests/introspection)."""
        return self._tail.offset

    def _tail_new_records(self) -> None:
        """Incremental tail via the shared crash-evidence reader
        (monitor/ledger.tail_jsonl): a netlogger that died mid-line
        leaves a torn tail that is SKIPPED, not fatal, exactly like the
        flight recorder's and journal's readers.  On truncation/rotation
        the cursor resets and the bounded record window is dropped with
        it (the file's records are the window's source of truth)."""
        from ..monitor.ledger import tail_jsonl

        resets = self._tail.resets
        recs = tail_jsonl(self.egress_path, self._tail)
        if self._tail.resets != resets:
            self._records.clear()
        self._records.extend(recs)

    def refresh_once(self) -> int:
        """Synchronous tail + re-score; returns number of scored windows."""
        try:
            self._tail_new_records()
            if not self._records:
                return 0
            keys, X = F.featurize(self._records, window_s=self.window_s)
            if not keys:
                return 0
            rep = score_windows(X, keys, train_steps=self.train_steps)
        except Exception as e:  # noqa: BLE001 - watcher must not die
            msg = f"{e.__class__.__name__}: {e}"
            if msg != self.last_error:   # surface each distinct failure once
                self.last_error = msg
                self.on_error(msg)
            return 0
        self.last_error = ""   # recovered: a recurring failure re-fires
        with self._lock:
            self._scores = {a.agent: a for a in rep.agents}
            newly = [a for a in rep.agents
                     if a.latest >= ANOMALY_Z and a.agent not in self._flagged]
            self._flagged.update(a.agent for a in newly)
        for a in newly:
            self.on_anomaly(a.agent, a.latest)
        return len(rep.keys)

    def start(self) -> "AnomalyWatch":
        self._thread = threading.Thread(target=self._loop,
                                        name="anomaly-watch", daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.refresh_once()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(2.0)
