"""Anomaly runtime: train-on-the-fleet, score, and watch.

Glue between the host-side featurizer (features.py) and the TPU model
(anomaly.py): ``score_windows`` fits the autoencoder on the window set
(the fleet's behavior is its own normal profile -- self-supervised) and
returns per-window reconstruction-error scores normalized as robust
z-scores; ``AnomalyWatch`` re-scores an egress jsonl on an interval for
the loop dashboard / scheduler without blocking their render paths.

jax is imported lazily inside functions so the CLI, scheduler and
dashboard stay importable (and fast) on hosts without an accelerator;
``jax_available()`` gates callers.

Parity reference: net-new (VERDICT r4 task 2 / __graft_entry__
contract: "the fleet-telemetry anomaly model used by `clawker monitor
anomalies` and the loop scheduler").
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from pathlib import Path

import numpy as np

from . import features as F

TRAIN_STEPS = 120
ANOMALY_Z = 3.5          # robust z-score threshold for "anomalous"


def jax_available() -> bool:
    try:
        import jax  # noqa: F401

        return True
    except Exception:  # noqa: BLE001 - any import failure means "no"
        return False


@dataclass
class ScoreReport:
    keys: list[F.WindowKey]
    raw: np.ndarray          # per-window reconstruction error
    z: np.ndarray            # robust z-score of raw
    agents: list[F.AgentScore]   # per-agent fold of z
    train_steps: int
    train_ms: float
    score_ms: float
    device: str


def _robust_z(raw: np.ndarray) -> np.ndarray:
    """Median/MAD z-scores: a few hot windows must not drag the scale."""
    if raw.size == 0:
        return raw
    med = float(np.median(raw))
    mad = float(np.median(np.abs(raw - med)))
    scale = 1.4826 * mad if mad > 0 else (float(raw.std()) or 1.0)
    return (raw - med) / scale


_PAD_BUCKET = 128    # rows padded up to a multiple of this: stable jit shapes
_jit_cache: dict = {}


def _standardize(X: np.ndarray) -> np.ndarray:
    """Zero-mean/unit-var per feature over the window set, so the
    reconstruction error weights dimensions evenly."""
    mu = X.mean(axis=0) if len(X) else np.zeros(X.shape[1], np.float32)
    sd = X.std(axis=0) if len(X) else np.ones(X.shape[1], np.float32)
    sd = np.where(sd < 1e-6, 1.0, sd).astype(np.float32)
    return ((X - mu) / sd).astype(np.float32)


def _jitted():
    """Module-level jitted fit/score: one compilation per input shape,
    shared by every AnomalyWatch poll and CLI run in the process."""
    if "fit" not in _jit_cache:
        import jax

        from . import anomaly

        def fit(params, x, noises, lr):
            # noises: [steps, n, feat], generated host-side -- keeps
            # threefry out of the compiled program (see
            # anomaly.denoise_step_with_noise)
            def body(p, noise):
                p, loss = anomaly.denoise_step_with_noise(p, x, noise, lr=lr)
                return p, loss

            return jax.lax.scan(body, params, noises)

        _jit_cache["fit"] = jax.jit(fit)
        _jit_cache["score"] = jax.jit(anomaly.score)
    return _jit_cache["fit"], _jit_cache["score"]


def _fit_and_score(X: np.ndarray, *, train_steps: int, lr: float, seed: int):
    """-> (raw_scores[n], params, x_padded, timings).  Rows are padded by
    edge-replication up to _PAD_BUCKET multiples so a growing stream
    reuses compilations; padded scores are sliced off."""
    import jax
    import jax.numpy as jnp

    n = len(X)
    padded = max(_PAD_BUCKET, -(-n // _PAD_BUCKET) * _PAD_BUCKET)
    Xn = _standardize(X)
    if padded != n:
        pad = Xn[np.arange(padded - n) % max(n, 1)] if n else np.zeros(
            (padded, X.shape[1]), np.float32)
        Xn = np.concatenate([Xn, pad], axis=0)

    fit, score_fn = _jitted()
    params = anomaly_init(seed)
    x = jnp.asarray(Xn)
    # the whole noise tensor as ONE un-jitted device op: threefry stays
    # out of the compiled scan (pathological compile on tunneled
    # backends) without shipping tens of MB host->device per fit
    noises = jax.random.normal(jax.random.key(seed + 1),
                               (train_steps,) + Xn.shape, jnp.float32)

    t0 = time.perf_counter()
    params, losses = fit(params, x, noises, lr)
    jax.block_until_ready(losses)
    train_ms = (time.perf_counter() - t0) * 1000.0

    t0 = time.perf_counter()
    raw = np.asarray(score_fn(params, x))[:n]
    score_ms = (time.perf_counter() - t0) * 1000.0
    dev = next(iter(x.devices()), None) if hasattr(x, "devices") else None
    return raw, params, x, {"train_ms": train_ms, "score_ms": score_ms,
                            "device": str(dev) if dev else "unknown"}


def anomaly_init(seed: int):
    import jax

    from . import anomaly

    return anomaly.init_params(jax.random.key(seed))


def score_windows(X: np.ndarray, keys: list[F.WindowKey], *,
                  train_steps: int = TRAIN_STEPS, lr: float = 1e-2,
                  seed: int = 0) -> ScoreReport:
    """Fit on all windows (denoising objective), score all windows."""
    raw, _, _, t = _fit_and_score(X, train_steps=train_steps, lr=lr, seed=seed)
    z = _robust_z(raw)
    return ScoreReport(
        keys=keys, raw=raw, z=z, agents=F.summarize(keys, z),
        train_steps=train_steps, train_ms=t["train_ms"],
        score_ms=t["score_ms"], device=t["device"],
    )


def bench_lane(records: list[dict], *, train_steps: int = 100,
               reps: int = 20) -> dict:
    """Featurize + fit + steady-state score timing for bench.py: the
    SAME pipeline `monitor anomalies` and AnomalyWatch run (denoising
    fit), so the bench cannot drift from the product path."""
    import jax

    t0 = time.perf_counter()
    keys, X = F.featurize(records)
    featurize_ms = (time.perf_counter() - t0) * 1000.0
    raw, params, x, t = _fit_and_score(X, train_steps=train_steps,
                                       lr=1e-2, seed=0)
    _, score_fn = _jitted()
    jax.block_until_ready(score_fn(params, x))   # warm
    steps = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(score_fn(params, x))
        steps.append(time.perf_counter() - t0)
    steps.sort()
    return {
        "windows": len(keys),
        "featurize_ms": round(featurize_ms, 1),
        "train_ms": round(t["train_ms"], 1),
        "train_steps": train_steps,
        "score_step_us": round(steps[len(steps) // 2] * 1e6, 1),
        "device": t["device"],
    }


def score_file(path: str | Path, *, window_s: int = F.WINDOW_S,
               train_steps: int = TRAIN_STEPS) -> ScoreReport | None:
    """Featurize + score one egress jsonl; None when it yields no windows."""
    keys, X = F.featurize(F.load_jsonl(path), window_s=window_s)
    if not keys:
        return None
    return score_windows(X, keys, train_steps=train_steps)


class AnomalyWatch:
    """Background re-scorer for the loop dashboard / scheduler.

    Tails the egress jsonl incrementally (byte offset remembered across
    polls; cost is O(new bytes), with a bounded record window), keeps
    the latest per-agent z-scores, and records which agents cross
    ANOMALY_Z.  All the render path touches is a dict under a lock.
    """

    MAX_RECORDS = 100_000

    def __init__(self, egress_path: Path, *, interval_s: float = 15.0,
                 window_s: int = F.WINDOW_S, train_steps: int = 60,
                 on_anomaly=None, on_error=None):
        import collections

        self.egress_path = Path(egress_path)
        self.interval_s = interval_s
        self.window_s = window_s
        self.train_steps = train_steps
        self.on_anomaly = on_anomaly or (lambda agent, z: None)
        self.on_error = on_error or (lambda msg: None)
        self._records: collections.deque = collections.deque(
            maxlen=self.MAX_RECORDS)
        self._offset = 0
        self._carry = b""
        self._scores: dict[str, F.AgentScore] = {}
        self._flagged: set[str] = set()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.last_error = ""

    # ------------------------------------------------------------- surface

    def scores(self) -> dict[str, F.AgentScore]:
        with self._lock:
            return dict(self._scores)

    def score_for(self, agent_or_container: str) -> F.AgentScore | None:
        """Match loop agent names against container-named score rows.
        Container names are dot-separated (``clawker.<proj>.<agent>``),
        so match whole segments -- 'loop-1' must never pick up
        'clawker.p.loop-10'."""
        if not agent_or_container:
            return None
        with self._lock:
            hit = self._scores.get(agent_or_container)
            if hit is not None:
                return hit
            for name, sc in self._scores.items():
                if agent_or_container in name.split("."):
                    return sc
        return None

    # ------------------------------------------------------------ lifecycle

    def _tail_new_records(self) -> None:
        """Read bytes past the remembered offset; reset on truncation."""
        try:
            size = self.egress_path.stat().st_size
        except OSError:
            return
        if size < self._offset:      # rotated/truncated: start over
            self._offset = 0
            self._carry = b""
            self._records.clear()
        if size == self._offset:
            return
        try:
            with open(self.egress_path, "rb") as f:
                f.seek(self._offset)
                chunk = f.read(size - self._offset)
        except OSError:
            return
        self._offset += len(chunk)
        data = self._carry + chunk
        lines = data.split(b"\n")
        self._carry = lines.pop()    # possibly-partial last line
        import json as _json

        for line in lines:
            try:
                rec = _json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict):
                self._records.append(rec)

    def refresh_once(self) -> int:
        """Synchronous tail + re-score; returns number of scored windows."""
        try:
            self._tail_new_records()
            if not self._records:
                return 0
            keys, X = F.featurize(self._records, window_s=self.window_s)
            if not keys:
                return 0
            rep = score_windows(X, keys, train_steps=self.train_steps)
        except Exception as e:  # noqa: BLE001 - watcher must not die
            msg = f"{e.__class__.__name__}: {e}"
            if msg != self.last_error:   # surface each distinct failure once
                self.last_error = msg
                self.on_error(msg)
            return 0
        self.last_error = ""   # recovered: a recurring failure re-fires
        with self._lock:
            self._scores = {a.agent: a for a in rep.agents}
            newly = [a for a in rep.agents
                     if a.latest >= ANOMALY_Z and a.agent not in self._flagged]
            self._flagged.update(a.agent for a in newly)
        for a in newly:
            self.on_anomaly(a.agent, a.latest)
        return len(rep.keys)

    def start(self) -> "AnomalyWatch":
        self._thread = threading.Thread(target=self._loop,
                                        name="anomaly-watch", daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.refresh_once()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(2.0)
