"""Egress-anomaly autoencoder: jit/pjit-able scoring + training.

Feature vectors summarize an agent's egress behavior over a sliding window
(decision counts per verdict, unique domains, bytes, DNS rate, burst shape
-- assembled host-side from the netlogger event stream).  A two-layer
autoencoder learns the fleet's normal profile; reconstruction error is the
anomaly score.  Everything is static-shaped, bfloat16 on the matmul path,
and sharded: batch over the ``data`` (fleet) axis, hidden features over the
``model`` axis, so scoring a whole pod's agents is one SPMD program.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

FEATURES = 32   # per-window egress feature vector size
HIDDEN = 128    # autoencoder bottleneck width (MXU-friendly)


class AnomalyParams(NamedTuple):
    w_enc: jax.Array   # [FEATURES, HIDDEN]
    b_enc: jax.Array   # [HIDDEN]
    w_dec: jax.Array   # [HIDDEN, FEATURES]
    b_dec: jax.Array   # [FEATURES]


def init_params(key: jax.Array, feat: int = FEATURES, hidden: int = HIDDEN) -> AnomalyParams:
    k1, k2 = jax.random.split(key)
    scale_e = (2.0 / feat) ** 0.5
    scale_d = (2.0 / hidden) ** 0.5
    return AnomalyParams(
        w_enc=(jax.random.normal(k1, (feat, hidden)) * scale_e).astype(jnp.float32),
        b_enc=jnp.zeros((hidden,), jnp.float32),
        w_dec=(jax.random.normal(k2, (hidden, feat)) * scale_d).astype(jnp.float32),
        b_dec=jnp.zeros((feat,), jnp.float32),
    )


def _reconstruct(params: AnomalyParams, x: jax.Array) -> jax.Array:
    # bfloat16 matmuls (MXU path), float32 accumulation/output
    h = jnp.dot(
        x.astype(jnp.bfloat16),
        params.w_enc.astype(jnp.bfloat16),
        preferred_element_type=jnp.float32,
    ) + params.b_enc
    h = jax.nn.gelu(h)
    r = jnp.dot(
        h.astype(jnp.bfloat16),
        params.w_dec.astype(jnp.bfloat16),
        preferred_element_type=jnp.float32,
    ) + params.b_dec
    return r


def score(params: AnomalyParams, x: jax.Array) -> jax.Array:
    """Per-agent anomaly score: mean squared reconstruction error.

    x: [batch, FEATURES] window features; returns [batch] scores.
    """
    r = _reconstruct(params, x)
    return jnp.mean(jnp.square(r - x), axis=-1)


def _loss(params: AnomalyParams, x: jax.Array) -> jax.Array:
    return jnp.mean(jnp.square(_reconstruct(params, x) - x))


def train_step(
    params: AnomalyParams, x: jax.Array, lr: float = 1e-3
) -> tuple[AnomalyParams, jax.Array]:
    """One SGD step on the fleet's pooled windows (dp over data axis; the
    mean-gradient psum is inserted by XLA from the shardings)."""
    loss, grads = jax.value_and_grad(_loss)(params, x)
    new = AnomalyParams(*(p - lr * g for p, g in zip(params, grads)))
    return new, loss


def denoise_step(
    params: AnomalyParams, x: jax.Array, key: jax.Array,
    lr: float = 1e-3, sigma: float = 0.25,
) -> tuple[AnomalyParams, jax.Array]:
    """One denoising SGD step: reconstruct the CLEAN window from a noised
    input.  With small fleets (few windows) a plain autoencoder has
    enough capacity to memorize the anomalies it is supposed to flag;
    the denoising objective forces it to learn the fleet manifold
    instead, so off-manifold windows keep a high reconstruction error.
    Same jit/pjit shape as train_step (noise is elementwise, fused)."""
    noise = jax.random.normal(key, x.shape, x.dtype)
    return denoise_step_with_noise(params, x, noise, lr=lr, sigma=sigma)


def denoise_step_with_noise(
    params: AnomalyParams, x: jax.Array, noise: jax.Array,
    lr: float = 1e-3, sigma: float = 0.25,
) -> tuple[AnomalyParams, jax.Array]:
    """Denoising step with CALLER-SUPPLIED unit noise.  The scoring
    runtime precomputes the whole noise tensor host-side and scans over
    it: in-program threefry made the fit's compile pathologically slow
    on tunneled backends, and the objective does not care where the
    gaussians came from."""
    noisy = x + sigma * noise

    def loss_fn(p: AnomalyParams) -> jax.Array:
        return jnp.mean(jnp.square(_reconstruct(p, noisy) - x))

    loss, grads = jax.value_and_grad(loss_fn)(params)
    new = AnomalyParams(*(p - lr * g for p, g in zip(params, grads)))
    return new, loss


# ----------------------------------------------------------------- sharding

def fleet_mesh(n_devices: int | None = None) -> Mesh:
    """2D mesh: ``data`` (fleet/batch) x ``model`` (hidden features).

    The model axis is 2 when the device count allows, exercising tensor
    sharding of the hidden dimension; otherwise 1.
    """
    devs = jax.devices()[: n_devices or len(jax.devices())]
    n = len(devs)
    model = 2 if n % 2 == 0 and n >= 2 else 1
    data = n // model
    import numpy as np

    return Mesh(np.array(devs).reshape(data, model), ("data", "model"))


def shard_params(params: AnomalyParams, mesh: Mesh) -> AnomalyParams:
    """Hidden dim sharded over ``model`` (tp); biases/outputs replicated."""
    specs = AnomalyParams(
        w_enc=P(None, "model"),
        b_enc=P("model"),
        w_dec=P("model", None),
        b_dec=P(None),
    )
    return AnomalyParams(
        *(
            jax.device_put(p, NamedSharding(mesh, s))
            for p, s in zip(params, specs)
        )
    )


def shard_batch(x: jax.Array, mesh: Mesh) -> jax.Array:
    return jax.device_put(x, NamedSharding(mesh, P("data", None)))


def shard_noise(noises: jax.Array, mesh: Mesh) -> jax.Array:
    """The fit scan's [steps, n, feat] noise tensor, sharded like the
    batch it perturbs (rows over ``data``); the steps axis is the scan
    axis and stays unsharded."""
    return jax.device_put(noises, NamedSharding(mesh, P(None, "data", None)))
