"""CLI state store + release update check.

Parity reference: internal/state (CLI state store in the XDG state dir)
and internal/update (GitHub release check with a TTL cache; the check
runs in the background and surfaces a one-line teaser, never blocks a
command -- internal/clawker/cmd.go:79-120).

The fetcher is a seam: the default hits the GitHub releases API, tests
inject a canned responder, and air-gapped hosts (TPU-VM workers with
deny-by-default egress) simply get a cache miss and stay quiet.
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from urllib import error as urlerror
from urllib import request as urlrequest

from . import __version__, consts, logsetup
from .util import xdg
from .util.fs import atomic_write, file_lock

log = logsetup.get("state")

UPDATE_TTL_S = 24 * 3600
RELEASES_URL = "https://api.github.com/repos/clawker-tpu/clawker-tpu/releases/latest"


class StateStore:
    """Small JSON key/value store in the XDG state dir (atomic writes)."""

    def __init__(self, path: Path | None = None):
        self.path = path or (xdg.state_dir() / "cli-state.json")

    def _load(self) -> dict:
        try:
            return json.loads(self.path.read_text())
        except (OSError, ValueError):
            return {}

    def get(self, key: str, default=None):
        return self._load().get(key, default)

    def set(self, key: str, value) -> None:
        # locked read-modify-write: the background notices thread and
        # command-path writers (e.g. the bundle auto-update TTL stamp)
        # update different keys concurrently; an unlocked RMW would let
        # one writer silently drop the other's key (lost update)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        with file_lock(self.path):   # file_lock appends its own .lock
            data = self._load()
            data[key] = value
            atomic_write(self.path, json.dumps(data, indent=1).encode())

    def delete(self, key: str) -> None:
        self.path.parent.mkdir(parents=True, exist_ok=True)
        with file_lock(self.path):
            data = self._load()
            if key in data:
                del data[key]
                atomic_write(self.path, json.dumps(data, indent=1).encode())


def _default_fetch(timeout: float = 3.0) -> str:
    req = urlrequest.Request(RELEASES_URL,
                             headers={"Accept": "application/vnd.github+json"})
    try:
        with urlrequest.urlopen(req, timeout=timeout) as r:
            return str(json.loads(r.read()).get("tag_name") or "")
    except (urlerror.URLError, OSError, ValueError):
        return ""


def _newer(latest: str, current: str) -> bool:
    def parse(v: str) -> tuple:
        try:
            return tuple(int(x) for x in v.lstrip("v").split("."))
        except ValueError:
            return ()
    lp, cp = parse(latest), parse(current)
    return bool(lp and cp and lp > cp)


def check_for_update(*, state: StateStore | None = None, fetch=_default_fetch,
                     now: float | None = None) -> str:
    """Returns a teaser line when a newer release exists, else "".

    TTL-cached: at most one network probe per day; failures cache an
    empty result so offline hosts never retry per command.
    """
    state = state or StateStore()
    now = time.time() if now is None else now
    cached = state.get("update_check") or {}
    if "at" in cached and now - float(cached["at"]) < UPDATE_TTL_S:
        latest = str(cached.get("latest") or "")
    else:
        latest = fetch()
        state.set("update_check", {"at": now, "latest": latest})
    if latest and _newer(latest, __version__):
        return (f"{consts.PRODUCT} {latest} is available "
                f"(you have {__version__})")
    return ""
