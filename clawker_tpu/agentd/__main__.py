from .daemon import main

raise SystemExit(main())
