"""agentd: the in-container daemon (session listener + PID-1 supervision).

Parity reference: clawkerd/ (SURVEY.md 2.9).  Split design: the PID-1
process-supervision core is the native ``clawker-supervisord`` binary
(native/agentsup/supervisor.cpp); this package is the mTLS session daemon
that rides beside it and the client used to drive the supervisor socket.
"""

from .supervisor_client import SupervisorClient, SupervisorError
