"""The agentd session daemon: mTLS listener + session command execution.

Parity reference: clawkerd/ (SURVEY.md 2.9) -- boot reads the bootstrap
files, listens with mutual TLS on :7700 (client cert required, CP CN
pinned, ClientAuth EKU), then serves one ``Session`` bidi stream at a time:
Hello/HelloAck carrying Initialized/CmdRunning so the CP skips completed
plans on reconnect; ShellCommand pipelines with per-stage uid/gid drop;
Stdin/CloseStdin/Signal; AgentReady (spawn the user CMD -- via the native
supervisor when present, else a direct child); AgentInitialized (persist the
init marker); RegisterRequired (the daemon's one outbound call).  Structured
audit events go to stderr as JSON lines; every worker thread is
exception-recovered (reference: recoverGoroutine on every goroutine).
"""

from __future__ import annotations

import json
import os
import signal
import socket
import ssl
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path

from .. import consts
from . import protocol
from .protocol import ConnectionClosed, ProtocolError, read_msg, write_msg

CP_COMMON_NAME = "clawker-controlplane"


def _audit(event: str, **fields) -> None:
    rec = {"ts": round(time.time(), 3), "event": event}
    rec.update(fields)
    print(json.dumps(rec, separators=(",", ":")), file=sys.stderr, flush=True)


@dataclass
class AgentdConfig:
    bootstrap_dir: Path = Path(consts.BOOTSTRAP_DIR)
    port: int = consts.AGENTD_PORT
    host: str = "0.0.0.0"
    supervisor_socket: str = ""          # empty -> direct-spawn fallback
    ready_file: Path = Path(consts.READY_FILE)
    init_marker: Path = Path(consts.INIT_MARKER)
    require_client_cert: bool = True
    pinned_client_cn: str = CP_COMMON_NAME
    # image CMD captured at ENTRYPOINT time: what AgentReady spawns when the
    # CP sends no explicit argv (reference: clawkerd runs the user CMD from
    # the image config on AgentReady)
    default_cmd: list[str] = field(default_factory=list)
    default_uid: int = 0
    default_gid: int = 0


@dataclass
class _ShellJob:
    id: str
    procs: list[subprocess.Popen] = field(default_factory=list)
    stdin_open: bool = True

    def first_stdin(self):
        return self.procs[0].stdin if self.procs else None


class Agentd:
    """One daemon instance; ``serve_forever`` accepts sequential sessions."""

    def __init__(self, cfg: AgentdConfig):
        self.cfg = cfg
        self._ssl = self._build_ssl_context()
        self._listener: socket.socket | None = None
        self._stop = threading.Event()
        self._jobs: dict[str, _ShellJob] = {}
        self._jobs_lock = threading.Lock()
        self._cmd_running = False
        self._cmd_lock = threading.Lock()
        self._direct_child: subprocess.Popen | None = None
        self.bound_port = 0  # actual port after bind (tests use 0)

    # ------------------------------------------------------------ TLS boot

    def _build_ssl_context(self) -> ssl.SSLContext:
        d = self.cfg.bootstrap_dir
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.minimum_version = ssl.TLSVersion.TLSv1_3
        ctx.load_cert_chain(d / "agent.crt", d / "agent.key")
        if self.cfg.require_client_cert:
            ctx.verify_mode = ssl.CERT_REQUIRED
            ctx.load_verify_locations(d / "ca.crt")
        return ctx

    @staticmethod
    def _peer_cn(tls_sock: ssl.SSLSocket) -> str:
        cert = tls_sock.getpeercert() or {}
        for rdn in cert.get("subject", ()):  # ((('commonName','x'),),)
            for key, value in rdn:
                if key == "commonName":
                    return value
        return ""

    # ------------------------------------------------------------- serving

    def serve_forever(self) -> None:
        ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        ls.bind((self.cfg.host, self.cfg.port))
        ls.listen(4)
        self.bound_port = ls.getsockname()[1]
        self._listener = ls
        self._write_ready()
        _audit("agentd_listening", port=self.bound_port)
        while not self._stop.is_set():
            try:
                raw, addr = ls.accept()
            except OSError:
                break  # listener closed by stop()
            try:
                tls = self._ssl.wrap_socket(raw, server_side=True)
            except (ssl.SSLError, OSError) as e:
                _audit("session_tls_rejected", error=str(e), peer=str(addr))
                raw.close()
                continue
            cn = self._peer_cn(tls)
            if self.cfg.require_client_cert and cn != self.cfg.pinned_client_cn:
                _audit("session_cn_rejected", cn=cn)
                tls.close()
                continue
            _audit("session_started", peer=str(addr), cn=cn)
            try:
                self._serve_session(tls)
            except (ConnectionClosed, ProtocolError) as e:
                _audit("session_ended", reason=str(e))
            except Exception as e:  # recovered: daemon must outlive sessions
                _audit("session_crashed", error=repr(e))
            finally:
                try:
                    tls.close()
                except OSError:
                    pass

    def stop(self) -> None:
        self._stop.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass

    def _write_ready(self) -> None:
        try:
            self.cfg.ready_file.parent.mkdir(parents=True, exist_ok=True)
            self.cfg.ready_file.write_text("ok\n")
        except OSError as e:
            _audit("ready_file_failed", error=str(e))

    # ------------------------------------------------------------- session

    def _serve_session(self, sock: ssl.SSLSocket) -> None:
        wlock = threading.Lock()  # output threads interleave with replies

        def send(msg: dict) -> None:
            with wlock:
                write_msg(sock, msg)

        while True:
            msg = read_msg(sock)
            t = msg["type"]
            if t == "hello":
                send(
                    {
                        "type": "hello_ack",
                        "initialized": self.cfg.init_marker.exists(),
                        "cmd_running": self._is_cmd_running(),
                        "pid": os.getpid(),
                    }
                )
            elif t == "shell":
                self._start_shell(msg, send)
            elif t == "stdin":
                self._feed_stdin(msg)
            elif t == "close_stdin":
                self._close_stdin(msg)
            elif t == "signal":
                self._signal_job(msg, send)
            elif t == "agent_ready":
                self._agent_ready(msg, send)
            elif t == "agent_initialized":
                self.cfg.init_marker.parent.mkdir(parents=True, exist_ok=True)
                self.cfg.init_marker.write_text(str(int(time.time())))
                _audit("agent_initialized")
                send({"type": "init_ack"})
            elif t == "register_required":
                self._register(msg, send)
            elif t == "bye":
                return
            else:
                send({"type": "error", "error": f"unknown command {t!r}"})

    # ---------------------------------------------------------- shell jobs

    def _start_shell(self, msg: dict, send) -> None:
        """Pipeline of stages; stage N stdout feeds stage N+1 stdin.
        Per-stage uid/gid drop happens in the child pre-exec (kernel drop),
        mirroring the reference's per-stage credential switch."""
        job_id = msg.get("id") or f"job-{int(time.time()*1000)}"
        stages = msg.get("stages") or []
        if not stages:
            send({"type": "error", "id": job_id, "error": "shell: no stages"})
            return
        env = dict(os.environ)
        env.update(msg.get("env") or {})
        cwd = msg.get("dir") or None
        job = _ShellJob(id=job_id)
        try:
            prev_out = None
            for i, st in enumerate(stages):
                preexec = self._preexec(int(st.get("uid") or 0), int(st.get("gid") or 0))
                p = subprocess.Popen(
                    st["argv"],
                    stdin=prev_out if prev_out is not None else subprocess.PIPE,
                    stdout=subprocess.PIPE,
                    stderr=subprocess.PIPE,
                    env=env,
                    cwd=cwd,
                    preexec_fn=preexec,
                    start_new_session=True,
                )
                if prev_out is not None:
                    prev_out.close()  # parent's copy; pipe lives in children
                prev_out = p.stdout if i < len(stages) - 1 else None
                job.procs.append(p)
        except (OSError, ValueError) as e:
            for p in job.procs:
                p.kill()
            send({"type": "error", "id": job_id, "error": f"spawn: {e}"})
            return
        with self._jobs_lock:
            self._jobs[job_id] = job
        send({"type": "started", "id": job_id})
        _audit("shell_command_started", id=job_id, stages=len(stages))

        def pump(stage: int, fd: int, stream) -> None:
            try:
                for chunk in iter(lambda: stream.read(32768), b""):
                    send(
                        {
                            "type": "output",
                            "id": job_id,
                            "stage": stage,
                            "fd": fd,
                            "data": protocol.b64(chunk),
                        }
                    )
            except (OSError, ValueError):
                pass

        pumps: list[threading.Thread] = []

        def wait_all() -> None:
            try:
                codes = []
                for p in job.procs:
                    code = p.wait()
                    if code < 0:  # signal death -> bash convention
                        code = 128 - code
                    codes.append(code)
                # join output pumps BEFORE completion frames: a process can
                # exit while its last pipe chunks are still unread, and the
                # client stops listening at `done`
                for t in pumps:
                    t.join()
                for i, code in enumerate(codes):
                    send({"type": "stage_exit", "id": job_id, "stage": i, "code": code})
                send({"type": "done", "id": job_id, "code": codes[-1]})
                _audit("shell_command_done", id=job_id, code=codes[-1])
            except Exception as e:
                _audit("shell_wait_crashed", id=job_id, error=repr(e))
            finally:
                with self._jobs_lock:
                    self._jobs.pop(job_id, None)

        last = job.procs[-1]
        pumps.append(
            threading.Thread(target=pump, args=(len(stages) - 1, 1, last.stdout), daemon=True)
        )
        for i, p in enumerate(job.procs):
            pumps.append(threading.Thread(target=pump, args=(i, 2, p.stderr), daemon=True))
        for t in pumps:
            t.start()
        threading.Thread(target=wait_all, daemon=True).start()

    @staticmethod
    def _preexec(uid: int, gid: int):
        if uid <= 0 and gid <= 0:
            return None

        def fn() -> None:
            if gid > 0:
                os.setgroups([])
                os.setgid(gid)
            if uid > 0:
                os.setuid(uid)

        return fn

    def _feed_stdin(self, msg: dict) -> None:
        with self._jobs_lock:
            job = self._jobs.get(msg.get("id", ""))
        if job and job.stdin_open and job.first_stdin():
            try:
                job.first_stdin().write(protocol.unb64(msg.get("data", "")))
                job.first_stdin().flush()
            except (OSError, ValueError):
                pass

    def _close_stdin(self, msg: dict) -> None:
        with self._jobs_lock:
            job = self._jobs.get(msg.get("id", ""))
        if job and job.first_stdin():
            job.stdin_open = False
            try:
                job.first_stdin().close()
            except OSError:
                pass

    def _signal_job(self, msg: dict, send) -> None:
        with self._jobs_lock:
            job = self._jobs.get(msg.get("id", ""))
        if not job:
            send({"type": "error", "id": msg.get("id", ""), "error": "no such job"})
            return
        signum = int(msg.get("signum") or signal.SIGTERM)
        for p in job.procs:
            if p.poll() is None:
                try:
                    os.killpg(p.pid, signum)
                except (OSError, ProcessLookupError):
                    pass

    # --------------------------------------------------------- user CMD

    def _is_cmd_running(self) -> bool:
        if self.cfg.supervisor_socket:
            try:
                from .supervisor_client import SupervisorClient

                with SupervisorClient(self.cfg.supervisor_socket) as c:
                    kind, _ = c.status()
                return kind == "running"
            except Exception:
                return False
        return self._direct_child is not None and self._direct_child.poll() is None

    def _agent_ready(self, msg: dict, send) -> None:
        """Spawn the user CMD exactly once (CAS).  Through the native
        supervisor when configured; else a direct detached child (tests,
        images without the supervisor)."""
        with self._cmd_lock:
            if self._cmd_running or self._is_cmd_running():
                send({"type": "error", "error": "user command already running"})
                return
            argv = msg.get("argv") or list(self.cfg.default_cmd)
            if not argv:
                send({"type": "error", "error": "agent_ready: empty argv and no image CMD"})
                return
            uid = int(msg.get("uid") or self.cfg.default_uid)
            gid = int(msg.get("gid") or self.cfg.default_gid)
            env = msg.get("env") or {}
            cwd = msg.get("cwd") or consts.WORKSPACE_DIR
            if not Path(cwd).is_dir():
                cwd = ""  # supervisor skips chdir; direct path inherits ours
            try:
                if self.cfg.supervisor_socket:
                    from .supervisor_client import SupervisorClient

                    full_env = dict(os.environ)
                    full_env.update(env)
                    with SupervisorClient(self.cfg.supervisor_socket) as c:
                        pid = c.spawn(argv, uid=uid, gid=gid, cwd=cwd, env=full_env)
                else:
                    child_env = dict(os.environ)
                    child_env.update(env)
                    # grandfathered no-blocking-under-lock finding
                    # (analysis-baseline.json): the spawn-exactly-once CAS
                    # must be atomic with _cmd_running, and this in-container
                    # daemon serves ONE session connection -- nothing
                    # contends _cmd_lock while the fork runs.  Splitting the
                    # CAS to move Popen out would trade a real double-spawn
                    # hazard for a theoretical stall.
                    self._direct_child = subprocess.Popen(
                        argv,
                        env=child_env,
                        cwd=cwd or None,
                        preexec_fn=self._preexec(uid, gid),
                        start_new_session=True,
                    )
                    pid = self._direct_child.pid
            except Exception as e:
                send({"type": "error", "error": f"agent_ready: {e}"})
                return
            self._cmd_running = True
            _audit("agent_ready", pid=pid)
            send({"type": "ready_ack", "pid": pid})

    # ----------------------------------------------------------- register

    def _register(self, msg: dict, send) -> None:
        """The daemon's single outbound call: present the assertion JWT to
        the CP AgentService (reference: clawkerd register.go)."""
        from .register import register_with_cp

        try:
            register_with_cp(
                self.cfg.bootstrap_dir,
                host=msg.get("cp_host", ""),
                port=int(msg.get("cp_port") or consts.CP_AGENT_PORT),
            )
            send({"type": "register_done", "ok": True})
            _audit("registered")
        except Exception as e:
            send({"type": "register_done", "ok": False, "error": str(e)})
            _audit("register_failed", error=str(e))


def main(argv: list[str] | None = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="clawker-agentd")
    ap.add_argument("--bootstrap-dir", default=consts.BOOTSTRAP_DIR)
    ap.add_argument("--port", type=int, default=consts.AGENTD_PORT)
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--supervisor-socket", default="")
    ap.add_argument("--ready-file", default=consts.READY_FILE)
    ap.add_argument("--init-marker", default=consts.INIT_MARKER)
    ap.add_argument("--default-uid", type=int, default=0)
    ap.add_argument("--default-gid", type=int, default=0)
    # everything after --default-cmd is the image CMD Docker appended to the
    # supervisor ENTRYPOINT and the supervisor passed through to us
    ap.add_argument("--default-cmd", nargs=argparse.REMAINDER, default=[])
    args = ap.parse_args(argv)
    cfg = AgentdConfig(
        bootstrap_dir=Path(args.bootstrap_dir),
        port=args.port,
        host=args.host,
        supervisor_socket=args.supervisor_socket,
        ready_file=Path(args.ready_file),
        init_marker=Path(args.init_marker),
        default_cmd=list(args.default_cmd),
        default_uid=args.default_uid,
        default_gid=args.default_gid,
    )
    d = Agentd(cfg)
    try:
        d.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
