"""agentd's one outbound call: Register with the control plane.

Parity reference: clawkerd register.go -- on RegisterRequired the daemon
obtains a token (reference: Hydra client_credentials; this build: the
pre-minted assertion JWT from bootstrap material) and calls
AgentService.Register over mTLS so the CP binds the connection identity to
the agent row.  The CP answers over the same framed-JSON protocol the
session uses.
"""

from __future__ import annotations

import socket
import ssl
from pathlib import Path

from .. import consts
from ..errors import ClawkerError
from .protocol import read_msg, write_msg


class RegisterError(ClawkerError):
    pass


def _client_context(bootstrap_dir: Path) -> ssl.SSLContext:
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.minimum_version = ssl.TLSVersion.TLSv1_3
    ctx.load_cert_chain(bootstrap_dir / "agent.crt", bootstrap_dir / "agent.key")
    ctx.load_verify_locations(bootstrap_dir / "ca.crt")
    # CA-signed identity matters, hostname does not (containers dial by IP)
    ctx.check_hostname = False
    ctx.verify_mode = ssl.CERT_REQUIRED
    return ctx


def register_with_cp(
    bootstrap_dir: Path,
    *,
    host: str,
    port: int = consts.CP_AGENT_PORT,
    timeout: float = 10.0,
) -> dict:
    """Present the assertion JWT; returns the CP's ack payload."""
    if not host:
        raise RegisterError("register: no control-plane host")
    jwt = (bootstrap_dir / "assertion.jwt").read_text().strip()
    ctx = _client_context(bootstrap_dir)
    with socket.create_connection((host, port), timeout=timeout) as raw:
        with ctx.wrap_socket(raw, server_hostname=host) as tls:
            write_msg(tls, {"type": "register", "assertion": jwt})
            reply = read_msg(tls)
    if reply.get("type") != "register_ack" or not reply.get("ok"):
        raise RegisterError(f"register rejected: {reply.get('error', reply)}")
    return reply
