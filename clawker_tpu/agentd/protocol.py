"""Session wire protocol: length-prefixed JSON frames over (m)TLS.

Parity reference: api/clawkerd/v1/clawkerd.proto (SURVEY.md 2.12) -- the
reference streams a protobuf ``Command``/``Response`` oneof over gRPC; this
build keeps the exact message taxonomy (Hello/Shell/Stdin/CloseStdin/
Signal/RegisterRequired/AgentReady/AgentInitialized and HelloAck/Started/
OutputChunk/StageExit/Done/Error/RegisterDone) as JSON objects framed by a
4-byte big-endian length, which stdlib ``ssl`` sockets carry without a gRPC
dependency.
"""

from __future__ import annotations

import base64
import json
import struct
import socket

from ..errors import ClawkerError

MAX_FRAME = 8 * 1024 * 1024


class ProtocolError(ClawkerError):
    pass


class ConnectionClosed(ProtocolError):
    pass


def write_msg(sock, msg: dict) -> None:
    data = json.dumps(msg, separators=(",", ":")).encode()
    if len(data) > MAX_FRAME:
        raise ProtocolError(f"frame too large: {len(data)}")
    sock.sendall(struct.pack(">I", len(data)) + data)


def _recv_exact(sock, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except (ConnectionResetError, BrokenPipeError, socket.timeout) as e:
            raise ConnectionClosed(str(e)) from None
        if not chunk:
            raise ConnectionClosed("peer closed")
        buf += chunk
    return buf


def read_msg(sock) -> dict:
    (length,) = struct.unpack(">I", _recv_exact(sock, 4))
    if length > MAX_FRAME:
        raise ProtocolError(f"frame too large: {length}")
    msg = json.loads(_recv_exact(sock, length))
    if not isinstance(msg, dict) or "type" not in msg:
        raise ProtocolError("malformed session message")
    return msg


def b64(data: bytes) -> str:
    return base64.b64encode(data).decode()


def unb64(s: str) -> bytes:
    return base64.b64decode(s)
