"""Client for the native clawker-supervisord control socket.

Wire format (native/agentsup/supervisor.cpp): netstring frames
``<len>:<payload>,`` with NUL-separated fields; field 0 is the verb on
requests and the status on replies.
"""

from __future__ import annotations

import socket
from pathlib import Path

from ..errors import ClawkerError


class SupervisorError(ClawkerError):
    pass


def _encode(fields: list[str]) -> bytes:
    payload = b"\x00".join(f.encode() for f in fields)
    return str(len(payload)).encode() + b":" + payload + b","


class _FrameReader:
    def __init__(self, sock: socket.socket):
        self._sock = sock
        self._buf = b""

    def read_frame(self, timeout: float | None = None) -> list[str]:
        self._sock.settimeout(timeout)
        while True:
            colon = self._buf.find(b":")
            if colon >= 0:
                length = int(self._buf[:colon])
                end = colon + 1 + length
                if len(self._buf) > end:
                    if self._buf[end : end + 1] != b",":
                        raise SupervisorError("malformed frame from supervisor")
                    payload = self._buf[colon + 1 : end]
                    self._buf = self._buf[end + 1 :]
                    return [f.decode() for f in payload.split(b"\x00")]
            chunk = self._sock.recv(4096)
            if not chunk:
                raise SupervisorError("supervisor closed the connection")
            self._buf += chunk


class SupervisorClient:
    """One connection to the supervisor socket; one blocking call at a time."""

    def __init__(self, sock_path: str | Path):
        self.path = str(sock_path)
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.connect(self.path)
        self._reader = _FrameReader(self._sock)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def _call(self, fields: list[str], timeout: float | None = 10.0) -> list[str]:
        self._sock.sendall(_encode(fields))
        reply = self._reader.read_frame(timeout)
        if reply and reply[0] == "ERR":
            raise SupervisorError(reply[1] if len(reply) > 1 else "supervisor error")
        return reply

    # ------------------------------------------------------------- verbs

    def spawn(
        self,
        argv: list[str],
        *,
        uid: int = 0,
        gid: int = 0,
        cwd: str = "",
        env: dict[str, str] | None = None,
    ) -> int:
        """Start the user CMD (single-shot; second spawn raises).  Returns pid."""
        fields = ["SPAWN", str(uid), str(gid), cwd]
        fields.extend(f"{k}={v}" for k, v in (env or {}).items())
        fields.append("--")
        fields.extend(argv)
        reply = self._call(fields)
        return int(reply[1])

    def signal(self, signum: int) -> None:
        self._call(["SIGNAL", str(signum)])

    def status(self) -> tuple[str, int]:
        """-> ("idle" | "running" | "exited", pid-or-exit-code)."""
        reply = self._call(["STATUS"])
        kind = reply[0].lower()
        val = int(reply[1]) if len(reply) > 1 else 0
        return kind, val

    def wait(self, timeout: float | None = None) -> int:
        """Block until the user CMD exits; returns its bash-convention code."""
        reply = self._call(["WAIT"], timeout=timeout)
        if reply[0] != "EXIT":
            raise SupervisorError(f"unexpected WAIT reply: {reply}")
        return int(reply[1])

    def shutdown(self, grace_ms: int = 5000) -> None:
        """TERM the user CMD; after ``grace_ms`` the watchdog SIGKILLs."""
        self._call(["SHUTDOWN", str(grace_ms)])
