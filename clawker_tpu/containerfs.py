"""Host harness-config staging for container injection.

Interprets a harness bundle's ``staging`` manifest -- explicit
host->container copy directives (glob-capable src, optional JSON key
allowlist, per-file skips, JSON path rewrites) -- into a temp staging
mirror that callers pack into the per-agent config volume.  Only host
state OUTSIDE the workspace is staged; the workspace arrives via mount.
Credentials are never copied from the host: the user authenticates in
the container and the token family persists in the config volume.

Degradation contract: a missing host source (no ~/.claude, no keyring,
fresh machine) is a debug-logged soft skip, never an error -- an agent
must start on a host with zero harness state.

Leaf module: imports stdlib + logsetup only.

Parity reference: internal/containerfs/containerfs.go
(ResolveHostMountSource :41, PrepareConfig :64, stageCopy :94,
guardWorkspaceSrc :185, filterJSONKeys :321, rewriteJSONPaths :450) --
semantics re-derived.
"""

from __future__ import annotations

import glob as _glob
import io
import json
import os
import re
import shutil
import tarfile
import tempfile
import time
from dataclasses import dataclass, field
from pathlib import Path

from . import logsetup

log = logsetup.get("containerfs")

_VAR_DEFAULT = re.compile(r"\$\{([A-Za-z_][A-Za-z0-9_]*)(?::-([^}]*))?\}")


class StagingError(ValueError):
    pass


@dataclass
class JsonRewrite:
    """One JSON path rewrite applied to a named file in a copied tree.

    ``rewrite`` tokens: ``prefix-swap`` (host home prefix -> container
    home prefix) and ``replace-with-workdir`` (entire value -> the
    container workdir)."""

    file: str = ""
    key: str = ""
    rewrite: str = "prefix-swap"


@dataclass
class CopySpec:
    src: str = ""
    dest: str = ""
    json_keys: list[str] = field(default_factory=list)
    skip: list[str] = field(default_factory=list)
    json_rewrites: list[JsonRewrite] = field(default_factory=list)


@dataclass
class Staging:
    copy: list[CopySpec] = field(default_factory=list)
    # declared credential material (keyring-backed files).  NEVER staged
    # by default: only when settings ``credentials.stage`` opts in
    # (reference internal/containerfs stages its keyring path
    # unconditionally -- the opt-in is this framework's divergence;
    # see README "Credential staging")
    credentials: list[CopySpec] = field(default_factory=list)

    @classmethod
    def from_raw(cls, raw: dict | None) -> "Staging":
        out = cls()
        for section, target in (("copy", out.copy),
                                ("credentials", out.credentials)):
            for c in (raw or {}).get(section) or []:
                if not isinstance(c, dict):
                    raise StagingError(
                        f"staging.{section} entry must be a mapping: {c!r}")
                target.append(CopySpec(
                    src=str(c.get("src") or ""),
                    dest=str(c.get("dest") or ""),
                    json_keys=[str(k) for k in c.get("json_keys") or []],
                    skip=[str(s) for s in c.get("skip") or []],
                    json_rewrites=[JsonRewrite(
                        file=str(r.get("file") or ""),
                        key=str(r.get("key") or ""),
                        rewrite=str(r.get("rewrite") or "prefix-swap"))
                        for r in c.get("json_rewrites") or []],
                ))
        return out


# ------------------------------------------------------------- expansion

def expand_host_path(src: str) -> str:
    """``~``, ``$VAR``, and shell-style ``${VAR:-fallback}``."""
    def sub(m: re.Match) -> str:
        val = os.environ.get(m.group(1))
        if val:
            return val
        return m.group(2) if m.group(2) is not None else ""

    expanded = _VAR_DEFAULT.sub(sub, src)
    expanded = os.path.expandvars(expanded)
    return os.path.expanduser(expanded)


def resolve_host_mount_source(src: str) -> tuple[str, bool]:
    """Expand a manifest mount src and stat it.  (path, False) when the
    directory is absent -- callers soft-skip the bind; a path that exists
    but is not a directory errors."""
    path = expand_host_path(src)
    if not os.path.exists(path):
        return "", False
    if not os.path.isdir(path):
        raise StagingError(f"{path} exists but is not a directory")
    return path, True


# --------------------------------------------------------------- staging

def prepare_config(staging: Staging, *, container_home: str,
                   container_work: str, host_project_root: str,
                   include_credentials: bool = False) -> tuple[Path, "callable"]:
    """Run every copy directive into a temp staging mirror.  Returns
    (staging_dir, cleanup); the staged layout mirrors the container home:
    each directive lands at ``<dir>/<dest>``.

    ``include_credentials`` additionally stages the manifest's declared
    credential material -- the settings-gated opt-in (credentials.stage)
    that makes ``loop --parallel N`` start N authenticated agents
    without N manual logins."""
    tmp = Path(tempfile.mkdtemp(prefix="clawker-config-"))

    def cleanup() -> None:
        shutil.rmtree(tmp, ignore_errors=True)

    specs = list(staging.copy)
    if include_credentials:
        specs += staging.credentials
    try:
        for c in specs:
            _stage_copy(c, tmp, container_home=container_home,
                        container_work=container_work,
                        host_project_root=host_project_root)
    except Exception:
        cleanup()
        raise
    return tmp, cleanup


def _stage_copy(c: CopySpec, root: Path, *, container_home: str,
                container_work: str, host_project_root: str) -> None:
    pattern = expand_host_path(c.src)
    globbed = _glob.has_magic(pattern)
    matches = sorted(_glob.glob(pattern, recursive=True)) if globbed else (
        [pattern] if os.path.exists(pattern) else [])
    if not matches:
        log.debug("staging source %s not found on host, skipping", pattern)
        return

    dest_rel = c.dest.strip("/")
    if not dest_rel or ".." in Path(dest_rel).parts:
        # interior '..' segments would escape the staging mirror and
        # write arbitrary host paths -- a third-party loose-tier harness
        # bundle must not get that power
        raise StagingError(f"staging dest {c.dest!r} must be home-relative")
    dest_is_dir = globbed or len(matches) > 1 or c.dest.endswith("/")

    for match in matches:
        _guard_workspace_src(match, host_project_root)
        dst = root / dest_rel
        if dest_is_dir:
            dst = dst / os.path.basename(match)
        if os.path.isdir(match):
            _copy_tree(match, dst, skip=c.skip)
            _apply_rewrites(dst, c.json_rewrites,
                            container_home=container_home,
                            container_work=container_work)
        else:
            dst.parent.mkdir(parents=True, exist_ok=True)
            if c.json_keys:
                body = _filter_json_keys(match, c.json_keys)
                if body is None:
                    continue  # unparseable json: skip, never stage secrets
                dst.write_bytes(body)
            else:
                shutil.copyfile(match, dst)


def _guard_workspace_src(src: str, host_project_root: str) -> None:
    """The workspace is mounted, never staged -- staging it would fork
    the live tree into a stale volume copy."""
    if not host_project_root:
        return
    try:
        real_src = os.path.realpath(src)
        real_root = os.path.realpath(host_project_root)
        if real_src == real_root or real_src.startswith(real_root + os.sep):
            raise StagingError(
                f"staging src {src} is inside the project workspace "
                f"({host_project_root}); the workspace arrives via mount")
    except OSError:
        pass


def _filter_json_keys(path: str, keys: list[str]) -> bytes | None:
    """Allowlist: only the listed top-level keys survive (e.g. the claude
    bundle stages only enabledPlugins from settings.json -- the rest can
    hold secrets and host-specific state)."""
    try:
        data = json.loads(Path(path).read_text())
    except (OSError, json.JSONDecodeError) as e:
        log.warning("staging: unreadable json %s (%s); skipped", path, e)
        return None
    if not isinstance(data, dict):
        return None
    kept = {k: v for k, v in data.items() if k in keys}
    return json.dumps(kept, indent=2, sort_keys=True).encode()


def _copy_tree(src: str, dst: Path, *, skip: list[str]) -> None:
    dst.mkdir(parents=True, exist_ok=True)
    for entry in sorted(os.listdir(src)):
        if entry in skip:
            continue
        s = os.path.join(src, entry)
        d = dst / entry
        if os.path.islink(s):
            # never dereference: a staged tree (e.g. a third-party plugin
            # repo) could link to credentials or anything on the host --
            # following it would violate the never-stage-secrets contract
            log.warning("staging: symlink %s skipped (links are never "
                        "dereferenced into the container)", s)
            continue
        if os.path.isdir(s):
            _copy_tree(s, d, skip=skip)
        else:
            shutil.copyfile(s, d)


def _apply_rewrites(tree: Path, rules: list[JsonRewrite], *,
                    container_home: str, container_work: str) -> None:
    by_file: dict[str, list[JsonRewrite]] = {}
    for r in rules:
        by_file.setdefault(r.file, []).append(r)
    if not by_file:
        return
    host_home = os.path.expanduser("~")
    for path in tree.rglob("*.json"):
        rules_here = by_file.get(path.name)
        if not rules_here:
            continue
        try:
            data = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError):
            continue
        changed = _rewrite_json(data, rules_here, host_home=host_home,
                                container_home=container_home,
                                container_work=container_work)
        if changed:
            path.write_text(json.dumps(data, indent=2, sort_keys=True))


def _rewrite_json(v, rules: list[JsonRewrite], *, host_home: str,
                  container_home: str, container_work: str) -> bool:
    """Recursive key-targeted value rewrite (reference rewriteJSONPaths)."""
    changed = False
    if isinstance(v, dict):
        for key, val in v.items():
            for r in rules:
                if key != r.key or not isinstance(val, str):
                    continue
                if r.rewrite == "replace-with-workdir":
                    v[key] = container_work
                    changed = True
                elif r.rewrite == "prefix-swap" and val.startswith(host_home):
                    v[key] = container_home + val[len(host_home):]
                    changed = True
            if isinstance(val, (dict, list)):
                changed |= _rewrite_json(val, rules, host_home=host_home,
                                         container_home=container_home,
                                         container_work=container_work)
    elif isinstance(v, list):
        for item in v:
            changed |= _rewrite_json(item, rules, host_home=host_home,
                                     container_home=container_home,
                                     container_work=container_work)
    return changed


# --------------------------------------------------------------- packing

def staging_tar(staging_dir: Path, *, uid: int = 1000, gid: int = 1000) -> bytes:
    """Pack the staging mirror as a tar extracting at the container home.
    An empty mirror returns b"" so callers can skip the daemon round-trip
    entirely (the fresh-host no-op contract)."""
    if not any(staging_dir.rglob("*")):
        return b""
    buf = io.BytesIO()
    now = int(time.time())
    with tarfile.open(fileobj=buf, mode="w") as tf:
        for path in sorted(staging_dir.rglob("*")):
            rel = str(path.relative_to(staging_dir))
            info = tarfile.TarInfo(rel)
            info.uid, info.gid = uid, gid
            info.mtime = now
            if path.is_dir():
                info.type = tarfile.DIRTYPE
                info.mode = 0o755
                tf.addfile(info)
            else:
                body = path.read_bytes()
                info.size = len(body)
                info.mode = 0o644
                tf.addfile(info, io.BytesIO(body))
    return buf.getvalue()


def prepare_hook_tar(shell: str, script: str, name: str, *,
                     uid: int = 1000, gid: int = 1000) -> bytes:
    """Tar with ``.clawker/<name>.sh`` (shebang + ``set -e`` + script,
    0755) extracting at the container home.  Empty script -> bare no-op
    wrapper, so callers can always-deliver and overwrite stale content."""
    body = f"#!{shell}\nset -e\n{script.strip()}\n".encode()
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w") as tf:
        d = tarfile.TarInfo(".clawker")
        d.type = tarfile.DIRTYPE
        d.mode = 0o755
        d.uid, d.gid = uid, gid
        tf.addfile(d)
        info = tarfile.TarInfo(f".clawker/{name}.sh")
        info.size = len(body)
        info.mode = 0o755
        info.uid, info.gid = uid, gid
        tf.addfile(info, io.BytesIO(body))
    return buf.getvalue()
