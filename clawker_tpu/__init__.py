"""clawker-tpu: a TPU-native agent-sandbox framework.

A ground-up rebuild of the capabilities of schmitthub/clawker (reference at
/root/reference): run AI coding-agent harnesses inside locked-down containers
behind a deny-by-default egress firewall, with credential forwarding,
git-worktree parallel agents, a control-plane daemon, and an observability
stack -- re-designed so the compute backend is pluggable and Cloud TPU-VM
workers are the first-class distributed runtime.

Layer map (mirrors reference SURVEY.md section 1, re-architected for Python/C++):

    cli/            host CLI verbs (reference: internal/cmd/*)
    engine/         runtime-driver seam + Docker Engine API client
                    (reference: pkg/whail + internal/docker)
    runtime/        naming/label/PTY middleware (reference: internal/docker)
    storage/        layered YAML Store (reference: internal/storage)
    config/         project + settings schemas (reference: internal/config)
    bundler/        Dockerfile generation (reference: internal/bundler)
    bundle/         3-tier component resolution (reference: internal/bundle)
    controlplane/   CP daemon: pubsub, events, registry, dialer, executor
                    (reference: internal/controlplane + controlplane/*)
    firewall/       PKI, Envoy/CoreDNS config gen, policy engine, eBPF loader
                    (reference: controlplane/firewall)
    agentd/         session protocol client for the C++ in-container PID 1
                    (reference: clawkerd/)
    fleet/          TPU-pod worker inventory + placement          (net-new)
    loop/           autonomous agent-loop scheduler               (net-new)
    analytics/      JAX fleet-telemetry analytics on TPU          (net-new)
    monitor/        observability stack templates (reference: internal/monitor)
    hostproxy/      host side-channel HTTP server (reference: internal/hostproxy)
    socketbridge/   SSH/GPG agent forwarding mux (reference: internal/socketbridge)
"""

__version__ = "0.2.0"
