"""Cross-process distributed tracing (docs/tracing.md).

The flight-recorder span trees (telemetry/spans.py) stop at the
process boundary: loopd, workerd, and the federation router each added
a WAN/daemon hop where causality was lost.  This package closes those
seams with three small, dependency-light pieces:

- :mod:`~clawker_tpu.tracing.context` -- a W3C-traceparent-style
  :class:`TraceContext` carried as *frame fields* on every existing
  RPC (federation submit, the loopd wire protocol, workerd
  intent/event frames, engine HTTP headers).  Propagation never adds a
  round-trip: the ids ride messages that were already being sent.
- :mod:`~clawker_tpu.tracing.skew` -- per-channel clock-skew
  estimation from the round-trips each channel already performs
  (hello/ping midpoint offset, EWMA-smoothed), chained cumulatively so
  every daemon can stamp its spans with an auditable ``skew_s``
  offset back to the root clock.
- :mod:`~clawker_tpu.tracing.merge` -- joins the router / loopd /
  workerd / scheduler flight recorders into one causal tree, tolerant
  of torn tails and missing segments: a dead daemon's segment renders
  as an explicit *gap span*, never a broken tree.

:mod:`~clawker_tpu.tracing.names` is the span-name catalogue the
``registry-parity`` analyze checker enforces against the table in
docs/telemetry.md (the same diff-time contract metric names have).
"""

from __future__ import annotations

from .context import TraceContext, current, use
from .merge import MergeResult, merge_records, merge_run
from .skew import ChannelClock

__all__ = [
    "TraceContext", "current", "use",
    "ChannelClock",
    "MergeResult", "merge_records", "merge_run",
]
