"""Join per-process flight recorders into one causal span tree.

Four recorder families may hold pieces of one run's trace
(docs/tracing.md#merge):

- ``logs/flight/loop-<run>.jsonl`` -- the scheduler's own spans
  (iteration roots + phase children), wherever the scheduler ran;
- ``logs/flight/loopd-<pod>.jsonl`` -- daemon-lifetime ``loopd.submit``
  hop spans, one file per pod;
- ``logs/flight/router-<name>.jsonl`` -- the federation router's
  ``router.submit`` hop spans;
- ``logs/flight/workerd-<worker>.jsonl`` -- worker-side remote spans
  (``workerd.create`` / ``workerd.start`` / ``workerd.wait``).

Everything in those files that belongs to the run shares its
``trace_id`` (the run id).  Within one recorder, ``parent_id`` links
children exactly as telemetry/spans.py always has; ACROSS recorders a
segment's root carries a ``ctx_parent`` attribute naming its upstream
parent span id (iteration roots keep ``parent_id == ""`` so every
single-file consumer -- `loop trace`, the chaos span-tree invariant,
the console tail -- still sees them as roots).

The merge is defensive the way :func:`build_trees` is, and then some:

- **skew**: a record stamped ``skew_s`` (its recorder's cumulative
  clock offset to the root clock) is shifted by exactly that much and
  marked ``skew_adjusted`` -- raw timestamps stay in the file, only
  the merged rendering moves.  A child that still escapes its parent
  beyond tolerance is marked ``skew_suspect``, never re-ordered.
- **gaps**: a ``ctx_parent`` naming a span no recorder holds gets a
  synthesized ``gap`` placeholder node; an iteration that launched via
  workerd but has no worker-side segment (dead daemon, torn tail)
  gets an explicit ``gap`` child.  A dead workerd renders as a gap,
  not a broken tree.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable

from ..monitor.ledger import FLIGHT_DIR, flight_path, read_rotated_lines
from ..telemetry.spans import (SPAN_ITERATION, SpanNode, SpanRecord,
                               load_spans, tree_to_dict)
from .names import SPAN_GAP

# parent-encloses-child slack after skew adjustment: EWMA-smoothed
# midpoint offsets are good to ~rtt/2, and phase boundaries are stamped
# on different threads -- anything inside this window is clock noise,
# anything outside is a suspect estimate worth flagging
SKEW_TOLERANCE_S = 0.050


@dataclass
class MergeResult:
    run_id: str
    roots: list[SpanNode] = field(default_factory=list)
    spans: int = 0
    gaps: int = 0
    skew_suspects: int = 0
    sources: dict = field(default_factory=dict)     # source -> records used

    def to_dict(self) -> dict:
        return {
            "run": self.run_id, "spans": self.spans, "gaps": self.gaps,
            "skew_suspects": self.skew_suspects,
            "sources": dict(self.sources),
            "trees": [tree_to_dict(r) for r in self.roots],
        }


def _adjusted(rec: SpanRecord, source: str) -> SpanRecord:
    """Tag the record's source and apply its recorder's cumulative
    clock offset (attr ``skew_s``).  Pure: the on-disk record is not
    what renders, and the shift is auditable from the kept attrs."""
    attrs = dict(rec.attrs)
    attrs.setdefault("source", source)
    skew = float(attrs.get("skew_s") or 0.0)
    if skew:
        attrs["skew_adjusted"] = True
        return dataclasses.replace(rec, t_start=rec.t_start - skew,
                                   t_end=rec.t_end - skew, attrs=attrs)
    return dataclasses.replace(rec, attrs=attrs)


def _gap_record(run_id: str, span_id: str, *, agent: str = "",
                worker: str = "", t_start: float = 0.0,
                t_end: float = 0.0, **attrs) -> SpanRecord:
    return SpanRecord(
        trace_id=run_id, span_id=span_id, parent_id="", name=SPAN_GAP,
        agent=agent, worker=worker, t_start=t_start, t_end=t_end,
        status="ok", attrs={"gap": True, **attrs})


def merge_records(sources: dict, run_id: str) -> MergeResult:
    """``{source_name: [SpanRecord, ...]}`` -> one merged causal forest
    for ``run_id``.  Records whose trace_id differs are ignored (daemon
    recorders hold every run the daemon ever served)."""
    res = MergeResult(run_id=run_id)
    nodes: dict[str, SpanNode] = {}
    order: list[SpanNode] = []
    for source, recs in sources.items():
        used = 0
        for rec in recs:
            if rec.trace_id != run_id:
                continue
            used += 1
            rec = _adjusted(rec, source)
            if rec.span_id in nodes:
                # duplicate span_id (double flush / re-emit): keep LAST
                nodes[rec.span_id].record = rec
                continue
            node = SpanNode(rec)
            nodes[rec.span_id] = node
            order.append(node)
        if used:
            res.sources[source] = used
    res.spans = len(order)

    # ---- iteration-root index: workerd's LAUNCH-path spans cannot name
    # a parent span id (the scheduler opens the iteration root only when
    # the created event lands, AFTER the intent shipped), so they attach
    # by (agent, iteration) instead -- the one join key both sides hold.
    iter_roots: dict[tuple, SpanNode] = {}
    for node in order:
        rec = node.record
        if rec.name == SPAN_ITERATION:
            iter_roots[(rec.agent, rec.attrs.get("iteration"))] = node

    # ---- link: parent_id within a recorder, ctx_parent across them.
    # An upstream parent nothing recorded becomes a synthesized gap
    # placeholder so the segment stays ROOTED (torn router/loopd tail).
    placeholders: dict[str, SpanNode] = {}
    roots: list[SpanNode] = []
    for node in order:
        rec = node.record
        pid = rec.parent_id or str(rec.attrs.get("ctx_parent") or "")
        if not pid and rec.name.startswith("workerd."):
            host = iter_roots.get((rec.agent, rec.attrs.get("iteration")))
            if host is not None and host is not node:
                host.children.append(node)
                continue
        if not pid or nodes.get(pid) is node:
            roots.append(node)
            continue
        parent = nodes.get(pid)
        if parent is None:
            if rec.parent_id:
                # in-recorder parent lost (crashed writer): promote,
                # exactly like build_trees -- the segment still renders
                roots.append(node)
                continue
            ph = placeholders.get(pid)
            if ph is None:
                ph = SpanNode(_gap_record(
                    run_id, pid, agent=rec.agent, worker=rec.worker,
                    t_start=rec.t_start, t_end=rec.t_end,
                    expect="upstream"))
                placeholders[pid] = ph
                roots.append(ph)
            ph.record = dataclasses.replace(
                ph.record,
                t_start=min(ph.record.t_start, rec.t_start),
                t_end=max(ph.record.t_end, rec.t_end))
            ph.children.append(node)
            continue
        parent.children.append(node)
    res.gaps += len(placeholders)

    # ---- gap-mark iterations whose remote segment never arrived: the
    # scheduler's create/start children say the launch went VIA workerd
    # (attr workerd=True), so a complete trace must hold worker-side
    # spans under that root -- a dead workerd's loss is made explicit.
    from ..util import ids

    for node in order:
        rec = node.record
        if rec.name != SPAN_ITERATION:
            continue
        via, remote = "", False
        for c in node.children:
            if c.record.attrs.get("workerd"):
                via = via or c.record.worker
            if c.record.name.startswith("workerd."):
                remote = True
        if via and not remote:
            gap = SpanNode(_gap_record(
                run_id, ids.short_id(16), agent=rec.agent, worker=via,
                t_start=rec.t_start, t_end=rec.t_end, expect="workerd",
                iteration=rec.attrs.get("iteration")))
            gap.record = dataclasses.replace(gap.record,
                                             parent_id=rec.span_id)
            node.children.append(gap)
            res.gaps += 1

    # ---- monotonicity: after skew adjustment an enclosed child should
    # fall inside its parent (within tolerance).  Causal edges -- a
    # submit span linked via ctx_parent to work that outlives the RPC --
    # only promise that the effect does not precede the cause, so they
    # get the start check alone.  Launch-path children of an iteration
    # (workerd.* segments and the create/start spans that rode the
    # channel) legitimately start BEFORE their parent -- the iteration
    # root only opens when the created event lands -- so their start is
    # floored not by the parent but by the scheduler-side sibling that
    # caused them: workerd.create cannot precede create.  A skewed
    # remote clock betrays itself against that floor or by overrunning
    # the iteration's end.  A violator is FLAGGED, never re-ordered: a
    # wrong-looking time under a suspect offset is evidence, and
    # evidence does not get rewritten.
    def _audit(parent: SpanNode) -> None:
        p = parent.record
        for child in parent.children:
            c = child.record
            causal = not c.parent_id and c.attrs.get("ctx_parent")
            launch = p.name == SPAN_ITERATION and (
                c.name.startswith("workerd.") or c.attrs.get("workerd"))
            floor = p.t_start
            if launch:
                floor = None
                if c.name.startswith("workerd."):
                    base = c.name[len("workerd."):]
                    sib = next((s.record for s in parent.children
                                if s.record.name == base), None)
                    floor = sib.t_start if sib is not None else None
            if not c.attrs.get("gap") and (
                    (floor is not None
                     and c.t_start < floor - SKEW_TOLERANCE_S)
                    or (not causal
                        and c.t_end > p.t_end + SKEW_TOLERANCE_S)):
                attrs = dict(c.attrs)
                attrs["skew_suspect"] = True
                child.record = dataclasses.replace(c, attrs=attrs)
                res.skew_suspects += 1
            _audit(child)

    for node in order:
        node.children.sort(key=lambda n: (n.record.t_start, n.record.name))
    roots.sort(key=lambda n: (n.record.t_start, n.record.agent))
    for root in roots:
        _audit(root)
    res.roots = roots
    return res


def recorder_files(logs_dir: Path, run_id: str) -> dict:
    """Every recorder file that may hold a piece of this run's trace:
    ``{source_name: Path}``.  Daemon recorders are included wholesale
    (merge_records filters by trace id); missing files are fine."""
    out: dict = {}
    run_file = flight_path(logs_dir, run_id)
    if run_file.exists() or Path(str(run_file) + ".1").exists():
        out["scheduler"] = run_file
    fdir = Path(logs_dir) / FLIGHT_DIR
    for pattern, label in (("router*.jsonl", "router"),
                           ("loopd-*.jsonl", "loopd"),
                           ("workerd-*.jsonl", "workerd")):
        for p in sorted(fdir.glob(pattern)):
            if p.suffix == ".jsonl":
                out[f"{label}:{p.stem}"] = p
    return out


def merge_run(logs_dir: Path, run_id: str) -> MergeResult:
    """Discover + read + merge every recorder for ``run_id`` under
    ``logs_dir`` (rotation-aware: each recorder's ``.1`` generation is
    read first, so a rotated tail still joins)."""
    sources = {}
    for name, path in recorder_files(logs_dir, run_id).items():
        sources[name] = load_spans(read_rotated_lines(path))
    return merge_records(sources, run_id)


def hop_waits(roots: Iterable[SpanNode]) -> dict:
    """Aggregate per-hop WAN wait: ``{span_name: total_wan_ms}`` over
    every span carrying a ``wan_ms`` attribute (the submit/launch hops
    stamp it at emit time from their own round-trip measurements)."""
    waits: dict = {}
    def _walk(node: SpanNode) -> None:
        wan = node.record.attrs.get("wan_ms")
        if wan is not None:
            waits[node.record.name] = (
                waits.get(node.record.name, 0.0) + float(wan))
        for c in node.children:
            _walk(c)
    for r in roots:
        _walk(r)
    return {k: round(v, 3) for k, v in sorted(waits.items())}
