"""Per-channel clock-skew estimation from round-trips we already pay.

Every daemon channel in the system performs round-trips as part of its
ordinary life -- the workerd hello handshake, loopd hello/ping, the
federation router's lease RPCs.  Each reply now carries the server's
wall clock (``ts``), which turns every such round-trip into one NTP-ish
offset sample for free::

    offset ~= server_ts - (t0 + t1) / 2

where t0/t1 are the client's send/receive times.  The midpoint model
assumes a symmetric path; asymmetry error is bounded by rtt/2, so the
estimator also tracks the smallest rtt seen (best sample quality) and
smooths the offset with an EWMA rather than trusting any single
round-trip (docs/tracing.md#clock-skew).

Offsets CHAIN: the router estimates loopd's offset, loopd estimates
workerd's, and each hop hands its *cumulative* offset downstream as a
frame field (``clock_offset_s``) on messages already being sent.  A
daemon stamps every span it records with ``skew_s`` = its cumulative
offset to the root clock, so the merge layer converts remote times with
one auditable subtraction -- the raw server timestamps stay in the
record, only the rendering shifts.
"""

from __future__ import annotations

import threading

DEFAULT_ALPHA = 0.25        # EWMA weight for new offset samples


class ChannelClock:
    """One channel's skew estimator: feed it (t0, server_ts, t1)
    samples, read ``offset_s`` (server clock minus client clock) and
    ``cumulative(upstream)`` (server clock minus ROOT clock, given the
    client's own offset to the root).  Thread-safe: the sampling side
    (connect/ping paths) and the reading side (span emission) race."""

    def __init__(self, alpha: float = DEFAULT_ALPHA):
        self.alpha = alpha
        self._lock = threading.Lock()
        self.offset_s = 0.0
        self.rtt_s = 0.0            # smallest round-trip observed
        self.samples = 0

    def observe(self, t0: float, server_ts: float, t1: float) -> float:
        """One round-trip sample -> updated EWMA offset estimate.
        Degenerate samples (t1 < t0, zero server ts) are ignored --
        a channel must never un-learn its estimate off a bad frame."""
        if server_ts <= 0.0 or t1 < t0:
            return self.offset_s
        raw = server_ts - (t0 + t1) / 2.0
        rtt = t1 - t0
        with self._lock:
            if self.samples == 0:
                self.offset_s = raw
                self.rtt_s = rtt
            else:
                self.offset_s += self.alpha * (raw - self.offset_s)
                self.rtt_s = min(self.rtt_s, rtt)
            self.samples += 1
            return self.offset_s

    def cumulative(self, upstream_offset_s: float = 0.0) -> float:
        """Server-to-ROOT offset: the client's own offset to the root
        (0.0 when the client IS the root/viewer) plus this channel's
        estimate.  This is the value handed downstream as
        ``clock_offset_s`` and stamped on spans as ``skew_s``."""
        with self._lock:
            return upstream_offset_s + self.offset_s

    def stats(self) -> dict:
        with self._lock:
            return {"offset_s": round(self.offset_s, 6),
                    "rtt_s": round(self.rtt_s, 6),
                    "samples": self.samples}
