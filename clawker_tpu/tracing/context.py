"""Trace-context propagation: W3C-traceparent-style ids as frame fields.

A :class:`TraceContext` is the (trace_id, span_id, flags) triple that
crosses every RPC boundary.  On the wire it is one string field --
``tp`` on loopd/workerd JSON frames, the standard ``traceparent``
header on engine HTTP calls -- in the W3C shape::

    00-<trace_id>-<span_id>-<flags as 2 hex digits>

with the repo's own id widths (run ids and span ids are
``ids.short_id`` strings, not 16/8-byte hex), so a context survives a
round-trip through any of our frames without re-encoding.  Propagation
NEVER adds a round-trip: the ids ride frames that were already being
sent (docs/tracing.md#propagation).

The thread-local ambient context (:func:`use` / :func:`current`) exists
for the one boundary that has no frame of its own to extend: engine
HTTP calls.  The scheduler (or workerd) activates the current span's
context around an engine call; ``engine/httpapi.py`` reads it, adds the
``traceparent`` header, and records an ``engine.request`` child span
through the context's sink.  No active context means zero work on the
engine hot path -- health probes and CLI one-shots pay nothing.
"""

from __future__ import annotations

import contextlib
import threading
import time
from dataclasses import dataclass, field

from ..util import ids
from .names import SPAN_ENGINE_REQUEST

TRACEPARENT_VERSION = "00"


@dataclass(frozen=True)
class TraceContext:
    """One propagated span identity: the parent under which the next
    hop's spans land.  ``sink`` (never serialized) receives any span
    recorded *through* this context -- e.g. ``engine.request``."""

    trace_id: str
    span_id: str
    flags: int = 1
    agent: str = ""
    worker: str = ""
    sink: object = field(default=None, compare=False, repr=False)

    def to_header(self) -> str:
        return (f"{TRACEPARENT_VERSION}-{self.trace_id}-{self.span_id}"
                f"-{self.flags & 0xFF:02x}")

    @classmethod
    def from_header(cls, header: str) -> "TraceContext | None":
        """Parse a traceparent string; None on anything malformed (an
        unparseable context degrades to an unlinked trace, never an
        error on the RPC path).  An empty span id is LEGAL: the workerd
        launch path sends a root-less header -- the run id is known but
        the iteration root only opens when the created event lands --
        and the merge layer attaches those spans by (agent, iteration)
        instead of by parent id."""
        parts = str(header or "").split("-")
        if len(parts) != 4 or not parts[1]:
            return None
        try:
            flags = int(parts[3], 16)
        except ValueError:
            return None
        return cls(trace_id=parts[1], span_id=parts[2], flags=flags)

    def child(self, span_id: str = "", *, agent: str = "",
              worker: str = "") -> "TraceContext":
        """A context one hop down: same trace, new parent span id."""
        return TraceContext(
            trace_id=self.trace_id,
            span_id=span_id or ids.short_id(16),
            flags=self.flags,
            agent=agent or self.agent, worker=worker or self.worker,
            sink=self.sink)

    def record(self, name: str, t_start: float, t_end: float,
               status: str = "ok", **attrs):
        """Record a completed leaf span under this context through its
        sink.  A sink-less context records nothing (propagate-only)."""
        if self.sink is None:
            return None
        from ..telemetry.spans import SpanRecord

        rec = SpanRecord(
            trace_id=self.trace_id, span_id=ids.short_id(16),
            parent_id=self.span_id, name=name, agent=self.agent,
            worker=self.worker, t_start=t_start, t_end=t_end,
            status=status, attrs=dict(attrs))
        try:
            self.sink(rec)
        except Exception:   # noqa: BLE001 -- tracing never raises into
            pass            # the caller's hot path
        return rec


_tls = threading.local()


def current() -> TraceContext | None:
    """The thread's ambient context, or None outside any ``use()``."""
    return getattr(_tls, "ctx", None)


@contextlib.contextmanager
def use(ctx: TraceContext | None):
    """Activate ``ctx`` as the thread's ambient context for the block.
    ``use(None)`` is a no-op guard, so call sites need no conditional."""
    prev = getattr(_tls, "ctx", None)
    _tls.ctx = ctx
    try:
        yield ctx
    finally:
        _tls.ctx = prev


def record_engine_request(verb: str, path: str, t_start: float,
                          ok: bool = True) -> None:
    """Called by engine/httpapi on every unary request that ran under
    an ambient context: one ``engine.request`` span through the
    context's sink.  No context, no work."""
    ctx = current()
    if ctx is None:
        return
    ctx.record(SPAN_ENGINE_REQUEST, t_start, time.time(),
               status="ok" if ok else "failed", verb=verb, path=path)
