"""The span-name catalogue: every name any Tracer or daemon emits.

The ``registry-parity`` analyze checker cross-checks this tuple against
the span-catalogue table in docs/telemetry.md exactly the way metric
names are enforced: an emitted name missing from the doc table -- or a
documented name nothing emits -- is a diff-time finding.  Add the name
HERE and in the doc table in the same change that introduces the span.
"""

from __future__ import annotations

# cross-process hop spans (docs/tracing.md)
SPAN_ROUTER_SUBMIT = "router.submit"        # federation router -> pod
SPAN_LOOPD_SUBMIT = "loopd.submit"          # loopd accept -> run start
SPAN_WORKERD_CREATE = "workerd.create"      # worker-side container create
SPAN_WORKERD_START = "workerd.start"        # worker-side start + bootstrap
SPAN_WORKERD_WAIT = "workerd.wait"          # worker-resident exit waiter
SPAN_ENGINE_REQUEST = "engine.request"      # one engine HTTP unary call
SPAN_GAP = "gap"                            # synthesized by the merge:
#                             a remote segment that never arrived (dead
#                             daemon, torn tail) -- explicit, not broken

# Every span name that may appear in a flight recorder, scheduler-local
# names included (telemetry/spans.py defines those as constants; they
# are mirrored here as plain strings so the catalogue -- like
# SEAM_NAMES -- is one AST-parseable tuple of literals the analyzer
# reads without importing anything).
SPAN_CATALOGUE = (
    "iteration",
    "create",
    "start",
    "wait",
    "exit",
    "orphan",
    "migrate",
    "resume",
    "sentinel.tick",
    "router.submit",
    "loopd.submit",
    "workerd.create",
    "workerd.start",
    "workerd.wait",
    "engine.request",
    "gap",
)
