"""Fused fleet egress collection for the sentinel.

One :class:`StreamCollector` owns every worker's ``ebpf-egress.jsonl``
tail and merges the records into one bounded, worker-tagged feed:

- **local sources** (``local``/``fake`` drivers, or a worker whose
  stream lands on this host) are tailed incrementally on the sentinel's
  own tick via :func:`monitor.ledger.tail_jsonl` -- a netlogger that
  died mid-line leaves a torn tail that is SKIPPED, never fatal, and a
  rotated file replays from the top;
- **remote sources** (``tpu_vm`` workers) ride ``tail -F`` over the
  worker's existing SSH ControlMaster (the same mux the side channels
  and the dashboard's egress ticker use), pumped by a daemon thread.

Sources are DEDUPED by path: on a fake pod every worker's stream may be
one host file, and tailing it once per worker would multiply every
record.  Records keep their own ``worker`` field when the netlogger
wrote one; otherwise they are tagged with the owning source's id.

``kill()`` is the chaos seam (docs/chaos.md ``sentinel`` scenario): it
drops every source mid-run the way a SIGKILLed collector process would,
and ``revive()`` re-wires -- the scoring engine above must degrade to
stale scores, never crash, and the scheduler must not notice at all.
"""

from __future__ import annotations

import collections
import threading
import time
from pathlib import Path

from .. import logsetup
from ..fleet.egress_tail import REMOTE_EGRESS_LOG
from ..monitor.ledger import TailState, parse_jsonl, tail_jsonl

log = logsetup.get("sentinel.collector")


class StreamCollector:
    """Thread-safe bounded merge of per-worker egress streams."""

    def __init__(self, maxlen: int = 100_000):
        self._buf: collections.deque = collections.deque(maxlen=maxlen)
        self._lock = threading.Lock()
        self._dead = threading.Event()
        self._local: dict[Path, tuple[str, TailState]] = {}
        self._procs: list = []
        self._threads: list[threading.Thread] = []
        self._counts: dict[str, int] = {}     # worker -> records collected
        self._wired: list[tuple] = []         # re-wire recipe for revive()

    # ------------------------------------------------------------ sources

    def add_local(self, worker_id: str, path: Path) -> None:
        """Tail a host-side stream for ``worker_id``.  Deduped by
        resolved path; a missing file reads as no news until it
        appears (a worker may not have logged yet)."""
        path = Path(path)
        self._wired.append(("local", worker_id, path))
        if path not in self._local:
            self._local[path] = (worker_id, TailState())

    def add_remote(self, worker_id: str, transport) -> None:
        """``tail -F`` the worker-side stream over its SSH mux; the
        remote shell resolves the worker's XDG state path."""
        self._wired.append(("remote", worker_id, transport))
        cmd = transport.ssh_base() + [
            f"tail -n +1 -F {REMOTE_EGRESS_LOG} 2>/dev/null"]
        try:
            proc = transport.runner.spawn_piped(cmd)
        except OSError as e:
            log.warning("sentinel tail for %s failed to start: %s",
                        worker_id, e)
            return
        self._procs.append(proc)
        t = threading.Thread(target=self._pump_proc,
                             args=(worker_id, proc),
                             name=f"sentinel-tail-{worker_id}", daemon=True)
        t.start()
        self._threads.append(t)

    # -------------------------------------------------------------- pumps

    def _tag(self, rec: dict, worker_id: str) -> None:
        if worker_id:
            rec.setdefault("worker", worker_id)
        with self._lock:
            self._buf.append(rec)
            wid = str(rec.get("worker") or worker_id or "unknown")
            self._counts[wid] = self._counts.get(wid, 0) + 1

    def _pump_proc(self, worker_id: str, proc) -> None:
        try:
            for raw in iter(proc.stdout.readline, b""):
                if self._dead.is_set():
                    break
                line = (raw.decode("utf-8", "replace")
                        if isinstance(raw, bytes) else raw)
                for rec in parse_jsonl([line]):
                    self._tag(rec, worker_id)
        except (OSError, ValueError):
            pass

    def poll(self) -> int:
        """Tail every local source once (remote pumps push
        asynchronously); returns records newly collected.  Called from
        the sentinel's tick thread."""
        if self._dead.is_set():
            return 0
        n = 0
        for path, (worker_id, state) in list(self._local.items()):
            for rec in tail_jsonl(path, state):
                self._tag(rec, worker_id)
                n += 1
        return n

    # -------------------------------------------------------------- reads

    def records(self) -> list[dict]:
        with self._lock:
            return list(self._buf)

    def counts(self) -> dict[str, int]:
        """Per-worker collected-record counters (stream-silence and
        fusion evidence for the CLI/status surfaces)."""
        with self._lock:
            return dict(self._counts)

    def total(self) -> int:
        with self._lock:
            return sum(self._counts.values())

    def wait_quiescent(self, deadline_s: float = 2.0,
                       settle_s: float = 0.15) -> None:
        """Block until the feed stops growing (or ``deadline_s``).

        A one-shot scorer wired over REMOTE tails must not score
        milliseconds after spawn -- the SSH ``tail -F`` pumps replay
        the worker-side history asynchronously, and an immediate tick
        would read a busy fleet as empty.  Local-only collectors
        return after one poll (their tail is synchronous)."""
        self.poll()
        if not self._procs:
            return
        deadline = time.monotonic() + max(0.0, deadline_s)
        last = self.total()
        while time.monotonic() < deadline:
            time.sleep(settle_s)
            self.poll()
            now = self.total()
            if now == last and now > 0:
                return
            last = now

    @property
    def alive(self) -> bool:
        return not self._dead.is_set()

    # ---------------------------------------------------------- lifecycle

    def kill(self) -> None:
        """Chaos seam: drop every source mid-run like a SIGKILL would --
        no flush, no unwind.  The collected buffer stays readable (a
        dead collector serves stale records, exactly what a scorer
        downstream of a dead process would see)."""
        self._dead.set()
        for proc in self._procs:
            try:
                proc.kill()
            except OSError:
                pass
        self._procs.clear()
        self._local = {}

    def revive(self) -> None:
        """Re-wire every source recorded by the add_* calls (collector
        restart after a chaos kill; tails resume from scratch)."""
        if not self._dead.is_set():
            return
        self._dead = threading.Event()
        wired, self._wired = list(self._wired), []
        for kind, worker_id, src in wired:
            if kind == "local":
                self.add_local(worker_id, src)
            else:
                self.add_remote(worker_id, src)

    def stop(self) -> None:
        self._dead.set()
        for proc in self._procs:
            try:
                proc.terminate()
            except OSError:
                pass
        for t in self._threads:
            t.join(1.0)
        self._threads.clear()
        self._procs.clear()


def wire_fleet(collector: StreamCollector, driver, cfg) -> None:
    """Wire one source per fleet worker: remote engines (a transport on
    the engine) tail worker-side over the SSH mux; local/fake workers
    read host files -- a per-worker ``ebpf-egress-<worker>.jsonl``
    beside the shared stream when present (how a multi-worker fake pod
    keeps distinct streams on one host), else the shared
    ``ebpf-egress.jsonl``."""
    shared = cfg.logs_dir / "ebpf-egress.jsonl"
    for worker in driver.workers():
        engine = worker.engine
        transport = getattr(engine, "transport", None) if engine else None
        if transport is not None:
            collector.add_remote(worker.id, transport)
            continue
        per_worker = cfg.logs_dir / f"ebpf-egress-{worker.id}.jsonl"
        collector.add_local(worker.id, per_worker)
        collector.add_local(worker.id, shared)
