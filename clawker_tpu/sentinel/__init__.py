"""Online fleet sentinel: pod-sharded live anomaly scoring.

The production half of the analytics subsystem (docs/analytics-online.md):
where ``analytics/`` scores a recorded egress file offline, the sentinel
fuses EVERY fleet worker's live egress stream with the scheduler's typed
event stream and scores the whole fleet's open windows as one sharded
program per tick -- publishing typed ``anomaly.flag`` bus events,
registry metrics, and flight-recorder spans.  Strictly observe-only:
flags never feed breakers or placement.

Surfaces: ``clawker fleet anomaly`` (one-shot / --watch / --json),
``clawker loop --sentinel``, loopd status, and the loop dashboard's
ANOM-Z column (the sentinel implements the AnomalyWatch surface).

jax is imported lazily inside the scoring tick; importing this package
costs nothing on accelerator-less hosts.
"""

from .collector import StreamCollector, wire_fleet
from .engine import DEFAULT_THRESHOLD, ScoringEngine, TickReport
from .features import BEHAVIOR_FEATURES, EXT_FEATURES, BehaviorTracker, featurize_fused
from .sentinel import STATE_DIR, FleetSentinel, state_path

__all__ = [
    "BEHAVIOR_FEATURES",
    "BehaviorTracker",
    "DEFAULT_THRESHOLD",
    "EXT_FEATURES",
    "FleetSentinel",
    "STATE_DIR",
    "ScoringEngine",
    "StreamCollector",
    "TickReport",
    "featurize_fused",
    "state_path",
    "wire_fleet",
]
