"""FleetSentinel: the online fleet-wide anomaly scorer.

Glues the three halves together on one tick thread: the
:class:`~.collector.StreamCollector` (fused multi-worker egress tails),
the :class:`~.features.BehaviorTracker` (typed EventBus records), and
the :class:`~.engine.ScoringEngine` (one sharded fit/score program per
tick).  Each tick the sentinel

1. polls the collector and featurizes every agent's open windows into
   the 40-dim extended ABI,
2. scores them against per-worker rolling baselines,
3. publishes: typed ``anomaly.flag`` bus events (once per flagged
   (agent, window)), ``anomaly_score{agent}`` /
   ``anomaly_flags_total{worker,kind}`` registry metrics, and a
   ``sentinel.tick`` span into the run's flight recorder.

**Strictly observe-only.**  The sentinel holds no engine, placement, or
admission reference; its only outputs are events, metrics, spans, and
its own state file.  ``audit()`` returns the mutation counters the
chaos observe-only invariant checks (they are zero by construction --
the counter exists so the invariant can PROVE it, not merely trust it).

The sentinel exposes the AnomalyWatch surface (``scores`` /
``score_for`` / ``on_anomaly`` / ``on_error`` / ``refresh_once`` /
``start`` / ``stop``), so the loop dashboard's ANOM-Z column, the
scheduler's status rows, and ``attach_anomaly_watch`` all work
unchanged.

State (per-worker baselines + already-flagged windows) persists to
``logs/sentinel/<run>.json`` each tick; a ``--resume`` of the run picks
the normal profile back up instead of re-learning it.
"""

from __future__ import annotations

import json
import threading
import time
from pathlib import Path

from .. import logsetup, telemetry
from ..analytics.features import WINDOW_S, AgentScore
from ..monitor.events import ANOMALY_FLAG, AnomalyFlagEvent
from ..util.fs import atomic_write
from .collector import StreamCollector, wire_fleet
from .engine import DEFAULT_THRESHOLD, ScoringEngine
from .features import BehaviorTracker, featurize_fused

log = logsetup.get("sentinel")

STATE_DIR = "sentinel"          # under Config.logs_dir

_SCORE = telemetry.gauge(
    "anomaly_score", "Latest sentinel anomaly z-score per agent",
    labels=("agent",))
_FLAGS = telemetry.counter(
    "anomaly_flags_total", "Sentinel anomaly flags raised",
    labels=("worker", "kind"))
_TICKS = telemetry.counter(
    "sentinel_ticks_total", "Sentinel scoring ticks executed",
    labels=("result",))         # result: scored | empty | error


def state_path(logs_dir: Path, run_id: str) -> Path:
    return Path(logs_dir) / STATE_DIR / f"{run_id}.json"


class FleetSentinel:
    """Pod-sharded live anomaly scoring as a production security signal."""

    def __init__(self, cfg, driver=None, *, run_id: str = "",
                 interval_s: float = 5.0, window_s: int = WINDOW_S,
                 train_steps: int = 40,
                 threshold: float = DEFAULT_THRESHOLD,
                 baseline_window: int = 256,
                 collector: StreamCollector | None = None,
                 on_anomaly=None, on_error=None):
        self.cfg = cfg
        self.run_id = run_id
        self.interval_s = interval_s
        self.window_s = window_s
        self.collector = collector if collector is not None else (
            StreamCollector())
        if collector is None and driver is not None:
            wire_fleet(self.collector, driver, cfg)
        self.behavior = BehaviorTracker(window_s=window_s)
        self.engine = ScoringEngine(train_steps=train_steps,
                                    threshold=threshold,
                                    baseline_window=baseline_window)
        self.on_anomaly = on_anomaly or (lambda agent, z: None)
        self.on_error = on_error or (lambda msg: None)
        self.last_error = ""
        self.flight = None          # FlightRecorder, bound by the scheduler
        self._events = None         # EventBus, bound by the scheduler
        self._scores: dict[str, AgentScore] = {}
        self._worker_of: dict[str, str] = {}
        self._flagged: set[tuple[str, int]] = set()   # (agent, window)
        self._flag_rows: list[dict] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.ticks = 0
        self.last_tick = None       # TickReport | None
        self._scored_at = (-1, -1)  # (collector total, behavior version)
        #                             of the last SCORED tick: an idle
        #                             tick (nothing new on any stream or
        #                             the bus) must not re-featurize the
        #                             whole bounded buffer
        # observe-only audit counters: the sentinel has NO path that
        # could increment these -- the chaos invariant asserts they
        # stay zero, turning the design promise into checked evidence
        self._mutations = {"engine_calls": 0, "breaker_reports": 0,
                           "placement_calls": 0}
        if run_id:
            self._load_state()

    # ------------------------------------------------------------ binding

    def bind_run(self, *, run_id: str = "", events=None, flight=None) -> None:
        """Attach the sentinel to a live run: the bus (typed flag emits
        + the behavioral tap) and the run's flight recorder.  Called by
        ``LoopScheduler.attach_sentinel``."""
        if run_id and run_id != self.run_id:
            self.run_id = run_id
            self._load_state()
        if events is not None:
            self._events = events
            events.add_tap(self.behavior)
        if flight is not None:
            self.flight = flight

    # ------------------------------------------------------------ surface

    def scores(self) -> dict[str, AgentScore]:
        with self._lock:
            return dict(self._scores)

    def score_for(self, agent_or_container: str) -> AgentScore | None:
        """AnomalyWatch-compatible lookup: exact row, else match the
        loop agent against container-name dot segments."""
        if not agent_or_container:
            return None
        with self._lock:
            hit = self._scores.get(agent_or_container)
            if hit is not None:
                return hit
            for name, sc in self._scores.items():
                if agent_or_container in name.split("."):
                    return sc
        return None

    def rows(self) -> list[dict]:
        """Render-ready per-agent rows (CLI table / loopd status)."""
        counts = self.collector.counts()
        with self._lock:
            scores = dict(self._scores)
            worker_of = dict(self._worker_of)
            flagged_agents = {a for a, _w in self._flagged}
        out = []
        for agent, sc in sorted(scores.items()):
            worker = worker_of.get(agent, "")
            out.append({
                "agent": agent,
                "worker": worker,
                "windows": sc.windows,
                "latest_z": round(sc.latest, 2),
                "peak_z": round(sc.peak, 2),
                "flagged": agent in flagged_agents,
                "stream_records": counts.get(worker, 0),
            })
        return out

    def flags(self) -> list[dict]:
        with self._lock:
            return list(self._flag_rows)

    def audit(self) -> dict:
        """Observe-only evidence for the chaos invariant."""
        return dict(self._mutations)

    def status_doc(self) -> dict:
        return {
            "enabled": True,
            "run": self.run_id,
            "ticks": self.ticks,
            "collector_alive": self.collector.alive,
            "threshold": self.engine.threshold,
            "baseline_samples": self.engine.baseline_depth(),
            "stream_counts": self.collector.counts(),
            "rows": self.rows(),
            "flags": self.flags(),
        }

    # --------------------------------------------------------------- tick

    def refresh_once(self) -> int:
        """One synchronous collect -> featurize -> score -> emit tick;
        returns windows scored.  The tick must never raise into its
        thread: a broken scorer surfaces once per distinct failure via
        ``on_error`` and leaves the previous scores standing."""
        t0 = time.time()
        try:
            self.collector.poll()
            seen = (self.collector.total(), self.behavior.version)
            if seen == self._scored_at:
                # nothing new arrived on any stream or the bus: the
                # previous scores stand, and re-featurizing the whole
                # bounded buffer (100k records of strptime) for an
                # identical answer would burn a core forever on an
                # idle fleet
                _TICKS.labels("idle").inc()
                return 0
            records = self.collector.records()
            keys, X, worker_of = featurize_fused(
                records, self.behavior, window_s=self.window_s)
            rep = self.engine.score_tick(keys, X, worker_of)
            self._scored_at = seen
        except Exception as e:      # noqa: BLE001 -- watcher must not die
            msg = f"{e.__class__.__name__}: {e}"
            if msg != self.last_error:
                self.last_error = msg
                self.on_error(msg)
            _TICKS.labels("error").inc()
            return 0
        self.last_error = ""
        self.ticks += 1
        if rep is None:
            _TICKS.labels("empty").inc()
            return 0
        self.last_tick = rep
        newly: list[tuple[str, str, float, str]] = []
        with self._lock:
            self._scores = {a.agent: a for a in rep.agents}
            for agent, worker in worker_of.items():
                self._worker_of[agent] = worker
            for i, (key, z) in enumerate(zip(rep.keys, rep.z)):
                if float(z) < self.engine.threshold:
                    continue
                if (rep.supports is not None
                        and float(rep.supports[i])
                        < self.engine.min_support):
                    continue    # off-manifold but evidence-starved (a
                    #             partial boundary window): scored, shown,
                    #             never flagged
                mark = (key.agent, key.start_unix)
                if mark in self._flagged:
                    continue        # one flag per (agent, window)
                self._flagged.add(mark)
                kind = self.engine.flag_kind(i)
                worker = self._worker_of.get(key.agent, "")
                newly.append((key.agent, worker, float(z), kind))
        for agent, worker, z, kind in newly:
            _FLAGS.labels(worker or "unknown", kind).inc()
            row = {"agent": agent, "worker": worker, "z": round(z, 2),
                   "kind": kind, "at": time.time()}
            with self._lock:
                self._flag_rows.append(row)
                del self._flag_rows[:-256]
            if self._events is not None:
                self._events.emit(agent, ANOMALY_FLAG, AnomalyFlagEvent(
                    agent, worker, z, kind).detail())
            self.on_anomaly(agent, z)
        for a in rep.agents:
            _SCORE.labels(a.agent).set(round(float(a.latest), 4))
        _TICKS.labels("scored").inc()
        self._record_span(t0, rep, len(newly))
        self._save_state()
        return rep.windows

    def _record_span(self, t0: float, rep, n_flags: int) -> None:
        if self.flight is None:
            return
        from ..telemetry.spans import SPAN_SENTINEL_TICK, SpanRecord
        from ..util import ids

        self.flight.append(SpanRecord(
            trace_id=self.run_id or "sentinel", span_id=ids.short_id(),
            parent_id="", name=SPAN_SENTINEL_TICK, agent="sentinel",
            worker="", t_start=t0, t_end=time.time(), status="ok",
            attrs={"windows": rep.windows, "flags": n_flags,
                   "device": rep.device,
                   "train_ms": round(rep.train_ms, 1)}).to_json())

    # ---------------------------------------------------------- lifecycle

    def start(self) -> "FleetSentinel":
        self._thread = threading.Thread(target=self._loop,
                                        name="fleet-sentinel", daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.refresh_once()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(2.0)
        if self._events is not None:
            self._events.remove_tap(self.behavior)
        self.collector.stop()
        self._save_state()

    def kill_collector(self) -> None:
        """Chaos seam: SIGKILL the collection half mid-run.  Scoring
        keeps running over the stale buffer; the fleet must not notice."""
        self.collector.kill()

    # -------------------------------------------------------- persistence

    def _state_path(self) -> Path | None:
        if not self.run_id:
            return None
        return state_path(self.cfg.logs_dir, self.run_id)

    def _save_state(self) -> None:
        path = self._state_path()
        if path is None:
            return
        with self._lock:
            flagged = sorted([a, s] for a, s in self._flagged)
        doc = {"run": self.run_id, "ticks": self.ticks,
               "baselines": self.engine.baseline_doc(),
               "flagged": flagged}
        try:
            path.parent.mkdir(parents=True, exist_ok=True)
            atomic_write(path, (json.dumps(doc) + "\n").encode())
        except OSError:
            pass            # state is an accelerator, never a dependency

    def _load_state(self) -> None:
        path = self._state_path()
        if path is None or not path.exists():
            return
        try:
            doc = json.loads(path.read_text(encoding="utf-8"))
        except (OSError, ValueError):
            return
        n = self.engine.load_baselines(doc.get("baselines") or {})
        with self._lock:
            for pair in doc.get("flagged") or []:
                try:
                    agent, start = pair
                    self._flagged.add((str(agent), int(start)))
                except (TypeError, ValueError):
                    continue
        self.ticks = int(doc.get("ticks") or 0)
        if n:
            log.info("sentinel: resumed %d baseline sample(s) for run %s",
                     n, self.run_id)
