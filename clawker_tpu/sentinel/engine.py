"""Sentinel scoring engine: one sharded SPMD scoring program per tick.

Each tick takes the fused (egress + behavior) window matrix for EVERY
open window of EVERY agent in the fleet and runs the existing denoising
autoencoder's ``fit``/``score`` (analytics/anomaly.py, via the
module-level jit cache in analytics/runtime.py) over it as ONE program:
on a multi-device backend params/batch/noise are placed on the
``fleet_mesh`` (batch over ``data``, hidden features over ``model``),
so scoring the whole pod's agents is a single SPMD dispatch per tick --
never a per-agent loop, and the PR-8 degradation ladder remains the
bench's fallback, not the steady state (the persistent compilation
cache + padded shapes mean tick N>1 reuses tick 1's executable).

Scores normalize in two stages: a robust (median/MAD) z within the
tick, then re-centered against the agent's WORKER's rolling baseline of
recent tick-z values -- a worker whose whole population drifts hot
surfaces even when its agents stay mutually consistent.  Baselines are
plain floats, serialized into the sentinel state file so ``--resume``
continues from the dead run's normal profile instead of re-learning it.
"""

from __future__ import annotations

import collections
import threading
import time
from dataclasses import dataclass, field

import numpy as np

from ..analytics import runtime as art
from ..analytics.features import AgentScore, WindowKey, summarize
from .features import EXT_FEATURES

BASELINE_MIN = 4          # baseline samples before it re-centers anything
DEFAULT_THRESHOLD = 3.5   # flag at this worker-relative robust z


@dataclass
class TickReport:
    keys: list[WindowKey]
    raw: np.ndarray                 # per-window reconstruction error
    z: np.ndarray                   # worker-relative robust z
    agents: list[AgentScore]        # per-agent fold of z
    supports: np.ndarray | None = None   # per-window evidence weight
    train_ms: float = 0.0
    score_ms: float = 0.0
    device: str = ""
    windows: int = 0


@dataclass
class ScoringEngine:
    train_steps: int = 40
    threshold: float = DEFAULT_THRESHOLD
    baseline_window: int = 256      # per-worker recent tick-z samples kept
    min_support: float = 10.0       # evidence floor before a window may
    #                                 FLAG (it is always scored): a
    #                                 handful-of-records partial window
    #                                 at a stream boundary is legitimately
    #                                 off-manifold but not an incident.
    #                                 Support = egress records + 5x
    #                                 behavioral events (behavioral
    #                                 events are rare and each is heavy).
    seed: int = 0
    lr: float = 1e-2
    _baselines: dict = field(default_factory=dict)  # worker -> deque[float]
    # guards _baselines: the tick thread inserts worker keys while
    # status/CLI threads (loopd RPC, fleet anomaly) read depth/doc
    _baselines_lock: threading.Lock = field(default_factory=threading.Lock)

    # ------------------------------------------------------------ scoring

    def _mesh(self):
        import jax

        if len(jax.devices()) > 1:
            from ..analytics import anomaly

            return anomaly.fleet_mesh()
        return None

    def score_tick(self, keys: list[WindowKey], X: np.ndarray,
                   worker_of: dict[str, str]) -> TickReport | None:
        """Fit + score every open window; None when there is nothing to
        score.  ``worker_of`` maps window agents to worker ids for the
        baseline stage (unknown agents share the '' baseline)."""
        if not keys:
            return None
        raw, params, x, t = art._fit_and_score(
            X, train_steps=self.train_steps, lr=self.lr, seed=self.seed,
            mesh=self._mesh(), feat=EXT_FEATURES)
        z_tick = art._robust_z(raw)
        z = np.array([
            self._worker_z(worker_of.get(k.agent, ""), float(zt))
            for k, zt in zip(keys, z_tick)], np.float32)
        self._params = params       # for flag attribution (host-side)
        self._x_std = np.asarray(x)[: len(keys)]
        # evidence weight per window, from the PRE-standardized counts:
        # dim 0 is log1p(egress records), the last behavior dim is
        # log1p(total behavioral events)
        supports = (np.expm1(X[:, 0])
                    + 5.0 * np.expm1(X[:, EXT_FEATURES - 1]))
        return TickReport(
            keys=keys, raw=raw, z=z, agents=summarize(keys, z),
            supports=supports.astype(np.float32),
            train_ms=t["train_ms"], score_ms=t["score_ms"],
            device=t["device"], windows=len(keys))

    def _worker_z(self, worker: str, z_tick: float) -> float:
        """Re-center a tick z against the worker's rolling baseline,
        then feed the baseline (post-read: a score never normalizes
        against itself)."""
        with self._baselines_lock:
            base = self._baselines.get(worker)
            if base is None:
                base = self._baselines[worker] = collections.deque(
                    maxlen=self.baseline_window)
            arr = (np.asarray(base, np.float32)
                   if len(base) >= BASELINE_MIN else None)
            base.append(z_tick)
        if arr is None:
            return z_tick
        med = float(np.median(arr))
        mad = float(np.median(np.abs(arr - med)))
        scale = max(1.0, 1.4826 * mad)   # a too-quiet baseline must
        #                                  not inflate ordinary noise
        return (z_tick - med) / scale

    # ------------------------------------------------------- attribution

    def flag_kind(self, row_index: int) -> str:
        """'egress' | 'behavior': which feature family dominates the
        flagged window's reconstruction error.  Host-side numpy over
        the tick's fitted params (40x128 -- trivial), only computed for
        rows that actually flag."""
        try:
            p = self._params
            x = self._x_std[row_index]
        except (AttributeError, IndexError):
            return "egress"
        h = np.asarray(x) @ np.asarray(p.w_enc) + np.asarray(p.b_enc)
        h = 0.5 * h * (1.0 + np.tanh(0.7978845608 * (h + 0.044715 * h**3)))
        r = h @ np.asarray(p.w_dec) + np.asarray(p.b_dec)
        err = np.square(r - x)
        from ..analytics.features import FEATURES as EGRESS_DIMS

        return ("behavior" if float(err[EGRESS_DIMS:].sum())
                > float(err[:EGRESS_DIMS].sum()) else "egress")

    # ------------------------------------------------------- persistence

    def baseline_doc(self) -> dict:
        """Serializable rolling baselines (sentinel state file)."""
        with self._baselines_lock:
            return {w: [round(float(v), 4) for v in vals]
                    for w, vals in self._baselines.items()}

    def load_baselines(self, doc: dict) -> int:
        n = 0
        for worker, vals in (doc or {}).items():
            base = collections.deque(maxlen=self.baseline_window)
            for v in vals[-self.baseline_window:]:
                try:
                    base.append(float(v))
                except (TypeError, ValueError):
                    continue
            with self._baselines_lock:
                self._baselines[str(worker)] = base
            n += len(base)
        return n

    def baseline_depth(self, worker: str = "") -> int:
        with self._baselines_lock:
            return sum(len(v) for w, v in self._baselines.items()
                       if not worker or w == worker)


def now_window(window_s: int) -> int:
    now = int(time.time())
    return now - now % window_s
