"""Extended feature ABI: fused egress windows + scheduler behavior.

The offline anomaly lane scores 32-dim egress window vectors
(analytics/features.py).  The sentinel extends each (agent, window)
vector with ``BEHAVIOR_FEATURES`` dims derived from the typed EventBus
stream -- exit codes, orphans, migrations, restarts -- so an agent that
goes quiet on the network while crash-looping (or that keeps exiting 0
while spraying denies) is off-manifold in ONE vector.  numpy only; the
TPU half stays analytics/anomaly.py, which is feature-width agnostic.

Extension layout (dims 32..39, appended after the egress 32):

  32  log1p(iterations completed in window)
  33  log1p(nonzero exits)
  34  failure ratio (nonzero / completed)
  35  log1p(orphan events)
  36  log1p(migrations)
  37  log1p(iteration starts)
  38  log1p(distinct workers whose stream carried the agent this window)
  39  log1p(total behavioral events)

The fused record stream tags every egress record with the worker whose
stream carried it (collector.py); behavioral events are bucketed at
arrival time into the same aligned windows.  An agent with behavior but
zero egress still yields a row (zeroed egress dims): a suddenly-silent
stream is itself a signal.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Iterable

import numpy as np

from ..analytics import features as F

BEHAVIOR_FEATURES = 8
EXT_FEATURES = F.FEATURES + BEHAVIOR_FEATURES      # 40

# bus events the tracker folds into behavioral windows
_TRACKED = ("iteration_start", "iteration_done", "orphaned", "migrated",
            "resumed", "adopted", "failed")


@dataclass
class _Window:
    starts: int = 0
    done: int = 0
    failures: int = 0
    orphans: int = 0
    migrations: int = 0
    total: int = 0


@dataclass
class BehaviorTracker:
    """Thread-safe per-(agent, aligned-window) fold of bus records.

    Attached to a scheduler's EventBus as a tap; records are stamped at
    ARRIVAL time (bus records carry no timestamp), which is within the
    scoring window for anything the sentinel can act on.  Bounded: only
    ``keep_windows`` windows per agent are retained.
    """

    window_s: int = F.WINDOW_S
    keep_windows: int = 16
    clock: object = time.time
    version: int = 0        # bumped per folded record: the sentinel's
    #                         idle-tick short-circuit reads it
    _lock: threading.Lock = field(default_factory=threading.Lock)
    _by_agent: dict = field(default_factory=dict)   # agent -> {start: _Window}

    def __call__(self, rec) -> None:               # EventBus tap signature
        self.observe(rec.agent, rec.event, rec.detail)

    def observe(self, agent: str, event: str, detail: str = "") -> None:
        if event not in _TRACKED:
            return
        now = int(self.clock())
        start = now - now % self.window_s
        with self._lock:
            self.version += 1
            windows = self._by_agent.setdefault(agent, {})
            w = windows.get(start)
            if w is None:
                w = windows[start] = _Window()
                if len(windows) > self.keep_windows:
                    del windows[min(windows)]
            w.total += 1
            if event == "iteration_start":
                w.starts += 1
            elif event == "iteration_done":
                w.done += 1
                # detail is "<iteration>:<code>"
                code = detail.rpartition(":")[2]
                if code not in ("", "0"):
                    w.failures += 1
            elif event == "failed":
                w.failures += 1
            elif event == "orphaned":
                w.orphans += 1
            elif event == "migrated":
                w.migrations += 1

    def snapshot(self) -> dict:
        """{agent: {window_start: _Window}} deep-enough copy."""
        with self._lock:
            return {a: dict(ws) for a, ws in self._by_agent.items()}


def _behavior_vec(w: _Window | None, n_workers: int) -> np.ndarray:
    v = np.zeros(BEHAVIOR_FEATURES, np.float32)
    if w is not None:
        v[0] = np.log1p(w.done)
        v[1] = np.log1p(w.failures)
        v[2] = w.failures / w.done if w.done else (1.0 if w.failures else 0.0)
        v[3] = np.log1p(w.orphans)
        v[4] = np.log1p(w.migrations)
        v[5] = np.log1p(w.starts)
        v[7] = np.log1p(w.total)
    v[6] = np.log1p(n_workers)
    return v


def _loop_agent_of(container: str, behavior_agents: Iterable[str]) -> str:
    """Map a container-named egress key back to its loop agent name.
    Container names are dot-separated (``clawker.<proj>.<agent>``), so
    match whole segments -- the same rule AnomalyWatch.score_for uses."""
    segments = container.split(".")
    for agent in behavior_agents:
        if agent in segments:
            return agent
    return container


def featurize_fused(records: Iterable[dict],
                    behavior: BehaviorTracker | None = None, *,
                    window_s: int = F.WINDOW_S,
                    ) -> tuple[list[F.WindowKey], np.ndarray, dict[str, str]]:
    """Fused records (+ optional behavior) -> (keys, X[n, EXT_FEATURES],
    worker_of).

    ``keys`` keep analytics' deterministic (agent, window-start) sort so
    jit shapes and row order are stable for a given input; ``worker_of``
    maps each key's agent to the worker whose stream(s) dominated its
    records (for per-worker baselines and flag attribution).  Behavior
    windows with no matching egress window become zero-egress rows keyed
    by the loop agent name itself.
    """
    records = list(records)
    keys, X_egress = F.featurize(records, window_s=window_s)

    # per (container-agent, window): worker tags of the records
    workers_by_key: dict[F.WindowKey, set] = {}
    for rec in records:
        ts = F.parse_ts(rec.get("@timestamp", ""))
        if not ts:
            continue
        key = F.WindowKey(str(rec.get("container") or rec.get("cgroup_id")
                              or "unknown"), ts - ts % window_s)
        wid = str(rec.get("worker") or "")
        if wid:
            workers_by_key.setdefault(key, set()).add(wid)

    snap = behavior.snapshot() if behavior is not None else {}
    behavior_agents = list(snap)
    covered: set[tuple[str, int]] = set()
    rows: list[np.ndarray] = []
    worker_of: dict[str, str] = {}
    for i, key in enumerate(keys):
        agent = _loop_agent_of(key.agent, behavior_agents)
        w = snap.get(agent, {}).get(key.start_unix)
        if w is not None:
            covered.add((agent, key.start_unix))
        tags = sorted(workers_by_key.get(key, ()))
        rows.append(np.concatenate(
            [X_egress[i], _behavior_vec(w, len(tags))]))
        if tags:
            worker_of.setdefault(key.agent, tags[0])

    # behavior-only windows: an agent with scheduler events but a silent
    # egress stream still gets a (zero-egress) row
    extra_keys: list[F.WindowKey] = []
    for agent, windows in sorted(snap.items()):
        for start, w in sorted(windows.items()):
            if (agent, start) in covered:
                continue
            extra_keys.append(F.WindowKey(agent, start))
            rows.append(np.concatenate(
                [np.zeros(F.FEATURES, np.float32), _behavior_vec(w, 0)]))
    all_keys = list(keys) + extra_keys
    if not all_keys:
        return [], np.zeros((0, EXT_FEATURES), np.float32), {}
    X = np.stack(rows).astype(np.float32)
    # keep the deterministic (agent, start) global sort across both halves
    order = sorted(range(len(all_keys)),
                   key=lambda j: (all_keys[j].agent, all_keys[j].start_unix))
    return ([all_keys[j] for j in order], X[order], worker_of)
