"""Project registry + worktree service (reference: internal/project)."""

from .manager import ProjectManager, ProjectRecord, WorktreeRecord

__all__ = ["ProjectManager", "ProjectRecord", "WorktreeRecord"]
