"""ProjectManager: global registry.yaml CRUD + worktree lifecycle.

Parity reference: internal/project (manager.go:45 ProjectManager,
registry.yaml in XDG data dir, worktree_service.go) + internal/git
integration.  Worktrees live under ``<data>/worktrees/<project>/<name>``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path

from ..config import Config
from ..errors import ConflictError, NotFoundError
from ..gitx import GitManager
from ..storage import Layer, Store
from ..util.text import validate_name


@dataclass
class WorktreeRecord:
    name: str
    path: Path
    branch: str

    @property
    def main_git_dir(self) -> Path:
        """The main repo's git dir (for read-only mounting into containers)."""
        gm = GitManager(self.path)
        return gm.git_dir()


@dataclass
class ProjectRecord:
    name: str
    root: Path
    worktrees: list[WorktreeRecord] = field(default_factory=list)


class ProjectManager:
    def __init__(self, cfg: Config):
        self.cfg = cfg
        self._store = Store([Layer("registry", cfg.registry_path)])

    # ------------------------------------------------------------ registry

    def _load(self) -> dict[str, ProjectRecord]:
        raw = self._store.raw().get("projects") or {}
        out: dict[str, ProjectRecord] = {}
        for name, rec in raw.items():
            out[name] = ProjectRecord(
                name=name,
                root=Path(rec.get("root", "")),
                worktrees=[
                    WorktreeRecord(name=w["name"], path=Path(w["path"]), branch=w.get("branch", ""))
                    for w in rec.get("worktrees", [])
                ],
            )
        return out

    def _save(self, projects: dict[str, ProjectRecord]) -> None:
        tree = {
            "projects": {
                p.name: {
                    "root": str(p.root),
                    "worktrees": [
                        {"name": w.name, "path": str(w.path), "branch": w.branch}
                        for w in p.worktrees
                    ],
                }
                for p in projects.values()
            }
        }
        self._store.write_layer("registry", tree)

    def register_current(self) -> ProjectRecord:
        name = self.cfg.project_name()
        root = self.cfg.project_root
        if root is None:
            raise NotFoundError("no project config found (run `clawker init` first)")
        projects = self._load()
        existing = projects.get(name)
        if existing and existing.root != root:
            raise ConflictError(
                f"project {name!r} already registered at {existing.root}; "
                "remove it first or rename this project"
            )
        rec = existing or ProjectRecord(name=name, root=root)
        rec.root = root
        projects[name] = rec
        self._save(projects)
        return rec

    def get(self, name: str) -> ProjectRecord:
        projects = self._load()
        if name not in projects:
            raise NotFoundError(f"project {name!r} not registered")
        return projects[name]

    def list_projects(self) -> list[ProjectRecord]:
        return sorted(self._load().values(), key=lambda p: p.name)

    def remove(self, name: str) -> None:
        projects = self._load()
        if name not in projects:
            raise NotFoundError(f"project {name!r} not registered")
        del projects[name]
        self._save(projects)

    # ----------------------------------------------------------- worktrees

    def _ensure_registered(self, project: str) -> ProjectRecord:
        projects = self._load()
        if project in projects:
            return projects[project]
        # auto-register when invoked from within the project
        if self.cfg.project_root is not None and self.cfg.project_name() == project:
            return self.register_current()
        raise NotFoundError(f"project {project!r} not registered")

    def add_worktree(self, project: str, name: str, *, branch: str = "") -> WorktreeRecord:
        validate_name("worktree", name)
        rec = self._ensure_registered(project)
        if any(w.name == name for w in rec.worktrees):
            raise ConflictError(f"worktree {name!r} already exists for {project!r}")
        branch = branch or f"clawker/{name}"
        dest = self.cfg.worktrees_dir / project / name
        gm = GitManager(rec.root)
        if not gm.is_repo():
            raise ConflictError(f"project root {rec.root} is not a git repository")
        info = gm.setup_worktree(dest, branch)
        wt = WorktreeRecord(name=name, path=info.path, branch=info.branch)
        projects = self._load()
        projects.setdefault(project, rec).worktrees = [
            w for w in rec.worktrees if w.name != name
        ] + [wt]
        self._save(projects)
        return wt

    def get_worktree(self, project: str, name: str) -> WorktreeRecord:
        rec = self.get(project)
        for w in rec.worktrees:
            if w.name == name:
                return w
        raise NotFoundError(f"worktree {name!r} not found for project {project!r}")

    def list_worktrees(self, project: str) -> list[WorktreeRecord]:
        try:
            return list(self.get(project).worktrees)
        except NotFoundError:
            return []

    def remove_worktree(self, project: str, name: str, *, force: bool = False) -> None:
        rec = self.get(project)
        wt = self.get_worktree(project, name)
        gm = GitManager(rec.root)
        if wt.path.exists():
            if not force and gm.is_dirty(wt.path):
                raise ConflictError(
                    f"worktree {name!r} has local changes; use --force to discard"
                )
            gm.remove_worktree(wt.path, force=force)
        else:
            gm.prune_worktrees()
        projects = self._load()
        projects[project].worktrees = [w for w in rec.worktrees if w.name != name]
        self._save(projects)

    def prune_worktrees(self, project: str) -> list[str]:
        """Drop registry records whose directories no longer exist."""
        rec = self.get(project)
        gone = [w.name for w in rec.worktrees if not w.path.exists()]
        if gone:
            GitManager(rec.root).prune_worktrees()
            projects = self._load()
            projects[project].worktrees = [w for w in rec.worktrees if w.path.exists()]
            self._save(projects)
        return gone
