"""Socket-level firewall parity: executable Envoy-bootstrap interpreter,
attacker capture server, virtual-internet world, and the 22-scenario
reference scorecard (`python -m clawker_tpu.parity`)."""

from .world import CurlResult, EgressBlocked, World  # noqa: F401
